from agentic_traffic_testing_tpu.agents.agent_b.server import main

main()
