"""Host-side KV block allocator + per-sequence block tables.

The device-side cache layout is `runtime/kv_cache.py`; this module owns the
*policy*: which physical blocks belong to which sequence, free-list accounting,
and the capacity numbers exported through the `llm_kv_cache_*` Prometheus
gauges (mirroring what the reference reads off vLLM's cache config —
reference: llm/serve_llm.py:245-264, 410-502).

A C++ implementation of the same interface lives in `native/` (built as a
CPython extension); this pure-Python version is the always-available fallback
and the behavioral spec.
"""

from __future__ import annotations

from typing import Optional

from agentic_traffic_testing_tpu.runtime.kv_cache import TRASH_BLOCK


class BlockAllocator:
    """Free-list allocator over physical KV blocks.

    Block ids run [1, num_blocks); block 0 is the shared trash block that
    padding lanes write into (see kv_cache.py). LIFO reuse keeps recently
    freed blocks hot in any downstream cache hierarchy.
    """

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 usable + trash), got {num_blocks}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: list[int] = list(range(num_blocks - 1, TRASH_BLOCK, -1))

    @property
    def num_free_blocks(self) -> int:
        return len(self._free)

    @property
    def num_used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    @property
    def usable_tokens(self) -> int:
        return (self.num_blocks - 1) * self.block_size

    def blocks_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> Optional[list[int]]:
        """Allocate n blocks, or None (all-or-nothing) if unavailable."""
        if n > len(self._free):
            return None
        taken = self._free[-n:] if n else []
        del self._free[len(self._free) - n:]
        return taken

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if not (TRASH_BLOCK < b < self.num_blocks):
                raise ValueError(f"freeing invalid block id {b}")
        self._free.extend(blocks)
        if len(self._free) > self.num_blocks - 1:
            raise RuntimeError("double free detected: free list exceeds capacity")

    def new_sequence(self) -> "SequenceBlocks":
        return SequenceBlocks(self)


class SequenceBlocks:
    """Block-table bookkeeping for one sequence."""

    def __init__(self, allocator: BlockAllocator) -> None:
        self._alloc = allocator
        self.blocks: list[int] = []

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    @property
    def capacity_tokens(self) -> int:
        return len(self.blocks) * self._alloc.block_size

    def ensure_capacity(self, num_tokens: int) -> bool:
        """Grow to hold num_tokens; False (and no change) if blocks ran out."""
        need = self._alloc.blocks_needed(num_tokens) - len(self.blocks)
        if need <= 0:
            return True
        got = self._alloc.allocate(need)
        if got is None:
            return False
        self.blocks.extend(got)
        return True

    def release(self) -> None:
        if self.blocks:
            self._alloc.free(self.blocks)
            self.blocks = []

    def table_row(self, width: int) -> list[int]:
        """Fixed-width block-table row, padded with the trash block."""
        row = self.blocks[:width] + [TRASH_BLOCK] * max(0, width - len(self.blocks))
        return row


def make_block_allocator(num_blocks: int, block_size: int, native: Optional[bool] = None):
    """Allocator factory: C++ core when available, Python fallback otherwise.

    `native=None` (default) auto-selects: the `native/` C++ library if it
    loads (honoring ATT_TPU_NATIVE=0), else this module's pure-Python
    implementation. Both are bit-exact interchangeable (tests/test_native.py).
    """
    if native is not False:
        try:
            from agentic_traffic_testing_tpu import native as native_mod

            if native_mod.available():
                return native_mod.NativeBlockAllocator(num_blocks, block_size)
        except (ImportError, RuntimeError):
            pass
        if native is True:
            raise RuntimeError("native block allocator requested but unavailable")
    return BlockAllocator(num_blocks, block_size)
