"""Host-side KV block allocator + per-sequence block tables.

The device-side cache layout is `runtime/kv_cache.py`; this module owns the
*policy*: which physical blocks belong to which sequence, free-list accounting,
and the capacity numbers exported through the `llm_kv_cache_*` Prometheus
gauges (mirroring what the reference reads off vLLM's cache config —
reference: llm/serve_llm.py:245-264, 410-502).

A C++ implementation of the same interface lives in `native/` (built as a
CPython extension); this pure-Python version is the always-available fallback
and the behavioral spec.
"""

from __future__ import annotations

from typing import Callable, Optional

from agentic_traffic_testing_tpu.runtime.kv_cache import TRASH_BLOCK


class BlockAllocator:
    """Free-list allocator over physical KV blocks.

    Block ids run [1, num_blocks); block 0 is the shared trash block that
    padding lanes write into (see kv_cache.py). LIFO reuse keeps recently
    freed blocks hot in any downstream cache hierarchy.
    """

    def __init__(self, num_blocks: int, block_size: int) -> None:
        if num_blocks < 2:
            raise ValueError(f"need >= 2 blocks (1 usable + trash), got {num_blocks}")
        self.num_blocks = num_blocks
        self.block_size = block_size
        self._free: list[int] = list(range(num_blocks - 1, TRASH_BLOCK, -1))

    @property
    def num_free_blocks(self) -> int:
        return len(self._free)

    @property
    def num_used_blocks(self) -> int:
        return (self.num_blocks - 1) - len(self._free)

    @property
    def usable_tokens(self) -> int:
        return (self.num_blocks - 1) * self.block_size

    def blocks_needed(self, num_tokens: int) -> int:
        return -(-num_tokens // self.block_size)

    def can_allocate(self, n: int) -> bool:
        return n <= len(self._free)

    def allocate(self, n: int) -> Optional[list[int]]:
        """Allocate n blocks, or None (all-or-nothing) if unavailable."""
        if n > len(self._free):
            return None
        taken = self._free[-n:] if n else []
        del self._free[len(self._free) - n:]
        return taken

    def free(self, blocks: list[int]) -> None:
        for b in blocks:
            if not (TRASH_BLOCK < b < self.num_blocks):
                raise ValueError(f"freeing invalid block id {b}")
        self._free.extend(blocks)
        if len(self._free) > self.num_blocks - 1:
            raise RuntimeError("double free detected: free list exceeds capacity")

    def new_sequence(self) -> "SequenceBlocks":
        return SequenceBlocks(self)


class SequenceBlocks:
    """Block-table bookkeeping for one sequence."""

    def __init__(self, allocator: BlockAllocator) -> None:
        self._alloc = allocator
        self.blocks: list[int] = []

    @property
    def num_blocks(self) -> int:
        return len(self.blocks)

    @property
    def capacity_tokens(self) -> int:
        return len(self.blocks) * self._alloc.block_size

    def ensure_capacity(self, num_tokens: int) -> bool:
        """Grow to hold num_tokens; False (and no change) if blocks ran out."""
        need = self._alloc.blocks_needed(num_tokens) - len(self.blocks)
        if need <= 0:
            return True
        got = self._alloc.allocate(need)
        if got is None:
            return False
        self.blocks.extend(got)
        return True

    def release(self) -> None:
        if self.blocks:
            self._alloc.free(self.blocks)
            self.blocks = []

    def table_row(self, width: int) -> list[int]:
        """Fixed-width block-table row, padded with the trash block."""
        row = self.blocks[:width] + [TRASH_BLOCK] * max(0, width - len(self.blocks))
        return row


class PrefixCachingAllocator(BlockAllocator):
    """Free-list allocator with content-addressed block reuse.

    vLLM-style automatic prefix caching (the reference can reach it through
    vLLM's --enable-prefix-caching; here it is first-party): every FULL
    prompt block is indexed by hash(parent_hash, its tokens). A new request
    shares the longest chain of already-computed blocks (refcounted) and
    only computes its suffix — which rides the chunked-prefill machinery
    (scheduler.ChunkPrefill with chunk_start = cached tokens). This is the
    agentic testbed's own traffic shape: AgentVerse stages and agent-b
    workers resend near-identical system/context prefixes all day.

    Lifecycle: a released block whose content is indexed parks in an LRU
    "evictable" pool — still reusable by content, reclaimed (and unindexed)
    only when fresh allocations need it. Shared/indexed blocks are never
    written: writes always target blocks past the cached prefix.
    """

    def __init__(self, num_blocks: int, block_size: int) -> None:
        super().__init__(num_blocks, block_size)
        # chain-hash -> (block id, block tokens). The tokens are compared on
        # every lookup: a 64-bit hash collision must degrade to a cache miss,
        # never serve another prompt's KV (cross-request content leakage).
        self._index: dict[int, tuple[int, tuple[int, ...]]] = {}
        self._block_key: dict[int, int] = {}  # block id -> chain-hash
        self._refcount: dict[int, int] = {}   # live users of a shared block
        # LRU of refcount-0 indexed blocks (dict preserves insertion order).
        self._evictable: dict[int, None] = {}
        self.hit_tokens = 0
        self.query_tokens = 0
        # Optional host-RAM tier (runtime/kv_offload.HostKVStore): reclaimed
        # indexed blocks spill there instead of being dropped, and prefix
        # matching extends past the device index into the host chain. None
        # (default) keeps every path bit-identical to the single-tier cache.
        self._host = None
        self._on_evict: Optional[Callable[[int, int, tuple], None]] = None
        self.host_hit_tokens = 0

    # -- capacity (evictable blocks count as available) ---------------------

    @property
    def num_free_blocks(self) -> int:
        return len(self._free) + len(self._evictable)

    @property
    def num_used_blocks(self) -> int:
        return (self.num_blocks - 1) - self.num_free_blocks

    def can_allocate(self, n: int) -> bool:
        return n <= self.num_free_blocks

    def allocate(self, n: int) -> Optional[list[int]]:
        if n > self.num_free_blocks:
            return None
        taken: list[int] = []
        take_free = min(n, len(self._free))
        if take_free:
            taken = self._free[-take_free:]
            del self._free[len(self._free) - take_free:]
        while len(taken) < n:  # reclaim LRU cached blocks, dropping their index
            blk = next(iter(self._evictable))
            del self._evictable[blk]
            if self._on_evict is not None:
                # Host-tier spill: hand the engine (block, chain key, tokens)
                # BEFORE unindexing — it slices the pages device-side right
                # here, so dispatch order puts the read ahead of whatever
                # write reuses the block.
                key = self._block_key.get(blk)
                if key is not None:
                    entry = self._index.get(key)
                    if entry is not None and entry[0] == blk:
                        self._on_evict(blk, key, entry[1])
            self._unindex(blk)
            taken.append(blk)
        for blk in taken:
            # Explicit ownership count: sharers via match_prefix stack on top
            # of this 1 (an implicit owner count would let a sharer's release
            # drive the count to 0 while the computing owner still decodes).
            self._refcount[blk] = 1
        return taken

    def _unindex(self, blk: int) -> None:
        key = self._block_key.pop(blk, None)
        if key is not None:
            entry = self._index.get(key)
            if entry is not None and entry[0] == blk:
                del self._index[key]

    def free(self, blocks: list[int]) -> None:
        """Release a sequence's blocks: shared ones decref, indexed ones park
        in the evictable LRU, plain ones return to the free list."""
        for b in blocks:
            if not (TRASH_BLOCK < b < self.num_blocks):
                raise ValueError(f"freeing invalid block id {b}")
            rc = self._refcount.get(b, 1) - 1
            if rc > 0:
                self._refcount[b] = rc
                continue
            self._refcount.pop(b, None)
            if b in self._block_key:
                self._evictable[b] = None  # most-recently-used position
            else:
                self._free.append(b)
        if len(self._free) + len(self._evictable) > self.num_blocks - 1:
            raise RuntimeError("double free detected: free list exceeds capacity")

    # -- content addressing -------------------------------------------------

    def chain_keys(self, prompt_ids: list[int]) -> tuple[list[int], list[tuple]]:
        """(chained content hashes, per-block token tuples) for every FULL
        block of this prompt.

        O(prompt) hashing + tuple building — callers memoize per request
        (see `request_chain_keys`) so probing the same waiting head every
        engine step is dict lookups, not re-hashing or re-slicing."""
        keys, toks, parent = [], [], 0
        bs = self.block_size
        for i in range(len(prompt_ids) // bs):
            t = tuple(prompt_ids[i * bs:(i + 1) * bs])
            parent = hash((parent, t))
            keys.append(parent)
            toks.append(t)
        return keys, toks

    def _matchable_blocks(self, prompt_ids: list[int]) -> int:
        # Only FULL blocks are addressable, and at least one prompt token
        # must remain to compute (its logits seed the first sampled token).
        return (len(prompt_ids) - 1) // self.block_size

    def _lookup(self, key: int, tokens: tuple[int, ...]) -> Optional[int]:
        entry = self._index.get(key)
        if entry is None or entry[1] != tokens:  # hash collision -> miss
            return None
        return entry[0]

    def probe_prefix(self, prompt_ids: list[int],
                     keys: Optional[tuple[list[int], list[tuple]]] = None) -> int:
        """Cached-token count a match would yield; no state changes."""
        bs = self.block_size
        ks, toks = keys if keys is not None else self.chain_keys(prompt_ids)
        cached = 0
        for i in range(self._matchable_blocks(prompt_ids)):
            if self._lookup(ks[i], toks[i]) is None:
                break
            cached += bs
        return cached

    def match_prefix(self, prompt_ids: list[int],
                     keys: Optional[tuple[list[int], list[tuple]]] = None,
                     ) -> tuple["SequenceBlocks", int]:
        """Acquire the longest cached block chain for this prompt.

        Returns (sequence holding the shared blocks, cached token count).
        The caller grows the sequence with plain blocks for the suffix and
        MUST release it on failure paths (refcounts are already taken)."""
        bs = self.block_size
        ks, toks = keys if keys is not None else self.chain_keys(prompt_ids)
        seq = SequenceBlocks(self)
        cached = 0
        for i in range(self._matchable_blocks(prompt_ids)):
            blk = self._lookup(ks[i], toks[i])
            if blk is None:
                break
            self._refcount[blk] = self._refcount.get(blk, 0) + 1
            self._evictable.pop(blk, None)
            seq.blocks.append(blk)
            cached += bs
        return seq, cached

    # -- host tier (runtime/kv_offload.py) ---------------------------------

    def attach_host_store(self, store,
                          on_evict: Optional[Callable[[int, int, tuple], None]]
                          = None) -> None:
        """Wire the host-RAM tier in: reclaimed indexed blocks report to
        `on_evict(block, chain_key, tokens)` (the engine's save hook) and
        prefix probing/matching extends into `store`'s chain."""
        self._host = store
        self._on_evict = on_evict

    @property
    def host_store(self):
        return self._host

    def probe_prefix_tiered(self, prompt_ids: list[int],
                            keys: Optional[tuple[list[int], list[tuple]]] = None,
                            ) -> tuple[int, int]:
        """(device-cached tokens, host-restorable tokens) a tiered match
        would yield; no state changes. The walk mirrors match_prefix_tiered:
        each block resolves device-first, then host, stopping at the first
        miss in both tiers — so a device block sitting past a host-only gap
        still counts (it is shareable once the gap restores)."""
        bs = self.block_size
        ks, toks = keys if keys is not None else self.chain_keys(prompt_ids)
        dev = host = 0
        for i in range(self._matchable_blocks(prompt_ids)):
            if self._lookup(ks[i], toks[i]) is not None:
                dev += bs
            elif self._host is not None and self._host.contains(ks[i], toks[i]):
                host += bs
            else:
                break
        return dev, host

    def match_prefix_tiered(self, prompt_ids: list[int],
                            keys: Optional[tuple[list[int], list[tuple]]] = None,
                            ) -> tuple["SequenceBlocks", int, list]:
        """Acquire the longest cached block chain across BOTH tiers.

        Returns (sequence, cached token count, restore plan). Device-indexed
        blocks are shared exactly like match_prefix; host-tier blocks get a
        FRESH device block each (allocated here, so capacity pressure can
        shorten the restore chain gracefully) and a RestoreBlock entry the
        engine must apply (host→device page write + register_restored)
        before the suffix prefills. The caller MUST release the sequence on
        failure paths — unapplied restore blocks are unindexed, so they
        return to the free list holding garbage no one can match."""
        bs = self.block_size
        ks, toks = keys if keys is not None else self.chain_keys(prompt_ids)
        seq = SequenceBlocks(self)
        cached = 0
        restores: list = []
        for i in range(self._matchable_blocks(prompt_ids)):
            blk = self._lookup(ks[i], toks[i])
            if blk is not None:
                self._refcount[blk] = self._refcount.get(blk, 0) + 1
                self._evictable.pop(blk, None)
                seq.blocks.append(blk)
                cached += bs
                continue
            if self._host is not None:
                entry = self._host.get(ks[i], toks[i])
                if entry is not None:
                    got = self.allocate(1)
                    if got is None:
                        break  # pool exhausted: restore what fits, compute the rest
                    from agentic_traffic_testing_tpu.runtime.kv_offload import (
                        RestoreBlock,
                    )

                    restores.append(RestoreBlock(
                        block=got[0], key=ks[i], tokens=toks[i],
                        k=entry.k, v=entry.v,
                        k_scale=entry.k_scale, v_scale=entry.v_scale))
                    seq.blocks.append(got[0])
                    cached += bs
                    continue
            break
        return seq, cached, restores

    def register_restored(self, restores: list) -> None:
        """Index restore blocks whose pages the engine just wrote (dispatch
        order guarantees any later reader's dispatch sees them). First
        writer wins, same rule as register_computed."""
        for rb in restores:
            if rb.key in self._index:
                continue
            if rb.block in self._block_key:
                continue
            self._index[rb.key] = (rb.block, rb.tokens)
            self._block_key[rb.block] = rb.key

    def record_host_hit(self, hit_tokens: int) -> None:
        """Host-tier hit accounting, called (like record_prefix_stats) once
        per admission that actually APPLIES the restore plan."""
        self.host_hit_tokens += hit_tokens

    def record_prefix_stats(self, query_tokens: int, hit_tokens: int) -> None:
        """Hit-rate accounting: call once per admission that actually APPLIES
        the cached prefix (counting inside match_prefix would inflate the
        rate on KV-starved retries and on batch-path full recomputes)."""
        self.query_tokens += query_tokens
        self.hit_tokens += hit_tokens

    def register_computed(self, seq: "SequenceBlocks", prompt_ids: list[int],
                          keys: Optional[list[int]] = None) -> None:
        """Index this sequence's full prompt blocks for future sharing.

        Called once the prompt's pages are written (dispatch order guarantees
        any later reader's dispatch sees them). First writer wins: keys that
        already map to another block keep their canonical block."""
        bs = self.block_size
        ks, toks = keys if keys is not None else self.chain_keys(prompt_ids)
        full = len(prompt_ids) // bs
        for i in range(min(full, len(seq.blocks))):
            key = ks[i]
            blk = seq.blocks[i]
            if key in self._index:
                continue
            if blk in self._block_key:  # already indexed under its own key
                continue
            self._index[key] = (blk, toks[i])
            self._block_key[blk] = key

    def kv_extra_stats(self) -> dict:
        stats = {
            "prefix_cache_hit_tokens": self.hit_tokens,
            "prefix_cache_query_tokens": self.query_tokens,
            "prefix_cache_indexed_blocks": len(self._index),
        }
        if self._host is not None:
            # Key present only with a host tier attached: the no-tier stats
            # dict stays byte-identical to the single-tier cache's.
            stats["host_cache_hit_tokens"] = self.host_hit_tokens
        return stats


def request_chain_keys(allocator, req):
    """Memoized (chain keys, block token tuples) for a request's current
    prompt (invalidated by length change — preemption only ever appends
    tokens). None when the allocator has no content addressing."""
    if not isinstance(allocator, PrefixCachingAllocator):
        return None
    n = req.num_prompt_tokens
    memo = req.prefix_keys_cache
    if memo is not None and memo[0] == n:
        return memo[1]
    keys = allocator.chain_keys(req.prompt_ids)
    req.prefix_keys_cache = (n, keys)
    return keys


def make_block_allocator(num_blocks: int, block_size: int,
                         native: Optional[bool] = None,
                         prefix_caching: bool = False):
    """Allocator factory: C++ core when available, Python fallback otherwise.

    `native=None` (default) auto-selects: the `native/` C++ library if it
    loads (honoring ATT_TPU_NATIVE=0), else this module's pure-Python
    implementation. Both are bit-exact interchangeable (tests/test_native.py).
    `prefix_caching=True` selects the content-addressed Python allocator (no
    native equivalent yet).
    """
    if prefix_caching:
        if native is True:
            raise RuntimeError("prefix caching has no native allocator yet")
        return PrefixCachingAllocator(num_blocks, block_size)
    if native is not False:
        try:
            from agentic_traffic_testing_tpu import native as native_mod

            if native_mod.available():
                return native_mod.NativeBlockAllocator(num_blocks, block_size)
        except (ImportError, RuntimeError):
            pass
        if native is True:
            raise RuntimeError("native block allocator requested but unavailable")
    return BlockAllocator(num_blocks, block_size)
