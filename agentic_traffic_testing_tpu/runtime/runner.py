"""ModelRunner: fused device dispatches for the serving engine.

Each scheduled step is ONE device dispatch: `decode_steps` fused model steps
+ on-device sampling, with each sampled token fed straight back as the next
step's input without touching the host. This matters doubly on TPU: (a) XLA
fuses the sampling epilogue into the decode program; (b) host↔device round
trips are expensive at small batch (observed ~10-100 ms through the axon
tunnel vs ~ms of compute), so the engine only *reads back* a [B, decode_steps]
int32 token array — asynchronously, with a configurable lag (engine.py).

The vLLM analog is the streaming `engine.generate` hot loop the reference
consumes (reference: llm/serve_llm.py:527-605); there the engine process owns
the GPU loop, here the runner owns jitted TPU programs. A tensor-parallel
runner (parallel/tp_runner.py) subclasses this and shards the same impl
functions over a mesh.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.models.llama import (
    decode_step_impl,
    hybrid_step_impl,
    prefill_chunk_impl,
    prefill_impl,
    prefill_pipeline_impl,
    verify_step_impl,
)
from agentic_traffic_testing_tpu.ops.sampling import make_row_keys, sample
from agentic_traffic_testing_tpu.ops.speculative import (
    accept_counts,
    align_drafts,
    rollback_commit,
    snapshot_pages,
    touched_pages,
)
from agentic_traffic_testing_tpu.runtime.kv_cache import KVCache


class SamplingArrays(NamedTuple):
    """Per-lane sampling parameters, device-resident for a batch's lifetime."""

    temperature: jax.Array  # [B] f32
    top_k: jax.Array        # [B] i32
    top_p: jax.Array        # [B] f32
    seeds: jax.Array        # [B] i32


class DecodeState(NamedTuple):
    """Device-resident state that advances without host involvement."""

    tokens: jax.Array     # [B] i32 — input token for the next step
    positions: jax.Array  # [B] i32 — position of `tokens`
    steps: jax.Array      # [B] i32 — per-request sampling step (PRNG stream)


def _prefill_sample_impl(params, cfg: ModelConfig, tokens, cache, block_tables,
                         seq_lens, samp: SamplingArrays, steps,
                         kv_writer_mode=None, attn_mode=None, attn_mesh=None,
                         attn_axis=None):
    logits, cache = prefill_impl(params, cfg, tokens, cache, block_tables,
                                 seq_lens, kv_writer_mode=kv_writer_mode,
                                 attn_mode=attn_mode, attn_mesh=attn_mesh,
                                 attn_axis=attn_axis)
    keys = make_row_keys(samp.seeds, steps)
    out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
    state = DecodeState(tokens=out, positions=seq_lens, steps=steps + 1)
    return state, cache, out


def _prefill_chunk_sample_impl(params, cfg: ModelConfig, tokens, cache,
                               block_tables, chunk_start, chunk_len,
                               samp: SamplingArrays, steps,
                               kv_writer_mode=None, attn_mode=None,
                               attn_mesh=None, attn_axis=None):
    """One chunk of a chunked prefill + sampling of the chunk's last token
    (the sample only matters on the final chunk; earlier chunks discard it)."""
    logits, cache = prefill_chunk_impl(params, cfg, tokens, cache,
                                       block_tables, chunk_start, chunk_len,
                                       kv_writer_mode=kv_writer_mode,
                                       attn_mode=attn_mode,
                                       attn_mesh=attn_mesh,
                                       attn_axis=attn_axis)
    keys = make_row_keys(samp.seeds, steps)
    out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
    return cache, out


def _prefill_pipeline_sample_impl(params, cfg: ModelConfig, tokens, cache,
                                  block_tables, chunk_start, seq_lens, carry,
                                  samp: SamplingArrays, steps,
                                  kv_writer_mode=None, attn_mode=None):
    """One position-chunk of a pipelined prefill + carry-merged sampling.

    Every chunk samples its per-row logits with the SAME (seed, step) keys
    the single-dispatch prefill would use, then merges into `carry` only
    the rows whose last real token fell inside this chunk — so after the
    final chunk, `carry` holds exactly the tokens the fused prefill+sample
    dispatch would have produced, with zero host synchronization between
    chunks (engine reads `carry` back once, at the tail). `cache` and
    `carry` are donated: the K dispatches chain device-side buffers.
    """
    logits, cache = prefill_pipeline_impl(
        params, cfg, tokens, cache, block_tables, chunk_start, seq_lens,
        kv_writer_mode=kv_writer_mode, attn_mode=attn_mode)
    keys = make_row_keys(samp.seeds, steps)
    out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
    c = tokens.shape[1]
    last = seq_lens - 1
    mine = jnp.logical_and(last >= chunk_start, last < chunk_start + c)
    return cache, jnp.where(mine, out, carry)


def _hybrid_sample_impl(params, cfg: ModelConfig, dec_tokens, chunk_tokens,
                        cache, block_tables, positions, chunk_start,
                        chunk_len, samp: SamplingArrays, steps,
                        attn_mode=None, fused_kv_write=False):
    """One FUSED hybrid step (B decode lanes + one prefill chunk in a
    single ragged dispatch) + sampling for every row.

    `samp`/`steps` cover B+1 lanes: the B decode lanes first, the chunk's
    request last. Returns (DecodeState for the B decode lanes, cache,
    decode tokens [B], chunk's sampled last token [1] — meaningful only on
    the final chunk, exactly like prefill_chunk's sample)."""
    b = dec_tokens.shape[0]
    dec_logits, chunk_logits, cache = hybrid_step_impl(
        params, cfg, dec_tokens, chunk_tokens, cache, block_tables,
        positions, chunk_start, chunk_len, attn_mode=attn_mode,
        fused_kv_write=fused_kv_write)
    keys = make_row_keys(samp.seeds, steps)
    out = sample(jnp.concatenate([dec_logits, chunk_logits]), keys,
                 samp.temperature, samp.top_k, samp.top_p)
    state = DecodeState(tokens=out[:b], positions=positions + 1,
                        steps=steps[:b] + 1)
    return state, cache, out[:b], out[b:]


def _decode_sample_impl(params, cfg: ModelConfig, cache, block_tables,
                        state: DecodeState, samp: SamplingArrays,
                        num_steps: int = 1, attn_mode=None, attn_mesh=None,
                        attn_axis=None, fused_kv_write=False):
    """`num_steps` fused decode steps in ONE dispatch (lax.scan on device).

    The sampled token feeds the next step without leaving the device, so the
    host pays one dispatch round trip per `num_steps` tokens — the decisive
    lever when dispatch latency (not compute) bounds small-batch decode.
    Returns tokens [B, num_steps]; tokens sampled past a request's stop point
    are dropped host-side at harvest (engine.py), so output text is exact.
    """

    def body(carry, _):
        st, cache = carry
        logits, cache = decode_step_impl(params, cfg, st.tokens, cache,
                                         block_tables, st.positions,
                                         attn_mode=attn_mode,
                                         attn_mesh=attn_mesh,
                                         attn_axis=attn_axis,
                                         fused_kv_write=fused_kv_write)
        keys = make_row_keys(samp.seeds, st.steps)
        out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
        new_st = DecodeState(tokens=out, positions=st.positions + 1, steps=st.steps + 1)
        return (new_st, cache), out

    (state, cache), toks = jax.lax.scan(body, (state, cache), None, length=num_steps)
    return state, cache, toks.T  # [B, num_steps]


def _spec_verify_sample_impl(params, cfg: ModelConfig, cache, block_tables,
                             state: DecodeState, samp: SamplingArrays,
                             drafts: jax.Array,
                             num_steps: int = 1, spec_tokens: int = 3,
                             attn_mode=None, attn_mesh=None,
                             attn_axis=None):
    """`num_steps` fused speculative verify rounds in ONE dispatch.

    `drafts` [B, E] is the HOST-proposed continuation stream
    (ops/speculative.propose_stream — prompt-lookup over the engine's own
    token history, so no device-resident history buffer exists and the
    carry is a plain DecodeState, donor-able exactly like non-speculative
    decode). Each scan round: align into the stream by value
    (align_drafts — the lane's current last token anchors its γ drafts,
    which is what lets K rounds chain on device and stale host streams
    still hit under the overlapped loop), verify [last-accepted,
    draft 1..γ] in one multi-token model pass (verify_step_impl — the
    same ragged/multistep verify layout the paged kernels parity-pin,
    int8 dequant included), sample every position with its own
    (seed, step) PRNG key, keep the longest draft-consistent prefix,
    then COMMIT only the accepted inputs' KV: the touched pages (raw
    bytes + int8 scales) were snapshotted before the round's writes and
    rejected appends roll back via the serial write chain replay
    (ops/speculative.rollback_commit) — rejected drafts leave nothing
    behind (reject-independence, pinned by tests). Emits per round the
    full sample
    row [B, γ+1] plus the per-lane emitted count m ∈ [1, γ+1]; the host
    drops the discarded tail at harvest exactly like it drops post-stop
    tokens. Returns (state, cache, tokens [B, K, γ+1], counts [B, K]).

    Sampling-step keys advance by m per lane, so emitted token t of a
    request uses the same key as non-speculative decode would — output is
    identical with speculation on or off, up to step-shape numerics
    (bit-exact in fp32; see ops/speculative.py on the bf16 and int8
    transient-scale caveats).
    """
    s = spec_tokens + 1
    bs = cache.block_size
    capacity = block_tables.shape[1] * bs
    # Flattened per-(lane, position) sampling params; row order matches
    # logits.reshape(B*S, V): row = lane*S + position.
    temp_f = jnp.repeat(samp.temperature, s)
    topk_f = jnp.repeat(samp.top_k, s)
    topp_f = jnp.repeat(samp.top_p, s)
    seeds_f = jnp.repeat(samp.seeds, s)
    offs = jnp.arange(s, dtype=jnp.int32)

    def body(carry, _):
        st, cache = carry
        drafts_k = align_drafts(drafts, st.tokens, spec_tokens)   # [B, γ]
        inputs = jnp.concatenate([st.tokens[:, None], drafts_k], axis=1)  # [B, S]
        blks = touched_pages(block_tables, st.positions, s, bs)
        snap = snapshot_pages(cache, blks)
        logits, cache, k_seq, v_seq = verify_step_impl(
            params, cfg, inputs, cache, block_tables, st.positions,
            attn_mode=attn_mode, attn_mesh=attn_mesh, attn_axis=attn_axis,
            return_kv=True)
        b = inputs.shape[0]
        steps_f = (st.steps[:, None] + offs[None]).reshape(-1)
        keys = make_row_keys(seeds_f, steps_f)
        toks = sample(logits.reshape(b * s, -1), keys,
                      temp_f, topk_f, topp_f).reshape(b, s)
        m = accept_counts(toks, drafts_k)                               # [B]
        cache = rollback_commit(cache, snap, blks, k_seq, v_seq,
                                block_tables, st.positions, m, capacity)
        last = jnp.take_along_axis(toks, (m - 1)[:, None], axis=1)[:, 0]
        new_st = DecodeState(tokens=last, positions=st.positions + m,
                             steps=st.steps + m)
        return (new_st, cache), (toks, m)

    (state, cache), (toks, counts) = jax.lax.scan(
        body, (state, cache), None, length=num_steps)
    return state, cache, toks.transpose(1, 0, 2), counts.T  # [B,K,S], [B,K]


class ModelRunner:
    """Single-device runner. Owns the jitted step programs (not the cache)."""

    def __init__(self, cfg: ModelConfig, params, decode_steps: int = 1,
                 spec_tokens: int = 0, spec_ngram: int = 3,
                 fused_kv_write: bool = False) -> None:
        self.cfg = cfg
        self.params = params
        self.decode_steps = max(1, int(decode_steps))
        self.spec_tokens = max(0, int(spec_tokens))
        # Consumed by the ENGINE's host-side proposal (round 14 — no jit
        # reads it): engine._propose_drafts prefers this value over its
        # cfg's, so a runner built with a different lookup length keeps
        # meaning something.
        self.spec_ngram = max(1, int(spec_ngram))
        # LLM_FUSED_KV_WRITE: decode dispatches write the fresh token KV
        # inside the paged-attention call (in-kernel on dma2/dma3,
        # functionally elsewhere) and the hybrid dispatch folds its chunk
        # page scatter into the ragged kernel. Baked into the jits below,
        # so an engine must be built with a matching runner.
        self.fused_kv_write = bool(fused_kv_write)
        self._prefill = jax.jit(
            partial(_prefill_sample_impl, cfg=cfg,
                    kv_writer_mode=self.kv_writer_mode,
                    attn_mode=self.prefill_attn_mode,
                    attn_mesh=self.prefill_attn_mesh,
                    attn_axis=self.prefill_attn_axis),
            donate_argnames=("cache",),
        )
        self._prefill_chunk = jax.jit(
            partial(_prefill_chunk_sample_impl, cfg=cfg,
                    kv_writer_mode=self.kv_writer_mode,
                    attn_mode=self.chunk_attn_mode,
                    attn_mesh=self.prefill_attn_mesh,
                    attn_axis=self.prefill_attn_axis),
            donate_argnames=("cache",),
        )
        self._hybrid = jax.jit(
            partial(_hybrid_sample_impl, cfg=cfg,
                    attn_mode=self.hybrid_attn_mode,
                    fused_kv_write=self.fused_kv_write),
            donate_argnames=("cache",),
        )
        self._prefill_pipeline = jax.jit(
            partial(_prefill_pipeline_sample_impl, cfg=cfg,
                    kv_writer_mode=self.kv_writer_mode,
                    attn_mode=self.pipeline_attn_mode),
            donate_argnames=("cache", "carry"),
        )
        if self.spec_tokens > 0:
            # The speculative verify dispatch: drafts arrive host-proposed
            # per dispatch, the carry is a plain DecodeState — so the
            # overlapped-loop variant below is the same donation shape as
            # non-speculative decode (round 14; overlap x spec composes).
            spec_impl = partial(
                _spec_verify_sample_impl, cfg=cfg,
                num_steps=self.decode_steps, spec_tokens=self.spec_tokens,
                attn_mode=self.attn_mode, attn_mesh=self.attn_mesh,
                attn_axis=self.attn_axis)
            self._decode = jax.jit(spec_impl, donate_argnames=("cache",))
            self._decode_overlapped = jax.jit(
                spec_impl, donate_argnames=("cache", "state"))
        else:
            self._decode = jax.jit(
                partial(_decode_sample_impl, cfg=cfg, num_steps=self.decode_steps,
                        attn_mode=self.attn_mode, attn_mesh=self.attn_mesh,
                        attn_axis=self.attn_axis,
                        fused_kv_write=self.fused_kv_write),
                donate_argnames=("cache",),
            )
            # Overlapped-decode variant (LLM_DECODE_OVERLAP): identical
            # numerics, but the DecodeState carry is DONATED too. With the
            # engine dispatching fused-step N+1 while N still executes,
            # XLA then ping-pongs exactly two state buffer sets (the
            # "two-slot carry") instead of allocating fresh [B] leaves per
            # dispatch — no host-side array churn in the hot loop. A
            # separate jit so the default path's programs stay
            # byte-identical to pre-knob builds.
            self._decode_overlapped = jax.jit(
                partial(_decode_sample_impl, cfg=cfg, num_steps=self.decode_steps,
                        attn_mode=self.attn_mode, attn_mesh=self.attn_mesh,
                        attn_axis=self.attn_axis,
                        fused_kv_write=self.fused_kv_write),
                donate_argnames=("cache", "state"),
            )

    #: chips the KV cache is sharded across (overridden by parallel/tp_runner.py)
    tp_size: int = 1
    #: decode-attention implementation baked into the jit (None = auto;
    #: the TP runner picks "shard_dma" on TPU / "gather" elsewhere —
    #: see ops/attention_backend.py)
    attn_mode: Optional[str] = None
    #: mesh + head-sharding axis for attn_mode="shard_dma" (TP runner sets)
    attn_mesh = None
    attn_axis: Optional[str] = None
    #: prompt-page KV writer baked into the prefill jit (None = auto;
    #: the TP runner forces "dus" — see ops/kv_writer.py)
    kv_writer_mode: Optional[str] = None
    #: prefill-attention implementation baked into the prefill jit (None =
    #: auto: flash on TPU / jnp oracle; the SP runner sets "ring_sp" with
    #: its mesh + axis — see models/llama.prefill_impl)
    prefill_attn_mode: Optional[str] = None
    prefill_attn_mesh = None
    prefill_attn_axis: Optional[str] = None
    #: chunk-attention implementation baked into the chunk jit (None =
    #: auto: gather + causal/flash site; the SP runners set "ring_sp" —
    #: the round-5 chunk-ring hybrid, models/llama.prefill_chunk_impl —
    #: reusing prefill_attn_mesh/axis)
    chunk_attn_mode: Optional[str] = None
    #: whether this runner's chunk jit serves the engine's chunked-prefill
    #: path faithfully (since round 5 every runner does: the SP runners'
    #: chunk jit rides the chunk-ring hybrid)
    supports_chunked_prefill: bool = True
    #: ragged-attention implementation baked into the hybrid jit (None =
    #: auto: ragged Pallas kernel on TPU, jnp grouped-gather oracle
    #: elsewhere — ops/attention_backend.hybrid_ragged_attention)
    hybrid_attn_mode: Optional[str] = None
    #: whether this runner serves the engine's fused hybrid prefill+decode
    #: path (hybrid_token_budget > 0). The mesh runners don't yet: the
    #: ragged kernel has no shard_map wrapper, so a hybrid step there
    #: would all-gather the head-sharded pool (parallel/ runners set
    #: False).
    supports_hybrid: bool = True
    #: attention mode baked into the pipelined-prefill chunk jit (None =
    #: auto: flash on TPU / jnp oracle; no mesh mode exists — see below)
    pipeline_attn_mode: Optional[str] = None
    #: whether this runner serves the engine's pipelined-prefill path
    #: (prefill_pipeline_chunks >= 2). The mesh runners don't: their
    #: prefill parallelism (ring sp, staged pp, head-sharded tp) has no
    #: pipelined-chunk wrapper yet, and silently running the single-chip
    #: jit replicated would serve the knob's name without its meaning
    #: (parallel/ runners set False).
    supports_prefill_pipeline: bool = True
    #: whether this runner serves the engine's overlapped decode loop
    #: (decode_overlap=1, round 7): the fast path needs the donated
    #: two-slot decode jit above. The mesh runners don't — their sharded
    #: decode wrappers were built without state donation, and the fast
    #: path's device-resident table scatter has no shard_map rule, so the
    #: engine refuses the knob at build (parallel/ runners set False),
    #: matching the hybrid/pipeline precedent.
    supports_decode_overlap: bool = True
    #: whether this runner serves the scaled int8 KV pool
    #: (kv_cache_dtype="int8", round 10). The mesh runners don't: the
    #: shard_dma attention wrapper has no scale-sharding rule, and the
    #: sharded gather path would replicate the scale arrays incoherently
    #: with a head-sharded pool — the engine refuses at build (parallel/
    #: runners set False). fp8 pages (scale-free casts) are unaffected.
    supports_quantized_kv: bool = True
    #: whether this runner serves the fused KV-write decode/hybrid
    #: dispatches (LLM_FUSED_KV_WRITE, round 10): the mesh runners' sharded
    #: wrappers have no aliasing rule for the in-kernel pool writes, so the
    #: engine refuses the knob at build (parallel/ runners set False).
    supports_fused_kv_write: bool = True
    #: whether this runner serves live stream migration (LLM_MIGRATION,
    #: round 11): checkpoint slices KV pages straight off the single-chip
    #: pool (engine.checkpoint_request) and adopt writes them into a
    #: fresh single-chip pool — the mesh runners' sharded/staged caches
    #: have no per-block host slicing or restore-write rule, so the
    #: engine refuses the knob at build (parallel/ runners set False).
    supports_migration: bool = True
    #: whether this runner serves n-gram speculative decoding
    #: (LLM_SPECULATION, rebuilt round 14): drafts are host-proposed and
    #: the verify carry is a plain DecodeState, so the single-chip runner
    #: AND the tp/sp runners serve it (the verify pass rides the same
    #: shard-mapped/gather attention as plain decode — pinned by
    #: tests/test_parallel.py). PPRunner alone declares False: the staged
    #: pipeline jits have no multi-token verify stage, and its
    #: constructor refuses spec_tokens outright — the engine refuses a
    #: supplied speculative runner at build via this flag.
    supports_speculation: bool = True

    def prepare_cache(self, cache: KVCache) -> KVCache:
        """Hook for placing a freshly allocated cache (TP runner shards it)."""
        return cache

    # statics: hot-region(dispatch-wrappers)
    def prefill(self, tokens, cache, block_tables, seq_lens, samp, steps):
        """-> (DecodeState, cache, sampled_first_tokens [B])."""
        return self._prefill(self.params, tokens=tokens, cache=cache,
                             block_tables=block_tables, seq_lens=seq_lens,
                             samp=samp, steps=steps)

    # statics: hot-region(dispatch-wrappers)
    def prefill_chunk(self, tokens, cache, block_tables, chunk_start,
                      chunk_len, samp, steps):
        """-> (cache, sampled_last_chunk_tokens [1])."""
        return self._prefill_chunk(
            self.params, tokens=tokens, cache=cache, block_tables=block_tables,
            chunk_start=chunk_start, chunk_len=chunk_len, samp=samp, steps=steps,
        )

    # statics: hot-region(dispatch-wrappers)
    def prefill_pipeline(self, tokens, cache, block_tables, chunk_start,
                         seq_lens, carry, samp, steps):
        """One position-chunk of a pipelined prefill -> (cache, carry).

        `carry` [B] i32 accumulates each row's sampled first token (merged
        on the chunk containing the row's last real token); `chunk_start`
        is a traced scalar, so all K chunks of a (batch, chunk) bucket
        share ONE compiled program. cache and carry are donated — the
        engine dispatches chunks back-to-back and reads carry once."""
        return self._prefill_pipeline(
            self.params, tokens=tokens, cache=cache,
            block_tables=block_tables, chunk_start=chunk_start,
            seq_lens=seq_lens, carry=carry, samp=samp, steps=steps)

    # statics: hot-region(dispatch-wrappers)
    def hybrid(self, dec_tokens, chunk_tokens, cache, block_tables,
               positions, chunk_start, chunk_len, samp, steps):
        """One fused hybrid dispatch: B decode lanes + one prefill chunk.

        block_tables is [B+1, W] (row B = the chunk's); samp/steps cover
        B+1 lanes (chunk last). -> (DecodeState [B lanes], cache,
        decode tokens [B], chunk last-token sample [1])."""
        return self._hybrid(
            self.params, dec_tokens=dec_tokens, chunk_tokens=chunk_tokens,
            cache=cache, block_tables=block_tables, positions=positions,
            chunk_start=chunk_start, chunk_len=chunk_len, samp=samp,
            steps=steps,
        )

    # statics: hot-region(dispatch-wrappers)
    def decode(self, cache, block_tables, state, samp, drafts=None):
        """One fused dispatch covering `decode_steps` model steps. `state`
        is a DecodeState either way.

        Non-speculative (spec_tokens == 0): returns (DecodeState, cache,
        tokens [B, decode_steps]); `drafts` must be None.
        Speculative: `drafts` is the host-proposed [B, E] continuation
        stream (each round aligns into it by value on device — see
        ops/speculative.align_drafts); returns (DecodeState, cache, tokens
        [B, decode_steps, spec_tokens+1], counts [B, decode_steps]) — the
        engine keeps counts[b, k] tokens of row k. The verify pass writes
        through the chained writers regardless of `fused_kv_write` (the
        in-kernel fused write carries exactly one token; the multi-token
        verify chain IS its write sequence), so the knob composes
        functionally: every single-token dispatch stays fused."""
        if self.spec_tokens > 0:
            return self._decode(self.params, cache=cache,
                                block_tables=block_tables, state=state,
                                samp=samp, drafts=drafts)
        return self._decode(self.params, cache=cache, block_tables=block_tables,
                            state=state, samp=samp)

    # statics: hot-region(dispatch-wrappers)
    def decode_overlapped(self, cache, block_tables, state, samp, drafts=None):
        """decode() with the DecodeState carry donated (LLM_DECODE_OVERLAP
        hot loop). Callers must treat `state` as consumed — the engine
        replaces its reference with the returned state, and the in-flight
        token outputs are separate buffers, so the donation is invisible
        outside the dispatch. The speculative variant takes the same
        host-proposed `drafts` operand as decode()."""
        if self.spec_tokens > 0:
            return self._decode_overlapped(
                self.params, cache=cache, block_tables=block_tables,
                state=state, samp=samp, drafts=drafts)
        return self._decode_overlapped(
            self.params, cache=cache, block_tables=block_tables,
            state=state, samp=samp)

    def compile_stats(self) -> dict:
        return {
            "prefill_variants": self._prefill._cache_size() if hasattr(self._prefill, "_cache_size") else -1,
            "decode_variants": self._decode._cache_size() if hasattr(self._decode, "_cache_size") else -1,
        }
