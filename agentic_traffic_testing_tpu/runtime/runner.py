"""ModelRunner: fused device dispatches for the serving engine.

Each scheduled step is ONE device dispatch: `decode_steps` fused model steps
+ on-device sampling, with each sampled token fed straight back as the next
step's input without touching the host. This matters doubly on TPU: (a) XLA
fuses the sampling epilogue into the decode program; (b) host↔device round
trips are expensive at small batch (observed ~10-100 ms through the axon
tunnel vs ~ms of compute), so the engine only *reads back* a [B, decode_steps]
int32 token array — asynchronously, with a configurable lag (engine.py).

The vLLM analog is the streaming `engine.generate` hot loop the reference
consumes (reference: llm/serve_llm.py:527-605); there the engine process owns
the GPU loop, here the runner owns jitted TPU programs. A tensor-parallel
runner (parallel/tp_runner.py) subclasses this and shards the same impl
functions over a mesh.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.models.llama import (
    decode_step_impl,
    hybrid_step_impl,
    prefill_chunk_impl,
    prefill_impl,
    prefill_pipeline_impl,
    verify_step_impl,
)
from agentic_traffic_testing_tpu.ops.sampling import make_row_keys, sample
from agentic_traffic_testing_tpu.ops.speculative import (
    accept_counts,
    propose_ngram,
    update_history,
)
from agentic_traffic_testing_tpu.runtime.kv_cache import KVCache


class SamplingArrays(NamedTuple):
    """Per-lane sampling parameters, device-resident for a batch's lifetime."""

    temperature: jax.Array  # [B] f32
    top_k: jax.Array        # [B] i32
    top_p: jax.Array        # [B] f32
    seeds: jax.Array        # [B] i32


class DecodeState(NamedTuple):
    """Device-resident state that advances without host involvement."""

    tokens: jax.Array     # [B] i32 — input token for the next step
    positions: jax.Array  # [B] i32 — position of `tokens`
    steps: jax.Array      # [B] i32 — per-request sampling step (PRNG stream)


class SpecDecodeState(NamedTuple):
    """DecodeState + the token history n-gram speculation proposes from.

    `history[b, :positions[b]+1]` is the sequence so far (prompt + accepted
    output); it advances on device with the accepted samples each step, so
    proposal/verify/accept all stay inside the fused scan.
    """

    tokens: jax.Array     # [B] i32 — last accepted token
    positions: jax.Array  # [B] i32 — its position
    steps: jax.Array      # [B] i32 — per-request sampling step (PRNG stream)
    history: jax.Array    # [B, L] i32 — token history buffer


def _prefill_sample_impl(params, cfg: ModelConfig, tokens, cache, block_tables,
                         seq_lens, samp: SamplingArrays, steps,
                         kv_writer_mode=None, attn_mode=None, attn_mesh=None,
                         attn_axis=None):
    logits, cache = prefill_impl(params, cfg, tokens, cache, block_tables,
                                 seq_lens, kv_writer_mode=kv_writer_mode,
                                 attn_mode=attn_mode, attn_mesh=attn_mesh,
                                 attn_axis=attn_axis)
    keys = make_row_keys(samp.seeds, steps)
    out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
    state = DecodeState(tokens=out, positions=seq_lens, steps=steps + 1)
    return state, cache, out


def _prefill_chunk_sample_impl(params, cfg: ModelConfig, tokens, cache,
                               block_tables, chunk_start, chunk_len,
                               samp: SamplingArrays, steps,
                               kv_writer_mode=None, attn_mode=None,
                               attn_mesh=None, attn_axis=None):
    """One chunk of a chunked prefill + sampling of the chunk's last token
    (the sample only matters on the final chunk; earlier chunks discard it)."""
    logits, cache = prefill_chunk_impl(params, cfg, tokens, cache,
                                       block_tables, chunk_start, chunk_len,
                                       kv_writer_mode=kv_writer_mode,
                                       attn_mode=attn_mode,
                                       attn_mesh=attn_mesh,
                                       attn_axis=attn_axis)
    keys = make_row_keys(samp.seeds, steps)
    out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
    return cache, out


def _prefill_pipeline_sample_impl(params, cfg: ModelConfig, tokens, cache,
                                  block_tables, chunk_start, seq_lens, carry,
                                  samp: SamplingArrays, steps,
                                  kv_writer_mode=None, attn_mode=None):
    """One position-chunk of a pipelined prefill + carry-merged sampling.

    Every chunk samples its per-row logits with the SAME (seed, step) keys
    the single-dispatch prefill would use, then merges into `carry` only
    the rows whose last real token fell inside this chunk — so after the
    final chunk, `carry` holds exactly the tokens the fused prefill+sample
    dispatch would have produced, with zero host synchronization between
    chunks (engine reads `carry` back once, at the tail). `cache` and
    `carry` are donated: the K dispatches chain device-side buffers.
    """
    logits, cache = prefill_pipeline_impl(
        params, cfg, tokens, cache, block_tables, chunk_start, seq_lens,
        kv_writer_mode=kv_writer_mode, attn_mode=attn_mode)
    keys = make_row_keys(samp.seeds, steps)
    out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
    c = tokens.shape[1]
    last = seq_lens - 1
    mine = jnp.logical_and(last >= chunk_start, last < chunk_start + c)
    return cache, jnp.where(mine, out, carry)


def _hybrid_sample_impl(params, cfg: ModelConfig, dec_tokens, chunk_tokens,
                        cache, block_tables, positions, chunk_start,
                        chunk_len, samp: SamplingArrays, steps,
                        attn_mode=None, fused_kv_write=False):
    """One FUSED hybrid step (B decode lanes + one prefill chunk in a
    single ragged dispatch) + sampling for every row.

    `samp`/`steps` cover B+1 lanes: the B decode lanes first, the chunk's
    request last. Returns (DecodeState for the B decode lanes, cache,
    decode tokens [B], chunk's sampled last token [1] — meaningful only on
    the final chunk, exactly like prefill_chunk's sample)."""
    b = dec_tokens.shape[0]
    dec_logits, chunk_logits, cache = hybrid_step_impl(
        params, cfg, dec_tokens, chunk_tokens, cache, block_tables,
        positions, chunk_start, chunk_len, attn_mode=attn_mode,
        fused_kv_write=fused_kv_write)
    keys = make_row_keys(samp.seeds, steps)
    out = sample(jnp.concatenate([dec_logits, chunk_logits]), keys,
                 samp.temperature, samp.top_k, samp.top_p)
    state = DecodeState(tokens=out[:b], positions=positions + 1,
                        steps=steps[:b] + 1)
    return state, cache, out[:b], out[b:]


def _decode_sample_impl(params, cfg: ModelConfig, cache, block_tables,
                        state: DecodeState, samp: SamplingArrays,
                        num_steps: int = 1, attn_mode=None, attn_mesh=None,
                        attn_axis=None, fused_kv_write=False):
    """`num_steps` fused decode steps in ONE dispatch (lax.scan on device).

    The sampled token feeds the next step without leaving the device, so the
    host pays one dispatch round trip per `num_steps` tokens — the decisive
    lever when dispatch latency (not compute) bounds small-batch decode.
    Returns tokens [B, num_steps]; tokens sampled past a request's stop point
    are dropped host-side at harvest (engine.py), so output text is exact.
    """

    def body(carry, _):
        st, cache = carry
        logits, cache = decode_step_impl(params, cfg, st.tokens, cache,
                                         block_tables, st.positions,
                                         attn_mode=attn_mode,
                                         attn_mesh=attn_mesh,
                                         attn_axis=attn_axis,
                                         fused_kv_write=fused_kv_write)
        keys = make_row_keys(samp.seeds, st.steps)
        out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
        new_st = DecodeState(tokens=out, positions=st.positions + 1, steps=st.steps + 1)
        return (new_st, cache), out

    (state, cache), toks = jax.lax.scan(body, (state, cache), None, length=num_steps)
    return state, cache, toks.T  # [B, num_steps]


def _spec_decode_sample_impl(params, cfg: ModelConfig, cache, block_tables,
                             state: SpecDecodeState, samp: SamplingArrays,
                             num_steps: int = 1, spec_tokens: int = 3,
                             ngram: int = 3, attn_mode=None, attn_mesh=None,
                             attn_axis=None):
    """`num_steps` fused n-gram-speculative steps in ONE dispatch.

    Each scan iteration: propose γ=spec_tokens drafts from the device-resident
    history (ops/speculative.py), verify all γ+1 positions in one model step
    (verify_step_impl), sample every position with its own (seed, step) PRNG
    key, keep the longest draft-consistent prefix. Emits per iteration the
    full sample row [B, γ+1] plus the per-lane emitted count m ∈ [1, γ+1];
    the host drops the discarded tail at harvest exactly like it drops
    post-stop tokens. Returns (state, cache, tokens [B, K, γ+1], counts [B, K]).

    Sampling-step keys advance by m per lane, so emitted token t of a request
    uses the same key as non-speculative decode would — output is identical
    with speculation on or off, up to step-shape numerics (bit-exact in fp32;
    see ops/speculative.py on the bf16 caveat).
    """
    s = spec_tokens + 1
    # Flattened per-(lane, position) sampling params; row order matches
    # logits.reshape(B*S, V): row = lane*S + position.
    temp_f = jnp.repeat(samp.temperature, s)
    topk_f = jnp.repeat(samp.top_k, s)
    topp_f = jnp.repeat(samp.top_p, s)
    seeds_f = jnp.repeat(samp.seeds, s)
    offs = jnp.arange(s, dtype=jnp.int32)

    def body(carry, _):
        st, cache = carry
        drafts = propose_ngram(st.history, st.positions, spec_tokens, ngram)
        inputs = jnp.concatenate([st.tokens[:, None], drafts], axis=1)  # [B, S]
        logits, cache = verify_step_impl(params, cfg, inputs, cache,
                                         block_tables, st.positions,
                                         attn_mode=attn_mode,
                                         attn_mesh=attn_mesh,
                                         attn_axis=attn_axis)
        b = inputs.shape[0]
        steps_f = (st.steps[:, None] + offs[None]).reshape(-1)
        keys = make_row_keys(seeds_f, steps_f)
        toks = sample(logits.reshape(b * s, -1), keys,
                      temp_f, topk_f, topp_f).reshape(b, s)
        m = accept_counts(toks, drafts)                                 # [B]
        last = jnp.take_along_axis(toks, (m - 1)[:, None], axis=1)[:, 0]
        hist = update_history(st.history, toks, st.positions)
        new_st = SpecDecodeState(tokens=last, positions=st.positions + m,
                                 steps=st.steps + m, history=hist)
        return (new_st, cache), (toks, m)

    (state, cache), (toks, counts) = jax.lax.scan(
        body, (state, cache), None, length=num_steps)
    return state, cache, toks.transpose(1, 0, 2), counts.T  # [B,K,S], [B,K]


class ModelRunner:
    """Single-device runner. Owns the jitted step programs (not the cache)."""

    def __init__(self, cfg: ModelConfig, params, decode_steps: int = 1,
                 spec_tokens: int = 0, spec_ngram: int = 3,
                 fused_kv_write: bool = False) -> None:
        self.cfg = cfg
        self.params = params
        self.decode_steps = max(1, int(decode_steps))
        self.spec_tokens = max(0, int(spec_tokens))
        self.spec_ngram = max(1, int(spec_ngram))
        # LLM_FUSED_KV_WRITE: decode dispatches write the fresh token KV
        # inside the paged-attention call (in-kernel on dma2/dma3,
        # functionally elsewhere) and the hybrid dispatch folds its chunk
        # page scatter into the ragged kernel. Baked into the jits below,
        # so an engine must be built with a matching runner.
        self.fused_kv_write = bool(fused_kv_write)
        self._prefill = jax.jit(
            partial(_prefill_sample_impl, cfg=cfg,
                    kv_writer_mode=self.kv_writer_mode,
                    attn_mode=self.prefill_attn_mode,
                    attn_mesh=self.prefill_attn_mesh,
                    attn_axis=self.prefill_attn_axis),
            donate_argnames=("cache",),
        )
        self._prefill_chunk = jax.jit(
            partial(_prefill_chunk_sample_impl, cfg=cfg,
                    kv_writer_mode=self.kv_writer_mode,
                    attn_mode=self.chunk_attn_mode,
                    attn_mesh=self.prefill_attn_mesh,
                    attn_axis=self.prefill_attn_axis),
            donate_argnames=("cache",),
        )
        self._hybrid = jax.jit(
            partial(_hybrid_sample_impl, cfg=cfg,
                    attn_mode=self.hybrid_attn_mode,
                    fused_kv_write=self.fused_kv_write),
            donate_argnames=("cache",),
        )
        self._prefill_pipeline = jax.jit(
            partial(_prefill_pipeline_sample_impl, cfg=cfg,
                    kv_writer_mode=self.kv_writer_mode,
                    attn_mode=self.pipeline_attn_mode),
            donate_argnames=("cache", "carry"),
        )
        if self.spec_tokens > 0:
            self._decode = jax.jit(
                partial(_spec_decode_sample_impl, cfg=cfg,
                        num_steps=self.decode_steps,
                        spec_tokens=self.spec_tokens, ngram=self.spec_ngram,
                        attn_mode=self.attn_mode, attn_mesh=self.attn_mesh,
                        attn_axis=self.attn_axis),
                donate_argnames=("cache",),
            )
            self._decode_overlapped = None  # engine refuses overlap x spec
        else:
            self._decode = jax.jit(
                partial(_decode_sample_impl, cfg=cfg, num_steps=self.decode_steps,
                        attn_mode=self.attn_mode, attn_mesh=self.attn_mesh,
                        attn_axis=self.attn_axis,
                        fused_kv_write=self.fused_kv_write),
                donate_argnames=("cache",),
            )
            # Overlapped-decode variant (LLM_DECODE_OVERLAP): identical
            # numerics, but the DecodeState carry is DONATED too. With the
            # engine dispatching fused-step N+1 while N still executes,
            # XLA then ping-pongs exactly two state buffer sets (the
            # "two-slot carry") instead of allocating fresh [B] leaves per
            # dispatch — no host-side array churn in the hot loop. A
            # separate jit so the default path's programs stay
            # byte-identical to pre-knob builds.
            self._decode_overlapped = jax.jit(
                partial(_decode_sample_impl, cfg=cfg, num_steps=self.decode_steps,
                        attn_mode=self.attn_mode, attn_mesh=self.attn_mesh,
                        attn_axis=self.attn_axis,
                        fused_kv_write=self.fused_kv_write),
                donate_argnames=("cache", "state"),
            )

    #: chips the KV cache is sharded across (overridden by parallel/tp_runner.py)
    tp_size: int = 1
    #: decode-attention implementation baked into the jit (None = auto;
    #: the TP runner picks "shard_dma" on TPU / "gather" elsewhere —
    #: see ops/attention_backend.py)
    attn_mode: Optional[str] = None
    #: mesh + head-sharding axis for attn_mode="shard_dma" (TP runner sets)
    attn_mesh = None
    attn_axis: Optional[str] = None
    #: prompt-page KV writer baked into the prefill jit (None = auto;
    #: the TP runner forces "dus" — see ops/kv_writer.py)
    kv_writer_mode: Optional[str] = None
    #: prefill-attention implementation baked into the prefill jit (None =
    #: auto: flash on TPU / jnp oracle; the SP runner sets "ring_sp" with
    #: its mesh + axis — see models/llama.prefill_impl)
    prefill_attn_mode: Optional[str] = None
    prefill_attn_mesh = None
    prefill_attn_axis: Optional[str] = None
    #: chunk-attention implementation baked into the chunk jit (None =
    #: auto: gather + causal/flash site; the SP runners set "ring_sp" —
    #: the round-5 chunk-ring hybrid, models/llama.prefill_chunk_impl —
    #: reusing prefill_attn_mesh/axis)
    chunk_attn_mode: Optional[str] = None
    #: whether this runner's chunk jit serves the engine's chunked-prefill
    #: path faithfully (since round 5 every runner does: the SP runners'
    #: chunk jit rides the chunk-ring hybrid)
    supports_chunked_prefill: bool = True
    #: ragged-attention implementation baked into the hybrid jit (None =
    #: auto: ragged Pallas kernel on TPU, jnp grouped-gather oracle
    #: elsewhere — ops/attention_backend.hybrid_ragged_attention)
    hybrid_attn_mode: Optional[str] = None
    #: whether this runner serves the engine's fused hybrid prefill+decode
    #: path (hybrid_token_budget > 0). The mesh runners don't yet: the
    #: ragged kernel has no shard_map wrapper, so a hybrid step there
    #: would all-gather the head-sharded pool (parallel/ runners set
    #: False).
    supports_hybrid: bool = True
    #: attention mode baked into the pipelined-prefill chunk jit (None =
    #: auto: flash on TPU / jnp oracle; no mesh mode exists — see below)
    pipeline_attn_mode: Optional[str] = None
    #: whether this runner serves the engine's pipelined-prefill path
    #: (prefill_pipeline_chunks >= 2). The mesh runners don't: their
    #: prefill parallelism (ring sp, staged pp, head-sharded tp) has no
    #: pipelined-chunk wrapper yet, and silently running the single-chip
    #: jit replicated would serve the knob's name without its meaning
    #: (parallel/ runners set False).
    supports_prefill_pipeline: bool = True
    #: whether this runner serves the engine's overlapped decode loop
    #: (decode_overlap=1, round 7): the fast path needs the donated
    #: two-slot decode jit above. The mesh runners don't — their sharded
    #: decode wrappers were built without state donation, and the fast
    #: path's device-resident table scatter has no shard_map rule, so the
    #: engine refuses the knob at build (parallel/ runners set False),
    #: matching the hybrid/pipeline precedent.
    supports_decode_overlap: bool = True
    #: whether this runner serves the scaled int8 KV pool
    #: (kv_cache_dtype="int8", round 10). The mesh runners don't: the
    #: shard_dma attention wrapper has no scale-sharding rule, and the
    #: sharded gather path would replicate the scale arrays incoherently
    #: with a head-sharded pool — the engine refuses at build (parallel/
    #: runners set False). fp8 pages (scale-free casts) are unaffected.
    supports_quantized_kv: bool = True
    #: whether this runner serves the fused KV-write decode/hybrid
    #: dispatches (LLM_FUSED_KV_WRITE, round 10): the mesh runners' sharded
    #: wrappers have no aliasing rule for the in-kernel pool writes, so the
    #: engine refuses the knob at build (parallel/ runners set False).
    supports_fused_kv_write: bool = True
    #: whether this runner serves live stream migration (LLM_MIGRATION,
    #: round 11): checkpoint slices KV pages straight off the single-chip
    #: pool (engine.checkpoint_request) and adopt writes them into a
    #: fresh single-chip pool — the mesh runners' sharded/staged caches
    #: have no per-block host slicing or restore-write rule, so the
    #: engine refuses the knob at build (parallel/ runners set False).
    supports_migration: bool = True

    def prepare_cache(self, cache: KVCache) -> KVCache:
        """Hook for placing a freshly allocated cache (TP runner shards it)."""
        return cache

    # statics: hot-region(dispatch-wrappers)
    def prefill(self, tokens, cache, block_tables, seq_lens, samp, steps):
        """-> (DecodeState, cache, sampled_first_tokens [B])."""
        return self._prefill(self.params, tokens=tokens, cache=cache,
                             block_tables=block_tables, seq_lens=seq_lens,
                             samp=samp, steps=steps)

    # statics: hot-region(dispatch-wrappers)
    def prefill_chunk(self, tokens, cache, block_tables, chunk_start,
                      chunk_len, samp, steps):
        """-> (cache, sampled_last_chunk_tokens [1])."""
        return self._prefill_chunk(
            self.params, tokens=tokens, cache=cache, block_tables=block_tables,
            chunk_start=chunk_start, chunk_len=chunk_len, samp=samp, steps=steps,
        )

    # statics: hot-region(dispatch-wrappers)
    def prefill_pipeline(self, tokens, cache, block_tables, chunk_start,
                         seq_lens, carry, samp, steps):
        """One position-chunk of a pipelined prefill -> (cache, carry).

        `carry` [B] i32 accumulates each row's sampled first token (merged
        on the chunk containing the row's last real token); `chunk_start`
        is a traced scalar, so all K chunks of a (batch, chunk) bucket
        share ONE compiled program. cache and carry are donated — the
        engine dispatches chunks back-to-back and reads carry once."""
        return self._prefill_pipeline(
            self.params, tokens=tokens, cache=cache,
            block_tables=block_tables, chunk_start=chunk_start,
            seq_lens=seq_lens, carry=carry, samp=samp, steps=steps)

    # statics: hot-region(dispatch-wrappers)
    def hybrid(self, dec_tokens, chunk_tokens, cache, block_tables,
               positions, chunk_start, chunk_len, samp, steps):
        """One fused hybrid dispatch: B decode lanes + one prefill chunk.

        block_tables is [B+1, W] (row B = the chunk's); samp/steps cover
        B+1 lanes (chunk last). -> (DecodeState [B lanes], cache,
        decode tokens [B], chunk last-token sample [1])."""
        return self._hybrid(
            self.params, dec_tokens=dec_tokens, chunk_tokens=chunk_tokens,
            cache=cache, block_tables=block_tables, positions=positions,
            chunk_start=chunk_start, chunk_len=chunk_len, samp=samp,
            steps=steps,
        )

    # statics: hot-region(dispatch-wrappers)
    def decode(self, cache, block_tables, state, samp):
        """One fused dispatch covering `decode_steps` model steps.

        Non-speculative (spec_tokens == 0): state is a DecodeState; returns
        (DecodeState, cache, tokens [B, decode_steps]).
        Speculative: state is a SpecDecodeState; returns (SpecDecodeState,
        cache, tokens [B, decode_steps, spec_tokens+1], counts
        [B, decode_steps]) — the engine keeps counts[b, k] tokens of row k."""
        return self._decode(self.params, cache=cache, block_tables=block_tables,
                            state=state, samp=samp)

    # statics: hot-region(dispatch-wrappers)
    def decode_overlapped(self, cache, block_tables, state, samp):
        """decode() with the DecodeState carry donated (LLM_DECODE_OVERLAP
        hot loop; non-speculative only). Callers must treat `state` as
        consumed — the engine replaces its reference with the returned
        state, and the in-flight token outputs are separate buffers, so
        the donation is invisible outside the dispatch."""
        return self._decode_overlapped(
            self.params, cache=cache, block_tables=block_tables,
            state=state, samp=samp)

    def compile_stats(self) -> dict:
        return {
            "prefill_variants": self._prefill._cache_size() if hasattr(self._prefill, "_cache_size") else -1,
            "decode_variants": self._decode._cache_size() if hasattr(self._decode, "_cache_size") else -1,
        }
