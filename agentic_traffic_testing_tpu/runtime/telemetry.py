"""Step-clock telemetry plane: bounded request-lifecycle + dispatch tracing.

The engine between `enqueue` and `finish_time` used to be a black box:
`serving/metrics.py` reproduces the reference's request-level families
(reference: llm/serve_llm.py:92-167) but nothing recorded *where inside
the engine* a request's latency went — queue vs prefill vs host-tier
restore vs decode — or what each device dispatch actually was. This
module is that instrument (ROADMAP item 2 needs per-request TTFT/ITL
classes as a first-class metric before the round-8 admission policy can
act on them; the vLLM-vs-TGI serving comparison in PAPERS.md frames
exactly these percentiles as the numbers that arbitrate serving designs).

Design constraints, in priority order:

  * OFF BY DEFAULT and absent from the hot loop: the engine holds
    `telemetry = None` unless `LLM_STEP_TRACE` is set, and every hook in
    engine.py is behind an `if rec is not None` guard — with the knob off
    the dispatch paths run byte-identically and the recorder performs
    ZERO per-step allocations (tests/test_telemetry.py pins this).
  * Allocation-light when ON: one `StepRecord` (a __slots__ object of
    scalars) per device dispatch / drain, appended to a bounded
    `deque(maxlen=...)` ring; per-request timelines are flat event
    tuples, retired into a second bounded ring. Nothing here ever calls
    into jax except `jax.profiler.TraceAnnotation` (a host-side trace
    label), so the statics host-sync lint stays green: every stamp is
    `time.monotonic()` on values already on the host path.
  * Thread-safe: the engine thread records, the HTTP thread reads. The
    exporter drain queues are lock-free (deque append/popleft are atomic
    under the GIL; the worst outcome is a sample landing in the next
    scrape), but the step ring and the timeline containers are iterated
    by readers, so a small mutex guards mutation and snapshotting —
    uncontended in the engine thread, and absent entirely with the knob
    off.

Three export surfaces read this recorder:

  1. Prometheus — `serving/metrics.py` drains the sample queues on
     scrape into `llm_ttft_seconds` / `llm_itl_seconds` /
     `llm_step_duration_seconds{phase}` / `llm_batch_occupancy` /
     `llm_slo_attainment_total{slo,status}`.
  2. Chrome trace-event JSON — `chrome_trace()` renders one track per
     replica (the step clock) plus one per request (phase spans),
     loadable in Perfetto; served by `GET /debug/timeline` and
     `scripts/dev/dump_timeline.py`.
  3. OTel — `utils/tracing.py emit_phase_spans` replays a retired
     request's timeline as child spans of the server's HTTP span.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict, deque
from typing import Optional

# Dispatch phase kinds (one per engine dispatch site). `DRAIN` is the
# harvest readback — the other half of the wall-time split.
PHASE_PREFILL = "prefill"
PHASE_PIPELINED_PREFILL = "pipelined_prefill"
PHASE_CHUNK = "chunk"
PHASE_HYBRID = "hybrid"
PHASE_DECODE = "decode"
PHASE_OVERLAPPED_DECODE = "overlapped_decode"
PHASE_SPECULATIVE_DECODE = "speculative_decode"
PHASE_DRAIN = "drain"

#: every phase a StepRecord can carry — the exporter pre-touches these
#: label values so a scrape shows zeroed series before traffic.
STEP_PHASES = (
    PHASE_PREFILL,
    PHASE_PIPELINED_PREFILL,
    PHASE_CHUNK,
    PHASE_HYBRID,
    PHASE_DECODE,
    PHASE_OVERLAPPED_DECODE,
    PHASE_SPECULATIVE_DECODE,
    PHASE_DRAIN,
)

# Instant (zero-duration) engine-track events.
EVENT_HOST_SAVE = "host_save"
EVENT_HOST_RESTORE = "host_restore"
EVENT_MISPREDICT = "overlap_mispredict"

# Per-request lifecycle event names, in their canonical order. `TOKENS`
# events repeat (one per harvest application); `RESTORE` is optional.
REQ_QUEUED = "queued"
REQ_ADMITTED = "admitted"
REQ_PREFILL_CHUNK = "prefill_chunk"
REQ_RESTORE = "restore"
REQ_FIRST_TOKEN = "first_token"
REQ_TOKENS = "tokens"
REQ_RETIRED = "retired"


class StepRecord:
    """One engine dispatch (or drain): the step clock's unit.

    `dur_s` is host wall time inside the engine's dispatch call — for
    async dispatches that is the host/tunnel cost of issuing the step
    (device compute overlaps); for `drain` it is the blocking readback.
    `predicted` marks an overlapped-decode fast-path dispatch."""

    __slots__ = ("seq", "kind", "t", "dur_s", "batch", "tokens", "predicted")

    def __init__(self, seq: int, kind: str, t: float, dur_s: float,
                 batch: int, tokens: int, predicted: bool = False) -> None:
        self.seq = seq
        self.kind = kind
        self.t = t
        self.dur_s = dur_s
        self.batch = batch
        self.tokens = tokens
        self.predicted = predicted


class RequestTimeline:
    """Flat per-request phase timeline: (event, t, value) tuples in
    arrival order. `value` is event-specific (token count for `tokens`,
    restored bytes for `restore`, cached tokens for `admitted`)."""

    __slots__ = ("request_id", "events", "first_token_t", "last_token_t",
                 "queued_t", "finish_reason")

    def __init__(self, request_id: str, queued_t: float) -> None:
        self.request_id = request_id
        self.queued_t = queued_t
        self.events: list[tuple[str, float, float]] = [(REQ_QUEUED, queued_t, 0.0)]
        self.first_token_t: Optional[float] = None
        self.last_token_t: Optional[float] = None
        self.finish_reason: Optional[str] = None

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.queued_t


class _NullContext:
    """Reusable, state-free context manager for the trace-off path (a
    fresh contextlib.nullcontext() per dispatch would be an allocation)."""

    def __enter__(self):
        return None

    def __exit__(self, *a):
        return False


NULL_ANNOTATION = _NullContext()


class StepClock:
    """The recorder: bounded step ring + per-request timelines + drain
    queues for the Prometheus exporter.

    One per engine (a replica pool has one per replica; the chrome trace
    merges them onto per-replica pids). All capacities are hard bounds —
    a recorder left running under traffic with nobody scraping holds a
    fixed working set and drops oldest-first."""

    def __init__(self, capacity: int = 4096,
                 slo_ttft_ms: float = 0.0,
                 slo_itl_ms: float = 0.0,
                 retired_capacity: int = 256,
                 sample_capacity: int = 8192) -> None:
        if capacity < 2:
            raise ValueError(f"step ring capacity must be >= 2, got {capacity}")
        self.capacity = capacity
        # Live-timeline budget is decoupled from the step ring: the
        # LLM_STEP_TRACE>=2 knob tunes dispatch-record history, and a
        # small ring must NOT evict still-running requests' timelines
        # (that would silently drop their TTFT/ITL/SLO samples).
        self.live_capacity = max(capacity, 4096)
        self.slo_ttft_ms = slo_ttft_ms
        self.slo_itl_ms = slo_itl_ms
        self.steps: deque[StepRecord] = deque(maxlen=capacity)
        self._seq = 0
        # Guards the step ring + timeline containers against HTTP-thread
        # readers iterating mid-mutation (the exporter drain queues stay
        # lock-free).
        self._lock = threading.Lock()
        # monotonic -> wall-clock offset, captured once: chrome traces and
        # OTel spans need absolute timestamps while every stamp in the
        # engine is time.monotonic().
        self.epoch_ns = time.time_ns() - int(time.monotonic() * 1e9)
        # Per-request timelines: live (keyed by request id) + a bounded
        # retire ring. OrderedDict so an overflow of live entries (a
        # caller that never retires) evicts oldest-first.
        self._live: "OrderedDict[str, RequestTimeline]" = OrderedDict()
        self._retired: deque[RequestTimeline] = deque(maxlen=retired_capacity)
        # Exporter drain queues (popped by the scrape thread).
        self.ttft_samples: deque[float] = deque(maxlen=sample_capacity)
        self.itl_samples: deque[float] = deque(maxlen=sample_capacity)
        # (slo_kind, met) events; empty unless an SLO is configured for
        # the request (knob default or per-request override).
        self.slo_events: deque[tuple[str, bool]] = deque(maxlen=sample_capacity)
        self.step_samples: deque[tuple[str, float]] = deque(maxlen=sample_capacity)
        # Most recent decode-dispatch occupancy (lanes), for the gauge.
        self.last_decode_batch = 0
        # Cumulative counters (cheap ints; survive ring eviction).
        self.num_dispatches = 0
        self.num_drains = 0
        self.num_requests_retired = 0

    # -- step clock (engine track) ----------------------------------------

    def annotation(self, kind: str):
        """`jax.profiler.TraceAnnotation` for a dispatch site, so XLA
        device traces line up with step records; degrades to the shared
        null context when the profiler is unavailable."""
        try:
            import jax

            return jax.profiler.TraceAnnotation(f"step_clock/{kind}")
        except Exception:  # pragma: no cover - profiler always importable with jax
            return NULL_ANNOTATION

    # statics: thread(engine-loop)
    def record_dispatch(self, kind: str, t0: float, t1: float, batch: int,
                        tokens: int, predicted: bool = False) -> None:
        with self._lock:
            self._seq += 1
            self.num_dispatches += 1
            self.steps.append(StepRecord(self._seq, kind, t0, t1 - t0, batch,
                                         tokens, predicted))
        self.step_samples.append((kind, t1 - t0))
        if kind in (PHASE_DECODE, PHASE_OVERLAPPED_DECODE,
                    PHASE_SPECULATIVE_DECODE):
            self.last_decode_batch = batch

    # statics: thread(engine-loop)
    def record_drain(self, t0: float, t1: float, entries: int,
                     tokens: int) -> None:
        with self._lock:
            self._seq += 1
            self.num_drains += 1
            self.steps.append(StepRecord(self._seq, PHASE_DRAIN, t0, t1 - t0,
                                         entries, tokens))
        self.step_samples.append((PHASE_DRAIN, t1 - t0))

    # statics: thread(engine-loop)
    def record_instant(self, kind: str, t: float, value: float = 0.0) -> None:
        """Zero-duration engine-track event (host-tier save/restore,
        overlap mispredict): rides the same ring, dur_s = 0."""
        with self._lock:
            self._seq += 1
            self.steps.append(StepRecord(self._seq, kind, t, 0.0, 0,
                                         int(value)))

    # -- request lifecycle --------------------------------------------------

    # statics: thread(engine-loop)
    def request_queued(self, request_id: str, t: float) -> None:
        with self._lock:
            if len(self._live) >= self.live_capacity:
                # Bounded even against a caller that never retires: evict
                # the oldest live timeline into the retired ring unfinished.
                _, tl = self._live.popitem(last=False)
                self._retired.append(tl)
            self._live[request_id] = RequestTimeline(request_id, t)

    # statics: thread(engine-loop)
    def request_event(self, request_id: str, name: str, t: float,
                      value: float = 0.0) -> None:
        tl = self._live.get(request_id)
        if tl is None:
            return  # retired already (an abort's trailing drain), or evicted
        tl.events.append((name, t, value))

    # statics: thread(engine-loop)
    def request_tokens(self, request_id: str, t: float, n: int) -> None:
        """`n` tokens landed on host for this request at time `t` (one
        harvest application). Stamps first-token, derives ITL samples —
        a fused-K dispatch lands K tokens at one instant, so the honest
        host-side ITL spreads the inter-arrival gap over the burst."""
        if n <= 0:
            return
        tl = self._live.get(request_id)
        if tl is None:
            return
        if tl.first_token_t is None:
            tl.first_token_t = t
            tl.events.append((REQ_FIRST_TOKEN, t, 0.0))
            self.ttft_samples.append(t - tl.queued_t)
            gap_tokens = n - 1  # tokens after the first in this burst
        else:
            gap_tokens = n
        if gap_tokens > 0 and tl.last_token_t is not None:
            per_tok = max(0.0, t - tl.last_token_t) / gap_tokens
            for _ in range(gap_tokens):
                self.itl_samples.append(per_tok)
        tl.last_token_t = t
        tl.events.append((REQ_TOKENS, t, float(n)))

    # statics: thread(engine-loop)
    def request_retired(self, request_id: str, t: float,
                        reason: Optional[str] = None,
                        slo_ttft_ms: Optional[float] = None,
                        slo_itl_ms: Optional[float] = None) -> None:
        """Close a request's timeline; emits SLO attainment events using
        the per-request override when given, else the recorder defaults
        (0/None = no SLO for that axis, nothing emitted)."""
        with self._lock:
            tl = self._live.pop(request_id, None)
            if tl is None:
                return
            tl.finish_reason = reason
            tl.events.append((REQ_RETIRED, t, 0.0))
            self.num_requests_retired += 1
            self._retired.append(tl)
        if reason in ("abort", "error"):
            return  # an aborted/unservable request attains no SLO verdict
        ttft_cap = slo_ttft_ms if slo_ttft_ms is not None else self.slo_ttft_ms
        if ttft_cap and tl.ttft_s is not None:
            self.slo_events.append(("ttft", tl.ttft_s <= ttft_cap / 1e3))
        itl_cap = slo_itl_ms if slo_itl_ms is not None else self.slo_itl_ms
        if itl_cap and tl.first_token_t is not None and tl.last_token_t is not None:
            n_after_first = sum(
                v for name, _, v in tl.events if name == REQ_TOKENS) - 1
            if n_after_first > 0:
                mean_itl = (tl.last_token_t - tl.first_token_t) / n_after_first
                self.slo_events.append(("itl", mean_itl <= itl_cap / 1e3))

    # -- exporter drains (scrape thread) ------------------------------------

    @staticmethod
    def _drain(dq: deque) -> list:
        out = []
        while True:
            try:
                out.append(dq.popleft())
            except IndexError:
                return out

    # statics: thread(scrape)
    def drain_ttft_samples(self) -> list[float]:
        return self._drain(self.ttft_samples)

    # statics: thread(scrape)
    def drain_itl_samples(self) -> list[float]:
        return self._drain(self.itl_samples)

    # statics: thread(scrape)
    def drain_slo_events(self) -> list[tuple[str, bool]]:
        return self._drain(self.slo_events)

    # statics: thread(scrape)
    def drain_step_samples(self) -> list[tuple[str, float]]:
        return self._drain(self.step_samples)

    # -- timeline lookups ----------------------------------------------------

    # statics: thread(handler)
    def timeline_for(self, request_id: str) -> Optional[RequestTimeline]:
        with self._lock:
            tl = self._live.get(request_id)
            if tl is not None:
                return tl
            for tl in reversed(self._retired):
                if tl.request_id == request_id:
                    return tl
            return None

    # statics: thread(handler)
    def timelines(self) -> list[RequestTimeline]:
        """Every timeline the recorder still holds, retired first."""
        with self._lock:
            return list(self._retired) + list(self._live.values())

    # -- Chrome trace-event export -------------------------------------------

    def _us(self, mono_t: float) -> float:
        """monotonic seconds -> absolute wall-clock microseconds."""
        return (self.epoch_ns + mono_t * 1e9) / 1e3

    # statics: thread(handler)
    def chrome_trace(self, pid: int = 0, name: str = "replica0") -> list[dict]:
        """Trace-event JSON objects (the `traceEvents` list entries):
        tid 0 = the engine step clock (one `X` slice per dispatch/drain,
        `i` instants for save/restore/mispredict), tid >= 1 = one track
        per request (phase slices queued/prefill/decode + token instants).
        Loadable in Perfetto / chrome://tracing."""
        events: list[dict] = [
            {"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": name}},
            {"ph": "M", "name": "thread_name", "pid": pid, "tid": 0,
             "args": {"name": "engine step clock"}},
        ]
        with self._lock:
            step_snapshot = list(self.steps)
        for rec in step_snapshot:
            if rec.kind in STEP_PHASES:
                events.append({
                    "ph": "X", "name": rec.kind, "cat": "engine",
                    "ts": self._us(rec.t), "dur": max(rec.dur_s, 0.0) * 1e6,
                    "pid": pid, "tid": 0,
                    "args": {"batch": rec.batch, "tokens": rec.tokens,
                             "predicted": rec.predicted, "seq": rec.seq},
                })
            else:
                events.append({
                    "ph": "i", "name": rec.kind, "cat": "engine",
                    "ts": self._us(rec.t), "pid": pid, "tid": 0, "s": "t",
                    "args": {"value": rec.tokens},
                })
        tid = 1
        for tl in self.timelines():
            events.append({"ph": "M", "name": "thread_name", "pid": pid,
                           "tid": tid,
                           "args": {"name": f"req {tl.request_id}"}})
            events.extend(self._request_slices(tl, pid, tid))
            tid += 1
        return events

    def _request_slices(self, tl: RequestTimeline, pid: int,
                        tid: int) -> list[dict]:
        """Phase slices for one request track: queued (arrival ->
        admission), prefill (admission -> first token), decode (first
        token -> retire), plus instants for restores and token bursts."""
        out: list[dict] = []
        by_name: dict[str, float] = {}
        for name, t, value in tl.events:
            if name not in by_name:
                by_name[name] = t
            if name in (REQ_RESTORE, REQ_TOKENS):
                out.append({"ph": "i", "name": name, "cat": "request",
                            "ts": self._us(t), "pid": pid, "tid": tid,
                            "s": "t", "args": {"value": value}})
        end_t = by_name.get(REQ_RETIRED, tl.last_token_t or tl.queued_t)

        def slice_(name: str, t0: Optional[float], t1: Optional[float]):
            if t0 is None or t1 is None or t1 < t0:
                return
            out.append({"ph": "X", "name": name, "cat": "request",
                        "ts": self._us(t0), "dur": (t1 - t0) * 1e6,
                        "pid": pid, "tid": tid,
                        "args": {"request_id": tl.request_id}})

        admitted = by_name.get(REQ_ADMITTED)
        slice_("queued", tl.queued_t, admitted or tl.first_token_t or end_t)
        slice_("prefill", admitted, tl.first_token_t or end_t)
        slice_("decode", tl.first_token_t, end_t)
        return out


def chrome_trace_document(recorders: list, names: Optional[list[str]] = None) -> dict:
    """Merge per-replica recorders into one Chrome trace JSON document
    (`{"traceEvents": [...]}`), pid = replica index."""
    events: list[dict] = []
    for i, rec in enumerate(recorders):
        if rec is None:
            continue
        label = names[i] if names and i < len(names) else f"replica{i}"
        events.extend(rec.chrome_trace(pid=i, name=label))
    return {"traceEvents": events, "displayTimeUnit": "ms"}
