"""LLMEngine: continuous batching over jitted TPU steps.

Replaces the vLLM `AsyncLLMEngine` the reference wraps (reference:
llm/serve_llm.py:343-612) with a first-party engine:

  host (Python)                       device (TPU, jitted)
  ─────────────                       ────────────────────
  Scheduler.plan()  ──────────────▶   fused prefill+sample   (one dispatch)
  block allocation                    fused K-step decode+sample (one dispatch)
  stop conditions, streaming  ◀────   sampled tokens [B, K] (async readback)

Key TPU-driven design points:
  * Decode advances entirely on device (DecodeState feeds itself); the host
    only reads back the [B] sampled-token array, asynchronously, processing
    it `pipeline_depth` steps behind the dispatch frontier. Stop conditions
    are therefore detected with bounded lag; the scheduler pre-allocates
    `decode_lookahead` KV slots so lagged steps never overrun a block table.
  * Tokens sampled past a stop point are dropped at harvest time, so output
    text is exact regardless of lag.
  * Shapes are bucketed by the scheduler; each (batch, length) bucket
    compiles once.

TTFT semantics match the reference: `queue_wait_s` = request arrival →
first token available on host (reference: llm/serve_llm.py:546-558).
"""

from __future__ import annotations

import dataclasses
import logging
import time
import uuid
from collections import OrderedDict, deque
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from agentic_traffic_testing_tpu.models.config import ModelConfig, resolve_config
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.block_allocator import (
    make_block_allocator,
    request_chain_keys,
)
from agentic_traffic_testing_tpu.runtime.kv_cache import TRASH_BLOCK, make_kv_cache
from agentic_traffic_testing_tpu.runtime.request import (
    FinishReason,
    Request,
    RequestState,
    SamplingParams,
)
from agentic_traffic_testing_tpu.runtime.runner import (
    DecodeState,
    ModelRunner,
    SamplingArrays,
)
from agentic_traffic_testing_tpu.runtime.scheduler import (
    ChunkPrefill,
    DecodeBatch,
    HybridBatch,
    PrefillBatch,
    QueueFullError,
    Scheduler,
    SchedulerConfig,
)
from agentic_traffic_testing_tpu.runtime.telemetry import (
    EVENT_HOST_RESTORE,
    EVENT_HOST_SAVE,
    EVENT_MISPREDICT,
    NULL_ANNOTATION,
    PHASE_CHUNK,
    PHASE_DECODE,
    PHASE_HYBRID,
    PHASE_OVERLAPPED_DECODE,
    PHASE_PIPELINED_PREFILL,
    PHASE_PREFILL,
    PHASE_SPECULATIVE_DECODE,
    REQ_ADMITTED,
    REQ_PREFILL_CHUNK,
    REQ_RESTORE,
)

log = logging.getLogger("att_tpu.engine")


@dataclasses.dataclass
class EngineConfig:
    """Env-compatible engine knobs (names mirror the reference's LLM_* envs —
    reference: llm/serve_llm.py:52-82)."""

    model: str = "tiny"
    dtype: str = "bfloat16"
    max_num_seqs: int = 12
    max_num_batched_tokens: int = 8192
    max_model_len: int = 4096
    block_size: int = 16
    num_blocks: Optional[int] = None       # None -> derive from HBM budget
    memory_utilization: float = 0.90       # LLM_GPU_MEMORY_UTILIZATION analog
    pipeline_depth: int = 2                # decode dispatches in flight before readback
    # Model steps fused into ONE decode dispatch (lax.scan on device). The
    # sampled token feeds the next step without host involvement, so dispatch
    # round-trip cost is amortized K×. None -> auto: 16 on TPU, 1 elsewhere
    # (keeps CPU tests step-exact by default). The budget-aware dispatcher
    # (_decode_budget_satisfied) makes max_tokens-bounded work waste-free at
    # any K — r2 measured bs=8 at 1079/1207/1210 tok/s for K=16/32/64 — but
    # EOS-stopping chat still discards a partial dispatch on stop, so the
    # auto default stays at the latency-friendlier 16; throughput-oriented
    # deployments (bench.py) set 32.
    decode_steps: Optional[int] = None
    # Prompts longer than this prefill in fixed chunks (bounded bucket +
    # per-step latency); 0/None disables chunking. Raised 2048 -> 4096 in
    # round 3: the flash prefill site (ops/flash_prefill.py) makes a solo
    # 4096 pass ~2x cheaper than two chunked dispatches (each chunk re-pays
    # the dispatch overhead and attends over the prior-pages gather).
    prefill_chunk_tokens: Optional[int] = 4096
    # Multi-request prefill batches form only up to this padded length
    # (None -> scheduler default 128). Raising it lets concurrent long-prompt
    # arrivals prefill in ONE weight-streaming pass instead of solo — the
    # TTFT-under-fan-out lever — but each (batch, length) bucket is a fresh
    # XLA compile; pair with warmup_prefill_buckets() so a burst never
    # compiles mid-traffic.
    prefill_batch_max_len: Optional[int] = None
    # Pipelined prefill (round 6 — the prefill-MFU-0.13 dispatch half):
    # split solo/batched prefills into up to this many position-chunks and
    # dispatch them back-to-back with NO host synchronization — chunk
    # i+1's dispatch rides the device queue while chunk i computes, so the
    # ~0.1 s axon-tunnel dispatch overhead amortizes to one chunk's worth,
    # with donated carry buffers and a single first-token readback at the
    # tail. 0/1 (default 0) keeps the single-dispatch path bit-identical;
    # on, outputs are token-identical and KV pages byte-identical
    # (tests/test_prefill_pipeline.py pins both). Chunks reuse the chunked
    # -prefill model impl, so one compiled program serves every chunk of a
    # bucket. Single-chip runners only. (Composes with speculation since
    # round 14: the spec prefill handoff is the same async DecodeState
    # handoff as plain decode — no first-token readback to pipeline past.)
    prefill_pipeline_chunks: int = 0
    # Hybrid prefill+decode batching (Sarathi-style chunked piggyback over
    # the ragged Pallas kernel): when > 0, a pending prefill chunk and the
    # decode batch fuse into ONE ragged dispatch whose padded token total
    # (decode lanes + chunk bucket) stays under this budget — decode lanes
    # stop serializing behind chunks, which is the queue-wait lever under
    # mixed agentic traffic. 0 (default) keeps every path bit-identical to
    # the serial scheduler. Pair with warmup_hybrid_buckets() so the
    # (batch, chunk) shapes never compile mid-traffic.
    hybrid_token_budget: int = 0
    # Overlapped decode loop (round 7 — the bs32 roofline_frac culprit's
    # host half): while fused-step N executes on device, the engine
    # dispatches fused-step N+1 against the PREDICTED composition (decode
    # composition only changes on EOS/stop/admission, which the host
    # observes one readback late anyway) — the scheduler's
    # composition_stable hint skips the full per-dispatch plan() pass,
    # block tables stay device-resident and grow by an incremental scatter
    # of only the changed cells (ops/pallas/kv_write.update_table_cells)
    # instead of a host rebuild + [B, W] upload, and the DecodeState carry
    # is donated (runner.decode_overlapped's two-slot ping-pong). On a
    # mispredict (a stop landed, an admission opened) the speculative
    # dispatch's post-stop outputs are discarded at harvest and the step
    # re-runs on the corrected batch via the normal drain + re-plan, so
    # token streams are identical to the serial loop. 0 (default) keeps
    # every path bit-identical to today. Single-chip runners only
    # (tp/sp/pp refuse at build). Composes with speculation since
    # round 14: the speculative verify dispatch IS the predicted
    # next-step dispatch (its carry is a donated DecodeState), and a
    # rejected draft is just another mispredict reconciled through the
    # same drain + re-plan.
    decode_overlap: int = 0
    # Step-clock telemetry plane (round 8 — runtime/telemetry.py): 0
    # (default) keeps the hot loop byte-identical and allocation-free —
    # the engine holds NO recorder and every hook is one `is not None`
    # test. 1 records one bounded ring-buffer entry per device dispatch
    # and drain (phase kind, batch composition, token counts, dispatch
    # vs drain wall split, overlap mispredicts, host-tier save/restore
    # events) plus a per-request phase timeline (queued → admitted →
    # prefill chunks → restores → first token → decode → retired), all
    # from time.monotonic() stamps already on the host path — no device
    # syncs, so the statics host-sync lint stays green. Values >= 2
    # additionally set the step-ring capacity (default 4096).
    step_trace: int = 0
    # SLO classes for the telemetry plane's attainment accounting
    # (llm_slo_attainment_total{slo,status}): per-request TTFT and
    # mean-ITL caps in milliseconds. 0 (default) = no SLO on that axis,
    # nothing emitted. Per-request overrides ride SamplingParams
    # (slo_ttft_ms / slo_itl_ms — the HTTP body fields). Only measured
    # when step_trace is on (the recorder is the measurement plane).
    slo_ttft_ms: float = 0.0
    slo_itl_ms: float = 0.0
    # Bounded wait queue (round 9 — the robustness plane's overload
    # policy): add_request raises scheduler.QueueFullError once this many
    # requests are already waiting; the serving layer maps it to 503 +
    # Retry-After. 0 (default) keeps the queue unbounded.
    max_queue: int = 0
    # Default per-request completion deadline in milliseconds, measured
    # from arrival: the engine's step sweep aborts queued AND running
    # requests past it (FinishReason.DEADLINE) through the abort path, so
    # a stalled queue cannot hold client work forever. 0 (default) = no
    # deadline and no per-step sweep state at all; per-request
    # sampling.deadline_ms (the HTTP body field) overrides.
    deadline_ms: float = 0.0
    # Deterministic fault injection (runtime/faultinject.py): a spec
    # string ("dispatch_error:p=0.05;restore_error:p=0.1") compiled into
    # named hooks at the dispatch and restore sites. Empty (default) =
    # no injector object exists and every hook is one `is not None`
    # test — the hot path is byte-identical. Seeded by fault_seed (the
    # replica pool offsets it per replica).
    fault_spec: str = ""
    fault_seed: int = 0
    # Live migration of in-flight streams (round 11 — the elastic-serving
    # plane): 1 lets the engine checkpoint a running request's decode
    # state (token history, sampling carry, position, RNG step) plus its
    # full KV blocks (engine.checkpoint_request) and resume a checkpoint
    # from another replica (engine.adopt_request), token-identical to the
    # never-migrated stream. With it on, _fail_dispatch drains-and-
    # migrates started streams instead of killing them (the round-9 kill
    # path stays the degrade target — injected `migrate_error`, no
    # survivor, or a failed checkpoint all fall back to it). 0 (default)
    # keeps every path byte-identical to round 9: no checkpoint machinery
    # is consulted anywhere. Host-side only — compiled programs are
    # untouched either way. Single-chip runners only. (Composes with
    # speculation since round 14: the token history is host-side and the
    # rejection rollback leaves no draft bytes behind, so the plain-decode
    # checkpoint rule covers the speculative stream unchanged.)
    migration: int = 0
    # Disaggregated serving role (round 16 — serving/replica_pool.py pool
    # roles): "" / "mixed" (default) serve both phases exactly as before;
    # "prefill" checkpoints every stream right after its first sampled
    # token (trigger="disagg", requires migration=1) so the pool resumes
    # decode on a decode/mixed replica through the byte-identical
    # migration plane; "decode" admits its wait queue by SLO class
    # (tightest slo_ttft_ms first) instead of FCFS. Host-side only —
    # compiled programs are untouched for every value.
    disagg_role: str = ""
    # Content-addressed reuse of full prompt blocks (vLLM automatic-prefix-
    # caching analog); cached requests prefill only their suffix.
    prefix_caching: bool = False
    # Host-RAM second tier for the prefix cache (runtime/kv_offload.py):
    # indexed blocks reclaimed under capacity pressure spill device→host
    # (async, overlapped with decode) and stream back into fresh blocks on
    # a later prefix hit instead of recomputing. GB budget; 0 (default)
    # keeps every path bit-identical to the single-tier cache. Requires
    # prefix_caching (the tier extends the content-addressed index). A
    # pool-shared store can be injected via LLMEngine(host_store=...),
    # overriding this knob's engine-private store.
    host_cache_gb: float = 0.0
    seed: int = 0
    # Weight-only quantization: None (serve in `dtype`), "int8"
    # (models/quant.py — halves weight HBM so Llama-3-8B fits one v5e chip),
    # or "int4" (nibble-packed, served by the pallas int4 matmul kernel —
    # halves int8's streamed bytes again; single-chip dense models only).
    quantization: Optional[str] = None
    # AWQ-style K-group size for int4 scales (0 = one scale per full-K
    # column). 512 is the accuracy knob for real checkpoints
    # (models/quant.py quantize_array4 k_group).
    int4_k_group: int = 0
    # MoE expert-capacity override (None -> model default). HF Mixtral drops
    # no tokens; >= num_experts guarantees no capacity drops (exact HF
    # numerics) at the cost of E-fold larger expert buffers (models/moe.py).
    moe_capacity_factor: Optional[float] = None
    # KV-cache page dtype: None (follow `dtype`), "fp8" (float8_e4m3 pages
    # — exactly double the KV capacity / concurrency and half the decode KV
    # stream, no scale plumbing; the vLLM analog is --kv-cache-dtype fp8,
    # which the reference inherits through its vllm dependency), or "int8"
    # (round 10: scaled int8 pages + one fp32 scale per (layer, page,
    # kv-head), quantized at write and dequantized inside the dma2/dma3/
    # ragged kernels' chunk walk — same byte savings as fp8 without its
    # cast error, at the cost of a per-page requant on decode appends).
    # Accuracy envelopes: e4m3's per-element dynamic exponent costs ~2% RMS
    # on K/V (~6% on individual pre-softmax scores, averaging out over
    # slots) — tests/test_kv_fp8.py pins it; int8's per-(page x kv-head)
    # symmetric scale is ~0.5% RMS on settled K/V (127 levels against the
    # page absmax) plus at most one extra re-round per louder newcomer
    # token — tests/test_kv_quant.py pins that tier. Single-chip runners
    # only for int8 (supports_quantized_kv).
    kv_cache_dtype: Optional[str] = None
    # Fused KV page writes (round 10, LLM_FUSED_KV_WRITE): 1 folds the
    # decode token write into the dma2/dma3 attention kernels (aliased
    # pool, requant in-kernel for int8) and the hybrid chunk's page
    # scatter into the ragged kernel — eliminating the separate chained-
    # DUS write ops per layer. 0 (default) keeps every write path
    # bit-identical to pre-knob builds. Off-TPU modes fuse functionally
    # (same bytes, one call site), so the knob is CPU-testable.
    # Single-chip runners only; int8 x hybrid refuses. Composes with
    # speculation (round 14): single-token dispatches stay fused while
    # the multi-token verify keeps its chained write sequence (the
    # in-kernel fused write carries exactly one token).
    fused_kv_write: int = 0
    # None = auto (C++ native/ core if it builds, Python otherwise);
    # True/False force one implementation.
    native_allocator: Optional[bool] = None
    # Speculative decoding: None (off) or "ngram" (draft-model-free
    # prompt-lookup speculation — ops/speculative.py). Drafts are proposed
    # HOST-side from the request's own token history (round 14) and each
    # fused decode round verifies spec_tokens drafts + 1 in one multi-token
    # model step, with rejected KV appends rolled back to the serial
    # loop's bytes; greedy output is bit-identical to non-speculative
    # decode (fp32 CPU pins). Composes with hybrid batching, the
    # overlapped loop, int8 KV, fused writes, the pipelined prefill, and
    # migration; pp runners refuse (supports_speculation).
    speculation: Optional[str] = None
    spec_tokens: int = 3   # γ — drafts verified per step
    spec_ngram: int = 3    # trailing n-gram length matched against history
    # Bound the host-side prompt-lookup scan to the trailing this-many
    # tokens of each lane's history (LLM_SPEC_LOOKUP_WINDOW). 0 (default)
    # scans the whole history — the original proposal semantics; long
    # multi-turn agentic histories set a window to cap the per-dispatch
    # host scan at O(window) per lane.
    spec_lookup_window: int = 0

    def __post_init__(self) -> None:
        # Fail fast: a typo'd scheme must not silently serve full-precision
        # (or, behind a broad except in the server's weight loader, random)
        # weights.
        if self.quantization not in (None, "int8", "int4"):
            raise ValueError(
                f"unknown quantization {self.quantization!r}; "
                f"supported: int8, int4")
        if self.kv_cache_dtype not in (None, "fp8", "fp8_e4m3", "int8"):
            raise ValueError(
                f"unknown kv_cache_dtype {self.kv_cache_dtype!r}; "
                f"supported: fp8, int8")
        if self.fused_kv_write not in (0, 1):
            raise ValueError(
                f"fused_kv_write must be 0 or 1, got {self.fused_kv_write}")
        if (self.fused_kv_write and self.hybrid_token_budget
                and self.kv_cache_dtype == "int8"):
            # A ragged q-block smaller than a page cannot own the page's
            # int8 scale; the hybrid int8 path keeps its separate
            # quantizing writes instead.
            raise ValueError(
                "fused_kv_write x hybrid_token_budget x kv_cache_dtype="
                "'int8' is not wired — disable one of the three")
        if (self.fused_kv_write and self.hybrid_token_budget
                and self.block_size % 8):
            # 8 = the ragged kernel's q_tokens_per_block: fused in-grid
            # writes need block_size % qblk == 0 so no q-block straddles a
            # page — refuse at build, not at the first hybrid trace.
            raise ValueError(
                f"fused_kv_write x hybrid_token_budget needs block_size % 8 "
                f"== 0 (the ragged q-block tile), got {self.block_size}")
        if self.speculation not in (None, "ngram"):
            raise ValueError(
                f"unknown speculation {self.speculation!r}; supported: ngram")
        if self.hybrid_token_budget < 0:
            raise ValueError(
                f"hybrid_token_budget must be >= 0, got {self.hybrid_token_budget}")
        if self.prefill_pipeline_chunks < 0:
            raise ValueError(
                f"prefill_pipeline_chunks must be >= 0, "
                f"got {self.prefill_pipeline_chunks}")
        if self.decode_overlap not in (0, 1):
            raise ValueError(
                f"decode_overlap must be 0 or 1, got {self.decode_overlap}")
        if self.migration not in (0, 1):
            raise ValueError(
                f"migration must be 0 or 1, got {self.migration}")
        if self.disagg_role not in ("", "mixed", "prefill", "decode"):
            raise ValueError(
                f"disagg_role must be '', mixed, prefill or decode, got "
                f"{self.disagg_role!r}")
        if self.disagg_role == "prefill" and not self.migration:
            raise ValueError(
                "disagg_role='prefill' requires migration=1 — the "
                "first-token handoff rides the checkpoint/adopt plane")
        if self.step_trace < 0:
            raise ValueError(
                f"step_trace must be >= 0, got {self.step_trace}")
        if self.slo_ttft_ms < 0 or self.slo_itl_ms < 0:
            raise ValueError(
                f"SLO caps must be >= 0 ms, got ttft={self.slo_ttft_ms} "
                f"itl={self.slo_itl_ms}")
        if self.host_cache_gb < 0:
            raise ValueError(
                f"host_cache_gb must be >= 0, got {self.host_cache_gb}")
        if self.max_queue < 0:
            raise ValueError(
                f"max_queue must be >= 0, got {self.max_queue}")
        if self.deadline_ms < 0:
            raise ValueError(
                f"deadline_ms must be >= 0, got {self.deadline_ms}")
        if self.fault_spec:
            # Compile-check at config time: a typo'd chaos spec must fail
            # the build, not silently inject nothing.
            from agentic_traffic_testing_tpu.runtime.faultinject import (
                parse_fault_spec,
            )

            parse_fault_spec(self.fault_spec)
        if self.host_cache_gb and not self.prefix_caching:
            # The host tier is addressed by the prefix cache's chain keys;
            # without the device index there is nothing to spill or match.
            raise ValueError(
                "host_cache_gb requires prefix_caching=True (the host tier "
                "extends the content-addressed prefix cache)")
        if self.speculation and self.spec_tokens < 1:
            raise ValueError("spec_tokens must be >= 1 when speculation is on")
        if self.spec_lookup_window < 0:
            raise ValueError(
                f"spec_lookup_window must be >= 0 (0 = scan the whole "
                f"history), got {self.spec_lookup_window}")
        if self.moe_capacity_factor is not None and self.moe_capacity_factor <= 0:
            # 0 would clamp every expert to one slot -> near-total token
            # dropping served behind healthy 200s.
            raise ValueError(
                f"moe_capacity_factor must be > 0, got {self.moe_capacity_factor}")

    @property
    def effective_spec_tokens(self) -> int:
        """Drafts per verify step, 0 when speculation is off — the ONE gate
        every runner-construction site uses (a future mode added to the
        validator only needs handling here)."""
        return self.spec_tokens if self.speculation == "ngram" else 0

    def resolved_decode_steps(self, platform: str) -> int:
        """Fused decode steps per dispatch when LLM_DECODE_STEPS is unset.

        Auto now SCALES WITH BATCH on TPU (ROADMAP item 2, round 6): at
        bs32 the per-dispatch host work (table refresh, readback
        bookkeeping) grows with B while per-step device time stays
        weight-streaming-bound, so a larger K amortizes the growing host
        term over more tokens — bench measured bs8 flat across K=16/32/64
        but bs32 losing roofline fraction at K=16. Fused-K output stays
        token-identical to K single steps (tests/test_multistep_decode.py
        pins the parity at the bs32 auto value)."""
        if self.decode_steps is not None:
            return max(1, self.decode_steps)
        if platform != "tpu":
            return 1
        return 32 if self.max_num_seqs >= 32 else 16

    def scheduler_config(self, decode_steps: int = 1) -> SchedulerConfig:
        # Lookahead must cover every KV write a lagged in-flight dispatch can
        # make: (pipeline_depth unharvested + 1 dispatching) × decode_steps.
        # Speculative engines pass decode_steps * (spec_tokens + 1) here
        # (the engine constructor's one call site): each fused round can
        # emit — and write KV for — up to γ+1 positions per lane.
        return SchedulerConfig(
            max_num_seqs=self.max_num_seqs,
            max_num_batched_tokens=self.max_num_batched_tokens,
            max_model_len=self.max_model_len,
            block_size=self.block_size,
            decode_lookahead=max(4, (self.pipeline_depth + 1) * decode_steps),
            prefill_chunk_tokens=self.prefill_chunk_tokens or None,
            hybrid_token_budget=self.hybrid_token_budget,
            max_queue=self.max_queue,
            slo_class_admission=(self.disagg_role == "decode"),
            **({"prefill_batch_max_len": self.prefill_batch_max_len}
               if self.prefill_batch_max_len is not None else {}),
        )


@dataclasses.dataclass
class StepOutput:
    """Per-request increment produced by Engine.step()."""

    request: Request
    new_token_ids: list[int]
    finished: bool


class _Inflight:
    """A dispatched decode step whose sampled tokens are still on device.

    `counts` is None for plain decode (every token row is fully emitted);
    for speculative decode it is the [B, K] per-iteration emitted-token
    counts matching tokens [B, K, spec_tokens+1]. `predicted` marks an
    overlap fast-path dispatch (issued against the predicted composition
    without a plan() reconcile — the mispredict accounting's unit)."""

    __slots__ = ("tokens", "requests", "counts", "predicted")

    def __init__(self, tokens: jax.Array, requests: list[Request],
                 counts: Optional[jax.Array] = None,
                 predicted: bool = False) -> None:
        self.tokens = tokens
        self.requests = requests
        self.counts = counts
        self.predicted = predicted


def _plan_requests(plan) -> list[Request]:
    """Every request a step plan would dispatch (the failure domain of
    one dispatch exception — see LLMEngine._fail_dispatch)."""
    if isinstance(plan, PrefillBatch):
        return list(plan.requests)
    if isinstance(plan, HybridBatch):
        return list(plan.decode.requests) + [plan.chunk.request]
    if isinstance(plan, ChunkPrefill):
        return [plan.request]
    if isinstance(plan, DecodeBatch):
        return list(plan.requests)
    return []


class LLMEngine:
    """Synchronous engine core; `serving/` wraps it in asyncio."""

    def __init__(
        self,
        cfg: EngineConfig,
        model_cfg: Optional[ModelConfig] = None,
        params=None,
        runner: Optional[ModelRunner] = None,
        host_store=None,
    ) -> None:
        # Runtime ownership sanitizer (LLM_CONCURRENCY_CHECK=1): installs
        # __setattr__ assertions compiled from statics/ownership_registry
        # on the serving-plane classes. Off (default) = one env read here
        # and NOTHING else — no wrapper exists, the hot loop is
        # byte-identical (pinned by tests/test_statics_concurrency.py).
        from agentic_traffic_testing_tpu.runtime import concurrency

        concurrency.maybe_install()
        self.cfg = cfg
        self.model_cfg = model_cfg or resolve_config(cfg.model)
        if (cfg.moe_capacity_factor is not None and self.model_cfg.num_experts
                and self.model_cfg.moe_capacity_factor != cfg.moe_capacity_factor):
            # Applied here (the one model-cfg resolution point) so every
            # construction path — server, bench, tests — honors the knob.
            # A caller-supplied runner compiled its programs from its own
            # cfg, so the override must already match it (the server's TP
            # branch applies it before building the runner).
            self.model_cfg = dataclasses.replace(
                self.model_cfg, moe_capacity_factor=cfg.moe_capacity_factor)
            if runner is not None and runner.cfg.moe_capacity_factor != (
                    cfg.moe_capacity_factor):
                raise ValueError(
                    "moe_capacity_factor override conflicts with the supplied "
                    "runner's model config — apply it before building the runner")
        dtype = jnp.bfloat16 if cfg.dtype in ("bfloat16", "bf16") else jnp.float32
        platform = jax.devices()[0].platform
        decode_steps = cfg.resolved_decode_steps(platform)
        if runner is not None:
            chunk_reachable = (
                (cfg.prefill_chunk_tokens
                 and cfg.max_model_len > cfg.prefill_chunk_tokens)
                # Prefix-cached requests prefill their suffix through the
                # chunk path REGARDLESS of the chunk threshold.
                or cfg.prefix_caching)
            if chunk_reachable and not runner.supports_chunked_prefill:
                # Fail at construction, not mid-request: the chunk jit is
                # one this runner cannot serve faithfully (e.g.
                # SPPrefillRunner — chunks would run replicated with zero
                # sp speedup; the sp feature IS the one sharded
                # long-prompt pass).
                raise ValueError(
                    f"{type(runner).__name__} does not support the chunked-"
                    f"prefill path — build the engine with "
                    f"prefill_chunk_tokens=0 and prefix_caching=False "
                    f"(the serving sp branch does)")
            self.runner = runner
            decode_steps = runner.decode_steps
        else:
            if params is None:
                log.warning("no checkpoint: random-initializing %s", self.model_cfg.name)
                if cfg.quantization:
                    from agentic_traffic_testing_tpu.models.llama import init_params_quantized

                    params = init_params_quantized(self.model_cfg, cfg.seed,
                                                   dtype=dtype,
                                                   scheme=cfg.quantization,
                                                   int4_k_group=cfg.int4_k_group)
                else:
                    params = init_params(self.model_cfg, jax.random.key(cfg.seed), dtype=dtype)
            elif cfg.quantization:
                from agentic_traffic_testing_tpu.models.quant import (
                    QTensor4,
                    is_quantized,
                    quantize_params,
                )

                if not is_quantized(params):
                    # No delete_originals: the caller still owns these arrays
                    # (memory-critical loads pre-quantize in weights.py /
                    # init_params_quantized instead).
                    params = quantize_params(params, scheme=cfg.quantization,
                                             int4_k_group=cfg.int4_k_group)
                elif (isinstance(params["layers"]["wq"], QTensor4)
                      != (cfg.quantization == "int4")):
                    # Pre-quantized params of the OTHER scheme: serving them
                    # would silently mislabel every metric and benchmark.
                    # Keyed on a layer weight, not unembed — int4 x TP
                    # legitimately hybridizes the lm_head to int8
                    # (models/quant.py quantize_params).
                    raise ValueError(
                        f"engine configured quantization="
                        f"{cfg.quantization!r} but the supplied params are "
                        f"quantized with the other scheme")
            self.runner = ModelRunner(
                self.model_cfg, params, decode_steps=decode_steps,
                spec_tokens=cfg.effective_spec_tokens,
                spec_ngram=cfg.spec_ngram,
                fused_kv_write=bool(cfg.fused_kv_write),
            )

        if cfg.hybrid_token_budget and not getattr(
                self.runner, "supports_hybrid", False):
            # Fail at construction, not mid-request: the mesh runners have
            # no shard_map wrapper for the ragged hybrid step yet.
            raise ValueError(
                f"{type(self.runner).__name__} does not support the fused "
                f"hybrid prefill+decode path — build the engine with "
                f"hybrid_token_budget=0")
        if cfg.prefill_pipeline_chunks > 1 and not getattr(
                self.runner, "supports_prefill_pipeline", False):
            # Same rule as hybrid: the mesh runners have no sharded wrapper
            # for the pipelined-prefill chunk jit.
            raise ValueError(
                f"{type(self.runner).__name__} does not support the "
                f"pipelined-prefill path — build the engine with "
                f"prefill_pipeline_chunks=0 (unset LLM_PREFILL_PIPELINE)")
        if cfg.decode_overlap and not getattr(
                self.runner, "supports_decode_overlap", False):
            # Mesh runners have no donated-state decode jit. (Speculative
            # runners compose since round 14: the spec verify carry is a
            # plain DecodeState with its own donated-state jit.)
            raise ValueError(
                f"{type(self.runner).__name__} does not support the "
                f"overlapped decode loop — build the engine with "
                f"decode_overlap=0 (unset LLM_DECODE_OVERLAP)")
        if (cfg.effective_spec_tokens or getattr(self.runner, "spec_tokens", 0)
                ) and not getattr(self.runner, "supports_speculation", False):
            # The pp runner's staged jits have no multi-token verify
            # stage (its constructor refuses spec_tokens too; this guard
            # covers caller-supplied runners and cfg-level speculation).
            raise ValueError(
                f"{type(self.runner).__name__} does not support speculative "
                f"decoding — build the engine with speculation=None "
                f"(unset LLM_SPECULATION)")

        kv_quantized = cfg.kv_cache_dtype == "int8"
        if kv_quantized:
            # A pinned legacy attention mode (ATT_TPU_ATTENTION=dma/pallas/
            # interpret) cannot dequantize the scaled pool: refuse at
            # construction, not on every dispatch's trace.
            from agentic_traffic_testing_tpu.ops.attention_backend import (
                backend_choice,
            )

            attn_mode = getattr(self.runner, "attn_mode", None) or backend_choice()
            if attn_mode in ("dma", "pallas", "interpret"):
                raise ValueError(
                    f"attention mode {attn_mode!r} does not serve the scaled "
                    f"int8 KV pool — set ATT_TPU_ATTENTION to dma2, dma3, "
                    f"ragged, or gather (or unset LLM_KV_CACHE_DTYPE)")
        if kv_quantized and not getattr(self.runner, "supports_quantized_kv",
                                        False):
            # The shard_dma wrapper has no scale-sharding rule and the
            # staged/sharded gather paths no scale plumbing: fail at
            # construction, not first step.
            raise ValueError(
                f"{type(self.runner).__name__} does not support the scaled "
                f"int8 KV pool — build the engine with kv_cache_dtype=None "
                f"or 'fp8' (unset LLM_KV_CACHE_DTYPE)")
        if cfg.migration and not getattr(self.runner, "supports_migration",
                                         False):
            # The mesh runners' sharded/staged caches have no per-block
            # host slicing or restore-write rule: fail at construction,
            # not at the first checkpoint.
            raise ValueError(
                f"{type(self.runner).__name__} does not support live "
                f"stream migration — build the engine with migration=0 "
                f"(unset LLM_MIGRATION)")
        if cfg.fused_kv_write and not getattr(
                self.runner, "supports_fused_kv_write", False):
            raise ValueError(
                f"{type(self.runner).__name__} does not support fused KV "
                f"page writes — build the engine with fused_kv_write=0 "
                f"(unset LLM_FUSED_KV_WRITE)")
        if runner is not None and bool(cfg.effective_spec_tokens) != bool(
                getattr(self.runner, "spec_tokens", 0)):
            # The speculative verify program is baked into the runner's
            # jits; a mismatched supplied runner would silently serve the
            # other decode path while llm_config_speculation reports the
            # cfg's value (the same silent-misconfiguration class the
            # fused_kv_write check below refuses).
            raise ValueError(
                "speculation conflicts with the supplied runner's programs "
                "— build the runner with matching spec_tokens")
        if (runner is not None and cfg.effective_spec_tokens
                and getattr(self.runner, "spec_ngram",
                            cfg.spec_ngram) != cfg.spec_ngram):
            # Proposal uses the runner's lookup length (it sits next to
            # spec_tokens, the runner-owned half); a disagreeing cfg
            # would silently misreport the knob — same rule as above.
            raise ValueError(
                "spec_ngram conflicts with the supplied runner's — build "
                "the runner with the same lookup length")
        if runner is not None and bool(cfg.fused_kv_write) != bool(
                getattr(self.runner, "fused_kv_write", False)):
            # The fused flag is baked into the runner's jitted programs; a
            # mismatched supplied runner would silently serve the other
            # write path behind the knob's name.
            raise ValueError(
                "fused_kv_write conflicts with the supplied runner's "
                "programs — build the runner with the same flag")

        num_blocks = cfg.num_blocks or self._default_num_blocks()
        kv_dtype = (jnp.float8_e4m3fn if cfg.kv_cache_dtype in ("fp8", "fp8_e4m3")
                    else jnp.int8 if kv_quantized else dtype)
        self.cache = self.runner.prepare_cache(
            make_kv_cache(self.model_cfg, num_blocks, cfg.block_size, kv_dtype,
                          quantized=kv_quantized)
        )
        self.allocator = make_block_allocator(num_blocks, cfg.block_size,
                                              native=cfg.native_allocator,
                                              prefix_caching=cfg.prefix_caching)
        # Host-RAM tier (runtime/kv_offload.py): an injected store (the
        # replica pool shares ONE across engines) wins over the knob's
        # engine-private store; None keeps every path bit-identical.
        self._host_store = host_store
        if self._host_store is None and cfg.host_cache_gb:
            from agentic_traffic_testing_tpu.runtime.kv_offload import (
                host_store_from_gb,
            )

            self._host_store = host_store_from_gb(cfg.host_cache_gb)
        self._save_pending: list = []  # (key, tokens, k, v, ks, vs) queue
        #                                (ks/vs = scale slices, None unless
        #                                the pool is scaled int8)
        self.host_restore_bytes = 0    # cumulative host→device restore bytes
        if self._host_store is not None:
            if not cfg.prefix_caching:
                raise ValueError(
                    "a host KV store requires prefix_caching=True (the host "
                    "tier extends the content-addressed prefix cache)")
            self.allocator.attach_host_store(
                self._host_store, on_evict=self._queue_block_save)
        # Per-dispatch KV growth bounds the scheduler's lookahead: every fused
        # iteration can emit up to spec_tokens+1 tokens (and writes draft KV
        # that far ahead) when speculation is on.
        spec = getattr(self.runner, "spec_tokens", 0)
        self.scheduler = Scheduler(
            cfg.scheduler_config(decode_steps * (1 + spec)), self.allocator)
        # Fixed block-table width: worst-case blocks for max_model_len.
        self.table_width = -(-cfg.max_model_len // cfg.block_size)
        # Chunked prefill gathers prior KV over the table width it is given
        # (prefill_chunk_impl), so a width ladder lets short chunks avoid
        # attending over max_model_len worth of slots. On TPU we accept one
        # full-width variant instead: the gather costs a bounded extra HBM
        # read per chunk (~0.3 ms/chunk at 2048 ctx for a 1B model —
        # context, not width, dominates once fused), and collapsing the
        # ladder cuts compile variants 6x, which is what ends the cold-
        # compile stalls under prefix-cached traffic (docs/BENCHMARKS.md r2
        # spec x prefix investigation). Off-TPU keeps the ladder: CPU test
        # models compile in seconds and the gather there is the whole cost.
        from agentic_traffic_testing_tpu.runtime.scheduler import pow2_buckets

        self._chunk_width_buckets = (
            [self.table_width] if platform == "tpu"
            else pow2_buckets(4, self.table_width))

        self._inflight: deque[_Inflight] = deque()
        # Pipelined-prefill chunk dispatches issued (cumulative; the
        # llm_prefill_pipeline_dispatches_total gauge).
        self.num_pipeline_dispatches = 0
        # Overlapped-decode accounting (round 7): fast-path dispatches
        # issued against a predicted composition, and mispredict events —
        # a churn (stop/admission/abort) surfacing while predicted
        # dispatches were still in flight, i.e. speculative device work
        # whose post-stop tail the harvest discarded
        # (llm_decode_overlap_mispredicts_total).
        self.num_overlap_dispatches = 0
        self.num_overlap_mispredicts = 0
        self._overlap_unharvested = 0   # predicted dispatches not yet applied
        self._decode_epoch = -1         # scheduler epoch the armed batch saw
        # Memoized SamplingArrays keyed by the (padded, per-lane params)
        # composition: recurring waves of identical generation params (the
        # bench shape, and any steady fan-out traffic) reuse the uploaded
        # device arrays instead of rebuilding four host arrays + four
        # transfers per composition change (ROADMAP bs32 host-overhead
        # nibble). An OrderedDict so the capacity bound evicts LRU
        # (move-to-end on hit) instead of the old wholesale clear(),
        # which made a churning composition mix periodically re-pay every
        # rebuild the memo existed to avoid.
        self._samp_cache: OrderedDict = OrderedDict()
        self._decode_requests: list[Request] = []   # composition of device state
        self._decode_state: Optional[DecodeState] = None
        self._decode_tables: Optional[jax.Array] = None
        self._decode_samp: Optional[SamplingArrays] = None
        self._new_tokens: dict[str, list[int]] = {}
        self._requests: dict[str, Request] = {}  # live (unreported-finish) requests
        # Cumulative counters for metrics
        self.num_steps = 0
        # Robustness plane (round 9): per-batch dispatch-failure isolation,
        # deadline sweep, host-restore fallback, admission shedding.
        self.num_dispatch_failures = 0   # dispatches that failed their batch
        self.num_deadline_expired = 0    # requests aborted past deadline
        self.num_restore_fallbacks = 0   # host restores degraded to recompute
        self.num_shed = 0                # add_request refusals (bounded queue)
        # request_ids carrying a deadline: empty (the common case — knob
        # off, no body overrides) makes the per-step sweep one falsy test.
        self._deadline_ids: set[str] = set()
        # Deterministic fault injector (runtime/faultinject.py); None when
        # LLM_FAULT_SPEC is unset — every hook is one `is not None` test.
        self._faults = None
        if cfg.fault_spec:
            from agentic_traffic_testing_tpu.runtime.faultinject import (
                FaultInjector,
            )

            self._faults = FaultInjector.from_spec(cfg.fault_spec,
                                                   cfg.fault_seed)
        # Speculation acceptance accounting (live request lanes only):
        # emitted/iters = mean tokens per verify step in [1, spec_tokens+1];
        # accepted/drafted = the draft acceptance rate (llm_spec_* gauges —
        # iters doubles as the rounds counter, llm_spec_rounds_total).
        self.spec_iters = 0
        self.spec_emitted = 0
        self.spec_drafted = 0    # draft tokens proposed (consumed rounds)
        self.spec_accepted = 0   # draft tokens verification accepted
        # Step-clock telemetry (runtime/telemetry.py): None unless the
        # knob is on, so the hot loop stays byte-identical and every
        # hook below costs one `is not None` test with the plane off.
        self.telemetry = None
        if cfg.step_trace:
            self.enable_step_trace(
                capacity=cfg.step_trace if cfg.step_trace >= 2 else 4096)

    def enable_step_trace(self, capacity: int = 4096):
        """Install a StepClock recorder (host-only state, safe on any
        runner): LLM_STEP_TRACE routes here at construction; bench probes
        attach one to an already-built engine. Returns the recorder."""
        from agentic_traffic_testing_tpu.runtime.telemetry import StepClock

        self.telemetry = StepClock(capacity=capacity,
                                   slo_ttft_ms=self.cfg.slo_ttft_ms,
                                   slo_itl_ms=self.cfg.slo_itl_ms)
        self.scheduler.on_admit = self._record_admission
        return self.telemetry

    # statics: thread(engine-loop)
    def _record_admission(self, req: Request) -> None:
        """Scheduler admission callback (wired only when tracing): the
        exact instant a request turned RUNNING, with its cached-token
        discount."""
        rec = self.telemetry
        if rec is not None:
            rec.request_event(req.request_id, REQ_ADMITTED,
                              time.monotonic(), req.num_computed_tokens)

    def _default_num_blocks(self) -> int:
        """Budget KV blocks from device memory, vLLM-profiling style."""
        from agentic_traffic_testing_tpu.runtime.kv_cache import profile_num_blocks

        try:
            dev = jax.devices()[0]
            stats = dev.memory_stats() or {}
            limit = stats.get("bytes_limit", 0)
            used = stats.get("bytes_in_use", 0)
            free = max(0, limit - used)
        except Exception:
            free = 0
        if free <= 0:
            # No introspection (CPU tests): small fixed pool.
            return 512
        bytes_per = 2 if self.cfg.dtype in ("bfloat16", "bf16") else 4
        # fp8/int8 pages store one byte per element — the profiling pass
        # hands out roughly double the blocks. (int8's transient scan
        # outputs stay in compute dtype until the per-page quantize, so its
        # prefill transient is sized at bytes_per below.)
        kv_bytes = 1 if self.cfg.kv_cache_dtype else bytes_per
        transient_bytes = (bytes_per if self.cfg.kv_cache_dtype == "int8"
                           else kv_bytes)
        # Reserve room for prefill's per-layer K/V scan outputs (llama.py
        # prefill_impl defers pool writes; the transient peaks at one full
        # prefill bucket, B*T <= max_num_batched_tokens, lane-padded).
        from agentic_traffic_testing_tpu.runtime.kv_cache import phys_head_dim

        transient = (2 * self.model_cfg.num_layers
                     * self.cfg.max_num_batched_tokens
                     * self.model_cfg.num_kv_heads
                     * phys_head_dim(self.model_cfg.head_dim_)
                     * transient_bytes)
        free = max(0, free - transient)
        n = profile_num_blocks(
            self.model_cfg, self.cfg.block_size, free,
            self.cfg.memory_utilization, kv_bytes,
            tp_size=self.runner.tp_size,
            # PPRunner shards the pool's layer axis over its stages.
            pp_size=getattr(self.runner, "pp", 1),
            # int8 pools carry a K+V fp32 scale per (layer, page, kv-head).
            scale_bytes_per_head=(8 if self.cfg.kv_cache_dtype == "int8"
                                  else 0),
        )
        # Never exceed what max_num_seqs * max_model_len can actually use.
        cap = self.cfg.max_num_seqs * self.table_width + 1
        return max(2, min(n, cap))

    def warmup_decode_buckets(self) -> int:
        """Precompile the decode program for every batch bucket.

        Staggered arrivals walk the engine through small-batch buckets
        (1, 2, 4, ...) before reaching steady state; each cold bucket is a
        10-20 s XLA compile that BLOCKS the step loop mid-traffic (observed:
        a 5-way cache-hit fan-out crawling at 0.6 tok/s for 62 s while
        buckets compiled — docs/BENCHMARKS.md r2 A/B). Dummy lanes point at
        the trash block, so the KV writes land in the slot reserved for
        exactly this. Returns the number of programs compiled."""
        from agentic_traffic_testing_tpu.runtime.scheduler import pow2_buckets

        spec = getattr(self.runner, "spec_tokens", 0)
        n = 0
        for b in pow2_buckets(1, self.cfg.max_num_seqs):
            tables = jnp.full((b, self.table_width), TRASH_BLOCK, jnp.int32)
            state = DecodeState(tokens=jnp.zeros((b,), jnp.int32),
                                positions=jnp.zeros((b,), jnp.int32),
                                steps=jnp.zeros((b,), jnp.int32))
            samp = self._sampling_arrays([], b)
            # Warm the program the live loop will actually run: the
            # overlapped (donated-state) jit under decode_overlap, the
            # plain one otherwise — else the first fast-path dispatch
            # would cold-compile mid-traffic.
            decode = (self.runner.decode_overlapped
                      if self.cfg.decode_overlap else self.runner.decode)
            if spec > 0:
                drafts = jnp.zeros((b, self._spec_stream_len()), jnp.int32)
                result = decode(self.cache, tables, state, samp,
                                drafts=drafts)
            else:
                result = decode(self.cache, tables, state, samp)
            # decode donates the cache: keep the returned one (dummy writes
            # went to the trash block; real pages are untouched).
            self.cache = result[1]
            jax.block_until_ready(result[2])
            n += 1
        return n

    def warmup_prefill_buckets(self, min_len: int = 0,
                               max_len: Optional[int] = None) -> int:
        """Precompile the batched-prefill program for every (batch, length)
        bucket combination the live path can emit.

        Relevant when `prefill_batch_max_len` is raised past the 128 default:
        concurrent long-prompt arrivals then prefill together, and each cold
        (batch, length) shape is a 15-40 s XLA compile that would otherwise
        land mid-burst (the exact failure prefill_batch_max_len=128 existed
        to avoid). `min_len`/`max_len` bound the warmed length buckets so
        deployments that only see one prompt shape (bench.py's fan-out probe)
        don't pay for the whole ladder. Dummy lanes write to the trash block.
        Returns the number of programs compiled."""
        from agentic_traffic_testing_tpu.runtime.scheduler import bucket_up

        scfg = self.scheduler.cfg
        cap = min(scfg.prefill_batch_max_len,
                  max_len if max_len is not None else scfg.prefill_batch_max_len)
        # Prompts past the batching cap still take the batched-prefill path
        # SOLO (the scheduler's cap only limits batches of >= 2 members), up
        # to the chunk threshold's bucket — past that they route through the
        # chunk path (warmup_chunk_buckets' territory) and warming batched
        # shapes would be pure wasted startup time.
        solo_cap = max(scfg.prefill_buckets)
        if scfg.prefill_chunk_tokens is not None:
            chunk_bucket = bucket_up(scfg.prefill_chunk_tokens,
                                     scfg.prefill_buckets)
            solo_cap = (-(-chunk_bucket // self.cfg.block_size)
                        * self.cfg.block_size)
        cap = min(cap, solo_cap)
        lens = sorted({-(-t // self.cfg.block_size) * self.cfg.block_size
                       for t in scfg.prefill_buckets})
        n = 0
        for t in lens:
            if t < min_len or t > solo_cap:
                continue
            # The scheduler bounds the UNPADDED member count by the token
            # budget, then pads UP to a batch bucket — so the largest live
            # shape at this length is bucket_up(k_max), not the largest
            # bucket with b*t under the budget. Above the batching cap only
            # the solo shape is live.
            if t > cap:
                b_cap = 1
            else:
                k_max = max(1, min(scfg.max_num_seqs,
                                   scfg.max_num_batched_tokens // t))
                b_cap = bucket_up(k_max, scfg.batch_buckets)
            for b in scfg.batch_buckets:
                if b > b_cap:
                    break
                tokens = jnp.zeros((b, t), jnp.int32)
                tables = jnp.full((b, self.table_width), TRASH_BLOCK, jnp.int32)
                seq_lens = jnp.ones((b,), jnp.int32)
                samp = self._sampling_arrays([], b)
                split = self._pipeline_split(t)
                if split is not None:
                    # Pipelined path live: warm ITS program for this
                    # bucket (one chunk suffices — chunk_start is traced,
                    # so every chunk of the bucket shares the compile).
                    width = bucket_up(-(-t // self.cfg.block_size),
                                      self._chunk_width_buckets)
                    self.cache, carry = self.runner.prefill_pipeline(
                        tokens[:, :split], self.cache, tables[:, :width],
                        jnp.int32(0), seq_lens, jnp.zeros((b,), jnp.int32),
                        samp, jnp.zeros((b,), jnp.int32))
                    jax.block_until_ready(carry)
                    n += 1
                    continue
                state, self.cache, out = self.runner.prefill(
                    tokens, self.cache, tables, seq_lens, samp,
                    jnp.zeros((b,), jnp.int32))
                jax.block_until_ready(out)
                n += 1
        return n

    def warmup_chunk_buckets(self) -> int:
        """Precompile the chunked-prefill program for every (chunk, width)
        bucket combination the live path can emit.

        Prefix-cached requests prefill only their suffix through the chunk
        path, and the suffix length walks the bucket ladder as prompts vary
        — each cold bucket is a ~15-20 s compile serialized against live
        decode (the r2 spec x prefix fan-out stall's second half). Chunk
        lengths come from the scheduler's chunk_ladder() (the exact compiled
        set: _next_chunk splits chunks rather than emitting off-ladder
        lengths); widths are this engine's _chunk_width_buckets (one on TPU,
        the pow2 ladder off-TPU). Only worth the startup time when prefix
        caching (or very long prompts) will actually route traffic here."""
        n = 0
        for c in self.scheduler.cfg.chunk_ladder():
            for width in self._chunk_width_buckets:
                if width * self.cfg.block_size < c:
                    continue  # live path never attends narrower than a chunk
                tokens = jnp.zeros((1, c), jnp.int32)
                tables = jnp.full((1, width), TRASH_BLOCK, jnp.int32)
                samp = self._sampling_arrays([], 1)
                self.cache, out = self.runner.prefill_chunk(
                    tokens, self.cache, tables, jnp.int32(0), jnp.int32(1),
                    samp, jnp.zeros((1,), jnp.int32),
                )
                jax.block_until_ready(out)
                n += 1
        return n

    # -- request API -------------------------------------------------------

    # statics: thread(engine-loop)
    def add_request(
        self,
        prompt_ids: list[int],
        sampling: Optional[SamplingParams] = None,
        request_id: Optional[str] = None,
    ) -> Request:
        req = Request(
            request_id=request_id or uuid.uuid4().hex[:16],
            prompt_ids=list(prompt_ids),
            sampling=sampling or SamplingParams(),
        )
        try:
            self.scheduler.add_request(req)
        except QueueFullError:
            self.num_shed += 1
            raise
        # Deadline: per-request override, else the engine default (0 = no
        # deadline — nothing is tracked and the step sweep stays one test).
        dl_ms = req.sampling.deadline_ms
        if dl_ms is None and self.cfg.deadline_ms > 0:
            dl_ms = self.cfg.deadline_ms
        if dl_ms is not None and dl_ms > 0:
            req.deadline = req.arrival_time + dl_ms / 1000.0
            self._deadline_ids.add(req.request_id)
        self._requests[req.request_id] = req
        if self.telemetry is not None:
            self.telemetry.request_queued(req.request_id, req.arrival_time)
        return req

    # statics: thread(engine-loop)
    def abort_request(self, req: Request) -> list[StepOutput]:
        """Abort one request. Returns any SIBLING events the abort produced:
        the drain applies in-flight tokens, which can finish other lanes —
        and if that empties the engine, no later step() would ever flush
        them (a disconnect-triggered abort would strand the survivors'
        streams). Callers that abort from outside the step loop must route
        the returned events exactly like step()'s."""
        if req.is_finished():
            # Already completed (e.g. a PREVIOUS abort's drain finished this
            # lane normally): don't clobber FINISHED/STOP state with ABORT.
            return []
        # Mark aborted BEFORE draining: _apply_inflight_host skips
        # non-RUNNING lanes, so no token computed-but-unharvested at abort
        # time lands on the request.
        if self._overlap_unharvested > 0 and req in self._decode_requests:
            # Overlap mispredict: speculative dispatches in flight carry
            # tokens for the aborted lane that the drain below discards.
            self.num_overlap_mispredicts += 1
            if self.telemetry is not None:
                self.telemetry.record_instant(EVENT_MISPREDICT,
                                              time.monotonic())
        req.state = RequestState.ABORTED
        req.finish_reason = FinishReason.ABORT
        req.finish_time = time.monotonic()
        self._drain_all()
        self.scheduler.abort(req)
        self._requests.pop(req.request_id, None)
        self._new_tokens.pop(req.request_id, None)
        if self._deadline_ids:
            self._deadline_ids.discard(req.request_id)
        self._invalidate_decode_state()
        if self.telemetry is not None:
            # Sibling retirements ride _flush_events; the aborted lane
            # itself never reaches it (its _new_tokens entry was popped).
            self.telemetry.request_retired(
                req.request_id, req.finish_time, reason="abort")
        return self._flush_events()

    def has_work(self) -> bool:
        return self.scheduler.has_work() or bool(self._inflight)

    # -- the step loop -----------------------------------------------------

    # statics: thread(engine-loop)
    def step(self) -> list[StepOutput]:
        """Advance by one device dispatch (or drain); return request events."""
        self.num_steps += 1
        if self._deadline_ids:
            self._expire_deadlines()

        # Only tear the decode pipeline down for admission when the head of
        # the waiting queue could actually be admitted — an unadmittable
        # (KV-starved) waiter must not degrade decode to synchronous readback.
        admission_possible = self._admission_possible()
        if (not admission_possible and self.scheduler.waiting
                and self._inflight and self._decode_requests
                and self._decode_budget_satisfied()):
            # Wave overlap: every running lane's remaining tokens are already
            # computed inside in-flight dispatches, so their KV blocks and
            # scheduler slots are dead weight — release them NOW and dispatch
            # the next wave's prefill behind the in-flight work instead of
            # draining first. The final result copy then crosses the tunnel
            # (~110 ms one-way on axon) while the next wave computes; tokens
            # still land via the normal harvest. Device execution is FIFO, so
            # the prefill's writes into reused blocks order after the old
            # wave's reads/writes.
            for r in self._decode_requests:
                if not r.is_finished():
                    self.scheduler.finish(r)
            self._invalidate_decode_state()
            admission_possible = self._admission_possible()
            if admission_possible:
                self._plan_and_dispatch()
                self._harvest(max_inflight=self.cfg.pipeline_depth)
                if self.cfg.disagg_role == "prefill":
                    self._disagg_handoff()
                return self._flush_events()
            # Released but still unadmittable (pool too small for the next
            # head): fall through to the drain path below.
        if admission_possible or self._decode_state is None or not self._decode_requests:
            # Composition may change: sync up, then let the scheduler decide.
            self._drain_all()
            self._plan_and_dispatch()
        elif self._decode_budget_satisfied() and self._inflight:
            # Every running lane's remaining token budget is already covered
            # by in-flight dispatches: one more dispatch would compute only
            # tokens the harvester drops. Retire the oldest instead of
            # pipelining waste (the bench shape: max_tokens=64, K=16,
            # depth=2 used to run 6 dispatches for 4 dispatches of work).
            self._retire([self._inflight.popleft()])
        else:
            self._dispatch_decode()

        self._harvest(max_inflight=self.cfg.pipeline_depth)
        if self.cfg.disagg_role == "prefill":
            self._disagg_handoff()
        return self._flush_events()

    def _admission_possible(self) -> bool:
        """Would the scheduler change composition if we synced up right now?"""
        return (self.scheduler.can_admit_head()
                or self.scheduler.has_pending_chunk()
                or bool(self.scheduler.failed))

    def _plan_and_dispatch(self) -> None:
        """Plan against *current* (post-drain) state and run the step.

        Dispatch exceptions (injected faults included) fail ONLY the
        planned batch's requests — a structured error reaches each
        stream via the normal event flush, the scheduler reconciles
        through the abort path, and the step loop keeps serving every
        other request (round 9; the async layer's fail-all remains the
        escalation for failures outside any batch)."""
        plan = self.scheduler.plan()
        self._fail_unservable()
        try:
            if isinstance(plan, PrefillBatch):
                self._run_prefill(plan)
            elif isinstance(plan, HybridBatch):
                self._run_hybrid(plan)
            elif isinstance(plan, ChunkPrefill):
                self._run_chunk(plan)
            elif isinstance(plan, DecodeBatch):
                self._setup_decode(plan)
                self._do_decode_dispatch()
            else:
                self._invalidate_decode_state()
        except Exception as exc:
            self._fail_dispatch(_plan_requests(plan), exc)

    def _expire_deadlines(self) -> None:
        """Abort every live request past its deadline (queued or running)
        through the abort machinery: in-flight tokens drain first (they
        belong to the client), blocks release, and the stream gets a
        terminal FinishReason.DEADLINE event via the normal flush."""
        now = time.monotonic()
        expired = []
        for rid in self._deadline_ids:
            req = self._requests.get(rid)
            if (req is not None and not req.is_finished()
                    and req.deadline is not None and now >= req.deadline):
                expired.append(req)
        if not expired:
            return
        self._drain_all()
        now = time.monotonic()
        teardown = False
        for req in expired:
            if req.is_finished():
                continue  # the drain delivered its final token in time
            teardown = teardown or req in self._decode_requests
            self.scheduler.abort(req)
            req.state = RequestState.ABORTED
            req.finish_reason = FinishReason.DEADLINE
            req.finish_time = now
            req.error = (f"deadline exceeded after "
                         f"{(now - req.arrival_time) * 1000:.0f} ms")
            self.num_deadline_expired += 1
            # An empty increment keys the terminal event for the stream.
            self._new_tokens.setdefault(req.request_id, [])
        if teardown:
            self._invalidate_decode_state()

    def _fail_dispatch(self, reqs: list[Request], exc: Exception) -> None:
        """Fail exactly one batch: the requests whose dispatch raised.

        In-flight entries predate the failure and carry valid tokens, so
        they drain first; each still-live member then aborts through the
        scheduler (blocks released, queues consistent) and reports a
        structured error event. Waiting requests and other waves are
        untouched — the next step re-plans from clean state. Injected
        faults (runtime/faultinject.py) raise BEFORE the runner call, so
        this path never sees half-donated buffers; real mid-execution
        failures recover best-effort and escalate to the async layer's
        fail-all if the drain itself is poisoned."""
        self.num_dispatch_failures += 1
        log.warning("dispatch failed; failing %d request(s): %s",
                    len(reqs), exc)
        self._drain_all()
        for r in reqs:
            if r.is_finished():
                continue  # the drain finished it normally first
            if self.cfg.migration and r.sampling_step > 0:
                # Drain-and-migrate (round 11): a STARTED stream's terminal
                # used to be this ERROR — with migration on it checkpoints
                # instead, and the pool re-queues it at the head of a
                # survivor (adopting the MIGRATED terminal). Un-started
                # requests keep the round-9 path below: the pool's
                # retry-once already moves them with no tokens to replay.
                # A failed checkpoint (injected migrate_error, capture
                # fault) degrades to the kill path inside the helper.
                self._checkpoint_or_fail(r, trigger="quarantine",
                                         note=f" (dispatch failed: {exc})")
                continue
            self._fail_request(r, f"dispatch failed: {exc}")
        self._invalidate_decode_state()

    def _fail_request(self, r: Request, msg: str) -> None:
        """Round-9 kill path for ONE request: abort through the scheduler
        (blocks released, queues consistent) and queue a structured ERROR
        terminal for its stream."""
        self.scheduler.abort(r)
        r.state = RequestState.ABORTED
        r.finish_reason = FinishReason.ERROR
        r.finish_time = time.monotonic()
        r.error = msg
        self._new_tokens.setdefault(r.request_id, [])

    def _fail_unservable(self) -> None:
        for req in self.scheduler.failed:
            self._finish(req, FinishReason.ERROR)
            # _finish marks FINISHED; reflect the error state instead.
            req.state = RequestState.ABORTED
            self._new_tokens.setdefault(req.request_id, [])
        self.scheduler.failed.clear()

    def _fill_tables(self, reqs: list[Request], tables: np.ndarray) -> None:
        """Build block-table rows for reqs into tables[:len(reqs)].

        One native call when the C++ core backs the allocator; otherwise a
        Python row loop. Rows beyond len(reqs) stay trash-padded.
        """
        fill = getattr(self.allocator, "fill_tables", None)
        if fill is not None and reqs:
            fill([r.blocks for r in reqs], self.table_width, tables[: len(reqs)])
        else:
            for i, r in enumerate(reqs):
                tables[i] = r.blocks.table_row(self.table_width)

    # -- prefill -----------------------------------------------------------

    def _pipeline_split(self, t: int) -> Optional[int]:
        """Chunk length for the pipelined-prefill path at padded length t,
        or None for the single-dispatch path.

        Splits t into the most chunks <= prefill_pipeline_chunks that keep
        every chunk equal-length AND block-aligned (uniform chunks are what
        let one compiled program — chunk_start is traced — serve the whole
        prefill; a ragged tail chunk would be a second program AND could
        page-write past the table). Serving buckets are pow2/block-aligned,
        so K = 2..8 always splits cleanly above 2 blocks; shapes that
        don't split fall back to the single dispatch, which is always
        correct."""
        k = self.cfg.prefill_pipeline_chunks
        if k < 2:
            return None
        bs = self.cfg.block_size
        for kk in range(min(k, t // bs), 1, -1):
            if t % kk == 0 and (t // kk) % bs == 0:
                return t // kk
        return None

    def _prefill_host_arrays(self, plan: PrefillBatch):
        """Host-side batch assembly shared by the single-dispatch and
        pipelined prefill paths: (tokens [B, T], seq_lens [B], full-width
        tables [B, W], sampling steps [B]) as numpy arrays."""
        reqs = plan.requests
        b, t = plan.padded_batch, plan.padded_len
        tokens = np.zeros((b, t), np.int32)
        seq_lens = np.zeros((b,), np.int32)
        tables = np.full((b, self.table_width), TRASH_BLOCK, np.int32)
        steps = np.zeros((b,), np.int32)
        for i, r in enumerate(reqs):
            tokens[i, : r.num_prompt_tokens] = r.prompt_ids
            seq_lens[i] = r.num_prompt_tokens
            steps[i] = r.sampling_step
        self._fill_tables(reqs, tables)
        return tokens, seq_lens, tables, steps

    # statics: hot-region(prefill-dispatch)
    def _run_prefill(self, plan: PrefillBatch) -> None:
        if self._faults is not None:  # before any donation/state mutation
            self._faults.maybe_raise("dispatch_error")
        split = self._pipeline_split(plan.padded_len)
        if split is not None:
            self._run_prefill_pipelined(plan, split)
            return
        reqs = plan.requests
        b = plan.padded_batch
        tokens, seq_lens, tables, steps = self._prefill_host_arrays(plan)
        tables_dev = jnp.asarray(tables)
        samp = self._sampling_arrays(reqs, b)
        rec = self.telemetry
        t0 = time.monotonic() if rec is not None else 0.0
        span = rec.annotation(PHASE_PREFILL) if rec is not None else NULL_ANNOTATION
        with span:
            state, self.cache, out = self.runner.prefill(
                jnp.asarray(tokens), self.cache, tables_dev,
                jnp.asarray(seq_lens), samp, jnp.asarray(steps),
            )
        if rec is not None:
            rec.record_dispatch(
                PHASE_PREFILL, t0, time.monotonic(), len(reqs),
                sum(r.num_prompt_tokens for r in reqs))
        for r in reqs:
            r.num_computed_tokens = r.num_prompt_tokens
            self._register_prefix(r)
        # Async prefill -> decode handoff: the prefill program already
        # returns a ready DecodeState (sampled token, positions, PRNG steps),
        # so decode dispatches can follow back-to-back without waiting for
        # the first token's host round trip (~100 ms through the axon tunnel
        # for a bs=8 batch). The sampled tokens join the harvest pipeline as
        # a 1-token in-flight entry; TTFT is stamped when they land on host.
        first = out[:, None]  # [B] -> [B, 1], harvest expects [B, K]
        try:
            first.copy_to_host_async()
        except Exception:
            pass
        self._decode_requests = list(reqs)
        self._decode_state = state
        self._decode_tables = tables_dev
        self._decode_samp = samp
        self._decode_block_counts = [r.blocks.num_blocks for r in reqs]
        self._decode_epoch = self.scheduler.composition_epoch
        self._inflight.append(_Inflight(first, list(reqs)))

    # statics: hot-region(prefill-pipeline)
    def _run_prefill_pipelined(self, plan: PrefillBatch, c: int) -> None:
        """The round-6 dispatch-overlap path: K = T/c position-chunks of
        the (solo or batched) prefill dispatched back-to-back with NO host
        synchronization — chunk i+1's host-side dispatch (and its tunnel
        transfer) overlaps chunk i's device compute, so the per-dispatch
        overhead is paid once, not K times, and the whole prompt still
        reads back exactly ONE [B] token array at the tail. The sampled
        first token rides a donated device carry across chunks
        (runner.prefill_pipeline); the decode handoff below is identical
        to _run_prefill's async path."""
        reqs = plan.requests
        b, t = plan.padded_batch, plan.padded_len
        tokens, seq_lens, tables, steps = self._prefill_host_arrays(plan)
        from agentic_traffic_testing_tpu.runtime.scheduler import bucket_up

        # The chunk impl gathers prior pages over the width it is given
        # (as in _run_chunk): bound it to the bucket covering this prompt.
        need_cols = -(-t // self.cfg.block_size)
        width = bucket_up(need_cols, self._chunk_width_buckets)
        chunk_tables = jnp.asarray(tables[:, :width])
        tables_dev = jnp.asarray(tables)   # full width for the decode handoff
        samp = self._sampling_arrays(reqs, b)
        seq_dev = jnp.asarray(seq_lens)
        steps_dev = jnp.asarray(steps)
        tokens_dev = jnp.asarray(tokens)   # ONE host upload; chunks slice on device
        carry = jnp.zeros((b,), jnp.int32)
        rec = self.telemetry
        for start in range(0, t, c):
            t0 = time.monotonic() if rec is not None else 0.0
            span = (rec.annotation(PHASE_PIPELINED_PREFILL)
                    if rec is not None else NULL_ANNOTATION)
            with span:
                self.cache, carry = self.runner.prefill_pipeline(
                    tokens_dev[:, start:start + c], self.cache, chunk_tables,
                    jnp.int32(start), seq_dev, carry, samp, steps_dev,
                )
            self.num_pipeline_dispatches += 1
            if rec is not None:
                rec.record_dispatch(PHASE_PIPELINED_PREFILL, t0,
                                    time.monotonic(), len(reqs), b * c)
        for r in reqs:
            r.num_computed_tokens = r.num_prompt_tokens
            self._register_prefix(r)
        # Tail: same async prefill -> decode handoff as _run_prefill
        # (speculative engines included — the spec decode state is the
        # same plain DecodeState since round 14).
        first = carry[:, None]
        try:
            first.copy_to_host_async()
        except Exception:
            pass
        self._decode_requests = list(reqs)
        self._decode_state = DecodeState(tokens=carry, positions=seq_dev,
                                         steps=steps_dev + 1)
        self._decode_tables = tables_dev
        self._decode_samp = samp
        self._decode_block_counts = [r.blocks.num_blocks for r in reqs]
        self._decode_epoch = self.scheduler.composition_epoch
        self._inflight.append(_Inflight(first, list(reqs)))

    def _register_prefix(self, r: Request) -> None:
        """Index this prompt's full blocks for prefix reuse (no-op unless the
        prefix-caching allocator is active and the request still holds its
        blocks — _append_token may have finished+released it already)."""
        register = getattr(self.allocator, "register_computed", None)
        if register is not None and r.blocks is not None:
            register(r.blocks, r.prompt_ids,
                     keys=request_chain_keys(self.allocator, r))

    # -- host-tier KV offload (runtime/kv_offload.py) ----------------------

    # statics: thread(engine-loop)
    def _queue_block_save(self, blk: int, key: int, tokens: tuple) -> None:
        """Eviction hook: slice the reclaimed block's pages and start their
        device→host copy. Called from inside allocator.allocate() — i.e.
        during plan(), BEFORE the reclaiming prefill/decode dispatches — so
        device FIFO ordering guarantees the slice reads the old content.
        The blocking fetch happens later in _flush_saves, overlapped with
        whatever dispatched in between (plain copies on the CPU test mesh,
        where copy_to_host_async is a no-op)."""
        if self._host_store.contains(key, tokens):
            return  # already spilled (a prior eviction of the same content)
        if len(self._save_pending) >= 64:
            # Bound the device-side transient: each pending save holds a
            # fresh K+V block copy in HBM, and a single long-prompt
            # admission can reclaim hundreds of blocks in one allocate()
            # while HBM is already under the capacity pressure that caused
            # the reclaim. Drain mid-wave past 64 blocks (~64 MB on the 1B
            # layout) instead of accumulating a whole evictable pool.
            self._flush_saves()
        k = self.cache.k[:, :, blk]
        v = self.cache.v[:, :, blk]
        # Quantized pools spill raw int8 pages PLUS their per-head scales —
        # no round trip through bf16, so a later restore is byte-identical
        # and the host tier holds ~2x the blocks per GB.
        ks = vs = None
        if self.cache.quantized:
            ks = self.cache.k_scale[:, blk]
            vs = self.cache.v_scale[:, blk]
        for a in (k, v) if ks is None else (k, v, ks, vs):
            try:
                a.copy_to_host_async()
            except Exception:
                pass
        self._save_pending.append((key, tokens, k, v, ks, vs))
        if self.telemetry is not None:
            self.telemetry.record_instant(EVENT_HOST_SAVE, time.monotonic())

    # statics: hot-region(host-tier-drain)
    def _flush_saves(self) -> None:
        """Drain the save queue into the host store with ONE batched host
        transfer (the slices' async copies started at evict time, so this
        mostly collects finished buffers rather than waiting)."""
        if not self._save_pending:
            return
        pending, self._save_pending = self._save_pending, []
        leaves: list = []
        for _, _, k, v, ks, vs in pending:
            leaves.extend((k, v) if ks is None else (k, v, ks, vs))
        fetched = iter(jax.device_get(leaves))  # statics: allow-host-sync(batched host-tier save drain; async copies started at evict time)
        for key, tokens, _, _, ks, _ in pending:
            if ks is None:
                self._host_store.put(key, tokens, next(fetched), next(fetched))
            else:
                self._host_store.put(key, tokens, next(fetched), next(fetched),
                                     k_scale=next(fetched),
                                     v_scale=next(fetched))

    def _apply_pending_restore(self, r: Request) -> bool:
        """Write a request's host-tier restore plan into its freshly
        allocated device blocks, then index them for sharing. Runs right
        before the request's first suffix chunk dispatches, so every
        subsequent reader (the chunk's prior-page gather included) orders
        after the writes.

        Returns False when the restore failed (corrupt pages, injected
        restore_error) and the request was degraded to the recompute
        path (_restore_fallback) — the caller must skip its dispatch
        this step; the request is already back at the head of the queue."""
        restores = r.pending_restore
        if not restores:
            return True
        r.pending_restore = None
        try:
            if self._faults is not None:
                self._faults.maybe_raise("restore_error")
            self._write_restore_blocks(restores)
        except Exception as exc:
            self._restore_fallback(r, restores, exc)
            return False
        self.allocator.register_restored(restores)
        nbytes = sum(int(rb.k.nbytes) + int(rb.v.nbytes) for rb in restores)
        self.host_restore_bytes += nbytes
        if self.telemetry is not None:
            now = time.monotonic()
            self.telemetry.record_instant(EVENT_HOST_RESTORE, now,
                                          len(restores))
            self.telemetry.request_event(r.request_id, REQ_RESTORE, now,
                                         nbytes)
        return True

    # statics: hot-region(host-tier-drain)
    def _write_restore_blocks(self, restores: list) -> None:
        """Validated host→device page write shared by the host-tier
        restore path and migration adoption: every block's pages (and,
        on a quantized pool, its scale pair) must match the live pool's
        geometry, then land in ONE batched scatter. Raises on any
        mismatch — callers own the degrade path (recompute)."""
        # Validate against the live pool's page geometry BEFORE any
        # write: a corrupt host block must degrade to recompute, not
        # scatter garbage-shaped pages (or raise) mid-step.
        shape = self.cache.k.shape[:2] + self.cache.k.shape[3:]
        sshape = (None if not self.cache.quantized
                  else (self.cache.k_scale.shape[0],
                        self.cache.k_scale.shape[2]))
        for rb in restores:
            if (rb.k.shape != shape or rb.v.shape != shape
                    or rb.k.dtype != self.cache.k.dtype
                    or rb.v.dtype != self.cache.v.dtype):
                raise ValueError(
                    f"host block {rb.key} pages {rb.k.shape}/"
                    f"{rb.k.dtype} do not match the pool page "
                    f"{shape}/{self.cache.k.dtype}")
            if sshape is not None and (
                    rb.k_scale is None or rb.v_scale is None
                    or rb.k_scale.shape != sshape
                    or rb.v_scale.shape != sshape):
                raise ValueError(
                    f"host block {rb.key} carries no (or mis-shaped) "
                    f"int8 scales for the quantized pool ({sshape})")
            if sshape is None and rb.k_scale is not None:
                raise ValueError(
                    f"host block {rb.key} carries int8 scales but the "
                    f"pool is not quantized")
        blks = jnp.asarray([rb.block for rb in restores], jnp.int32)
        # .at[].set on TPU lowers as copy-pool-then-update (~2 ms/GB,
        # the reason per-step KV writes are DUS chains — kv_cache.py).
        # Here it runs ONCE per admission against a >= 100 ms prefill
        # recompute, and a donated/jitted DUS chain would compile per
        # restore length — the scatter is the right trade at this call
        # rate. [N, L, KH, bs, hd] -> pool axes [L, KH, N, bs, hd]
        k_new = np.stack([rb.k for rb in restores]).transpose(1, 2, 0, 3, 4)
        v_new = np.stack([rb.v for rb in restores]).transpose(1, 2, 0, 3, 4)
        cache = self.cache._replace(
            k=self.cache.k.at[:, :, blks].set(k_new),
            v=self.cache.v.at[:, :, blks].set(v_new),
        )
        if sshape is not None:
            # Scales restore unchanged alongside their pages ([N, L,
            # KH] -> scale axes [L, N, KH]) — the byte-identity the
            # quantized evict->restore test pins.
            ks_new = np.stack([rb.k_scale for rb in restores]
                              ).transpose(1, 0, 2)
            vs_new = np.stack([rb.v_scale for rb in restores]
                              ).transpose(1, 0, 2)
            cache = cache._replace(
                k_scale=cache.k_scale.at[:, blks].set(ks_new),
                v_scale=cache.v_scale.at[:, blks].set(vs_new),
            )
        self.cache = cache

    def _restore_fallback(self, r: Request, restores: list,
                          exc: Exception) -> None:
        """Degrade a failed host-tier restore to the recompute path.

        The offending store entries are invalidated (re-admission must
        not re-match them) and the WHOLE admission is torn down and
        re-queued at the head rather than patched in place: blocks after
        the failed restore can be device-shared, and recomputing into
        them would rewrite shared KV under live sharers. Re-admission
        recomputes exactly what the tier can no longer supply — the
        preempt-and-recompute fallback PagedAttention treats as the
        universal correctness escape (PAPERS.md)."""
        self.num_restore_fallbacks += 1
        log.warning("host-tier restore failed for %s; degrading to "
                    "recompute: %s", r.request_id, exc)
        if self._host_store is not None:
            for rb in restores:
                self._host_store.invalidate(rb.key)
        self.scheduler.abort(r)  # releases blocks, removes from running
        r.state = RequestState.WAITING
        r.num_computed_tokens = 0
        self.scheduler.waiting.appendleft(r)

    # -- live migration (round 11, runtime/scheduler.MigrationPlan) --------

    # statics: thread(engine-loop)
    def checkpoint_request(self, req: Request, trigger: str = "drain"):
        """Checkpoint a live request for migration: drain its in-flight
        tokens (they belong to the client and ride the MIGRATED terminal),
        capture token history + sampling carry + full KV blocks, then
        release the request from this engine exactly like an abort.

        Returns the MigrationPlan (also attached to `req.migration` on the
        terminal event), or None when the drain finished the request
        normally — its ordinary terminal flushes instead. Raises on the
        injected `migrate_error` fault (BEFORE any capture or teardown, so
        the caller's degrade path sees an intact request) and on real
        capture failures; callers route those to the round-9 kill path
        (`_checkpoint_or_fail`). Works mid-chunked-prefill too: only the
        computed full blocks travel and the target resumes the remaining
        chunks — migration completes cleanly rather than refusing."""
        from agentic_traffic_testing_tpu.runtime.scheduler import (
            MigrationBlock,
            MigrationPlan,
        )

        if req.is_finished():
            return None
        if self._faults is not None:
            self._faults.maybe_raise("migrate_error")
        if self._overlap_unharvested > 0 and req in self._decode_requests:
            # Overlap mispredict: speculative dispatches in flight carry
            # post-checkpoint tokens for this lane that the drain below
            # keeps (they are real tokens) — but the pipeline itself is
            # torn down, which is the mispredict accounting's unit.
            self.num_overlap_mispredicts += 1
            if self.telemetry is not None:
                self.telemetry.record_instant(EVENT_MISPREDICT,
                                              time.monotonic())
        self._drain_all()
        if req.is_finished():
            return None  # the drain delivered its final token in time
        token_ids = req.prompt_ids + req.output_ids
        # KV coverage: a prefilling request has pages for its computed
        # prompt tokens (block-aligned — only whole chunks completed); a
        # decoding one for EVERY position but the last sampled token's
        # (its page write rides the next dispatch, which never runs here).
        # The decode-phase capture includes the partial tail block on
        # purpose: the target then resumes directly on the DECODE path —
        # the exact dispatch the source would have run next — which is
        # what makes the resumed tokens byte-identical (a chunk-path tail
        # recompute would produce bitwise-different KV/logits than the
        # baseline's decode writes).
        bs = self.cfg.block_size
        decodable = not req.is_prefilling
        kv_tokens = (max(0, req.total_len - 1) if decodable
                     else req.num_computed_tokens)
        kv_tokens = min(kv_tokens, len(token_ids) - 1)
        n_blocks = -(-kv_tokens // bs) if decodable else kv_tokens // bs
        mig_blocks: list = []
        if req.blocks is not None and n_blocks > 0:
            blks = list(req.blocks.blocks[:n_blocks])
            leaves = [self.cache.k[:, :, blks], self.cache.v[:, :, blks]]
            if self.cache.quantized:
                leaves += [self.cache.k_scale[:, blks],
                           self.cache.v_scale[:, blks]]
            fetched = jax.device_get(leaves)
            k_all, v_all = fetched[0], fetched[1]
            ks_all = fetched[2] if self.cache.quantized else None
            vs_all = fetched[3] if self.cache.quantized else None
            for i in range(n_blocks):
                mig_blocks.append(MigrationBlock(
                    tokens=tuple(token_ids[i * bs:min((i + 1) * bs,
                                                      kv_tokens)]),
                    k=k_all[:, :, i], v=v_all[:, :, i],
                    k_scale=None if ks_all is None else ks_all[:, i],
                    v_scale=None if vs_all is None else vs_all[:, i],
                ))
        else:
            kv_tokens = 0
        plan = MigrationPlan(
            request_id=req.request_id,
            token_ids=token_ids,
            sampling=req.sampling,
            sampling_step=req.sampling_step,
            num_orig_prompt_tokens=req.num_orig_prompt_tokens,
            arrival_time=req.arrival_time,
            depth_at_enqueue=req.depth_at_enqueue,
            num_computed_tokens=req.num_computed_tokens,
            blocks=mig_blocks,
            kv_tokens=kv_tokens,
            decodable=decodable,
            block_size=bs,
            deadline=req.deadline,
            trigger=trigger,
            created_t=time.monotonic(),
            hops=req.migration_hops + 1,
        )
        # Teardown mirrors abort_request — pages are host-resident (the
        # device_get above is synchronous), so releasing the blocks now is
        # safe even though a later dispatch may overwrite them. Drained
        # tokens already in _new_tokens ride the MIGRATED terminal.
        req.state = RequestState.ABORTED
        req.finish_reason = FinishReason.MIGRATED
        req.finish_time = time.monotonic()
        req.migration = plan
        self.scheduler.abort(req)
        # The MIGRATED terminal rides the normal event flush (which also
        # drops the request from _requests, discards its deadline entry,
        # and retires its telemetry timeline under reason="migrated").
        self._new_tokens.setdefault(req.request_id, [])
        self._invalidate_decode_state()
        return plan

    # statics: thread(engine-loop)
    def _checkpoint_or_fail(self, r: Request, trigger: str,
                            note: str = "") -> bool:
        """Checkpoint `r`; any failure (injected `migrate_error` included)
        degrades to the round-9 kill path — a structured ERROR terminal —
        so a stream never hangs on a failed migration. True when the
        request reached a MIGRATED terminal (or finished normally during
        the drain)."""
        try:
            self.checkpoint_request(r, trigger=trigger)
            return True
        except Exception as exc:
            log.warning("checkpoint failed for %s; degrading to the "
                        "round-9 kill path: %s", r.request_id, exc)
            if not r.is_finished():
                self._fail_request(r, f"migration failed: {exc}{note}")
            return False

    # statics: thread(engine-loop)
    def _disagg_handoff(self) -> None:
        """Prefill-role step hook (disagg_role='prefill'): every stream
        whose first token has been sampled checkpoints with
        trigger='disagg' so the pool resumes its decode on a decode/mixed
        replica — TTFT is stamped on this replica, the decode tail
        belongs to the adopter. A stream that finished during the
        checkpoint drain (EOS mid-batch) flushes its ordinary terminal
        instead, and a failed checkpoint degrades to the round-9 kill
        path inside _checkpoint_or_fail — never a hang."""
        live = [r for r in self._requests.values()
                if not r.is_finished() and not r.is_prefilling
                and r.sampling_step > 0]
        for r in live:
            self._checkpoint_or_fail(r, "disagg")

    # statics: thread(engine-loop)
    def drain_for_migration(self, trigger: str, count: Optional[int] = None,
                            started_only: bool = False) -> list[StepOutput]:
        """Checkpoint live requests for migration, newest-arrival first
        (the SLO-rebalance trigger moves the NEWEST streams — the oldest
        are closest to finishing and have the most KV to move), and flush
        the resulting events. `count` bounds how many migrate (None =
        drain everything live, the scale-down/retire shape);
        `started_only` restricts to decoding streams that already emitted
        (the rebalance shape — queued work is the router's problem)."""
        live = [r for r in self._requests.values() if not r.is_finished()]
        if started_only:
            live = [r for r in live if r.sampling_step > 0
                    and not r.is_prefilling]
        live.sort(key=lambda r: r.arrival_time, reverse=True)
        if count is not None:
            live = live[:count]
        for r in live:
            self._checkpoint_or_fail(r, trigger)
        return self._flush_events()

    # statics: thread(engine-loop)
    def adopt_request(self, plan) -> Request:
        """Resume a checkpointed stream on THIS engine (the drain path's
        other half). Reconstructs the request with its generated tokens
        folded into the prompt (the preemption shape) and its sampling
        carry intact, then tries to transplant the checkpointed KV blocks
        into freshly allocated pages — the suffix prefills as one chunk.
        Any transplant obstacle (no seat, no KV room, geometry mismatch,
        no pages in the plan) degrades to the head of the waiting queue
        for a full recompute: token-identical either way, because the
        sampler keys on (seed, sampling_step)."""
        req = Request(
            request_id=plan.request_id,
            prompt_ids=list(plan.token_ids),
            sampling=plan.sampling,
            arrival_time=plan.arrival_time,
        )
        req.num_orig_prompt_tokens = plan.num_orig_prompt_tokens
        req.sampling_step = plan.sampling_step
        req.depth_at_enqueue = plan.depth_at_enqueue
        req.migration_hops = plan.hops
        if plan.deadline is not None:
            req.deadline = plan.deadline
            self._deadline_ids.add(req.request_id)
        self._requests[req.request_id] = req
        if self.telemetry is not None:
            self.telemetry.request_queued(req.request_id, req.arrival_time)
        if not self._try_transplant(req, plan):
            req.num_computed_tokens = 0
            self.scheduler.requeue_front(req)
        return req

    def _try_transplant(self, req: Request, plan) -> bool:
        """Write a migration plan's KV blocks into fresh device pages and
        seat the request: directly decodable for a decode-phase plan (the
        next dispatch IS the decode step the source would have run),
        mid-chunked-prefill otherwise. False (nothing mutated beyond a
        clean release) sends the caller to the recompute path."""
        from agentic_traffic_testing_tpu.runtime.kv_offload import (
            RestoreBlock,
        )

        bs = self.cfg.block_size
        kv_tokens = min(plan.kv_tokens, len(req.prompt_ids) - 1)
        if not plan.blocks or plan.block_size != bs or kv_tokens <= 0:
            return False
        if kv_tokens != plan.kv_tokens:
            return False  # malformed plan: coverage past the history
        if len(self.scheduler.running) >= self.cfg.max_num_seqs:
            return False  # no seat; admission recomputes when one frees
        n = len(plan.blocks)
        # Allocate through the sequence API only: the native (C++)
        # allocator's `.blocks` is an FFI-marshaled COPY, so growing a
        # sequence by hand-extending that list would silently desync the
        # table from the pages. ensure_capacity covers the restored
        # blocks AND the decode tail in one all-or-nothing grab; the
        # first n block ids are then the page-write targets.
        seq = self.allocator.new_sequence()
        need = (req.num_prompt_tokens + 1
                + self.scheduler.cfg.decode_lookahead)
        if not seq.ensure_capacity(need):
            # KV pressure: recompute beats evicting live sharers.
            seq.release()
            return False
        got = list(seq.blocks[:n])
        chain = getattr(self.allocator, "chain_keys", None)
        keys = (chain(req.prompt_ids)[0] if chain is not None
                else [0] * n)
        restores = [
            RestoreBlock(block=got[i],
                         key=keys[i] if i < len(keys) else 0,
                         tokens=b.tokens, k=b.k, v=b.v,
                         k_scale=b.k_scale, v_scale=b.v_scale)
            for i, b in enumerate(plan.blocks)
        ]
        try:
            self._write_restore_blocks(restores)
        except Exception as exc:
            log.warning("migration transplant failed for %s; recomputing: "
                        "%s", req.request_id, exc)
            seq.release()
            return False
        register = getattr(self.allocator, "register_restored", None)
        if register is not None and chain is not None:
            # Prefix-caching pools index the transplanted blocks: the
            # migrated stream's history becomes shareable device KV,
            # exactly like a host-tier restore. FULL blocks only — a
            # decode-phase plan's partial tail block covers fewer tokens
            # than its key's content hash claims.
            register([rb for i, rb in enumerate(restores)
                      if (i + 1) * bs <= kv_tokens and i < len(keys)])
        req.blocks = seq
        # A decode-phase plan resumes decodable: every prompt position's
        # KV is present except the last sampled token's, which the next
        # decode dispatch writes (exactly as the source's would have).
        req.num_computed_tokens = (req.num_prompt_tokens if plan.decodable
                                   else n * bs)
        self.scheduler.adopt_running(req)
        if self.telemetry is not None:
            now = time.monotonic()
            nbytes = sum(int(rb.k.nbytes) + int(rb.v.nbytes)
                         for rb in restores)
            self.telemetry.record_instant(EVENT_HOST_RESTORE, now, n)
            self.telemetry.request_event(req.request_id, REQ_RESTORE, now,
                                         nbytes)
        return True

    # statics: hot-region(chunk-dispatch)
    def _run_chunk(self, plan: ChunkPrefill) -> None:
        """One chunk of a chunked prefill (single long prompt, solo)."""
        r = plan.request
        if not self._apply_pending_restore(r):
            # Restore degraded to recompute: the request went back to the
            # head of the queue; this step idles and the next plan()
            # re-admits it against whatever the host tier still holds.
            self._invalidate_decode_state()
            return
        if self._faults is not None:
            self._faults.maybe_raise("dispatch_error")
        c = plan.padded_len
        tokens = np.zeros((1, c), np.int32)
        chunk = r.prompt_ids[plan.chunk_start : plan.chunk_start + plan.chunk_len]
        tokens[0, : len(chunk)] = chunk
        tables = np.full((1, self.table_width), TRASH_BLOCK, np.int32)
        self._fill_tables([r], tables)
        from agentic_traffic_testing_tpu.runtime.scheduler import bucket_up

        need_cols = -(-(plan.chunk_start + c) // self.cfg.block_size)
        tables = tables[:, : bucket_up(need_cols, self._chunk_width_buckets)]
        samp = self._sampling_arrays([r], 1)
        rec = self.telemetry
        t0 = time.monotonic() if rec is not None else 0.0
        span = rec.annotation(PHASE_CHUNK) if rec is not None else NULL_ANNOTATION
        with span:
            self.cache, out = self.runner.prefill_chunk(
                jnp.asarray(tokens), self.cache, jnp.asarray(tables),
                jnp.int32(plan.chunk_start), jnp.int32(plan.chunk_len),
                samp, jnp.asarray([r.sampling_step], jnp.int32),
            )
        if rec is not None:
            rec.record_dispatch(PHASE_CHUNK, t0, time.monotonic(), 1,
                                plan.chunk_len)
            rec.request_event(r.request_id, REQ_PREFILL_CHUNK, t0,
                              plan.chunk_len)
        self._apply_chunk_result(plan, out)
        # Intermediate chunk samples stay on device and are simply dropped.
        self._invalidate_decode_state()

    # statics: hot-region(chunk-dispatch)
    def _apply_chunk_result(self, plan: ChunkPrefill, out) -> None:
        """Chunk bookkeeping shared by the serial and hybrid paths —
        progress accounting plus, on the FINAL chunk, prefix registration
        and the synchronous first-token readback (this sample IS the
        request's first token, so TTFT stamps here). One site keeps the
        two schedulers' first-token behavior in lockstep."""
        r = plan.request
        r.num_computed_tokens += plan.chunk_len
        if plan.is_final:
            self._register_prefix(r)
            toks = jax.device_get(out)  # statics: allow-host-sync(final-chunk sample IS the first token; TTFT stamps on its arrival)
            now = time.monotonic()
            if r.first_token_time is None:
                r.first_token_time = now
            if self.telemetry is not None:
                self.telemetry.request_tokens(r.request_id, now, 1)
            self._append_token(r, int(toks[0]))

    # -- hybrid (fused chunk + decode) -------------------------------------

    # statics: hot-region(hybrid-dispatch)
    def _run_hybrid(self, plan: HybridBatch) -> None:
        """ONE fused ragged dispatch: every decode lane advances a token
        while one prefill chunk computes in the same device program
        (runner.hybrid -> models/llama.hybrid_step_impl). The decode
        tokens join the async harvest pipeline exactly like a prefill
        handoff entry; the chunk bookkeeping matches _run_chunk."""
        dec, ck = plan.decode, plan.chunk
        reqs = dec.requests
        b = dec.padded_batch
        r = ck.request
        if not self._apply_pending_restore(r):
            # Restore fallback re-queued the chunk request; the decode
            # lanes lose one idle step and re-plan next step.
            self._invalidate_decode_state()
            return
        if self._faults is not None:
            self._faults.maybe_raise("dispatch_error")
        c = ck.padded_len
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        steps = np.zeros((b + 1,), np.int32)
        tables = np.full((b + 1, self.table_width), TRASH_BLOCK, np.int32)
        for i, q in enumerate(reqs):
            tokens[i] = q.output_ids[-1] if q.output_ids else q.prompt_ids[-1]
            positions[i] = q.total_len - 1
            steps[i] = q.sampling_step
        steps[b] = r.sampling_step
        self._fill_tables(reqs, tables)
        self._fill_tables([r], tables[b:b + 1])  # chunk row rides lane B
        chunk_tok = np.zeros((1, c), np.int32)
        seg = r.prompt_ids[ck.chunk_start : ck.chunk_start + ck.chunk_len]
        chunk_tok[0, : len(seg)] = seg
        samp = self._sampling_arrays(
            list(reqs) + [None] * (b - len(reqs)) + [r], b + 1)
        rec = self.telemetry
        t0 = time.monotonic() if rec is not None else 0.0
        span = rec.annotation(PHASE_HYBRID) if rec is not None else NULL_ANNOTATION
        with span:
            _, self.cache, dec_out, chunk_out = self.runner.hybrid(
                jnp.asarray(tokens), jnp.asarray(chunk_tok), self.cache,
                jnp.asarray(tables), jnp.asarray(positions),
                jnp.int32(ck.chunk_start), jnp.int32(ck.chunk_len),
                samp, jnp.asarray(steps),
            )
        if rec is not None:
            rec.record_dispatch(PHASE_HYBRID, t0, time.monotonic(),
                                len(reqs), len(reqs) + ck.chunk_len)
            rec.request_event(r.request_id, REQ_PREFILL_CHUNK, t0,
                              ck.chunk_len)
        self._apply_chunk_result(ck, chunk_out)
        # Decode lanes' tokens land via the normal async harvest; the
        # composition changes next step anyway (the chunk continues, or
        # its request joins decode), so no continuation state is kept.
        first = dec_out[:, None]  # [B] -> [B, 1], harvest expects [B, K]
        try:
            first.copy_to_host_async()
        except Exception:
            pass
        self._inflight.append(_Inflight(first, list(reqs)))
        self._invalidate_decode_state()

    def warmup_hybrid_buckets(self, max_chunk: Optional[int] = None) -> int:
        """Precompile the fused hybrid program for every (decode-batch
        bucket, chunk rung) combination the hybrid planner can emit under
        `hybrid_token_budget` — each cold (batch, chunk) shape is a fresh
        XLA compile that would otherwise land mid-traffic, the same
        failure mode warmup_decode_buckets exists for. Dummy lanes and
        dummy chunk pages all point at the trash block. `max_chunk` bounds
        the warmed rungs for deployments whose prompts can't reach the
        bigger ones. Returns the number of programs compiled."""
        from agentic_traffic_testing_tpu.runtime.scheduler import pow2_buckets

        budget = self.cfg.hybrid_token_budget
        if not budget:
            return 0
        ladder = [ck for ck in self.scheduler.cfg.chunk_ladder()
                  if max_chunk is None or ck <= max_chunk]
        n = 0
        for b in pow2_buckets(1, self.cfg.max_num_seqs):
            for ck in ladder:
                if b + ck > budget:
                    continue  # the planner's room check — unreachable shape
                tokens = jnp.zeros((b,), jnp.int32)
                chunk = jnp.zeros((1, ck), jnp.int32)
                tables = jnp.full((b + 1, self.table_width), TRASH_BLOCK,
                                  jnp.int32)
                positions = jnp.zeros((b,), jnp.int32)
                steps = jnp.zeros((b + 1,), jnp.int32)
                samp = self._sampling_arrays([], b + 1)
                _, self.cache, _, out = self.runner.hybrid(
                    tokens, chunk, self.cache, tables, positions,
                    jnp.int32(0), jnp.int32(1), samp, steps)
                jax.block_until_ready(out)
                n += 1
        return n

    # -- decode ------------------------------------------------------------

    # statics: hot-region(decode-loop)
    def _setup_decode(self, plan: DecodeBatch) -> None:
        reqs = plan.requests
        b = plan.padded_batch
        tokens = np.zeros((b,), np.int32)
        positions = np.zeros((b,), np.int32)
        steps = np.zeros((b,), np.int32)
        tables = np.full((b, self.table_width), TRASH_BLOCK, np.int32)
        for i, r in enumerate(reqs):
            last = r.output_ids[-1] if r.output_ids else r.prompt_ids[-1]
            tokens[i] = last
            positions[i] = r.total_len - 1
            steps[i] = r.sampling_step
        self._fill_tables(reqs, tables)
        self._decode_requests = list(reqs)
        # ONE state shape for plain and speculative decode (round 14): the
        # n-gram history lives host-side (the requests' own token lists),
        # so speculation adds no device-resident state to arm here —
        # drafts ride each dispatch as a small [B, K, γ] operand instead.
        self._decode_state = DecodeState(
            tokens=jnp.asarray(tokens),
            positions=jnp.asarray(positions),
            steps=jnp.asarray(steps),
        )
        self._decode_tables = jnp.asarray(tables)
        self._decode_samp = self._sampling_arrays(reqs, b)
        self._decode_block_counts = [r.blocks.num_blocks for r in reqs]
        self._decode_epoch = self.scheduler.composition_epoch

    # statics: hot-region(decode-loop)
    def _refresh_decode_tables(self) -> None:
        """Re-upload block tables if any sequence grew into new blocks.

        The DecodeState (tokens/positions) stays device-resident; only the
        [B, W] table array is re-built. Without this, a sequence crossing a
        block boundary mid-decode would silently write its KV into the trash
        block (stale table row) and corrupt its own continuation.
        """
        counts = [r.blocks.num_blocks for r in self._decode_requests]
        if counts == self._decode_block_counts:
            return
        b = self._decode_tables.shape[0]
        tables = np.full((b, self.table_width), TRASH_BLOCK, np.int32)
        self._fill_tables(self._decode_requests, tables)
        self._decode_tables = jnp.asarray(tables)
        self._decode_block_counts = counts

    # statics: hot-region(decode-loop)
    def _refresh_decode_tables_incremental(self) -> None:
        """Overlap fast-path table maintenance: the [B, W] table stays
        device-resident and only the cells where a lane grew into new
        blocks are scattered in (ops/pallas/kv_write.update_table_cells) —
        an O(changed) upload instead of the serial path's full host
        rebuild + [B, W] transfer per block-boundary crossing (at bs32 /
        K=32 every lane crosses every dispatch, so that rebuild was pure
        per-step host work scaling with B)."""
        counts = [r.blocks.num_blocks for r in self._decode_requests]
        if counts == self._decode_block_counts:
            return
        rows: list[int] = []
        cols: list[int] = []
        vals: list[int] = []
        for i, (r, old, new) in enumerate(zip(
                self._decode_requests, self._decode_block_counts, counts)):
            if new < old:
                # A shrink cannot happen on a stable composition; if it
                # somehow does, the full rebuild is always correct.
                self._refresh_decode_tables()
                return
            if new == old:
                continue
            # One property read per grown lane: with the native allocator
            # .blocks marshals the whole block list across FFI, so reading
            # it per CELL would re-pay O(num_blocks) per new block.
            blk = r.blocks.blocks
            for j in range(old, min(new, self.table_width)):
                rows.append(i)
                cols.append(j)
                vals.append(blk[j])
        self._decode_block_counts = counts
        if not rows:
            return  # growth past the table width only (table_row clamps too)
        from agentic_traffic_testing_tpu.ops.pallas.kv_write import (
            update_table_cells,
        )

        # Pad to a pow2 length by repeating the first triple (idempotent
        # per cell): one compiled scatter per bucket, not per update count.
        n = 1 << (len(rows) - 1).bit_length()
        pad = n - len(rows)
        if pad:
            rows += rows[:1] * pad
            cols += cols[:1] * pad
            vals += vals[:1] * pad
        self._decode_tables = update_table_cells(
            self._decode_tables,
            jnp.asarray(rows, jnp.int32), jnp.asarray(cols, jnp.int32),
            jnp.asarray(vals, jnp.int32))

    def _decode_budget_satisfied(self) -> bool:
        """True when no running decode lane still needs tokens beyond what
        the in-flight dispatches will already deliver.

        Each in-flight dispatch is guaranteed to emit at least `decode_steps`
        tokens per live lane (speculative iterations emit >= 1 each), so a
        lane with `sampling_step + K * inflight` past its max_tokens (or its
        context past max_model_len) gains nothing from another dispatch.
        EOS stops are not predictable host-side and are handled as today:
        harvest notices, and the post-stop tail is dropped."""
        if not self._decode_requests:
            return False
        for r in self._decode_requests:
            if r.is_finished():
                continue
            # tokens.shape[1] = steps per lane in that dispatch: 1 for the
            # prefill handoff entry, decode_steps for decode (speculative
            # [B, K, S] entries emit >= K, so K is the guaranteed floor).
            inflight_toks = sum(
                int(inf.tokens.shape[1]) for inf in self._inflight
                if r in inf.requests)  # identity: Request is eq=False
            needed = min(
                r.sampling.max_tokens - r.sampling_step,
                self.cfg.max_model_len - r.total_len,
            )
            if inflight_toks < needed:
                return False
        return True

    # statics: hot-region(decode-loop)
    def _dispatch_decode(self) -> None:
        if self._decode_state is None:
            return
        if (self.cfg.decode_overlap
                and self.scheduler.composition_stable(self._decode_epoch)):
            # Overlap fast path: the composition epoch is unchanged since
            # this batch was armed, so plan() would hand back the same
            # DecodeBatch — dispatch fused-step N+1 against that predicted
            # composition NOW (while step N executes), paying only the
            # O(B) capacity grow and the incremental table scatter instead
            # of the full sorted plan + host table rebuild. Reconciliation
            # happens at harvest: a stop/admission surfacing there
            # invalidates the pipeline, discards the speculative tail, and
            # the next step re-plans the corrected batch — token streams
            # stay identical to the serial loop.
            if self.scheduler.extend_decode(self._decode_requests):
                batch = self._decode_requests
                try:
                    self._refresh_decode_tables_incremental()
                    self._do_decode_dispatch(predicted=True)
                except Exception as exc:
                    self._fail_dispatch(list(batch), exc)
                return
            # KV pool exhausted mid-wave: fall through to the full plan,
            # which re-grows survivors and preempts exactly as the serial
            # schedule would.
        # KV headroom for this step (may preempt; then state must be rebuilt).
        plan = self.scheduler.plan()
        if isinstance(plan, DecodeBatch) and plan.requests == self._decode_requests:
            try:
                self._refresh_decode_tables()
                # Same composition confirmed by a full plan: re-arm the
                # overlap hint (an unadmittable arrival bumps the epoch
                # without changing the decode batch — without this
                # re-snapshot one such arrival would force the slow path
                # for the rest of the wave).
                self._decode_epoch = self.scheduler.composition_epoch
                self._do_decode_dispatch()
            except Exception as exc:
                self._fail_dispatch(list(plan.requests), exc)
            return
        # Composition changed (preemption / drain-out): sync fully first.
        self._drain_all()
        if isinstance(plan, PrefillBatch):
            # Not stale: plan() just admitted these requests and they hold
            # their blocks regardless of what harvesting finished.
            self._fail_unservable()
            try:
                self._run_prefill(plan)
            except Exception as exc:
                self._fail_dispatch(list(plan.requests), exc)
            return
        # A decode plan IS stale after draining — harvest may have finished
        # members and released their blocks — so re-plan from current state.
        self._plan_and_dispatch()

    def _spec_stream_len(self) -> int:
        """Static per-engine length of the host-proposed continuation
        stream: every round of every dispatch that can be in flight must
        find runway — (pipeline_depth unharvested + 1 dispatching)
        dispatches × decode_steps rounds × up to γ+1 emitted each, plus
        the anchor slot (stream[0] = the last host-known token)."""
        s = self.runner.spec_tokens + 1
        return (self.cfg.pipeline_depth + 1) * self.runner.decode_steps * s + 1

    # statics: hot-region(decode-loop)
    def _propose_drafts(self) -> jax.Array:
        """Host-side prompt-lookup proposal for one speculative dispatch:
        a [B, E] predicted-continuation stream from the requests' own
        token histories (plain numpy — no device work, no sync). Each
        verify round aligns into the stream by VALUE on device, so under
        the overlapped loop / pipelining a stream proposed from history
        that lags by the in-flight tokens still anchors at wherever the
        device actually is; a stale or wrong stream is just a weaker
        guess (acceptance is sample-and-compare), never a correctness
        hazard."""
        from agentic_traffic_testing_tpu.ops.speculative import (
            history_tail,
            propose_stream,
        )

        # The runner's spec_ngram wins when set (it sits next to
        # spec_tokens, the runner-owned half of the speculation config;
        # every construction site passes cfg.spec_ngram into it, so the
        # two agree unless a caller deliberately overrode the runner's).
        ngram = getattr(self.runner, "spec_ngram", 0) or self.cfg.spec_ngram
        window = self.cfg.spec_lookup_window
        mat = propose_stream(
            [history_tail(r.prompt_ids, r.output_ids, ngram, window)
             for r in self._decode_requests],
            int(self._decode_tables.shape[0]), self._spec_stream_len(),
            ngram, window)
        return jnp.asarray(mat)

    # statics: hot-region(decode-loop)
    def _do_decode_dispatch(self, predicted: bool = False) -> None:
        if self._faults is not None:  # before the donated-state call below
            self._faults.maybe_raise("dispatch_error")
        # Under decode_overlap every decode dispatch runs the donated-state
        # jit (the speculative verify included — its carry is a plain
        # DecodeState since round 14), so ONE program serves both the
        # armed first dispatch and the fast-path ones — no duplicate
        # compiles per bucket. The old state leaves are consumed by the
        # donation; nothing else references them (the handoff's readback
        # entry is a separate [B, 1] buffer).
        decode = (self.runner.decode_overlapped if self.cfg.decode_overlap
                  else self.runner.decode)
        spec = getattr(self.runner, "spec_tokens", 0)
        rec = self.telemetry
        t0 = time.monotonic() if rec is not None else 0.0
        kind = (PHASE_SPECULATIVE_DECODE if spec > 0
                else PHASE_OVERLAPPED_DECODE if predicted else PHASE_DECODE)
        span = rec.annotation(kind) if rec is not None else NULL_ANNOTATION
        with span:
            if spec > 0:
                result = decode(
                    self.cache, self._decode_tables, self._decode_state,
                    self._decode_samp, drafts=self._propose_drafts()
                )
            else:
                result = decode(
                    self.cache, self._decode_tables, self._decode_state,
                    self._decode_samp
                )
        if rec is not None:
            b = len(self._decode_requests)
            # Token count = positions the dispatch PROCESSES: K per lane
            # for plain decode, K*(γ+1) verified positions for the
            # speculative phase (emission is variable per round and only
            # known at harvest — the acceptance gauges own that split).
            rec.record_dispatch(kind, t0, time.monotonic(), b,
                                b * self.runner.decode_steps * (1 + spec),
                                predicted=predicted)
        counts = None
        if spec > 0:
            self._decode_state, self.cache, out, counts = result
        else:
            self._decode_state, self.cache, out = result
        for arr in (out,) if counts is None else (out, counts):
            try:
                arr.copy_to_host_async()
            except Exception:
                pass
        if predicted:
            self.num_overlap_dispatches += 1
            self._overlap_unharvested += 1
        self._inflight.append(
            _Inflight(out, list(self._decode_requests), counts,
                      predicted=predicted))

    def _sampling_arrays(self, reqs: list[Request], padded: int) -> SamplingArrays:
        # Memoized on the full per-lane param composition: identical
        # compositions (every wave of the bench workload, steady agentic
        # fan-out) reuse the device-resident arrays — SamplingArrays are
        # only ever read by dispatches (never donated), so sharing is safe.
        key = (padded, tuple(
            None if r is None else (r.sampling.temperature, r.sampling.top_k,
                                    r.sampling.top_p, r.sampling.seed)
            for r in reqs))
        cached = self._samp_cache.get(key)
        if cached is not None:
            self._samp_cache.move_to_end(key)  # LRU bump
            return cached
        # None entries are padding gaps (the hybrid step places the chunk's
        # request at lane `padded_batch`, past the real decode lanes).
        temp = np.zeros((padded,), np.float32)
        top_k = np.zeros((padded,), np.int32)
        top_p = np.ones((padded,), np.float32)
        seeds = np.zeros((padded,), np.int32)
        for i, r in enumerate(reqs):
            if r is None:
                continue
            temp[i] = r.sampling.temperature
            top_k[i] = r.sampling.top_k
            top_p[i] = r.sampling.top_p
            seeds[i] = r.sampling.seed
        arrays = SamplingArrays(
            temperature=jnp.asarray(temp), top_k=jnp.asarray(top_k),
            top_p=jnp.asarray(top_p), seeds=jnp.asarray(seeds),
        )
        if len(self._samp_cache) >= 256:
            # Bound the memo under churn by evicting LRU — a wholesale
            # clear() here used to make a churning composition mix
            # periodically re-pay every rebuild it had memoized.
            self._samp_cache.popitem(last=False)
        self._samp_cache[key] = arrays
        return arrays

    # -- harvest / stop conditions ----------------------------------------

    def _harvest(self, max_inflight: int) -> None:
        batch: list[_Inflight] = []
        while len(self._inflight) > max_inflight or (
            self._inflight and self._any_request_gone(self._inflight[0])
        ):
            batch.append(self._inflight.popleft())
        # Note: retiring these may finish requests that also appear in the
        # remaining entries; those are picked up next step() — the pipeline
        # already tolerates that one-dispatch lag.
        self._retire(batch)

    def _drain_all(self) -> None:
        batch = list(self._inflight)
        self._inflight.clear()
        self._retire(batch)

    # statics: hot-region(harvest)
    def _retire(self, infs: list[_Inflight]) -> None:
        """Fetch + apply in-flight entries with ONE batched host transfer:
        each separate device_get is a full host<->device round trip (tens of
        ms through the axon tunnel), so retiring a wave entry-by-entry would
        turn the pipeline tail into N round trips."""
        if not infs:
            return
        rec = self.telemetry
        t0 = time.monotonic() if rec is not None else 0.0
        drained_tokens = 0
        leaves: list = []
        for inf in infs:
            leaves.append(inf.tokens)
            if inf.counts is not None:
                leaves.append(inf.counts)
        fetched = iter(jax.device_get(leaves))  # statics: allow-host-sync(THE harvest readback: one batched transfer retires the whole in-flight wave)
        for inf in infs:
            toks = next(fetched)  # device_get already returned numpy
            counts = next(fetched) if inf.counts is not None else None
            if rec is not None:
                drained_tokens += int(toks.size)
            if inf.predicted:
                # Decrement BEFORE applying: if this entry's tokens finish
                # a lane, the mispredict check must see only the
                # speculative dispatches issued AFTER this one.
                self._overlap_unharvested -= 1
            self._apply_inflight_host(inf.requests, toks, counts)
        if rec is not None:
            rec.record_drain(t0, time.monotonic(), len(infs), drained_tokens)

    def _any_request_gone(self, inf: _Inflight) -> bool:
        return any(r.is_finished() for r in inf.requests)

    def _apply_inflight_host(self, requests: list[Request], toks: np.ndarray,
                             counts: Optional[np.ndarray]) -> None:
        # Plain decode: tokens [B, K], every entry emitted; the prefill
        # handoff entry is [B, 1]. Speculative: tokens [B, K, spec+1] with
        # counts [B, K] — only the first counts[b, k] entries of iteration k
        # were accepted on device.
        now = time.monotonic()
        rec = self.telemetry
        for i, r in enumerate(requests):
            if r.is_finished() or r.state is not RequestState.RUNNING:
                continue  # stopped at an earlier lagged step, or preempted
            if r.first_token_time is None:
                r.first_token_time = now
            n0 = r.sampling_step
            if counts is None:
                for tok in toks[i]:
                    self._append_token(r, int(tok))
                    if r.is_finished():
                        break  # device tokens past the stop point are dropped
                if rec is not None and r.sampling_step > n0:
                    rec.request_tokens(r.request_id, now,
                                       r.sampling_step - n0)
            else:
                # Acceptance gauges count only consumed iterations and kept
                # tokens — post-stop garbage rows would otherwise dominate
                # the ratio for short completions at large decode_steps.
                for k in range(toks.shape[1]):
                    if r.is_finished():
                        break
                    self.spec_iters += 1
                    # Per consumed round: γ = S-1 drafts proposed, m-1 of
                    # them accepted by verification (the m-th emitted token
                    # is the round's own correction/bonus sample).
                    self.spec_drafted += toks.shape[2] - 1
                    self.spec_accepted += int(counts[i, k]) - 1
                    for tok in toks[i, k, : counts[i, k]]:
                        self._append_token(r, int(tok))
                        self.spec_emitted += 1
                        if r.is_finished():
                            break
                if rec is not None and r.sampling_step > n0:
                    rec.request_tokens(r.request_id, now,
                                       r.sampling_step - n0)

    def _append_token(self, r: Request, tok: int) -> None:
        r.output_ids.append(tok)
        r.sampling_step += 1
        self._new_tokens.setdefault(r.request_id, []).append(tok)
        eos_hit = (not r.sampling.ignore_eos) and (
            tok in r.sampling.stop_token_ids
        )
        if eos_hit:
            self._finish(r, FinishReason.STOP)
        elif r.sampling_step >= r.sampling.max_tokens:
            # sampling_step counts ALL generated tokens (it survives
            # preemption, unlike len(output_ids)).
            self._finish(r, FinishReason.LENGTH)
        elif r.total_len >= self.cfg.max_model_len:
            self._finish(r, FinishReason.LENGTH)

    def _finish(self, r: Request, reason: FinishReason) -> None:
        r.state = RequestState.FINISHED
        r.finish_reason = reason
        r.finish_time = time.monotonic()
        self.scheduler.finish(r)  # no-op if the lane was released early
        # Only tear down the decode pipeline if r is part of the CURRENT
        # composition — harvesting a previous (early-released) wave's finish
        # must not stall the wave already decoding.
        if r in self._decode_requests:  # identity: Request is eq=False
            if self._overlap_unharvested > 0:
                if self.telemetry is not None:
                    self.telemetry.record_instant(EVENT_MISPREDICT,
                                                  time.monotonic())
                # Overlap mispredict: a stop landed while fast-path
                # dispatches issued AFTER it were still in flight — their
                # post-stop tails for this lane are discarded at harvest
                # and the next step re-plans the corrected batch
                # (llm_decode_overlap_mispredicts_total). The wave-release
                # and budget-satisfied teardowns never reach here with
                # outstanding predicted work that isn't still needed, so
                # this counts only genuinely wasted speculation.
                self.num_overlap_mispredicts += 1
            self._invalidate_decode_state()

    def _invalidate_decode_state(self) -> None:
        self._decode_state = None
        self._decode_requests = []
        self._decode_tables = None
        self._decode_samp = None

    def _flush_events(self) -> list[StepOutput]:
        if self._save_pending:
            # Every step exit passes through here, so spilled blocks become
            # host-probeable by the NEXT plan() — their async copies have
            # been in flight since evict time.
            self._flush_saves()
        events = []
        rec = self.telemetry
        for rid, toks in self._new_tokens.items():
            req = self._requests[rid]
            events.append(StepOutput(request=req, new_token_ids=toks,
                                     finished=req.is_finished()))
            if req.is_finished():
                if self._deadline_ids:
                    self._deadline_ids.discard(rid)
                if rec is not None:
                    # Retired HERE (not in _finish) so the burst that
                    # carried the final token is already on the timeline
                    # when the SLO attainment math runs.
                    rec.request_retired(
                        rid, req.finish_time or time.monotonic(),
                        reason=(req.finish_reason.value
                                if req.finish_reason else None),
                        slo_ttft_ms=req.sampling.slo_ttft_ms,
                        slo_itl_ms=req.sampling.slo_itl_ms)
                del self._requests[rid]
        self._new_tokens.clear()
        return events

    # -- offline convenience ----------------------------------------------

    # statics: thread(engine-loop)
    def generate(
        self,
        prompt_ids: list[int],
        sampling: Optional[SamplingParams] = None,
    ) -> Request:
        """Blocking single-request generation (tests/CLI)."""
        req = self.add_request(prompt_ids, sampling)
        while not req.is_finished():
            events = self.step()
            if not events and not self.has_work():
                break
        return req

    # statics: thread(scrape)
    def kv_stats(self) -> dict:
        stats = self.scheduler.kv_stats()
        if self._host_store is not None:
            stats["host_cache_restore_bytes"] = self.host_restore_bytes
            stats["host_cache_save_queue_depth"] = len(self._save_pending)
            stats.update(self._host_store.stats())
        return stats

    # -- router-facing snapshots (read from OTHER threads) -----------------

    # statics: thread(handler)
    def load_snapshot(self) -> dict:
        """Lock-free load view for the replica router (serving/router.py).

        Called from the HTTP thread while the step thread mutates the
        engine: every field is ONE len()/attribute read of a host Python
        object — atomic under the GIL, never blocking the step loop.
        Fields from different instants may be mutually inconsistent (a
        request can move waiting -> running between two reads); routing
        needs a load estimate, not a transaction, so that is fine."""
        return {
            "num_waiting": len(self.scheduler.waiting),
            "num_running": len(self.scheduler.running),
            "inflight_dispatches": len(self._inflight),
            "free_blocks": self.allocator.num_free_blocks,
            "max_num_seqs": self.cfg.max_num_seqs,
            "block_size": self.cfg.block_size,
        }

    # statics: thread(handler)
    def chain_keys_for(self, prompt_ids: list[int]):
        """Content-addressing chain keys for a prompt, or None without a
        prefix-caching allocator. Computed once by the router and shared
        across every replica's probe (replicas share block_size)."""
        chain = getattr(self.allocator, "chain_keys", None)
        if chain is None:
            return None
        return chain(list(prompt_ids))

    # statics: thread(handler)
    def probe_prefix_tokens(self, prompt_ids: list[int], keys=None) -> int:
        """Read-only prefix-cache probe: cached tokens a prompt would reuse
        on THIS replica right now; 0 without prefix caching.

        Safe against the step thread without a lock: probe_prefix walks the
        index with dict.get (one C call per block) and mutates nothing, so
        the worst concurrent outcome is a slightly stale hit count — a
        routing inaccuracy, never corruption."""
        probe = getattr(self.allocator, "probe_prefix", None)
        if probe is None:
            return 0
        return probe(list(prompt_ids), keys)
