"""Iteration-level continuous-batching scheduler.

TPU-native rethink of the scheduling capability the reference delegates to
vLLM's engine (`AsyncEngineArgs(max_num_seqs=…, max_num_batched_tokens=…)` —
reference: llm/serve_llm.py:362-378; compose defaults 12/8192 —
infra/docker-compose.distributed.yml:40-41). Differences driven by XLA:

  * Every step must have a *statically bucketed* shape — batch sizes and
    padded prefill lengths are rounded up to a small fixed ladder so the jit
    cache stays bounded (SURVEY.md §7 "keeping jit recompilation bounded").
  * The schedule itself is computed host-side in plain Python (cheap), only
    the chosen step runs on device.

Policy: prefill-priority admission (matches vLLM's default and preserves the
TTFT semantics the testbed measures), LIFO preemption of the youngest running
sequence when KV blocks run out, all-or-nothing block allocation. With
`hybrid_token_budget` > 0 a pending prefill chunk and the decode batch fuse
into one HybridBatch (Sarathi-style chunked piggyback over the ragged
Pallas kernel) instead of serializing; 0 keeps the serial schedule
bit-identical.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass, field
from typing import Optional, Union

from agentic_traffic_testing_tpu.runtime.block_allocator import (
    BlockAllocator,
    request_chain_keys,
)
from agentic_traffic_testing_tpu.runtime.request import Request, RequestState


class QueueFullError(RuntimeError):
    """add_request refused: the bounded wait queue (`max_queue`) is at
    capacity. The serving layer maps this to 503 + Retry-After (load
    shedding beats admitting work that will sit past its SLO); the
    preemption path never raises it — admitted work is never dropped."""


def pow2_buckets(lo: int, hi: int) -> list[int]:
    out, v = [], lo
    while v < hi:
        out.append(v)
        v *= 2
    out.append(hi)
    return out


def bucket_up(n: int, buckets: list[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


@dataclass
class PrefillBatch:
    """One prefill step: same padded length for all members."""

    requests: list[Request]
    padded_len: int
    padded_batch: int

    @property
    def token_budget(self) -> int:
        return self.padded_len * len(self.requests)


@dataclass
class DecodeBatch:
    """One decode step over every running sequence."""

    requests: list[Request]
    padded_batch: int


@dataclass
class ChunkPrefill:
    """One chunk of one long prompt (chunked prefill; request runs alone)."""

    request: Request
    chunk_start: int   # absolute position of the chunk's first token
    chunk_len: int     # real tokens in this chunk (<= padded_len)
    padded_len: int    # compiled chunk bucket (block-aligned)

    @property
    def is_final(self) -> bool:
        return self.chunk_start + self.chunk_len >= self.request.num_prompt_tokens


@dataclass
class HybridBatch:
    """One FUSED step: the decode batch plus one prefill chunk riding along
    in a single ragged dispatch (Sarathi-style chunked-prefill piggyback:
    decode rows soak the idle FLOPs of the chunk instead of waiting behind
    it). Emitted only when `hybrid_token_budget` > 0; the fused token count
    (decode padded lanes + chunk padded length) stays under that budget."""

    decode: DecodeBatch
    chunk: ChunkPrefill

    @property
    def token_budget(self) -> int:
        return self.decode.padded_batch + self.chunk.padded_len


StepPlan = Union[PrefillBatch, DecodeBatch, ChunkPrefill, HybridBatch, None]


@dataclass
class MigrationBlock:
    """One KV block of a checkpointed stream: raw host pages (the pool's
    dtype — int8 pools carry the fp32 scale pair raw, exactly like
    kv_offload.HostBlock, so migration never round-trips through bf16 and
    int8 halves the migration bytes) plus the covered token ids. The LAST
    block of a decode-phase checkpoint may be partial (its trailing slots
    hold stale bytes nothing ever reads — attention masks by position);
    partial blocks are never prefix-indexed on adopt."""

    tokens: tuple           # token ids covered by this block's valid slots
    k: "object"             # np.ndarray [L, KH, block_size, hd_phys]
    v: "object"
    k_scale: Optional["object"] = None   # [L, KH] f32 (int8 pools only)
    v_scale: Optional["object"] = None


@dataclass
class MigrationPlan:
    """A checkpointed in-flight stream, ready to resume on another replica.

    Built by `engine.checkpoint_request` (token history + sampling carry +
    KV pages), consumed by `engine.adopt_request`. Token identity is the
    contract: `token_ids` folds generated tokens into the prompt exactly
    like preemption does, and `sampling_step` carries the per-request RNG
    position ((seed, sampling_step) keys the sampler). A decode-phase plan
    (`decodable`) carries KV for every position but the last sampled
    token's, so the target's FIRST dispatch is the exact decode step the
    source would have run next — byte-for-byte identical tokens, pinned by
    tests/test_migration.py. A mid-prefill plan carries the computed full
    blocks and the target resumes the remaining chunks on the same ladder
    rungs. With the pages dropped (capacity pressure on the target,
    geometry mismatch), the whole history recomputes from the folded
    prompt — the deterministic preemption path the scheduler has always
    trusted, though recomputed KV is not bitwise-pinned against the
    uninterrupted stream's."""

    request_id: str
    token_ids: list          # original prompt + every generated token so far
    sampling: "object"       # SamplingParams (carries seed/top_k/... + SLO class)
    sampling_step: int       # RNG carry: tokens sampled so far
    num_orig_prompt_tokens: int   # user-visible prompt boundary
    arrival_time: float      # preserved: deadlines/TTFT stay the request's own
    num_computed_tokens: int      # prefill progress at checkpoint (chunked)
    blocks: list = field(default_factory=list)   # list[MigrationBlock]
    kv_tokens: int = 0       # positions the blocks' valid slots cover
    # True = checkpointed mid-decode: kv_tokens == len(token_ids) - 1 and
    # the adopter seats the request directly decodable (the next dispatch
    # is the decode step the source would have run). False = mid-chunked-
    # prefill: full blocks only, the chunk path resumes.
    decodable: bool = False
    block_size: int = 0      # geometry attestation for the adopter
    deadline: Optional[float] = None  # absolute monotonic abort instant
    # Preserved so the server's per-slot queue-wait EWMA keeps dividing
    # the measured wait by the depth the request ACTUALLY waited behind
    # (the PR-8 spurious-429 fix) — a migrated terminal must not report
    # depth 0.
    depth_at_enqueue: int = 0
    trigger: str = "drain"   # quarantine | rebalance | scale_down | drain | disagg
    source_replica: int = -1
    created_t: float = 0.0   # checkpoint instant (migration-duration metric)
    # Total checkpoints this stream has been through (survives
    # re-checkpoints of an adopted stream): the pool's ping-pong bound
    # (replica_pool.MAX_STREAM_MIGRATIONS) reads it.
    hops: int = 1


@dataclass
class SchedulerConfig:
    max_num_seqs: int = 12           # compose default (reference: docker-compose.distributed.yml:40)
    max_num_batched_tokens: int = 8192
    max_model_len: int = 4096
    block_size: int = 16
    # Extra tokens of KV headroom per running seq so the engine can pipeline
    # a couple of speculative steps past a stop condition (see engine.py).
    decode_lookahead: int = 4
    min_prefill_bucket: int = 32
    # Prompts longer than this prefill in fixed chunks of this many tokens
    # (one compiled bucket instead of one per long-prompt length; bounded
    # per-step latency). None disables chunking.
    prefill_chunk_tokens: Optional[int] = 2048
    # Hybrid prefill+decode batching: when > 0, a pending prefill chunk and
    # the decode batch fuse into ONE ragged dispatch (HybridBatch) whose
    # total padded token count (decode lanes + chunk bucket) stays under
    # this budget — the chunk splits onto a smaller ladder rung when it
    # must. 0 (default) disables fusion entirely: planning is bit-identical
    # to the serial prefill-priority policy.
    hybrid_token_budget: int = 0
    # Bounded wait queue (round 9 — the overload-policy half of ROADMAP
    # item 2): add_request raises QueueFullError once this many requests
    # are already waiting. 0 (default) keeps the queue unbounded, exactly
    # as before the knob existed. Preemption re-queues bypass the bound
    # (appendleft in _preempt): shedding applies to NEW work only.
    max_queue: int = 0
    # SLO-class admission (round 16 — decode-role replicas in a
    # disaggregated pool): add_request inserts by SLO class — tightest
    # slo_ttft_ms first, unclassed (None) requests last, FIFO within a
    # class — instead of plain FCFS, so an adopted tight-SLO stream never
    # queues behind a batch of best-effort work. False (default) keeps
    # admission order byte-identical to plain append.
    slo_class_admission: bool = False
    # Multi-request prefill batches only form for buckets up to this length.
    # Longer prompts prefill solo: a (batch, long-bucket) combination is a
    # fresh XLA compile (~tens of seconds) that a burst of concurrent
    # arrivals would otherwise trigger mid-traffic — measured 5 concurrent
    # ~300-token requests at 31.8 s vs 4.1 s sequential purely from one such
    # compile. Long prefills saturate the MXU solo anyway.
    prefill_batch_max_len: int = 128

    def __post_init__(self) -> None:
        if self.prefill_chunk_tokens is not None:
            c = min(self.prefill_chunk_tokens, self.max_num_batched_tokens,
                    self.max_model_len)
            self.prefill_chunk_tokens = max(self.block_size,
                                            c - c % self.block_size)
        self.prefill_buckets = [
            b for b in pow2_buckets(self.min_prefill_bucket, self.max_model_len)
        ]
        self.batch_buckets = pow2_buckets(1, self.max_num_seqs)

    def chunk_ladder(self) -> list[int]:
        """The complete set of compiled chunk lengths (block-aligned,
        capped at the chunk size). _next_chunk only ever emits these —
        splitting a chunk rather than clamping off-ladder — so a warmup
        pass over this list covers every chunk program (engine.py
        warmup_chunk_buckets)."""
        bs = self.block_size
        cap = self.prefill_chunk_tokens or self.max_model_len
        rungs = {min(-(-b // bs) * bs, cap) for b in self.prefill_buckets}
        rungs.add(bs)  # the end-of-table fallback floor
        return sorted(rungs)


class Scheduler:
    """Owns the waiting queue, the running set, and block allocation."""

    def __init__(self, cfg: SchedulerConfig, allocator: BlockAllocator) -> None:
        assert allocator.block_size == cfg.block_size
        self.cfg = cfg
        self.allocator = allocator
        self.waiting: collections.deque[Request] = collections.deque()
        self.running: list[Request] = []
        # Requests found unservable during planning (can never fit the pool);
        # the engine drains this list and fails them upward.
        self.failed: list[Request] = []
        # Cumulative counters (exported by the serving layer)
        self.num_preemptions = 0
        self.num_scheduled_prefills = 0
        self.num_scheduled_decodes = 0
        self.num_scheduled_hybrid = 0  # fused chunk+decode steps
        # Composition epoch (round 7, the overlapped-decode hint): bumped
        # whenever the waiting/running membership changes — admission,
        # finish, abort, preemption, a new arrival. The engine snapshots it
        # when it arms a decode batch; an unchanged epoch means plan()
        # would return the same DecodeBatch, so the overlap fast path can
        # dispatch against the predicted composition via extend_decode()
        # without paying the full sorted capacity pass per dispatch.
        self.composition_epoch = 0
        # Admission observer (round 8, the step-clock telemetry plane):
        # called with each request the instant it turns RUNNING — both
        # admission paths below fire it, so the per-request timeline's
        # queued→admitted boundary is exact. None (default) costs one
        # attribute test per admission and nothing else.
        self.on_admit = None

    # -- admission ---------------------------------------------------------

    def add_request(self, req: Request) -> None:
        if self.cfg.max_queue and len(self.waiting) >= self.cfg.max_queue:
            raise QueueFullError(
                f"wait queue at capacity ({self.cfg.max_queue}); retry later")
        if req.num_prompt_tokens == 0:
            raise ValueError("empty prompt: nothing to prefill")
        if req.num_prompt_tokens >= self.cfg.max_model_len:
            raise ValueError(
                f"prompt of {req.num_prompt_tokens} tokens >= max_model_len "
                f"{self.cfg.max_model_len}; the serving layer must truncate first"
            )
        need = self.allocator.blocks_needed(
            req.num_prompt_tokens + 1 + self.cfg.decode_lookahead
        )
        if need > self.allocator.num_blocks - 1:
            raise ValueError(
                f"prompt needs {need} KV blocks but the pool only has "
                f"{self.allocator.num_blocks - 1}; raise num_blocks or shrink the prompt"
            )
        req.state = RequestState.WAITING
        req.depth_at_enqueue = len(self.waiting)
        if self.cfg.slo_class_admission:
            self._insert_by_slo_class(req)
        else:
            self.waiting.append(req)
        self.composition_epoch += 1

    @staticmethod
    def _slo_class(req: Request) -> float:
        slo = getattr(req.sampling, "slo_ttft_ms", None)
        return slo if slo is not None else float("inf")

    def _insert_by_slo_class(self, req: Request) -> None:
        """Decode-role admission order: tightest TTFT-SLO class first,
        FIFO within a class (stable — scan from the tail for the last
        entry whose class is <= ours)."""
        cls = self._slo_class(req)
        for i in range(len(self.waiting), 0, -1):
            if self._slo_class(self.waiting[i - 1]) <= cls:
                self.waiting.insert(i, req)
                return
        self.waiting.appendleft(req)

    def composition_stable(self, epoch: int) -> bool:
        """True when no membership change has happened since `epoch` was
        read off `composition_epoch` — the overlapped-decode loop's
        no-churn hint (a stale epoch sends the engine back through the
        full plan()/reconcile path)."""
        return epoch == self.composition_epoch

    def extend_decode(self, requests: list[Request]) -> bool:
        """Grow per-lane KV capacity for ONE more fused decode dispatch
        over an unchanged composition, skipping plan()'s arrival sort and
        preemption pass (the per-dispatch host work that scales with B —
        the bs32 roofline_frac culprit). Capacity targets are identical
        to _plan_decode's, and growth is idempotent, so a False return
        (pool exhausted, or a lane no longer RUNNING) simply falls back
        to the full pass, which re-grows the survivors and preempts
        exactly as the serial schedule would have."""
        for r in requests:
            if (r.state is not RequestState.RUNNING or r.blocks is None
                    or r.is_prefilling):
                return False
            if not self._ensure_decode_capacity(r):
                return False
        self.num_scheduled_decodes += 1
        return True

    def can_admit_head(self) -> bool:
        """Cheap check: could plan() admit the head of the waiting queue right
        now? Lets the engine keep its decode pipeline intact instead of
        draining every step while a request waits for KV to free up."""
        if not self.waiting:
            return False
        if len(self.running) >= self.cfg.max_num_seqs:
            return False
        head = self.waiting[0]
        # Same formula as admission (prompt + first decode slot + lookahead,
        # minus any cached prefix match_prefix would supply): a mismatch here
        # makes the engine tear down its decode pipeline every step for a
        # head that _plan_prefill then refuses — or, with the cache discount
        # missing, never admit a cache-hit request whose suffix would fit.
        # (Slightly optimistic when the matched blocks are themselves in the
        # evictable pool; _plan_prefill just declines that step.) Only the
        # DEVICE hit discounts: host-tier blocks restore into freshly
        # allocated blocks, so they still count toward the need.
        device_cached, _host = self._probe_cached(head)
        need = self.allocator.blocks_needed(
            head.num_prompt_tokens + 1 + self.cfg.decode_lookahead
        ) - device_cached // self.cfg.block_size
        return self.allocator.can_allocate(max(0, need))

    def has_pending_chunk(self) -> bool:
        """A running request is mid-chunked-prefill (its next chunk should be
        planned before any decode)."""
        return any(r.is_prefilling for r in self.running)

    def _needs_chunking(self, req: Request) -> bool:
        c = self.cfg.prefill_chunk_tokens
        return c is not None and req.num_prompt_tokens > c

    def _probe_cached(self, req: Request) -> tuple[int, int]:
        """(device-cached, host-restorable) hit sizes (tokens) admission
        would get; (0, 0) without a prefix-caching allocator. Chain keys are
        memoized per request, so the per-step re-probe of a waiting head is
        a dict walk, not a re-hash."""
        keys = request_chain_keys(self.allocator, req)
        if keys is None:
            return 0, 0
        return self.allocator.probe_prefix_tiered(req.prompt_ids, keys)

    def _acquire_blocks(self, req: Request, need_tokens: int,
                        tiered: bool = True):
        """All-or-nothing block acquisition, honoring any cached prefix
        across both tiers.

        Returns (blocks, cached_tokens, restore plan) or (None, 0, []) if
        the pool can't hold the request right now. Host-tier restores in
        the plan are freshly allocated blocks whose pages the engine writes
        before the suffix prefill; on the failure path their release sends
        them back unindexed (they hold no valid content yet).

        `tiered=False` (the batched-prefill path) matches the DEVICE index
        only: under a pool-shared host store, another replica's step thread
        can put a chain key between this plan's probe and match, and a
        late host hit surfacing mid-batch has no chunk step to ride — the
        request simply recomputes, which is always correct."""
        keys = request_chain_keys(self.allocator, req)
        if keys is not None and tiered:
            blocks, cached, restores = self.allocator.match_prefix_tiered(
                req.prompt_ids, keys)
        elif keys is not None:
            blocks, cached = self.allocator.match_prefix(req.prompt_ids, keys)
            restores = []
        else:
            blocks, cached, restores = self.allocator.new_sequence(), 0, []
        if not blocks.ensure_capacity(need_tokens):
            blocks.release()
            return None, 0, []
        return blocks, cached, restores

    def _next_chunk(self, req: Request,
                    max_padded: Optional[int] = None) -> Optional[ChunkPrefill]:
        start = req.num_computed_tokens
        remaining = req.num_prompt_tokens - start
        c = self.cfg.prefill_chunk_tokens
        real = remaining if c is None else min(c, remaining)
        # Pick the compiled chunk length from the block-aligned ladder (a
        # cache-hit suffix is usually far shorter than the full chunk size).
        # chunk_start + padded must never exceed the block table — the
        # padded tail's page writes would otherwise clamp onto the last real
        # block and destroy its KV. Near the table end we SPLIT the chunk
        # onto a smaller rung instead of clamping to an off-ladder length
        # (every off-ladder shape is a fresh 10-20 s XLA compile serialized
        # against live traffic; the warmup pass compiles exactly
        # cfg.chunk_ladder()). The remainder continues next plan().
        # `max_padded` adds the hybrid planner's token-budget cap the same
        # way; when even the smallest rung overruns it, returns None (the
        # caller falls back to the serial paths).
        bs = self.cfg.block_size
        table_tokens = -(-self.cfg.max_model_len // bs) * bs
        ladder = self.cfg.chunk_ladder()
        room = table_tokens - start
        if max_padded is not None:
            room = min(room, max_padded)
        padded = next((a for a in ladder if a >= real), ladder[-1])
        if padded > room:
            fits = [a for a in ladder if a <= room]
            # Without max_padded: room >= remaining >= 1 and the ladder
            # floor is block_size, so fits is empty only when room <
            # block_size — impossible, since start is block-aligned
            # progress within table_tokens. With max_padded it is the
            # budget-doesn't-fit signal.
            if not fits:
                return None
            padded = fits[-1]
            real = min(real, padded)
        return ChunkPrefill(request=req, chunk_start=start, chunk_len=real,
                            padded_len=padded)

    def requeue_front(self, req: Request) -> None:
        """Re-queue already-admitted work at the head of the waiting queue,
        bypassing the max_queue bound — the preemption contract (admitted
        work is never shed) extended to migration adopts whose KV could
        not transplant: the request recomputes from its folded history."""
        req.state = RequestState.WAITING
        self.waiting.appendleft(req)
        self.composition_epoch += 1

    def adopt_running(self, req: Request) -> None:
        """Seat an adopted (migrated-in) request directly in the running
        set, mid-chunked-prefill: its restored blocks hold
        `num_computed_tokens` of KV and the suffix prefills through the
        normal chunk path. The caller verified the seat and block
        capacity; this is only the membership bookkeeping."""
        req.state = RequestState.RUNNING
        self.running.append(req)
        self.composition_epoch += 1
        if self.on_admit is not None:
            self.on_admit(req)

    def abort(self, req: Request) -> None:
        self.composition_epoch += 1
        if req in self.running:
            self.running.remove(req)
        try:
            self.waiting.remove(req)
        except ValueError:
            pass
        self._release(req)

    # -- planning ----------------------------------------------------------

    def plan(self) -> StepPlan:
        """Choose the next device step. Prefill-priority; with
        `hybrid_token_budget` set, a pending chunk and the decode batch
        fuse into one HybridBatch when both exist."""
        if self.cfg.hybrid_token_budget:
            hb = self._plan_hybrid()
            if hb is not None:
                self.num_scheduled_prefills += 1
                self.num_scheduled_decodes += 1
                self.num_scheduled_hybrid += 1
                return hb
        pf = self._plan_prefill()
        if pf is not None:
            self.num_scheduled_prefills += 1
            return pf
        dec = self._plan_decode()
        if dec is not None:
            self.num_scheduled_decodes += 1
        return dec

    def _plan_hybrid(self) -> Optional[HybridBatch]:
        """Fuse the in-flight (or newly admitted) prefill chunk with a
        decode step over every OTHER running lane — one ragged dispatch.

        Falls back (returns None) whenever the fusion has no partner on
        either side: no pending chunk, no other running lanes, the decode
        capacity pass preempted everyone, or even the smallest chunk rung
        overruns the budget after the decode lanes take their share."""
        pref = next((r for r in self.running if r.is_prefilling), None)
        if pref is None:
            pref = self._admit_chunk_head()
        if pref is None:
            return None
        others = [r for r in self.running
                  if r is not pref and not r.is_prefilling]
        if not others:
            return None
        # Budget feasibility BEFORE the capacity pass: _plan_decode grows
        # block capacity and may PREEMPT lanes — side effects that would be
        # kept while the batch it built gets discarded if no chunk rung
        # fits afterwards, turning an unfusably small budget into spurious
        # preemptions the serial schedule never makes. The pass only ever
        # shrinks the batch, so the full candidate set's bucket bounds the
        # decode share from above; if the smallest ladder rung doesn't fit
        # beside it, skip fusion without touching any allocator state.
        worst_room = (self.cfg.hybrid_token_budget
                      - bucket_up(len(others), self.cfg.batch_buckets))
        if self.cfg.chunk_ladder()[0] > worst_room:
            return None
        dec = self._plan_decode(candidates=others)
        if dec is None:
            return None
        room = self.cfg.hybrid_token_budget - dec.padded_batch
        chunk = self._next_chunk(pref, max_padded=room)
        if chunk is None:
            return None
        return HybridBatch(decode=dec, chunk=chunk)

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def _padded_prompt_len(self, req: Request) -> int:
        n = bucket_up(req.num_prompt_tokens, self.cfg.prefill_buckets)
        # Prefill writes whole blocks; keep the bucket block-aligned.
        bs = self.cfg.block_size
        return -(-n // bs) * bs

    def _admit_chunk_head(self) -> Optional[Request]:
        """Admit the head of the waiting queue onto the chunk path (long or
        cache-hit prompts, which prefill chunk by chunk). Returns the
        admitted (now RUNNING) request, or None — not eligible, no seat,
        or no KV room. Shared by the serial prefill planner and the hybrid
        planner so admission policy stays in one place."""
        if not self.waiting:
            return None
        head = self.waiting[0]
        if not (self._needs_chunking(head) or sum(self._probe_cached(head)) > 0):
            return None
        if len(self.running) >= self.cfg.max_num_seqs:
            return None
        need_tokens = head.num_prompt_tokens + 1 + self.cfg.decode_lookahead
        blocks, cached, restores = self._acquire_blocks(head, need_tokens)
        if blocks is None:
            if not self.running:
                bad = self.waiting.popleft()
                bad.error = (
                    f"sequence of {bad.num_prompt_tokens} tokens cannot fit "
                    f"the KV pool ({self.allocator.usable_tokens} tokens)"
                )
                self.failed.append(bad)
            return None  # no KV room: let decode drain / preemption handle it
        head.blocks = blocks
        head.num_computed_tokens = cached
        head.pending_restore = restores or None
        record = getattr(self.allocator, "record_prefix_stats", None)
        if record is not None:  # hit tokens are actually applied here
            host_tokens = len(restores) * self.cfg.block_size
            record(head.num_prompt_tokens, cached - host_tokens)
            if restores:
                self.allocator.record_host_hit(host_tokens)
        head.state = RequestState.RUNNING
        self.running.append(self.waiting.popleft())
        self.composition_epoch += 1
        if self.on_admit is not None:
            self.on_admit(head)
        return head

    def _plan_prefill(self) -> Union[PrefillBatch, ChunkPrefill, None]:
        """Admit waiting requests of one shared length bucket, or continue /
        start a chunked prefill (long prompts run alone, chunk by chunk)."""
        for r in self.running:  # in-flight chunked prompt finishes first
            if r.is_prefilling:
                return self._next_chunk(r)
        if not self.waiting:
            return None
        head = self.waiting[0]
        # Long prompts AND cache-hit prompts admit solo on the chunk path: a
        # cached request prefills only its suffix (chunk_start = cached
        # tokens), which a batched same-bucket prefill cannot express.
        # Probe cost is O(prompt) hashing — done for the HEAD only; later
        # queue entries are re-examined when they reach the head (a cached
        # request slipping into a batch is correct, it just recomputes).
        if self._needs_chunking(head) or sum(self._probe_cached(head)) > 0:
            head = self._admit_chunk_head()
            if head is None:
                return None
            return self._next_chunk(head)
        batch: list[Request] = []
        bucket_len = 0
        while self.waiting:
            req = self.waiting[0]
            if self._needs_chunking(req) or sum(self._probe_cached(req)) > 0:
                # Solo (chunk-path) admission when it reaches the head: a
                # batched prefill would REWRITE the shared prefix blocks
                # (from a different compiled bucket -> bitwise-different bf16
                # KV under a live sharer). Probe is memoized per request.
                break
            if len(self.running) + len(batch) >= self.cfg.max_num_seqs:
                break
            padded = self._padded_prompt_len(req)
            cand_len = max(bucket_len, padded)
            if batch and cand_len * (len(batch) + 1) > self.cfg.max_num_batched_tokens:
                break
            if batch and cand_len != bucket_len:
                # Keep one shape per step: only batch prompts of the same bucket.
                break
            if batch and cand_len > self.cfg.prefill_batch_max_len:
                break  # long buckets prefill solo (bounded compile variants)
            # All-or-nothing KV allocation: prompt + first decode slot +
            # lookahead headroom (keep in sync with can_admit_head).
            need_tokens = req.num_prompt_tokens + 1 + self.cfg.decode_lookahead
            # Device-only match (tiered=False): plan() is single-threaded
            # against its own index and allocation only ever REMOVES
            # entries, so a batched request can never be a late DEVICE hit;
            # the shared host store has no such guarantee (another
            # replica's drain can insert concurrently) and is not consulted.
            blocks, cached, restores = self._acquire_blocks(
                req, need_tokens, tiered=False)
            assert cached == 0 and not restores, (
                "cache hit leaked into the batched-prefill path")
            if blocks is None:
                if not self.running and not batch:
                    # The pool is completely idle and the head still cannot
                    # fit (e.g. a preempted prompt grew past pool capacity):
                    # it never will — fail it instead of wedging the queue.
                    bad = self.waiting.popleft()
                    bad.error = (
                        f"sequence of {bad.num_prompt_tokens} tokens cannot fit "
                        f"the KV pool ({self.allocator.usable_tokens} tokens)"
                    )
                    self.failed.append(bad)
                    continue
                break  # no KV room: let decode drain / preemption handle it
            req.blocks = blocks
            bucket_len = cand_len
            batch.append(self.waiting.popleft())
        if not batch:
            return None
        record = getattr(self.allocator, "record_prefix_stats", None)
        self.composition_epoch += 1
        for r in batch:
            if record is not None:  # cache misses still count as queries
                record(r.num_prompt_tokens, 0)
            r.state = RequestState.RUNNING
            self.running.append(r)
            if self.on_admit is not None:
                self.on_admit(r)
        return PrefillBatch(
            requests=batch,
            padded_len=bucket_len,
            padded_batch=bucket_up(len(batch), self.cfg.batch_buckets),
        )

    def _plan_decode(self, candidates: Optional[list[Request]] = None
                     ) -> Optional[DecodeBatch]:
        """One token for every running sequence; preempt if KV runs out.

        `candidates` restricts the pass to a subset of the running set (the
        hybrid planner decodes every lane EXCEPT the one mid-prefill);
        victims are then chosen among the candidates only, and the
        preemption bookkeeping in _preempt keeps self.running consistent."""
        if candidates is None:
            if not self.running:
                return None
            # plan() only reaches here once no chunked prefill is pending:
            # _plan_prefill returns the next chunk for any mid-prefill
            # request.
            assert not any(r.is_prefilling for r in self.running), (
                "decode planned while a chunked prefill is in flight")
            pool = self.running
        else:
            if not candidates:
                return None
            pool = candidates
        # Grow each sequence's KV capacity for this step (+ lookahead).
        # Victims are chosen LIFO (youngest arrival) — vLLM's policy, which
        # protects the oldest requests' latency.
        ordered = sorted(pool, key=lambda r: r.arrival_time)
        native_pass = getattr(self.allocator, "decode_capacity_pass", None)
        if native_pass is not None:
            # One C++ call does the whole grow/evict pass (native/ core);
            # preempted wrappers come back released, so _preempt's release
            # is a no-op and only the queue bookkeeping runs here.
            needs = [r.total_len + 1 + self.cfg.decode_lookahead for r in ordered]
            keep = native_pass([r.blocks for r in ordered], needs)
            # Requeue victims youngest-first (the order LIFO eviction picks
            # them), matching the fallback loop's appendleft sequence.
            for req, kept in reversed(list(zip(ordered, keep))):
                if not kept:
                    self._preempt(req)
            survivors = [r for r, k in zip(ordered, keep) if k]
        else:
            survivors = []
            for req in ordered:
                if req.state is not RequestState.RUNNING:
                    continue  # already preempted as a victim earlier in this pass
                while not self._ensure_decode_capacity(req):
                    victim = self._pick_victim(ordered, exclude=req)
                    if victim is None:
                        # Nothing left to evict; this request itself must wait.
                        self._preempt(req)
                        req = None
                        break
                    self._preempt(victim)
                    survivors = [r for r in survivors if r.state == RequestState.RUNNING]
                if req is not None and req.state == RequestState.RUNNING:
                    survivors.append(req)
        if candidates is None:
            self.running = survivors
        # candidates path: _preempt already removed each victim from
        # self.running; the mid-prefill lane must stay, so no reassignment.
        if not survivors:
            return None
        return DecodeBatch(
            requests=list(survivors),
            padded_batch=bucket_up(len(survivors), self.cfg.batch_buckets),
        )

    def _ensure_decode_capacity(self, req: Request) -> bool:
        assert req.blocks is not None
        return req.blocks.ensure_capacity(req.total_len + 1 + self.cfg.decode_lookahead)

    def _pick_victim(self, ordered: list[Request], exclude: Request) -> Optional[Request]:
        """Youngest still-running other request. Scans the arrival-sorted list
        from the back so equal arrival_times break the same way as the C++
        pass (last index wins) — keeps the two paths trace-identical."""
        for r in reversed(ordered):
            if r is not exclude and r.state == RequestState.RUNNING:
                return r
        return None

    def _preempt(self, req: Request) -> None:
        """Evict to the waiting queue; its KV is recomputed on re-admission."""
        self.composition_epoch += 1
        self._release(req)
        req.state = RequestState.PREEMPTED
        req.num_preemptions += 1
        req.num_computed_tokens = 0  # chunked-prefill progress is in the blocks
        self.num_preemptions += 1
        # Re-admit with its generated tokens folded into the prompt so the
        # recompute prefill reproduces the exact sequence so far.
        req.prompt_ids = req.prompt_ids + req.output_ids
        req.output_ids = []
        req.state = RequestState.WAITING
        self.waiting.appendleft(req)
        if req in self.running:
            self.running.remove(req)

    # -- completion --------------------------------------------------------

    def finish(self, req: Request) -> None:
        self.composition_epoch += 1
        if req in self.running:
            self.running.remove(req)
        self._release(req)

    def _release(self, req: Request) -> None:
        if req.blocks is not None:
            req.blocks.release()
            req.blocks = None
        # An unapplied restore plan refers to blocks the release just sent
        # back to the free list — never let a later re-admission apply it.
        req.pending_restore = None

    # -- accounting (Prometheus) ------------------------------------------

    def kv_stats(self) -> dict:
        a = self.allocator
        extra = getattr(a, "kv_extra_stats", None)
        if extra is not None:
            return {**self._base_kv_stats(), **extra()}
        return self._base_kv_stats()

    def _base_kv_stats(self) -> dict:
        a = self.allocator
        return {
            "num_blocks": a.num_blocks - 1,
            "block_size": a.block_size,
            "total_tokens": a.usable_tokens,
            "used_blocks": a.num_used_blocks,
            "free_blocks": a.num_free_blocks,
            "num_waiting": len(self.waiting),
            "num_running": len(self.running),
            "num_preemptions": self.num_preemptions,
        }
