"""Runtime ownership sanitizer (`LLM_CONCURRENCY_CHECK=1`).

The static half of the concurrency plane (statics/concurrency.py) proves
the *declared* thread discipline lexically; this module asserts it on the
*live* process, compiled from the SAME registry
(statics/ownership_registry.py): `install()` wraps `__setattr__` on every
registered class so that

  * a context-owned attribute (e.g. every LLMEngine counter, owner
    `engine-loop`) binds to the first thread that writes it after
    construction and raises `OwnershipViolation` on a write from any
    other thread — binding (rather than thread *names*) makes both
    serving mode (the AsyncLLMEngine dispatch thread owns the engine)
    and sync bench/test mode (the driving thread IS the engine loop)
    assert correctly;
  * a lock-guarded attribute (e.g. every ReplicaHealth field, lock
    `_mu`) raises when written while its declared lock is not held.
    Caveat: a plain `threading.Lock` cannot report WHO holds it, so the
    assertion is `lock.locked()` — it catches writes while the lock is
    idle (the common unguarded-write bug) but not a racy write landing
    while ANOTHER thread legitimately holds the lock. The static
    checker's lexical containment rule is the sound half of that
    guarantee; this runtime check is its best-effort shadow.

Off by default and ZERO cost when off: `maybe_install()` is one
`os.environ` read at engine construction — with the knob unset no class
is touched, no wrapper exists, and the hot loop is byte-identical
(tests/test_statics_concurrency.py pins the class dicts untouched).
When on, every attribute write pays one dict lookup — a debugging mode
for churn/chaos tests (tests_faults-style workloads double as a dynamic
race detector), never production serving.

Ownership is asserted per OS thread; contexts that share the event-loop
thread (`handler` / `health-probe` / `scrape`) form one thread class
(`ownership_registry.THREAD_CLASS`) — distinguishing them is the static
checker's job. Container mutations (`self.x.append(...)`) don't pass
through `__setattr__` and stay checker-only; rebinds and augmented
assignments (every counter) are asserted here.
"""

from __future__ import annotations

import os
import threading

_INSTALLED: list = []       # (cls, had_setattr, orig_setattr, had_init, orig_init)
num_checks = 0              # writes inspected (cheap observability for tests)
num_violations = 0          # raised OwnershipViolations (pre-raise count)

_INIT_FLAG = "_concurrency_in_init"
_BIND_FLAG = "_concurrency_owner_threads"


class OwnershipViolation(AssertionError):
    """A registered attribute was written from the wrong thread / outside
    its declared lock while LLM_CONCURRENCY_CHECK=1."""


def enabled() -> bool:
    # Same accepted truthy spellings as serving/config.py's _env_bool —
    # "false"/"off"/"no" must not install a production sanitizer.
    return os.environ.get("LLM_CONCURRENCY_CHECK", "0").lower() in (
        "1", "true", "yes", "on")


def installed() -> bool:
    return bool(_INSTALLED)


def maybe_install() -> bool:
    """Install the sanitizer iff the knob is on (idempotent). Called once
    per engine construction — with the knob off this is a single env
    read and nothing else happens."""
    if not enabled():
        return False
    install()
    return True


def _build_specs() -> dict:
    """class name -> {attr: ("ctx", thread_class) | ("lock", lock_name)}
    from the shared ownership registry (imported lazily: with the
    sanitizer off the statics package never loads)."""
    from agentic_traffic_testing_tpu.statics.ownership_registry import (
        ANY,
        INIT,
        OWNED_ATTRS,
        THREAD_CLASS,
    )

    specs: dict[str, dict] = {}
    for a in OWNED_ATTRS:
        if a.lock:
            spec = ("lock", a.lock)
        elif a.owner in (ANY, INIT):
            # `any` is a documented multi-context contract; `init` writes
            # happen before publication — neither is thread-assertable.
            continue
        else:
            spec = ("ctx", THREAD_CLASS[a.owner])
        specs.setdefault(a.cls, {})[a.attr] = spec
    return specs


def _wrap_class(cls, attr_specs: dict) -> None:
    orig_setattr = cls.__setattr__
    orig_init = cls.__init__
    had_setattr = "__setattr__" in cls.__dict__
    had_init = "__init__" in cls.__dict__

    def init(self, *args, **kwargs):
        d = object.__getattribute__(self, "__dict__")
        d[_INIT_FLAG] = True
        try:
            orig_init(self, *args, **kwargs)
        finally:
            d.pop(_INIT_FLAG, None)

    def setattr_(self, name, value):
        spec = attr_specs.get(name)
        if spec is not None:
            d = object.__getattribute__(self, "__dict__")
            if _INIT_FLAG not in d:
                global num_checks, num_violations
                num_checks += 1
                kind, want = spec
                if kind == "lock":
                    # An attribute-CREATING write is construction, even
                    # without the init flag: install() can land mid-way
                    # through an enclosing __init__ (the server builds
                    # its engine — which installs — before its own later
                    # fields), so the first write of each field must not
                    # assert.
                    lock = d.get(want) if name in d else None
                    if lock is not None and not lock.locked():
                        num_violations += 1
                        raise OwnershipViolation(
                            f"{type(self).__name__}.{name} written without "
                            f"holding {want} (declared in "
                            f"statics/ownership_registry.py)")
                else:
                    me = threading.current_thread()
                    binds = d.get(_BIND_FLAG)
                    if binds is None:
                        binds = d[_BIND_FLAG] = {}
                    owner = binds.get(want)
                    if owner is None:
                        binds[want] = me
                    elif owner is not me:
                        num_violations += 1
                        raise OwnershipViolation(
                            f"{type(self).__name__}.{name} is owned by the "
                            f"'{want}' thread class, bound to "
                            f"{owner.name!r}, but was written from "
                            f"{me.name!r} — a cross-thread write the "
                            f"ownership registry forbids")
        orig_setattr(self, name, value)

    cls.__init__ = init
    cls.__setattr__ = setattr_
    _INSTALLED.append((cls, had_setattr, orig_setattr, had_init, orig_init))


def install() -> int:
    """Wrap every importable registered class; returns how many were
    wrapped. Idempotent. Classes whose module cannot import in this
    environment (e.g. aiohttp missing for LLMServer) are skipped — the
    sanitizer must never make a deployment less runnable than the code
    it audits."""
    if _INSTALLED:
        return len(_INSTALLED)
    import importlib

    from agentic_traffic_testing_tpu.statics.ownership_registry import (
        REGISTERED_CLASSES,
    )

    specs = _build_specs()
    for cls_name, path in REGISTERED_CLASSES.items():
        attr_specs = specs.get(cls_name)
        if not attr_specs:
            continue
        mod_name, _, qual = path.partition(":")
        try:
            cls = getattr(importlib.import_module(mod_name), qual)
        except Exception:
            continue
        _wrap_class(cls, attr_specs)
    return len(_INSTALLED)


def uninstall() -> None:
    """Restore every wrapped class (tests MUST call this — the wrap is
    class-global and would otherwise leak across the suite)."""
    while _INSTALLED:
        cls, had_setattr, orig_setattr, had_init, orig_init = _INSTALLED.pop()
        if had_setattr:
            cls.__setattr__ = orig_setattr
        else:
            del cls.__setattr__
        if had_init:
            cls.__init__ = orig_init
        else:
            del cls.__init__


def rebind(obj) -> None:
    """Forget an object's thread bindings (the publication handover:
    AsyncLLMEngine.start() hands an engine from the constructing thread
    to its real engine-loop thread, which then binds on its first
    write)."""
    object.__getattribute__(obj, "__dict__").pop(_BIND_FLAG, None)
