"""Paged KV cache: block-table layout + functional read/write ops.

TPU-native analog of vLLM's block KV-cache manager, whose accounting the
reference testbed reads and re-exports as Prometheus gauges
(reference: llm/serve_llm.py:245-264, 410-502 and gauge defs :142-162).

Layout (per model):
    k_cache, v_cache : [L, KH, num_blocks, block_size, hd]
    block_tables     : [max_seqs, max_blocks_per_seq] int32
    context_lens     : [max_seqs] int32

The pool is *heads-major* (KH before the block axis) so a single page of one
KV head — the unit the Pallas paged-attention kernel streams HBM->VMEM — is a
contiguous [block_size, hd] tile that satisfies Mosaic's (8, 128) tiling rule.
This is the standard TPU paged-KV layout; the reference's GPU stack keeps
heads innermost because CUDA warps gather per-token instead.

Block 0 is reserved as a *trash block*: padding rows of every block table point
at it, so scatter-writes from padded lanes land harmlessly and reads from it
are always masked out by `kv_valid_len`. Usable capacity is therefore
`(num_blocks - 1) * block_size` tokens; the exported `llm_kv_cache_*` gauges
report usable numbers.

All functions here are pure and shape-static — they are called from inside
jitted prefill/decode steps. Allocation policy (which blocks belong to which
sequence) lives host-side in `block_allocator.py`.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import ModelConfig

TRASH_BLOCK = 0

# TPU lane width: the last dim of a page is padded up to this so pages are
# tile-aligned. The tiled HBM layout pads head_dim < 128 to 128 lanes
# physically ANYWAY, so storing the pad explicitly costs no extra memory —
# and it makes a page a legal DMA source for the Pallas decode kernel
# (Mosaic cannot slice a sub-lane-width window out of an HBM memref).
PAGE_LANES = 128


def phys_head_dim(head_dim: int) -> int:
    """Physical (lane-aligned) page head dim for a logical head dim."""
    return -(-head_dim // PAGE_LANES) * PAGE_LANES


class KVCache(NamedTuple):
    """Stacked per-layer paged KV storage (a pytree; lives in HBM)."""

    k: jax.Array  # [L, KH, num_blocks, block_size, hd]
    v: jax.Array  # [L, KH, num_blocks, block_size, hd]

    @property
    def num_blocks(self) -> int:
        return self.k.shape[2]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def usable_tokens(self) -> int:
        return (self.num_blocks - 1) * self.block_size


def make_kv_cache(
    cfg: ModelConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16
) -> KVCache:
    """Pages store `phys_head_dim(head_dim)` lanes; the pad lanes stay zero
    (writers only touch [..., :head_dim]) and consumers slice or mask them."""
    shape = (cfg.num_layers, cfg.num_kv_heads, num_blocks, block_size,
             phys_head_dim(cfg.head_dim_))
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def write_prompt_kv(
    cache_l: jax.Array,
    new: jax.Array,
    block_tables: jax.Array,
) -> jax.Array:
    """Scatter a padded prompt's K (or V) into one layer's block pool.

    cache_l      [KH, num_blocks, bs, hd]
    new          [B, T, KH, hd] with T % bs == 0 (caller pads)
    block_tables [B, max_blocks]; entries beyond each prompt's blocks = TRASH_BLOCK
    """
    kh, nb_cache, bs, hd = cache_l.shape
    b, t, _, _ = new.shape
    nb = t // bs
    blocks = new.reshape(b * nb, bs, kh, hd).transpose(2, 0, 1, 3)  # [KH, B*nb, bs, hd]
    idx = block_tables[:, :nb].reshape(b * nb)
    # Duplicate trash-block indices race among themselves only; real blocks are unique.
    return cache_l.at[:, idx].set(blocks, mode="drop", unique_indices=False)


def write_decode_kv(
    cache_l: jax.Array,
    new: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
) -> jax.Array:
    """Write one token per sequence into one layer's block pool.

    cache_l      [KH, num_blocks, bs, hd]
    new          [B, KH, hd]
    block_tables [B, max_blocks]
    positions    [B] absolute position being written (trash rows may point anywhere;
                 caller sets their block table rows to TRASH_BLOCK)
    """
    kh, nb_cache, bs, hd = cache_l.shape
    b = new.shape[0]
    block_idx = jnp.take_along_axis(block_tables, (positions // bs)[:, None], axis=1)[:, 0]
    flat_idx = block_idx * bs + positions % bs  # [B] into [KH, (num_blocks*bs), hd]
    flat = cache_l.reshape(kh, nb_cache * bs, hd)
    flat = flat.at[:, flat_idx].set(new.transpose(1, 0, 2), mode="drop")
    return flat.reshape(kh, nb_cache, bs, hd)


def write_decode_kv_full(
    cache: jax.Array,         # [L, KH, num_blocks, bs, hd] (full stacked pool)
    layer: jax.Array,         # scalar i32 — layer being written
    new: jax.Array,           # [B, KH, hd]
    block_tables: jax.Array,  # [B, max_blocks]
    positions: jax.Array,     # [B] absolute position being written
    valid=None,               # [B] bool — False routes the write to the trash block
) -> jax.Array:
    """One-token-per-sequence write into the FULL stacked pool via chained
    `dynamic_update_slice` — not scatter: XLA:TPU lowers scatter as
    copy-the-operand-then-update (a full-pool copy per op, ~2 ms/GB on v5e),
    while chained DUS aliases in place after the first update.
    Trash lanes (block table row = TRASH_BLOCK) land in the trash block.

    `valid=False` lanes also land in the trash block. Speculative verify
    passes `positions + i < table capacity` here: an over-capacity position's
    table lookup would CLAMP to the row's last real block and overwrite live
    KV that the same step's attention still reads for kept tokens — routing
    to trash keeps every kept token's context intact. (Plain decode's only
    over-capacity writes come from overrun iterations whose tokens are all
    dropped host-side, so its clamp was harmless; it gains the same masking
    for free via the shared layer body.)
    """
    _, kh, _, bs, _ = cache.shape
    b, _, hd = new.shape  # logical head dim; pool lanes may be padded wider
    zero = jnp.int32(0)
    new = new.astype(cache.dtype)  # fp8 pages: quantize at write
    for i in range(b):
        blk = block_tables[i, positions[i] // bs]  # OOB positions clamp; see above
        if valid is not None:
            blk = jnp.where(valid[i], blk, TRASH_BLOCK)
        upd = new[i].reshape(1, kh, 1, 1, hd)
        cache = jax.lax.dynamic_update_slice(
            cache, upd, (layer, zero, blk, positions[i] % bs, zero)
        )
    return cache


def gather_kv(cache_l: jax.Array, block_tables: jax.Array) -> jax.Array:
    """Materialize each sequence's KV from one layer's pool (jnp reference path).

    cache_l      [KH, num_blocks, bs, hd]
    block_tables [B, max_blocks]
    returns      [B, max_blocks*bs, KH, hd]

    The Pallas paged-attention kernel replaces this gather on TPU; this path is
    the correctness oracle and the CPU/test fallback.
    """
    kh, nb_cache, bs, hd = cache_l.shape
    b, max_blocks = block_tables.shape
    gathered = cache_l[:, block_tables.reshape(-1)]  # [KH, B*max_blocks, bs, hd]
    return gathered.reshape(kh, b, max_blocks * bs, hd).transpose(1, 2, 0, 3)


def kv_cache_bytes(cfg: ModelConfig, num_blocks: int, block_size: int, dtype_bytes: int = 2) -> int:
    return (2 * cfg.num_layers * num_blocks * block_size * cfg.num_kv_heads
            * phys_head_dim(cfg.head_dim_) * dtype_bytes)


def profile_num_blocks(
    cfg: ModelConfig,
    block_size: int,
    hbm_bytes_free: int,
    memory_utilization: float,
    dtype_bytes: int = 2,
    tp_size: int = 1,
    pp_size: int = 1,
) -> int:
    """Derive the block budget from free HBM, vLLM-profiling style.

    The reference reads `num_gpu_blocks` off vLLM's cache config after its
    profiling pass (reference: llm/serve_llm.py:245-264); here the equivalent
    computation is explicit: blocks = utilization * free_hbm / bytes_per_block.
    With tensor parallelism each chip holds KH/tp heads, so per-chip block
    bytes shrink accordingly (min 1 head group); with pipeline stages each
    chip holds L/pp layers of every block (parallel/pp_runner.py shards the
    pool's layer axis), shrinking per-chip block bytes the same way — the
    capacity win is PP's whole purpose, so the budget must see it.
    """
    kh_local = max(1, cfg.num_kv_heads // tp_size)
    layers_local = max(1, cfg.num_layers // pp_size)
    per_block = (2 * layers_local * block_size * kh_local
                 * phys_head_dim(cfg.head_dim_) * dtype_bytes)
    budget = int(hbm_bytes_free * memory_utilization)
    return max(0, budget // per_block)
