"""Paged KV cache: block-table layout + functional read/write ops.

TPU-native analog of vLLM's block KV-cache manager, whose accounting the
reference testbed reads and re-exports as Prometheus gauges
(reference: llm/serve_llm.py:245-264, 410-502 and gauge defs :142-162).

Layout (per model):
    k_cache, v_cache : [L, KH, num_blocks, block_size, hd]
    block_tables     : [max_seqs, max_blocks_per_seq] int32
    context_lens     : [max_seqs] int32

The pool is *heads-major* (KH before the block axis) so a single page of one
KV head — the unit the Pallas paged-attention kernel streams HBM->VMEM — is a
contiguous [block_size, hd] tile that satisfies Mosaic's (8, 128) tiling rule.
This is the standard TPU paged-KV layout; the reference's GPU stack keeps
heads innermost because CUDA warps gather per-token instead.

Block 0 is reserved as a *trash block*: padding rows of every block table point
at it, so scatter-writes from padded lanes land harmlessly and reads from it
are always masked out by `kv_valid_len`. Usable capacity is therefore
`(num_blocks - 1) * block_size` tokens; the exported `llm_kv_cache_*` gauges
report usable numbers.

All functions here are pure and shape-static — they are called from inside
jitted prefill/decode steps. Allocation policy (which blocks belong to which
sequence) lives host-side in `block_allocator.py`.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import ModelConfig

TRASH_BLOCK = 0

# Scaled int8 KV quantization (kv_cache_dtype="int8"): symmetric, one fp32
# scale per (layer, page, kv-head). 127 levels each side; the trash block's
# scale accumulates garbage like its pages do (reads are always masked).
KV_QMAX = 127.0
# Guard divisor for empty scales: a scale of exactly 0 marks a never-written
# (or all-zero) page, whose quantized values are forced to 0.
_EPS = 1e-30

# TPU lane width: the last dim of a page is padded up to this so pages are
# tile-aligned. The tiled HBM layout pads head_dim < 128 to 128 lanes
# physically ANYWAY, so storing the pad explicitly costs no extra memory —
# and it makes a page a legal DMA source for the Pallas decode kernel
# (Mosaic cannot slice a sub-lane-width window out of an HBM memref).
PAGE_LANES = 128


def phys_head_dim(head_dim: int) -> int:
    """Physical (lane-aligned) page head dim for a logical head dim."""
    return -(-head_dim // PAGE_LANES) * PAGE_LANES


class KVCache(NamedTuple):
    """Stacked per-layer paged KV storage (a pytree; lives in HBM).

    `k_scale`/`v_scale` are None except under kv_cache_dtype="int8": then
    the pages are int8 and each (layer, page, kv-head) carries one fp32
    dequantization scale — [L, num_blocks, KH], pages-major so one page's
    KH scales are contiguous (a DMA-able row for the decode kernels).
    A None scale pair keeps the pytree structure (and therefore every
    compiled program) of the pre-quantization cache bit-identical.
    """

    k: jax.Array  # [L, KH, num_blocks, block_size, hd]
    v: jax.Array  # [L, KH, num_blocks, block_size, hd]
    k_scale: Optional[jax.Array] = None  # [L, num_blocks, KH] f32 (int8 only)
    v_scale: Optional[jax.Array] = None

    @property
    def num_blocks(self) -> int:
        return self.k.shape[2]

    @property
    def block_size(self) -> int:
        return self.k.shape[3]

    @property
    def usable_tokens(self) -> int:
        return (self.num_blocks - 1) * self.block_size

    @property
    def quantized(self) -> bool:
        """True for the scaled int8 pool (trace-time static: pytree shape)."""
        return self.k_scale is not None


def make_kv_cache(
    cfg: ModelConfig, num_blocks: int, block_size: int, dtype=jnp.bfloat16,
    quantized: bool = False,
) -> KVCache:
    """Pages store `phys_head_dim(head_dim)` lanes; the pad lanes stay zero
    (writers only touch [..., :head_dim]) and consumers slice or mask them.
    `quantized` builds the scaled int8 pool: int8 pages plus zeroed
    per-(page x kv-head) fp32 scales (scale 0 = never written)."""
    shape = (cfg.num_layers, cfg.num_kv_heads, num_blocks, block_size,
             phys_head_dim(cfg.head_dim_))
    if quantized:
        if dtype != jnp.int8:
            raise ValueError(f"quantized pool stores int8 pages, got {dtype}")
        sshape = (cfg.num_layers, num_blocks, cfg.num_kv_heads)
        return KVCache(k=jnp.zeros(shape, jnp.int8),
                       v=jnp.zeros(shape, jnp.int8),
                       k_scale=jnp.zeros(sshape, jnp.float32),
                       v_scale=jnp.zeros(sshape, jnp.float32))
    return KVCache(k=jnp.zeros(shape, dtype), v=jnp.zeros(shape, dtype))


def quantize_with_scale(x: jax.Array, scale: jax.Array) -> jax.Array:
    """Symmetric int8 quantization of `x` against a broadcastable `scale`.

    The EXACT op sequence (where -> round -> clip -> cast, f32 throughout)
    is shared by every quantizing writer — XLA paths and the fused in-kernel
    write replicate it verbatim so the fused-vs-separate byte-identity pin
    holds bit-for-bit."""
    q = jnp.where(scale > 0, x / jnp.maximum(scale, _EPS), 0.0)
    return jnp.clip(jnp.round(q), -KV_QMAX, KV_QMAX).astype(jnp.int8)


def dequantize(q: jax.Array, scale: jax.Array) -> jax.Array:
    """int8 -> f32 against a broadcastable scale (the oracle-side inverse)."""
    return q.astype(jnp.float32) * scale


def requant_page_int8(page_i8: jax.Array, tok_f32: jax.Array,
                      s_old: jax.Array, row) -> tuple[jax.Array, jax.Array]:
    """Append one token row to an int8 page, re-quantizing the page against
    s_new = max(s_old, absmax(token)/127). Returns (new int8 page, s_new).

    Shapes: page [KH, bs, hdp] int8, tok [KH, hdp] f32, s_old [KH] f32,
    row scalar i32. The ONE requant op sequence — the XLA writer
    (write_decode_kv_full_quant) and the fused in-kernel write
    (ops/pallas/paged_attention.py) both call THIS function, so
    fused-vs-separate byte identity holds by construction, not by
    two-file discipline."""
    bs = page_i8.shape[1]
    s_new = jnp.maximum(s_old, jnp.max(jnp.abs(tok_f32), axis=-1) / KV_QMAX)
    r = jnp.where(s_new > 0, s_old / jnp.maximum(s_new, _EPS), 0.0)
    page_f = page_i8.astype(jnp.float32) * r[:, None, None]
    q_tok = jnp.where(s_new[:, None] > 0,
                      tok_f32 / jnp.maximum(s_new[:, None], _EPS), 0.0)
    rowmask = jax.lax.broadcasted_iota(jnp.int32, (1, bs, 1), 1) == row
    page_f = jnp.where(rowmask, q_tok[:, None, :], page_f)
    page_q = jnp.clip(jnp.round(page_f), -KV_QMAX, KV_QMAX).astype(jnp.int8)
    return page_q, s_new


def write_prompt_kv(
    cache_l: jax.Array,
    new: jax.Array,
    block_tables: jax.Array,
) -> jax.Array:
    """Scatter a padded prompt's K (or V) into one layer's block pool.

    cache_l      [KH, num_blocks, bs, hd]
    new          [B, T, KH, hd] with T % bs == 0 (caller pads)
    block_tables [B, max_blocks]; entries beyond each prompt's blocks = TRASH_BLOCK
    """
    kh, nb_cache, bs, hd = cache_l.shape
    b, t, _, _ = new.shape
    nb = t // bs
    blocks = new.reshape(b * nb, bs, kh, hd).transpose(2, 0, 1, 3)  # [KH, B*nb, bs, hd]
    idx = block_tables[:, :nb].reshape(b * nb)
    # Duplicate trash-block indices race among themselves only; real blocks are unique.
    return cache_l.at[:, idx].set(blocks, mode="drop", unique_indices=False)


def write_decode_kv(
    cache_l: jax.Array,
    new: jax.Array,
    block_tables: jax.Array,
    positions: jax.Array,
) -> jax.Array:
    """Write one token per sequence into one layer's block pool.

    cache_l      [KH, num_blocks, bs, hd]
    new          [B, KH, hd]
    block_tables [B, max_blocks]
    positions    [B] absolute position being written (trash rows may point anywhere;
                 caller sets their block table rows to TRASH_BLOCK)
    """
    kh, nb_cache, bs, hd = cache_l.shape
    b = new.shape[0]
    block_idx = jnp.take_along_axis(block_tables, (positions // bs)[:, None], axis=1)[:, 0]
    flat_idx = block_idx * bs + positions % bs  # [B] into [KH, (num_blocks*bs), hd]
    flat = cache_l.reshape(kh, nb_cache * bs, hd)
    flat = flat.at[:, flat_idx].set(new.transpose(1, 0, 2), mode="drop")
    return flat.reshape(kh, nb_cache, bs, hd)


def write_decode_kv_full(
    cache: jax.Array,         # [L, KH, num_blocks, bs, hd] (full stacked pool)
    layer: jax.Array,         # scalar i32 — layer being written
    new: jax.Array,           # [B, KH, hd]
    block_tables: jax.Array,  # [B, max_blocks]
    positions: jax.Array,     # [B] absolute position being written
    valid=None,               # [B] bool — False routes the write to the trash block
) -> jax.Array:
    """One-token-per-sequence write into the FULL stacked pool via chained
    `dynamic_update_slice` — not scatter: XLA:TPU lowers scatter as
    copy-the-operand-then-update (a full-pool copy per op, ~2 ms/GB on v5e),
    while chained DUS aliases in place after the first update.
    Trash lanes (block table row = TRASH_BLOCK) land in the trash block.

    `valid=False` lanes also land in the trash block. Speculative verify
    passes `positions + i < table capacity` here: an over-capacity position's
    table lookup would CLAMP to the row's last real block and overwrite live
    KV that the same step's attention still reads for kept tokens — routing
    to trash keeps every kept token's context intact. (Plain decode's only
    over-capacity writes come from overrun iterations whose tokens are all
    dropped host-side, so its clamp was harmless; it gains the same masking
    for free via the shared layer body.)
    """
    _, kh, _, bs, _ = cache.shape
    b, _, hd = new.shape  # logical head dim; pool lanes may be padded wider
    zero = jnp.int32(0)
    new = new.astype(cache.dtype)  # fp8 pages: quantize at write
    for i in range(b):
        blk = block_tables[i, positions[i] // bs]  # OOB positions clamp; see above
        if valid is not None:
            blk = jnp.where(valid[i], blk, TRASH_BLOCK)
        upd = new[i].reshape(1, kh, 1, 1, hd)
        cache = jax.lax.dynamic_update_slice(
            cache, upd, (layer, zero, blk, positions[i] % bs, zero)
        )
    return cache


def write_decode_kv_full_quant(
    cache: jax.Array,         # [L, KH, num_blocks, bs, hdp] int8 pool
    scale: jax.Array,         # [L, num_blocks, KH] f32 per-page scales
    layer: jax.Array,         # scalar i32 — layer being written
    new: jax.Array,           # [B, KH, hd] (compute dtype; hd <= hdp)
    block_tables: jax.Array,  # [B, max_blocks]
    positions: jax.Array,     # [B] absolute position being written
    valid=None,               # [B] bool — False routes the write to trash
) -> tuple[jax.Array, jax.Array]:
    """Quantizing one-token write into the scaled int8 pool.

    Per-page symmetric scales cannot absorb a louder-than-the-page token by
    casting alone: appending token t to page p re-quantizes the WHOLE page
    against s_new = max(s_old, absmax(t)/127) (a [KH, bs, hdp] read-modify-
    write per lane per layer — bounded, and tiny next to the attention read
    of the full context). s_old/s_new <= 1, so settled pages re-round at
    most once per louder newcomer; the fp-tol parity tiers in
    tests/test_kv_quant.py own the accumulated error budget. Trash-block
    lanes race onto page 0 exactly like the unquantized writer — its scale
    is garbage and its reads are always masked.

    The requant itself is `requant_page_int8` — the SAME function the
    fused in-kernel write (ops/pallas/paged_attention.py) calls, so fused
    and separate writes are byte-identical by construction, not by
    two-file discipline."""
    _, kh, _, bs, hdp = cache.shape
    b, _, hd = new.shape
    zero = jnp.int32(0)
    newf = new.astype(jnp.float32)
    if hd < hdp:
        newf = jnp.pad(newf, ((0, 0), (0, 0), (0, hdp - hd)))
    for i in range(b):
        blk = block_tables[i, positions[i] // bs]  # OOB clamps; trash below
        if valid is not None:
            blk = jnp.where(valid[i], blk, TRASH_BLOCK)
        row = positions[i] % bs
        s_old = jax.lax.dynamic_slice(
            scale, (layer, blk, zero), (1, 1, kh))[0, 0]      # [KH]
        page = jax.lax.dynamic_slice(
            cache, (layer, zero, blk, zero, zero),
            (1, kh, 1, bs, hdp))[0, :, 0]                     # [KH, bs, hdp]
        page_q, s_new = requant_page_int8(page, newf[i], s_old, row)
        cache = jax.lax.dynamic_update_slice(
            cache, page_q[None, :, None], (layer, zero, blk, zero, zero))
        scale = jax.lax.dynamic_update_slice(
            scale, s_new[None, None, :], (layer, blk, zero))
    return cache, scale


def write_chunk_pages_quant(
    cache: jax.Array,         # [L, KH, num_blocks, bs, hdp] int8 pool
    scale: jax.Array,         # [L, num_blocks, KH] f32
    layer: jax.Array,         # scalar i32
    pages: jax.Array,         # [KH, C, hd] one row's chunk KV (compute dtype)
    table_row: jax.Array,     # [max_blocks] the row's block table
    first_block: jax.Array,   # scalar i32 — table column of pages[:, 0]
) -> tuple[jax.Array, jax.Array]:
    """Quantize + write one prefill chunk's whole pages (hybrid step path).

    Chunk blocks are private suffix blocks written exactly once per layer,
    so each page's scale is simply absmax/127 over the page — no requant.
    Garbage rows beyond chunk_len quantize along (slots nothing reads)."""
    _, kh, _, bs, hdp = cache.shape
    _, c, hd = pages.shape
    zero = jnp.int32(0)
    x = pages.astype(jnp.float32)
    if hd < hdp:
        x = jnp.pad(x, ((0, 0), (0, 0), (0, hdp - hd)))
    for p in range(c // bs):
        blk = table_row[first_block + p]
        pg = x[:, p * bs:(p + 1) * bs]                        # [KH, bs, hdp]
        s = jnp.max(jnp.abs(pg), axis=(-2, -1)) / KV_QMAX     # [KH]
        q = quantize_with_scale(pg, s[:, None, None])
        cache = jax.lax.dynamic_update_slice(
            cache, q[None, :, None], (layer, zero, blk, zero, zero))
        scale = jax.lax.dynamic_update_slice(
            scale, s[None, None, :], (layer, blk, zero))
    return cache, scale


def gather_kv(cache_l: jax.Array, block_tables: jax.Array) -> jax.Array:
    """Materialize each sequence's KV from one layer's pool (jnp reference path).

    cache_l      [KH, num_blocks, bs, hd]
    block_tables [B, max_blocks]
    returns      [B, max_blocks*bs, KH, hd]

    The Pallas paged-attention kernel replaces this gather on TPU; this path is
    the correctness oracle and the CPU/test fallback.
    """
    kh, nb_cache, bs, hd = cache_l.shape
    b, max_blocks = block_tables.shape
    gathered = cache_l[:, block_tables.reshape(-1)]  # [KH, B*max_blocks, bs, hd]
    return gathered.reshape(kh, b, max_blocks * bs, hd).transpose(1, 2, 0, 3)


def gather_kv_dequant(cache_l: jax.Array, scale_l: jax.Array,
                      block_tables: jax.Array) -> jax.Array:
    """`gather_kv` for the scaled int8 pool: dequantized f32 sequences.

    cache_l [KH, num_blocks, bs, hd] int8; scale_l [num_blocks, KH] f32.
    Returns [B, max_blocks*bs, KH, hd] f32 — the jnp oracle (and CPU/chunk
    gather path) every quantized decode kernel is tested against."""
    bs = cache_l.shape[2]
    g = gather_kv(cache_l, block_tables)          # [B, W*bs, KH, hd] int8
    s = scale_l[block_tables]                     # [B, W, KH]
    s = jnp.repeat(s, bs, axis=1)                 # [B, W*bs, KH]
    return g.astype(jnp.float32) * s[..., None]


def kv_cache_bytes(cfg: ModelConfig, num_blocks: int, block_size: int, dtype_bytes: int = 2) -> int:
    return (2 * cfg.num_layers * num_blocks * block_size * cfg.num_kv_heads
            * phys_head_dim(cfg.head_dim_) * dtype_bytes)


def profile_num_blocks(
    cfg: ModelConfig,
    block_size: int,
    hbm_bytes_free: int,
    memory_utilization: float,
    dtype_bytes: int = 2,
    tp_size: int = 1,
    pp_size: int = 1,
    scale_bytes_per_head: int = 0,
) -> int:
    """Derive the block budget from free HBM, vLLM-profiling style.

    The reference reads `num_gpu_blocks` off vLLM's cache config after its
    profiling pass (reference: llm/serve_llm.py:245-264); here the equivalent
    computation is explicit: blocks = utilization * free_hbm / bytes_per_block.
    With tensor parallelism each chip holds KH/tp heads, so per-chip block
    bytes shrink accordingly (min 1 head group); with pipeline stages each
    chip holds L/pp layers of every block (parallel/pp_runner.py shards the
    pool's layer axis), shrinking per-chip block bytes the same way — the
    capacity win is PP's whole purpose, so the budget must see it.
    """
    kh_local = max(1, cfg.num_kv_heads // tp_size)
    layers_local = max(1, cfg.num_layers // pp_size)
    # scale_bytes_per_head: the int8 pool's per-(layer, page, kv-head) fp32
    # scale pair (2 * 4 bytes) — tiny, but the budget should not lie.
    per_block = (2 * layers_local * block_size * kh_local
                 * phys_head_dim(cfg.head_dim_) * dtype_bytes
                 + layers_local * kh_local * scale_bytes_per_head)
    budget = int(hbm_bytes_free * memory_utilization)
    return max(0, budget // per_block)
