"""Host-RAM tier for evicted prefix-cache KV blocks ("L2 KV cache").

The device prefix cache (block_allocator.PrefixCachingAllocator) is the only
KV tier the engine had: when capacity pressure reclaims the LRU evictable
pool, the content is unindexed and the pages are overwritten — the next
arrival of the same scenario prefix pays a full prefill recompute, the exact
hot path ROADMAP flags as the worst bench gap (prefill MFU 0.13). HBM is
small (~16 GB per v5e chip) while host RAM is plentiful, so this module adds
the second tier PagedAttention's block granularity makes cheap (arXiv:
2309.06180) and vAttention's residency/kernel decoupling argues for (arXiv:
2405.04437): evicted full indexed blocks spill device→host and stream back
into freshly allocated blocks on a later prefix hit, instead of recomputing.

Addressing is the SAME content-hash chain key the device index uses
(PrefixCachingAllocator.chain_keys), so the two tiers form one lookup chain:
a prefix probe walks device blocks first, then host blocks, and stops at the
first miss. Token tuples are stored alongside and compared on every get —
a 64-bit hash collision degrades to a miss, never serves another prompt's
KV (the same cross-request-leakage rule the device index enforces).

The store is deliberately host-only and engine-agnostic: it holds numpy
arrays and does no jax work. The ENGINE owns the copies (engine.py:
`_queue_block_save` slices pages device-side at eviction time — dispatch
order puts the read before the reclaiming prefill's write — and drains the
async host copies off the step loop; `_apply_pending_restore` writes host
pages into freshly allocated blocks before the uncached tail prefills).
That split lets ONE store back every replica of an EnginePool: replicas
share no device state, but a prefix computed (then evicted) on replica 0
becomes a host hit for replica 1 — the prefix-affinity router's cold-replica
fallback turns replica misses into restores instead of recomputes.

Thread safety: every public method takes the internal lock. Engines call
put/get from their step threads and the router probes via contains from the
HTTP thread; entries are immutable once stored (numpy arrays are written
once by device_get and only read afterwards).
"""

from __future__ import annotations

import dataclasses
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np


@dataclasses.dataclass
class HostBlock:
    """One offloaded KV block: the page pair + its content identity.

    Quantized (int8) pools additionally carry the block's per-(layer,
    kv-head) fp32 scales — stored raw, so save/restore never round-trips
    through bf16 and the tier holds ~2x the blocks per GB."""

    tokens: tuple           # the block's token ids (collision check)
    k: np.ndarray           # [L, KH, block_size, hd_phys], cache dtype
    v: np.ndarray           # same shape/dtype as k
    nbytes: int
    k_scale: Optional[np.ndarray] = None  # [L, KH] f32 (int8 pools only)
    v_scale: Optional[np.ndarray] = None


@dataclasses.dataclass
class RestoreBlock:
    """A planned host→device restore: host pages bound to a freshly
    allocated device block. Built by match_prefix_tiered, applied by the
    engine right before the request's first (suffix) prefill chunk."""

    block: int              # device block id the pages will be written into
    key: int                # chain hash (re-indexed under this key on apply)
    tokens: tuple
    k: np.ndarray
    v: np.ndarray
    k_scale: Optional[np.ndarray] = None
    v_scale: Optional[np.ndarray] = None


class HostKVStore:
    """LRU host-RAM store of full prefix blocks, keyed by chain hash.

    Capacity is a byte budget (`LLM_HOST_CACHE_GB` at the serving layer);
    inserting past it evicts least-recently-used entries. `get` refreshes
    recency, `contains` (the probe path) does not — a router probe must not
    reorder the LRU under the step threads.
    """

    def __init__(self, capacity_bytes: int) -> None:
        if capacity_bytes <= 0:
            raise ValueError(
                f"host KV store needs a positive byte budget, got {capacity_bytes}")
        self.capacity_bytes = int(capacity_bytes)
        self._lock = threading.Lock()
        self._entries: OrderedDict[int, HostBlock] = OrderedDict()
        self.used_bytes = 0
        # Page geometry attested by the first put(): every later block must
        # match it, and every get() re-checks — a corrupt entry degrades to
        # a MISS (dropped + counted), it never raises into the admission
        # path that is probing it (scheduler._acquire_blocks runs inside
        # plan(); an exception there used to fail the whole step).
        self._page_shape: Optional[tuple] = None
        self._page_dtypes: Optional[tuple] = None
        # Scale geometry (int8 pools): (shape, dtype) of the per-block
        # scale pair, or None for unquantized pools — attested like the
        # page geometry by the first put().
        self._scale_shape: Optional[tuple] = None
        # Cumulative counters (exported as llm_host_cache_* families).
        self.saved_blocks = 0     # successful put()s
        self.evicted_blocks = 0   # LRU evictions (capacity pressure)
        self.corrupt_dropped = 0  # validation failures degraded to misses
        self.invalidated_blocks = 0  # explicit drops (restore fallback)

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # statics: thread(engine-loop, handler)
    def contains(self, key: int, tokens: tuple) -> bool:
        """Read-only probe: no LRU touch (safe for the router/scheduler's
        per-step re-probe of a waiting head)."""
        with self._lock:
            e = self._entries.get(key)
            return e is not None and e.tokens == tokens

    def _valid(self, e: HostBlock) -> bool:
        """Restore-side validation: the entry's pages must still match the
        store's attested geometry. Anything off — wrong shape, dtype, a
        k/v pair that disagrees — is corruption, not a servable block."""
        if not (isinstance(e.k, np.ndarray) and isinstance(e.v, np.ndarray)):
            return False
        if e.k.shape != e.v.shape or e.k.shape != self._page_shape:
            return False
        if (e.k.dtype, e.v.dtype) != self._page_dtypes:
            return False
        if self._scale_shape is None:
            return e.k_scale is None and e.v_scale is None
        return (isinstance(e.k_scale, np.ndarray)
                and isinstance(e.v_scale, np.ndarray)
                and e.k_scale.shape == self._scale_shape
                and e.v_scale.shape == self._scale_shape)

    # statics: thread(engine-loop, handler)
    def get(self, key: int, tokens: tuple) -> Optional[HostBlock]:
        """Entry for `key`, or None on miss/collision/corruption;
        refreshes recency. Validation failures DROP the entry and count
        in `corrupt_dropped` — the caller sees a plain miss and takes the
        recompute path, never an exception mid-admission."""
        with self._lock:
            e = self._entries.get(key)
            if e is None or e.tokens != tokens:
                return None
            if not self._valid(e):
                del self._entries[key]
                self.used_bytes -= e.nbytes
                self.corrupt_dropped += 1
                return None
            self._entries.move_to_end(key)
            return e

    # statics: thread(engine-loop)
    def invalidate(self, key: int) -> bool:
        """Drop one entry (the engine's restore-fallback path: a block
        that failed to apply must not be re-matched on re-admission).
        Counted separately from corrupt_dropped — a fallback plan can
        invalidate healthy siblings of the one bad block, and conflating
        them would make the corruption metric lie. True if it existed."""
        with self._lock:
            e = self._entries.pop(key, None)
            if e is None:
                return False
            self.used_bytes -= e.nbytes
            self.invalidated_blocks += 1
            return True

    # statics: thread(engine-loop)
    def put(self, key: int, tokens: tuple, k: np.ndarray, v: np.ndarray,
            k_scale: Optional[np.ndarray] = None,
            v_scale: Optional[np.ndarray] = None) -> bool:
        """Insert (or refresh) one block; False if it can never fit (or
        fails the geometry attestation a first put established). Quantized
        pools pass the block's fp32 scale pair — stored raw alongside the
        int8 pages (no bf16 round trip; the scale bytes count toward the
        budget)."""
        if (k_scale is None) != (v_scale is None):
            # A half scale pair is corruption, not a servable block — and
            # it must never raise into the caller (PR-8 contract: the
            # store degrades, exceptions never escape into admission).
            with self._lock:
                self.corrupt_dropped += 1
            return False
        nbytes = int(k.nbytes) + int(v.nbytes)
        if k_scale is not None:
            nbytes += int(k_scale.nbytes) + int(v_scale.nbytes)
        if nbytes > self.capacity_bytes:
            return False
        with self._lock:
            if self._page_shape is None:
                self._page_shape = k.shape
                self._page_dtypes = (k.dtype, v.dtype)
                self._scale_shape = (None if k_scale is None
                                     else k_scale.shape)
            elif (k.shape != self._page_shape or v.shape != k.shape
                  or (k.dtype, v.dtype) != self._page_dtypes
                  or (k_scale is None) != (self._scale_shape is None)
                  or (k_scale is not None
                      and (k_scale.shape != self._scale_shape
                           or v_scale is None
                           or v_scale.shape != self._scale_shape))):
                self.corrupt_dropped += 1
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self.used_bytes -= old.nbytes
            while self._entries and self.used_bytes + nbytes > self.capacity_bytes:
                _, ev = self._entries.popitem(last=False)
                self.used_bytes -= ev.nbytes
                self.evicted_blocks += 1
            self._entries[key] = HostBlock(tokens=tokens, k=k, v=v,
                                           nbytes=nbytes, k_scale=k_scale,
                                           v_scale=v_scale)
            self.used_bytes += nbytes
            self.saved_blocks += 1
            return True

    # statics: thread(scrape)
    def stats(self) -> dict:
        """Store-level stats under the metric key names. These describe the
        ONE (possibly pool-shared) store — EnginePool.kv_stats reports them
        once instead of summing per replica."""
        with self._lock:
            return {
                "host_cache_used_bytes": self.used_bytes,
                "host_cache_capacity_bytes": self.capacity_bytes,
                "host_cache_entries": len(self._entries),
                "host_cache_saved_blocks": self.saved_blocks,
                "host_cache_evicted_blocks": self.evicted_blocks,
                "host_cache_corrupt_dropped": self.corrupt_dropped,
                "host_cache_invalidated_blocks": self.invalidated_blocks,
            }


def host_store_from_gb(host_cache_gb: float) -> Optional[HostKVStore]:
    """ServerConfig/EngineConfig knob -> store (None when the knob is 0,
    which keeps every existing path bit-identical)."""
    if not host_cache_gb or host_cache_gb <= 0:
        return None
    return HostKVStore(int(host_cache_gb * 1e9))
