"""Deterministic, knob-driven fault injection for the serving plane.

Every robustness behavior the round-9 fault-tolerant serving plane adds —
per-batch dispatch failure isolation (engine), host-tier restore fallback
(engine + kv_offload), replica quarantine and retry-once failover
(replica_pool) — is only trustworthy if it is *testable on CPU in tier-1*
and soak-testable under load. Real TPUs fail rarely and unreproducibly;
this module makes failure a first-class, seeded input instead.

`LLM_FAULT_SPEC` compiles a spec string into named fault points consulted
at the three call sites the robustness plane hardens:

    dispatch_error:p=0.05    engine device-dispatch sites (prefill, chunk,
                             hybrid, decode) raise InjectedFault with
                             probability p BEFORE the runner call — i.e.
                             before any donated buffer is consumed, so the
                             recovery path under test is the real one
    restore_error:p=0.1      host-tier restore application fails with
                             probability p, exercising the recompute
                             fallback (engine._apply_pending_restore)
    slow_replica:idx=1,ms=200  replica `idx`'s step loop sleeps `ms` before
                             every dispatch (replica_pool wiring) — the
                             stuck/degraded-replica shape health routing
                             and the watchdog must absorb
    migrate_error:p=0.2      a live-migration checkpoint
                             (engine.checkpoint_request) fails with
                             probability p BEFORE any state capture or
                             teardown, exercising the degrade path: the
                             stream falls back to the round-9 kill path
                             (structured ERROR terminal) instead of
                             migrating

Grammar: `point[:k=v[,k=v...]][;point...]` — semicolon-separated points,
comma-separated key=value params, numbers parsed as float (int when
integral). Unknown point names or malformed params raise at compile time
(a typo'd chaos spec silently injecting nothing would "pass" every chaos
run). `p` defaults to 1.0 when a probabilistic point is named bare.

Determinism: each point draws from its OWN `random.Random(seed ^
crc(name))` stream, so two runs with the same spec, seed and dispatch
sequence inject the exact same fault pattern — the chaos suite
(tests/test_faults.py) pins this, and the identity gate in
scripts/dev/chaos_ab.py depends on it. Seed comes from `LLM_FAULT_SEED`
(+ replica index under a pool, so replicas don't fault in lockstep).

Cost when off: the engine/pool hold no injector at all (`_faults is
None`, the same contract as the step-clock recorder), so the hot path is
byte-identical with the knob unset.
"""

from __future__ import annotations

import random
import zlib
from typing import Optional

#: the complete set of compile-time-valid fault point names.
FAULT_POINTS = ("dispatch_error", "restore_error", "slow_replica",
                "migrate_error")


class InjectedFault(RuntimeError):
    """Raised by a firing fault point; carries the point name so handlers
    and tests can attribute the failure."""

    def __init__(self, point: str) -> None:
        super().__init__(f"injected fault: {point}")
        self.point = point


def _parse_value(raw: str) -> float:
    v = float(raw)
    return int(v) if v.is_integer() else v


def parse_fault_spec(spec: str) -> dict[str, dict]:
    """`"a:p=0.05;b:idx=1,ms=200"` -> `{"a": {"p": 0.05}, "b": {...}}`.

    Raises ValueError on unknown points, malformed params, or
    out-of-range probabilities — loud at compile, never at fire time.
    """
    points: dict[str, dict] = {}
    for part in filter(None, (s.strip() for s in spec.split(";"))):
        name, _, rest = part.partition(":")
        name = name.strip()
        if name not in FAULT_POINTS:
            raise ValueError(
                f"unknown fault point {name!r} in LLM_FAULT_SPEC; "
                f"supported: {', '.join(FAULT_POINTS)}")
        params: dict = {}
        for kv in filter(None, (s.strip() for s in rest.split(","))):
            key, sep, raw = kv.partition("=")
            if not sep:
                raise ValueError(
                    f"malformed fault param {kv!r} for {name!r} "
                    f"(expected key=value)")
            try:
                params[key.strip()] = _parse_value(raw.strip())
            except ValueError:
                raise ValueError(
                    f"non-numeric fault param {kv!r} for {name!r}") from None
        if name in ("dispatch_error", "restore_error", "migrate_error"):
            p = params.setdefault("p", 1.0)
            if not 0.0 <= p <= 1.0:
                raise ValueError(
                    f"fault point {name!r} needs 0 <= p <= 1, got {p}")
        if name == "slow_replica":
            if "ms" not in params:
                raise ValueError("slow_replica needs ms=<delay>")
            params.setdefault("idx", 0)
        points[name] = params
    return points


class FaultInjector:
    """Compiled fault points with per-point seeded RNG streams."""

    def __init__(self, points: dict[str, dict], seed: int = 0) -> None:
        self.points = dict(points)
        self.seed = int(seed)
        self._rng = {
            name: random.Random(self.seed ^ zlib.crc32(name.encode()))
            for name in self.points
        }
        # Fired-count accounting per point: the chaos suite's "every
        # injected fault is accounted for" gate reads these.
        self.fired: dict[str, int] = {name: 0 for name in self.points}

    @classmethod
    def from_spec(cls, spec: Optional[str],
                  seed: int = 0) -> Optional["FaultInjector"]:
        """Compile a spec string; None/empty -> None (no injector exists,
        the zero-cost off state)."""
        if not spec:
            return None
        return cls(parse_fault_spec(spec), seed=seed)

    def fire(self, point: str) -> bool:
        """Draw the point's RNG; True = inject now. Unconfigured points
        never fire and never draw (the configured points' streams stay
        aligned regardless of which sites consult the injector)."""
        params = self.points.get(point)
        if params is None:
            return False
        if self._rng[point].random() < params.get("p", 1.0):
            self.fired[point] += 1
            return True
        return False

    def maybe_raise(self, point: str) -> None:
        if self.fire(point):
            raise InjectedFault(point)

    def delay_s(self, idx: int) -> float:
        """slow_replica delay for replica `idx` (0.0 for everyone else)."""
        params = self.points.get("slow_replica")
        if params is None or int(params.get("idx", 0)) != idx:
            return 0.0
        return float(params["ms"]) / 1000.0
