"""Request/sampling datatypes shared by scheduler, engine and serving layer."""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Optional

from agentic_traffic_testing_tpu.runtime.block_allocator import SequenceBlocks


@dataclasses.dataclass
class SamplingParams:
    """Per-request sampling knobs (reference default is near-greedy
    temperature 0.2 — reference: llm/serve_llm.py:379,522)."""

    max_tokens: int = 512
    temperature: float = 0.2
    top_k: int = 0          # <= 0 disables
    top_p: float = 1.0      # >= 1 disables
    seed: int = 0
    stop_token_ids: tuple[int, ...] = ()
    ignore_eos: bool = False
    # Per-request SLO class overrides for the step-clock telemetry plane
    # (runtime/telemetry.py): TTFT / mean-ITL caps in milliseconds. None
    # falls back to the engine-level LLM_SLO_TTFT_MS / LLM_SLO_ITL_MS
    # knobs; only read when LLM_STEP_TRACE is on (no recorder, no SLO
    # accounting). Never touches sampling math or the device arrays.
    slo_ttft_ms: Optional[float] = None
    slo_itl_ms: Optional[float] = None
    # Per-request completion deadline in milliseconds (wall clock from
    # arrival; the robustness plane's abort budget — engine step sweeps
    # expire queued AND running requests past it through the abort path,
    # FinishReason.DEADLINE). None falls back to the engine-level
    # LLM_DEADLINE_MS knob; 0/unset there means no deadline at all, which
    # keeps every path cost-free (the engine tracks no deadline set).
    deadline_ms: Optional[float] = None


class RequestState(enum.Enum):
    WAITING = "waiting"
    RUNNING = "running"
    PREEMPTED = "preempted"
    FINISHED = "finished"
    ABORTED = "aborted"


class FinishReason(enum.Enum):
    STOP = "stop"          # hit an EOS/stop token
    LENGTH = "length"      # max_tokens or max_model_len
    ABORT = "abort"
    ERROR = "error"        # unservable, or a dispatch failed under it
    DEADLINE = "deadline"  # request deadline expired (queued or running)
    SHED = "shed"          # rejected at admission (bounded queue)
    # Internal terminal: the request was checkpointed for live migration
    # (engine.checkpoint_request) and its MigrationPlan rides
    # `request.migration`. The replica pool adopts it on a survivor and
    # NEVER surfaces this reason to a client — a plan nobody adopts is
    # converted to ERROR.
    MIGRATED = "migrated"


@dataclasses.dataclass(eq=False)  # identity semantics: a request is not its field values
class Request:
    """One generation request moving through the continuous batch."""

    request_id: str
    prompt_ids: list[int]
    sampling: SamplingParams
    arrival_time: float = dataclasses.field(default_factory=time.monotonic)

    state: RequestState = RequestState.WAITING
    output_ids: list[int] = dataclasses.field(default_factory=list)
    blocks: Optional[SequenceBlocks] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    finish_reason: Optional[FinishReason] = None
    error: Optional[str] = None
    # Scheduling bookkeeping
    num_preemptions: int = 0
    # Prompt tokens already prefilled into the KV pool (chunked prefill:
    # advances chunk by chunk; == num_prompt_tokens once decodable).
    num_computed_tokens: int = 0
    # Memoized (prompt_len, chain_keys) for prefix caching — see
    # block_allocator.request_chain_keys.
    prefix_keys_cache: Optional[tuple] = None
    # Host-tier restore plan (list of kv_offload.RestoreBlock) attached at
    # admission and applied by the engine right before the first suffix
    # chunk dispatches; cleared on apply and on release (an unapplied plan
    # refers to blocks that went back to the free list).
    pending_restore: Optional[list] = None
    # Total tokens sampled so far, *surviving preemption* (preemption folds
    # output_ids back into prompt_ids; sampling keys use (seed, sampling_step)
    # so the regenerated continuation stays reproducible).
    sampling_step: int = 0
    # Absolute monotonic instant after which the request must be aborted
    # (None = no deadline). Stamped by the engine at add_request from
    # sampling.deadline_ms / the LLM_DEADLINE_MS default.
    deadline: Optional[float] = None
    # Live-migration checkpoint (runtime/scheduler.MigrationPlan), attached
    # by engine.checkpoint_request to the MIGRATED terminal event so the
    # replica pool can resume the stream on a survivor. None everywhere
    # else; never serialized to a client.
    migration: Optional[object] = None
    # Checkpoints this stream has already been through (set by
    # engine.adopt_request from the plan; feeds the next plan's hop
    # count so the pool's migration bound survives re-checkpoints).
    migration_hops: int = 0
    # Waiting-queue depth of the OWNING replica at enqueue (stamped by
    # scheduler.add_request). The serving layer's per-slot wait EWMA
    # divides the measured queue wait by this — it must be the depth the
    # request actually waited behind, not the pool-minimum the admission
    # pre-check reads (a round-robin route to a deeper replica would
    # otherwise inflate the EWMA and shed spuriously).
    depth_at_enqueue: int = 0

    def __post_init__(self) -> None:
        # Preemption folds generated tokens into prompt_ids for recompute
        # (scheduler.py); the user-visible boundary stays fixed here.
        self.num_orig_prompt_tokens = len(self.prompt_ids)

    @property
    def generated_ids(self) -> list[int]:
        """All tokens generated for this request, surviving preemption."""
        return self.prompt_ids[self.num_orig_prompt_tokens:] + self.output_ids

    @property
    def num_prompt_tokens(self) -> int:
        return len(self.prompt_ids)

    @property
    def total_len(self) -> int:
        return len(self.prompt_ids) + len(self.output_ids)

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.arrival_time

    def is_finished(self) -> bool:
        return self.state in (RequestState.FINISHED, RequestState.ABORTED)

    @property
    def is_prefilling(self) -> bool:
        """Mid-chunked-prefill: holds KV blocks but is not yet decodable."""
        return (self.state is RequestState.RUNNING
                and self.num_computed_tokens < self.num_prompt_tokens)
