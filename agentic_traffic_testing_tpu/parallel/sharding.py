"""Tensor-parallel sharding specs for the Llama parameter/cache pytrees.

Megatron-style TP expressed as `PartitionSpec`s and left to XLA's SPMD
partitioner (the scaling-book recipe: annotate, compile, let XLA insert the
collectives over ICI). This replaces the NCCL tensor parallelism the reference
delegates to vLLM (reference: llm/config/llama-3.1-8b.yaml:2,7-9; SURVEY.md §2.2).

Layout (param schema from models/llama.py:init_params, stacked [L, ...]):
    wq/wk/wv  [L, D, Hhd]  column-parallel -> shard output dim on `tp`
    wo        [L, Hhd, D]  row-parallel    -> shard input  dim on `tp`
                            (XLA inserts the all-reduce after x @ wo)
    w_gate/up [L, D, F]    column-parallel
    w_down    [L, F, D]    row-parallel
    norms     [·, D]       replicated
    tok_embed [V, D]       D-sharded (the token gather stays chip-local;
                            XLA all-gathers the small [B,T,D] activations)
    unembed   [D, V]       V-sharded -> logits arrive V-sharded; sampling's
                            argmax/sort reductions run as XLA collectives
    KV cache  [L, KH, nb, bs, hd] shard KV heads on `tp`

Constraint: tp must divide num_kv_heads (KV-head sharding) and num_heads.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.parallel.mesh import AXIS_EP, AXIS_SP, AXIS_TP
from agentic_traffic_testing_tpu.runtime.kv_cache import KVCache


def validate_tp(cfg: ModelConfig, tp: int) -> None:
    if tp <= 1:
        return
    if cfg.num_kv_heads % tp or cfg.num_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_heads={cfg.num_heads} and "
            f"num_kv_heads={cfg.num_kv_heads} ({cfg.name})"
        )


def param_pspecs(cfg: ModelConfig) -> dict:
    """PartitionSpec pytree matching init_params(cfg)'s structure."""
    layers = {
        "ln_attn": P(None, None),
        "ln_mlp": P(None, None),
        "wq": P(None, None, AXIS_TP),
        "wk": P(None, None, AXIS_TP),
        "wv": P(None, None, AXIS_TP),
        "wo": P(None, AXIS_TP, None),
    }
    if cfg.num_experts:
        # Expert parallelism is a sharding of the expert axis; the MoE
        # dispatch/combine einsums (models/moe.py) become GSPMD all-to-alls.
        # Each expert's SwiGLU keeps the Megatron column/row split on tp.
        layers.update({
            "w_router": P(None, None, None),
            "w_gate": P(None, AXIS_EP, None, AXIS_TP),
            "w_up": P(None, AXIS_EP, None, AXIS_TP),
            "w_down": P(None, AXIS_EP, AXIS_TP, None),
        })
    else:
        layers.update({
            "w_gate": P(None, None, AXIS_TP),
            "w_up": P(None, None, AXIS_TP),
            "w_down": P(None, AXIS_TP, None),
        })
    if cfg.qkv_bias:
        layers["bq"] = P(None, AXIS_TP)
        layers["bk"] = P(None, AXIS_TP)
        layers["bv"] = P(None, AXIS_TP)
    specs: dict = {
        "tok_embed": P(None, AXIS_TP),
        "layers": layers,
        "final_norm": P(None),
        "unembed": P(None, AXIS_TP),
    }
    return specs


def kv_cache_pspecs() -> KVCache:
    spec = P(None, AXIS_TP, None, None, None)
    return KVCache(k=spec, v=spec)


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """device_put a pytree onto the mesh under the given PartitionSpecs."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs,
        is_leaf=lambda x: x is None,
    )


def _qtensor_spec(spec: P, rank: int, cls) -> Any:
    """Expand a weight's PartitionSpec to its quantized (q|packed, scale) pair.

    int8/int4 quantization is per-output-channel over the contraction dim
    (models/quant.py: scale shape = weight shape with dim -2 collapsed to 1
    for int8, or to 2 half-rows for int4 — either way size-independent of
    the weight's contraction dim), so the scale inherits the weight's spec
    except that its contraction axis must stay unsharded. Column-parallel
    weights therefore get tp-sharded scales; row-parallel weights get
    replicated scales — and the q @ x partials are scaled AFTER the
    psum-of-partials, which is exact because the per-channel scale is
    constant across the contraction shards. The int4 packed array keeps the
    weight's spec unchanged (N -> N/2 preserves the axis; grouped packing —
    quantize_params int4_groups — makes the N/2 shards logically
    contiguous)."""
    full = tuple(spec) + (None,) * (rank - len(spec))
    kw = "q" if cls.__name__ == "QTensor" else "packed"
    return cls(**{kw: P(*full)}, scale=P(*full[:-2], None, full[-1]))


def _qtensor4_grouped_spec(spec: P, rank: int, groups: int) -> Any:
    """QTensor4 with K-group-wise scales [..., Gk, 2, N/2]: the group axis
    sits where K sat, so it inherits K's sharding (row-parallel leaves
    shard it; column-parallel leaves leave it replicated). `groups` mirrors
    the param leaf's packing aux so the spec tree's treedef matches."""
    from agentic_traffic_testing_tpu.models.quant import QTensor4

    full = tuple(spec) + (None,) * (rank - len(spec))
    return QTensor4(packed=P(*full),
                    scale=P(*full[:-1], None, full[-1]),
                    groups=groups)


def expand_quant_specs(params: Any, specs: Any) -> Any:
    """Replace specs of quantized params with per-leaf (q, scale) specs."""
    from agentic_traffic_testing_tpu.models.quant import QTensor, QTensor4

    def rec(p, s):
        if isinstance(p, QTensor4) and p.scale.ndim == p.packed.ndim + 1:
            return _qtensor4_grouped_spec(s, p.packed.ndim, p.groups)
        if isinstance(p, QTensor4):
            out = _qtensor_spec(s, p.packed.ndim, QTensor4)
            out.groups = p.groups   # mirror packing aux: treedefs must match
            return out
        if isinstance(p, QTensor):
            return _qtensor_spec(s, p.q.ndim, QTensor)
        if isinstance(p, dict):
            return {k: rec(p[k], s[k]) for k in p}
        return s

    return rec(params, specs)


def wrap_int4_tp(params: Any, mesh: Mesh) -> Any:
    """Wrap sharded QTensor4 matmul leaves in QTensor4TP (models/quant.py).

    Gives each leaf the static TP context (col/row kind + mesh + axis) that
    routes dense() through the shard_map int4-kernel path — the GSPMD
    partitioner cannot partition a pallas_call. tok_embed stays a plain
    QTensor4: its gather+unpack is ordinary XLA, which GSPMD partitions
    globally (grouping irrelevance: it is never locally reinterpreted).
    """
    from agentic_traffic_testing_tpu.models.quant import (
        TP_KIND,
        QTensor4,
        QTensor4TP,
    )

    # On a composed (sp, tp) mesh the matmul may additionally shard the
    # activation's token dim over sp (decided per call site by shape —
    # models/quant._dense4_tp).
    sp_axis = AXIS_SP if dict(mesh.shape).get(AXIS_SP, 1) > 1 else None

    def wrap(key: str, leaf: Any) -> Any:
        kind = TP_KIND.get(key)
        if kind is None or not isinstance(leaf, QTensor4):
            return leaf
        # Expert stacks ([L, E, K, N/2] — one leading axis more than a
        # dense stack's [L, K, N/2]) carry the ep axis; models/moe.py
        # routes them through the expert-scan shard_map.
        ep_axis = AXIS_EP if leaf.packed.ndim == 4 else None
        return QTensor4TP(leaf.packed, leaf.scale, kind, mesh, AXIS_TP,
                          sp_axis=sp_axis, ep_axis=ep_axis,
                          groups=leaf.groups)

    out = {k: wrap(k, v) for k, v in params.items() if k != "layers"}
    out["layers"] = {k: wrap(k, v) for k, v in params["layers"].items()}
    return out


def wrap_int4_replicated(params: Any, mesh: Mesh) -> Any:
    """Guarded int4 wrap for runners that REPLICATE weights over the mesh
    (sp-only serving): each chip keeps the full packed tensors, wrapped in
    QTensor4TP over the size-1 tp axis so the matmul runs the kernel under
    shard_map (with the prefill activation's token dim sp-sharded by shape
    — models/quant._dense4_tp).

    Replication (not weight sharding) is a deliberate design for sp-only
    meshes, not a gap. sp-only presumes the model fits one chip — the 8B
    int4 profile is ~4 GiB of a 16 GiB v5e, leaving ~11 GiB of KV pages
    per chip either way, because per-chip HBM (not pod-total bytes) is
    the serving constraint. Sharding weights over sp (ZeRO-3 style) would
    save 3 GiB/chip at sp=4 but turn every decode step's weight read into
    an ICI all-gather: ~45-90 GB/s per v5e link vs the ~700 GB/s measured
    HBM stream (docs/BENCHMARKS.md decode anatomy) — an order of
    magnitude off the weight-streaming bound that decode lives on. Models
    that need sharding to FIT take the sp x tp mesh (SPTPRunner), where
    int4 shards for real under the grouped-packing contract.

    int4 x MoE x sp (round 5, the matrix's last refusal lifted): expert
    stacks wrap like everything else — QTensor4TP with ep_axis over the
    SIZE-1 ep axis — and the expert scan runs under
    models/moe._expert_dense4_tp's shard_map with both weight axes sized
    1: each sp chip keeps the full expert stacks and computes the expert
    MLP replicated (the dispatch einsum's sp-sharded input is gathered at
    the shard_map boundary). Ring attention still carries the sp win;
    the MoE MLP is replicated compute, same as decode — documented, not
    silent. TP-packed leaves (groups > 1) are likewise ACCEPTED as of
    round 5: the wrap propagates the packing aux and the matmul decodes
    grouped layouts per contiguous group (models/quant._dense4), so a
    tp-packed checkpoint serves on an sp mesh without repacking.
    """
    from agentic_traffic_testing_tpu.models.quant import QTensor4

    leaves = list(params["layers"].items()) + [
        ("unembed", params.get("unembed"))]
    if not any(isinstance(l, QTensor4) for _, l in leaves):
        return params
    return wrap_int4_tp(params, mesh)


def shard_params(params: Any, cfg: ModelConfig, mesh: Mesh,
                 int4_groups: Optional[int] = None) -> Any:
    """Shard a param tree for the mesh; quantized leaves expand their specs.

    `int4_groups` is the caller's attestation of how int4 column-parallel
    leaves were packed (quantize_params' int4_groups). Sharding ungrouped
    packing over tp chips silently decodes garbage (the lo/hi nibble
    pairing crosses shard boundaries) — so when int4 leaves meet a tp>1
    mesh, the attestation is REQUIRED and must equal the tp degree. Leaves
    that RECORD their packing (QTensor4.groups aux; random-init leaves are
    layout-free and record 1) are additionally cross-checked against it.
    """
    from agentic_traffic_testing_tpu.models.quant import TP_KIND, QTensor4

    validate_tp(cfg, mesh.shape[AXIS_TP])
    tp = mesh.shape[AXIS_TP]
    has_int4 = any(isinstance(l, QTensor4)
                   for l in list(params["layers"].values())
                   + [params.get("unembed")])
    if tp > 1 and has_int4 and int4_groups != tp:
        raise ValueError(
            f"int4 x TP requires grouped packing: quantize with "
            f"quantize_params(..., scheme='int4', int4_groups={tp}) (or "
            f"init_params_quantized, whose random packing is layout-free) "
            f"and pass int4_groups={tp} to shard_params/TPRunner — got "
            f"int4_groups={int4_groups!r}")
    for key, leaf in list(params["layers"].items()) + [
            ("unembed", params.get("unembed")),
            ("tok_embed", params.get("tok_embed"))]:
        if not isinstance(leaf, QTensor4) or leaf.groups == 1:
            continue
        # Recorded packing must agree with the target layout when the
        # weight is actually SHARDED: a groups=g byte layout splits into
        # exactly g contiguous column shards, so on a tp>1 mesh it must be
        # a column-parallel leaf with groups == tp. On tp=1 meshes (single
        # chip, sp-only replication) grouped leaves are fine — the global
        # matmul path decodes them per contiguous group (round 5,
        # models/quant._dense4), so tp-packed checkpoints serve without
        # repacking.
        if tp > 1 and (TP_KIND.get(key) != "col" or leaf.groups != tp):
            raise ValueError(
                f"param {key!r} is int4-packed with groups={leaf.groups}, "
                f"which cannot be served on a tp={tp} mesh — repack with "
                f"quantize_params(..., int4_groups={tp})")
    specs = expand_quant_specs(params, param_pspecs(cfg))
    params = shard_pytree(params, specs, mesh)
    has_int4_experts = any(isinstance(l, QTensor4) and l.packed.ndim == 4
                           for l in params["layers"].values())
    # Wrap on tp>1 as before; ALSO on an ep-sharded mesh with int4 expert
    # stacks (tp may be 1): the expert scan is a pallas path GSPMD cannot
    # partition, so it must run under the expert shard_map
    # (models/moe.py _expert_dense4_tp) whenever its operands are sharded.
    if tp > 1 or (dict(mesh.shape).get(AXIS_EP, 1) > 1 and has_int4_experts):
        params = wrap_int4_tp(params, mesh)
    return params


def shard_kv_cache(cache: KVCache, mesh: Mesh) -> KVCache:
    return shard_pytree(cache, kv_cache_pspecs(), mesh)
