"""Tensor-parallel sharding specs for the Llama parameter/cache pytrees.

Megatron-style TP expressed as `PartitionSpec`s and left to XLA's SPMD
partitioner (the scaling-book recipe: annotate, compile, let XLA insert the
collectives over ICI). This replaces the NCCL tensor parallelism the reference
delegates to vLLM (reference: llm/config/llama-3.1-8b.yaml:2,7-9; SURVEY.md §2.2).

Layout (param schema from models/llama.py:init_params, stacked [L, ...]):
    wq/wk/wv  [L, D, Hhd]  column-parallel -> shard output dim on `tp`
    wo        [L, Hhd, D]  row-parallel    -> shard input  dim on `tp`
                            (XLA inserts the all-reduce after x @ wo)
    w_gate/up [L, D, F]    column-parallel
    w_down    [L, F, D]    row-parallel
    norms     [·, D]       replicated
    tok_embed [V, D]       D-sharded (the token gather stays chip-local;
                            XLA all-gathers the small [B,T,D] activations)
    unembed   [D, V]       V-sharded -> logits arrive V-sharded; sampling's
                            argmax/sort reductions run as XLA collectives
    KV cache  [L, KH, nb, bs, hd] shard KV heads on `tp`

Constraint: tp must divide num_kv_heads (KV-head sharding) and num_heads.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.parallel.mesh import AXIS_EP, AXIS_TP
from agentic_traffic_testing_tpu.runtime.kv_cache import KVCache


def validate_tp(cfg: ModelConfig, tp: int) -> None:
    if tp <= 1:
        return
    if cfg.num_kv_heads % tp or cfg.num_heads % tp:
        raise ValueError(
            f"tp={tp} must divide num_heads={cfg.num_heads} and "
            f"num_kv_heads={cfg.num_kv_heads} ({cfg.name})"
        )


def param_pspecs(cfg: ModelConfig) -> dict:
    """PartitionSpec pytree matching init_params(cfg)'s structure."""
    layers = {
        "ln_attn": P(None, None),
        "ln_mlp": P(None, None),
        "wq": P(None, None, AXIS_TP),
        "wk": P(None, None, AXIS_TP),
        "wv": P(None, None, AXIS_TP),
        "wo": P(None, AXIS_TP, None),
    }
    if cfg.num_experts:
        # Expert parallelism is a sharding of the expert axis; the MoE
        # dispatch/combine einsums (models/moe.py) become GSPMD all-to-alls.
        # Each expert's SwiGLU keeps the Megatron column/row split on tp.
        layers.update({
            "w_router": P(None, None, None),
            "w_gate": P(None, AXIS_EP, None, AXIS_TP),
            "w_up": P(None, AXIS_EP, None, AXIS_TP),
            "w_down": P(None, AXIS_EP, AXIS_TP, None),
        })
    else:
        layers.update({
            "w_gate": P(None, None, AXIS_TP),
            "w_up": P(None, None, AXIS_TP),
            "w_down": P(None, AXIS_TP, None),
        })
    if cfg.qkv_bias:
        layers["bq"] = P(None, AXIS_TP)
        layers["bk"] = P(None, AXIS_TP)
        layers["bv"] = P(None, AXIS_TP)
    specs: dict = {
        "tok_embed": P(None, AXIS_TP),
        "layers": layers,
        "final_norm": P(None),
        "unembed": P(None, AXIS_TP),
    }
    return specs


def kv_cache_pspecs() -> KVCache:
    spec = P(None, AXIS_TP, None, None, None)
    return KVCache(k=spec, v=spec)


def shard_pytree(tree: Any, specs: Any, mesh: Mesh) -> Any:
    """device_put a pytree onto the mesh under the given PartitionSpecs."""
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs,
        is_leaf=lambda x: x is None,
    )


def _qtensor_spec(spec: P, rank: int) -> "QTensor":
    """Expand a weight's PartitionSpec to its QTensor (q, scale) pair.

    int8 quantization is per-output-channel over the contraction dim
    (models/quant.py: scale shape = weight shape with dim -2 collapsed to 1),
    so the scale inherits the weight's spec except that its size-1
    contraction axis must stay unsharded. Column-parallel weights therefore
    get tp-sharded scales; row-parallel weights get replicated scales — and
    the q @ x partials are scaled AFTER the psum-of-partials XLA inserts,
    which is exact because the per-channel scale is constant across the
    contraction shards."""
    from agentic_traffic_testing_tpu.models.quant import QTensor

    full = tuple(spec) + (None,) * (rank - len(spec))
    return QTensor(q=P(*full), scale=P(*full[:-2], None, full[-1]))


def expand_quant_specs(params: Any, specs: Any) -> Any:
    """Replace specs of QTensor-valued params with per-leaf (q, scale) specs."""
    from agentic_traffic_testing_tpu.models.quant import QTensor

    def rec(p, s):
        if isinstance(p, QTensor):
            return _qtensor_spec(s, p.q.ndim)
        if isinstance(p, dict):
            return {k: rec(p[k], s[k]) for k in p}
        return s

    return rec(params, specs)


def shard_params(params: Any, cfg: ModelConfig, mesh: Mesh) -> Any:
    validate_tp(cfg, mesh.shape[AXIS_TP])
    specs = expand_quant_specs(params, param_pspecs(cfg))
    return shard_pytree(params, specs, mesh)


def shard_kv_cache(cache: KVCache, mesh: Mesh) -> KVCache:
    return shard_pytree(cache, kv_cache_pspecs(), mesh)
