"""Sequence-parallel serving runner: long-prompt prefill sharded over `sp`.

The reference testbed handles long context by truncation only (reference:
llm/serve_llm.py:812-844; SURVEY.md §5.7). Round 3 gave serving chunked
prefill (latency-bounded, single-chip) and training ring attention; this
runner closes the last box — SEQUENCE-PARALLEL SERVING PREFILL. The use
case: a prompt long enough that one chip's prefill latency (or its score
memory) is the bottleneck, on a pod where extra chips are available but
the model fits one chip (so TP buys nothing but collective overhead).

Design: prefill's attention site swaps to ring attention over the sp axis
(models/llama.prefill_impl attn_mode="ring_sp"): T sharded across chips,
O(T/sp) score memory each, KV shards rotating by `lax.ppermute` one ICI
hop per ring step. Every OTHER op in prefill is per-token math — GSPMD
shards it over T from the same input sharding for free, and the deferred
page write (T-sharded values into the replicated pool) becomes the one
all-gather, exactly the KV decode needs anyway. Decode is UNCHANGED: the
pool is replicated, every chip runs the identical decode program (decode
is weight-streaming-bound; sp was never its lever — docs/BENCHMARKS.md).

Token-exactness vs the single-device engine holds because ring attention
is exact causal attention (same softmax, f32 accumulation) and everything
else is the same jitted math — pinned by tests/test_parallel.py and
dryrun leg 3c (__graft_entry__.py).
"""

from __future__ import annotations

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.parallel.mesh import AXIS_SP, AXIS_TP
from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner
from agentic_traffic_testing_tpu.runtime.kv_cache import KVCache
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner


class SPPrefillRunner(ModelRunner):
    """Runner whose prefill runs ring attention over an `sp` mesh axis.

    Params and KV pool are replicated over the mesh (the model fits one
    chip by assumption — otherwise compose TP via SPTPRunner); only
    prefill activations are sequence-sharded. Decode runs replicated: the
    pallas DMA kernel has no GSPMD partitioning rule, so on TPU it rides
    the same shard_map wrapper TPRunner uses — here over the SIZE-1 tp
    axis (full heads per chip, replicated over sp) — and off-TPU the jnp
    gather path keeps CPU-mesh tests fast (ATT_TP_ATTENTION overrides for
    targeted interpret-mode tests).
    """

    kv_writer_mode = "dus"   # pallas writer has no GSPMD partitioning rule
    prefill_attn_mode = "ring_sp"
    # Round 5: the chunk jit rides the chunk-ring hybrid — the chunk's
    # token dim shards over sp while gathered prior pages (replicated pool)
    # seed each chip's streaming softmax (models/llama.prefill_chunk_impl,
    # ops/ring_attention.make_sp_chunk_attention). This is what makes
    # prefix caching compose with sp: cache-hit suffixes prefill sharded.
    # The server still zeroes prefill_chunk_tokens under sp (one sharded
    # long-prompt pass beats chunking there), but the path is faithful if
    # an operator chunks deliberately.
    chunk_attn_mode = "ring_sp"
    supports_chunked_prefill = True
    # No mesh wrapper for the ragged hybrid step (see TPRunner), nor for
    # the pipelined-prefill chunk jit, nor a donated-state decode jit for
    # the overlapped loop; engine refuses all three knobs at build.
    supports_hybrid = False
    supports_prefill_pipeline = False
    supports_decode_overlap = False
    # Nor for the scaled int8 pool / fused KV writes (see TPRunner).
    supports_quantized_kv = False
    supports_fused_kv_write = False
    # Nor per-block host slicing for live migration (see TPRunner).
    supports_migration = False

    def __init__(self, cfg: ModelConfig, params, mesh: Mesh,
                 decode_steps: int = 1, spec_tokens: int = 0,
                 spec_ngram: int = 3) -> None:
        from agentic_traffic_testing_tpu.parallel.tp_runner import (
            resolve_decode_attn_mode,
        )

        sp = mesh.shape[AXIS_SP]
        if sp < 2:
            raise ValueError(f"SPPrefillRunner needs an sp axis >= 2, got {sp}")
        self.mesh = mesh
        self.prefill_attn_mesh = mesh
        self.prefill_attn_axis = AXIS_SP
        mode = resolve_decode_attn_mode()
        self.attn_mode = mode
        if mode == "shard_dma":
            self.attn_mesh = mesh
            self.attn_axis = AXIS_TP
        params = jax.device_put(params, NamedSharding(mesh, P()))
        # int4 x sp-only (round 4): the pallas matmul cannot ride plain
        # GSPMD over the sp mesh, but the QTensor4TP shard_map wrapper
        # works with a SIZE-1 tp axis — each chip keeps the full packed
        # weight while the prefill activation's token dim shards over sp
        # (shape-gated, models/quant._dense4_tp). As of round 5 the wrap
        # covers EVERY int4 tree: MoE expert stacks route through the
        # expert shard_map with size-1 weight axes, and TP-packed
        # (groups>1) checkpoints decode per contiguous group. The config
        # this enables: 8B int4 (~4 GiB) fits one chip, sp divides a
        # long prompt.
        from agentic_traffic_testing_tpu.parallel.sharding import (
            wrap_int4_replicated,
        )

        params = wrap_int4_replicated(params, mesh)
        super().__init__(cfg, params, decode_steps=decode_steps,
                         spec_tokens=spec_tokens, spec_ngram=spec_ngram)

    @property
    def sp_size(self) -> int:
        return self.mesh.shape[AXIS_SP]

    def prepare_cache(self, cache: KVCache) -> KVCache:
        """Replicate the page pool (decode reads it whole on every chip)."""
        return jax.device_put(cache, NamedSharding(self.mesh, P()))


class SPTPRunner(TPRunner):
    """Tensor-parallel runner whose PREFILL additionally shards the
    sequence over an `sp` mesh axis (round-4 composition: the long-context
    profile for models that do NOT fit one chip).

    Layout on an (sp, tp) mesh: params and KV pool are tp-sharded exactly
    as in TPRunner (replicated over sp); prefill activations are
    T-sharded over sp with heads tp-sharded inside the ring adapter
    (ops/ring_attention.py make_sp_prefill_attention — the same head
    layout the training sp x tp step uses). Decode is TPRunner's path
    unchanged, with the sp groups running it redundantly (decode is
    weight-streaming-bound; sp buys nothing there and the redundancy
    costs no wall-clock). int4 composes too: the QTensor4TP shard_map
    carries the sp axis and shards the prefill activation's token dim by
    SHAPE at trace time (models/quant._dense4_tp), so the kernel keeps
    its tp-only weight layout while sp still divides the token work;
    the usual `int4_groups=tp` packing attestation applies.
    """

    prefill_attn_mode = "ring_sp"
    chunk_attn_mode = "ring_sp"   # chunk-ring hybrid, heads tp-sharded
    supports_chunked_prefill = True
    supports_prefill_pipeline = False  # see SPPrefillRunner
    supports_decode_overlap = False    # see SPPrefillRunner
    supports_quantized_kv = False      # see SPPrefillRunner
    supports_fused_kv_write = False    # see SPPrefillRunner
    supports_migration = False         # see SPPrefillRunner

    def __init__(self, cfg: ModelConfig, params, mesh: Mesh,
                 decode_steps: int = 1, spec_tokens: int = 0,
                 spec_ngram: int = 3, int4_groups=None) -> None:
        sp = mesh.shape[AXIS_SP]
        if sp < 2 or mesh.shape[AXIS_TP] < 2:
            raise ValueError(
                f"SPTPRunner needs sp >= 2 AND tp >= 2 (got sp={sp}, "
                f"tp={mesh.shape[AXIS_TP]}) — use TPRunner or "
                f"SPPrefillRunner for a single-axis mesh")
        self.prefill_attn_mesh = mesh
        self.prefill_attn_axis = AXIS_SP
        super().__init__(cfg, params, mesh, decode_steps=decode_steps,
                         spec_tokens=spec_tokens, spec_ngram=spec_ngram,
                         int4_groups=int4_groups)

    @property
    def sp_size(self) -> int:
        return self.mesh.shape[AXIS_SP]
