"""Multi-host bootstrap: `jax.distributed` over DCN, collectives over ICI.

The reference testbed's multi-node story is SSH + per-node docker compose
with NCCL confined inside vLLM (reference: scripts/deploy/deploy.sh:120-186;
SURVEY.md §2.4). The TPU equivalent is jax.distributed: every host in a
multi-host slice (or multi-slice deployment) runs the same program, calls
`initialize()` against a shared coordinator, and from then on
`jax.devices()` spans the whole fleet — a `Mesh` laid out over it routes
per-layer all-reduces over ICI within a slice and only crosses DCN on axes
that span slices (the scaling-book recipe).

Environment contract (mirrors the testbed's env-first config style,
SURVEY.md §5.6):

    ATT_COORDINATOR_ADDRESS   host:port of process 0 (unset -> single-host)
    ATT_NUM_PROCESSES         total process count
    ATT_PROCESS_ID            this process's index (0-based)
    ATT_LOCAL_DEVICE_IDS      optional comma list restricting local devices

On TPU pods all three can usually be omitted even when multi-host —
jax.distributed auto-discovers from the TPU runtime — so
`maybe_initialize()` also honors a bare ATT_MULTIHOST=1 switch.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger("att_tpu.distributed")

_initialized = False


def is_initialized() -> bool:
    return _initialized


def maybe_initialize() -> bool:
    """Initialize jax.distributed from the environment if configured.

    Returns True when running as part of a multi-process fleet. Safe to call
    more than once and from single-host runs (no-op there). Must run BEFORE
    the first touch of jax.devices() in the process.
    """
    global _initialized
    if _initialized:
        return True
    coord = os.environ.get("ATT_COORDINATOR_ADDRESS")
    auto = os.environ.get("ATT_MULTIHOST", "").lower() in ("1", "true", "yes")
    if not coord and not auto:
        return False

    import jax

    kwargs: dict = {}
    if coord:
        kwargs["coordinator_address"] = coord
        # num_processes/process_id are optional for jax on TPU pods (runtime
        # auto-detect); pass them only when the operator sets them so a
        # coordinator-only config still works.
        nproc = os.environ.get("ATT_NUM_PROCESSES")
        pid = os.environ.get("ATT_PROCESS_ID")
        if (nproc is None) != (pid is None):
            raise ValueError(
                "set both ATT_NUM_PROCESSES and ATT_PROCESS_ID (or neither "
                "for TPU-runtime auto-detect)")
        if nproc is not None:
            kwargs["num_processes"] = int(nproc)
            kwargs["process_id"] = int(pid)
    # Device restriction applies in auto-detect (ATT_MULTIHOST) mode too,
    # e.g. two processes per host each claiming half the chips.
    local = os.environ.get("ATT_LOCAL_DEVICE_IDS")
    if local:
        kwargs["local_device_ids"] = [int(x) for x in local.split(",")]
    jax.distributed.initialize(**kwargs)
    _initialized = True
    log.info(
        "jax.distributed up: process %d/%d, %d local / %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


def process_info() -> dict:
    """Identity block for logs/metrics (shape mirrors the testbed's
    node/agent identity fields, agents/common/telemetry.py)."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
        "distributed": _initialized,
    }


def global_mesh_devices(n: Optional[int] = None):
    """Devices for a fleet-wide mesh, ICI-contiguous first.

    `jax.devices()` on a multi-host slice orders by (process, local torus),
    which is exactly the layout `parallel.mesh.make_mesh` wants: the
    innermost mesh axis lands on same-host ICI neighbors, outer axes cross
    hosts (DCN) as rarely as possible.
    """
    import jax

    devices = jax.devices()
    if n is None:
        return devices
    if not 1 <= n <= len(devices):
        raise ValueError(f"need 1 <= n <= {len(devices)}, got {n}")
    return devices[:n]
