"""Pipeline-parallel SERVING runner: layer stages over the `pp` mesh axis.

Why this exists (and when to use it): docs/architecture_diagrams/
serving_stack.md's round-5 ADR shows tp x sp dominates pp on every
serving metric on a v5e pod — PP decodes one request stream at 1/P chip
utilization by construction. What PP uniquely buys is CAPACITY without
constraints: L/P weight layers AND L/P KV-cache layers per chip, with no
KV-head-divisibility requirement (TP's binding constraint past tp=8 on
Llama-70B's 8 KV heads) and no interconnect-bandwidth exposure on the
decode path beyond one [B, D] activation hop per stage. This runner is
that capacity escape hatch, shipped and token-exact; the ADR's latency
math is unchanged and documented honestly below.

Execution model (phase loop, not GPipe): serving steps are latency-bound
single passes, so the schedule is P sequential phases inside one
`jax.shard_map` over `pp`. At phase j, chip j holds the REAL activation
and applies its local layer stack; a `ppermute` hands the output one hop
along the ring. Every chip runs every phase in SPMD lockstep (inactive
phases compute on garbage — the wall-clock equals the idle bubble either
way), so per-token latency equals the FULL layer stack (single-chip
latency + P activation hops): PP here scales capacity, never speed. KV
writes during inactive phases route to the trash block
(`write_decode_kv_full(valid=...)`), and each chip banks prompt KV only
from its own real phase, so the pp-sharded pool (cache layer axis
`P('pp')`) only ever holds real pages.

No contraction is split across chips (unlike TP's row-parallel psum), so
outputs are BIT-identical to the single-chip engine — pinned token-exact
by tests/test_parallel.py and dryrun leg 6 (__graft_entry__.py).

The reference has no pipeline parallelism anywhere (vLLM-internal only,
never configured — SURVEY.md §2.3); serving-PP goes past the training
GPipe stack (parallel/pipeline.py) that round 2 shipped.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.models.llama import (
    _mlp_block,
    _prefill_layer_body,
    _qkv,
    _unembed,
)
from agentic_traffic_testing_tpu.ops.attention_backend import (
    paged_decode_attention,
)
from agentic_traffic_testing_tpu.ops.flash_prefill import prefill_attention
from agentic_traffic_testing_tpu.ops.jnp_ops import (
    apply_rope,
    rms_norm,
    rope_sin_cos,
)
from agentic_traffic_testing_tpu.ops.kv_writer import write_prompt_pages
from agentic_traffic_testing_tpu.parallel.mesh import AXIS_PP
from agentic_traffic_testing_tpu.parallel.pipeline import pp_param_pspecs
from agentic_traffic_testing_tpu.parallel.sharding import shard_pytree
from agentic_traffic_testing_tpu.runtime import kv_cache as kvc
from agentic_traffic_testing_tpu.runtime.kv_cache import KVCache
from agentic_traffic_testing_tpu.ops.sampling import make_row_keys, sample
from agentic_traffic_testing_tpu.runtime.runner import (
    DecodeState,
    ModelRunner,
    SamplingArrays,
)


def _ring_perm(pp: int):
    return [(i, (i + 1) % pp) for i in range(pp)]


def pp_prefill_impl(params, cfg: ModelConfig, tokens, cache: KVCache,
                    block_tables, seq_lens, mesh: Mesh):
    """Staged prefill. tokens [B, T] -> (last-token logits [B, V] f32,
    updated pp-sharded cache). Each chip banks its own stage's prompt KV
    (taken from its real phase) and bulk-writes it into its local layer
    slice of the pool."""
    b, t = tokens.shape
    if t % cache.block_size != 0:
        raise ValueError(
            f"prefill length {t} not a multiple of block_size "
            f"{cache.block_size}")
    pp = mesh.shape[AXIS_PP]
    positions = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    from agentic_traffic_testing_tpu.models.quant import embed_lookup

    x = embed_lookup(params["tok_embed"], tokens,
                     dtype=params["final_norm"].dtype)
    sin, cos = rope_sin_cos(positions, cfg.head_dim_, cfg.rope_theta,
                            cfg.rope_scaling)

    def attn_site(q, k, v, li):
        return prefill_attention(q, k, v, q_positions=positions,
                                 kv_valid_len=seq_lens)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS_PP), P(), P(AXIS_PP), P(AXIS_PP), P()),
        out_specs=(P(), P(AXIS_PP), P(AXIS_PP)),
        check_vma=False,
    )
    def staged(local_layers, x0, kc, vc, tables):
        p = jax.lax.axis_index(AXIS_PP)
        local_cache = KVCache(kc, vc)
        n_local = kc.shape[0]

        def run_stage(x):
            def body(x, xs):
                lp, li = xs
                return _prefill_layer_body(x, lp, li, cfg, sin, cos,
                                           attn_site, local_cache)
            return jax.lax.scan(
                body, x,
                (local_layers, jnp.arange(n_local, dtype=jnp.int32)))

        x_held = x0
        ks_bank = vs_bank = None
        for j in range(pp):
            y, (ks, vs) = run_stage(x_held)
            # Bank this phase's KV only on the chip whose REAL phase it is;
            # phase 0 seeds the bank (any chip's j=0 values are overwritten
            # by its own phase p before the loop ends).
            keep = p == jnp.int32(j)
            ks_bank = jnp.where(keep, ks, ks if ks_bank is None else ks_bank)
            vs_bank = jnp.where(keep, vs, vs if vs_bank is None else vs_bank)
            x_held = jax.lax.ppermute(y, AXIS_PP, _ring_perm(pp))
        # After P phases the finished activation sits on chip 0; everyone
        # else contributes zeros so one psum replicates it.
        x_fin = jax.lax.psum(
            jnp.where(p == 0, x_held, jnp.zeros_like(x_held)), AXIS_PP)
        kc, vc = write_prompt_pages(kc, vc, ks_bank, vs_bank, tables,
                                    mode="dus")
        return x_fin, kc, vc

    x, kc, vc = staged(params["layers"], x, cache.k, cache.v, block_tables)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    last = jnp.take_along_axis(
        x, jnp.maximum(seq_lens - 1, 0)[:, None, None], axis=1)[:, 0]
    return _unembed(last[:, None, :], params, cfg)[:, 0], KVCache(kc, vc)


def pp_decode_step_impl(params, cfg: ModelConfig, tokens, cache: KVCache,
                        block_tables, positions, mesh: Mesh):
    """One staged decode step. tokens [B] -> (logits [B, V] f32, cache).
    Mirrors verify_step_impl's S=1 layer body; inactive phases' KV writes
    route to the trash block so only the owning chip's real phase lands."""
    b = tokens.shape[0]
    pp = mesh.shape[AXIS_PP]
    pos_grid = positions[:, None]                                # [B, 1]
    from agentic_traffic_testing_tpu.models.quant import dense, embed_lookup

    x = embed_lookup(params["tok_embed"], tokens[:, None],
                     dtype=params["final_norm"].dtype)            # [B, 1, D]
    sin, cos = rope_sin_cos(pos_grid, cfg.head_dim_, cfg.rope_theta,
                            cfg.rope_scaling)
    capacity = block_tables.shape[1] * cache.block_size

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(AXIS_PP), P(), P(AXIS_PP), P(AXIS_PP), P()),
        out_specs=(P(), P(AXIS_PP), P(AXIS_PP)),
        check_vma=False,
    )
    def staged(local_layers, x0, kc, vc, tables):
        p = jax.lax.axis_index(AXIS_PP)
        n_local = kc.shape[0]

        def run_stage(x, kc, vc, active):
            def body(carry, xs):
                x, kc, vc = carry
                lp, li = xs
                xa = rms_norm(x, lp["ln_attn"], cfg.rms_norm_eps)
                q, k, v = _qkv(xa, lp, cfg)
                q = apply_rope(q, sin, cos)
                k = apply_rope(k, sin, cos)
                ok = (positions < capacity) & active
                kc = kvc.write_decode_kv_full(kc, li, k[:, 0], tables,
                                              positions, valid=ok)
                vc = kvc.write_decode_kv_full(vc, li, v[:, 0], tables,
                                              positions, valid=ok)
                attn = paged_decode_attention(q, kc, vc, tables, positions,
                                              layer=li)
                x = x + dense(attn.reshape(b, 1, -1), lp["wo"])
                xm = rms_norm(x, lp["ln_mlp"], cfg.rms_norm_eps)
                y, _ = _mlp_block(xm, lp, cfg)
                return (x + y, kc, vc), None

            (x, kc, vc), _ = jax.lax.scan(
                body, (x, kc, vc),
                (local_layers, jnp.arange(n_local, dtype=jnp.int32)))
            return x, kc, vc

        x_held = x0
        for j in range(pp):
            active = jnp.broadcast_to(p == jnp.int32(j), (b,))
            x_held, kc, vc = run_stage(x_held, kc, vc, active)
            x_held = jax.lax.ppermute(x_held, AXIS_PP, _ring_perm(pp))
        x_fin = jax.lax.psum(
            jnp.where(p == 0, x_held, jnp.zeros_like(x_held)), AXIS_PP)
        return x_fin, kc, vc

    x, kc, vc = staged(params["layers"], x, cache.k, cache.v, block_tables)
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    return _unembed(x, params, cfg)[:, 0], KVCache(kc, vc)


def _pp_prefill_sample_impl(params, cfg, tokens, cache, block_tables,
                            seq_lens, samp: SamplingArrays, steps, mesh=None):
    logits, cache = pp_prefill_impl(params, cfg, tokens, cache, block_tables,
                                    seq_lens, mesh)
    keys = make_row_keys(samp.seeds, steps)
    out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
    return DecodeState(tokens=out, positions=seq_lens, steps=steps + 1), \
        cache, out


def _pp_decode_sample_impl(params, cfg, cache, block_tables,
                           state: DecodeState, samp: SamplingArrays,
                           num_steps: int = 1, mesh=None):
    def body(carry, _):
        st, cache = carry
        logits, cache = pp_decode_step_impl(params, cfg, st.tokens, cache,
                                            block_tables, st.positions, mesh)
        keys = make_row_keys(samp.seeds, st.steps)
        out = sample(logits, keys, samp.temperature, samp.top_k, samp.top_p)
        new_st = DecodeState(tokens=out, positions=st.positions + 1,
                             steps=st.steps + 1)
        return (new_st, cache), out

    (state, cache), toks = jax.lax.scan(body, (state, cache), None,
                                        length=num_steps)
    return state, cache, toks.T


class PPRunner(ModelRunner):
    """Serving runner over a pp-only mesh (capacity scaling; see module
    docstring for the latency model and the ADR pointer)."""

    kv_writer_mode = "dus"
    supports_chunked_prefill = False   # no staged chunk jit (and no prefix
    #                                    caching): engine refuses at build
    supports_hybrid = False            # no staged hybrid jit either
    supports_prefill_pipeline = False  # no staged pipelined-chunk jit
    supports_decode_overlap = False    # no donated-state staged decode jit
    supports_quantized_kv = False      # no staged scale plumbing (int8 KV)
    supports_fused_kv_write = False    # no aliasing rule in the staged jits
    supports_migration = False         # no host slicing of the staged pool
    supports_speculation = False       # no staged multi-token verify jit
    #                                    (constructor refuses spec_tokens;
    #                                    engine guards supplied runners)

    def __init__(self, cfg: ModelConfig, params, mesh: Mesh,
                 decode_steps: int = 1, spec_tokens: int = 0,
                 spec_ngram: int = 3) -> None:
        from agentic_traffic_testing_tpu.models.quant import is_quantized

        pp = mesh.shape[AXIS_PP]
        if pp < 2:
            raise ValueError(f"PPRunner needs a pp axis >= 2, got {pp}")
        if cfg.num_layers % pp:
            raise ValueError(
                f"num_layers={cfg.num_layers} not divisible by pp={pp}")
        if spec_tokens:
            raise NotImplementedError(
                "speculation x pipeline-parallel serving is not wired — "
                "unset LLM_SPECULATION with pp, or use tp/sp")
        from agentic_traffic_testing_tpu.models.quant import (
            QTensor,
            QTensor4,
        )

        if is_quantized(params) or any(
                isinstance(l, (QTensor, QTensor4))
                for l in params["layers"].values()):
            raise NotImplementedError(
                "quantization x pipeline-parallel serving is not wired — "
                "pp is the capacity escape hatch for bf16; use tp/sp for "
                "quantized serving")
        self.cfg = cfg
        self.mesh = mesh
        self.pp = pp
        self.decode_steps = max(1, int(decode_steps))
        self.spec_tokens = 0
        self.spec_ngram = max(1, int(spec_ngram))
        self.params = shard_pytree(params, pp_param_pspecs(cfg), mesh)
        self._prefill = jax.jit(
            partial(_pp_prefill_sample_impl, cfg=cfg, mesh=mesh),
            donate_argnames=("cache",))
        self._decode = jax.jit(
            partial(_pp_decode_sample_impl, cfg=cfg, mesh=mesh,
                    num_steps=self.decode_steps),
            donate_argnames=("cache",))
        self._prefill_chunk = None  # unreachable: supports_chunked_prefill

    def prepare_cache(self, cache: KVCache) -> KVCache:
        """Shard the pool's layer axis over pp: each stage holds exactly
        its own layers' pages."""
        spec = NamedSharding(self.mesh, P(AXIS_PP))
        return KVCache(k=jax.device_put(cache.k, spec),
                       v=jax.device_put(cache.v, spec))
