"""Device-mesh construction for TPU slices.

The reference testbed's only intra-model parallelism knob is vLLM's
`tensor_parallel_size` backed by NCCL (reference: llm/config/llama-3.1-8b.yaml:2,
SURVEY.md §2.3/§2.4). The TPU rebuild makes the mesh first-class: every
parallelism axis is a named `jax.sharding.Mesh` dimension and all collectives
are XLA collectives riding ICI (intra-slice) / DCN (cross-slice).

Axis vocabulary (scaling-book convention):
    dp  — data parallel (batch dim; gradient psum in training, request-level in serving)
    pp  — pipeline parallel (layer-stack stages; GPipe microbatch handoffs
          over ICI ppermutes — parallel/pipeline.py)
    ep  — expert parallel (MoE expert dim; GSPMD all-to-alls on the
          dispatch/combine einsums — models/moe.py)
    sp  — sequence/context parallel (ring attention over ICI neighbors)
    tp  — tensor parallel (head/feature dim; all-reduce after row-parallel matmuls)

A serving deployment is usually `make_mesh(tp=N)`; training composes them.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_DP = "dp"
AXIS_PP = "pp"
AXIS_EP = "ep"
AXIS_SP = "sp"
AXIS_TP = "tp"
MESH_AXES = (AXIS_DP, AXIS_PP, AXIS_EP, AXIS_SP, AXIS_TP)


def make_mesh(
    dp: int = 1,
    sp: int = 1,
    tp: int = 1,
    pp: int = 1,
    ep: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (dp, pp, sp, tp) mesh over the first dp*pp*sp*tp devices.

    On real hardware, `jax.devices()` order follows the physical torus, so
    the innermost axis (tp) lands on nearest ICI neighbors — the axis with
    the most chatter (per-layer all-reduces) gets the shortest hops, then sp
    (ring ppermutes), then pp (one activation handoff per stage per
    microbatch), then dp (one psum per step). Axes default to 1, so existing
    (dp, sp, tp) callers are unchanged — PartitionSpecs simply never mention
    `pp` unless pipeline stages are in play.
    """
    devices = list(devices if devices is not None else jax.devices())
    n = dp * sp * tp * pp * ep
    if len(devices) < n:
        raise ValueError(
            f"mesh (dp={dp},pp={pp},ep={ep},sp={sp},tp={tp}) needs {n} "
            f"devices, have {len(devices)}")
    arr = np.array(devices[:n]).reshape(dp, pp, ep, sp, tp)
    return Mesh(arr, MESH_AXES)


def auto_mesh_shape(n_devices: int) -> tuple[int, int, int]:
    """Factor a device count into a (dp, sp, tp) shape for dry runs.

    Policy: exercise every axis the count allows — tp=2 and sp=2 first
    (collective-bearing axes), remainder to dp. tp stays small so it divides
    the KV-head counts of even the tiny test configs.
    """
    if n_devices % 4 == 0:
        return (n_devices // 4, 2, 2)
    if n_devices % 2 == 0:
        return (n_devices // 2, 1, 2)
    return (n_devices, 1, 1)


def single_axis_mesh(axis: str, n: Optional[int] = None,
                     devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """A 1-axis mesh (e.g. pure-TP serving); other axes sized 1."""
    devices = list(devices if devices is not None else jax.devices())
    n = n or len(devices)
    sizes = {a: 1 for a in MESH_AXES}
    if axis not in sizes:
        raise ValueError(f"unknown axis {axis!r}")
    sizes[axis] = n
    return make_mesh(dp=sizes[AXIS_DP], sp=sizes[AXIS_SP], tp=sizes[AXIS_TP],
                     pp=sizes[AXIS_PP], ep=sizes[AXIS_EP], devices=devices)


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
