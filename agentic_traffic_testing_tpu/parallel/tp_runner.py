"""Tensor-parallel ModelRunner: same jitted step programs, sharded pytrees.

The single-device runner's prefill/decode jits are mesh-agnostic; tensor
parallelism enters purely through input shardings (params column/row-sharded,
KV cache head-sharded). XLA's SPMD partitioner then emits the per-layer
all-reduces over ICI — the role NCCL plays inside vLLM for the reference
(reference: llm/config/llama-3.1-8b.yaml:2; SURVEY.md §2.4).

Host-side batch arrays (tokens, block tables, sampling params) stay
replicated: they are tiny, and every chip runs the identical program.
"""

from __future__ import annotations

import os

import jax
from jax.sharding import Mesh

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.parallel.mesh import AXIS_TP
from agentic_traffic_testing_tpu.parallel.sharding import (
    shard_kv_cache,
    shard_params,
    validate_tp,
)
from agentic_traffic_testing_tpu.runtime.kv_cache import KVCache
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner


def resolve_decode_attn_mode() -> str:
    """Decode-attention implementation for mesh runners: shard_dma on TPU
    (the pallas DMA kernel under jax.shard_map — plain GSPMD cannot
    partition a pallas_call), jnp gather elsewhere (shard_dma off-TPU
    interprets the kernel — correct but slow; ATT_TP_ATTENTION overrides
    for targeted tests). Shared by TPRunner and the sp runners so the env
    contract cannot drift between them."""
    mode = os.environ.get("ATT_TP_ATTENTION")
    if mode is None:
        mode = "shard_dma" if jax.default_backend() == "tpu" else "gather"
    if mode not in ("shard_dma", "gather"):
        raise ValueError(
            f"ATT_TP_ATTENTION={mode!r} invalid; choose shard_dma|gather")
    return mode


class TPRunner(ModelRunner):
    """Runner whose params/cache live sharded on a `tp` mesh axis."""

    # A pallas_call has no SPMD partitioning rule, so decode attention cannot
    # ride plain GSPMD. On TPU the DMA kernel runs under jax.shard_map with
    # each chip holding its KV-head shard of the page pool ("shard_dma");
    # off-TPU the jnp gather path keeps CPU-mesh tests fast (shard_dma there
    # interprets the kernel — correct but slow; ATT_TP_ATTENTION overrides
    # for targeted tests). Page writes stay on the DUS writer, which the
    # partitioner shards cleanly.
    kv_writer_mode = "dus"
    # The ragged hybrid kernel has no shard_map wrapper yet: a hybrid step
    # under tp would all-gather the head-sharded pool. Engine refuses the
    # hybrid_token_budget knob at build instead of degrading silently.
    supports_hybrid = False
    # No sharded wrapper for the pipelined-prefill chunk jit either; the
    # engine refuses prefill_pipeline_chunks >= 2 at build.
    supports_prefill_pipeline = False
    # No donated-state sharded decode jit for the overlapped decode loop;
    # the engine refuses decode_overlap=1 at build.
    supports_decode_overlap = False
    # No scale-sharding rule in the shard_dma wrapper (int8 KV) and no
    # aliasing rule for in-kernel pool writes (fused KV write); the engine
    # refuses both knobs at build.
    supports_quantized_kv = False
    supports_fused_kv_write = False
    # No per-block host slicing / restore-write rule for the head-sharded
    # pool: live migration (LLM_MIGRATION) refuses at engine build.
    supports_migration = False

    def __init__(self, cfg: ModelConfig, params, mesh: Mesh,
                 decode_steps: int = 1, spec_tokens: int = 0,
                 spec_ngram: int = 3, int4_groups=None) -> None:
        """`int4_groups`: required attestation (= tp degree) when params
        carry int4 QTensor4 leaves — see parallel/sharding.shard_params."""
        validate_tp(cfg, mesh.shape[AXIS_TP])
        self.mesh = mesh
        mode = resolve_decode_attn_mode()
        self.attn_mode = mode
        if mode == "shard_dma":
            self.attn_mesh = mesh
            self.attn_axis = AXIS_TP
        params = shard_params(params, cfg, mesh, int4_groups=int4_groups)
        super().__init__(cfg, params, decode_steps=decode_steps,
                         spec_tokens=spec_tokens, spec_ngram=spec_ngram)

    @property
    def tp_size(self) -> int:
        return self.mesh.shape[AXIS_TP]

    def prepare_cache(self, cache: KVCache) -> KVCache:
        """Shard a freshly allocated KV cache across KV heads."""
        return shard_kv_cache(cache, self.mesh)
