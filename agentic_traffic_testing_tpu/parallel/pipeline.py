"""GPipe-style pipeline parallelism over the `pp` mesh axis.

The stacked-[L, ...] parameter layout (models/llama.py) makes pipeline
stages a SHARDING, not a refactor: splitting the layer stack across chips is
`P('pp', ...)` on the leading layer axis, and each chip's shard IS its
stage's weights. The schedule runs inside `jax.shard_map(axis_names={'pp'})`
— manual over `pp` only, so tensor parallelism (Megatron PartitionSpecs on
the trailing dims) and data parallelism (batch dim) keep riding GSPMD
*inside* each stage untouched.

Schedule (classic GPipe, M microbatches over P stages, M + P - 1 ticks):

    tick t:  stage 0 injects microbatch t (while t < M); every stage runs
             its local layer stack; activations ppermute one hop to the
             next stage over ICI; the last stage banks the finished
             microbatch t-(P-1). Bubble fraction = (P-1)/(M+P-1).

The last stage's banked activations are psum-broadcast over `pp` (every
other stage contributes zeros), so embedding, final norm/unembed, and the
loss all stay in plain GSPMD outside the shard_map. Backward differentiates
straight through the schedule: ppermute transposes to the reverse
ppermute, the psum to a broadcast, and each stage's weight gradients stay
chip-local — no hand-written backward pass.

The reference testbed has no pipeline parallelism anywhere (vLLM-internal
only, never configured — SURVEY.md §2.3); this is a capability extension of
the TPU rebuild, sized for models past TP=8's reach (Llama-3-70B+ across
hosts: tp over ICI inside a host, pp over DCN between hosts).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.models.llama import decoder_layer, init_params
from agentic_traffic_testing_tpu.models.quant import dense, embed_lookup
from agentic_traffic_testing_tpu.ops.jnp_ops import rms_norm, rope_sin_cos
from agentic_traffic_testing_tpu.ops.ring_attention import ring_attention
from agentic_traffic_testing_tpu.parallel.mesh import (
    AXIS_DP,
    AXIS_PP,
    AXIS_SP,
)
from agentic_traffic_testing_tpu.parallel.sharding import (
    param_pspecs,
    shard_pytree,
    validate_tp,
)


def pp_param_pspecs(cfg: ModelConfig) -> dict:
    """TP specs (parallel/sharding.py) with the leading layer axis of every
    stacked weight additionally sharded over `pp` — chip (p_i, t_j) holds
    stage i's layers, TP-shard j. Embedding/norms/unembed stay pp-replicated
    (stage 0 / last stage use them; they are small next to the stack)."""
    specs = param_pspecs(cfg)
    specs["layers"] = {
        k: P(AXIS_PP, *tuple(s)[1:]) for k, s in specs["layers"].items()
    }
    return specs


def make_pp_pipeline(cfg: ModelConfig, mesh: Mesh, num_microbatches: int,
                     remat: bool = True):
    """Build pipeline(local_layers, x_mb) -> activations, shard_mapped over
    pp (and sp when the mesh has one).

    x_mb: [M, mb, T, D] microbatched embeddings, pp-replicated with T
    sharded over `sp` (dp sharding of the mb dim and tp sharding inside
    each stage keep riding GSPMD — only pp/sp are manual here). With sp > 1
    the attention site is ring attention over the sp axis (the activations
    each stage hands to the next stay sequence-sharded; KV shards rotate
    over ICI inside each layer — ops/ring_attention.py), and RoPE positions
    are offset by the shard's global sequence start.
    Returns the post-stack activations in the same layout.
    """
    pp = mesh.shape[AXIS_PP]
    sp = mesh.shape[AXIS_SP]
    m = num_microbatches
    x_spec = P(None, None, AXIS_SP, None)

    @partial(jax.shard_map, mesh=mesh, axis_names={AXIS_PP, AXIS_SP},
             in_specs=(P(AXIS_PP), x_spec), out_specs=(x_spec, P()),
             check_vma=False)
    def pipeline(local_layers, x_mb):
        p = jax.lax.axis_index(AXIS_PP)
        mb, t = x_mb.shape[1], x_mb.shape[2]  # t = LOCAL (per-sp-shard) len
        start = jax.lax.axis_index(AXIS_SP) * t
        positions = jnp.broadcast_to(
            start + jnp.arange(t, dtype=jnp.int32)[None], (mb, t))
        seq_lens = jnp.full((mb,), t, jnp.int32)
        sin, cos = rope_sin_cos(positions, cfg.head_dim_, cfg.rope_theta,
                                cfg.rope_scaling)

        attn_fn = None
        if sp > 1:
            def attn_fn(q, k, v, *, q_positions=None, kv_valid_len=None):
                # Positions are the implicit global arange (the offsets
                # above feed only RoPE); full-sequence forward only, like
                # training/train.py's adapter.
                return ring_attention(q, k, v, axis_name=AXIS_SP)

        def run_stage(x):
            def body(x, lp):
                y, aux = decoder_layer(x, lp, cfg, sin, cos, positions,
                                       seq_lens, attn_fn=attn_fn)
                return y, aux
            x, auxs = jax.lax.scan(body, x, local_layers)
            return x, jnp.sum(auxs)

        if remat:
            run_stage = jax.checkpoint(run_stage)

        perm = [(i, (i + 1) % pp) for i in range(pp)]

        def tick(carry, tk):
            x_cur, out, aux_acc = carry
            # Stage 0 injects microbatch tk; warm-up/drain ticks past M just
            # recycle the last one — their results are never banked.
            inject = x_mb[jnp.minimum(tk, m - 1)]
            x_in = jnp.where(p == 0, inject, x_cur)
            y, aux = run_stage(x_in)
            # Stage p holds real microbatch tk-p exactly when 0 <= tk-p < M;
            # warm-up (zero-input) and drain (recycled-input) ticks must not
            # contribute their layers' MoE load-balance terms.
            aux_valid = (tk >= p) & (tk - p < m)
            aux_acc = aux_acc + jnp.where(aux_valid, aux, 0.0)
            # Last stage banks finished microbatch tk-(pp-1); other stages
            # (and warm-up ticks) rewrite the slot with its current value.
            slot = jnp.clip(tk - (pp - 1), 0, m - 1)
            prev = jax.lax.dynamic_index_in_dim(out, slot, 0, keepdims=False)
            take = (tk >= pp - 1) & (p == pp - 1)
            out = jax.lax.dynamic_update_index_in_dim(
                out, jnp.where(take, y, prev), slot, 0)
            x_next = jax.lax.ppermute(y, AXIS_PP, perm)
            return (x_next, out, aux_acc), None

        (x_last, out, aux_acc), _ = jax.lax.scan(
            tick,
            (jnp.zeros_like(x_mb[0]), jnp.zeros_like(x_mb), jnp.float32(0.0)),
            jnp.arange(m + pp - 1, dtype=jnp.int32))
        # Only the last stage banked activations; everyone else holds zeros,
        # so one psum broadcasts the result (and totals the per-stage aux
        # sums) and the loss stays in GSPMD outside. aux is the sum over
        # (layer, microbatch); the caller averages over microbatches.
        return jax.lax.psum(out, AXIS_PP), jax.lax.psum(aux_acc, AXIS_PP)

    return pipeline


def make_pp_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
    num_microbatches: int = 2,
    remat: bool = True,
    moe_aux_coeff: float = 0.01,
):
    """Pipelined analog of training/train.py:make_train_step over a
    (dp, pp, tp) mesh. Composes with dp (batch dim, GSPMD) and tp (Megatron
    specs inside each stage, GSPMD) and sp (sequence dim sharded through
    the schedule; ring attention over sp inside every stage — dense configs
    only, since MoE capacity/aux semantics are defined over the full
    sequence). Requires cfg.num_layers % pp == 0, batch %
    num_microbatches == 0, and T % sp == 0.

    MoE configs add the Switch load-balance term like the plain step, with
    one gradient-accumulation-style caveat: each tick's aux is computed over
    its MICROBATCH's tokens and the terms are averaged, so the objective is
    mean_m aux(microbatch_m), not aux(full batch) — the f·P products are
    means over fewer tokens. Routing, capacity drops, and the forward
    activations are exactly microbatch-invariant (capacity competition is
    per sequence, models/moe.py expert_capacity), so only the aux scalar
    differs from the unpipelined objective.
    """
    from agentic_traffic_testing_tpu.parallel.mesh import AXIS_TP
    from agentic_traffic_testing_tpu.training.train import causal_lm_loss

    pp = mesh.shape[AXIS_PP]
    validate_tp(cfg, mesh.shape[AXIS_TP])  # same guard as the plain path
    if mesh.shape[AXIS_SP] != 1 and cfg.num_experts:
        raise ValueError(
            "pipelined MoE requires sp=1: expert capacity and the "
            "load-balance aux are defined over the full sequence, which "
            "sequence sharding would silently change")
    if cfg.num_layers % pp:
        raise ValueError(
            f"num_layers={cfg.num_layers} not divisible by pp={pp}")
    optimizer = optimizer or optax.adamw(3e-4, weight_decay=0.01)
    m = num_microbatches
    pipeline = make_pp_pipeline(cfg, mesh, m, remat=remat)
    batch_sharding = NamedSharding(mesh, P(AXIS_DP, AXIS_SP))

    with_aux = bool(cfg.num_experts) and moe_aux_coeff != 0.0

    def loss_fn(params, tokens, mask):
        b, t = tokens.shape
        x = embed_lookup(params["tok_embed"], tokens,
                         dtype=params["final_norm"].dtype)
        h, aux = pipeline(params["layers"], x.reshape(m, b // m, t, -1))
        h = rms_norm(h.reshape(b, t, -1), params["final_norm"],
                     cfg.rms_norm_eps)
        logits = dense(h, params["unembed"]).astype(jnp.float32)
        loss = causal_lm_loss(logits, tokens, mask)
        if with_aux:
            loss = loss + moe_aux_coeff * aux / m  # mean over microbatches
        return loss

    sp = mesh.shape[AXIS_SP]

    @partial(jax.jit, donate_argnums=(0, 1))
    def step_fn(params, opt_state, tokens, mask):
        if tokens.shape[0] % m:
            raise ValueError(f"batch {tokens.shape[0]} % microbatches {m} != 0")
        if tokens.shape[1] % sp:
            raise ValueError(
                f"sequence length {tokens.shape[1]} % sp {sp} != 0")
        tokens = jax.lax.with_sharding_constraint(tokens, batch_sharding)
        mask = jax.lax.with_sharding_constraint(mask, batch_sharding)
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    from agentic_traffic_testing_tpu.training.train import TrainStep

    return TrainStep(step_fn=step_fn, optimizer=optimizer, mesh=mesh)


def init_pp_train_state(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    seed: int = 0,
    dtype=jnp.float32,
):
    """init_train_state with the layer stack additionally pp-sharded."""
    params = init_params(cfg, jax.random.key(seed), dtype=dtype)
    params = shard_pytree(params, pp_param_pspecs(cfg), mesh)
    opt_state = jax.jit(optimizer.init)(params)
    return params, opt_state
