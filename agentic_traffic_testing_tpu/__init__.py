"""agentic_traffic_testing_tpu — TPU-native agentic-traffic testbed framework.

Ground-up JAX/XLA/Pallas rebuild of the capabilities of the
dlamagna/agentic-traffic-testing testbed: the GPU `llm-backend`
(vLLM + CUDA paged attention + NCCL) is replaced by an in-tree TPU serving
stack — paged-KV attention, continuous batching, tensor parallelism over ICI —
behind the identical HTTP + Prometheus contract, so the agents, dashboards and
experiment pipeline run unmodified.

Package map:
  models/    Llama-family model definitions (pure-functional JAX, scan-over-layers)
  ops/       compute kernels: jnp reference ops + Pallas TPU kernels
  runtime/   paged KV cache, block allocator, continuous-batching scheduler, engine
  parallel/  device mesh, TP/SP shardings, ring attention, collectives
  serving/   HTTP serving layer (aiohttp), Prometheus metrics, chat templating
  training/  minimal sharded train step (used by multi-chip dry-run + finetuning)
  utils/     tokenizers, env config, misc
"""

__version__ = "0.1.0"
