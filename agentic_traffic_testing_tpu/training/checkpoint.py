"""Sharded training checkpoint/resume via orbax.

The reference testbed has no model checkpointing at all — weights come from
the HF hub and the only resume machinery is experiment-level (SURVEY.md
§5.4); the TPU rebuild ships training as a first-class capability
(training/train.py), so it gets the idiomatic TPU persistence layer to
match: orbax saves each chip's shard of the (params, opt_state) pytrees and
restores them straight onto the target mesh sharding — no host-side
gather/scatter of a 70B state dict.

Layout on disk: `<dir>/<step>/{params,opt_state}` managed by an orbax
CheckpointManager (bounded retention, atomic finalization, latest-step
discovery), the same pattern the experiment runner relies on for its own
resume (`runs.jsonl` + summary — scripts/experiment/run_experiment.sh).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp


@dataclasses.dataclass
class TrainCheckpointer:
    """Bounded-retention checkpoint manager for (step, params, opt_state)."""

    directory: str
    max_to_keep: int = 3

    def __post_init__(self) -> None:
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self.max_to_keep, create=True),
            item_names=("params", "opt_state"),
        )

    def save(self, step: int, params: Any, opt_state: Any,
             wait: bool = False) -> None:
        """Save one step (async by default; `wait` forces completion)."""
        self._mngr.save(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardSave(params),
                opt_state=ocp.args.StandardSave(opt_state),
            ),
        )
        if wait:
            self._mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, params_like: Any, opt_state_like: Any,
                step: Optional[int] = None):
        """Restore (params, opt_state) at `step` (default: latest).

        `*_like` are pytrees of jax.Arrays OR jax.ShapeDtypeStruct with
        `.sharding` set — each leaf is restored directly onto that sharding,
        so a checkpoint written from one mesh can be reloaded onto another
        (e.g. tp=8 -> dp=2,tp=4) without materializing the full state on any
        single host.
        """
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self.directory}")
        restored = self._mngr.restore(
            step,
            args=ocp.args.Composite(
                params=ocp.args.StandardRestore(_abstract(params_like)),
                opt_state=ocp.args.StandardRestore(_abstract(opt_state_like)),
            ),
        )
        return step, restored.params, restored.opt_state

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def _abstract(tree: Any) -> Any:
    """Pytree of ShapeDtypeStructs carrying the target shardings."""
    def leaf(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        return x
    return jax.tree_util.tree_map(leaf, tree)
