"""Sharded causal-LM training step over a (dp, sp, tp) mesh.

The reference testbed is inference-only (SURVEY.md §5.4: "no training
anywhere"); the TPU framework ships training as a first-class capability so
the same model/ops stack covers fine-tuning the models it serves. Design is
the scaling-book recipe: pick a mesh, annotate param/batch shardings, let
XLA's SPMD partitioner insert the collectives —
    dp: gradient psum (batch dim sharded)
    sp: ring attention over ICI (ops/ring_attention.py, exact causal)
    tp: Megatron column/row param sharding (parallel/sharding.py), per-layer
        all-reduce on the row-parallel matmul outputs
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.models.llama import forward_full_impl, init_params
from agentic_traffic_testing_tpu.ops.ring_attention import make_sp_attention
from agentic_traffic_testing_tpu.parallel.mesh import AXIS_DP, AXIS_SP, AXIS_TP
from agentic_traffic_testing_tpu.parallel.sharding import param_pspecs, shard_params


def causal_lm_loss(
    logits: jax.Array,   # [B, T, V] fp32
    tokens: jax.Array,   # [B, T] int32
    mask: jax.Array,     # [B, T] 1.0 on real tokens
) -> jax.Array:
    """Mean next-token cross-entropy over unmasked positions."""
    targets = tokens[:, 1:]
    lp = jax.nn.log_softmax(logits[:, :-1], axis=-1)
    nll = -jnp.take_along_axis(lp, targets[..., None], axis=-1)[..., 0]
    m = mask[:, 1:]
    return jnp.sum(nll * m) / jnp.maximum(jnp.sum(m), 1.0)


@dataclasses.dataclass
class TrainStep:
    """A jitted, mesh-sharded (loss, grads, update) step."""

    step_fn: Any          # (params, opt_state, tokens, mask) -> (params, opt_state, loss)
    optimizer: optax.GradientTransformation
    mesh: Mesh

    def __call__(self, params, opt_state, tokens, mask):
        return self.step_fn(params, opt_state, tokens, mask)


def make_train_step(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: Optional[optax.GradientTransformation] = None,
    remat: bool = True,
    moe_aux_coeff: float = 0.01,
) -> TrainStep:
    """Build the jitted train step for `cfg` over `mesh`.

    Batch layout: tokens/mask [B, T] sharded P(dp, sp); B % dp == 0 and
    T % sp == 0. When sp > 1 the attention site runs ring attention via
    shard_map; tp shards heads inside the same shard_map. `remat`
    checkpoints the layer scan body — the standard HBM-for-FLOPs trade on
    TPU for long sequences.

    MoE configs (cfg.num_experts > 0) add the Switch load-balance aux term
    to the objective: loss = lm_loss + moe_aux_coeff * Σ_layers aux (the
    standard λ=0.01 default; 0 disables). Without it the router collapses
    onto a few experts.
    """
    optimizer = optimizer or optax.adamw(3e-4, weight_decay=0.01)
    sp = mesh.shape[AXIS_SP]
    attn_fn = None
    if sp > 1:
        ring = make_sp_attention(mesh)

        def attn_fn(q, k, v, *, q_positions=None, kv_valid_len=None):
            # Ring attention derives positions from the global arange; this
            # adapter is only valid for the contiguous full-sequence forward
            # (loss_fn below never passes custom positions). kv_valid_len is
            # the full T by construction there.
            return ring(q, k, v)

    with_aux = bool(cfg.num_experts) and moe_aux_coeff != 0.0

    def loss_fn(params, tokens, mask):
        if remat:
            fwd = jax.checkpoint(
                partial(forward_full_impl, attn_fn=attn_fn, with_aux=with_aux),
                static_argnums=(1,),
            )
            out = fwd(params, cfg, tokens)
        else:
            out = forward_full_impl(params, cfg, tokens, attn_fn=attn_fn,
                                    with_aux=with_aux)
        if with_aux:
            logits, aux = out
            return causal_lm_loss(logits, tokens, mask) + moe_aux_coeff * aux
        return causal_lm_loss(out, tokens, mask)

    batch_sharding = NamedSharding(mesh, P(AXIS_DP, AXIS_SP))

    @partial(jax.jit, donate_argnums=(0, 1))
    def step_fn(params, opt_state, tokens, mask):
        tokens = jax.lax.with_sharding_constraint(tokens, batch_sharding)
        mask = jax.lax.with_sharding_constraint(mask, batch_sharding)
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, mask)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return TrainStep(step_fn=step_fn, optimizer=optimizer, mesh=mesh)


def init_train_state(
    cfg: ModelConfig,
    mesh: Mesh,
    optimizer: optax.GradientTransformation,
    seed: int = 0,
    dtype=jnp.float32,
):
    """Random-init params sharded per TP specs + matching optimizer state.

    `optax` inits moments with `zeros_like`, which preserves input sharding,
    so the optimizer state lands sharded exactly like the params.
    """
    params = init_params(cfg, jax.random.key(seed), dtype=dtype)
    params = shard_params(params, cfg, mesh)
    opt_state = jax.jit(optimizer.init)(params)
    return params, opt_state


def batch_pspec() -> P:
    return P(AXIS_DP, AXIS_SP)
