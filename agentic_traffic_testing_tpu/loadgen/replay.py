"""Open-loop asyncio replay engine.

`run_open_loop` fires a replay plan's requests at their scheduled
instants and NEVER waits on completions between firings — a stalled
completion cannot delay a later arrival (the coordinated-omission pin in
tests/test_loadgen.py). Each firing is an independent task driven
through a target:

  * `InProcessTarget` — AsyncLLMEngine / EnginePool `generate()` facade,
    the CPU-testable path bench.py and scripts/dev/loadgen_soak.py use.
    TTFT is taken from the ENGINE's own request stamps
    (`Request.queue_wait_s` — the same instants the step-clock telemetry
    plane turns into llm_slo_attainment verdicts), so a loadgen report
    reconciles exactly with the server-side counters.
  * `HTTPTarget` — SSE `/chat` client for a live deployment
    (`python -m agentic_traffic_testing_tpu.loadgen`), stamping
    client-observed TTFT and tagging SLO classes via the round-8
    slo_ttft_ms / slo_itl_ms body overrides.
"""

from __future__ import annotations

import asyncio
import dataclasses
import os
import time
from typing import Optional

from agentic_traffic_testing_tpu.loadgen.trace import (
    Trace,
    TraceNode,
    build_replay_plan,
    materialize_prompts,
)


@dataclasses.dataclass
class ReplayConfig:
    """Loadgen knobs (env surface: LOADGEN_*)."""

    arrival: str = "poisson"       # LOADGEN_ARRIVAL
    rate: float = 4.0              # LOADGEN_RATE (req/s; poisson/deterministic)
    seed: int = 0                  # LOADGEN_SEED
    time_scale: float = 1.0        # LOADGEN_TIME_SCALE (trace arrivals)
    trace_path: str = ""           # LOADGEN_TRACE (recorded trace JSON)
    metrics_port: int = 0          # LOADGEN_METRICS_PORT (0 = no exposition)

    @classmethod
    def from_env(cls) -> "ReplayConfig":
        c = cls()
        c.arrival = os.environ.get("LOADGEN_ARRIVAL") or c.arrival
        c.rate = float(os.environ.get("LOADGEN_RATE") or c.rate)
        c.seed = int(os.environ.get("LOADGEN_SEED") or c.seed)
        c.time_scale = float(
            os.environ.get("LOADGEN_TIME_SCALE") or c.time_scale)
        c.trace_path = os.environ.get("LOADGEN_TRACE") or c.trace_path
        c.metrics_port = int(
            os.environ.get("LOADGEN_METRICS_PORT") or c.metrics_port)
        if c.arrival != "trace" and c.rate <= 0:
            # trace arrivals replay the recorded offsets; the rate knob
            # is documented as ignored there, so it must not refuse.
            raise ValueError(f"LOADGEN_RATE must be > 0, got {c.rate}")
        if c.time_scale <= 0:
            raise ValueError(
                f"LOADGEN_TIME_SCALE must be > 0, got {c.time_scale}")
        if c.metrics_port < 0:
            raise ValueError(
                f"LOADGEN_METRICS_PORT must be >= 0, got {c.metrics_port}")
        return c


def engine_geometry(trace: Trace, seats: int,
                    block_size: int = 16) -> tuple:
    """(max_model_len, num_blocks) sized for a trace's longest request
    (prefix + suffix + completion, with headroom) — the ONE sizing
    formula the soak driver and the bench probe both build their
    engines from, so the two can never drift apart silently."""
    longest = max(n.prompt_tokens + trace.prefixes.get(n.prefix_id or "", 0)
                  + n.max_tokens for n in trace.nodes)
    max_len = max(256, longest + 64)
    num_blocks = max(512, 2 * seats * (-(-max_len // block_size) + 4))
    return max_len, num_blocks


@dataclasses.dataclass
class RequestRecord:
    """One fired request's measured outcome (loadgen side)."""

    request_id: str
    session_id: str
    role: str
    stage: str
    slo_class: str
    scheduled_s: float             # planned fire offset
    fire_s: float                  # actual fire offset
    lag_s: float                   # fire_s - scheduled_s (open-loop health)
    # pending until the target stamps a terminal (ok | shed | deadline |
    # error); "hung" = still pending when the drain timeout cancelled it.
    # A non-terminal status is what fails the all_terminated gate.
    status: str = "pending"
    ttft_s: Optional[float] = None
    e2e_s: Optional[float] = None
    n_tokens: int = 0
    mean_itl_s: Optional[float] = None
    slo_ttft_ms: Optional[float] = None
    slo_itl_ms: Optional[float] = None
    error: Optional[str] = None

    @property
    def ttft_met(self) -> Optional[bool]:
        """TTFT SLO verdict, mirroring runtime/telemetry.py exactly:
        only completed (ok) and deadline-expired-with-a-first-token
        requests attain a verdict; shed/error/non-terminal ones don't."""
        if self.slo_ttft_ms is None or self.ttft_s is None:
            return None
        if self.status not in ("ok", "deadline"):
            return None
        return self.ttft_s <= self.slo_ttft_ms / 1e3

    @property
    def itl_met(self) -> Optional[bool]:
        if (self.slo_itl_ms is None or self.mean_itl_s is None
                or self.status not in ("ok", "deadline")):
            return None
        return self.mean_itl_s <= self.slo_itl_ms / 1e3


class InProcessTarget:
    """Drive an AsyncLLMEngine or EnginePool generate() facade."""

    def __init__(self, async_engine, prompts: dict, *,
                 stop_token_ids: tuple = (), ignore_eos: bool = True) -> None:
        self.async_engine = async_engine
        self.prompts = prompts
        self.stop_token_ids = tuple(stop_token_ids)
        self.ignore_eos = ignore_eos

    async def fire(self, node: TraceNode, trace: Trace, rec: RequestRecord,
                   seq: int) -> None:
        from agentic_traffic_testing_tpu.runtime.request import (
            FinishReason,
            SamplingParams,
        )

        ttft_ms, itl_ms = trace.slo_for(node)
        rec.slo_ttft_ms, rec.slo_itl_ms = ttft_ms, itl_ms
        sampling = SamplingParams(
            max_tokens=node.max_tokens, temperature=node.temperature,
            stop_token_ids=self.stop_token_ids, ignore_eos=self.ignore_eos,
            seed=seq, slo_ttft_ms=ttft_ms, slo_itl_ms=itl_ms)
        t0 = time.monotonic()
        first_t = last_t = None
        n = 0
        final = None
        try:
            async for ev in self.async_engine.generate(
                    self.prompts[node.request_id], sampling,
                    f"lg{seq}-{node.request_id}"):
                now = time.monotonic()
                if ev.new_token_ids:
                    if first_t is None:
                        first_t = now
                    last_t = now
                    n += len(ev.new_token_ids)
                if ev.finished:
                    final = ev.request
                    break
        except Exception as exc:  # target fault — record, never raise
            rec.status, rec.error = "error", str(exc)
            return
        rec.n_tokens = n
        rec.e2e_s = time.monotonic() - t0
        # Engine-stamped TTFT (arrival -> first token on the engine
        # thread): the instant llm_slo_attainment judges. Loadgen-side
        # first-event time is the fallback for targets without stamps.
        if final is not None and final.queue_wait_s is not None:
            rec.ttft_s = final.queue_wait_s
        elif first_t is not None:
            rec.ttft_s = first_t - t0
        if first_t is not None and last_t is not None and n > 1:
            rec.mean_itl_s = (last_t - first_t) / (n - 1)
        fr = final.finish_reason if final is not None else None
        if fr in (FinishReason.STOP, FinishReason.LENGTH):
            rec.status = "ok"
        elif fr is FinishReason.SHED:
            rec.status = "shed"
        elif fr is FinishReason.DEADLINE:
            rec.status = "deadline"
        else:
            rec.status = "error"
            rec.error = getattr(final, "error", None) or "no terminal event"


class HTTPTarget:
    """Drive a live server's /chat SSE endpoint (client-observed TTFT)."""

    def __init__(self, url: str, texts: dict, *, session=None) -> None:
        self.url = url
        self.texts = texts
        self._session = session

    async def session(self):
        import aiohttp

        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=600))
        return self._session

    async def close(self) -> None:
        if self._session is not None and not self._session.closed:
            await self._session.close()

    async def fire(self, node: TraceNode, trace: Trace, rec: RequestRecord,
                   seq: int) -> None:
        import json as json_mod

        ttft_ms, itl_ms = trace.slo_for(node)
        rec.slo_ttft_ms, rec.slo_itl_ms = ttft_ms, itl_ms
        body = {"prompt": self.texts[node.request_id],
                "max_tokens": node.max_tokens, "stream": True,
                "request_id": f"lg{seq}-{node.request_id}"}
        if ttft_ms is not None:
            body["slo_ttft_ms"] = ttft_ms
        if itl_ms is not None:
            body["slo_itl_ms"] = itl_ms
        t0 = time.monotonic()
        first_t = last_t = None
        n = 0
        try:
            sess = await self.session()
            async with sess.post(self.url, json=body) as resp:
                if resp.status != 200:
                    rec.status = ("shed" if resp.status in (429, 503)
                                  else "deadline" if resp.status == 504
                                  else "error")
                    rec.error = f"http {resp.status}"
                    rec.e2e_s = time.monotonic() - t0
                    return
                async for raw in resp.content:
                    line = raw.decode("utf-8", "replace").strip()
                    if not line.startswith("data: "):
                        continue
                    ev = json_mod.loads(line[len("data: "):])
                    now = time.monotonic()
                    toks = ev.get("token_ids") or []
                    if toks or (ev.get("finished") and ev.get("text")):
                        if first_t is None:
                            first_t = now
                        last_t = now
                        n += len(toks)
                    if ev.get("finished"):
                        rec.status = ("error" if ev.get("error")
                                      else "ok")
                        rec.error = ev.get("error")
                        if ev.get("reason") == "deadline":
                            rec.status = "deadline"
                        elif ev.get("reason") == "queue_full":
                            rec.status = "shed"
                        break
        except Exception as exc:
            rec.status, rec.error = "error", str(exc)
            return
        rec.n_tokens = n
        rec.e2e_s = time.monotonic() - t0
        if first_t is not None:
            rec.ttft_s = first_t - t0
            if last_t is not None and n > 1:
                rec.mean_itl_s = (last_t - first_t) / (n - 1)


async def run_open_loop(plan, trace: Trace, target, *, metrics=None,
                        clock=None,
                        drain_timeout_s: Optional[float] = None) -> list:
    """Fire the plan open-loop; returns one RequestRecord per node.

    Scheduling is against the event-loop clock: the dispatcher sleeps to
    each request's fire instant and spawns its task WITHOUT awaiting any
    earlier task — completions are gathered only after the last firing.
    `metrics` (LoadgenMetrics) observes firings and completions live.

    `drain_timeout_s` bounds the post-firing drain: a request still
    pending when it expires is cancelled and recorded with status
    "hung" — the non-terminal outcome the report's all_terminated gate
    exists to catch (None = wait forever).
    """
    loop = asyncio.get_running_loop()
    now = clock or loop.time
    t0 = now()
    tasks = []
    records = []
    for seq, sched in enumerate(plan):
        delay = (t0 + sched.fire_at_s) - now()
        if delay > 0:
            await asyncio.sleep(delay)
        fire_s = now() - t0
        rec = RequestRecord(
            request_id=sched.node.request_id,
            session_id=sched.node.session_id, role=sched.node.role,
            stage=sched.node.stage, slo_class=sched.node.slo_class,
            scheduled_s=sched.fire_at_s, fire_s=fire_s,
            lag_s=fire_s - sched.fire_at_s)
        records.append(rec)
        if metrics is not None:
            metrics.observe_fired(rec)

        async def _one(node=sched.node, rec=rec, seq=seq):
            try:
                await target.fire(node, trace, rec, seq)
                if rec.status == "pending":
                    # A conforming target always stamps a terminal; a
                    # non-conforming one must not fake all_terminated.
                    rec.status, rec.error = "error", "target stamped no terminal"
            except Exception as exc:  # a raising target must not sink
                rec.status = "error"  # the whole run's record set
                rec.error = str(exc)
            if metrics is not None:
                metrics.observe_done(rec)

        tasks.append(asyncio.ensure_future(_one()))
    if tasks:
        done, pending = await asyncio.wait(tasks, timeout=drain_timeout_s)
        if pending:
            # Genuinely wedged streams: cancel, mark non-terminal (the
            # cancellation rips through _one before observe_done runs).
            for task in pending:
                task.cancel()
            await asyncio.gather(*pending, return_exceptions=True)
            for rec in records:
                if rec.status == "pending":
                    rec.status = "hung"
                    rec.error = "no terminal event before drain timeout"
                    if metrics is not None:
                        metrics.observe_done(rec)
    return records


def replay_against_engine(engine, trace: Trace, *, arrival: str = "poisson",
                          rate: float = 4.0, seed: int = 0,
                          time_scale: float = 1.0, vocab_size: int,
                          metrics=None, ignore_eos: bool = True,
                          drain_timeout_s: Optional[float] = 600.0) -> tuple:
    """Synchronous convenience: replay `trace` open-loop against an
    in-process LLMEngine/EnginePool and return (records, report).

    Owns the AsyncLLMEngine lifecycle for a bare engine (a pool is used
    as its own facade) and runs a private event loop — callable from
    bench.py probes, soak scripts and tests.
    """
    from agentic_traffic_testing_tpu.loadgen.measure import build_report
    from agentic_traffic_testing_tpu.runtime.engine import LLMEngine
    from agentic_traffic_testing_tpu.serving.async_engine import AsyncLLMEngine

    # A bare LLMEngine gets a private facade (owned: shut down on exit);
    # an AsyncLLMEngine/EnginePool is used as-is (start() is idempotent,
    # shutdown stays with its owner).
    owns = isinstance(engine, LLMEngine)
    facade = AsyncLLMEngine(engine) if owns else engine
    prompts = materialize_prompts(trace, vocab_size, seed=seed)
    plan = build_replay_plan(trace, arrival=arrival, rate=rate, seed=seed,
                             time_scale=time_scale)
    target = InProcessTarget(facade, prompts, ignore_eos=ignore_eos)

    async def _run():
        t0 = time.monotonic()
        records = await run_open_loop(plan, trace, target, metrics=metrics,
                                      drain_timeout_s=drain_timeout_s)
        return records, time.monotonic() - t0

    facade.start()
    try:
        records, duration = asyncio.run(_run())
    finally:
        if owns:
            facade.shutdown()
    report = build_report(records, trace=trace, duration_s=duration,
                          arrival=arrival, rate=rate, seed=seed)
    if metrics is not None:
        metrics.set_rates(offered=report["offered_rate"],
                          achieved=report["achieved_rate"],
                          goodput=report["goodput_rate"])
    return records, report
