"""Agentic traffic plane (round 15 — ROADMAP item 5).

Open-loop, trace-driven load generation for the serving stack: the
reference testbed's AgentVerse workload (recruit → decide → execute →
evaluate fan-out, MCP tool-call interleavings, shared-prefix system
prompts) expressed as a conversation-DAG trace format, replayed against
the engine/pool/HTTP surface at controlled arrival rates with
no coordinated omission, measured into loadgen-side Prometheus families
and a JSON run report (SLO attainment per class, per-role latency
percentiles, capacity knee).

Modules:
  trace    — the DAG trace schema, the AgentVerse synthesizer seeded
             from agents/templates/agentverse_workflow.json, and the
             live-run recorder (same schema either way)
  arrival  — arrival processes (poisson | deterministic | trace)
  replay   — the open-loop asyncio replay engine + in-process/HTTP
             targets
  measure  — loadgen Prometheus exposition (own registry, own port)
             and the run-report / capacity-knee math
"""

from agentic_traffic_testing_tpu.loadgen.arrival import arrival_offsets
from agentic_traffic_testing_tpu.loadgen.measure import (
    LoadgenMetrics,
    MetricsExposition,
    build_report,
    capacity_knee,
)
from agentic_traffic_testing_tpu.loadgen.replay import (
    InProcessTarget,
    ReplayConfig,
    RequestRecord,
    replay_against_engine,
    run_open_loop,
)
from agentic_traffic_testing_tpu.loadgen.trace import (
    Trace,
    TraceNode,
    TraceRecorder,
    build_replay_plan,
    materialize_prompts,
    synthesize_agentverse_trace,
)

__all__ = [
    "Trace",
    "TraceNode",
    "TraceRecorder",
    "synthesize_agentverse_trace",
    "build_replay_plan",
    "materialize_prompts",
    "arrival_offsets",
    "ReplayConfig",
    "RequestRecord",
    "InProcessTarget",
    "run_open_loop",
    "replay_against_engine",
    "LoadgenMetrics",
    "MetricsExposition",
    "build_report",
    "capacity_knee",
]
