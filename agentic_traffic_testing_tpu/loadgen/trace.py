"""Conversation-DAG trace format + AgentVerse synthesizer + live recorder.

One trace = one multi-agent workload: a list of request nodes, each tagged
with its session (one orchestrator task run), role (recruiter / expert /
solver / reviewer / evaluator / mcp_tool), pipeline stage (recruit /
decide / tool_call / execute / evaluate), DAG parents, shared-prefix id,
prompt/completion sizes, SLO class, and a trace-clock arrival offset.

The same schema serves three producers:

  * `synthesize_agentverse_trace` — deterministic synthesis seeded from
    `agents/templates/agentverse_workflow.json` (the reference workflow
    pack): per task, a recruit call fans out into parallel expert
    discussion, MCP tool-call interleavings hang off the experts, a
    solver/reviewer critique ladder runs `vertical_iterations` rounds,
    and an evaluator closes the session — the recruit → decide →
    execute → evaluate shape of PAPER.md's L7/L8 layer.
  * `TraceRecorder` — captures a LIVE AgentVerse run into the identical
    schema (wired opt-in into agents/common/llm_client.py behind
    LOADGEN_RECORD_TRACE), so a recorded production workload replays
    through the same engine as a synthetic one.
  * hand-written JSON (the format is stable and versioned).

Prompts are stored as SIZES + prefix ids, not token ids: a trace is
model-agnostic, and `materialize_prompts` expands it deterministically
against a vocab so every node sharing a prefix_id shares an exact token
prefix (the shared-prefix fan-out the prefix cache and affinity router
were built for). `materialize_texts` renders the same structure as text
for the HTTP target.
"""

from __future__ import annotations

import dataclasses
import json
import os
import random
import zlib
from typing import Iterable, Optional

SCHEMA_VERSION = 1

#: canonical stages of the AgentVerse pipeline (PAPER.md L7).
STAGES = ("recruit", "decide", "tool_call", "execute", "evaluate")

DEFAULT_TEMPLATE = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "agents", "templates", "agentverse_workflow.json")

#: default SLO classes: interactive covers the latency-critical
#: orchestration hops (a slow recruit stalls the whole DAG), batch covers
#: the long evaluator synthesis. Budgets are deliberately generous — a
#: λ sweep is about WHERE attainment collapses, not absolute numbers.
DEFAULT_SLO_CLASSES = {
    "interactive": {"ttft_ms": 2000.0, "itl_ms": 500.0},
    "batch": {"ttft_ms": 15000.0, "itl_ms": 0.0},
}


@dataclasses.dataclass
class TraceNode:
    """One LLM request in the DAG."""

    request_id: str
    session_id: str
    role: str
    stage: str
    arrival_offset_s: float          # trace clock, seconds from trace start
    prefix_id: Optional[str] = None  # shared-prefix pool key (None = solo)
    prompt_tokens: int = 64          # suffix tokens AFTER the shared prefix
    max_tokens: int = 32
    slo_class: str = "interactive"
    parents: tuple = ()              # request_ids this node depends on
    temperature: float = 0.0

    @property
    def total_prompt_tokens(self) -> int:
        return self.prompt_tokens  # prefix length is added at materialize


@dataclasses.dataclass
class Trace:
    """A replayable workload: nodes + shared-prefix pool + SLO classes."""

    name: str
    seed: Optional[int]
    prefixes: dict                   # prefix_id -> prefix token length
    slo_classes: dict                # class name -> {ttft_ms, itl_ms}
    nodes: list

    def __post_init__(self) -> None:
        ids = [n.request_id for n in self.nodes]
        if len(set(ids)) != len(ids):
            raise ValueError("trace has duplicate request_ids")
        for n in self.nodes:
            if n.slo_class not in self.slo_classes:
                raise ValueError(
                    f"node {n.request_id} names unknown SLO class "
                    f"{n.slo_class!r} (declared: {sorted(self.slo_classes)})")
            if n.prefix_id is not None and n.prefix_id not in self.prefixes:
                raise ValueError(
                    f"node {n.request_id} names unknown prefix "
                    f"{n.prefix_id!r}")

    # -- serialization ---------------------------------------------------

    def to_json(self) -> str:
        return json.dumps({
            "schema_version": SCHEMA_VERSION,
            "name": self.name,
            "seed": self.seed,
            "prefixes": self.prefixes,
            "slo_classes": self.slo_classes,
            "nodes": [dataclasses.asdict(n) for n in self.nodes],
        }, indent=1)

    @classmethod
    def from_json(cls, text: str) -> "Trace":
        doc = json.loads(text)
        if doc.get("schema_version") != SCHEMA_VERSION:
            raise ValueError(
                f"unsupported trace schema_version "
                f"{doc.get('schema_version')!r} (this build reads "
                f"{SCHEMA_VERSION})")
        nodes = [TraceNode(**{**n, "parents": tuple(n.get("parents", ()))})
                 for n in doc["nodes"]]
        return cls(name=doc["name"], seed=doc.get("seed"),
                   prefixes=dict(doc["prefixes"]),
                   slo_classes=dict(doc["slo_classes"]), nodes=nodes)

    def save(self, path: str) -> None:
        with open(path, "w") as f:
            f.write(self.to_json())

    @classmethod
    def load(cls, path: str) -> "Trace":
        with open(path) as f:
            return cls.from_json(f.read())

    def slo_for(self, node: TraceNode) -> tuple:
        """(slo_ttft_ms, slo_itl_ms) for a node; 0 entries become None
        (no SLO on that axis — the telemetry plane's convention)."""
        cls = self.slo_classes[node.slo_class]
        ttft = float(cls.get("ttft_ms") or 0.0) or None
        itl = float(cls.get("itl_ms") or 0.0) or None
        return ttft, itl


# -- deterministic synthesis --------------------------------------------


def _rng(seed: int, *keys) -> random.Random:
    tag = "/".join(str(k) for k in keys)
    return random.Random(seed ^ zlib.crc32(tag.encode()))


def synthesize_agentverse_trace(
    *,
    tasks: int = 2,
    seed: int = 0,
    template_path: str = DEFAULT_TEMPLATE,
    session_interval_s: float = 2.0,
    stage_gap_s: float = 0.25,
    prompt_tokens: int = 48,
    prefix_tokens: int = 64,
    max_tokens: int = 16,
    tool_call_prob: float = 0.5,
    slo_classes: Optional[dict] = None,
) -> Trace:
    """Deterministic AgentVerse workload from the reference template pack.

    Per task (session): recruit → `num_experts` parallel decide calls
    (each possibly followed by an MCP tool call) → `vertical_iterations`
    solver+reviewer critique rounds → one evaluator call. Every agent
    node in a session shares that session's prefix (system prompt +
    task), which itself extends the global system prefix — the nested
    shared-prefix shape; tool calls share one flat tool-schema prefix.
    """
    with open(template_path) as f:
        tpl = json.load(f)
    defaults = tpl.get("workflow_defaults", {})
    num_experts = int(defaults.get("num_experts", 3))
    rounds = int(defaults.get("vertical_iterations", 2))
    roles = [r["name"] for r in tpl.get("role_catalog", [])] or ["Expert"]
    task_pack = tpl.get("example_tasks", []) or [{"task_id": "task"}]

    slo_classes = dict(slo_classes or DEFAULT_SLO_CLASSES)
    prefixes = {"system": prefix_tokens, "tool-schema": prefix_tokens // 2}
    nodes: list[TraceNode] = []

    for si in range(tasks):
        task = task_pack[si % len(task_pack)]
        sid = f"s{si}-{task['task_id']}"
        spfx = f"session-{si}"
        # Session prefix = the task statement riding on the system prompt
        # (materialize nests it under the global system prefix).
        prefixes[spfx] = prefix_tokens + prompt_tokens
        r = _rng(seed, "session", si)
        t = si * session_interval_s

        def node(rid: str, role: str, stage: str, t: float, parents=(),
                 prefix: str = spfx, ptok: int = prompt_tokens,
                 mtok: int = max_tokens, slo: str = "interactive"):
            nodes.append(TraceNode(
                request_id=f"{sid}/{rid}", session_id=sid, role=role,
                stage=stage, arrival_offset_s=round(t, 4), prefix_id=prefix,
                prompt_tokens=ptok, max_tokens=mtok, slo_class=slo,
                parents=tuple(f"{sid}/{p}" for p in parents)))
            return rid

        recruit = node("recruit", "recruiter", "recruit", t)
        t += stage_gap_s
        experts = []
        for ei in range(num_experts):
            role = roles[ei % len(roles)]
            jitter = r.uniform(0.0, stage_gap_s / 2)
            rid = node(f"decide{ei}", role, "decide", t + jitter,
                       parents=[recruit])
            experts.append(rid)
            if r.random() < tool_call_prob:
                # MCP tool-call interleaving: short schema-prefixed call
                # issued while the expert discussion is still running.
                node(f"tool{ei}", "mcp_tool", "tool_call",
                     t + jitter + stage_gap_s / 2, parents=[rid],
                     prefix="tool-schema", ptok=prompt_tokens // 2,
                     mtok=max(4, max_tokens // 4))
        t += stage_gap_s
        prev = experts
        for ri in range(rounds):
            solver = node(f"solve{ri}", "solver", "execute", t, parents=prev)
            t += stage_gap_s
            reviewers = []
            for vi in range(max(1, num_experts - 1)):
                jitter = r.uniform(0.0, stage_gap_s / 2)
                reviewers.append(node(
                    f"review{ri}.{vi}", roles[(vi + 1) % len(roles)],
                    "execute", t + jitter, parents=[solver]))
            t += stage_gap_s
            prev = reviewers
        node("evaluate", "evaluator", "evaluate", t, parents=prev,
             mtok=max_tokens * 2, slo="batch")

    nodes.sort(key=lambda n: (n.arrival_offset_s, n.request_id))
    return Trace(name=f"agentverse-{tasks}x{num_experts}", seed=seed,
                 prefixes=prefixes, slo_classes=slo_classes, nodes=nodes)


# -- materialization ----------------------------------------------------


def _materialize(trace: Trace, base: int, gen) -> dict:
    """Shared prefix-pool expansion: request_id -> element list.

    `gen(n, *keys)` yields n deterministic elements for an rng keyed by
    (base, keys). ONE body serves both the token and text renderings, so
    the nested sharing structure — nodes with one prefix_id share that
    exact element prefix, session prefixes extend the global "system"
    prefix — cannot drift between the in-process and HTTP targets.
    """
    system = gen(trace.prefixes.get("system", 0), "prefix", "system")
    pool = {}
    for pid, length in trace.prefixes.items():
        if pid == "system":
            pool[pid] = list(system)
        elif pid.startswith("session-") and length > len(system):
            pool[pid] = system + gen(length - len(system), "prefix", pid)
        else:
            pool[pid] = gen(length, "prefix", pid)
    out = {}
    for n in trace.nodes:
        prefix = pool.get(n.prefix_id, []) if n.prefix_id else []
        out[n.request_id] = list(prefix) + gen(n.prompt_tokens, "node",
                                               n.request_id)
    return out


def materialize_prompts(trace: Trace, vocab_size: int,
                        seed: Optional[int] = None) -> dict:
    """request_id -> prompt token ids, deterministic under (trace.seed |
    seed). Nodes sharing a prefix_id share that exact token prefix;
    session prefixes additionally extend the global "system" prefix, so
    fan-out siblings AND cross-session requests overlap the way real
    templated agent prompts do."""
    base = seed if seed is not None else (trace.seed or 0)
    lo, hi = 10, max(11, vocab_size - 10)

    def toks(n: int, *keys) -> list:
        r = _rng(base, *keys)
        return [r.randrange(lo, hi) for _ in range(n)]

    return _materialize(trace, base, toks)


_WORDS = ("plan", "measure", "batch", "token", "cache", "agent", "route",
          "probe", "queue", "shard", "trace", "layer")


def materialize_texts(trace: Trace, seed: Optional[int] = None) -> dict:
    """request_id -> prompt text for the HTTP target: the SAME sharing
    structure as the token materialization (~1 word per token), via the
    same _materialize body."""
    base = seed if seed is not None else (trace.seed or 0)

    def words(n: int, *keys) -> list:
        r = _rng(base, *keys)
        return [r.choice(_WORDS) for _ in range(n)]

    return {rid: " ".join(elems)
            for rid, elems in _materialize(trace, base, words).items()}


# -- replay plan --------------------------------------------------------


@dataclasses.dataclass
class ScheduledRequest:
    """One planned firing: the node plus its wall-clock offset."""

    fire_at_s: float
    node: TraceNode


def build_replay_plan(trace: Trace, *, arrival: str = "trace",
                      rate: float = 0.0, seed: int = 0,
                      time_scale: float = 1.0) -> list:
    """Assign fire times to the trace's nodes under an arrival process.

    Nodes are taken in trace order (arrival_offset_s, request_id) — the
    synthesizer emits them DAG-topologically, so any monotonic re-timing
    preserves parent-before-child ordering. `arrival="trace"` replays the
    recorded offsets (scaled by time_scale); "poisson"/"deterministic"
    re-time the same ordered stream at offered rate λ=`rate`
    (requests/s). Deterministic under `seed`.
    """
    from agentic_traffic_testing_tpu.loadgen.arrival import arrival_offsets

    nodes = sorted(trace.nodes,
                   key=lambda n: (n.arrival_offset_s, n.request_id))
    offsets = arrival_offsets(
        len(nodes), arrival, rate, seed=seed,
        trace_offsets=[n.arrival_offset_s for n in nodes],
        time_scale=time_scale)
    return [ScheduledRequest(fire_at_s=o, node=n)
            for o, n in zip(offsets, nodes)]


# -- live-run recorder --------------------------------------------------


class TraceRecorder:
    """Capture a live agent run into the trace schema.

    Producers call `record_call` per LLM request (the llm_client hook
    passes its call metadata); offsets are stamped from the first call.
    `to_trace` freezes the capture. Prompt sizes are recorded as ~4
    chars/token estimates when only text lengths are known — the replay
    cares about magnitude and sharing structure, not exact tokenization.
    """

    def __init__(self, name: str = "recorded") -> None:
        self.name = name
        self._t0: Optional[float] = None
        self._nodes: list[TraceNode] = []
        self._last_by_session: dict = {}
        self._id_counts: dict = {}

    def record_call(self, *, request_id: str, session_id: str, role: str,
                    stage: str = "execute", prompt_chars: int = 0,
                    prompt_tokens: Optional[int] = None,
                    max_tokens: int = 32, t: Optional[float] = None,
                    prefix_id: Optional[str] = None) -> None:
        import time

        now = t if t is not None else time.monotonic()
        if self._t0 is None:
            self._t0 = now
        parent = self._last_by_session.get(session_id)
        # Caller-supplied ids can repeat (client retries reuse
        # X-Request-ID); dedup at record time so to_trace() can never
        # raise — an atexit flush that throws would lose the whole
        # captured run for one duplicate.
        seen = self._id_counts.get(request_id, 0)
        self._id_counts[request_id] = seen + 1
        if seen:
            request_id = f"{request_id}#{seen + 1}"
        self._nodes.append(TraceNode(
            request_id=request_id, session_id=session_id, role=role,
            stage=stage if stage in STAGES else "execute",
            arrival_offset_s=round(now - self._t0, 4), prefix_id=prefix_id,
            prompt_tokens=(prompt_tokens if prompt_tokens is not None
                           else max(1, prompt_chars // 4)),
            max_tokens=max_tokens,
            parents=(parent,) if parent else ()))
        self._last_by_session[session_id] = request_id

    def __len__(self) -> int:
        return len(self._nodes)

    def to_trace(self, slo_classes: Optional[dict] = None) -> Trace:
        return Trace(name=self.name, seed=None, prefixes={},
                     slo_classes=dict(slo_classes or DEFAULT_SLO_CLASSES),
                     nodes=list(self._nodes))


def topological_order_ok(trace: Trace,
                         plan: Iterable[ScheduledRequest]) -> bool:
    """True when every node fires at-or-after all of its parents (the
    invariant build_replay_plan preserves for any monotonic arrival)."""
    fire = {s.node.request_id: s.fire_at_s for s in plan}
    return all(fire[p] <= fire[n.request_id]
               for n in trace.nodes for p in n.parents if p in fire)
