"""CLI: replay an agentic trace open-loop against a live backend.

    python -m agentic_traffic_testing_tpu.loadgen \
        --url http://localhost:8000/chat --rate 8 --arrival poisson \
        --tasks 4 --report /tmp/loadgen_report.json

Env mirrors the flags (LOADGEN_ARRIVAL / LOADGEN_RATE / LOADGEN_SEED /
LOADGEN_TIME_SCALE / LOADGEN_TRACE / LOADGEN_METRICS_PORT); flags win.
With LOADGEN_TRACE (or --trace) a recorded trace JSON replays instead of
a synthesized one. LOADGEN_METRICS_PORT > 0 serves the loadgen's own
Prometheus registry for the run's duration.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import sys
import time
from typing import Optional

from agentic_traffic_testing_tpu.loadgen.measure import (
    LoadgenMetrics,
    MetricsExposition,
    build_report,
)
from agentic_traffic_testing_tpu.loadgen.replay import (
    HTTPTarget,
    ReplayConfig,
    run_open_loop,
)
from agentic_traffic_testing_tpu.loadgen.trace import (
    Trace,
    build_replay_plan,
    materialize_texts,
    synthesize_agentverse_trace,
)


def main(argv: Optional[list] = None) -> int:
    env = ReplayConfig.from_env()
    p = argparse.ArgumentParser(
        description="open-loop agentic-trace load generator")
    p.add_argument("--url", default="http://localhost:8000/chat")
    p.add_argument("--arrival", default=env.arrival,
                   choices=("poisson", "deterministic", "trace"))
    p.add_argument("--rate", type=float, default=env.rate,
                   help="offered rate λ (req/s)")
    p.add_argument("--seed", type=int, default=env.seed)
    p.add_argument("--time-scale", type=float, default=env.time_scale)
    p.add_argument("--trace", default=env.trace_path,
                   help="recorded trace JSON (default: synthesize)")
    p.add_argument("--tasks", type=int, default=2,
                   help="AgentVerse sessions to synthesize")
    p.add_argument("--metrics-port", type=int, default=env.metrics_port,
                   help="serve loadgen Prometheus families here (0 = off)")
    p.add_argument("--report", default="",
                   help="write the run report JSON here (default stdout)")
    a = p.parse_args(argv)

    trace = (Trace.load(a.trace) if a.trace
             else synthesize_agentverse_trace(tasks=a.tasks, seed=a.seed))
    plan = build_replay_plan(trace, arrival=a.arrival, rate=a.rate,
                             seed=a.seed, time_scale=a.time_scale)
    metrics = LoadgenMetrics.for_trace(trace)
    exposition = (MetricsExposition(metrics, a.metrics_port)
                  if a.metrics_port else None)
    target = HTTPTarget(a.url, materialize_texts(trace, seed=a.seed))

    async def _run():
        t0 = time.monotonic()
        try:
            records = await run_open_loop(plan, trace, target,
                                          metrics=metrics)
        finally:
            await target.close()
        return records, time.monotonic() - t0

    try:
        records, duration = asyncio.run(_run())
        report = build_report(records, trace=trace, duration_s=duration,
                              arrival=a.arrival, rate=a.rate, seed=a.seed)
        # Rate gauges land BEFORE the exposition closes, so a scraper
        # polling the loadgen port sees the run's final numbers.
        metrics.set_rates(offered=report["offered_rate"],
                          achieved=report["achieved_rate"],
                          goodput=report["goodput_rate"])
    finally:
        if exposition is not None:
            exposition.close()
    text = json.dumps(report, indent=1)
    if a.report:
        with open(a.report, "w") as f:
            f.write(text)
    print(text, flush=True)
    return 0 if report["all_terminated"] else 1


if __name__ == "__main__":
    sys.exit(main())
