"""Arrival processes for the open-loop replay engine.

Offsets are ABSOLUTE seconds from replay start. The open-loop contract
(docs/loadgen.md): requests fire at these instants regardless of how many
earlier requests have completed — the generator never waits on the
system under test, so a stall shows up as latency, not as a silently
reduced offered rate (the coordinated-omission failure mode the
serving-comparison literature warns about).
"""

from __future__ import annotations

import random
from typing import Optional

ARRIVAL_PROCESSES = ("poisson", "deterministic", "trace")


def arrival_offsets(n: int, process: str, rate: float, *, seed: int = 0,
                    trace_offsets: Optional[list] = None,
                    time_scale: float = 1.0) -> list:
    """Fire offsets for `n` requests.

    poisson        — exponential interarrivals at λ=rate (req/s), the
                     memoryless open-loop standard; deterministic under
                     `seed`.
    deterministic  — uniform 1/rate spacing (the paced sweep arm).
    trace          — the recorded `trace_offsets`, scaled by
                     `time_scale` (2.0 = replay at half speed, 0.5 =
                     double speed); `rate` is ignored.
    """
    if process not in ARRIVAL_PROCESSES:
        raise ValueError(
            f"unknown arrival process {process!r} "
            f"(expected one of {ARRIVAL_PROCESSES})")
    if process == "trace":
        if trace_offsets is None:
            raise ValueError("trace arrivals need trace_offsets")
        if time_scale <= 0:
            raise ValueError(f"time_scale must be > 0, got {time_scale}")
        t0 = min(trace_offsets) if trace_offsets else 0.0
        return [(t - t0) * time_scale for t in trace_offsets]
    if rate <= 0:
        raise ValueError(
            f"{process} arrivals need a positive rate (req/s), got {rate}")
    if process == "deterministic":
        return [i / rate for i in range(n)]
    rng = random.Random(seed)
    offsets, t = [], 0.0
    for _ in range(n):
        t += rng.expovariate(rate)
        offsets.append(t)
    return offsets
