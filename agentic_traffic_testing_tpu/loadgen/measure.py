"""Loadgen-side measurement surface: Prometheus families + run report.

The loadgen is its own exporter: `loadgen_*` families live in a private
CollectorRegistry served on a private port (`LOADGEN_METRICS_PORT`), so
a λ sweep's offered/achieved view scrapes independently of the server's
`llm_*` families — the two-sided measurement the serving-comparison
methodology needs (offered rate is a loadgen fact, service rate a
server fact).

Exposition follows serving/metrics.py's always-registered rule: every
family (and every label combination with a bounded label set) exists
from construction, so the scrape contract is stable before the first
request fires.
"""

from __future__ import annotations

from typing import Optional

from prometheus_client import (
    CONTENT_TYPE_LATEST,
    CollectorRegistry,
    Counter,
    Gauge,
    Histogram,
    generate_latest,
)

from agentic_traffic_testing_tpu.serving.metrics import (
    ITL_BUCKETS,
    LATENCY_BUCKETS,
    TTFT_BUCKETS,
)

#: open-loop dispatcher lag: how late a firing left the loadgen relative
#: to its schedule (sustained growth = the GENERATOR is saturated and
#: the offered rate is no longer honest — report.schedule_lag_* gates it).
LAG_BUCKETS = [0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
               0.25, 0.5, 1.0, 2.5]

#: terminal outcomes; a record still "pending" (target never stamped a
#: terminal) or "hung" (cancelled at the drain timeout) counts against
#: the report's all_terminated gate.
STATUSES = ("ok", "shed", "deadline", "error")


class LoadgenMetrics:
    """The `loadgen_*` family set, one instance per replay run/sweep."""

    content_type = CONTENT_TYPE_LATEST

    def __init__(self, roles: tuple = (), slo_classes: tuple = ()) -> None:
        r = self.registry = CollectorRegistry()
        self.offered = Counter(
            "loadgen_offered_requests", "Requests fired open-loop "
            "(scheduled arrivals that left the generator)", registry=r)
        self.requests = Counter(
            "loadgen_requests", "Completed loadgen requests by role/stage "
            "and terminal status", ["role", "stage", "status"], registry=r)
        self.ttft = Histogram(
            "loadgen_ttft_seconds", "Time to first token by role "
            "(engine-stamped for the in-process target, client-observed "
            "for HTTP)", ["role"], buckets=TTFT_BUCKETS, registry=r)
        self.itl = Histogram(
            "loadgen_itl_seconds", "Mean inter-token latency per request "
            "by role", ["role"], buckets=ITL_BUCKETS, registry=r)
        self.e2e = Histogram(
            "loadgen_e2e_seconds", "Fire -> terminal wall time by role",
            ["role"], buckets=LATENCY_BUCKETS, registry=r)
        self.schedule_lag = Histogram(
            "loadgen_schedule_lag_seconds", "Actual fire instant minus "
            "scheduled instant (open-loop dispatcher health)",
            buckets=LAG_BUCKETS, registry=r)
        self.slo_attainment = Counter(
            "loadgen_slo_attainment", "Per-request SLO verdicts by class "
            "and axis (slo=ttft|itl, status=met|violated), mirroring the "
            "server's llm_slo_attainment_total math",
            ["slo_class", "slo", "status"], registry=r)
        self.offered_rate = Gauge(
            "loadgen_offered_rate", "Configured/actual offered arrival "
            "rate λ (req/s) of the most recent run", registry=r)
        self.achieved_rate = Gauge(
            "loadgen_achieved_rate", "Completed-ok request throughput of "
            "the most recent run (req/s; sheds/deadlines/errors excluded)",
            registry=r)
        self.goodput_rate = Gauge(
            "loadgen_goodput_rate", "Completions that also met every SLO "
            "axis they declared, per second (goodput)", registry=r)
        # Pre-touch label combinations for the run's bounded sets so the
        # scrape shows zeroed series before the first request.
        for role in roles:
            self.ttft.labels(role=role)
            self.itl.labels(role=role)
            self.e2e.labels(role=role)
        for cls in slo_classes:
            for slo in ("ttft", "itl"):
                for status in ("met", "violated"):
                    self.slo_attainment.labels(slo_class=cls, slo=slo,
                                               status=status)

    @classmethod
    def for_trace(cls, trace) -> "LoadgenMetrics":
        roles = tuple(sorted({n.role for n in trace.nodes}))
        return cls(roles=roles, slo_classes=tuple(sorted(trace.slo_classes)))

    def observe_fired(self, rec) -> None:
        self.offered.inc()
        self.schedule_lag.observe(max(0.0, rec.lag_s))

    def observe_done(self, rec) -> None:
        self.requests.labels(role=rec.role, stage=rec.stage,
                             status=rec.status).inc()
        if rec.ttft_s is not None:
            self.ttft.labels(role=rec.role).observe(rec.ttft_s)
        if rec.mean_itl_s is not None:
            self.itl.labels(role=rec.role).observe(rec.mean_itl_s)
        if rec.e2e_s is not None:
            self.e2e.labels(role=rec.role).observe(rec.e2e_s)
        for slo, met in (("ttft", rec.ttft_met), ("itl", rec.itl_met)):
            if met is not None:
                self.slo_attainment.labels(
                    slo_class=rec.slo_class, slo=slo,
                    status="met" if met else "violated").inc()

    def set_rates(self, *, offered: float, achieved: float,
                  goodput: float) -> None:
        self.offered_rate.set(offered)
        self.achieved_rate.set(achieved)
        self.goodput_rate.set(goodput)

    def render(self) -> bytes:
        return generate_latest(self.registry)


class MetricsExposition:
    """Serve a registry on its own port (the loadgen's /metrics).

    Thin lifecycle wrapper over prometheus_client.start_http_server —
    its own daemon thread, so the loadgen never depends on the serving
    stack's event loop (it measures it). `port=0` binds an ephemeral
    port (tests); `.port` reports the bound value.
    """

    def __init__(self, metrics: LoadgenMetrics, port: int = 0,
                 host: str = "0.0.0.0") -> None:
        from prometheus_client import start_http_server

        self._httpd, self._thread = start_http_server(
            port, addr=host, registry=metrics.registry)
        self.port = self._httpd.server_address[1]

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


# -- run report ----------------------------------------------------------


def _percentile(values: list, q: float) -> Optional[float]:
    if not values:
        return None
    v = sorted(values)
    return v[min(len(v) - 1, int(q * len(v)))]


def _round(x: Optional[float], nd: int = 5) -> Optional[float]:
    return None if x is None else round(x, nd)


def build_report(records: list, *, trace, duration_s: float,
                 arrival: str, rate: float, seed: int = 0) -> dict:
    """The run-report artifact (docs/loadgen.md §report).

    Pure record math — everything here is recomputable from the
    RequestRecord list, and the soak driver cross-checks the SLO/shed
    numbers against the server's Prometheus counters.
    """
    n = len(records)
    by_status = {s: sum(1 for r in records if r.status == s)
                 for s in STATUSES}
    terminated = sum(by_status.values())
    ok = [r for r in records if r.status == "ok"]
    span = max((r.scheduled_s for r in records), default=0.0)
    goodput = sum(1 for r in ok
                  if r.ttft_met is not False and r.itl_met is not False)

    slo: dict = {}
    for cls in sorted(trace.slo_classes):
        rows = [r for r in records if r.slo_class == cls]
        verdicts = {}
        for axis, attr in (("ttft", "ttft_met"), ("itl", "itl_met")):
            vs = [getattr(r, attr) for r in rows
                  if getattr(r, attr) is not None]
            verdicts[f"{axis}_met"] = sum(1 for v in vs if v)
            verdicts[f"{axis}_total"] = len(vs)
            verdicts[f"{axis}_attainment"] = (
                round(sum(1 for v in vs if v) / len(vs), 4) if vs else None)
        slo[cls] = {"requests": len(rows), **verdicts}

    roles: dict = {}
    for role in sorted({r.role for r in records}):
        rows = [r for r in records if r.role == role]
        ttfts = [r.ttft_s for r in rows if r.ttft_s is not None]
        itls = [r.mean_itl_s for r in rows if r.mean_itl_s is not None]
        e2es = [r.e2e_s for r in rows if r.e2e_s is not None]
        roles[role] = {
            "requests": len(rows),
            "ok": sum(1 for r in rows if r.status == "ok"),
            "ttft_p50_s": _round(_percentile(ttfts, 0.50)),
            "ttft_p99_s": _round(_percentile(ttfts, 0.99)),
            "itl_p50_s": _round(_percentile(itls, 0.50)),
            "e2e_p50_s": _round(_percentile(e2es, 0.50)),
            "e2e_p99_s": _round(_percentile(e2es, 0.99)),
        }

    ttft_all = [r.ttft_met for r in records if r.ttft_met is not None]
    lags = [r.lag_s for r in records]
    return {
        "trace": trace.name,
        "arrival": arrival,
        "seed": seed,
        "offered_rate": round(rate if arrival != "trace"
                              else (n / span if span > 0 else float(n)), 4),
        "requests": n,
        "duration_s": round(duration_s, 4),
        "completed": by_status["ok"],
        "shed": by_status["shed"],
        "deadline": by_status["deadline"],
        "errors": by_status["error"],
        "hung": n - terminated,
        "all_terminated": terminated == n,
        "achieved_rate": round(by_status["ok"] / duration_s, 4)
        if duration_s > 0 else 0.0,
        "goodput_rate": round(goodput / duration_s, 4)
        if duration_s > 0 else 0.0,
        "ttft_attainment": (round(sum(1 for v in ttft_all if v)
                                  / len(ttft_all), 4) if ttft_all else None),
        "schedule_lag_p50_s": _round(_percentile(lags, 0.50)),
        "schedule_lag_p99_s": _round(_percentile(lags, 0.99)),
        "slo": slo,
        "roles": roles,
    }


def capacity_knee(sweep: list, *, target: float = 0.99) -> Optional[float]:
    """Max sustainable λ: the highest offered rate in a [(rate, report)]
    sweep such that it AND every lower swept rate attain >= target on
    TTFT (the `agentic_load` probe's headline). Walking up from the
    lowest rate and stopping at the first miss keeps a noisy or bimodal
    sweep from reporting a rate "sustainable" while a lower one failed;
    a rate with no verdicts counts as a miss. None when the lowest
    swept rate already misses."""
    best = None
    for rate, report in sorted(sweep, key=lambda rr: rr[0]):
        att = report.get("ttft_attainment")
        if att is None or att < target:
            break
        best = rate
    return best
