"""Chunked prefill: long prompts prefill in fixed-size chunks.

The invariant under test: chunking is purely a scheduling strategy — outputs
are token-identical to the unchunked engine for greedy and seeded sampling,
TTFT lands on the final chunk, KV accounting drains, and short prompts and
decode batchmates are unaffected. (The reference gets this capability from
vLLM's enable_chunked_prefill; here it is first-party —
runtime/scheduler.py ChunkPrefill + models/llama.py prefill_chunk_impl.)
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import FinishReason, SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

CFG = PRESETS["tiny"]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def make_engine(params, chunk, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 256)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 128)
    kw.setdefault("max_num_seqs", 4)
    ecfg = EngineConfig(prefill_chunk_tokens=chunk, **kw)
    runner = ModelRunner(CFG, params, decode_steps=1)
    return LLMEngine(ecfg, model_cfg=CFG, runner=runner)


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


def run_all(engine, reqs):
    for _ in range(10_000):
        engine.step()
        if all(r.is_finished() for r in reqs):
            return
        if not engine.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


def oracle(params, prompt, sampling):
    eng = make_engine(params, chunk=None)
    return eng.generate(prompt, sampling).generated_ids


@pytest.mark.parametrize("plen", [33, 64, 100])
def test_chunked_matches_unchunked_greedy(params, plen):
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, CFG.vocab_size, plen).tolist()
    want = oracle(params, prompt, greedy(10))
    eng = make_engine(params, chunk=32)  # prompts > 32 tokens chunk at 32
    req = eng.generate(prompt, greedy(10))
    assert req.generated_ids == want
    assert req.finish_reason == FinishReason.LENGTH


def test_chunked_seeded_sampling_matches(params):
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, CFG.vocab_size, 80).tolist()
    sp = lambda: SamplingParams(max_tokens=10, temperature=0.8, top_k=20, seed=9)
    want = oracle(params, prompt, sp())
    eng = make_engine(params, chunk=32)
    req = eng.generate(prompt, sp())
    assert req.generated_ids == want


def test_long_and_short_mixed(params):
    """A chunked long prompt and normal short prompts coexist correctly."""
    rng = np.random.default_rng(2)
    long_p = rng.integers(0, CFG.vocab_size, 90).tolist()
    shorts = [rng.integers(0, CFG.vocab_size, n).tolist() for n in (6, 14)]
    wants = [oracle(params, p, greedy(8)) for p in [long_p] + shorts]

    eng = make_engine(params, chunk=32)
    reqs = [eng.add_request(p, greedy(8)) for p in [long_p] + shorts]
    run_all(eng, reqs)
    assert [r.generated_ids for r in reqs] == wants


def test_ttft_and_kv_accounting(params):
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, CFG.vocab_size, 70).tolist()
    eng = make_engine(params, chunk=32)
    req = eng.generate(prompt, greedy(5))
    assert req.queue_wait_s is not None and req.queue_wait_s >= 0
    assert req.num_computed_tokens == req.num_prompt_tokens
    stats = eng.kv_stats()
    assert stats["used_blocks"] == 0, stats


def test_short_prompts_never_chunk(params):
    """Prompts <= chunk size take the normal batched-prefill path."""
    rng = np.random.default_rng(4)
    eng = make_engine(params, chunk=32)
    reqs = [eng.add_request(rng.integers(0, CFG.vocab_size, 10).tolist(), greedy(4))
            for _ in range(3)]
    run_all(eng, reqs)
    assert eng.scheduler.num_scheduled_prefills >= 1
    for r in reqs:
        assert len(r.generated_ids) == 4


def test_multistep_decode_with_chunked_prefill(params):
    """Chunked prefill composes with fused multi-step decode."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, CFG.vocab_size, 70).tolist()
    want = oracle(params, prompt, greedy(9))
    ecfg = EngineConfig(model="tiny", dtype="float32", max_model_len=256,
                       block_size=8, num_blocks=128, max_num_seqs=4,
                       prefill_chunk_tokens=32, decode_steps=4)
    runner = ModelRunner(CFG, params, decode_steps=4)
    eng = LLMEngine(ecfg, model_cfg=CFG, runner=runner)
    req = eng.generate(prompt, greedy(9))
    assert req.generated_ids == want


def test_next_chunk_stays_on_compile_ladder():
    """Every emitted padded_len is in cfg.chunk_ladder(), even when the
    chunk would overrun the block table near max_model_len — the scheduler
    splits the chunk onto a smaller rung instead of clamping to an
    off-ladder (fresh-compile) length."""
    from agentic_traffic_testing_tpu.runtime.block_allocator import (
        make_block_allocator,
    )
    from agentic_traffic_testing_tpu.runtime.request import Request
    from agentic_traffic_testing_tpu.runtime.scheduler import (
        Scheduler,
        SchedulerConfig,
    )

    cfg = SchedulerConfig(max_model_len=4096, block_size=16,
                          prefill_chunk_tokens=1024)
    sched = Scheduler(cfg, make_block_allocator(600, 16))
    ladder = cfg.chunk_ladder()

    # The verdict-finding shape: 3200 cached tokens of a 4000-token prompt;
    # the naive clamp would emit padded = 4096 - 3200 = 896 (off-ladder).
    req = Request(request_id="r", prompt_ids=list(range(4000)),
                  sampling=SamplingParams(max_tokens=4))
    req.num_computed_tokens = 3200
    seen = []
    while req.num_computed_tokens < req.num_prompt_tokens:
        plan = sched._next_chunk(req)
        assert plan.padded_len in ladder, (plan.padded_len, ladder)
        assert plan.chunk_len <= plan.padded_len
        assert plan.chunk_start + plan.padded_len <= 4096
        seen.append((plan.chunk_len, plan.padded_len))
        req.num_computed_tokens += plan.chunk_len
    assert sum(c for c, _ in seen) == 800


@pytest.mark.parametrize("plen", [64, 100])
def test_chunk_flash_site_matches_unchunked_greedy(params, plen, monkeypatch):
    """ATT_CHUNK_ATTENTION=flash swaps the chunk attention site for the
    pallas chunk-flash kernel (interpret mode here): greedy output must
    match the unchunked oracle exactly, including the bucketed prior
    width's garbage tail and partial final chunks. A call counter pins
    that the kernel actually ran — the jnp fallback would produce the
    same tokens, so output equality alone cannot catch a disconnected
    dispatch."""
    from agentic_traffic_testing_tpu.ops.pallas import chunk_flash as cfmod

    calls = []
    real = cfmod.chunk_flash_attention

    def counting(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(cfmod, "chunk_flash_attention", counting)
    monkeypatch.setenv("ATT_CHUNK_ATTENTION", "flash")
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, CFG.vocab_size, plen).tolist()
    want = oracle(params, prompt, greedy(10))
    eng = make_engine(params, chunk=32)
    req = eng.generate(prompt, greedy(10))
    assert req.generated_ids == want
    assert calls, "chunk_flash_attention was never invoked"
