"""Sampling op tests: greedy, temperature, top-k/top-p filtering, determinism."""

import numpy as np

import jax.numpy as jnp

from agentic_traffic_testing_tpu.ops.sampling import make_row_keys, sample


def _params(b, temp=0.0, top_k=0, top_p=1.0):
    return (
        jnp.full((b,), temp, jnp.float32),
        jnp.full((b,), top_k, jnp.int32),
        jnp.full((b,), top_p, jnp.float32),
    )


def test_greedy_picks_argmax():
    logits = jnp.asarray(np.random.default_rng(0).normal(size=(4, 32)), jnp.float32)
    keys = make_row_keys(jnp.arange(4), jnp.zeros(4, jnp.int32))
    t, k, p = _params(4, temp=0.0)
    out = sample(logits, keys, t, k, p)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(jnp.argmax(logits, -1)))


def test_top_k_restricts_support():
    rng = np.random.default_rng(1)
    logits = jnp.asarray(rng.normal(size=(2, 64)), jnp.float32)
    top2 = np.argsort(-np.asarray(logits), axis=-1)[:, :2]
    t, k, p = _params(2, temp=1.5, top_k=2)
    for step in range(20):
        keys = make_row_keys(jnp.asarray([7, 8]), jnp.full((2,), step, jnp.int32))
        out = np.asarray(sample(logits, keys, t, k, p))
        for row in range(2):
            assert out[row] in top2[row]


def test_top_p_keeps_at_least_one():
    logits = jnp.asarray(np.eye(3, 16) * 50.0, jnp.float32)  # near-delta rows + flat row
    t, k, p = _params(3, temp=1.0, top_p=0.01)
    keys = make_row_keys(jnp.arange(3), jnp.zeros(3, jnp.int32))
    out = np.asarray(sample(logits, keys, t, k, p))
    assert out[0] == 0 and out[1] == 1  # nucleus collapses to the argmax


def test_per_row_determinism_is_batch_independent():
    """A request's sampled token depends only on (seed, step), not batchmates."""
    rng = np.random.default_rng(2)
    row = rng.normal(size=(1, 128)).astype(np.float32)
    big = np.concatenate([row, rng.normal(size=(5, 128)).astype(np.float32)])
    t1, k1, p1 = _params(1, temp=0.9, top_k=40, top_p=0.95)
    t6, k6, p6 = _params(6, temp=0.9, top_k=40, top_p=0.95)
    for step in range(5):
        keys1 = make_row_keys(jnp.asarray([42]), jnp.full((1,), step, jnp.int32))
        keys6 = make_row_keys(jnp.asarray([42, 1, 2, 3, 4, 5]), jnp.full((6,), step, jnp.int32))
        a = np.asarray(sample(jnp.asarray(row), keys1, t1, k1, p1))[0]
        b = np.asarray(sample(jnp.asarray(big), keys6, t6, k6, p6))[0]
        assert a == b
