"""Pipelined prefill (LLM_PREFILL_PIPELINE): dispatch overlap must be a pure
performance knob.

The round-6 path splits solo/batched prefills into K position-chunks
dispatched back-to-back with no host synchronization (engine.
_run_prefill_pipelined -> runner.prefill_pipeline -> models/llama.
prefill_pipeline_impl). Invariants pinned here:

  * knob OFF (default): the single-dispatch path runs exactly as before —
    one runner.prefill call, zero pipeline dispatches, oracle-equal output.
  * knob ON: outputs are token-identical to the single-dispatch engine for
    greedy and seeded sampling, solo and batched (mixed real lengths in one
    bucket), with decode and KV accounting unaffected.
  * the ASYNC pipelining itself is free: pages after the tail readback are
    byte-identical to the same chunk dispatches run with a host sync after
    each. Cross-path (pipeline vs single dispatch) pages agree to fp
    tolerance with layer 0 exact — the chunked attention site reduces its
    softmax over a different kv width than the in-register site, which
    costs last-ulp differences (the same structural property the serial
    chunked-prefill suite pins token-identity across).
  * config guards: speculation x pipeline composes (round 14); decode_steps auto-scale
    (ROADMAP bs32 nibble) resolves as documented.
"""

import numpy as np
import pytest

# Heavyweight tier: CPU jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

CFG = PRESETS["tiny"]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def make_engine(params, pipeline, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_num_seqs", 4)
    ecfg = EngineConfig(prefill_pipeline_chunks=pipeline, **kw)
    runner = ModelRunner(CFG, params, decode_steps=1)
    return LLMEngine(ecfg, model_cfg=CFG, runner=runner)


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


def run_all(engine, reqs):
    for _ in range(10_000):
        engine.step()
        if all(r.is_finished() for r in reqs):
            return
        if not engine.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


def oracle(params, prompt, sampling):
    eng = make_engine(params, pipeline=0)
    return eng.generate(prompt, sampling).generated_ids


def test_knob_off_is_single_dispatch(params, monkeypatch):
    """Default off: ONE runner.prefill dispatch, pipeline program never
    touched — the bit-identical-to-main contract's observable half."""
    eng = make_engine(params, pipeline=0)
    calls = {"prefill": 0, "pipeline": 0}
    orig = eng.runner.prefill

    def counting(*a, **kw):
        calls["prefill"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(eng.runner, "prefill", counting)
    monkeypatch.setattr(
        eng.runner, "prefill_pipeline",
        lambda *a, **kw: calls.__setitem__("pipeline", calls["pipeline"] + 1))
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, CFG.vocab_size, 20).tolist()
    want = oracle(params, prompt, greedy(6))
    req = eng.generate(prompt, greedy(6))
    assert req.generated_ids == want
    assert calls == {"prefill": 1, "pipeline": 0}
    assert eng.num_pipeline_dispatches == 0


@pytest.mark.parametrize("plen", [20, 28])
def test_pipeline_token_identical_greedy(params, plen):
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, CFG.vocab_size, plen).tolist()
    want = oracle(params, prompt, greedy(8))
    eng = make_engine(params, pipeline=2)
    req = eng.generate(prompt, greedy(8))
    assert req.generated_ids == want
    assert eng.num_pipeline_dispatches == 2  # 32-token bucket / 16-chunks


def test_pipeline_seeded_sampling_matches(params):
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, CFG.vocab_size, 30).tolist()
    sp = lambda: SamplingParams(max_tokens=8, temperature=0.8, top_k=20,
                                seed=9)
    want = oracle(params, prompt, sp())
    eng = make_engine(params, pipeline=2)
    req = eng.generate(prompt, sp())
    assert req.generated_ids == want


def test_pipeline_batched_mixed_lengths(params):
    """Rows of one padded bucket with different REAL lengths: each row's
    first token must merge from the chunk holding ITS last real token."""
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, CFG.vocab_size, n).tolist()
               for n in (6, 17, 30)]  # last tokens land in chunk 0 and 1
    wants = [oracle(params, p, greedy(6)) for p in prompts]
    eng = make_engine(params, pipeline=2)
    reqs = [eng.add_request(p, greedy(6)) for p in prompts]
    run_all(eng, reqs)
    assert [r.generated_ids for r in reqs] == wants
    assert eng.num_pipeline_dispatches > 0
    assert eng.kv_stats()["used_blocks"] == 0


def _prefill_pages(eng, prompt, sync_each_chunk=False):
    """Run ONE prefill step and return the request's real KV page slots.

    `sync_each_chunk` forces a host sync after every pipelined chunk
    dispatch (the anti-pipelining control arm)."""
    if sync_each_chunk:
        orig = eng.runner.prefill_pipeline

        def synced(*a, **kw):
            cache, carry = orig(*a, **kw)
            jax.block_until_ready(carry)
            return cache, carry

        eng.runner.prefill_pipeline = synced
    r = eng.add_request(prompt, greedy(4))
    eng.step()
    row = r.blocks.table_row(eng.table_width)
    n, bs = len(prompt), eng.cfg.block_size
    nb = -(-n // bs)
    kp = np.asarray(jax.device_get(eng.cache.k))[:, :, row[:nb]]
    vp = np.asarray(jax.device_get(eng.cache.v))[:, :, row[:nb]]
    # [L, KH, nb, bs, hdp] -> position-ordered slots, real tokens only
    kp = kp.reshape(kp.shape[0], kp.shape[1], -1, kp.shape[-1])[:, :, :n]
    vp = vp.reshape(vp.shape[0], vp.shape[1], -1, vp.shape[-1])[:, :, :n]
    return kp, vp


def test_async_pipelining_pages_byte_identical(params):
    """The tail readback observes EXACTLY the pages a fully synchronized
    run of the same chunk dispatches produces — the overlap mechanism
    (queued dispatches, donated carry) adds or loses nothing."""
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, CFG.vocab_size, 28).tolist()
    k_async, v_async = _prefill_pages(make_engine(params, pipeline=2), prompt)
    k_sync, v_sync = _prefill_pages(make_engine(params, pipeline=2), prompt,
                                    sync_each_chunk=True)
    assert np.array_equal(k_async, k_sync)
    assert np.array_equal(v_async, v_sync)


def test_pipeline_pages_match_single_dispatch(params):
    """Cross-path pages: layer 0 (no attention upstream of its K/V) must be
    byte-identical; deeper layers agree to fp32 tolerance (the chunk site's
    softmax reduces over a different kv width — last-ulp only)."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, CFG.vocab_size, 28).tolist()
    k0, v0 = _prefill_pages(make_engine(params, pipeline=0), prompt)
    k2, v2 = _prefill_pages(make_engine(params, pipeline=2), prompt)
    assert np.array_equal(k0[0], k2[0])
    assert np.array_equal(v0[0], v2[0])
    np.testing.assert_allclose(k2, k0, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(v2, v0, rtol=1e-5, atol=1e-5)


def test_pipeline_with_multistep_decode(params):
    rng = np.random.default_rng(6)
    prompt = rng.integers(0, CFG.vocab_size, 25).tolist()
    want = oracle(params, prompt, greedy(9))
    ecfg = EngineConfig(model="tiny", dtype="float32", max_model_len=128,
                        block_size=8, num_blocks=64, max_num_seqs=4,
                        prefill_pipeline_chunks=2, decode_steps=4)
    runner = ModelRunner(CFG, params, decode_steps=4)
    eng = LLMEngine(ecfg, model_cfg=CFG, runner=runner)
    req = eng.generate(prompt, greedy(9))
    assert req.generated_ids == want


def test_warmup_covers_pipeline_program(params, monkeypatch):
    """warmup_prefill_buckets warms the PIPELINE program (not the dead
    single-dispatch one) when the knob routes live prefills there."""
    eng = make_engine(params, pipeline=2)
    calls = {"pipeline": 0, "prefill": 0}
    orig = eng.runner.prefill_pipeline
    monkeypatch.setattr(
        eng.runner, "prefill_pipeline",
        lambda *a, **kw: calls.__setitem__(
            "pipeline", calls["pipeline"] + 1) or orig(*a, **kw))
    origp = eng.runner.prefill
    monkeypatch.setattr(
        eng.runner, "prefill",
        lambda *a, **kw: calls.__setitem__(
            "prefill", calls["prefill"] + 1) or origp(*a, **kw))
    n = eng.warmup_prefill_buckets(max_len=32)
    assert n > 0
    assert calls["pipeline"] == n and calls["prefill"] == 0


def test_pipeline_composes_with_speculation():
    # Round 14: the spec prefill handoff is the same async DecodeState
    # handoff as plain decode (no first-token readback to pipeline past),
    # so the combination BUILDS (identity pinned in test_speculative.py).
    EngineConfig(prefill_pipeline_chunks=2, speculation="ngram")


def test_pipeline_rejects_negative():
    with pytest.raises(ValueError, match="prefill_pipeline_chunks"):
        EngineConfig(prefill_pipeline_chunks=-1)


def test_resolved_decode_steps_scales_with_batch():
    """ROADMAP item 2 (bs32 nibble): unset LLM_DECODE_STEPS auto-scales
    the fused dispatch length with the lane count on TPU; explicit values
    and non-TPU platforms are untouched."""
    assert EngineConfig(max_num_seqs=8).resolved_decode_steps("tpu") == 16
    assert EngineConfig(max_num_seqs=12).resolved_decode_steps("tpu") == 16
    assert EngineConfig(max_num_seqs=32).resolved_decode_steps("tpu") == 32
    assert EngineConfig(max_num_seqs=64).resolved_decode_steps("tpu") == 32
    assert EngineConfig(max_num_seqs=32).resolved_decode_steps("cpu") == 1
    assert EngineConfig(max_num_seqs=32,
                        decode_steps=16).resolved_decode_steps("tpu") == 16
