"""Paged-KV prefill + decode must reproduce the full no-cache forward.

This is the correctness surface vLLM covers with its paged-attention CUDA
kernels (which the reference consumes via the `vllm` wheel — reference:
llm/serve_llm.py:22-34); here the block-table read/write path is first-party
and is diffed against `forward_full` token by token.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import (
    decode_step,
    forward_full,
    init_params,
    prefill,
)
from agentic_traffic_testing_tpu.runtime.kv_cache import (
    TRASH_BLOCK,
    make_kv_cache,
)

import jax

BLOCK_SIZE = 4


@pytest.fixture(scope="module")
def setup():
    cfg = PRESETS["tiny"]
    params = init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


def _block_tables(lens, max_blocks, bs):
    """Sequential block allocation: seq i gets blocks [start, start+n)."""
    bt = np.full((len(lens), max_blocks), TRASH_BLOCK, np.int32)
    nxt = 1  # block 0 is trash
    for i, ln in enumerate(lens):
        n = -(-ln // bs)
        bt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
    return jnp.asarray(bt), nxt


def test_prefill_matches_full_forward(setup):
    cfg, params = setup
    rng = np.random.default_rng(0)
    lens = [5, 8, 3]
    t_pad = 8
    tokens = np.zeros((3, t_pad), np.int32)
    for i, ln in enumerate(lens):
        tokens[i, :ln] = rng.integers(0, cfg.vocab_size, ln)

    bt, _ = _block_tables([t_pad] * 3, max_blocks=8, bs=BLOCK_SIZE)
    cache = make_kv_cache(cfg, num_blocks=32, block_size=BLOCK_SIZE, dtype=jnp.float32)
    logits, cache = prefill(params, cfg, jnp.asarray(tokens), cache, bt, jnp.asarray(lens, jnp.int32))

    for i, ln in enumerate(lens):
        full = forward_full(params, cfg, jnp.asarray(tokens[i:i + 1, :ln]))
        np.testing.assert_allclose(
            np.asarray(logits[i]), np.asarray(full[0, ln - 1]), atol=2e-4, rtol=2e-3
        )


def test_decode_steps_match_full_forward(setup):
    cfg, params = setup
    rng = np.random.default_rng(1)
    lens = [6, 2]
    t_pad = 8
    n_decode = 5
    tokens = np.zeros((2, t_pad), np.int32)
    seqs = [rng.integers(0, cfg.vocab_size, ln).tolist() for ln in lens]
    for i, s in enumerate(seqs):
        tokens[i, :len(s)] = s

    max_blocks = 8
    # Allocate enough blocks for prompt + all decode steps (no accidental
    # reliance on the trash block absorbing overflow writes).
    bt, _ = _block_tables([t_pad + n_decode] * 2, max_blocks, BLOCK_SIZE)
    cache = make_kv_cache(cfg, num_blocks=32, block_size=BLOCK_SIZE, dtype=jnp.float32)
    logits, cache = prefill(params, cfg, jnp.asarray(tokens), cache, bt, jnp.asarray(lens, jnp.int32))

    # Greedy-continue each sequence through the paged decode path.
    for step in range(n_decode):
        next_tok = np.argmax(np.asarray(logits), axis=-1).astype(np.int32)
        for i in range(2):
            seqs[i].append(int(next_tok[i]))
        positions = jnp.asarray([len(s) - 1 for s in seqs], jnp.int32)
        logits, cache = decode_step(
            params, cfg, jnp.asarray(next_tok), cache, bt, positions
        )
        for i in range(2):
            full = forward_full(params, cfg, jnp.asarray([seqs[i]], jnp.int32))
            np.testing.assert_allclose(
                np.asarray(logits[i]),
                np.asarray(full[0, -1]),
                atol=5e-4,
                rtol=2e-3,
                err_msg=f"seq {i} step {step}",
            )


def test_decode_with_inactive_lanes(setup):
    """Padding lanes (trash block tables, position 0) must not corrupt real lanes."""
    cfg, params = setup
    rng = np.random.default_rng(2)
    seq = rng.integers(0, cfg.vocab_size, 4).tolist()
    tokens = np.zeros((4, 4), np.int32)
    tokens[0, :4] = seq

    bt = np.full((4, 8), TRASH_BLOCK, np.int32)
    bt[0, :2] = [1, 2]
    cache = make_kv_cache(cfg, num_blocks=16, block_size=BLOCK_SIZE, dtype=jnp.float32)
    logits, cache = prefill(
        params, cfg, jnp.asarray(tokens), cache, jnp.asarray(bt),
        jnp.asarray([4, 0, 0, 0], jnp.int32),
    )
    next_tok = int(np.argmax(np.asarray(logits[0])))
    seq.append(next_tok)
    logits2, cache = decode_step(
        params, cfg,
        jnp.asarray([next_tok, 0, 0, 0], jnp.int32),
        cache, jnp.asarray(bt),
        jnp.asarray([4, 0, 0, 0], jnp.int32),
    )
    full = forward_full(params, cfg, jnp.asarray([seq], jnp.int32))
    np.testing.assert_allclose(
        np.asarray(logits2[0]), np.asarray(full[0, -1]), atol=5e-4, rtol=2e-3
    )
