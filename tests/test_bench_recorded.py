"""bench.py's recorded-result fallback (round 5, r4 verdict weak #6):
when the live device probe fails, the launcher must emit the newest
watcher-recorded measurement — clearly labeled — instead of zeroing the
round's one perf artifact.
"""

import importlib.util
import json
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.join(os.path.dirname(os.path.abspath(__file__)), "..")

spec = importlib.util.spec_from_file_location(
    "bench", os.path.join(REPO, "bench.py"))
bench = importlib.util.module_from_spec(spec)
spec.loader.exec_module(bench)


def test_latest_recorded_prefers_newest_and_headline_tag(tmp_path):
    docs = tmp_path / "docs"
    docs.mkdir()
    older = docs / "bench_sweep_r4.jsonl"
    older.write_text(
        json.dumps({"metric": "decode_throughput_x", "value": 1.0,
                    "sweep_tag": "8b-int4-bs8"}) + "\n"
        + json.dumps({"metric": "decode_throughput_y", "value": 2.0,
                      "sweep_tag": "1b-bf16-bs32"}) + "\n")
    newer = docs / "bench_watcher_20990101T000000Z.json"
    newer.write_text(json.dumps(
        {"metric": "decode_throughput_z", "value": 3.0}) + "\n")
    past = time.time() - 1000
    os.utime(older, (past, past))

    rec = bench.latest_recorded_result(str(docs))
    assert rec is not None
    assert rec["row"]["value"] == 3.0          # newest file wins

    newer.unlink()
    rec = bench.latest_recorded_result(str(docs))
    # Within a sweep file, the headline 1b-bf16-bs32 row wins over later rows.
    assert rec["row"]["sweep_tag"] == "1b-bf16-bs32"


def test_latest_recorded_skips_error_lines_and_garbage(tmp_path):
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "bench_watcher_a.json").write_text(
        json.dumps({"metric": None, "error": "no usable backend"}) + "\n"
        + "not json\n")
    assert bench.latest_recorded_result(str(docs)) is None
    assert bench.latest_recorded_result(str(tmp_path / "missing")) is None


@pytest.mark.full
def test_launcher_emits_recorded_line_when_probe_fails(tmp_path):
    """End-to-end: a guaranteed-failing probe (bogus platform) + a recorded
    artifact => rc=0 and a clearly-labeled recorded JSON line."""
    docs = tmp_path / "repo_docs"
    docs.mkdir()
    row = {"metric": "decode_throughput_llama-3.2-1b_bs32_n96_tpu",
           "value": 4132.0, "unit": "tok/s", "vs_baseline": 2.066}
    (docs / "bench_watcher_test.json").write_text(json.dumps(row) + "\n")

    env = dict(os.environ)
    env.update({"JAX_PLATFORMS": "bogus", "BENCH_ATTEMPTS": "1",
                "BENCH_PROBE_TIMEOUT": "60"})
    # Point the launcher at the fixture docs dir via a wrapper that
    # monkeypatches latest_recorded_result's default path.
    wrapper = (
        "import importlib.util, sys, functools\n"
        f"spec = importlib.util.spec_from_file_location('bench', {str(os.path.join(REPO, 'bench.py'))!r})\n"
        "bench = importlib.util.module_from_spec(spec)\n"
        "spec.loader.exec_module(bench)\n"
        "orig = bench.latest_recorded_result\n"
        f"bench.latest_recorded_result = functools.partial(orig, {str(docs)!r})\n"
        "sys.exit(bench.launcher())\n"
    )
    proc = subprocess.run([sys.executable, "-c", wrapper], env=env,
                          capture_output=True, text=True, timeout=300,
                          cwd=REPO)
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert proc.returncode == 0, proc.stderr[-500:]
    assert out["recorded"] is True
    assert out["value"] == 4132.0
    assert "bench_watcher_test.json" in out["recorded_from"]
    assert out["recorded_utc"].endswith("Z")
    assert "live_probe_error" in out
