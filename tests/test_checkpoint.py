"""Sharded training checkpoint/resume (training/checkpoint.py).

Round-trips a TP-sharded train state through orbax on the virtual CPU mesh,
including restore onto a DIFFERENT mesh layout, and verifies training
resumes bit-continuously.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
from agentic_traffic_testing_tpu.training.checkpoint import TrainCheckpointer
from agentic_traffic_testing_tpu.training.train import (
    init_train_state,
    make_train_step,
)

CFG = ModelConfig(
    name="ckpt-test", vocab_size=128, hidden_size=32, intermediate_size=64,
    num_layers=2, num_heads=4, num_kv_heads=2, head_dim=8,
)


def _batch(seed):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 16)), jnp.int32)
    return tokens, jnp.ones_like(tokens, jnp.float32)


def test_roundtrip_and_resume(tmp_path):
    mesh = make_mesh(tp=2)
    opt = optax.adamw(1e-3)
    params, opt_state = init_train_state(CFG, mesh, opt)
    step = make_train_step(CFG, mesh, opt)

    params, opt_state, _ = step(params, opt_state, *_batch(0))
    ck = TrainCheckpointer(str(tmp_path / "ck"), max_to_keep=2)
    ck.save(1, params, opt_state, wait=True)

    # Continue the reference run two more steps.
    p_ref, o_ref = params, opt_state
    losses_ref = []
    for i in (1, 2):
        p_ref, o_ref, loss = step(p_ref, o_ref, *_batch(i))
        losses_ref.append(float(loss))

    # Restore and replay: identical losses and final params.
    got_step, p2, o2 = ck.restore(params, opt_state)
    assert got_step == 1
    losses = []
    for i in (1, 2):
        p2, o2, loss = step(p2, o2, *_batch(i))
        losses.append(float(loss))
    assert losses == pytest.approx(losses_ref, abs=1e-7)
    for a, b in zip(jax.tree_util.tree_leaves(p_ref),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    ck.close()


def test_restore_onto_different_mesh(tmp_path):
    """A tp=2 checkpoint restores directly onto a (dp=2, tp=2) layout."""
    opt = optax.adamw(1e-3)
    mesh_a = make_mesh(tp=2)
    params, opt_state = init_train_state(CFG, mesh_a, opt)
    ck = TrainCheckpointer(str(tmp_path / "ck"))
    ck.save(0, params, opt_state, wait=True)

    mesh_b = make_mesh(dp=2, tp=2)
    target_p, target_o = init_train_state(CFG, mesh_b, opt, seed=1)
    _, p2, o2 = ck.restore(target_p, target_o)
    # values come from the checkpoint, sharding from the new mesh
    for a, b in zip(jax.tree_util.tree_leaves(params),
                    jax.tree_util.tree_leaves(p2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    wq = p2["layers"]["wq"]
    assert wq.sharding.mesh.shape["dp"] == 2
    ck.close()


def test_retention_and_latest(tmp_path):
    mesh = make_mesh(tp=2)
    opt = optax.adamw(1e-3)
    params, opt_state = init_train_state(CFG, mesh, opt)
    ck = TrainCheckpointer(str(tmp_path / "ck"), max_to_keep=2)
    for s in (0, 1, 2):
        ck.save(s, params, opt_state, wait=True)
    assert ck.latest_step() == 2
    ck2 = TrainCheckpointer(str(tmp_path / "ck"), max_to_keep=2)
    got, _, _ = ck2.restore(params, opt_state)
    assert got == 2
    ck.close(); ck2.close()


def test_restore_missing_raises(tmp_path):
    ck = TrainCheckpointer(str(tmp_path / "empty"))
    with pytest.raises(FileNotFoundError):
        ck.restore({}, {})
    ck.close()
