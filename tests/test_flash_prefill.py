"""First-party causal flash kernel (ops/pallas/chunk_flash.py round-4):
interpret-mode equivalence vs the jnp oracle at serving-bucket shapes.

The solo/batched prefill site (ops/flash_prefill.py) routes to
`causal_flash_attention` on TPU; these tests pin the kernel's numerics on
CPU via pallas interpret mode (SURVEY.md §4 kernel-test strategy), across
batch, GQA grouping, multi-block grids, and the odd (non-power-of-two)
buckets the pow2-divisor block picker must serve. The chunked-site entry
point (`chunk_flash_attention`, same kernel body) keeps its own tests in
test_chunked_prefill.py.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
from agentic_traffic_testing_tpu.ops.pallas.chunk_flash import (
    causal_flash_attention,
)


def _mk(b, t, h, kh, hd, seed=0):
    ks = jax.random.split(jax.random.key(seed), 3)
    q = jax.random.normal(ks[0], (b, t, h, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, t, kh, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, t, kh, hd), jnp.float32)
    return q, k, v


def _oracle(q, k, v):
    b, t = q.shape[:2]
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    return causal_attention(q, k, v, q_positions=pos,
                            kv_valid_len=jnp.full((b,), t, jnp.int32))


@pytest.mark.parametrize("b,t,h,kh,hd", [
    (1, 256, 4, 4, 64),     # solo, MHA
    (1, 256, 8, 2, 64),     # solo, GQA 4:1 (llama-1B head layout)
    (3, 256, 8, 2, 64),     # batched prefill
    (1, 512, 4, 2, 128),    # hd=128 lane tile
])
def test_causal_flash_matches_oracle(b, t, h, kh, hd):
    q, k, v = _mk(b, t, h, kh, hd)
    want = _oracle(q, k, v)
    got = causal_flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_causal_flash_multiblock_grid_and_skip():
    """T large enough that the grid has several q and kv blocks, so the
    online-softmax carry across kv blocks AND the beyond-diagonal compute
    skip are both exercised (a wrong skip bound shows up as a softmax
    normalization error on the block boundary rows)."""
    q, k, v = _mk(1, 2048, 4, 1, 64, seed=1)
    want = _oracle(q, k, v)
    got = causal_flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_causal_flash_odd_bucket():
    """640 = the odd serving bucket from the round-3 blocker: not a
    multiple of 512/256, so the block picker must fall to 128-token
    blocks and pad kv to the 640-tile — no trace-time ValueError, exact
    numerics."""
    q, k, v = _mk(1, 640, 8, 2, 64, seed=2)
    want = _oracle(q, k, v)
    got = causal_flash_attention(q, k, v, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_causal_flash_bf16_matches_oracle():
    """Serving dtype: bf16 q/k/v through the kernel (f32 accumulation
    in-kernel, output cast back) tracks the oracle within bf16 rounding."""
    q, k, v = [x.astype(jnp.bfloat16) for x in _mk(2, 256, 8, 2, 64, seed=4)]
    want = _oracle(q, k, v).astype(jnp.float32)
    got = causal_flash_attention(q, k, v, interpret=True).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-2, atol=2e-2)


def test_padded_tail_rows_do_not_corrupt_real_rows():
    """The site contract (ops/flash_prefill.py): padding only at the tail,
    causality alone protects real rows. Real rows' outputs must be
    identical whether the tail holds garbage or real tokens."""
    b, t, real = 1, 256, 200
    q, k, v = _mk(b, t, 4, 2, 64, seed=3)
    got_full = causal_flash_attention(q, k, v, interpret=True)
    junk = jnp.full_like(k[:, real:], 37.0)
    got_junk = causal_flash_attention(
        q,
        k.at[:, real:].set(junk), v.at[:, real:].set(junk),
        interpret=True)
    np.testing.assert_allclose(np.asarray(got_junk[:, :real]),
                               np.asarray(got_full[:, :real]),
                               rtol=2e-5, atol=2e-5)


def test_prefill_attention_env_escape_hatch(monkeypatch):
    """ATT_PREFILL_ATTENTION routes the site (round-4 advisor): `jnp`
    forces the oracle even at kernel-eligible shapes, `library` routes to
    the preserved jax.experimental path, default routes to the first-party
    kernel. Routing is pinned by stubbing the two kernel targets — their
    numerics have their own tests (and the library kernel needs Mosaic)."""
    from agentic_traffic_testing_tpu.ops import flash_prefill

    b, t, h, kh, hd = 1, 256, 4, 2, 64
    q, k, v = _mk(b, t, h, kh, hd)
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    vlen = jnp.full((b,), t, jnp.int32)
    want = _oracle(q, k, v)

    # Make the TPU-only shape gate pass on CPU so routing is observable.
    monkeypatch.setattr(flash_prefill, "_flash_ok", lambda tq, hd: True)
    calls = []
    monkeypatch.setattr(flash_prefill, "_library_flash_attention",
                        lambda q, k, v: calls.append("library") or want)
    import agentic_traffic_testing_tpu.ops.pallas.chunk_flash as cf
    monkeypatch.setattr(cf, "causal_flash_attention",
                        lambda q, k, v: calls.append("flash") or want)

    monkeypatch.setenv("ATT_PREFILL_ATTENTION", "jnp")
    got = flash_prefill.prefill_attention(q, k, v, q_positions=pos,
                                          kv_valid_len=vlen)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-6)
    assert calls == []

    monkeypatch.setenv("ATT_PREFILL_ATTENTION", "library")
    flash_prefill.prefill_attention(q, k, v, q_positions=pos,
                                    kv_valid_len=vlen)
    assert calls == ["library"]

    monkeypatch.delenv("ATT_PREFILL_ATTENTION")
    flash_prefill.prefill_attention(q, k, v, q_positions=pos,
                                    kv_valid_len=vlen)
    assert calls == ["library", "flash"]
