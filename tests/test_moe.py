"""MoE (models/moe.py) correctness: HF Mixtral golden logits, dense-oracle
equivalence, capacity-drop semantics, and the training aux-loss wiring.

The reference serves dense Llama only (SURVEY.md §2.3), so the oracle here
is transformers' MixtralForCausalLM instantiated locally (no hub access) —
the same golden pattern as tests/test_model_golden.py. Capacity note: HF
Mixtral never drops tokens; our GShard-style capacity can. At
capacity_factor >= num_experts dropping is impossible, so logits must match
HF exactly; the drop path is pinned separately.
"""

import dataclasses

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS, ModelConfig
from agentic_traffic_testing_tpu.models.llama import (
    forward_full,
    init_params,
    init_params_quantized,
)
from agentic_traffic_testing_tpu.models.moe import expert_capacity, moe_mlp
from agentic_traffic_testing_tpu.models.weights import params_from_hf_state_dict

MOE_CFG = PRESETS["tiny-moe"]


def _mixtral_pair(seed=0, cf=None):
    """(our cfg, our params, hf model) from one tiny random Mixtral."""
    import torch
    from transformers import MixtralConfig, MixtralForCausalLM

    torch.manual_seed(seed)
    hf_cfg = MixtralConfig(
        vocab_size=96, hidden_size=48, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2, rope_theta=10000.0,
        rms_norm_eps=1e-5, max_position_embeddings=128,
        tie_word_embeddings=False,
    )
    model = MixtralForCausalLM(hf_cfg).eval()
    cfg = ModelConfig.from_hf_config(hf_cfg.to_dict(), name="tiny-mixtral")
    if cf is not None:
        cfg = dataclasses.replace(cfg, moe_capacity_factor=cf)
    sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    params = params_from_hf_state_dict(cfg, sd, dtype=np.float32)
    return cfg, params, model


def test_mixtral_golden_logits_no_drop():
    """cf = E makes capacity dropping impossible -> exact HF numerics."""
    import torch

    cfg, params, model = _mixtral_pair(cf=4.0)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, cfg.vocab_size, (2, 12))
    ours = forward_full(params, cfg, jnp.asarray(tokens, jnp.int32))
    with torch.no_grad():
        theirs = model(torch.tensor(tokens)).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours, np.float32), theirs,
                               atol=3e-4, rtol=2e-3)


def test_moe_mlp_matches_dense_oracle():
    """moe_mlp's einsum dispatch/combine == explicit per-token top-k SwiGLU
    (no drops at cf=E)."""
    cfg = dataclasses.replace(MOE_CFG, moe_capacity_factor=float(MOE_CFG.num_experts))
    params = init_params(cfg, jax.random.key(1), dtype=jnp.float32)
    lp = {k: v[0] for k, v in params["layers"].items()
          if k in ("w_router", "w_gate", "w_up", "w_down")}
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 6, cfg.hidden_size)), jnp.float32)

    y, aux = moe_mlp(x, lp, cfg)

    # Oracle: loop tokens in numpy/jnp, no dispatch tensors.
    logits = np.einsum("btd,de->bte", np.asarray(x, np.float64),
                       np.asarray(lp["w_router"], np.float64))
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    want = np.zeros_like(np.asarray(x, np.float64))
    for b in range(x.shape[0]):
        for t in range(x.shape[1]):
            topk = np.argsort(-probs[b, t])[: cfg.num_experts_per_tok]
            gates = probs[b, t, topk] / probs[b, t, topk].sum()
            for g, e in zip(gates, topk):
                xe = np.asarray(x, np.float64)[b, t]
                gate = xe @ np.asarray(lp["w_gate"], np.float64)[e]
                up = xe @ np.asarray(lp["w_up"], np.float64)[e]
                act = gate / (1 + np.exp(-gate)) * up
                want[b, t] += g * (act @ np.asarray(lp["w_down"], np.float64)[e])
    np.testing.assert_allclose(np.asarray(y, np.float64), want,
                               atol=1e-4, rtol=1e-3)
    assert np.isfinite(float(aux)) and float(aux) > 0


def test_capacity_drops_assignments():
    """cf small enough forces drops: output differs from the no-drop run,
    and the dropped token keeps its other experts' contributions (finite,
    not zeroed)."""
    cfg_full = dataclasses.replace(MOE_CFG, moe_capacity_factor=float(MOE_CFG.num_experts))
    cfg_tight = dataclasses.replace(MOE_CFG, moe_capacity_factor=0.25)
    assert expert_capacity(8, cfg_tight) < expert_capacity(8, cfg_full)
    params = init_params(MOE_CFG, jax.random.key(3), dtype=jnp.float32)
    lp = {k: v[0] for k, v in params["layers"].items()
          if k in ("w_router", "w_gate", "w_up", "w_down")}
    x = jnp.asarray(np.random.default_rng(4).standard_normal((1, 8, MOE_CFG.hidden_size)),
                    jnp.float32)
    y_full, _ = moe_mlp(x, lp, cfg_full)
    y_tight, _ = moe_mlp(x, lp, cfg_tight)
    assert np.isfinite(np.asarray(y_tight)).all()
    assert not np.allclose(np.asarray(y_full), np.asarray(y_tight))


def test_train_step_includes_aux_loss():
    """ADVICE r1: the Switch aux term must actually reach the objective.
    With optax.sgd(0) the reported loss is pure objective: it must equal
    lm_loss + coeff * aux and move with the coefficient."""
    import optax

    from agentic_traffic_testing_tpu.models.llama import forward_full_impl
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.training.train import (
        causal_lm_loss,
        init_train_state,
        make_train_step,
    )

    cfg = MOE_CFG
    mesh = make_mesh(1, 1, 1, devices=jax.devices()[:1])
    opt = optax.sgd(0.0)
    rng = np.random.default_rng(5)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 16)), jnp.int32)
    mask = jnp.ones((2, 16), jnp.float32)

    params, opt_state = init_train_state(cfg, mesh, opt, seed=7)
    logits, aux = forward_full_impl(params, cfg, tokens, with_aux=True)
    lm = float(causal_lm_loss(logits, tokens, mask))
    aux = float(aux)
    assert aux > 0

    for coeff in (0.0, 0.01, 0.1):
        p, o = init_train_state(cfg, mesh, opt, seed=7)
        ts = make_train_step(cfg, mesh, opt, remat=False, moe_aux_coeff=coeff)
        _, _, loss = ts(p, o, tokens, mask)
        np.testing.assert_allclose(float(loss), lm + coeff * aux, rtol=1e-5)


def test_pipeline_moe_matches_microbatched_oracle():
    """Pipelined MoE training banks each tick's load-balance aux: the loss
    must equal lm(full batch) + coeff * mean_m aux(microbatch_m) — the
    gradient-accumulation convention (routing/drops are microbatch-invariant
    since capacity competition is per sequence, so only the aux means
    differ from the unpipelined objective) — and one optimizer step must
    match a pure-GSPMD oracle of that exact objective."""
    import optax

    from agentic_traffic_testing_tpu.models.llama import forward_full_impl
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.pipeline import (
        init_pp_train_state,
        make_pp_train_step,
    )
    from agentic_traffic_testing_tpu.training.train import (
        causal_lm_loss,
        init_train_state,
    )

    cfg, m, coeff = MOE_CFG, 2, 0.05
    rng = np.random.default_rng(9)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (4, 16)), jnp.int32)
    mask = jnp.ones((4, 16), jnp.float32)
    opt = optax.adamw(1e-3)

    mesh1 = make_mesh(1, 1, 1, devices=jax.devices()[:1])
    ref_params, ref_opt = init_train_state(cfg, mesh1, opt, seed=3)

    def oracle_loss(params):
        logits = forward_full_impl(params, cfg, tokens)
        lm = causal_lm_loss(logits, tokens, mask)
        mb = tokens.shape[0] // m
        aux = sum(
            forward_full_impl(params, cfg, tokens[i * mb:(i + 1) * mb],
                              with_aux=True)[1]
            for i in range(m))
        return lm + coeff * aux / m

    loss_ref, grads = jax.jit(jax.value_and_grad(oracle_loss))(ref_params)
    updates, _ = opt.update(grads, ref_opt, ref_params)
    ref_after = optax.apply_updates(ref_params, updates)

    mesh = make_mesh(pp=2)
    pp_params, pp_opt = init_pp_train_state(cfg, mesh, opt, seed=3)
    step = make_pp_train_step(cfg, mesh, opt, num_microbatches=m,
                              moe_aux_coeff=coeff)
    pp_params, _, loss_pp = step(pp_params, pp_opt, tokens, mask)
    assert np.isclose(float(loss_pp), float(loss_ref), atol=1e-5), (
        float(loss_pp), float(loss_ref))
    for a, b in zip(jax.tree_util.tree_leaves(ref_after),
                    jax.tree_util.tree_leaves(pp_params)):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   atol=2e-5, rtol=2e-5)


def test_engine_capacity_override_and_validation():
    """The capacity knob rides EngineConfig, so every construction path —
    server, bench, direct — honors it; <= 0 is rejected at config time."""
    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine

    eng = LLMEngine(EngineConfig(model="tiny-moe", dtype="float32",
                                 num_blocks=32, moe_capacity_factor=4.0))
    assert eng.model_cfg.moe_capacity_factor == 4.0
    with pytest.raises(ValueError, match="moe_capacity_factor"):
        EngineConfig(model="tiny-moe", moe_capacity_factor=0.0)


# ------------------------------------------------------ expert parallelism


def test_moe_forward_matches_under_ep_sharding():
    """EP is only a sharding: params placed with P('ep', ...) on the expert
    axis must reproduce single-device logits (GSPMD inserts the all-to-alls
    on the dispatch/combine einsums)."""
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.sharding import shard_params

    params = init_params(MOE_CFG, jax.random.key(11), dtype=jnp.float32)
    tokens = jnp.asarray(
        np.random.default_rng(12).integers(0, MOE_CFG.vocab_size, (2, 16)),
        jnp.int32)
    ref = forward_full(params, MOE_CFG, tokens)

    for ep, tp in ((2, 1), (4, 1), (2, 2)):
        mesh = make_mesh(ep=ep, tp=tp)
        sharded = shard_params(params, MOE_CFG, mesh)
        got = forward_full(sharded, MOE_CFG, tokens)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=2e-4, rtol=1e-3, err_msg=f"ep={ep},tp={tp}")


def test_moe_train_step_on_ep_mesh():
    """Full MoE training step (incl. the aux term) over a (dp, ep, tp) mesh:
    first-step loss equals the single-device step's."""
    import optax

    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.training.train import (
        init_train_state,
        make_train_step,
    )

    rng = np.random.default_rng(13)
    tokens = jnp.asarray(rng.integers(0, MOE_CFG.vocab_size, (4, 16)), jnp.int32)
    mask = jnp.ones((4, 16), jnp.float32)
    opt = optax.sgd(0.0)

    def first_loss(mesh):
        params, opt_state = init_train_state(MOE_CFG, mesh, opt, seed=5)
        ts = make_train_step(MOE_CFG, mesh, opt, remat=False)
        _, _, loss = ts(params, opt_state, tokens, mask)
        return float(loss)

    l_ep = first_loss(make_mesh(dp=2, ep=2, tp=2))
    l_single = first_loss(make_mesh(1, 1, 1, devices=jax.devices()[:1]))
    assert abs(l_ep - l_single) < 1e-4, (l_ep, l_single)


# ------------------------------------------------------------ int8 x MoE


def test_moe_int8_logits_track_full_precision():
    """Quantized expert einsums: int8 MoE logits track fp within the same
    per-channel error budget as the dense model's quant path."""
    from agentic_traffic_testing_tpu.models.quant import quantize_params

    params = init_params(MOE_CFG, jax.random.key(14), dtype=jnp.float32)
    qparams = quantize_params(params)
    tokens = jnp.asarray(
        np.random.default_rng(15).integers(0, MOE_CFG.vocab_size, (1, 12)),
        jnp.int32)
    ref = np.asarray(forward_full(params, MOE_CFG, tokens), np.float32)
    got = np.asarray(forward_full(qparams, MOE_CFG, tokens), np.float32)
    # Same top-1 almost everywhere and bounded absolute drift.
    agree = (ref.argmax(-1) == got.argmax(-1)).mean()
    assert agree >= 0.9, agree
    assert np.abs(got - ref).max() < 0.12 * np.abs(ref).max()


def test_moe_int8_engine_decode_and_ep_mesh():
    """The engine serves int8 MoE (guard removed), and EP x TP sharding of
    the QTensor expert leaves reproduces the single-device int8 decode
    token-exactly."""
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner
    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    qparams = init_params_quantized(MOE_CFG, 2, dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny-moe", dtype="float32", quantization="int8",
                        num_blocks=64, max_model_len=128)
    prompt = list(range(5, 21))
    samp = SamplingParams(temperature=0.0, max_tokens=8)
    ref = LLMEngine(ecfg, model_cfg=MOE_CFG, params=qparams).generate(prompt, samp)
    assert len(ref.output_ids) == 8

    runner = TPRunner(MOE_CFG, qparams, make_mesh(ep=2, tp=2))
    got = LLMEngine(ecfg, model_cfg=MOE_CFG, runner=runner).generate(prompt, samp)
    assert got.output_ids == ref.output_ids


# ------------------------------------------------------- int4 x MoE (round 3)


def test_moe_int4_matches_dequantized_oracle():
    """int4 expert einsums (pallas scan over experts on TPU, XLA unpack
    fallback here) are numerically identical to running moe_mlp on the
    dequantized weights — quantization error is the only delta vs fp."""
    from agentic_traffic_testing_tpu.models.moe import moe_mlp
    from agentic_traffic_testing_tpu.models.quant import (
        QTensor4,
        _unpack4,
        quantize_params,
    )

    params = init_params(MOE_CFG, jax.random.key(21), dtype=jnp.float32)
    q = quantize_params(params, scheme="int4")
    x = jax.random.normal(jax.random.key(22), (2, 8, MOE_CFG.hidden_size),
                          jnp.float32)
    lp4 = {"w_router": params["layers"]["w_router"][0]}
    lp_deq = {"w_router": params["layers"]["w_router"][0]}
    for k in ("w_gate", "w_up", "w_down"):
        qt = q["layers"][k]
        lp4[k] = QTensor4(qt.packed[0], qt.scale[0])
        lp_deq[k] = _unpack4(qt.packed[0], qt.scale[0], jnp.float32)
    y4, aux4 = moe_mlp(x, lp4, MOE_CFG)
    yd, auxd = moe_mlp(x, lp_deq, MOE_CFG)
    np.testing.assert_allclose(np.asarray(y4), np.asarray(yd), atol=1e-5)
    np.testing.assert_allclose(float(aux4), float(auxd), rtol=1e-6)


def test_moe_int4_engine_decode():
    """The engine serves int4 MoE end-to-end (guards removed round 3): the
    stacked [L, E, K, N/2] expert weights ride the layer scan's closure and
    the expert scan indexes layer*E + e into the flat stack."""
    from agentic_traffic_testing_tpu.models.quant import quantize_params
    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    params = init_params(MOE_CFG, jax.random.key(23), dtype=jnp.float32)
    q4 = quantize_params(params, scheme="int4")
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int4",
                        num_blocks=64, max_model_len=128)
    prompt = list(range(5, 21))
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    out = LLMEngine(ecfg, model_cfg=MOE_CFG, params=q4).generate(prompt, samp)
    assert len(out.output_ids) == 8

    # Ungrouped int4 packing still needs the TP attestation — same
    # fail-fast as the dense path (silently sharding ungrouped nibbles
    # would decode garbage).
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner
    with pytest.raises(ValueError, match="int4 x TP requires grouped"):
        TPRunner(MOE_CFG, q4, make_mesh(ep=2, tp=2))


@pytest.mark.parametrize("kg,seed", [(0, 7), (4, 31)])
def test_moe_int4_tp_serving_matches_single_device(kg, seed):
    """int4 x MoE x TP (round 5, closes the last refused composition in the
    quant matrix): col expert stacks pack group-wise (groups = tp), the
    expert scan runs under the (ep, tp) shard_map
    (models/moe.py _expert_dense4_tp), and greedy decode on the ep2 x tp2
    mesh is token-exact vs the single-chip int4 engine on the same logical
    weights. kg=4 additionally exercises K-group scales sharded with the
    contraction dim on the row leaf.

    Seeds are chosen per parameterization to avoid ROUTING near-ties:
    random-init router logits sit close together, and the row-parallel
    split-K psum's ~1e-8 fp32 reduction-order delta (measured; see
    test_moe_int4_tp_matches_global_path for the layout-exactness proof)
    can flip a top-k choice, which capacity dropping then amplifies into
    different tokens — the same documented near-tie phenomenon as
    spec-vs-plain on bf16. Dense int4 x TP tests need no such care (no
    discrete routing to amplify the noise)."""
    from agentic_traffic_testing_tpu.models.quant import quantize_params
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner
    from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    params = init_params(MOE_CFG, jax.random.key(seed), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny-moe", dtype="float32", quantization="int4",
                        int4_k_group=kg, num_blocks=64, max_model_len=128)
    prompt = [(17 * i + 3) % MOE_CFG.vocab_size for i in range(23)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    q_ref = quantize_params(params, scheme="int4", int4_k_group=kg)
    ref = LLMEngine(ecfg, model_cfg=MOE_CFG, params=q_ref).generate(
        prompt, samp)
    assert len(ref.output_ids) == 8

    q_tp = quantize_params(params, scheme="int4", int4_groups=2,
                           int4_k_group=kg)
    runner = TPRunner(MOE_CFG, q_tp, make_mesh(ep=2, tp=2), int4_groups=2)
    got = LLMEngine(ecfg, model_cfg=MOE_CFG, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids


@pytest.mark.parametrize("kg", [0, 4])
@pytest.mark.parametrize("shape", ["prefill", "decode"])
@pytest.mark.parametrize("ep,tp", [(2, 2), (2, 1)])
def test_moe_int4_tp_matches_global_path(kg, shape, ep, tp):
    """Layout-exactness proof for the (ep, tp) expert shard_map, seed-
    robust: moe_mlp on TP-sharded grouped-packed expert stacks matches the
    single-device global int4 path to fp32 reduction-order noise at BOTH
    the prefill ([2, 16, D]) and decode ([1, 1, D]) activation shapes.
    Any grouped-packing or scale-sharding mistake shows up here as O(1)
    error, not 1e-7. (ep=2, tp=1) pins the ep-only wrap branch in
    shard_params (expert stacks sharded, dense leaves wrapped over the
    size-1 tp axis)."""
    from agentic_traffic_testing_tpu.models.moe import moe_mlp
    from agentic_traffic_testing_tpu.models.quant import (
        Q4Slice,
        QTensor4,
        quantize_params,
    )
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.sharding import shard_params

    params = init_params(MOE_CFG, jax.random.key(29), dtype=jnp.float32)
    bt = (2, 16) if shape == "prefill" else (1, 1)
    x = jax.random.normal(jax.random.key(5), (*bt, MOE_CFG.hidden_size),
                          jnp.float32)

    q_ref = quantize_params(params, scheme="int4", int4_k_group=kg)
    lp_ref = {"w_router": params["layers"]["w_router"][0]}
    for k in ("w_gate", "w_up", "w_down"):
        qt = q_ref["layers"][k]
        lp_ref[k] = QTensor4(qt.packed[0], qt.scale[0])
    y_ref, aux_ref = moe_mlp(x, lp_ref, MOE_CFG)

    q_tp = quantize_params(params, scheme="int4", int4_groups=tp,
                           int4_k_group=kg)
    sh = shard_params(q_tp, MOE_CFG, make_mesh(ep=ep, tp=tp),
                      int4_groups=tp if tp > 1 else None)
    lp_tp = {"w_router": params["layers"]["w_router"][0]}
    for k in ("w_gate", "w_up", "w_down"):
        lp_tp[k] = Q4Slice(sh["layers"][k], jnp.int32(0))
    y_tp, aux_tp = moe_mlp(x, lp_tp, MOE_CFG)

    np.testing.assert_allclose(np.asarray(y_tp), np.asarray(y_ref),
                               atol=1e-6)
    np.testing.assert_allclose(float(aux_tp), float(aux_ref), rtol=1e-6)


def test_moe_train_step_with_sequence_parallelism():
    """MoE composes with sequence parallelism (round-3): the GShard
    dispatch/combine einsums and the capacity cumsum are ordinary XLA ops,
    so GSPMD partitions them over the sp-sharded T axis while ring
    attention (shard_map) handles the attention site — first-step loss
    matches the unsharded step."""
    import optax

    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.training.train import (
        init_train_state,
        make_train_step,
    )

    rng = np.random.default_rng(33)
    tokens = jnp.asarray(rng.integers(0, MOE_CFG.vocab_size, (4, 32)), jnp.int32)
    mask = jnp.ones((4, 32), jnp.float32)

    def first_loss(mesh):
        opt = optax.adamw(1e-3)
        params, opt_state = init_train_state(MOE_CFG, mesh, opt)
        step = make_train_step(MOE_CFG, mesh, opt)
        _, _, loss = step(params, opt_state, tokens, mask)
        return float(loss)

    ref = first_loss(make_mesh(1, 1, 1))
    assert abs(first_loss(make_mesh(2, 2, 1)) - ref) < 2e-3
    assert abs(first_loss(make_mesh(2, 2, 2)) - ref) < 2e-3
