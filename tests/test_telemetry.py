"""Step-clock telemetry plane (runtime/telemetry.py, round 8).

Pins the plane's two contracts: OFF means absent (no recorder object, no
per-step allocations, token streams byte-identical to the untraced
engine) and ON means faithful (per-request phase ordering under churn,
bounded rings, Perfetto-loadable Chrome trace schema, TTFT == the
request's own queue_wait stamps, histogram + SLO emission through
serving/metrics.py, replica-pool aggregation).
"""

import json
import threading
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner
from agentic_traffic_testing_tpu.runtime import telemetry
from agentic_traffic_testing_tpu.runtime.telemetry import (
    REQ_ADMITTED,
    REQ_FIRST_TOKEN,
    REQ_QUEUED,
    REQ_RETIRED,
    REQ_TOKENS,
    STEP_PHASES,
    StepClock,
    chrome_trace_document,
)

CFG = PRESETS["tiny"]


@pytest.fixture(scope="module")
def runner():
    # ONE runner for the whole module (the decode_overlap suite's trick):
    # every engine below shares its compiled programs, keeping this file
    # inside the default tier's budget.
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    return ModelRunner(CFG, params, decode_steps=1)


def make_engine(runner, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_num_seqs", 4)
    return LLMEngine(EngineConfig(**kw), model_cfg=CFG, runner=runner)


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


def drive(engine, reqs):
    for _ in range(10_000):
        engine.step()
        if all(r.is_finished() for r in reqs):
            return
        if not engine.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


def prompts(n=3):
    rng = np.random.default_rng(3)
    return [rng.integers(0, CFG.vocab_size, ln).tolist()
            for ln in (12, 20, 9, 15, 7)[:n]]


# ------------------------------------------------------- recorder unit level


def test_ring_buffer_bound_enforced():
    rec = StepClock(capacity=8)
    for i in range(100):
        rec.record_dispatch("decode", i * 1.0, i * 1.0 + 0.001, 2, 2)
    assert len(rec.steps) == 8
    # Oldest evicted: the surviving seqs are the last 8.
    assert [r.seq for r in rec.steps] == list(range(93, 101))
    assert rec.num_dispatches == 100  # cumulative counter survives eviction

    # Live-timeline budget is decoupled from the step ring: a small ring
    # (dispatch history) must NOT evict still-running requests' timelines.
    for i in range(3 * 8):
        rec.request_queued(f"r{i}", float(i))
    assert len(rec._live) == 3 * 8
    # ...but the live map is still hard-bounded against a caller that
    # never retires: past live_capacity the oldest evict unfinished.
    assert rec.live_capacity == 4096
    for i in range(3 * 8, rec.live_capacity + 10):
        rec.request_queued(f"r{i}", float(i))
    assert len(rec._live) == rec.live_capacity

    # Sample queues are bounded too.
    small = StepClock(capacity=4, sample_capacity=16)
    for i in range(100):
        small.step_samples.append(("decode", 0.001))
    assert len(small.drain_step_samples()) == 16


def test_small_ring_keeps_ttft_of_concurrent_requests():
    # Regression: live timelines used to share the STEP-ring capacity, so
    # LLM_STEP_TRACE=<small ring> under concurrency silently dropped
    # still-running requests' TTFT/SLO samples.
    rec = StepClock(capacity=2, slo_ttft_ms=1000.0)
    for i in range(200):
        rec.request_queued(f"r{i}", 0.0)
    rec.request_tokens("r0", 0.5, 1)
    rec.request_retired("r0", 0.6, "stop")
    assert rec.drain_ttft_samples() == [0.5]
    assert rec.drain_slo_events() == [("ttft", True)]


def test_concurrent_reader_never_raises():
    # Regression: the HTTP thread iterates the retired ring / live map /
    # step ring (timeline_for, timelines, chrome_trace) while the engine
    # thread mutates them; unsynchronized iteration raised RuntimeError
    # ("deque mutated during iteration") and 500'd successful requests.
    rec = StepClock(capacity=64)
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            rid = f"r{i}"
            rec.request_queued(rid, float(i))
            rec.request_event(rid, REQ_ADMITTED, i + 0.1)
            rec.request_tokens(rid, i + 0.2, 2)
            rec.record_dispatch("decode", float(i), i + 0.01, 1, 1)
            rec.request_retired(rid, i + 0.3, "stop")
            i += 1

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    deadline = time.monotonic() + 0.5
    try:
        while time.monotonic() < deadline:
            rec.timeline_for("r1")  # walks the retired ring
            rec.timelines()
            rec.chrome_trace()
            rec.drain_ttft_samples()
    finally:
        stop.set()
        t.join(timeout=2.0)


def test_capacity_validation():
    with pytest.raises(ValueError):
        StepClock(capacity=1)
    with pytest.raises(ValueError):
        EngineConfig(step_trace=-1)
    with pytest.raises(ValueError):
        EngineConfig(slo_ttft_ms=-1.0)


# ------------------------------------------------------------- off-path pin


def test_off_by_default_no_recorder_no_allocations(runner, monkeypatch):
    """LLM_STEP_TRACE=0 (the default) must leave the engine without any
    recorder and make ZERO telemetry allocations per step: constructing
    ANY telemetry object is made to explode, then a full generate runs."""
    eng = make_engine(runner)
    assert eng.telemetry is None
    assert eng.scheduler.on_admit is None

    def boom(*a, **k):
        raise AssertionError("telemetry allocated with step_trace=0")

    monkeypatch.setattr(telemetry.StepRecord, "__init__", boom)
    monkeypatch.setattr(telemetry.RequestTimeline, "__init__", boom)
    monkeypatch.setattr(telemetry.StepClock, "__init__", boom)
    req = eng.generate(prompts(1)[0], greedy(6))
    assert len(req.generated_ids) == 6


def test_traced_tokens_identical_to_untraced(runner):
    ps = prompts(3)
    base = make_engine(runner)
    want = [base.generate(p, greedy(8)).generated_ids for p in ps]

    eng = make_engine(runner, step_trace=1)
    reqs = [eng.add_request(p, greedy(8)) for p in ps]
    drive(eng, reqs)
    assert [r.generated_ids for r in reqs] == want
    assert eng.telemetry.num_dispatches > 0
    assert eng.telemetry.num_requests_retired == 3


# ------------------------------------------------- request phase ordering


def _phase_names(tl):
    return [name for name, _, _ in tl.events]


def _assert_ordered(tl, finished=True):
    names = _phase_names(tl)
    assert names[0] == REQ_QUEUED
    ts = [t for _, t, _ in tl.events]
    assert ts == sorted(ts), f"non-monotonic timeline: {tl.events}"
    if finished:
        assert names[-1] == REQ_RETIRED
        assert names.index(REQ_ADMITTED) < names.index(REQ_FIRST_TOKEN)
        assert names.index(REQ_FIRST_TOKEN) < names.index(REQ_RETIRED)
        assert names.count(REQ_ADMITTED) >= 1


def test_phase_ordering_eos_mid_batch(runner):
    """EOS mid-batch: every retired timeline stays queued -> admitted ->
    first_token -> tokens* -> retired even when lanes stop at different
    dispatches and the batch re-plans around them."""
    base = make_engine(runner)
    probe = base.generate(prompts(1)[0], greedy(10))
    stop_tok = probe.generated_ids[2]
    eng = make_engine(runner, step_trace=1)
    reqs = [eng.add_request(p, greedy(10, stop_token_ids=(stop_tok,)))
            for p in prompts(3)]
    drive(eng, reqs)
    rec = eng.telemetry
    for r in reqs:
        tl = rec.timeline_for(r.request_id)
        assert tl is not None
        _assert_ordered(tl)
        # Engine stamps and recorder stamps are the SAME monotonic reads.
        assert tl.ttft_s == pytest.approx(r.queue_wait_s, abs=1e-9)
        assert tl.finish_reason in ("stop", "length")


def test_phase_ordering_admission_mid_decode(runner):
    """2 seats, 3 requests: the third admits mid-wave; its queued span
    must cover the wait and its ordering stay canonical."""
    eng = make_engine(runner, step_trace=1, max_num_seqs=2)
    reqs = [eng.add_request(p, greedy(10)) for p in prompts(2)]
    for _ in range(5):
        eng.step()
    late = eng.add_request(prompts(3)[2], greedy(4))
    drive(eng, reqs + [late])
    rec = eng.telemetry
    for r in reqs + [late]:
        _assert_ordered(rec.timeline_for(r.request_id))
    tl = rec.timeline_for(late.request_id)
    names = _phase_names(tl)
    assert names.index(REQ_ADMITTED) >= 1


def test_phase_ordering_abort(runner):
    eng = make_engine(runner, step_trace=1)
    reqs = [eng.add_request(p, greedy(12)) for p in prompts(3)]
    for _ in range(5):
        eng.step()
    eng.abort_request(reqs[1])
    drive(eng, [reqs[0], reqs[2]])
    rec = eng.telemetry
    tl = rec.timeline_for(reqs[1].request_id)
    assert tl.finish_reason == "abort"
    assert _phase_names(tl)[-1] == REQ_RETIRED
    for r in (reqs[0], reqs[2]):
        _assert_ordered(rec.timeline_for(r.request_id))
    # Aborted requests attain no SLO verdict even with classes set.
    assert all(kind in ("ttft", "itl")
               for kind, _ in rec.drain_slo_events())


# ------------------------------------------------------- chrome trace schema


def test_chrome_trace_schema(runner):
    eng = make_engine(runner, step_trace=1)
    reqs = [eng.add_request(p, greedy(6)) for p in prompts(2)]
    drive(eng, reqs)
    doc = chrome_trace_document([eng.telemetry])
    json.dumps(doc)  # serializable as-is
    events = doc["traceEvents"]
    assert events
    for e in events:
        assert e["ph"] in ("X", "i", "M")
        assert "pid" in e and "tid" in e
        if e["ph"] in ("X", "i"):
            assert isinstance(e["ts"], (int, float))
        if e["ph"] == "X":
            assert isinstance(e["dur"], (int, float)) and e["dur"] >= 0
    # One engine track + one track per request, named.
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert "engine step clock" in names
    assert sum(1 for n in names if n.startswith("req ")) == 2
    # Dispatch slices carry the phase kinds the engine actually ran.
    kinds = {e["name"] for e in events if e["ph"] == "X" and e["tid"] == 0}
    assert "prefill" in kinds and "decode" in kinds and "drain" in kinds
    assert kinds <= set(STEP_PHASES)


def test_dispatch_vs_drain_split_recorded(runner):
    eng = make_engine(runner, step_trace=1)
    drive(eng, [eng.add_request(prompts(1)[0], greedy(6))])
    kinds = [s.kind for s in eng.telemetry.steps]
    assert kinds.count("drain") >= 1
    assert kinds.count("decode") >= 1
    for s in eng.telemetry.steps:
        assert s.dur_s >= 0


# ---------------------------------------------- Prometheus family emission


def test_histograms_and_slo_emission(runner):
    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics

    eng = make_engine(runner, step_trace=1, slo_ttft_ms=60_000.0,
                      slo_itl_ms=1e-4)
    reqs = [eng.add_request(p, greedy(8)) for p in prompts(2)]
    # One per-request override: an absurdly lax ITL class -> met.
    lax = eng.add_request(prompts(3)[2],
                          greedy(8, slo_itl_ms=1e6))
    drive(eng, reqs + [lax])
    m = LLMMetrics("llm")
    m.observe_step_clock([eng.telemetry])
    text = m.render().decode()
    assert "llm_ttft_seconds_count 3.0" in text
    assert "llm_itl_seconds_count" in text  # 7 tokens/request after first
    assert 'llm_step_duration_seconds_bucket{le="+Inf",phase="decode"}' in text
    assert 'llm_slo_attainment_total{slo="ttft",status="met"} 3.0' in text
    assert 'llm_slo_attainment_total{slo="itl",status="met"} 1.0' in text
    assert 'llm_slo_attainment_total{slo="itl",status="violated"} 2.0' in text
    assert "llm_batch_occupancy" in text
    # Drained: a second scrape adds nothing.
    m.observe_step_clock([eng.telemetry])
    assert "llm_ttft_seconds_count 3.0" in m.render().decode()


def test_ttft_matches_queue_wait(runner):
    """Acceptance pin: recorder TTFT == the request's queue_wait_s (the
    meta.queue_wait_s source) — same stamps, zero drift."""
    eng = make_engine(runner, step_trace=1)
    req = eng.generate(prompts(1)[0], greedy(6))
    tl = eng.telemetry.timeline_for(req.request_id)
    assert abs(tl.ttft_s - req.queue_wait_s) < 1e-3  # identical stamps


# -------------------------------------------------- replica-pool aggregation


def test_engine_pool_aggregation(runner):
    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics
    from agentic_traffic_testing_tpu.serving.replica_pool import EnginePool

    pool = EnginePool([make_engine(runner, step_trace=1) for _ in range(2)],
                      policy="round_robin")
    reqs = [pool.add_request(p, greedy(6)) for p in prompts(4)]
    for _ in range(10_000):
        pool.step()
        if all(r.is_finished() for r in reqs):
            break
    assert len(pool.telemetry_recorders) == 2
    m = LLMMetrics("llm", num_replicas=2)
    m.observe_step_clock(pool.telemetry_recorders)
    text = m.render().decode()
    assert "llm_ttft_seconds_count 4.0" in text  # both replicas drained
    doc = pool.chrome_trace()
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert pids == {0, 1}  # one track set per replica


# ----------------------------------------------------- tracing noop (no SDK)


def test_noop_span_metadata_clean():
    """Satellite fix: span_metadata() on a noop span returns {} cleanly —
    get_span_context is None by contract, not a RuntimeError swallowed by
    the blanket except."""
    from agentic_traffic_testing_tpu.utils.tracing import (
        _NoopSpan,
        _NoopTracer,
        span_metadata,
    )

    span = _NoopSpan()
    assert span.get_span_context() is None
    assert span_metadata(span) == {}
    # end() tolerates the explicit-timestamp kwarg emit_phase_spans uses.
    span.end(end_time=123)
    tracer = _NoopTracer()
    assert span_metadata(tracer.start_span("x", start_time=1)) == {}


def test_emit_phase_spans_noop_tracer():
    """emit_phase_spans degrades to no-ops on the no-SDK path and accepts
    a churned timeline (missing admitted, restore events)."""
    from agentic_traffic_testing_tpu.utils.tracing import (
        _NoopTracer,
        emit_phase_spans,
    )

    events = [("queued", 1.0, 0.0), ("first_token", 2.0, 0.0),
              ("restore", 1.5, 4096.0), ("tokens", 2.5, 3.0),
              ("retired", 3.0, 0.0)]
    emit_phase_spans(_NoopTracer(), events, epoch_ns=0)  # must not raise
