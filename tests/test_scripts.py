"""Scripts layer: TCP collector, pcap analyzer, scraper, IAT analysis.

These are the measurement tools the testbed exists for; each is tested
against synthetic inputs with known ground truth (SURVEY.md §4's gap the
rebuild fills: the reference shipped these with no tests at all).
"""

import importlib.util
import json
import os
import struct
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def load_script(relpath: str, name: str):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, relpath))
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod  # dataclasses resolve cls.__module__ here
    spec.loader.exec_module(mod)
    return mod


tcp_col = load_script("scripts/monitoring/tcp_metrics_collector.py", "tcp_col")
analyze = load_script("scripts/traffic/analyze_traffic.py", "analyze")
scrape = load_script("scripts/experiment/scrape_metrics.py", "scrape")
plots = load_script("scripts/experiment/plot_results.py", "plots")
correlate = load_script("scripts/experiment/correlate_metrics.py", "correlate")


# ------------------------------------------------------------ tcp collector


def test_parse_tcpdump_line():
    line = ("1690000000.123456 IP 172.23.0.10.52344 > 172.23.0.20.8000: "
            "Flags [S], seq 100, win 64240, length 0")
    pkt = tcp_col.parse_line(line)
    assert pkt.src == "172.23.0.10" and pkt.dport == 8000
    assert pkt.flags == "S" and pkt.length == 0
    assert tcp_col.parse_line("garbage line") is None
    data = tcp_col.parse_line(
        "1690000000.5 IP 172.23.0.20.8000 > 172.23.0.10.52344: "
        "Flags [P.], seq 1:201, ack 1, length 200")
    assert data.length == 200 and data.flags == "P."


def test_collector_rtt_pairing_and_render():
    m = tcp_col.TCPMetrics(tcp_col.DEFAULT_IP_MAP)
    syn = tcp_col.Packet(1000.0, "172.23.0.10", 5000, "172.23.0.20", 8000,
                         "S", 0)
    synack = tcp_col.Packet(1000.025, "172.23.0.20", 8000, "172.23.0.10", 5000,
                            "S.", 0)
    data = tcp_col.Packet(1000.030, "172.23.0.10", 5000, "172.23.0.20", 8000,
                          "P.", 512)
    for p in (syn, synack, data):
        m.process_packet(p)
    text = m.render()
    assert 'tcp_syn_total{src_service="agent_a",dst_service="llm_backend"} 1' in text
    assert 'tcp_bytes_total{src_service="agent_a",dst_service="llm_backend"} 512' in text
    # RTT 25ms lands in the le=0.025 bucket for the a->llm edge
    assert ('tcp_rtt_handshake_seconds_bucket{src_service="agent_a",'
            'dst_service="llm_backend",le="0.025"} 1') in text
    assert "tcp_active_flows 2" in text

    # Flow expiry moves flows into the duration histogram
    expired = m.expire_idle_flows(now=1000.0 + 500)
    assert expired == 2
    assert "tcp_active_flows 0" in m.render()


# ------------------------------------------------------------ pcap analyzer


def _mk_pcap(path: str, packets):
    """Write a classic little-endian pcap with Ethernet/IPv4/TCP frames."""
    with open(path, "wb") as f:
        f.write(struct.pack("<IHHiIII", 0xA1B2C3D4, 2, 4, 0, 0, 65535, 1))
        for ts, src, sport, dst, dport, flags, payload in packets:
            eth = b"\x00" * 12 + struct.pack("!H", 0x0800)
            pay = b"x" * payload
            tcp = (struct.pack("!HHIIBBHHH", sport, dport, 1, 1,
                               5 << 4, flags, 64240, 0, 0) + pay)
            ip = struct.pack("!BBHHHBBH4s4s", 0x45, 0, 20 + len(tcp), 0, 0,
                             64, 6, 0,
                             bytes(int(x) for x in src.split(".")),
                             bytes(int(x) for x in dst.split(".")))
            frame = eth + ip + tcp
            f.write(struct.pack("<IIII", int(ts), int((ts % 1) * 1e6),
                                len(frame), len(frame)))
            f.write(frame)


def test_pcap_flow_analysis(tmp_path):
    pcap = str(tmp_path / "t.pcap")
    _mk_pcap(pcap, [
        (100.0, "10.0.0.1", 1234, "10.0.0.2", 80, 0x02, 0),    # SYN
        (100.1, "10.0.0.2", 80, "10.0.0.1", 1234, 0x12, 0),    # SYN-ACK
        (100.2, "10.0.0.1", 1234, "10.0.0.2", 80, 0x18, 300),  # PSH-ACK data
        (101.0, "10.0.0.3", 999, "10.0.0.2", 80, 0x02, 0),     # 2nd flow SYN
    ])
    flows, per_second = analyze.analyze_pcap([pcap])
    assert len(flows) == 2
    main_flow = flows[("10.0.0.1", 1234, "10.0.0.2", 80)]
    assert main_flow.packets == 3
    assert main_flow.payload_bytes == 300
    assert main_flow.syns == 1
    assert per_second[100]["new_connections"] == 1
    assert per_second[101]["new_connections"] == 1


# ------------------------------------------------------- scraper (schema)


def test_dashboard_as_schema():
    dash = os.path.join(REPO, "infra/monitoring/grafana/dashboards",
                        "agentic-traffic.json")
    pairs = scrape.load_dashboard_panels(dash)
    assert len(pairs) >= 25
    exprs = " ".join(e for _, e in pairs)
    # Metric families the TPU backend exports must drive the dashboard.
    for family in ("llm_request_latency_seconds", "llm_queue_wait_seconds",
                   "llm_requests_total", "llm_kv_cache_total_tokens",
                   "tcp_rtt_handshake_seconds", "llm_interarrival_seconds"):
        assert family in exprs, f"dashboard missing {family}"


# --------------------------------------------------------- IAT analysis


def test_iat_analysis_recovers_exponential(tmp_path):
    rng = np.random.default_rng(0)
    t = np.cumsum(rng.exponential(0.5, size=400)) * 1000.0  # ms
    analysis = plots.analyse_iat_distributions(list(t), str(tmp_path))
    assert analysis is not None
    desc = analysis["descriptives"]
    assert 0.8 < desc["cv"] < 1.2  # exponential: CV == 1
    best = [f for f in analysis["fits"] if f.get("aic_rank") == 1][0]
    assert best["distribution"] in ("expon", "gamma", "weibull")
    assert os.path.isfile(tmp_path / "iat_analysis.json")
    assert os.path.isfile(tmp_path / "iat_report.txt")
    assert os.path.isfile(tmp_path / "plots" / "interarrival.png")
    assert "Poisson" in analysis["interpretation"]


def test_iat_analysis_flags_bursty(tmp_path):
    rng = np.random.default_rng(1)
    # Bursts: 5 arrivals 10ms apart, then a 5 s gap — heavy overdispersion.
    ts, t = [], 0.0
    for _ in range(60):
        for _ in range(5):
            t += 0.01
            ts.append(t * 1000)
        t += 5.0
    analysis = plots.analyse_iat_distributions(ts, str(tmp_path))
    assert analysis["descriptives"]["cv"] > 1.5
    assert "BURSTY" in analysis["interpretation"]


# --------------------------------------------------------- correlator


def test_correlate_offline(tmp_path):
    calls = tmp_path / "llm_calls.jsonl"
    rows = [
        {"call_id": "c1", "task_id": "t1", "agent_id": "agent_a",
         "prompt_tokens": 10, "completion_tokens": 5, "total_tokens": 15,
         "latency_ms": 100.0, "started_at_ms": 1000, "finished_at_ms": 1100},
        {"call_id": "c2", "task_id": "t1", "agent_id": "agent_b",
         "prompt_tokens": 20, "completion_tokens": 10, "total_tokens": 30,
         "latency_ms": 200.0, "started_at_ms": 1200, "finished_at_ms": 1400,
         "error": "boom"},
        {"call_id": "c3", "task_id": "t2", "agent_id": "agent_a",
         "prompt_tokens": 1, "completion_tokens": 1, "total_tokens": 2,
         "latency_ms": 10.0, "started_at_ms": 2000, "finished_at_ms": 2010},
    ]
    with open(calls, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")
    out = tmp_path / "correlated.csv"
    rc = correlate.main(["--calls", str(calls), "--out", str(out),
                         "--no-prometheus"])
    assert rc == 0
    import csv as csv_mod
    table = {r["task_id"]: r for r in csv_mod.DictReader(open(out))}
    assert table["t1"]["num_llm_calls"] == "2"
    assert table["t1"]["num_errors"] == "1"
    assert table["t1"]["total_tokens"] == "45"
    assert table["t1"]["agents"] == "agent_a,agent_b"
    assert float(table["t1"]["window_s"]) == pytest.approx(0.4 + 4.0, abs=0.01)


# --------------------------------------------------------- health check CLI


def test_health_check_reports_down_services():
    env = dict(os.environ, LLM_SERVER_URL="http://127.0.0.1:1/chat",
               AGENT_A_URL="http://127.0.0.1:1",
               AGENT_B_URLS="http://127.0.0.1:1",
               TOOL_DB_URL="http://127.0.0.1:1")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts/monitoring/health_check.py"),
         "--json", "--timeout", "2", "--skip-observability"],
        capture_output=True, text=True, env=env)
    assert proc.returncode == 1
    report = json.loads(proc.stdout)
    assert report["ok"] is False
    by_name = {c["check"]: c for c in report["checks"]}
    assert by_name["llm.health"]["error"] == "connection_refused"


# --------------------------------------------------------- router A/B


def test_router_ab_smoke(monkeypatch):
    """scripts/dev/router_ab.py end-to-end on the tiny model: one JSON row
    per policy, prefix_affinity serving strictly more cached prompt tokens
    than round_robin on the same fan-out workload (in-process so the warm
    jax/conftest CPU config is reused — a subprocess would re-pay init)."""
    monkeypatch.setenv("ROUTER_AB_MODEL", "tiny")
    monkeypatch.setenv("ROUTER_AB_POLICIES", "round_robin,prefix_affinity")
    router_ab = load_script("scripts/dev/router_ab.py", "router_ab")
    results = router_ab.main(["2", "1", "3", "48"])
    assert [r["policy"] for r in results] == ["round_robin", "prefix_affinity"]
    by_policy = {r["policy"]: r for r in results}
    for r in results:
        assert r["replicas"] == 2 and sum(r["routed"]) == 3
        assert r["queue_wait_p50_s"] >= 0 and r["decode_toks_s"] > 0
    assert (by_policy["prefix_affinity"]["hit_tokens"]
            > by_policy["round_robin"]["hit_tokens"])


# --------------------------------------------------------- offload A/B


def test_offload_ab_smoke(monkeypatch):
    """scripts/dev/offload_ab.py end-to-end on the tiny model with a tiny
    host-cache budget: the offload arm must actually restore from the host
    tier (hit tokens > 0) and both arms' completions must be byte-identical
    (in-process for the warm jax/conftest CPU config, like router_ab)."""
    monkeypatch.setenv("OFFLOAD_AB_MODEL", "tiny")
    offload_ab = load_script("scripts/dev/offload_ab.py", "offload_ab")
    results = offload_ab.main(["48", "2", "8"])
    assert [r["mode"] for r in results] == ["offload", "recompute"]
    by_mode = {r["mode"]: r for r in results}
    assert by_mode["offload"]["host_hit_tokens"] > 0
    assert by_mode["offload"]["restore_bytes"] > 0
    assert by_mode["recompute"]["host_hit_tokens"] == 0
    for r in results:
        assert r["outputs_match"] is True
        assert r["rearrival_ttft_s"] >= 0


# ------------------------------------------------ prefill-pipeline A/B


def test_prefill_pipeline_ab_smoke(monkeypatch):
    """scripts/dev/prefill_pipeline_ab.py end-to-end on the tiny model:
    one JSON row per arm, the pipeline arm actually takes the chunked-
    dispatch path (dispatches >= 2), the serial arm never does, and both
    arms' completions are token-identical (in-process for the warm
    jax/conftest CPU config, like router_ab/offload_ab)."""
    monkeypatch.setenv("PIPELINE_AB_MODEL", "tiny")
    monkeypatch.delenv("PIPELINE_AB_TUNE", raising=False)
    pipeline_ab = load_script("scripts/dev/prefill_pipeline_ab.py",
                              "prefill_pipeline_ab")
    results = pipeline_ab.main(["48", "2", "4"])
    assert [r["mode"] for r in results] == ["serial", "pipeline"]
    by_mode = {r["mode"]: r for r in results}
    assert by_mode["pipeline"]["pipeline_dispatches"] >= 2
    assert by_mode["serial"]["pipeline_dispatches"] == 0
    for r in results:
        assert r["outputs_match"] is True
        assert r["prefill_ttft_s"] >= 0


# ------------------------------------------------ decode-overlap A/B


def test_decode_overlap_ab_smoke(monkeypatch):
    """scripts/dev/decode_overlap_ab.py end-to-end on the tiny model:
    one JSON row per arm, the overlap arm actually takes the predicted-
    composition fast path (dispatches > 0) and reconciles churn
    (mispredicts counted — the workload stops lanes mid-dispatch on
    purpose), the serial arm never does, and both arms' completions are
    token-identical (in-process for the warm jax/conftest CPU config,
    like router_ab/offload_ab)."""
    monkeypatch.setenv("OVERLAP_AB_MODEL", "tiny")
    monkeypatch.setenv("OVERLAP_AB_SEATS", "4")
    overlap_ab = load_script("scripts/dev/decode_overlap_ab.py",
                             "decode_overlap_ab")
    results = overlap_ab.main(["6", "24", "10"])
    assert [r["mode"] for r in results] == ["serial", "overlap"]
    by_mode = {r["mode"]: r for r in results}
    assert by_mode["overlap"]["overlap_dispatches"] > 0
    assert by_mode["overlap"]["mispredicts"] >= 1
    assert by_mode["serial"]["overlap_dispatches"] == 0
    assert by_mode["serial"]["mispredicts"] == 0
    for r in results:
        assert r["outputs_match"] is True
        assert r["decode_toks_s"] > 0


# ------------------------------------------------ speculative-decoding A/B


def test_spec_ab_smoke(monkeypatch):
    """scripts/dev/spec_ab.py end-to-end on the tiny model (the ISSUE-14
    acceptance smoke): one JSON row per arm, the spec arm actually
    accepts drafts on the repetitive agentic workload (accept_rate > 0 —
    prompt-lookup's existence proof) while emitting token-identical
    completions under the script's churn (mixed stops, admissions,
    greedy+seeded), fp32-exact on CPU."""
    monkeypatch.setenv("SPEC_AB_MODEL", "tiny")
    monkeypatch.setenv("SPEC_AB_SEATS", "4")
    spec_ab = load_script("scripts/dev/spec_ab.py", "spec_ab")
    results = spec_ab.main(["6", "6", "12"])
    assert [r["mode"] for r in results] == ["serial", "spec"]
    by_mode = {r["mode"]: r for r in results}
    assert by_mode["spec"]["accept_rate"] > 0
    assert by_mode["spec"]["emitted_per_round"] >= 1.0
    for r in results:
        assert r["outputs_match"] is True
        assert r["decode_toks_s"] > 0
        assert r["itl_p50_s"] > 0


# ------------------------------------------------ KV-quantization A/B


def test_kv_quant_ab_smoke(monkeypatch):
    """scripts/dev/kv_quant_ab.py end-to-end on the tiny model: one JSON
    row per KV dtype (bf16/fp8/int8), the quantized arms' first greedy
    token matches the bf16 oracle with a sane logit RMS (int8's scaled
    error under the fp8 tier bound), bytes/step actually shrink, and the
    LLM_FUSED_KV_WRITE engines reproduce every arm's outputs exactly
    (in-process for the warm jax/conftest CPU config, like router_ab)."""
    monkeypatch.setenv("KV_QUANT_AB_MODEL", "tiny")
    kv_ab = load_script("scripts/dev/kv_quant_ab.py", "kv_quant_ab")
    rows = kv_ab.main(["2", "32", "6"])
    assert [r["mode"] for r in rows] == ["bf16", "fp8", "int8"]
    by_mode = {r["mode"]: r for r in rows}
    assert by_mode["bf16"]["logit_rms"] == 0.0
    for tag in ("fp8", "int8"):
        r = by_mode[tag]
        assert r["first_token_match"] is True
        assert r["token_identity"] >= 0.5
        assert 0 < r["logit_rms"] < 0.2
        assert r["kv_bytes_per_step"] < by_mode["bf16"]["kv_bytes_per_step"]
    for r in rows:
        assert r["fused_outputs_match"] is True
        assert r["decode_toks_s"] > 0


# --------------------------------------------------------- chaos soak A/B


def test_chaos_ab_smoke(monkeypatch):
    """scripts/dev/chaos_ab.py end-to-end on the tiny model: the clean arm
    completes everything, the chaos arm injects at least one dispatch
    fault yet every request terminates and the surviving completions are
    token-identical to the clean arm; the restore section degrades a
    fault-injected host-tier restore to a byte-identical recompute; the
    round-11 migration-soak arm checkpoints quarantine-interrupted
    streams onto the survivor token-identically; the scale-churn arm
    oscillates the pool size under load with identical completions
    (in-process for the warm jax/conftest CPU config, like router_ab)."""
    monkeypatch.setenv("CHAOS_AB_MODEL", "tiny")
    monkeypatch.setenv("CHAOS_AB_SEATS", "4")
    chaos_ab = load_script("scripts/dev/chaos_ab.py", "chaos_ab")
    clean, chaos, restore, soak, churn = chaos_ab.main(["8", "24", "10"])
    assert (clean["mode"], chaos["mode"]) == ("clean", "chaos")
    assert clean["completed"] == 8 and clean["dispatch_failures"] == 0
    assert chaos["dispatch_failures"] >= 1
    assert chaos["completed"] >= 1 and chaos["errored"] >= 1
    assert chaos["all_terminated"] and clean["all_terminated"]
    assert chaos["unaffected_identical"] is True
    assert restore["mode"] == "restore_fallback"
    assert restore["fallbacks"] >= 1
    assert restore["clean_restores_fell_back"] == 0
    assert restore["outputs_match"] is True
    assert soak["mode"] == "migration_soak"
    assert soak["all_terminated"] and soak["migrations_adopted"] >= 1
    assert soak["migrated_identical"] is True
    assert soak["clean_completed"] == 8
    assert churn["mode"] == "scale_churn"
    assert churn["all_terminated"] and churn["churn_identical"] is True
    assert churn["scale_events"] == 3 and churn["final_size"] == 2
    assert churn["migrations"].get("scale_down:adopted", 0) >= 1


# ------------------------------------------------ loadgen λ-sweep soak


def test_loadgen_soak_smoke(monkeypatch, tmp_path):
    """scripts/dev/loadgen_soak.py end-to-end on the tiny model (the
    ISSUE-15 acceptance smoke): the synthesized AgentVerse DAG trace
    replays open-loop at >= 2 arrival rates against an in-process
    engine, clean and under dispatch chaos — every request terminates,
    the report's SLO-attainment and shed counts reconcile EXACTLY with
    the engine's Prometheus counters, fault injection never improves
    attainment, and the loadgen's own exposition surface serves every
    family on its own port (in-process for the warm jax/conftest CPU
    config, like chaos_ab)."""
    monkeypatch.setenv("SOAK_MODEL", "tiny")
    monkeypatch.setenv("SOAK_RATES", "6,12")
    monkeypatch.setenv("SOAK_WRITE_BENCH", "1")
    monkeypatch.setenv("SOAK_BENCH_DIR", str(tmp_path))
    soak = load_script("scripts/dev/loadgen_soak.py", "loadgen_soak")
    results = soak.main(["1", "5"])
    runs = [r for r in results if r.get("mode") in ("clean", "chaos")]
    (sweep,) = [r for r in results if r.get("mode") == "sweep"]
    assert [(r["mode"], r["rate"]) for r in runs] == [
        ("clean", 6.0), ("chaos", 6.0), ("clean", 12.0), ("chaos", 12.0)]
    for r in runs:
        assert r["all_terminated"] is True
        assert r["counters_reconcile"] is True
        assert r["attainment_delta_ok"] is True
        assert r["requests"] == 13  # 1 task under the template shape
    for r in runs:
        if r["mode"] == "chaos":
            assert r["errors"] >= 1 and r["dispatch_failures"] >= 1
        else:
            assert r["completed"] == r["requests"]
    assert sweep["rates"] == [6.0, 12.0]
    assert sweep["port_scraped"] is True
    assert sweep["families_present"] is True
    # λ-knee trajectory (ISSUE-16 satellite): the sweep line landed on
    # disk as round r01, append-only — a second write takes r02.
    traj = tmp_path / "BENCH_LOADGEN_r01.json"
    assert traj.exists()
    on_disk = json.loads(traj.read_text())
    assert on_disk["n"] == 1
    assert on_disk["rates"] == [6.0, 12.0]
    assert on_disk["max_sustainable_lambda"] == sweep["max_sustainable_lambda"]
    assert set(on_disk["ttft_attainment_by_rate"]) == {"6", "12"}
    assert soak.write_bench_trajectory(sweep).endswith(
        "BENCH_LOADGEN_r02.json")


# ------------------------------------------ disaggregated serving A/B


def test_disagg_ab_smoke(monkeypatch):
    """scripts/dev/disagg_ab.py end-to-end on the tiny model (the
    ISSUE-16 acceptance smoke): the agentic trace replays against a
    2x mixed pool and a 1-prefill + 1-decode pool over one shared
    runner, plus the decode-ITL-under-long-prefill interference probe.
    Structural gates only (CPU wall-clock comparisons are noise in CI):
    every request terminates in both arms, the disagg arm's adopted
    handoff count reconciles EXACTLY with the replayed records (and the
    interference probe's with its stream set), the mixed arm records
    zero disagg migrations, and both knees and ITL figures land in the
    report."""
    monkeypatch.setenv("DISAGG_AB_MODEL", "tiny")
    monkeypatch.setenv("DISAGG_AB_RATES", "6")
    ab = load_script("scripts/dev/disagg_ab.py", "disagg_ab")
    out = ab.main(["1", "6", "2"])
    assert out["disagg_ab_rates"] == [6.0]
    assert out["disagg_ab_trace_nodes"] == 12
    assert out["mixed_counters_reconcile"] is True
    assert out["disagg_counters_reconcile"] is True
    assert out["mixed_migrations_adopted"] == 0
    assert out["disagg_migrations_adopted"] == 12  # every node hands off
    assert out["mixed_interference_counters_reconcile"] is True
    assert out["disagg_interference_counters_reconcile"] is True
    # 2 decode streams + the long-prefill request itself, exactly once.
    assert out["disagg_interference_migrations_adopted"] == 3
    assert out["disagg_interference_migrations_failed"] == 0
    for tag in ("mixed", "disagg"):
        assert out[f"agentic_load_{tag}_max_sustainable_lambda"] in (None, 6.0)
        assert out[f"{tag}_interference_itl_p99_s"] > 0
        assert out[f"{tag}_r6_ttft_attainment"] >= 0


# ------------------------------------------------ step-clock timeline dump


def test_dump_timeline_smoke(tmp_path, monkeypatch):
    """scripts/dev/dump_timeline.py end-to-end on the tiny model: a small
    traced CPU generate (with one mid-flight abort) dumped as Chrome
    trace-event JSON — the file parses, every event passes the
    ph/ts/pid/tid schema check, and a track exists per request
    (in-process for the warm jax/conftest CPU config, like the *_ab
    smokes)."""
    monkeypatch.setenv("TIMELINE_MODEL", "tiny")
    dump = load_script("scripts/dev/dump_timeline.py", "dump_timeline")
    out = str(tmp_path / "timeline.json")
    doc = dump.main([out, "3", "6"])
    on_disk = json.load(open(out))
    assert on_disk["traceEvents"]
    dump.validate_trace(on_disk)  # the same check the script exits on
    events = doc["traceEvents"]
    names = {e["args"]["name"] for e in events
             if e["ph"] == "M" and e["name"] == "thread_name"}
    assert sum(1 for n in names if n.startswith("req ")) == 3
    kinds = {e["name"] for e in events if e["ph"] == "X" and e["tid"] == 0}
    assert {"prefill", "decode", "drain"} <= kinds


# ------------------------------------------------- metric-docs parity


def test_metric_docs_parity():
    """Every llm_* family registered by serving/metrics.py is documented in
    docs/monitoring.md and vice versa (the north star pins the Prometheus
    contract; scripts/dev/check_metric_docs.py is the one gate)."""
    check = load_script("scripts/dev/check_metric_docs.py", "check_metric_docs")
    assert check.main([]) == 0


# --------------------------------------------------------- statics plane


def test_statics_all_smoke(capsys):
    """scripts/dev/statics_all.py exits 0 on the tree with zero
    unsuppressed findings — tier-1 therefore fails on any new
    unregistered env knob, supports_* flag without a refusal guard,
    un-pragma'd host sync in a hot region, post-donation buffer read,
    unowned cross-thread attribute write, lock-discipline violation,
    Pallas launch-contract violation (illegal tile, arity drift,
    aliasing mismatch, unjustified parallel grid, VMEM blowout), or
    knob/capability/threading/kernel doc drift (the per-checker behavior
    is pinned in tests/test_statics.py, tests/test_statics_concurrency.py
    and tests/test_statics_kernels.py against fixture trees)."""
    statics_all = load_script("scripts/dev/statics_all.py", "statics_all")
    rc = statics_all.main([])
    out = capsys.readouterr().out
    assert rc == 0, out
    import json as json_mod

    report = json_mod.loads(out)
    assert report["ok"] is True
    assert set(report["checkers"]) == {
        "knobs", "capabilities", "host-sync", "donation", "concurrency",
        "metric-docs", "kernelcontract"}
    # Per-checker wall time rides the report so CI can spot a checker
    # whose scan cost regressed.
    for entry in report["checkers"].values():
        assert isinstance(entry["wall_time_s"], float)


def test_statics_all_only_flag(capsys):
    """--only runs a single checker (fast edit-loop mode) and rejects
    unknown names with exit 2."""
    statics_all = load_script("scripts/dev/statics_all.py", "statics_all")
    rc = statics_all.main(["--only", "concurrency"])
    out = capsys.readouterr().out
    assert rc == 0, out
    import json as json_mod

    report = json_mod.loads(out)
    assert set(report["checkers"]) == {"concurrency"}
    assert statics_all.main(["--only", "nonesuch", "--quiet"]) == 2


def test_statics_all_only_kernelcontract(capsys):
    """The seventh checker is individually addressable and reports its
    wall time like the rest."""
    statics_all = load_script("scripts/dev/statics_all.py", "statics_all")
    rc = statics_all.main(["--only", "kernelcontract"])
    out = capsys.readouterr().out
    assert rc == 0, out
    import json as json_mod

    report = json_mod.loads(out)
    assert set(report["checkers"]) == {"kernelcontract"}
    assert isinstance(
        report["checkers"]["kernelcontract"]["wall_time_s"], float)


# --------------------------------------------------------- platform guard


def test_platform_guard_honors_explicit_cpu(monkeypatch):
    """force_cpu_if_requested (round 4): no-op unless JAX_PLATFORMS is
    exactly "cpu"; when it is, the axon plugin env is stripped so
    subprocesses cannot re-register it (the sitecustomize pin trap —
    see agentic_traffic_testing_tpu/platform_guard.py)."""
    from agentic_traffic_testing_tpu.platform_guard import (
        force_cpu_if_requested,
    )

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("PALLAS_AXON_POOL_IPS", "10.0.0.1")
    assert force_cpu_if_requested() is False
    assert os.environ.get("PALLAS_AXON_POOL_IPS") == "10.0.0.1"

    monkeypatch.setenv("JAX_PLATFORMS", "tpu,cpu")
    assert force_cpu_if_requested() is False

    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert force_cpu_if_requested() is True
    assert "PALLAS_AXON_POOL_IPS" not in os.environ
