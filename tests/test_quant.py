"""Weight-only int8 quantization (models/quant.py).

Motivation: Llama-3-8B bf16 (~16 GiB) does not fit one v5e chip; int8
weight-only is the capacity path for the north-star config (BASELINE.md §3).
These tests pin (a) the per-channel quantizer's reconstruction error, (b)
logits parity of the quantized model against the full-precision one, and
(c) the engine running end-to-end on quantized params (QTensor leaves riding
the layer scan and jit boundaries).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import (
    forward_full_impl,
    init_params,
    init_params_quantized,
)
from agentic_traffic_testing_tpu.models.quant import (
    QTensor,
    dense,
    embed_lookup,
    is_quantized,
    quantize_array,
    quantize_params,
)
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import SamplingParams

CFG = PRESETS["tiny"]


def test_quantize_array_reconstruction():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    qt = quantize_array(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 48)
    recon = qt.q.astype(jnp.float32) * qt.scale
    err = float(jnp.max(jnp.abs(recon - w)))
    # Per-column symmetric int8: worst case one half-step of the column scale.
    assert err <= float(jnp.max(qt.scale)) * 0.51, err


def test_dense_and_embed_match_full_precision():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    want = x @ w
    got = dense(x, quantize_array(w))
    assert float(jnp.max(jnp.abs(got - want))) / float(jnp.max(jnp.abs(want))) < 0.05

    emb = jnp.asarray(rng.standard_normal((50, 16)), jnp.float32)
    ids = jnp.asarray([0, 7, 49])
    got_rows = embed_lookup(quantize_array(emb), ids).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got_rows), np.asarray(emb[ids]),
                               atol=0.05, rtol=0.2)


def test_quantized_logits_track_full_precision():
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    qparams = quantize_params(params)
    assert is_quantized(qparams)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 12)), jnp.int32)
    full = np.asarray(forward_full_impl(params, CFG, tokens)).ravel()
    quant = np.asarray(forward_full_impl(qparams, CFG, tokens)).ravel()
    corr = np.corrcoef(full, quant)[0, 1]
    assert corr > 0.995, corr


def test_engine_end_to_end_quantized():
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int8",
                        max_model_len=128, block_size=8, num_blocks=64,
                        max_num_seqs=4)
    eng = LLMEngine(ecfg, model_cfg=CFG)
    rng = np.random.default_rng(3)
    reqs = [eng.add_request(rng.integers(0, CFG.vocab_size, n).tolist(),
                            SamplingParams(max_tokens=8, temperature=0.0))
            for n in (5, 11)]
    for _ in range(10_000):
        eng.step()
        if all(r.is_finished() for r in reqs):
            break
        if not eng.has_work():
            break
    for r in reqs:
        assert r.is_finished()
        assert len(r.generated_ids) >= 1
        assert all(0 <= t < CFG.vocab_size for t in r.generated_ids)


def test_unknown_quantization_fails_fast():
    with pytest.raises(ValueError, match="unknown quantization"):
        EngineConfig(model="tiny", quantization="int4")


def test_init_params_quantized_schema():
    params = init_params_quantized(CFG, seed=0)
    assert is_quantized(params)
    assert isinstance(params["layers"]["wq"], QTensor)
    assert params["layers"]["wq"].q.dtype == jnp.int8
    assert not isinstance(params["layers"]["ln_attn"], QTensor)
    # Tied config: unembed reconstruction matches tok_embed.T reconstruction.
    if CFG.tie_word_embeddings:
        te = params["tok_embed"]
        ue = params["unembed"]
        r1 = (te.q.astype(jnp.float32) * te.scale).T
        r2 = ue.q.astype(jnp.float32) * ue.scale
        np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), atol=0.02)
