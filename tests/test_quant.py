"""Weight-only int8 quantization (models/quant.py).

Motivation: Llama-3-8B bf16 (~16 GiB) does not fit one v5e chip; int8
weight-only is the capacity path for the north-star config (BASELINE.md §3).
These tests pin (a) the per-channel quantizer's reconstruction error, (b)
logits parity of the quantized model against the full-precision one, and
(c) the engine running end-to-end on quantized params (QTensor leaves riding
the layer scan and jit boundaries).
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import (
    forward_full_impl,
    init_params,
    init_params_quantized,
)
from agentic_traffic_testing_tpu.models.quant import (
    QTensor,
    dense,
    embed_lookup,
    is_quantized,
    quantize_array,
    quantize_array4,
    quantize_params,
)
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import SamplingParams

CFG = PRESETS["tiny"]


def test_quantize_array_reconstruction():
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    qt = quantize_array(w)
    assert qt.q.dtype == jnp.int8 and qt.scale.shape == (1, 48)
    recon = qt.q.astype(jnp.float32) * qt.scale
    err = float(jnp.max(jnp.abs(recon - w)))
    # Per-column symmetric int8: worst case one half-step of the column scale.
    assert err <= float(jnp.max(qt.scale)) * 0.51, err


def test_dense_and_embed_match_full_precision():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((4, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((64, 32)), jnp.float32)
    want = x @ w
    got = dense(x, quantize_array(w))
    assert float(jnp.max(jnp.abs(got - want))) / float(jnp.max(jnp.abs(want))) < 0.05

    emb = jnp.asarray(rng.standard_normal((50, 16)), jnp.float32)
    ids = jnp.asarray([0, 7, 49])
    got_rows = embed_lookup(quantize_array(emb), ids).astype(jnp.float32)
    np.testing.assert_allclose(np.asarray(got_rows), np.asarray(emb[ids]),
                               atol=0.05, rtol=0.2)


def test_quantized_logits_track_full_precision():
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    qparams = quantize_params(params)
    assert is_quantized(qparams)
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (2, 12)), jnp.int32)
    full = np.asarray(forward_full_impl(params, CFG, tokens)).ravel()
    quant = np.asarray(forward_full_impl(qparams, CFG, tokens)).ravel()
    corr = np.corrcoef(full, quant)[0, 1]
    assert corr > 0.995, corr


def test_engine_end_to_end_quantized():
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int8",
                        max_model_len=128, block_size=8, num_blocks=64,
                        max_num_seqs=4)
    eng = LLMEngine(ecfg, model_cfg=CFG)
    rng = np.random.default_rng(3)
    reqs = [eng.add_request(rng.integers(0, CFG.vocab_size, n).tolist(),
                            SamplingParams(max_tokens=8, temperature=0.0))
            for n in (5, 11)]
    for _ in range(10_000):
        eng.step()
        if all(r.is_finished() for r in reqs):
            break
        if not eng.has_work():
            break
    for r in reqs:
        assert r.is_finished()
        assert len(r.generated_ids) >= 1
        assert all(0 <= t < CFG.vocab_size for t in r.generated_ids)


def test_unknown_quantization_fails_fast():
    with pytest.raises(ValueError, match="unknown quantization"):
        EngineConfig(model="tiny", quantization="fp6")


# ----------------------------------------------------------- int4 (round 2)


def test_quantize_array4_reconstruction():
    from agentic_traffic_testing_tpu.models.quant import (
        _unpack4,
        quantize_array4,
    )

    rng = np.random.default_rng(7)
    w = jnp.asarray(rng.standard_normal((64, 48)), jnp.float32)
    qt = quantize_array4(w)
    assert qt.packed.shape == (64, 24) and qt.packed.dtype == jnp.int8
    assert qt.scale.shape == (2, 24)
    deq = np.asarray(_unpack4(qt.packed, qt.scale, jnp.float32))
    # Per-column scale = amax/7; int4 rounding error is bounded by scale/2.
    amax = np.abs(np.asarray(w)).max(axis=0)
    assert np.all(np.abs(deq - np.asarray(w)) <= amax[None, :] / 7 / 2 + 1e-6)


def test_pack_int4_unpack_roundtrip():
    """The kernel-side packing oracle (ops/pallas/int4_matmul.pack_int4) and
    the model-side unpacker must agree on the half-pairing byte layout —
    they are the two independent implementations of the convention."""
    from agentic_traffic_testing_tpu.models.quant import _unpack4
    from agentic_traffic_testing_tpu.ops.pallas.int4_matmul import pack_int4

    rng = np.random.default_rng(11)
    vals = rng.integers(-8, 8, (16, 32)).astype(np.int8)
    packed = jnp.asarray(pack_int4(vals))
    ones = jnp.ones((2, 16), jnp.float32)
    got = np.asarray(_unpack4(packed, ones, jnp.float32))
    np.testing.assert_array_equal(got, vals.astype(np.float32))


def test_int4_engine_matches_dequantized_oracle():
    """The int4 serving path (Q4Slice closures through every scan) must be
    numerically identical to serving the SAME dequantized weights in full
    precision — pinning the packing, the layer indexing, and the fallback
    matmul in one shot."""
    import jax.tree_util as jtu

    from agentic_traffic_testing_tpu.models.quant import QTensor4, _unpack4

    params = init_params(CFG, jax.random.key(1), dtype=jnp.float32)
    q4 = quantize_params(params, scheme="int4")
    assert is_quantized(q4)

    def deq(leaf):
        if isinstance(leaf, QTensor4):
            return _unpack4(leaf.packed, leaf.scale, jnp.float32)
        return leaf
    deq_params = jtu.tree_map(deq, q4,
                              is_leaf=lambda x: isinstance(x, QTensor4))

    rng = np.random.default_rng(8)
    prompts = [rng.integers(0, CFG.vocab_size, n).tolist() for n in (6, 13)]

    def run(p):
        from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

        eng = LLMEngine(
            EngineConfig(model="tiny", dtype="float32", max_model_len=128,
                         block_size=8, num_blocks=64, max_num_seqs=4),
            model_cfg=CFG, runner=ModelRunner(CFG, p))
        return [eng.generate(ids, SamplingParams(max_tokens=8, temperature=0.0)
                             ).generated_ids for ids in prompts]

    assert run(q4) == run(deq_params)


def test_init_params_quantized_schema():
    params = init_params_quantized(CFG, seed=0)
    assert is_quantized(params)
    assert isinstance(params["layers"]["wq"], QTensor)
    assert params["layers"]["wq"].q.dtype == jnp.int8
    assert not isinstance(params["layers"]["ln_attn"], QTensor)
    # Tied config: unembed reconstruction matches tok_embed.T reconstruction.
    if CFG.tie_word_embeddings:
        te = params["tok_embed"]
        ue = params["unembed"]
        r1 = (te.q.astype(jnp.float32) * te.scale).T
        r2 = ue.q.astype(jnp.float32) * ue.scale
        np.testing.assert_allclose(np.asarray(r1), np.asarray(r2), atol=0.02)


# ------------------------------------------------------- int8 x TP (round 2)


def test_tp_int8_decode_matches_single_device():
    """TP=2 int8 greedy decode is token-exact vs the single-device int8
    engine: QTensor leaves carry their own (q, scale) PartitionSpecs
    (parallel/sharding.py expand_quant_specs)."""
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner

    qparams = init_params_quantized(CFG, 0, dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int8",
                        num_blocks=64, max_model_len=128)
    prompt = list(range(7, 27))
    samp = SamplingParams(temperature=0.0, max_tokens=12)

    ref = LLMEngine(ecfg, model_cfg=CFG, params=qparams).generate(prompt, samp)
    runner = TPRunner(CFG, qparams, make_mesh(tp=2))
    tp = LLMEngine(ecfg, model_cfg=CFG, runner=runner).generate(prompt, samp)
    assert tp.output_ids == ref.output_ids


def test_tp8_70b_shape_int8_decode():
    """The llama-3-70b-tp8.yaml north star, scaled down: 8 KV heads over 8
    chips (one per chip) with int8 weights — the capacity configuration that
    fits 70B on a v5e-8."""
    from agentic_traffic_testing_tpu.models.config import ModelConfig
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner

    cfg = ModelConfig(
        name="70b-shape", vocab_size=512, hidden_size=128,
        intermediate_size=256, num_layers=2, num_heads=16, num_kv_heads=8,
        head_dim=8,
    )
    qparams = init_params_quantized(cfg, 1, dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int8",
                        num_blocks=64, max_model_len=128)
    prompt = list(range(3, 23))
    samp = SamplingParams(temperature=0.0, max_tokens=6)

    ref = LLMEngine(ecfg, model_cfg=cfg, params=qparams).generate(prompt, samp)
    runner = TPRunner(cfg, qparams, make_mesh(tp=8))
    got = LLMEngine(ecfg, model_cfg=cfg, runner=runner).generate(prompt, samp)
    assert got.output_ids == ref.output_ids


def test_llama70b_tp8_int8_fits_v5e8_hbm():
    """Capacity check for serving/configs/llama-3-70b-tp8.yaml: int8 weights
    sharded over 8 chips + the config's KV working set fit each v5e chip's
    16 GB HBM at the profile's memory_utilization (bf16 would not)."""
    from agentic_traffic_testing_tpu.models.config import resolve_config

    cfg = resolve_config("llama-3-70b")
    shapes = jax.eval_shape(
        lambda: init_params_quantized(cfg, 0, dtype=jnp.bfloat16))
    total = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(shapes))
    per_chip_weights = total / 8  # tp-sharded dims dominate; norms negligible
    # KV working set of the yaml profile: 8 seqs x 8192 tokens, bf16,
    # KV heads sharded 8-way.
    kv = (2 * cfg.num_layers * 8 * 8192 * cfg.num_kv_heads // 8
          * 128 * 2)  # phys head dim 128 lanes
    hbm = 16 * 1024**3 * 0.92
    assert per_chip_weights + kv < hbm, (per_chip_weights / 1e9, kv / 1e9)
    # ...and the point of int8: bf16 at tp=8 would NOT fit this profile.
    assert (2 * total / 8) + kv > hbm


# ------------------------------------------------------- int4 x TP (round 3)


def _hybrid_int4_single_device_params(params):
    """Single-device params with the SAME logical weights as the int4 x TP
    hybrid: int4 layer weights (grouped and ungrouped packing dequantize to
    identical values — scales are per-column) plus the int8 lm_head that
    quantize_params(int4_groups>1) ships under TP."""
    q = quantize_params(params, scheme="int4")
    q["unembed"] = quantize_array(params["unembed"])
    return q


def test_tp_int4_decode_matches_single_device():
    """TP=2 int4 greedy decode is token-exact vs the single-device engine
    on the same logical weights: column-parallel QTensor4 leaves pack
    group-wise (models/quant.py quantize_array4 groups=2) and run under
    shard_map (QTensor4TP), row-parallel leaves shard K and psum."""
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner

    params = init_params(CFG, jax.random.key(3), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int4",
                        num_blocks=64, max_model_len=128)
    prompt = list(range(7, 27))
    samp = SamplingParams(temperature=0.0, max_tokens=12)

    ref = LLMEngine(ecfg, model_cfg=CFG,
                    params=_hybrid_int4_single_device_params(params)
                    ).generate(prompt, samp)
    qtp = quantize_params(params, scheme="int4", int4_groups=2)
    runner = TPRunner(CFG, qtp, make_mesh(tp=2), int4_groups=2)
    tp = LLMEngine(ecfg, model_cfg=CFG, runner=runner).generate(prompt, samp)
    assert tp.output_ids == ref.output_ids


def test_tp8_70b_shape_int4_decode():
    """The llama-3-70b-int4-tp8.yaml north star, scaled down: 8 KV heads
    over 8 chips with int4 layer weights — the capacity configuration that
    halves int8's per-chip weight stream."""
    from agentic_traffic_testing_tpu.models.config import ModelConfig
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
    from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner

    cfg = ModelConfig(
        name="70b-shape", vocab_size=512, hidden_size=128,
        intermediate_size=256, num_layers=2, num_heads=16, num_kv_heads=8,
        head_dim=8,
    )
    params = init_params(cfg, jax.random.key(1), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int4",
                        num_blocks=64, max_model_len=128)
    prompt = list(range(3, 23))
    samp = SamplingParams(temperature=0.0, max_tokens=6)

    ref = LLMEngine(ecfg, model_cfg=cfg,
                    params=_hybrid_int4_single_device_params(params)
                    ).generate(prompt, samp)
    qtp = quantize_params(params, scheme="int4", int4_groups=8)
    runner = TPRunner(cfg, qtp, make_mesh(tp=8), int4_groups=8)
    got = LLMEngine(ecfg, model_cfg=cfg, runner=runner).generate(prompt, samp)
    assert got.output_ids == ref.output_ids


def test_tp_packed_int4_serves_single_chip():
    """Round 5: a TP-packed (groups>1) checkpoint serves on ONE chip
    without repacking — _dense4 decomposes the grouped layout into its
    contiguous per-group slices (each a well-formed groups=1 QTensor4)
    and concatenates, so greedy decode is token-exact vs the
    standard-packed engine on the same logical weights."""
    params = init_params(CFG, jax.random.key(3), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int4",
                        num_blocks=64, max_model_len=128)
    prompt = list(range(7, 27))
    samp = SamplingParams(temperature=0.0, max_tokens=12)

    ref = LLMEngine(ecfg, model_cfg=CFG,
                    params=_hybrid_int4_single_device_params(params)
                    ).generate(prompt, samp)
    qtp = quantize_params(params, scheme="int4", int4_groups=2)
    got = LLMEngine(ecfg, model_cfg=CFG, params=qtp).generate(prompt, samp)
    assert got.output_ids == ref.output_ids


def test_grouped_int4_packing_dequantizes_identically():
    """quantize_array4(w, groups=g) is a byte-layout change only: reshaping
    each group's packed shard through _unpack4 reproduces the ungrouped
    dequantization exactly (per-column scales are pairing-independent)."""
    from agentic_traffic_testing_tpu.models.quant import _unpack4

    w = jax.random.normal(jax.random.key(0), (32, 48), jnp.float32)
    q1 = quantize_array4(w)
    base = _unpack4(q1.packed, q1.scale, jnp.float32)
    g = 4
    qg = quantize_array4(w, groups=g)
    h = 48 // (2 * g)
    shards = [
        _unpack4(qg.packed[:, i * h:(i + 1) * h],
                 qg.scale[:, i * h:(i + 1) * h], jnp.float32)
        for i in range(g)
    ]
    np.testing.assert_array_equal(np.concatenate(shards, axis=1), np.asarray(base))


def test_llama70b_tp8_int4_fits_v5e8_hbm():
    """Capacity check for serving/configs/llama-3-70b-int4-tp8.yaml: int4
    layer weights + int8 lm_head sharded over 8 chips leave roughly half of
    int8's weight footprint — headroom that becomes KV pool."""
    from agentic_traffic_testing_tpu.models.config import resolve_config

    cfg = resolve_config("llama-3-70b")
    shapes = jax.eval_shape(
        lambda: init_params_quantized(cfg, 0, dtype=jnp.bfloat16,
                                      scheme="int4"))
    total = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(shapes))
    shapes8 = jax.eval_shape(
        lambda: init_params_quantized(cfg, 0, dtype=jnp.bfloat16))
    total8 = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                 for l in jax.tree_util.tree_leaves(shapes8))
    assert total < 0.6 * total8
    kv = (2 * cfg.num_layers * 8 * 8192 * cfg.num_kv_heads // 8 * 128 * 2)
    assert total / 8 + kv < 16 * 1024**3 * 0.92


# --------------------------------------------- int4 K-group scales (round 3)


def test_int4_k_group_improves_outlier_reconstruction():
    """AWQ-style K-group scales: an outlier K-row no longer washes out the
    whole column's scale — grouped reconstruction error is strictly better
    on outlier-bearing weights and identical layout otherwise."""
    from agentic_traffic_testing_tpu.models.quant import _unpack4

    w = jax.random.normal(jax.random.key(0), (256, 96), jnp.float32)
    w = w.at[3].mul(20.0)
    q0 = quantize_array4(w)
    d0 = _unpack4(q0.packed, q0.scale, jnp.float32)
    qg = quantize_array4(w, k_group=64)
    assert qg.scale.shape == (4, 2, 48)
    dg = _unpack4(qg.packed, qg.scale, jnp.float32)
    e0 = float(jnp.sqrt(jnp.mean((d0 - w) ** 2)))
    eg = float(jnp.sqrt(jnp.mean((dg - w) ** 2)))
    assert eg < 0.7 * e0, (eg, e0)


def test_int4_k_group_kernel_matches_fallback():
    """The pallas kernel's per-group partial-sum scaling (interpret mode
    here) is exact vs the XLA unpack fallback, including the K-chunked
    grid (K large enough to trigger VMEM-bound chunking) and the stacked
    layer-indexed path."""
    from agentic_traffic_testing_tpu.models.quant import _unpack4
    from agentic_traffic_testing_tpu.ops.pallas.int4_matmul import int4_matmul

    x = jax.random.normal(jax.random.key(1), (8, 256), jnp.float32)
    ws = jax.random.normal(jax.random.key(2), (2, 256, 128), jnp.float32)
    qs = quantize_array4(ws, k_group=64)
    q1 = quantize_array4(ws[1], k_group=64)
    ref = x @ _unpack4(q1.packed, q1.scale, jnp.float32)
    got = int4_matmul(x, qs.packed, qs.scale, layer=jnp.int32(1),
                      n_block=128, out_dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-4, rtol=1e-4)

    # K-chunked grid: K*hb*4 > 8 MB forces k_blk < K; groups nest in chunks.
    xk = jax.random.normal(jax.random.key(3), (8, 4096), jnp.float32)
    wk = jax.random.normal(jax.random.key(4), (4096, 1024), jnp.float32)
    qk = quantize_array4(wk, k_group=512)
    refk = xk @ _unpack4(qk.packed, qk.scale, jnp.float32)
    gotk = int4_matmul(xk, qk.packed, qk.scale, n_block=1024,
                       out_dtype=jnp.float32, interpret=True)
    np.testing.assert_allclose(np.asarray(gotk), np.asarray(refk),
                               atol=2e-3, rtol=1e-4)


def test_int4_k_group_engine_matches_dequantized_oracle():
    """End-to-end: the engine serving k-grouped int4 params (fallback path
    on CPU) is token-exact vs serving the dequantized weights."""
    import jax.tree_util as jtu

    from agentic_traffic_testing_tpu.models.quant import QTensor4, _unpack4
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    params = init_params(CFG, jax.random.key(9), dtype=jnp.float32)
    q4 = quantize_params(params, scheme="int4", int4_k_group=32)
    assert q4["layers"]["wq"].scale.ndim == 4

    def deq(leaf):
        if isinstance(leaf, QTensor4):
            return _unpack4(leaf.packed, leaf.scale, jnp.float32)
        return leaf
    deq_params = jtu.tree_map(deq, q4,
                              is_leaf=lambda x: isinstance(x, QTensor4))

    prompt = list(range(9, 29))
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    def run(p):
        eng = LLMEngine(
            EngineConfig(model="tiny", dtype="float32", max_model_len=128,
                         block_size=8, num_blocks=64, max_num_seqs=4),
            model_cfg=CFG, runner=ModelRunner(CFG, p))
        return eng.generate(prompt, samp).output_ids

    assert run(q4) == run(deq_params)


def test_load_params_quantizes_like_in_memory_path(tmp_path):
    """The checkpoint loader's quantize-at-load (weights.load_params) and
    the in-memory quantize_params produce identical QTensor leaves for the
    same weights — pinning the loader-quantizer integration the real-
    checkpoint serving path depends on."""
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    from agentic_traffic_testing_tpu.models.weights import (
        load_params,
        params_from_hf_state_dict,
    )

    torch.manual_seed(11)
    hf_cfg = LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        tie_word_embeddings=False)
    model = LlamaForCausalLM(hf_cfg).eval()
    model.save_pretrained(tmp_path, safe_serialization=True)

    cfg, loaded = load_params(str(tmp_path), dtype=jnp.float32,
                              quantization="int8")
    sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    mem = quantize_params(
        params_from_hf_state_dict(cfg, sd, dtype=np.float32))
    np.testing.assert_array_equal(
        np.asarray(loaded["layers"]["wq"].q), np.asarray(mem["layers"]["wq"].q))
    np.testing.assert_allclose(
        np.asarray(loaded["layers"]["wq"].scale),
        np.asarray(mem["layers"]["wq"].scale), rtol=1e-6)


def test_llama8b_bf16_pp2_fits_where_single_chip_does_not():
    """Capacity check for serving/configs/llama-3.1-8b-bf16-pp2.yaml: the
    8B bf16 weight stack alone crowds a 16 GB v5e chip (this is why the
    single-chip 8B profiles quantize), while pp=2 stages it — ~half the
    layer stack AND half of every KV block per chip — so the UNQUANTIZED
    model serves with the profile's KV working set in budget."""
    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params

    cfg = resolve_config("llama-3.1-8b")
    shapes = jax.eval_shape(
        lambda: init_params(cfg, jax.random.key(0), dtype=jnp.bfloat16))
    total = sum(int(np.prod(l.shape)) * l.dtype.itemsize
                for l in jax.tree_util.tree_leaves(shapes))
    # KV working set of the yaml profile: 8 seqs x 8192 tokens bf16 (8B
    # head_dim is already lane-width 128, so the logical helper equals
    # the phys footprint); the pool's layer axis shards over pp.
    kv_full = cfg.kv_bytes_per_token() * 8 * 8192
    hbm = 16 * 1024**3 * 0.90
    # Single chip: weights + KV blow the budget (the profile's raison
    # d'etre)...
    assert total + kv_full > hbm
    # ...pp=2: the layer stack halves (embeddings/unembed replicate) and
    # so does every block's resident share.
    embed = 2 * cfg.vocab_size * cfg.hidden_size * 2
    per_chip = (total - embed) / 2 + embed + kv_full / 2
    assert per_chip < hbm, per_chip / 1e9
