"""Concurrency statics (statics/concurrency.py) + the runtime ownership
sanitizer (runtime/concurrency.py, LLM_CONCURRENCY_CHECK).

Checker rules are exercised against fixture source trees with seeded
violations — an unowned write in every write shape, a lock-order cycle,
blocking/await under a threading lock, a non-atomic "lock-free" method —
plus clean-tree / pragma-suppression negatives and the generated-doc
round trip, mirroring tests/test_statics.py. Sanitizer tests pin the
off-by-default zero-cost contract and both trip shapes (outside-lock
write, cross-thread write), and run a real-engine churn under the knob
as a dynamic race detector.
"""

from __future__ import annotations

import textwrap
import threading

import numpy as np
import pytest

from agentic_traffic_testing_tpu.statics import concurrency
from agentic_traffic_testing_tpu.statics.common import Finding
from agentic_traffic_testing_tpu.statics.ownership_registry import (
    LockDecl,
    OwnedAttr,
)
from agentic_traffic_testing_tpu.runtime import concurrency as sanitizer

FIX_ATTRS = (
    OwnedAttr("Eng", "counter", "engine-loop", "", "fixture"),
    OwnedAttr("Eng", "items", "engine-loop", "", "fixture"),
    OwnedAttr("Eng", "guarded", "", "_lock", "fixture"),
    OwnedAttr("Eng", "frozen", "init", "", "fixture"),
    OwnedAttr("Eng", "free", "any", "", "fixture"),
)
FIX_LOCKS = (
    LockDecl("Eng", "_lock", "threading", "fixture"),
    LockDecl("Eng", "_lock2", "threading", "fixture"),
    LockDecl("", "_mod_lock", "threading", "fixture"),
)
FIX_REG = {"Eng": "fixture:Eng"}

HEADER = """\
    class Eng:
        def __init__(self):
            self.counter = 0
            self.items = []
            self.guarded = 0
            self.frozen = 1
            self.free = 0
            self._lock = object()
            self._lock2 = object()

        # Touches every registered attribute once so the thread-owner-dead
        # rule stays quiet in minimal fixtures (each test seeds only its
        # own violation).
        # statics: thread(engine-loop)
        def _keepalive(self):
            with self._lock:
                self.guarded += 1
            self.counter = 0
            self.items = []
            self.free = 0
            self.frozen = 1  # statics: allow-thread-unowned-write(fixture keepalive)
"""


def rules(findings: list[Finding]) -> list[str]:
    return sorted(f.rule for f in findings)


def check_fixture(tmp_path, body: str, attrs=FIX_ATTRS, locks=FIX_LOCKS,
                  registered=FIX_REG, with_doc: bool = True):
    p = tmp_path / "m.py"
    p.write_text(textwrap.dedent(body))
    doc = tmp_path / "threading.md"
    if with_doc:
        doc.write_text(concurrency.render(
            str(tmp_path), paths=[str(p)], attrs=attrs, locks=locks))
    return concurrency.check(root=str(tmp_path), paths=[str(p)],
                             attrs=attrs, locks=locks,
                             registered=registered, doc_path=str(doc))


# --------------------------------------------------------- context markers


def test_clean_fixture(tmp_path):
    assert check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def step(self):
            self.counter += 1
            self.items.append(1)
""") == []


def test_unknown_context_marker(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-lop)
        def step(self):
            self.counter += 1
""")
    assert "thread-unknown-context" in rules(fs)


def test_detached_marker_is_a_finding(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)

        def lost_marker_gap(self):
            pass
""")
    assert rules(fs) == ["thread-unknown-context"]


@pytest.mark.parametrize("write", [
    "self.counter = 2",          # plain rebind
    "self.counter += 1",         # augmented read-modify-write
    "self.items[0] = 1",         # container item store
    "self.items.append(1)",      # container mutator call
    "del self.items[0]",         # container delete
])
def test_unowned_write_every_shape(tmp_path, write):
    """Every write shape from a non-owner context is flagged."""
    fs = check_fixture(tmp_path, HEADER + f"""\

        # statics: thread(handler)
        def handler_path(self):
            {write}
""")
    assert rules(fs) == ["thread-unowned-write"]
    assert "handler" in fs[0].message or "owned by context" in fs[0].message


def test_context_propagates_to_unmarked_helper(tmp_path):
    """An unmarked helper inherits its caller's context through the call
    graph — the write inside it is flagged there."""
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(handler)
        def handler_path(self):
            self._helper()

        def _helper(self):
            self.counter += 1
""")
    assert rules(fs) == ["thread-unowned-write"]
    assert "_helper" in fs[0].message


def test_multi_context_write_flagged(tmp_path):
    """A helper reachable from owner AND non-owner contexts is a finding
    (the non-owner path is the race)."""
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def step(self):
            self._helper()

        # statics: thread(scrape)
        def scrape_path(self):
            self._helper()

        def _helper(self):
            self.counter += 1
""")
    assert rules(fs) == ["thread-unowned-write"]


def test_owner_context_write_ok_and_any_owner(tmp_path):
    assert check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def step(self):
            self.counter += 1

        # statics: thread(scrape)
        def scrape_path(self):
            self.free = 3
""") == []


def test_init_owned_attr_runtime_write_flagged(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(handler)
        def handler_path(self):
            self.frozen = 2
""")
    assert rules(fs) == ["thread-unowned-write"]
    assert "construction-only" in fs[0].message


def test_unregistered_attr_write(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def step(self):
            self.surprise = 1
""")
    assert rules(fs) == ["thread-attr-unregistered"]


def test_unregistered_class_with_runtime_writes(tmp_path):
    fs = check_fixture(tmp_path, """\
        class Rogue:
            def __init__(self):
                self.x = 0

            def mutate(self):
                self.x = 1
""", attrs=(), registered={})
    assert rules(fs) == ["thread-class-unregistered"]


def test_dead_registry_row(tmp_path):
    attrs = FIX_ATTRS + (OwnedAttr("Eng", "ghost", "engine-loop", "",
                                   "never written"),)
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def step(self):
            self.counter += 1
            self.items.append(1)
""", attrs=attrs)
    assert rules(fs) == ["thread-owner-dead"]
    assert "ghost" in fs[0].message


# ------------------------------------------------------------- lock rules


def test_lock_guarded_write_requires_lock(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def good(self):
            with self._lock:
                self.guarded += 1

        # statics: thread(engine-loop)
        def bad(self):
            self.guarded += 1
""")
    assert rules(fs) == ["thread-unowned-write"]
    assert "does not hold" in fs[0].message


def test_locked_helper_marker(tmp_path):
    """locked(_lock) lets a helper write under a caller-held lock — and
    the checker verifies every call site actually holds it."""
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: locked(_lock)
        def _apply(self):
            self.guarded += 1

        # statics: thread(engine-loop)
        def good(self):
            with self._lock:
                self._apply()

        # statics: thread(engine-loop)
        def bad(self):
            self._apply()
""")
    assert rules(fs) == ["thread-locked-helper"]
    assert "bad" in fs[0].message


def test_lock_order_cycle(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def ab(self):
            with self._lock:
                with self._lock2:
                    self.counter += 1

        # statics: thread(engine-loop)
        def ba(self):
            with self._lock2:
                with self._lock:
                    self.counter += 1
""")
    assert "thread-lock-order" in rules(fs)


def test_nested_locks_one_order_is_clean(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def ab(self):
            with self._lock:
                with self._lock2:
                    self.counter += 1
""")
    assert fs == []


def test_blocking_under_lock_direct(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def bad(self):
            import time
            with self._lock:
                time.sleep(1)
""")
    assert rules(fs) == ["thread-blocking-under-lock"]


def test_blocking_under_lock_transitive(tmp_path):
    """A blocking call reached THROUGH a scanned callee is still caught
    (the cpu_server get_pipeline shape)."""
    fs = check_fixture(tmp_path, """\
        import time
        import threading

        _mod_lock = threading.Lock()


        def _slow():
            time.sleep(1)


        def racy():
            with _mod_lock:
                _slow()
""", registered={})
    assert rules(fs) == ["thread-blocking-under-lock"]
    assert "_slow" in fs[0].message


def test_await_under_threading_lock(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(handler)
        async def bad(self):
            with self._lock:
                await something()
""")
    assert rules(fs) == ["thread-await-under-lock"]


def test_await_under_asyncio_lock_is_clean(tmp_path):
    locks = FIX_LOCKS + (LockDecl("Eng", "_alock", "asyncio", "fixture"),)
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(handler)
        async def fine(self):
            async with self._alock:
                await something()
""", locks=locks)
    assert fs == []


# ------------------------------------------------------ lock-free contract


def test_lockfree_docstring_mutation(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        def snapshot(self):
            \"\"\"Lock-free load view.\"\"\"
            self.counter += 1
            return self.counter
""")
    assert rules(fs) == ["thread-lockfree-mutation"]


def test_lockfree_double_read(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        def snapshot(self):
            \"\"\"Lock-free probe.\"\"\"
            if self.counter is not None:
                return self.counter
            return 0
""")
    assert rules(fs) == ["thread-lockfree-read"]


def test_lockfree_single_assignment_snapshot_clean(tmp_path):
    assert check_fixture(tmp_path, HEADER + """\

        def snapshot(self):
            \"\"\"Lock-free load view: single reads only.\"\"\"
            return {"c": self.counter, "n": len(self.items)}
""") == []


# ------------------------------------------------------ pragmas and docs


def test_pragma_suppresses_with_reason(tmp_path):
    assert check_fixture(tmp_path, HEADER + """\

        # statics: thread(handler)
        def handler_path(self):
            self.counter += 1  # statics: allow-thread-unowned-write(fixture knows better)
""") == []


def test_doc_drift(tmp_path):
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def step(self):
            self.counter += 1
            self.items.append(1)
""", with_doc=False)
    assert rules(fs) == ["thread-docs-stale"]


def test_real_tree_is_clean():
    """The repository itself carries no unsuppressed concurrency finding
    (the acceptance gate: every finding fixed or reason-pragma'd)."""
    assert concurrency.check() == []


def test_real_doc_matches_tree():
    from agentic_traffic_testing_tpu.statics.common import repo_root
    import os

    with open(os.path.join(repo_root(), concurrency.DOC_RELPATH)) as f:
        assert f.read().strip() == concurrency.render().strip()


# ------------------------------------------------------- runtime sanitizer


@pytest.fixture
def installed(monkeypatch):
    monkeypatch.setenv("LLM_CONCURRENCY_CHECK", "1")
    assert sanitizer.install() > 0
    yield
    sanitizer.uninstall()


def test_sanitizer_off_by_default_zero_cost():
    """Knob unset: maybe_install touches nothing — no wrapper exists on
    any registered class, so the hot loop is byte-identical and pays no
    per-step cost (there is literally no installed code)."""
    from agentic_traffic_testing_tpu.serving.replica_pool import ReplicaHealth
    from agentic_traffic_testing_tpu.runtime.telemetry import StepClock

    assert not sanitizer.enabled()
    assert sanitizer.maybe_install() is False
    assert not sanitizer.installed()
    for cls in (ReplicaHealth, StepClock):
        assert "__setattr__" not in cls.__dict__
        assert "__init__" in cls.__dict__  # the real one, unwrapped
        assert cls.__init__.__qualname__.startswith(cls.__name__)


def test_sanitizer_lock_trip(installed):
    from agentic_traffic_testing_tpu.serving.replica_pool import ReplicaHealth

    h = ReplicaHealth()
    h.record_ok()          # transitions hold _mu: fine
    h.check_stuck()
    assert h.probe() is False
    with pytest.raises(sanitizer.OwnershipViolation):
        h.state = "healthy"   # naked write outside _mu


def test_sanitizer_cross_thread_trip(installed):
    from agentic_traffic_testing_tpu.runtime.telemetry import StepClock

    clk = StepClock()
    t = threading.Thread(
        target=lambda: clk.record_dispatch("decode", 0.0, 0.1, 4, 64),
        name="engine-loop-test")
    t.start()
    t.join()
    with pytest.raises(sanitizer.OwnershipViolation):
        clk.last_decode_batch = 9   # engine-class attr from MainThread


def test_sanitizer_uninstall_restores():
    from agentic_traffic_testing_tpu.serving.replica_pool import ReplicaHealth

    sanitizer.install()
    try:
        h = ReplicaHealth()
        with pytest.raises(sanitizer.OwnershipViolation):
            h.state = "degraded"
    finally:
        sanitizer.uninstall()
    assert "__setattr__" not in ReplicaHealth.__dict__
    h2 = ReplicaHealth()
    h2.state = "degraded"   # unwrapped again


def test_sanitizer_engine_churn_clean(installed):
    """A real engine churn (the tests_faults workload shape) under
    LLM_CONCURRENCY_CHECK=1: the sanitizer observes every attribute
    write of the step loop and raises on none — the dynamic counterpart
    of test_real_tree_is_clean."""
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams

    checks0 = sanitizer.num_checks
    violations0 = sanitizer.num_violations
    eng = LLMEngine(EngineConfig(model="tiny", dtype="float32",
                                 max_num_seqs=4, max_model_len=128,
                                 block_size=16, num_blocks=64))
    wl = np.random.default_rng(7)
    reqs = [eng.add_request(wl.integers(10, 200, 12).tolist(),
                            SamplingParams(temperature=0.0, max_tokens=4,
                                           ignore_eos=True))
            for _ in range(5)]
    steps = 0
    while eng.has_work() and steps < 500:
        eng.step()
        steps += 1
    assert steps < 500
    assert all(r.is_finished() for r in reqs)
    assert sanitizer.num_checks > checks0      # it really was watching
    assert sanitizer.num_violations == violations0


def test_sanitizer_async_handover(installed):
    """Serving mode: the building thread constructs + owns the engine
    until AsyncLLMEngine.start() publishes it; the engine-loop thread
    then binds ownership, and the handler thread streaming results never
    trips. This is the engine-loop vs handler split the registry
    declares, asserted live."""
    import asyncio

    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams
    from agentic_traffic_testing_tpu.serving.async_engine import AsyncLLMEngine

    violations0 = sanitizer.num_violations
    eng = LLMEngine(EngineConfig(model="tiny", dtype="float32",
                                 max_num_seqs=2, max_model_len=128,
                                 block_size=16, num_blocks=64))
    # Pre-publication write from the building thread (the warmup shape).
    eng.num_steps = eng.num_steps
    a = AsyncLLMEngine(eng)

    async def run():
        a.start()
        toks = []
        async for ev in a.generate([5, 6, 7, 8],
                                   SamplingParams(temperature=0.0,
                                                  max_tokens=3,
                                                  ignore_eos=True)):
            toks.extend(ev.new_token_ids)
            if ev.finished:
                break
        return toks

    try:
        toks = asyncio.run(run())
        assert len(toks) == 3
        assert sanitizer.num_violations == violations0
    finally:
        a.shutdown()


def test_lock_reacquisition_deadlock(tmp_path):
    """Taking a non-reentrant lock already held — lexically nested — is
    an immediate self-deadlock finding."""
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def bad(self):
            with self._lock:
                with self._lock:
                    self.counter += 1
""")
    assert "thread-lock-order" in rules(fs)
    assert "re-acquires" in [f for f in fs
                             if f.rule == "thread-lock-order"][0].message


def test_cross_function_self_deadlock(tmp_path):
    """Calling a function that (transitively) acquires a lock the caller
    already holds deadlocks at runtime even though no single function
    nests the acquisition — the call-graph closure catches it."""
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def outer(self):
            with self._lock:
                self._inner()

        def _inner(self):
            with self._lock:
                self.counter += 1
""")
    assert "thread-lock-order" in rules(fs)
    assert any("acquires again" in f.message for f in fs
               if f.rule == "thread-lock-order")


def test_blocking_call_in_with_context_expr(tmp_path):
    """A blocking call used AS a context manager under a lock is still a
    finding (`with requests.get(u) as r:` evaluates the HTTP round trip
    while the lock is held)."""
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def bad(self):
            import requests
            with self._lock:
                with requests.get("http://x") as r:
                    self.counter += 1
""")
    assert "thread-blocking-under-lock" in rules(fs)


def test_with_as_self_attr_is_a_write(tmp_path):
    """`with open(p) as self.fh:` binds a self attribute — recorded as a
    write, so an unregistered target is flagged."""
    fs = check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def step(self):
            with open("p") as self.fh:
                self.counter += 1
""")
    assert "thread-attr-unregistered" in rules(fs)


def test_sanitizer_attr_creating_write_is_construction(installed):
    """install() can land mid-way through an enclosing __init__ (the
    server builds its engine — which installs — before its own later
    fields), so the FIRST write of a lock-guarded field must not assert;
    rewrites of an existing field must."""
    from agentic_traffic_testing_tpu.serving.replica_pool import (
        HEALTHY,
        ReplicaHealth,
    )

    h = ReplicaHealth.__new__(ReplicaHealth)   # no wrapped __init__ ran
    h.state = HEALTHY           # attr-creating write: construction shape
    h._mu = threading.Lock()
    with pytest.raises(sanitizer.OwnershipViolation):
        h.state = HEALTHY       # now it exists: the lock rule applies


def test_lock_order_findings_honor_pragmas(tmp_path):
    """Every thread-lock-order shape is pragma-suppressable (the module's
    suppression contract) — a justified nesting doesn't wedge tier-1."""
    assert check_fixture(tmp_path, HEADER + """\

        # statics: thread(engine-loop)
        def ab(self):
            with self._lock:
                with self._lock2:  # statics: allow-thread-lock-order(fixture says this order is global)
                    self.counter += 1

        # statics: thread(engine-loop)
        def ba(self):
            with self._lock2:
                with self._lock:  # statics: allow-thread-lock-order(fixture says this order is global)
                    self.counter += 1

        # statics: thread(engine-loop)
        def re(self):
            with self._lock:
                with self._lock:  # statics: allow-thread-lock-order(fixture re-entry is mocked)
                    self.counter += 1
""") == []


def test_sanitizer_enabled_bool_spellings(monkeypatch):
    """LLM_CONCURRENCY_CHECK parses like every other bool knob
    (_env_bool): explicit 'false'/'off'/'0' must NOT install a
    production sanitizer."""
    for off in ("0", "", "false", "off", "no"):
        monkeypatch.setenv("LLM_CONCURRENCY_CHECK", off)
        assert not sanitizer.enabled(), off
    for on in ("1", "true", "yes", "on", "TRUE"):
        monkeypatch.setenv("LLM_CONCURRENCY_CHECK", on)
        assert sanitizer.enabled(), on
