"""Pipeline parallelism (parallel/pipeline.py) vs the plain training step.

The GPipe schedule must be a pure parallelization: same loss, same gradients
(checked through one optimizer step), for any stage count and microbatch
count, composed with dp and tp. Runs on the 8-virtual-CPU-device mesh
(SURVEY.md §4 multi-chip test strategy).
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp
import optax

from agentic_traffic_testing_tpu.models.config import ModelConfig
from agentic_traffic_testing_tpu.parallel.mesh import make_mesh
from agentic_traffic_testing_tpu.parallel.pipeline import (
    init_pp_train_state,
    make_pp_train_step,
    pp_param_pspecs,
)
from agentic_traffic_testing_tpu.training.train import (
    init_train_state,
    make_train_step,
)


CFG = ModelConfig(
    name="pp-test", vocab_size=256, hidden_size=64, intermediate_size=128,
    num_layers=4, num_heads=4, num_kv_heads=2, head_dim=16,
)


def batch(b=4, t=16, seed=0):
    rng = np.random.default_rng(seed)
    tokens = jnp.asarray(rng.integers(0, CFG.vocab_size, (b, t)), jnp.int32)
    mask = jnp.ones((b, t), jnp.float32)
    return tokens, mask


def run_one_step(mesh, pipelined, num_microbatches=2, b=4):
    opt = optax.adamw(1e-3)
    tokens, mask = batch(b=b)
    if pipelined:
        params, opt_state = init_pp_train_state(CFG, mesh, opt)
        step = make_pp_train_step(CFG, mesh, opt,
                                  num_microbatches=num_microbatches)
    else:
        params, opt_state = init_train_state(CFG, mesh, opt)
        step = make_train_step(CFG, mesh, opt)
    params, _, loss = step(params, opt_state, tokens, mask)
    return float(loss), params


@pytest.mark.parametrize("pp,mb", [(2, 2), (2, 4), (4, 2), (4, 4)])
def test_pp_step_matches_plain(pp, mb):
    """Loss and post-step params identical (fp32 tolerance) to the
    unpipelined step — the schedule, handoffs, banking, and the backward
    through ppermute/psum are all exact."""
    ref_loss, ref_params = run_one_step(make_mesh(), pipelined=False)
    pp_loss, pp_params = run_one_step(make_mesh(pp=pp), pipelined=True,
                                      num_microbatches=mb)
    assert np.isclose(pp_loss, ref_loss, atol=1e-5), (pp_loss, ref_loss)
    flat_ref = jax.tree_util.tree_leaves(ref_params)
    flat_pp = jax.tree_util.tree_leaves(pp_params)
    for a, b_ in zip(flat_ref, flat_pp):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   atol=2e-5, rtol=2e-5)


def test_pp_composes_with_dp_and_tp():
    """(dp=2, pp=2, tp=2) over all 8 devices: stage weights pp-sharded AND
    Megatron tp-sharded, batch dp-sharded — loss still matches 1 device."""
    ref_loss, _ = run_one_step(make_mesh(), pipelined=False)
    mesh = make_mesh(dp=2, tp=2, pp=2)
    loss, params = run_one_step(mesh, pipelined=True, num_microbatches=2)
    assert np.isclose(loss, ref_loss, atol=1e-5)
    # the layer stack really is sharded over pp (2 stages x 2-way tp)
    wq = params["layers"]["wq"]
    assert len(wq.sharding.spec) >= 1 and wq.sharding.spec[0] == "pp"


def test_pp_composes_with_sp():
    """(dp=2, sp=2, pp=2): activations stay sequence-sharded through the
    schedule and every stage attends via ring attention over sp — loss and
    stepped params still match the unpipelined, unsharded step."""
    ref_loss, ref_params = run_one_step(make_mesh(), pipelined=False)
    loss, params = run_one_step(make_mesh(dp=2, sp=2, pp=2), pipelined=True,
                                num_microbatches=2)
    assert np.isclose(loss, ref_loss, atol=1e-5), (loss, ref_loss)
    for a, b_ in zip(jax.tree_util.tree_leaves(ref_params),
                     jax.tree_util.tree_leaves(params)):
        np.testing.assert_allclose(np.asarray(b_), np.asarray(a),
                                   atol=2e-5, rtol=2e-5)


def test_pp_validations():
    with pytest.raises(ValueError, match="divisible"):
        make_pp_train_step(CFG, make_mesh(pp=3))
    moe_cfg = ModelConfig(
        name="pp-moe-sp", vocab_size=256, hidden_size=64,
        intermediate_size=128, num_layers=4, num_heads=4, num_kv_heads=2,
        head_dim=16, num_experts=4, num_experts_per_tok=2,
    )
    with pytest.raises(ValueError, match="sp=1"):
        make_pp_train_step(moe_cfg, make_mesh(sp=2, pp=2))


def test_pp_pspecs_shape():
    specs = pp_param_pspecs(CFG)
    assert specs["layers"]["wq"][0] == "pp"
    assert specs["layers"]["wq"][2] == "tp"
    assert specs["tok_embed"][0] is None  # replicated over pp
