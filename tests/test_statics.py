"""The statics plane (agentic_traffic_testing_tpu/statics/).

Each checker is exercised against fixture source trees with seeded
violations — an unregistered knob read, a mesh runner missing its
refusal guard, an un-pragma'd host sync in a hot region, a post-dispatch
read of a donated buffer — plus clean-tree and pragma-suppression
negatives, and the generated-doc round trips (regenerate-and-diff).

Pure AST work on tmp files: no jax arrays, no engines — these run in
milliseconds in the default tier.
"""

import os
import textwrap

import pytest

from agentic_traffic_testing_tpu.statics import (
    capabilities,
    donation,
    host_sync,
    knobs,
    run_all,
    write_docs,
)
from agentic_traffic_testing_tpu.statics.common import (
    Finding,
    SourceFile,
    bare_pragma_findings,
    repo_root,
)
from agentic_traffic_testing_tpu.statics.knob_registry import KNOBS, Knob

REPO = repo_root()


def write(tmp_path, relpath: str, body: str) -> str:
    p = tmp_path / relpath
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(textwrap.dedent(body))
    return str(p)


def rules(findings: list[Finding]) -> list[str]:
    return sorted(f.rule for f in findings)


# ------------------------------------------------------------------ pragmas


def test_pragma_requires_reason(tmp_path):
    p = write(tmp_path, "m.py", """\
        import os
        x = os.environ.get("LLM_BOGUS_KNOB")  # statics: allow-knob-unregistered
    """)
    src = SourceFile(p, str(tmp_path))
    fs = bare_pragma_findings(src)
    assert rules(fs) == ["pragma-missing-reason"]
    # And the bare pragma does NOT suppress the underlying finding.
    assert not src.allowed("knob-unregistered", src.tree.body[1].value)


def test_pragma_empty_reason_is_bare(tmp_path):
    """`allow-rule()` is a reasonless allow, not a valid suppression."""
    p = write(tmp_path, "m.py", """\
        import os
        x = os.environ.get("LLM_BOGUS_KNOB")  # statics: allow-knob-unregistered()
    """)
    src = SourceFile(p, str(tmp_path))
    assert rules(bare_pragma_findings(src)) == ["pragma-missing-reason"]
    assert not src.allowed("knob-unregistered", src.tree.body[1].value)


def test_pragma_two_rules_one_comment(tmp_path):
    """One statics comment can suppress two rules on the same statement."""
    p = write(tmp_path, "m.py", """\
        import os
        x = os.environ.get("K")  # statics: allow-host-sync(a) allow-donation(b)
    """)
    src = SourceFile(p, str(tmp_path))
    node = src.tree.body[1].value
    assert src.allowed("host-sync", node)
    assert src.allowed("donation", node)
    assert bare_pragma_findings(src) == []


def test_pragma_spans_multiline_statement(tmp_path):
    p = write(tmp_path, "m.py", """\
        import os
        x = os.environ.get(
            "LLM_BOGUS_KNOB",  # statics: allow-knob-unregistered(fixture)
            "0")
    """)
    fs = knobs.check(root=str(tmp_path), knobs=(), paths=[p],
                     doc_path=str(tmp_path / "knobs.md"))
    assert rules(fs) == ["knob-docs-stale"]  # only the missing doc


# ------------------------------------------------------------------- knobs


FIXTURE_KNOBS = (
    Knob("LLM_FIXTURE_A", "int", "1", "m.py", "registered and read."),
)


def _knob_check(tmp_path, body: str, registry=FIXTURE_KNOBS):
    p = write(tmp_path, "m.py", body)
    doc = tmp_path / "knobs.md"
    doc.write_text(knobs.render_doc(registry))
    return knobs.check(root=str(tmp_path), knobs=registry, paths=[p],
                       doc_path=str(doc))


def test_knob_clean_tree(tmp_path):
    assert _knob_check(tmp_path, """\
        import os
        a = os.environ.get("LLM_FIXTURE_A", "1")
    """) == []


def test_knob_unregistered_read_fires(tmp_path):
    fs = _knob_check(tmp_path, """\
        import os
        a = os.environ.get("LLM_FIXTURE_A", "1")
        b = os.environ.get("BENCH_FIXTURE_UNREGISTERED")
    """)
    assert rules(fs) == ["knob-unregistered"]
    assert "BENCH_FIXTURE_UNREGISTERED" in fs[0].message
    assert fs[0].line == 3


@pytest.mark.parametrize("read", [
    'os.getenv("BENCH_FIXTURE_UNREGISTERED")',
    'os.environ["BENCH_FIXTURE_UNREGISTERED"]',
    'env.get("BENCH_FIXTURE_UNREGISTERED", "0")',
    '_env_bool("BENCH_FIXTURE_UNREGISTERED")',
])
def test_knob_read_shapes_detected(tmp_path, read):
    """Every env-read idiom in the tree is seen: os.getenv, subscript,
    env-dict .get, and the registered wrapper helpers."""
    fs = _knob_check(tmp_path, f"""\
        import os
        a = os.environ.get("LLM_FIXTURE_A", "1")
        env = dict(os.environ)
        b = {read}
    """)
    assert rules(fs) == ["knob-unregistered"]


def test_knob_write_is_not_a_read(tmp_path):
    assert _knob_check(tmp_path, """\
        import os
        a = os.environ.get("LLM_FIXTURE_A", "1")
        os.environ["BENCH_FIXTURE_UNREGISTERED"] = "1"
        os.environ.pop("BENCH_FIXTURE_UNREGISTERED", None)
    """) == []


def test_knob_pragma_suppresses(tmp_path):
    assert _knob_check(tmp_path, """\
        import os
        a = os.environ.get("LLM_FIXTURE_A", "1")
        b = os.environ.get("BENCH_FIXTURE_UNREGISTERED")  # statics: allow-knob-unregistered(fixture reason)
    """) == []


def test_knob_dead_entry_fires(tmp_path):
    registry = FIXTURE_KNOBS + (
        Knob("LLM_FIXTURE_DEAD", "int", "0", "m.py", "never read."),)
    fs = _knob_check(tmp_path, """\
        import os
        a = os.environ.get("LLM_FIXTURE_A", "1")
    """, registry=registry)
    assert rules(fs) == ["knob-dead"]
    assert "LLM_FIXTURE_DEAD" in fs[0].message


def test_knob_doc_round_trip(tmp_path):
    p = write(tmp_path, "m.py", """\
        import os
        a = os.environ.get("LLM_FIXTURE_A", "1")
    """)
    doc = tmp_path / "knobs.md"
    # Missing doc -> stale; regenerated doc -> clean; edited doc -> stale.
    fs = knobs.check(root=str(tmp_path), knobs=FIXTURE_KNOBS, paths=[p],
                     doc_path=str(doc))
    assert rules(fs) == ["knob-docs-stale"]
    doc.write_text(knobs.render_doc(FIXTURE_KNOBS))
    assert knobs.check(root=str(tmp_path), knobs=FIXTURE_KNOBS, paths=[p],
                       doc_path=str(doc)) == []
    doc.write_text(doc.read_text().replace("LLM_FIXTURE_A", "LLM_EDITED"))
    fs = knobs.check(root=str(tmp_path), knobs=FIXTURE_KNOBS, paths=[p],
                     doc_path=str(doc))
    assert rules(fs) == ["knob-docs-stale"]


# ------------------------------------------------------------ capabilities


RUNNER_FIXTURE = """\
    class ModelRunner:
        supports_fast_path: bool = True
        supports_other = True

    class MeshRunner(ModelRunner):
        supports_fast_path = False

    class MeshierRunner(MeshRunner):
        pass
"""

ENGINE_GUARDED = """\
    class Engine:
        def __init__(self, cfg, runner):
            if cfg.fast_path and not getattr(
                    runner, "supports_fast_path", False):
                raise ValueError("no fast path on this runner")
"""


def _cap_check(tmp_path, runner_body=RUNNER_FIXTURE,
               engine_body=ENGINE_GUARDED, write_doc=True):
    rp = write(tmp_path, "runner.py", runner_body)
    ep = write(tmp_path, "engine.py", engine_body)
    doc = tmp_path / "capabilities.md"
    if write_doc:
        srcs = [SourceFile(rp, str(tmp_path))]
        runners, bases, _ = capabilities.scan_runners(srcs)
        matrix = capabilities.resolve_matrix(runners, bases)
        order = ["ModelRunner"] + [c for c in runners if c != "ModelRunner"]
        doc.write_text(capabilities.render_doc(matrix, order))
    return capabilities.check(
        root=str(tmp_path), runner_path=rp, mesh_paths=[],
        guard_paths=[ep], doc_path=str(doc))


def test_capability_clean_tree(tmp_path):
    assert _cap_check(tmp_path) == []


def test_capability_missing_guard_fires(tmp_path):
    fs = _cap_check(tmp_path, engine_body="""\
        class Engine:
            def __init__(self, cfg, runner):
                pass
    """)
    assert rules(fs) == ["capability-missing-guard"]
    assert "supports_fast_path" in fs[0].message
    assert "MeshRunner" in fs[0].message


def test_capability_non_literal_flag_fires(tmp_path):
    """A computed flag value would resolve to '?' and dodge the
    missing-guard audit — it must be its own finding."""
    fs = _cap_check(tmp_path, runner_body=RUNNER_FIXTURE + """\

    class ComputedRunner(ModelRunner):
        supports_fast_path = _FAST_OK
    """, write_doc=False)
    assert "capability-non-literal" in rules(fs)


def test_capability_feature_branch_is_not_a_guard(tmp_path):
    """An `if` that READS the flag to take a feature path doesn't become a
    refusal guard just because some nested statement raises."""
    fs = _cap_check(tmp_path, engine_body="""\
        class Engine:
            def __init__(self, cfg, runner):
                if runner.supports_fast_path:
                    for step in cfg.steps:
                        if step < 0:
                            raise ValueError("bad step count")
    """)
    assert rules(fs) == ["capability-missing-guard"]


def test_capability_unknown_flag_fires(tmp_path):
    fs = _cap_check(tmp_path, runner_body=RUNNER_FIXTURE + """\

    class TypoRunner(ModelRunner):
        supports_fastpath = False  # typo'd: base declares supports_fast_path
    """, write_doc=False)
    assert "capability-unknown-flag" in rules(fs)


def test_capability_inheritance_resolves(tmp_path):
    """MeshierRunner declares nothing itself; the matrix must resolve its
    fast-path flag False through MeshRunner, not fall back to the base."""
    rp = write(tmp_path, "runner.py", RUNNER_FIXTURE)
    srcs = [SourceFile(rp, str(tmp_path))]
    runners, bases, _ = capabilities.scan_runners(srcs)
    matrix = capabilities.resolve_matrix(runners, bases)
    assert matrix["supports_fast_path"]["MeshierRunner"] is False
    assert matrix["supports_other"]["MeshierRunner"] is True


def test_capability_attribute_base_resolves(tmp_path):
    """A module-qualified base (`runner.ModelRunner`) keeps the subclass in
    the matrix — and its typo'd flags visible to the unknown-flag check."""
    fs = _cap_check(tmp_path, runner_body=RUNNER_FIXTURE + """\

    class QualifiedRunner(runner.MeshRunner):
        supports_fastpath = False  # typo'd: base declares supports_fast_path
    """, write_doc=False)
    assert "capability-unknown-flag" in rules(fs)


def test_capability_doc_round_trip(tmp_path):
    fs = _cap_check(tmp_path, write_doc=False)
    assert rules(fs) == ["capability-docs-stale"]


# ---------------------------------------------------------------- host-sync


HOT_CLEAN = """\
    import jax
    import jax.numpy as jnp

    class E:
        # statics: hot-region(decode-loop)
        def dispatch(self, state):
            tables = jnp.asarray([1, 2])          # upload: fine
            out = self.runner.decode(state, tables)
            out.copy_to_host_async()              # async: fine
            return out

        def cold(self, out):
            return jax.device_get(out)            # unmarked function: fine
"""


def test_host_sync_clean_tree(tmp_path):
    p = write(tmp_path, "e.py", HOT_CLEAN)
    assert host_sync.check(root=str(tmp_path), paths=[p]) == []


@pytest.mark.parametrize("sync,expect", [
    ("jax.device_get(out)", "jax.device_get"),
    ("out.block_until_ready()", ".block_until_ready()"),
    ("np.asarray(out)", "np.asarray"),
    ("out.item()", ".item()"),
    ("float(out)", "float() conversion"),
])
def test_host_sync_fires_in_hot_region(tmp_path, sync, expect):
    p = write(tmp_path, "e.py", f"""\
        import jax
        import numpy as np

        class E:
            # statics: hot-region(decode-loop)
            def dispatch(self, out):
                x = {sync}
                return x
    """)
    fs = host_sync.check(root=str(tmp_path), paths=[p])
    assert rules(fs) == ["host-sync"]
    assert expect in fs[0].message
    assert "decode-loop" in fs[0].message


def test_host_sync_pragma_suppresses(tmp_path):
    p = write(tmp_path, "e.py", """\
        import jax

        class E:
            # statics: hot-region(harvest)
            def retire(self, leaves):
                return jax.device_get(leaves)  # statics: allow-host-sync(the one batched readback)
    """)
    assert host_sync.check(root=str(tmp_path), paths=[p]) == []


def test_host_sync_repo_hot_regions_marked():
    """The live tree keeps its decode/prefill/hybrid dispatch paths marked
    — an empty marker set would silently disable the whole lint."""
    src = SourceFile(os.path.join(
        REPO, "agentic_traffic_testing_tpu", "runtime", "engine.py"), REPO)
    regions = {name for name, _ in src.hot_functions()}
    assert {"decode-loop", "prefill-pipeline", "hybrid-dispatch",
            "harvest"} <= regions


# ----------------------------------------------------------------- donation


RUNNER_DONATING = """\
    import jax
    from functools import partial

    def _decode_impl(params, cache, state):
        return state, cache, None

    class ModelRunner:
        def __init__(self):
            self._decode = jax.jit(
                partial(_decode_impl),
                donate_argnames=("cache", "state"),
            )

        def decode(self, cache, state):
            return self._decode(self.params, cache=cache, state=state)
"""


def _donation_check(tmp_path, engine_body):
    rp = write(tmp_path, "runner.py", RUNNER_DONATING)
    ep = write(tmp_path, "engine.py", engine_body)
    return donation.check(root=str(tmp_path), runner_path=rp,
                          caller_paths=[ep])


def test_donation_clean_rebind(tmp_path):
    assert _donation_check(tmp_path, """\
        class Engine:
            def step(self):
                self._state, self.cache, out = self.runner.decode(
                    self.cache, self._state)
                return out
    """) == []


def test_donation_post_dispatch_read_fires(tmp_path):
    fs = _donation_check(tmp_path, """\
        class Engine:
            def step(self):
                result = self.runner.decode(self.cache, self._state)
                stale = self._state.tokens    # reads the donated buffer
                self._state, self.cache, out = result
                return out, stale
    """)
    assert rules(fs) == ["donation"]
    assert "self._state" in fs[0].message
    assert fs[0].line == 4


def test_donation_keyword_arg_tracked(tmp_path):
    fs = _donation_check(tmp_path, """\
        class Engine:
            def step(self):
                result = self.runner.decode(cache=self.cache,
                                            state=self._state)
                leak = self.cache.k           # donated via keyword
                self._state, self.cache, out = result
                return leak
    """)
    assert rules(fs) == ["donation"]
    assert "self.cache" in fs[0].message


def test_donation_branchwise_rebind_is_clean(tmp_path):
    """The engine's real shape: the rebind happens inside an if/else —
    taint must clear only when EVERY branch rebinds."""
    assert _donation_check(tmp_path, """\
        class Engine:
            def step(self, spec):
                result = self.runner.decode(self.cache, self._state)
                if spec:
                    self._state, self.cache, out, counts = result
                else:
                    self._state, self.cache, out = result
                return self.cache, self._state
    """) == []


def test_donation_one_armed_rebind_still_tainted(tmp_path):
    fs = _donation_check(tmp_path, """\
        class Engine:
            def step(self, spec):
                result = self.runner.decode(self.cache, self._state)
                if spec:
                    self._state, self.cache, out = result
                return self._state
    """)
    assert rules(fs) == ["donation"]


def test_donation_loop_carried_read_fires(tmp_path):
    """Reading the donated binding at the top of the NEXT iteration."""
    fs = _donation_check(tmp_path, """\
        class Engine:
            def steps(self, n):
                for _ in range(n):
                    stale = self._state
                    out = self.runner.decode(self.cache, self._state)
                    self.cache = out[1]
                return stale
    """)
    # Two reads of the donated state: the top-of-loop snapshot AND the
    # re-pass into the next dispatch (both stale after iteration 1).
    assert set(rules(fs)) == {"donation"} and len(fs) == 2


def test_donation_attribute_store_keeps_taint(tmp_path):
    """`state.attr = x` mutates the donated buffer, it doesn't rebind
    `state` — reads after it must still be flagged."""
    fs = _donation_check(tmp_path, """\
        class Engine:
            def step(self):
                result = self.runner.decode(self.cache, self._state)
                self._state.steps = 0
                stale = self._state.tokens
                self._state, self.cache, out = result
                return out, stale
    """)
    assert set(rules(fs)) == {"donation"}
    assert {f.line for f in fs} == {4, 5}  # the mutation's read AND the later read


def test_donation_for_target_rebinds(tmp_path):
    """A for target rebinds its name every iteration — reads of it in the
    body are fresh, not stale reads of the donated buffer."""
    assert _donation_check(tmp_path, """\
        class Engine:
            def steps(self, plans):
                out = self.runner.decode(self.cache, states)
                for states in plans:
                    use = states.tokens
                return use
    """) == []


def test_donation_while_test_read_fires(tmp_path):
    """The while test re-evaluates after each iteration, so a binding
    donated by the body is stale when the test reads it again."""
    fs = _donation_check(tmp_path, """\
        class Engine:
            def steps(self):
                while self._state.ready:
                    out = self.runner.decode(self.cache, self._state)
                    self.cache = out[1]
    """)
    assert set(rules(fs)) == {"donation"}
    assert any(f.line == 3 for f in fs)  # the loop-test read itself


def test_donation_alias_dispatch_tracked(tmp_path):
    fs = _donation_check(tmp_path, """\
        class Engine:
            def step(self):
                decode = self.runner.decode
                result = decode(self.cache, self._state)
                leak = self._state
                self._state, self.cache, out = result
                return leak
    """)
    assert rules(fs) == ["donation"]


def test_donation_except_handler_read_fires(tmp_path):
    """A handler can run after the donation but before the body's rebind,
    so its read of the donated binding is stale even though the body
    rebinds on the success path."""
    fs = _donation_check(tmp_path, """\
        class Engine:
            def step(self):
                try:
                    out = self.runner.decode(self.cache, self._state)
                    self._state, self.cache, res = out
                except Exception:
                    self.recover(self._state)
                return res
    """)
    assert rules(fs) == ["donation"]
    assert fs[0].line == 7  # the handler's read


def test_donation_dispatch_in_if_test_taints(tmp_path):
    """A dispatch buried in a condition expression still donates."""
    fs = _donation_check(tmp_path, """\
        class Engine:
            def step(self):
                if self.runner.decode(self.cache, self._state)[2] is None:
                    return None
                return self.cache.k
    """)
    assert rules(fs) == ["donation"]
    assert "self.cache" in fs[0].message
    assert fs[0].line == 5


def test_donation_alias_rebind_invalidates(tmp_path):
    """Rebinding an alias name to a non-dispatch callable must stop calls
    through it from tainting their arguments."""
    assert _donation_check(tmp_path, """\
        class Engine:
            def step(self):
                decode = self.runner.decode
                out = decode(self.cache, self._state)
                self._state, self.cache, res = out
                decode = self._lookup_table.get
                val = decode(self.key)
                return res, self.key, val
    """) == []


def test_donation_pragma_suppresses(tmp_path):
    assert _donation_check(tmp_path, """\
        class Engine:
            def step(self):
                result = self.runner.decode(self.cache, self._state)
                stale = self._state  # statics: allow-donation(fixture: provably unreachable buffer)
                self._state, self.cache, out = result
                return stale
    """) == []


# ------------------------------------------------------------ whole plane


def test_run_all_green_on_tree():
    """The acceptance gate: zero unsuppressed findings on the live tree.
    (test_scripts.py::test_statics_all_smoke additionally runs the CLI.)"""
    report = run_all(REPO)
    assert report["ok"], {
        name: c["findings"] for name, c in report["checkers"].items()
        if c["findings"]}
    assert set(report["checkers"]) == {
        "knobs", "capabilities", "host-sync", "donation", "concurrency",
        "metric-docs", "kernelcontract"}


def test_run_all_dedups_repeats_not_distinct_findings(monkeypatch):
    """Cross-checker repeats of the same finding collapse; two findings
    sharing a location but differing in message both survive."""
    import agentic_traffic_testing_tpu.statics as statics_pkg
    shared = Finding("pragma-missing-reason", "engine.py", 7, "no reason")
    dead_a = Finding("knob-dead", "knob_registry.py", 1, "LLM_A is dead")
    dead_b = Finding("knob-dead", "knob_registry.py", 1, "LLM_B is dead")
    monkeypatch.setattr(statics_pkg, "CHECKERS", (
        ("first", lambda root: [shared, dead_a, dead_b]),
        ("second", lambda root: [shared]),
    ))
    report = statics_pkg.run_all(REPO)
    assert len(report["checkers"]["first"]["findings"]) == 3
    assert report["checkers"]["second"]["findings"] == []


def test_generated_docs_round_trip(tmp_path):
    """write_docs output == committed docs (the regenerate-and-diff gate,
    exercised through the real --write-docs file-writing path)."""
    # Mirror the runner + serving-plane + kernel sources into a tmp root
    # so write_docs() runs its actual path joins and file writes without
    # touching the repo.
    from agentic_traffic_testing_tpu.statics import concurrency, kernelcontract
    from agentic_traffic_testing_tpu.statics.kernel_registry import KERNELS

    for rel in ((capabilities.RUNNER_RELPATH,) + capabilities.MESH_RELPATHS
                + concurrency.SCAN_RELPATHS
                + tuple({k.module for k in KERNELS})):
        dst = tmp_path / rel
        dst.parent.mkdir(parents=True, exist_ok=True)
        dst.write_text(open(os.path.join(REPO, rel)).read())
    (tmp_path / "docs").mkdir()
    written = write_docs(str(tmp_path))
    assert sorted(written) == sorted(
        [knobs.DOC_RELPATH, capabilities.DOC_RELPATH,
         concurrency.DOC_RELPATH, kernelcontract.DOC_RELPATH])
    for rel in written:
        committed = open(os.path.join(REPO, rel)).read()
        assert (tmp_path / rel).read_text() == committed
