"""Infra-plane validity: shell syntax, compose/config YAML, dashboard JSON.

The reference has no tests for its ops plane (SURVEY.md §4); these pin the
files that deploy/measure the testbed so a bad edit fails CI, not a deploy.
"""

from __future__ import annotations

import json
import pathlib
import subprocess

import pytest
import yaml

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPTS = sorted((REPO / "scripts").rglob("*.sh"))
COMPOSE_FILES = sorted((REPO / "infra").glob("docker-compose*.yml"))
SERVING_CONFIGS = sorted(
    (REPO / "agentic_traffic_testing_tpu" / "serving" / "configs").glob("*.yaml"))


@pytest.mark.parametrize("script", SCRIPTS, ids=lambda p: str(p.relative_to(REPO)))
def test_shell_syntax(script):
    subprocess.run(["bash", "-n", str(script)], check=True)


@pytest.mark.parametrize("compose", COMPOSE_FILES, ids=lambda p: p.name)
def test_compose_parses(compose):
    doc = yaml.safe_load(compose.read_text())
    assert doc.get("services"), f"{compose.name}: no services"


def test_monitoring_composes_cover_observability_plane():
    for name in ("docker-compose.monitoring.yml",
                 "docker-compose.monitoring.distributed.yml"):
        doc = yaml.safe_load((REPO / "infra" / name).read_text())
        for svc in ("prometheus", "grafana", "cadvisor", "docker-mapping-exporter"):
            assert svc in doc["services"], f"{name}: missing {svc}"


def test_serving_configs_match_server_config_fields():
    import dataclasses

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.serving.config import ServerConfig

    fields = {f.name for f in dataclasses.fields(ServerConfig)}
    assert SERVING_CONFIGS, "no serving config profiles found"
    for path in SERVING_CONFIGS:
        doc = yaml.safe_load(path.read_text())
        unknown = set(doc) - fields
        assert not unknown, f"{path.name}: unknown keys {unknown}"
        resolve_config(doc["model"])  # every profile names a known architecture


def test_grafana_dashboard_json():
    dash = json.loads((REPO / "infra" / "monitoring" / "grafana" / "dashboards"
                       / "agentic-traffic.json").read_text())
    assert dash.get("uid") == "agentic-traffic-testbed"
    assert dash.get("panels") or dash.get("rows")


def test_grafana_dashboard_panel_parity():
    """Reference dashboard parity: >= 44 panels (the reference's count) and
    every PromQL expr references only metric families something in this repo
    (or cAdvisor/node-exporter, which the monitoring compose ships) exports.
    scrape_metrics.py treats the dashboard as the scrape schema, so a panel
    querying a family nothing exports silently shrinks every experiment's
    metrics.csv."""
    import re
    import sys

    dash_path = (REPO / "infra" / "monitoring" / "grafana" / "dashboards"
                 / "agentic-traffic.json")
    sys.path.insert(0, str(REPO / "scripts" / "experiment"))
    try:
        from scrape_metrics import load_dashboard_panels
    finally:
        sys.path.pop(0)
    pairs = load_dashboard_panels(str(dash_path))
    dash = json.loads(dash_path.read_text())
    assert len(dash["panels"]) >= 44, len(dash["panels"])
    assert len(pairs) >= 36  # every non-row panel carries at least one expr

    # The repo's own exported families.
    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics

    llm = set()
    for fam in LLMMetrics("llm").registry.collect():
        llm.add(fam.name)
        if fam.type == "histogram":
            llm.update({f"{fam.name}_bucket", f"{fam.name}_sum",
                        f"{fam.name}_count"})
        if fam.type == "counter":
            llm.add(f"{fam.name}_total")
    collector_src = (REPO / "scripts" / "monitoring"
                     / "tcp_metrics_collector.py").read_text()
    exporter_src = (REPO / "scripts" / "monitoring"
                    / "docker_mapping_exporter.py").read_text()
    exported = llm | set(re.findall(r"\btcp_[a-z_]+", collector_src)) \
        | set(re.findall(r"\bdocker_[a-z_]+", exporter_src))

    # Shipped by the monitoring compose's cAdvisor/node-exporter containers.
    shipped_prefixes = ("container_", "machine_", "node_")
    promql_funcs = {
        "rate", "irate", "increase", "sum", "avg", "min", "max", "count",
        "by", "le", "on", "ignoring", "group_left", "group_right", "vector",
        "time", "histogram_quantile", "label_replace", "clamp_min",
        "clamp_max", "abs", "or", "and", "unless", "without", "topk",
        "bottomk", "delta", "idelta", "deriv", "quantile", "max_over_time",
        "avg_over_time", "sum_over_time", "min_over_time",
    }
    bad = []
    for panel, expr in pairs:
        # Strip label selectors, strings, ranges, and by/without grouping
        # clauses (their contents are label names, not metric families).
        stripped = re.sub(r'\{[^}]*\}|"[^"]*"|\[[^\]]*\]', " ", expr)
        stripped = re.sub(r"\b(by|without|on|ignoring|group_left|group_right)"
                          r"\s*\([^)]*\)", " ", stripped)
        for tok in re.findall(r"[a-zA-Z_:][a-zA-Z0-9_:]*", stripped):
            if tok in promql_funcs or tok.startswith(shipped_prefixes):
                continue
            base = re.sub(r"_(bucket|sum|count)$", "", tok)
            if tok not in exported and base not in exported:
                bad.append((panel, tok))
    assert not bad, f"dashboard exprs reference unexported families: {bad}"


def test_prometheus_scrapes_llm_backend():
    doc = yaml.safe_load((REPO / "infra" / "monitoring" / "prometheus.yml").read_text())
    jobs = {j["job_name"] for j in doc["scrape_configs"]}
    assert "llm-backend" in jobs
