"""Infra-plane validity: shell syntax, compose/config YAML, dashboard JSON.

The reference has no tests for its ops plane (SURVEY.md §4); these pin the
files that deploy/measure the testbed so a bad edit fails CI, not a deploy.
"""

from __future__ import annotations

import json
import pathlib
import subprocess

import pytest
import yaml

REPO = pathlib.Path(__file__).resolve().parent.parent
SCRIPTS = sorted((REPO / "scripts").rglob("*.sh"))
COMPOSE_FILES = sorted((REPO / "infra").glob("docker-compose*.yml"))
SERVING_CONFIGS = sorted(
    (REPO / "agentic_traffic_testing_tpu" / "serving" / "configs").glob("*.yaml"))


@pytest.mark.parametrize("script", SCRIPTS, ids=lambda p: str(p.relative_to(REPO)))
def test_shell_syntax(script):
    subprocess.run(["bash", "-n", str(script)], check=True)


@pytest.mark.parametrize("compose", COMPOSE_FILES, ids=lambda p: p.name)
def test_compose_parses(compose):
    doc = yaml.safe_load(compose.read_text())
    assert doc.get("services"), f"{compose.name}: no services"


def test_monitoring_composes_cover_observability_plane():
    for name in ("docker-compose.monitoring.yml",
                 "docker-compose.monitoring.distributed.yml"):
        doc = yaml.safe_load((REPO / "infra" / name).read_text())
        for svc in ("prometheus", "grafana", "cadvisor", "docker-mapping-exporter"):
            assert svc in doc["services"], f"{name}: missing {svc}"


def test_serving_configs_match_server_config_fields():
    import dataclasses

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.serving.config import ServerConfig

    fields = {f.name for f in dataclasses.fields(ServerConfig)}
    assert SERVING_CONFIGS, "no serving config profiles found"
    for path in SERVING_CONFIGS:
        doc = yaml.safe_load(path.read_text())
        unknown = set(doc) - fields
        assert not unknown, f"{path.name}: unknown keys {unknown}"
        resolve_config(doc["model"])  # every profile names a known architecture


def test_grafana_dashboard_json():
    dash = json.loads((REPO / "infra" / "monitoring" / "grafana" / "dashboards"
                       / "agentic-traffic.json").read_text())
    assert dash.get("uid") == "agentic-traffic-testbed"
    assert dash.get("panels") or dash.get("rows")


def test_prometheus_scrapes_llm_backend():
    doc = yaml.safe_load((REPO / "infra" / "monitoring" / "prometheus.yml").read_text())
    jobs = {j["job_name"] for j in doc["scrape_configs"]}
    assert "llm-backend" in jobs
