"""fp8 (e4m3) KV-cache pages: capacity, kernel/oracle parity, accuracy.

Round-3 verdict item #4 ("int8 KV-cache pages"), shipped as fp8: e4m3's
per-element exponent needs NO scale plumbing (per-token int8 scales cannot
ride Mosaic's lane-width DMA granularity without real page overhead), and
fp8 KV is exactly what the reference inherits from vLLM
(--kv-cache-dtype fp8; reference llm/serve_llm.py engine args). Doubles
`llm_kv_cache_total_tokens` and computed concurrency, halves the decode
KV stream.

Parity structure: the pallas decode kernels and the jnp gather oracle
dequantize the SAME stored f8 values, so kernel-vs-oracle stays exact;
the accuracy cost of fp8 itself is pinned separately against a bf16-KV
engine (correlation + argmax agreement, not token-exactness — e4m3 is
~2% RMS on K/V).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import forward_full, init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import SamplingParams

CFG = PRESETS["tiny"]


def test_engine_config_validates_kv_dtype():
    with pytest.raises(ValueError, match="kv_cache_dtype"):
        EngineConfig(model="tiny", kv_cache_dtype="int3")


def test_fp8_pool_allocated_and_engine_decodes():
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny", dtype="float32", kv_cache_dtype="fp8",
                        num_blocks=64, max_model_len=128, max_num_seqs=4)
    eng = LLMEngine(ecfg, model_cfg=CFG, params=params)
    assert eng.cache.k.dtype == jnp.float8_e4m3fn
    out = eng.generate(list(range(5, 25)),
                       SamplingParams(temperature=0.0, max_tokens=8,
                                      ignore_eos=True))
    assert len(out.output_ids) == 8
    assert all(0 <= t < CFG.vocab_size for t in out.output_ids)


def test_fp8_decode_tracks_bf16_kv_logits():
    """fp8 KV pages degrade logits only within the e4m3 envelope: greedy
    argmax agreement stays high vs the full-precision-KV engine and the
    first decode step's tokens match (the first step reads only
    prefill-written KV of a short prompt)."""
    params = init_params(CFG, jax.random.key(1), dtype=jnp.float32)
    prompt = list(range(7, 27))
    samp = SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True)

    def run(kv):
        ecfg = EngineConfig(model="tiny", dtype="float32", kv_cache_dtype=kv,
                            num_blocks=64, max_model_len=128)
        return LLMEngine(ecfg, model_cfg=CFG, params=params).generate(
            prompt, samp).output_ids

    ref = run(None)
    got = run("fp8")
    assert got[0] == ref[0]
    # Trajectories may diverge after a near-tie; require substantial
    # agreement on this fixed seed.
    agree = sum(a == b for a, b in zip(ref, got)) / len(ref)
    assert agree >= 0.5, (ref, got)


def test_fp8_capacity_doubles():
    from agentic_traffic_testing_tpu.runtime.kv_cache import profile_num_blocks

    free = 1 << 30
    bf16 = profile_num_blocks(CFG, 16, free, 0.9, 2)
    fp8 = profile_num_blocks(CFG, 16, free, 0.9, 1)
    assert fp8 == 2 * bf16


def test_fp8_paged_kernel_matches_gather_oracle():
    """The dma/dma2/v1 kernels and the jnp gather path dequantize identical
    stored f8 bytes — outputs must agree to float tolerance (interpret mode
    on CPU; the same assertion the bf16 paged tests make)."""
    from agentic_traffic_testing_tpu.ops.attention_backend import (
        paged_decode_attention,
    )
    from agentic_traffic_testing_tpu.runtime import kv_cache as kvc

    cfg = CFG
    L, KH, NB, BS = cfg.num_layers, cfg.num_kv_heads, 8, 8
    hd = cfg.head_dim_
    hdp = kvc.phys_head_dim(hd)
    key = jax.random.key(3)
    pool_shape = (L, KH, NB, BS, hdp)
    k_pages = (jax.random.normal(key, pool_shape, jnp.float32)
               .astype(jnp.float8_e4m3fn))
    v_pages = (jax.random.normal(jax.random.key(4), pool_shape, jnp.float32)
               .astype(jnp.float8_e4m3fn))
    q = jax.random.normal(jax.random.key(5), (2, cfg.num_heads, hd),
                          jnp.float32)
    bt = jnp.asarray([[1, 2, 0, 0], [3, 4, 0, 0]], jnp.int32)
    ctx = jnp.asarray([11, 14], jnp.int32)

    ref = paged_decode_attention(q[:, None], k_pages, v_pages, bt, ctx - 1,
                                 mode="gather", layer=1)[:, 0]
    got = paged_decode_attention(q[:, None], k_pages, v_pages, bt, ctx - 1,
                                 mode="interpret", layer=1)[:, 0]
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=2e-5, rtol=1e-4)
    # The DMA kernels (dma2 = the TPU production default) in interpret mode
    # — covers the fp8 shape/dtype plumbing end to end. (Mosaic's real
    # 8-bit tiling behavior on hardware still needs a one-chip check; the
    # interpret path validates semantics, not tiling legality.)
    from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
        paged_attention_decode_dma,
        paged_attention_decode_dma2,
    )

    # Direct kernel API takes ctx_lens (tokens valid), not positions.
    for fn in (paged_attention_decode_dma, paged_attention_decode_dma2):
        out = fn(q, k_pages, v_pages, bt, ctx, layer=1, interpret=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5, rtol=1e-4)


def test_fp8_kv_gauges_report_doubled_tokens():
    """Server metrics reflect the doubled pool when the profile hands out
    2x blocks (here pinned explicitly: same tokens per block, more blocks)."""
    from agentic_traffic_testing_tpu.serving.config import ServerConfig
    from agentic_traffic_testing_tpu.serving.server import LLMServer

    cfg = ServerConfig(model="tiny", dtype="float32", max_num_seqs=2,
                       max_model_len=128, num_blocks=64,
                       kv_cache_dtype="fp8")
    srv = LLMServer(cfg)
    assert srv.engine.cache.k.dtype == jnp.float8_e4m3fn
    assert b"llm_kv_cache_total_tokens" in srv.metrics.render()


def test_fp8_composes_with_prefix_caching():
    """fp8 pages are content-addressed like bf16 ones (hashes are over
    token ids, not page bytes): a cache-hit prefill over f8 pages decodes
    the same greedy tokens as a cold one."""
    params = init_params(CFG, jax.random.key(5), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny", dtype="float32", kv_cache_dtype="fp8",
                        prefix_caching=True, num_blocks=64, max_model_len=128)
    eng = LLMEngine(ecfg, model_cfg=CFG, params=params)
    prompt = list(range(11, 43))
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    cold = eng.generate(prompt, samp).output_ids
    warm = eng.generate(prompt, samp).output_ids  # prefix-cache hit path
    assert cold == warm


def test_fp8_composes_with_speculation():
    """ngram speculation over f8 pages: verify-step drafts write f8 KV and
    greedy output matches the non-speculative fp8 engine exactly (same
    dequantized bytes, same argmax)."""
    params = init_params(CFG, jax.random.key(6), dtype=jnp.float32)
    prompt = [5, 6, 7, 8] * 6
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    def run(spec):
        ecfg = EngineConfig(model="tiny", dtype="float32",
                            kv_cache_dtype="fp8", num_blocks=64,
                            max_model_len=128,
                            speculation="ngram" if spec else None,
                            spec_tokens=2)
        return LLMEngine(ecfg, model_cfg=CFG, params=params).generate(
            prompt, samp).output_ids

    assert run(False) == run(True)
