"""Hybrid prefill+decode batching (HybridBatch + the fused ragged step).

The invariants under test:
  * hybrid_token_budget=0 (the default) is BIT-IDENTICAL to the serial
    prefill-priority schedule — zero hybrid steps, same tokens.
  * With the budget on, greedy and seeded-sampling outputs are
    token-identical to the serial engine (fusion is a scheduling strategy,
    never a numerics change), while fused steps actually happen.
  * The fused model step works against both ragged-attention backends
    (jnp grouped-gather oracle, and the Pallas ragged kernel in interpret
    mode).
  * Planner fallbacks: no decode partners -> solo chunk path; budget too
    small for any chunk rung -> no fusion; speculation x hybrid composes
    since round 14 (identity pinned in tests/test_speculative.py).
"""

import numpy as np
import pytest

# Heavyweight tier: CPU jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner
from agentic_traffic_testing_tpu.runtime.scheduler import HybridBatch

CFG = PRESETS["tiny"]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def make_engine(params, hybrid=0, chunk=32, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 256)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 128)
    kw.setdefault("max_num_seqs", 4)
    ecfg = EngineConfig(prefill_chunk_tokens=chunk,
                        hybrid_token_budget=hybrid, **kw)
    runner = ModelRunner(CFG, params, decode_steps=kw.get("decode_steps", 1))
    return LLMEngine(ecfg, model_cfg=CFG, runner=runner)


def greedy(n=8, **kw):
    return SamplingParams(max_tokens=n, temperature=0.0, **kw)


def run_all(engine, reqs):
    for _ in range(10_000):
        engine.step()
        if all(r.is_finished() for r in reqs):
            return
        if not engine.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


def mixed_workload(engine, sampling_fn):
    """Short prompts (decoding) + one long prompt (chunking) — the shape
    the hybrid planner fuses."""
    rng = np.random.default_rng(2)
    shorts = [rng.integers(0, CFG.vocab_size, n).tolist() for n in (6, 14)]
    long_p = rng.integers(0, CFG.vocab_size, 90).tolist()
    reqs = [engine.add_request(p, sampling_fn()) for p in shorts]
    reqs.append(engine.add_request(long_p, sampling_fn()))
    run_all(engine, reqs)
    return [r.generated_ids for r in reqs]


def test_budget_zero_schedules_no_hybrid_steps(params):
    eng = make_engine(params, hybrid=0)
    mixed_workload(eng, greedy)
    assert eng.scheduler.num_scheduled_hybrid == 0


def test_hybrid_greedy_matches_serial(params):
    want = mixed_workload(make_engine(params, hybrid=0), greedy)
    eng = make_engine(params, hybrid=64)
    got = mixed_workload(eng, greedy)
    assert eng.scheduler.num_scheduled_hybrid > 0, "fusion never engaged"
    assert got == want


def test_hybrid_seeded_sampling_matches_serial(params):
    sp = lambda: SamplingParams(max_tokens=6, temperature=0.8, top_k=20,
                                seed=9)
    want = mixed_workload(make_engine(params, hybrid=0), sp)
    eng = make_engine(params, hybrid=64)
    got = mixed_workload(eng, sp)
    assert eng.scheduler.num_scheduled_hybrid > 0
    assert got == want


def test_hybrid_with_ragged_kernel_matches_serial(params, monkeypatch):
    """Force the fused step's attention onto the Pallas ragged kernel
    (interpret mode on CPU) instead of the gather oracle: tokens must
    still match the serial engine — this is the in-engine parity pin for
    the kernel itself."""
    monkeypatch.setattr(ModelRunner, "hybrid_attn_mode", "ragged")
    want = mixed_workload(make_engine(params, hybrid=0), lambda: greedy(4))
    eng = make_engine(params, hybrid=64)
    got = mixed_workload(eng, lambda: greedy(4))
    assert eng.scheduler.num_scheduled_hybrid > 0
    assert got == want


def test_hybrid_solo_long_prompt_needs_no_partner(params):
    """With nothing decoding, the chunk path must run solo exactly as
    before (the hybrid planner falls back, it doesn't stall)."""
    rng = np.random.default_rng(4)
    long_p = rng.integers(0, CFG.vocab_size, 90).tolist()
    want = make_engine(params, hybrid=0).generate(long_p, greedy()).generated_ids
    eng = make_engine(params, hybrid=64)
    req = eng.generate(long_p, greedy())
    assert eng.scheduler.num_scheduled_hybrid == 0
    assert req.generated_ids == want


def test_hybrid_budget_too_small_falls_back(params):
    """A budget below decode-lanes + smallest chunk rung can never fuse:
    the planner must degrade to the serial schedule, not wedge."""
    eng = make_engine(params, hybrid=3)  # block_size=8 > 3 - padded_batch
    want = mixed_workload(make_engine(params, hybrid=0), greedy)
    got = mixed_workload(eng, greedy)
    assert eng.scheduler.num_scheduled_hybrid == 0
    assert got == want


def test_hybrid_chunk_splits_onto_budget_rung(params):
    """A tight budget forces the chunk onto a smaller ladder rung; the
    split remainder continues next step and output is unchanged."""
    want = mixed_workload(make_engine(params, hybrid=0), greedy)
    # budget 24: padded decode bucket 2 leaves room 22 -> rung 16 (< the
    # chunk size 32), so fused chunks split.
    eng = make_engine(params, hybrid=24)
    got = mixed_workload(eng, greedy)
    assert eng.scheduler.num_scheduled_hybrid > 0
    assert got == want


def test_hybrid_multistep_decode_composes(params):
    """decode_steps > 1: fused hybrid steps interleave with multi-step
    decode dispatches without token drift."""
    want = mixed_workload(make_engine(params, hybrid=0, decode_steps=4),
                          greedy)
    eng = make_engine(params, hybrid=64, decode_steps=4)
    got = mixed_workload(eng, greedy)
    assert eng.scheduler.num_scheduled_hybrid > 0
    assert got == want


def test_hybrid_token_budget_counts_padded_tokens(params):
    """Every emitted HybridBatch respects the budget on PADDED counts —
    the fused program's real shape, not the optimistic real-token count."""
    eng = make_engine(params, hybrid=24)
    sched = eng.scheduler
    orig = sched._plan_hybrid
    seen = []

    def spy():
        hb = orig()
        if hb is not None:
            seen.append((hb.decode.padded_batch, hb.chunk.padded_len))
        return hb

    sched._plan_hybrid = spy
    mixed_workload(eng, greedy)
    assert seen, "no hybrid plans emitted"
    for b, c in seen:
        assert b + c <= 24, (b, c)


def test_warmup_hybrid_buckets_compiles_reachable_shapes(params):
    from agentic_traffic_testing_tpu.runtime.scheduler import pow2_buckets

    eng = make_engine(params, hybrid=24)
    ladder = [c for c in eng.scheduler.cfg.chunk_ladder() if c <= 16]
    want = sum(1 for b in pow2_buckets(1, eng.cfg.max_num_seqs)
               for c in ladder if b + c <= 24)
    assert want > 0
    assert eng.warmup_hybrid_buckets(max_chunk=16) == want
    assert make_engine(params, hybrid=0).warmup_hybrid_buckets() == 0


def test_speculation_composes_with_hybrid():
    # Round 14: speculation keeps no device-resident history, so hybrid
    # steps advancing decode lanes need no spec state maintenance — the
    # combination BUILDS (identity pinned in tests/test_speculative.py).
    EngineConfig(model="tiny", speculation="ngram", hybrid_token_budget=64)


def test_bench_emits_hybrid_metric_on_cpu():
    """bench.py end-to-end (inner process, tiny shapes) on CPU: the script
    must still run and print ONE parseable JSON line, now carrying the
    hybrid on/off series — the CPU-degradation guard for the new metric."""
    import json
    import os
    import subprocess
    import sys as _sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(
        os.environ, JAX_PLATFORMS="cpu", BENCH_INNER="1",
        BENCH_MODEL="tiny", BENCH_BATCH="2", BENCH_SMALL_BATCH="0",
        BENCH_TOTAL_REQUESTS="2", BENCH_PROMPT_LEN="16",
        BENCH_DECODE_TOKENS="4", BENCH_REPS="1", BENCH_FANOUT="2",
        BENCH_FANOUT_PROMPT_LEN="32", BENCH_PREFILL_LEN="64",
        BENCH_HYBRID_BUDGET="24", BENCH_HYBRID_CHUNK="16",
        BENCH_HYBRID_LANES="3", BENCH_NO_RECORDED="1",
    )
    proc = subprocess.run(
        [_sys.executable, os.path.join(repo, "bench.py")],
        env=env, capture_output=True, text=True, timeout=900, cwd=repo)
    assert proc.returncode == 0, proc.stderr[-2000:]
    line = proc.stdout.strip().splitlines()[-1]
    out = json.loads(line)
    assert out["metric"] and out["value"] > 0
    assert out["hybrid_token_budget"] == 24, out
    assert out["hybrid_decode_toks_s"] > 0
    assert out["serial_decode_toks_s"] > 0
    assert out["hybrid_steps"] > 0, "fusion never engaged in the probe"
    assert out["hybrid_queue_wait_p50_s"] >= 0
    assert out["serial_queue_wait_p50_s"] >= 0


def test_hybrid_batch_token_budget_property():
    from agentic_traffic_testing_tpu.runtime.request import Request
    from agentic_traffic_testing_tpu.runtime.scheduler import (
        ChunkPrefill,
        DecodeBatch,
    )

    r = Request(request_id="x", prompt_ids=[1] * 40,
                sampling=SamplingParams(max_tokens=1))
    hb = HybridBatch(
        decode=DecodeBatch(requests=[], padded_batch=4),
        chunk=ChunkPrefill(request=r, chunk_start=0, chunk_len=30,
                           padded_len=32),
    )
    assert hb.token_budget == 36
