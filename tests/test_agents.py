"""Agents layer: scenario flows, AgentVerse workflow, SSE, parsing.

Strategy per SURVEY.md §4: the LLM backend is faked in-process with the real
/chat JSON contract (the analog of the reference's CPU fallback server), and
real Agent A + Agent B aiohttp apps run against it on ephemeral ports — the
whole L7/L8 call tree executes, with no model and no network egress.
"""

import asyncio
import json
import os

import pytest
from aiohttp import ClientSession, web

from agentic_traffic_testing_tpu.agents.agent_a.parsing import (
    extract_json,
    parse_evaluation,
    parse_experts,
    parse_subtasks,
)

# --------------------------------------------------------------------------
# Fake LLM backend: recognizes each stage's prompt shape and answers usefully
# --------------------------------------------------------------------------

EXPERTS_JSON = json.dumps([
    {"name": "Analyst", "expertise": "analysis", "responsibility": "analyze"},
    {"name": "Builder", "expertise": "building", "responsibility": "build"},
    {"name": "Reviewer", "expertise": "review", "responsibility": "review"},
])
EVAL_JSON = json.dumps({
    "completeness": 90, "correctness": 85, "clarity": 80,
    "overall_score": 86, "goal_achieved": True, "feedback": "solid work",
})


async def fake_llm_handler(request: web.Request) -> web.Response:
    body = await request.json()
    prompt = body.get("prompt", "")
    if "Propose" in prompt and "experts" in prompt:
        out = EXPERTS_JSON
    elif "weighted rubric" in prompt:
        out = EVAL_JSON
    elif "independent subtasks" in prompt:
        out = json.dumps(["subtask one", "subtask two", "subtask three"])
    elif "supervising a multi-step task" in prompt:
        out = "[DONE] the task is finished: 42"
    else:
        out = f"ok({len(prompt)} chars)"
    return web.json_response({
        "output": out,
        "meta": {
            "request_id": body.get("request_id", "r"),
            "latency_ms": 1.0, "queue_wait_s": 0.0,
            "prompt_tokens": max(1, len(prompt) // 4),
            "completion_tokens": max(1, len(out) // 4),
            "total_tokens": 2,
            "otel": {"trace_id": "t", "span_id": "s"},
        },
    })


class Stack:
    """Fake LLM + agent B + agent A running on ephemeral localhost ports."""

    def __init__(self, tmpdir: str) -> None:
        self.tmpdir = tmpdir
        self.runners = []
        self.agent_a_url = ""
        self.agent_b_url = ""
        self.llm_url = ""

    async def _start(self, app: web.Application) -> str:
        runner = web.AppRunner(app)
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        self.runners.append(runner)
        port = runner.addresses[0][1]
        return f"http://127.0.0.1:{port}"

    async def __aenter__(self) -> "Stack":
        os.environ["TELEMETRY_LOG_DIR"] = self.tmpdir
        llm_app = web.Application()
        llm_app.router.add_post("/chat", fake_llm_handler)
        self.llm_url = await self._start(llm_app)
        os.environ["LLM_SERVER_URL"] = f"{self.llm_url}/chat"

        from agentic_traffic_testing_tpu.agents.agent_b.server import AgentBServer
        self.agent_b_url = await self._start(AgentBServer("agent_b_test").build_app())
        os.environ["AGENT_B_URLS"] = self.agent_b_url

        from agentic_traffic_testing_tpu.agents.agent_a.server import AgentAServer
        self.agent_a_url = await self._start(AgentAServer().build_app())
        return self

    async def __aexit__(self, *exc) -> None:
        for runner in self.runners:
            await runner.cleanup()


@pytest.fixture()
def stack_env(tmp_path, monkeypatch):
    monkeypatch.setenv("TELEMETRY_LOG_DIR", str(tmp_path))
    monkeypatch.setenv("AGENTVERSE_MAX_ITERATIONS", "2")
    monkeypatch.setenv("AGENTVERSE_VERTICAL_ITERATIONS", "1")
    return str(tmp_path)


# --------------------------------------------------------------------------
# HTTP flow tests
# --------------------------------------------------------------------------


def test_agent_b_subtask_contract(stack_env):
    async def run():
        async with Stack(stack_env) as s, ClientSession() as http:
            async with http.post(f"{s.agent_b_url}/subtask",
                                 json={"subtask": "add 2+2", "role": "math"},
                                 headers={"X-Task-ID": "t1"}) as resp:
                assert resp.status == 200
                data = await resp.json()
        assert data["result"].startswith("ok(")
        assert "llm_prompt" in data and "llm_meta" in data and "otel" in data
        assert data["agent_id"] == "agent_b_test"
    asyncio.run(run())


def test_task_scenarios(stack_env):
    async def run():
        results = {}
        async with Stack(stack_env) as s, ClientSession() as http:
            for scenario in ("agentic_simple", "agentic_multi_hop",
                             "agentic_parallel"):
                async with http.post(f"{s.agent_a_url}/task",
                                     json={"task": "compute the answer",
                                           "scenario": scenario,
                                           "agent_count": 3}) as resp:
                    assert resp.status == 200, scenario
                    results[scenario] = await resp.json()
        simple = results["agentic_simple"]
        assert simple["result"].startswith("ok(")
        assert simple["aggregates"]["total_tokens"] > 0
        assert simple["aggregates"]["cost_estimate_usd"] >= 0

        hop = results["agentic_multi_hop"]
        assert "42" in hop["result"]
        assert hop["detail"]["turns"] == 1  # [DONE] on first progress check

        par = results["agentic_parallel"]
        assert par["detail"]["num_workers"] == 3
        assert len(par["detail"]["subtasks"]) == 3
        types = [st["type"] for st in par["detail"]["steps"]]
        assert types.count("agent_b") == 3
        assert "llm_planning" in types and "llm_synthesis" in types
    asyncio.run(run())


def test_task_rejects_bad_input(stack_env):
    async def run():
        async with Stack(stack_env) as s, ClientSession() as http:
            async with http.post(f"{s.agent_a_url}/task",
                                 json={"scenario": "agentic_simple"}) as resp:
                assert resp.status == 400
            async with http.post(f"{s.agent_a_url}/task",
                                 json={"task": "x", "scenario": "nope"}) as resp:
                assert resp.status == 400
    asyncio.run(run())


def test_agentverse_workflow_and_persistence(stack_env):
    async def run():
        async with Stack(stack_env) as s, ClientSession() as http:
            async with http.post(f"{s.agent_a_url}/agentverse",
                                 json={"task": "design a plan",
                                       "structure": "vertical"}) as resp:
                assert resp.status == 200
                data = await resp.json()
            assert data["final_output"]
            assert data["iteration_count"] == 1  # eval scores 86 >= 70
            assert data["evaluation"]["goal_achieved"] is True
            assert len(data["experts"]) == 3
            assert data["aggregates"]["num_llm_calls"] == len(data["llm_calls"])
            assert data["aggregates"]["cost_estimate_usd"] > 0

            # Persistence + retrieval endpoint
            async with http.get(
                    f"{s.agent_a_url}/agentverse/{data['task_id']}") as resp:
                assert resp.status == 200
                persisted = await resp.json()
            assert persisted["task_id"] == data["task_id"]

            # llm_calls.jsonl written with the Phase-0.1 schema fields
            path = os.path.join(stack_env, "llm_calls.jsonl")
            rows = [json.loads(l) for l in open(path)]
            assert rows and {"call_id", "task_id", "agent_id", "call_type",
                             "latency_ms"} <= set(rows[0])
    asyncio.run(run())


def test_agentverse_sse_event_stream(stack_env):
    async def run():
        async with Stack(stack_env) as s, ClientSession() as http:
            async with http.post(f"{s.agent_a_url}/agentverse",
                                 json={"task": "stream me", "stream": True,
                                       "structure": "horizontal"}) as resp:
                assert resp.status == 200
                assert resp.headers["Content-Type"].startswith("text/event-stream")
                raw = (await resp.read()).decode()
        events = [json.loads(line[len("data: "):])
                  for line in raw.splitlines() if line.startswith("data: ")]
        names = [e["event"] for e in events]
        for expected in ("stage_start", "stage_complete", "discussion_round",
                         "complete", "result"):
            assert expected in names, f"missing {expected} in {names}"
        assert names.index("complete") < names.index("result")
        final = events[names.index("result")]
        assert final["final_output"]
    asyncio.run(run())


def test_worker_failure_keeps_fanout_alive(stack_env):
    """One dead worker URL must degrade, not kill, agentic_parallel."""
    async def run():
        async with Stack(stack_env) as s, ClientSession() as http:
            os.environ["AGENT_B_URLS"] = (
                f"{s.agent_b_url},http://127.0.0.1:9")  # port 9: refused
            async with http.post(f"{s.agent_a_url}/task",
                                 json={"task": "resilience", "max_tokens": 64,
                                       "scenario": "agentic_parallel",
                                       "agent_count": 2}) as resp:
                assert resp.status == 200
                data = await resp.json()
        steps = [st for st in data["detail"]["steps"] if st["type"] == "agent_b"]
        errors = [st for st in steps if st.get("error")]
        assert len(steps) == 2 and len(errors) == 1
        assert data["result"]  # synthesis still ran
    asyncio.run(run())


# --------------------------------------------------------------------------
# Parsing unit tests
# --------------------------------------------------------------------------


def test_extract_json_variants():
    assert extract_json('{"a": 1}') == {"a": 1}
    assert extract_json('```json\n{"a": 1}\n```') == {"a": 1}
    assert extract_json('noise before {"a": 1, } noise after') == {"a": 1}
    assert extract_json('Here: [1, 2, 3] done', expect=list) == [1, 2, 3]
    assert extract_json("no json here") is None
    assert extract_json('nested {"a": {"b": [1]}} x')["a"]["b"] == [1]


def test_parse_subtasks_fallbacks():
    assert parse_subtasks('["a", "b"]', 2) == ["a", "b"]
    assert parse_subtasks("1. first\n2. second\n3. third", 2) == ["first", "second"]
    assert parse_subtasks("- only one", 3) == ["only one"] * 3
    assert parse_subtasks("free text", 1) == ["free text"]


def test_parse_experts_fallbacks():
    ex = parse_experts(EXPERTS_JSON, 3)
    assert [e["name"] for e in ex] == ["Analyst", "Builder", "Reviewer"]
    ex = parse_experts("1. Chemist: molecules\n2. Poet: verse", 2)
    assert ex[0]["name"] == "Chemist" and ex[1]["expertise"] == "verse"
    ex = parse_experts("garbage", 2)
    assert len(ex) == 2 and ex[0]["name"] == "Expert 1"


def test_parse_evaluation_robustness():
    good = parse_evaluation(EVAL_JSON)
    assert good["overall_score"] == 86 and good["goal_achieved"] is True
    broken = parse_evaluation("the work is fine I guess")
    assert broken["overall_score"] == 0.0 and broken["goal_achieved"] is False
    assert "fine" in broken["feedback"]
    partial = parse_evaluation('{"completeness": 100, "correctness": 50, "clarity": 100}')
    assert partial["overall_score"] == pytest.approx(0.4 * 100 + 0.4 * 50 + 0.2 * 100)


# ---------------------------------------------------------------- budgeting


def _make_orchestrator(monkeypatch, **env):
    from agentic_traffic_testing_tpu.agents.agent_a.orchestrator import (
        AgentVerseOrchestrator,
    )

    monkeypatch.delenv("LLM_TOKENIZER_PATH", raising=False)
    for k, v in env.items():
        monkeypatch.setenv(k, str(v))
    return AgentVerseOrchestrator(client=None)


def test_eval_budget_token_aware_trims_tail(monkeypatch):
    """Primary path (ref orchestrator.py:627-821): token budget =
    max_model_len − eval_max_tokens − margin − base prompt; oldest content
    trimmed, newest kept."""
    orch = _make_orchestrator(
        monkeypatch, LLM_TOKENIZER_PATH="byte", LLM_MAX_MODEL_LEN=1500,
        LLM_EVAL_MAX_TOKENS=100, LLM_PROMPT_SAFETY_MARGIN_TOKENS=16)
    results = "x" * 5000 + "THE-RECENT-TAIL"
    out = orch._budget_results_text(results, task="t", plan="p")
    assert out.startswith("[...truncated...]")
    assert out.endswith("THE-RECENT-TAIL")
    # Byte tokenizer: 1 token per ASCII char -> the whole prompt must fit
    # the model-len budget with completion + margin reserved.
    from agentic_traffic_testing_tpu.agents.agent_a import prompts

    prompt = prompts.EVALUATION_PROMPT.format(task="t", plan="p", results=out)
    assert len(prompt.encode()) <= 1500 - 100 - 16


def test_eval_budget_token_aware_passthrough(monkeypatch):
    orch = _make_orchestrator(
        monkeypatch, LLM_TOKENIZER_PATH="byte", LLM_MAX_MODEL_LEN=8192,
        LLM_EVAL_MAX_TOKENS=256)
    short = "short results"
    assert orch._budget_results_text(short, task="t", plan="p") == short


def test_eval_budget_char_fallback_without_tokenizer(monkeypatch):
    """No tokenizer resolves -> the pre-token char heuristic guards: results
    are trimmed so base prompt + results stay near EVAL_MAX_PROMPT_CHARS."""
    orch = _make_orchestrator(monkeypatch, EVAL_MAX_PROMPT_CHARS=1500)
    out = orch._budget_results_text("y" * 5000, task="t", plan="p")
    assert out.startswith("[...truncated...]")
    from agentic_traffic_testing_tpu.agents.agent_a import prompts

    prompt = prompts.EVALUATION_PROMPT.format(task="t", plan="p", results=out)
    assert len(prompt) <= 1500 + len("[...truncated...]\n")


def test_eval_budget_zero_budget_drops_results(monkeypatch):
    """Base prompt alone exceeding the limit yields empty results, not a
    negative slice."""
    orch = _make_orchestrator(
        monkeypatch, LLM_TOKENIZER_PATH="byte", LLM_MAX_MODEL_LEN=64,
        LLM_EVAL_MAX_TOKENS=32)
    assert orch._budget_results_text("z" * 100, task="t", plan="p") == ""
