"""Golden-logit tests: our functional JAX decoder vs transformers' reference.

Tiny model configs are instantiated locally (no hub access), weights are
converted through `models.weights.params_from_hf_state_dict`, and fp32 logits
must agree to tight tolerance. Covers: GQA, llama-3.1 RoPE scaling, tied
embeddings, and the Qwen2 qkv-bias variant — the model families the reference
testbed configures (reference: infra/.env.example:117-123).
"""

import numpy as np
import pytest

import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import ModelConfig, RopeScaling
from agentic_traffic_testing_tpu.models.llama import forward_full
from agentic_traffic_testing_tpu.models.weights import params_from_hf_state_dict


def _sd_to_numpy(model):
    return {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}


def _logits_close(ours, theirs, atol=2e-4):
    ours = np.asarray(ours, np.float32)
    theirs = np.asarray(theirs, np.float32)
    np.testing.assert_allclose(ours, theirs, atol=atol, rtol=2e-3)


@pytest.fixture(scope="module")
def torch_mod():
    import torch

    torch.manual_seed(0)
    return torch


def test_llama_gqa_rope_scaled_logits(torch_mod):
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=3,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_theta=500000.0,
        rope_scaling={
            "rope_type": "llama3",
            "factor": 8.0,
            "low_freq_factor": 1.0,
            "high_freq_factor": 4.0,
            "original_max_position_embeddings": 32,
        },
        max_position_embeddings=256,
        rms_norm_eps=1e-5,
        tie_word_embeddings=False,
        attention_bias=False,
    )
    model = LlamaForCausalLM(hf_cfg).eval()
    cfg = ModelConfig.from_hf_config(hf_cfg.to_dict(), name="tiny-llama")
    assert cfg.rope_scaling == RopeScaling(8.0, 1.0, 4.0, 32)
    params = params_from_hf_state_dict(cfg, _sd_to_numpy(model))

    tokens = np.array([[1, 5, 9, 100, 42, 17, 3, 77], [2, 4, 6, 8, 10, 12, 14, 16]], np.int32)
    import torch

    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours = forward_full(params, cfg, jnp.asarray(tokens))
    _logits_close(ours, theirs)


def test_llama_tied_embeddings_logits(torch_mod):
    from transformers import LlamaConfig, LlamaForCausalLM

    hf_cfg = LlamaConfig(
        vocab_size=96,
        hidden_size=48,
        intermediate_size=96,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        rope_theta=10000.0,
        max_position_embeddings=128,
        tie_word_embeddings=True,
    )
    model = LlamaForCausalLM(hf_cfg).eval()
    cfg = ModelConfig.from_hf_config(hf_cfg.to_dict(), name="tiny-tied")
    assert cfg.tie_word_embeddings
    params = params_from_hf_state_dict(cfg, _sd_to_numpy(model))

    tokens = np.arange(12, dtype=np.int32).reshape(1, 12) % 96
    import torch

    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours = forward_full(params, cfg, jnp.asarray(tokens))
    _logits_close(ours, theirs)


def test_qwen2_bias_logits(torch_mod):
    from transformers import Qwen2Config, Qwen2ForCausalLM

    hf_cfg = Qwen2Config(
        vocab_size=120,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        rope_theta=1000000.0,
        max_position_embeddings=128,
        tie_word_embeddings=False,
    )
    model = Qwen2ForCausalLM(hf_cfg).eval()
    cfg = ModelConfig.from_hf_config(hf_cfg.to_dict(), name="tiny-qwen")
    assert cfg.qkv_bias
    params = params_from_hf_state_dict(cfg, _sd_to_numpy(model))

    tokens = np.array([[3, 1, 4, 1, 5, 9, 2, 6]], np.int32)
    import torch

    with torch.no_grad():
        theirs = model(torch.tensor(tokens, dtype=torch.long)).logits.numpy()
    ours = forward_full(params, cfg, jnp.asarray(tokens))
    _logits_close(ours, theirs)
