"""Chaos suite for the round-9 fault-tolerant serving plane.

Covers the ISSUE-8 acceptance gates on CPU:
  * seeded, deterministic injection per fault point;
  * zero hung requests under faults (every request terminates);
  * streams unaffected by a failing batch are token-identical to a
    fault-free run;
  * all-knobs-off leaves the hot path untouched (machinery pinned
    never-invoked);
  * quarantine → re-admit round trip + retry-once failover;
  * shed / deadline / fallback metrics account for every injected fault.
"""

from __future__ import annotations

import asyncio
import time

import numpy as np
import pytest

from agentic_traffic_testing_tpu.models.config import resolve_config
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.faultinject import (
    FaultInjector,
    InjectedFault,
    parse_fault_spec,
)
from agentic_traffic_testing_tpu.runtime.kv_offload import HostKVStore
from agentic_traffic_testing_tpu.runtime.request import (
    FinishReason,
    SamplingParams,
)
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner
from agentic_traffic_testing_tpu.runtime.scheduler import QueueFullError
from agentic_traffic_testing_tpu.serving.replica_pool import (
    DEGRADED,
    HEALTHY,
    QUARANTINED,
    EnginePool,
    ReplicaHealth,
)

MODEL = "tiny"
DTYPE = "float32"


@pytest.fixture(scope="module")
def runner():
    """One shared ModelRunner: every engine below reuses its compiled
    programs (the ab-script idiom), keeping the suite inside the tier-1
    wall budget."""
    import jax
    import jax.numpy as jnp

    cfg = resolve_config(MODEL)
    params = init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, ModelRunner(cfg, params, decode_steps=1)


def make_engine(runner, **kw):
    model_cfg, r = runner
    defaults = dict(model=MODEL, dtype=DTYPE, max_num_seqs=4,
                    max_model_len=256, block_size=16, num_blocks=128)
    defaults.update(kw)
    return LLMEngine(EngineConfig(**defaults), model_cfg=model_cfg, runner=r)


def churn_prompts(n, length=16):
    wl = np.random.default_rng(97)
    return [wl.integers(10, 200, length).tolist() for _ in range(n)]


def churn_sampling(i, max_tokens=6):
    if i % 2 == 0:
        return SamplingParams(temperature=0.0, max_tokens=max_tokens - (i % 2),
                              ignore_eos=True)
    return SamplingParams(temperature=0.8, top_k=20, seed=5 + i,
                          max_tokens=max_tokens - 2, ignore_eos=True)


def drive(eng, reqs, cap=2000):
    steps = 0
    while eng.has_work() and steps < cap:
        eng.step()
        steps += 1
    assert steps < cap, "engine failed to drain (hung requests)"
    return reqs


# ---------------------------------------------------------- fault injector


def test_fault_spec_grammar():
    spec = parse_fault_spec(
        "dispatch_error:p=0.05;restore_error;slow_replica:idx=1,ms=200")
    assert spec["dispatch_error"] == {"p": 0.05}
    assert spec["restore_error"] == {"p": 1.0}
    assert spec["slow_replica"] == {"idx": 1, "ms": 200}
    for bad in ("bogus", "dispatch_error:p=2", "slow_replica:idx=1",
                "dispatch_error:p", "restore_error:p=x"):
        with pytest.raises(ValueError):
            parse_fault_spec(bad)
    assert FaultInjector.from_spec("", 0) is None
    assert FaultInjector.from_spec(None, 0) is None


def test_fault_injection_deterministic_per_point():
    mk = lambda: FaultInjector.from_spec(
        "dispatch_error:p=0.3;restore_error:p=0.3", seed=11)
    a, b = mk(), mk()
    seq_a = [(a.fire("dispatch_error"), a.fire("restore_error"))
             for _ in range(50)]
    seq_b = [(b.fire("dispatch_error"), b.fire("restore_error"))
             for _ in range(50)]
    assert seq_a == seq_b  # same seed -> identical per-point streams
    assert a.fired == b.fired and a.fired["dispatch_error"] > 0
    # Unconfigured points never fire and never perturb configured streams.
    c = FaultInjector.from_spec("dispatch_error:p=0.3", seed=11)
    interleaved = []
    for _ in range(50):
        assert c.fire("restore_error") is False
        interleaved.append(c.fire("dispatch_error"))
    assert interleaved == [x[0] for x in seq_a]
    with pytest.raises(InjectedFault):
        FaultInjector.from_spec("dispatch_error", 0).maybe_raise(
            "dispatch_error")


# ------------------------------------------------------- engine isolation


def test_defaults_touch_no_robustness_machinery(runner, monkeypatch):
    """All-knobs-off pin: a default engine constructs NO fault injector,
    tracks NO deadlines, bounds NO queue, and never enters the failure
    handlers — the hot path is the pre-round-9 one."""
    def boom(*a, **k):
        raise AssertionError("robustness machinery touched at defaults")

    monkeypatch.setattr(LLMEngine, "_fail_dispatch", boom)
    monkeypatch.setattr(LLMEngine, "_restore_fallback", boom)
    monkeypatch.setattr(FaultInjector, "__init__", boom)
    eng = make_engine(runner)
    assert eng._faults is None and not eng._deadline_ids
    assert eng.scheduler.cfg.max_queue == 0
    req = eng.generate(churn_prompts(1)[0], churn_sampling(0))
    assert req.finish_reason is FinishReason.LENGTH
    assert (eng.num_dispatch_failures, eng.num_deadline_expired,
            eng.num_restore_fallbacks, eng.num_shed) == (0, 0, 0, 0)


def test_dispatch_fault_fails_only_its_batch(runner):
    """Seeded dispatch faults: deterministic failure pattern, every
    request terminates, and survivors are token-identical to a fault-free
    run of the same workload."""
    prompts = churn_prompts(8)

    def run(spec):
        eng = make_engine(runner, fault_spec=spec, fault_seed=29)
        reqs = [eng.add_request(p, churn_sampling(i))
                for i, p in enumerate(prompts)]
        drive(eng, reqs)
        return eng, reqs

    _, clean = run("")
    assert all(r.finish_reason in (FinishReason.STOP, FinishReason.LENGTH)
               for r in clean)
    eng_a, chaos_a = run("dispatch_error:p=0.05")
    eng_b, chaos_b = run("dispatch_error:p=0.05")

    # Deterministic: the same requests fail on both chaos runs.
    pattern = [r.finish_reason for r in chaos_a]
    assert pattern == [r.finish_reason for r in chaos_b]
    assert eng_a.num_dispatch_failures == eng_b.num_dispatch_failures > 0
    errored = [r for r in chaos_a if r.finish_reason is FinishReason.ERROR]
    survived = [r for r in chaos_a
                if r.finish_reason in (FinishReason.STOP, FinishReason.LENGTH)]
    assert errored and survived, "need both failures and survivors"
    for r in errored:
        assert r.is_finished() and "dispatch failed" in (r.error or "")
    # Fault isolation: survivors match the clean streams exactly.
    for r, c in zip(chaos_a, clean):
        if r in survived:
            assert r.output_ids == c.output_ids


def test_dispatch_fault_events_reach_streams(runner):
    """The failing batch's requests surface FINISHED error events through
    the normal flush (the async layer forwards these as terminal stream
    events — no silent truncation)."""
    eng = make_engine(runner, fault_spec="dispatch_error:p=1")
    req = eng.add_request(churn_prompts(1)[0], churn_sampling(0))
    events = eng.step()
    assert [e.request.request_id for e in events if e.finished] == \
        [req.request_id]
    assert req.finish_reason is FinishReason.ERROR
    assert not eng.has_work()  # state reconciled: nothing left to serve


# ------------------------------------------------------ deadlines + queue


def test_deadline_expires_queued_and_running(runner):
    eng = make_engine(runner, max_num_seqs=1)
    # Two requests: one runs, one waits; both carry a microscopic deadline.
    sp = lambda: SamplingParams(temperature=0.0, max_tokens=64,
                                ignore_eos=True, deadline_ms=0.1)
    reqs = [eng.add_request(p, sp()) for p in churn_prompts(2)]
    assert len(eng._deadline_ids) == 2
    time.sleep(0.005)
    drive(eng, reqs)
    assert [r.finish_reason for r in reqs] == [FinishReason.DEADLINE] * 2
    assert eng.num_deadline_expired == 2
    assert all("deadline exceeded" in r.error for r in reqs)
    assert not eng._deadline_ids and not eng.has_work()


def test_deadline_default_knob_applies(runner):
    eng = make_engine(runner, deadline_ms=0.1)
    req = eng.add_request(churn_prompts(1)[0],
                          SamplingParams(max_tokens=64, ignore_eos=True))
    time.sleep(0.005)
    drive(eng, [req])
    assert req.finish_reason is FinishReason.DEADLINE
    # Per-request override beats the engine default.
    eng2 = make_engine(runner, deadline_ms=0.1)
    req2 = eng2.add_request(
        churn_prompts(1)[0],
        SamplingParams(temperature=0.0, max_tokens=4, ignore_eos=True,
                       deadline_ms=60_000.0))
    drive(eng2, [req2])
    assert req2.finish_reason is FinishReason.LENGTH


def test_bounded_queue_sheds(runner):
    eng = make_engine(runner, max_queue=2)
    prompts = churn_prompts(4)
    for p in prompts[:2]:
        eng.add_request(p, churn_sampling(0))
    with pytest.raises(QueueFullError):
        eng.add_request(prompts[2], churn_sampling(0))
    assert eng.num_shed == 1
    # Admitted work is never dropped: draining frees the queue again.
    drive(eng, [])
    eng.add_request(prompts[3], churn_sampling(0))
    drive(eng, [])


# -------------------------------------------------- host-restore fallback


def _evict_and_rearrive(runner, fault_spec):
    """offload_ab's recipe: compute a scenario prefix, evict it to the
    host tier via capacity pressure, re-request it."""
    model_cfg, _ = runner
    prefix_len, bs = 96, 16
    eng = make_engine(
        runner, max_num_seqs=2, max_model_len=prefix_len + 96,
        num_blocks=(-(-(prefix_len + 32) // bs) + 3) + 1,
        prefix_caching=True, host_cache_gb=0.05, fault_spec=fault_spec)
    wl = np.random.default_rng(11)
    scenario = wl.integers(10, 200, prefix_len).tolist()
    pressures = [wl.integers(10, 200, prefix_len).tolist() for _ in range(3)]
    sp = lambda: SamplingParams(temperature=0.0, max_tokens=6,
                                ignore_eos=True)
    eng.generate(scenario, sp())
    for p in pressures:
        eng.generate(p, sp())
    re_req = eng.generate(scenario, sp())
    return eng, re_req


def test_restore_error_degrades_to_recompute(runner):
    eng_ok, clean = _evict_and_rearrive(runner, "")
    assert eng_ok.num_restore_fallbacks == 0
    assert eng_ok.host_restore_bytes > 0, "recipe must actually restore"
    eng, re_req = _evict_and_rearrive(runner, "restore_error:p=1")
    assert eng.num_restore_fallbacks >= 1
    assert re_req.finish_reason in (FinishReason.STOP, FinishReason.LENGTH)
    assert re_req.generated_ids == clean.generated_ids
    # The offending entries were invalidated: no restore was applied.
    assert eng.host_restore_bytes == 0


def test_corrupt_host_block_degrades_to_miss():
    store = HostKVStore(1 << 20)
    k = np.ones((2, 1, 16, 4), np.float32)
    assert store.put(1, (1, 2), k, k)
    assert store.get(1, (1, 2)) is not None
    # Corrupt the entry in place (simulates host-RAM rot / writer bug).
    store._entries[1].k = np.ones((2, 1, 8, 4), np.float32)
    assert store.get(1, (1, 2)) is None          # miss, not an exception
    assert store.corrupt_dropped == 1 and len(store) == 0
    # Geometry attestation: a later put of a different shape is refused.
    assert store.put(2, (3, 4), k, k)
    assert not store.put(3, (5, 6), k[:, :, :8], k[:, :, :8])
    assert store.invalidate(2) and not store.invalidate(2)
    stats = store.stats()
    # Explicit invalidations (restore fallback) are NOT corruption.
    assert stats["host_cache_corrupt_dropped"] == 2
    assert stats["host_cache_invalidated_blocks"] == 1


# ------------------------------------------------- replica health + pool


def test_replica_health_state_machine():
    h = ReplicaHealth(error_threshold=2, watchdog_s=0.05, cooldown_s=0.02)
    assert h.state == HEALTHY and h.eligible()
    h.record_error()
    assert h.state == DEGRADED and h.eligible()
    h.record_ok()
    assert h.state == HEALTHY
    h.record_error()
    h.record_error()
    assert h.state == QUARANTINED and not h.eligible()
    until_1 = h.quarantined_until
    time.sleep(0.03)
    assert h.eligible()          # cooldown lapsed: lazily eligible again
    assert h.probe()             # background probe: -> probation
    assert h.state == DEGRADED
    h.record_error()             # one probation error -> re-quarantined
    assert h.state == QUARANTINED
    assert h.quarantined_until - time.monotonic() > until_1 - time.monotonic()
    time.sleep(0.05)
    assert h.probe()
    h.record_ok()                # clean probation step -> healthy
    assert h.state == HEALTHY and h.consecutive_errors == 0


def test_lazy_readmission_drives_probation():
    """eligible() re-admits a quarantined replica once its cooldown
    lapses, possibly before any probe() tick (or with no probe loop at
    all). Step outcomes on that lazily re-admitted work must drive the
    machine exactly like post-probe probation: an error re-quarantines
    with doubled backoff, a clean step heals — neither dead-ends in
    QUARANTINED."""
    h = ReplicaHealth(error_threshold=2, cooldown_s=0.02)
    h.record_error()
    h.record_error()
    assert h.state == QUARANTINED and h.num_quarantines == 1
    time.sleep(0.03)
    assert h.eligible()          # lazy re-admission, NO probe() call
    h.record_error()             # probation error -> re-quarantined
    assert h.state == QUARANTINED and h.num_quarantines == 2
    time.sleep(0.05)
    assert h.eligible()
    h.record_ok()                # clean lazily-probed step -> healthy
    assert h.state == HEALTHY and h.consecutive_errors == 0


def test_depth_at_enqueue_stamped_per_replica(runner):
    """The scheduler stamps each request with the waiting-queue depth it
    actually joined behind (its OWN replica's, not a pool minimum) — the
    basis the serving layer's per-slot wait EWMA divides by."""
    eng = make_engine(runner, max_num_seqs=1)
    prompts = churn_prompts(3)
    reqs = [eng.add_request(p, SamplingParams(max_tokens=2, ignore_eos=True))
            for p in prompts]
    assert [r.depth_at_enqueue for r in reqs] == [0, 1, 2]
    while eng.has_work():
        eng.step()


def test_replica_watchdog_quarantines_stuck_step():
    h = ReplicaHealth(error_threshold=3, watchdog_s=0.02, cooldown_s=10.0)
    h.step_started()
    assert not h.check_stuck()   # not past the timeout yet
    time.sleep(0.03)
    assert h.check_stuck() and h.state == QUARANTINED
    # The wedge resolving (step completes cleanly) lifts the quarantine.
    h.step_done()
    h.record_ok()
    assert h.state == HEALTHY


def test_replica_health_transitions_serialize():
    """Round-10 race fix (concurrency statics): every ReplicaHealth
    transition holds _mu, so an engine-thread step outcome cannot
    interleave with the routing-path watchdog or the probe — the
    double-backoff / HEALTHY-overwrites-fresh-QUARANTINE shapes the
    unlocked read-modify-writes allowed."""
    import threading

    h = ReplicaHealth(error_threshold=1, cooldown_s=60.0)
    started = threading.Event()
    done = threading.Event()

    def engine_side():
        started.set()
        h.record_error()             # must wait for _mu
        done.set()

    with h._mu:
        t = threading.Thread(target=engine_side, name="engine-loop-t")
        t.start()
        assert started.wait(1)
        assert not done.wait(0.05)   # transition blocked on the held lock
    t.join(1)
    assert done.is_set()
    assert h.state == QUARANTINED and h.num_quarantines == 1


def test_replica_health_concurrent_errors_quarantine_once():
    """N threads reporting errors at once produce exactly ONE quarantine
    (threshold=1): before the lock, two racers could both pass the
    `state is QUARANTINED` check and both _quarantine, doubling the
    backoff exponent per extra thread."""
    import threading

    h = ReplicaHealth(error_threshold=1, cooldown_s=60.0)
    barrier = threading.Barrier(8, timeout=5)

    def hammer():
        barrier.wait()
        h.record_error()

    ts = [threading.Thread(target=hammer) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(5)
    assert h.state == QUARANTINED
    assert h.num_quarantines == 1


def test_pool_quarantine_failover_and_readmit(runner):
    """2-replica pool, replica 1 fault-injected to fail every dispatch:
    un-started requests retry once onto replica 0 (no hung streams),
    replica 1 quarantines, its load is absorbed, and after the fault
    clears the probe re-admits it and it serves again."""
    model_cfg, r = runner

    def factory(i):
        return LLMEngine(EngineConfig(
            model=MODEL, dtype=DTYPE, max_num_seqs=4, max_model_len=256,
            block_size=16, num_blocks=128,
            fault_spec="dispatch_error:p=1" if i == 1 else "",
            fault_seed=i), model_cfg=model_cfg, runner=r)

    pool = EnginePool.build(
        factory, 2, policy="round_robin",
        health_params=dict(error_threshold=1, cooldown_s=0.05,
                           watchdog_s=0.0))
    pool.start()
    try:
        async def go():
            prompts = churn_prompts(4)
            outs = []
            for i, p in enumerate(prompts):
                toks = []
                async for ev in pool.generate(p, churn_sampling(i),
                                              request_id=f"r{i}"):
                    toks.extend(ev.new_token_ids)
                    if ev.finished:
                        assert ev.request.finish_reason in (
                            FinishReason.STOP, FinishReason.LENGTH), \
                            ev.request.error
                outs.append(toks)
            return outs

        outs = asyncio.run(go())
        assert all(outs), "every stream must deliver tokens"
        assert pool.request_retries >= 1
        assert pool.health[1].state == QUARANTINED
        assert pool.health[0].state == HEALTHY
        # Quarantined replica is skipped while its cooldown holds.
        pool.health[1].quarantined_until = time.monotonic() + 60
        assert pool.eligible_replicas() == [0]

        # Fault clears (the "repaired chip"); probe re-admits after
        # cooldown and the replica serves again.
        pool.engines[1]._faults = None
        pool.health[1].quarantined_until = time.monotonic()
        assert pool.health_probe() == 1
        assert pool.health[1].state == DEGRADED

        async def direct():
            toks = []
            async for ev in pool._async[1].generate(
                    churn_prompts(1)[0], churn_sampling(0), "re"):
                toks.extend(ev.new_token_ids)
                if ev.finished:
                    return toks, ev.request.finish_reason

        toks, reason = asyncio.run(direct())
        assert toks and reason in (FinishReason.STOP, FinishReason.LENGTH)
        assert pool.health[1].state == HEALTHY  # clean probation step
    finally:
        pool.shutdown()


# --------------------------------------------------------- HTTP contract


@pytest.fixture(scope="module")
def server():
    from agentic_traffic_testing_tpu.serving.config import ServerConfig
    from agentic_traffic_testing_tpu.serving.server import LLMServer

    cfg = ServerConfig(model="tiny", dtype="float32", max_num_seqs=4,
                       max_model_len=256, num_blocks=128, max_tokens=8,
                       temperature=0.0, warmup=False)
    srv = LLMServer(cfg)
    srv.async_engine.start()
    yield srv
    srv.async_engine.shutdown()


def _http(server, coro_fn):
    from aiohttp.test_utils import TestClient, TestServer

    async def wrapper():
        app = server.make_app(manage_engine=False)
        async with TestClient(TestServer(app)) as client:
            return await coro_fn(client)

    return asyncio.run(wrapper())


def test_http_queue_full_shed(server, monkeypatch):
    """Bounded-queue shedding: 503 + Retry-After + structured reason, and
    llm_requests_shed_total{reason="queue_full"} increments."""
    monkeypatch.setattr(server.cfg, "max_queue", 1)
    monkeypatch.setattr(server, "_queue_depth", lambda: 5)

    async def go(client):
        resp = await client.post("/chat", json={"prompt": "hi"})
        assert resp.status == 503
        assert int(resp.headers["Retry-After"]) >= 1
        assert (await resp.json())["reason"] == "queue_full"
        m = await client.get("/metrics")
        text = (await m.read()).decode()
        assert 'llm_requests_shed_total{reason="queue_full"} 1.0' in text

    _http(server, go)


def test_http_slo_projection_shed(server, monkeypatch):
    """SLO-aware shedding: a projected queue wait past the request's TTFT
    class rejects with 429 before the request costs a queue slot."""
    monkeypatch.setattr(server, "_wait_per_slot", 10.0)  # 10 s per slot

    async def go(client):
        resp = await client.post(
            "/chat", json={"prompt": "hi", "slo_ttft_ms": 50})
        assert resp.status == 429
        body = await resp.json()
        assert body["reason"] == "slo_unattainable"
        resp = await client.post(
            "/chat", json={"prompt": "hi", "deadline_ms": 50})
        assert resp.status == 429
        assert (await resp.json())["reason"] == "deadline_unattainable"
        m = await client.get("/metrics")
        text = (await m.read()).decode()
        assert 'llm_requests_shed_total{reason="slo_unattainable"} 1.0' in text
        assert ('llm_requests_shed_total{reason="deadline_unattainable"} 1.0'
                in text)

    _http(server, go)


def test_http_deadline_504_and_metric(server, monkeypatch):
    monkeypatch.setattr(server, "_wait_per_slot", None)  # never shed

    async def go(client):
        resp = await client.post(
            "/chat", json={"prompt": "hi", "deadline_ms": 0.1,
                           "max_tokens": 64})
        assert resp.status == 504
        body = await resp.json()
        assert body["reason"] == "deadline"
        assert "deadline exceeded" in body["error"]
        m = await client.get("/metrics")
        text = (await m.read()).decode()
        import re

        val = re.search(r"llm_request_deadline_exceeded_total (\d+)", text)
        assert val and int(val.group(1)) >= 1

    _http(server, go)


def _sse_events(raw: bytes) -> list:
    import json as _json

    return [_json.loads(line[len(b"data: "):])
            for line in raw.split(b"\n\n") if line.startswith(b"data: ")]


def test_sse_stream_success_terminal(server, monkeypatch):
    monkeypatch.setattr(server, "_wait_per_slot", None)

    async def go(client):
        resp = await client.post(
            "/chat", json={"prompt": "hi", "stream": True, "max_tokens": 4})
        assert resp.status == 200
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        events = _sse_events(await resp.read())
        assert events, "stream must carry events"
        assert all(ev["finished"] is False for ev in events[:-1])
        final = events[-1]
        assert final["finished"] is True and "error" not in final
        assert final["meta"]["completion_tokens"] >= 1
        assert sum(len(ev.get("token_ids", [])) for ev in events[:-1]) \
            == final["meta"]["completion_tokens"]

    _http(server, go)


def test_sse_stream_text_matches_nonstream(server, monkeypatch):
    """The concatenation of every SSE `text` field (terminal tail
    included) equals the non-stream output for the same greedy request.
    In particular a multibyte sequence split across token boundaries
    must stream as its resolved character once complete — never as a
    replacement char frozen into the client's transcript (deltas come
    from the decoder's stable prefix, not a slice of the unstable
    tail)."""
    monkeypatch.setattr(server, "_wait_per_slot", None)

    async def go(client):
        body = {"prompt": "hello robustness", "max_tokens": 8,
                "temperature": 0.0}
        resp = await client.post("/chat", json=body)
        assert resp.status == 200
        plain = (await resp.json())["output"]
        resp = await client.post("/chat", json=dict(body, stream=True))
        assert resp.status == 200
        events = _sse_events(await resp.read())
        assert events[-1]["finished"] is True
        streamed = "".join(ev.get("text", "") for ev in events)
        assert streamed == plain

    _http(server, go)


def test_wedged_replica_stays_ineligible_after_cooldown():
    """A replica still inside the overlong step that got it quarantined
    must NOT become routing-eligible (or probe-re-admitted) when its
    cooldown lapses — work routed there would hang with no terminal
    event, defeating the zero-hung-requests gate. The wedge resolving
    (step_done) restores the normal lazy re-admission."""
    h = ReplicaHealth(error_threshold=3, watchdog_s=0.02, cooldown_s=0.01)
    h.step_started()
    time.sleep(0.03)
    assert h.check_stuck() and h.state == QUARANTINED
    time.sleep(0.02)                 # cooldown lapsed; step STILL running
    assert not h.eligible()
    assert not h.probe()
    h.step_done()                    # wedge resolves
    assert h.eligible()
    assert h.probe() and h.state == DEGRADED


def test_slow_replica_wired_on_single_engine_server(runner):
    """`slow_replica:idx=0` must inject on a 1-replica server too — only
    EnginePool wired the delay before, so a valid spec against the
    single-engine path passed validation yet injected nothing (the
    silent-no-injection mode faultinject.py forbids)."""
    from agentic_traffic_testing_tpu.serving.config import ServerConfig
    from agentic_traffic_testing_tpu.serving.server import LLMServer

    cfg = ServerConfig(model=MODEL, dtype=DTYPE, max_num_seqs=4,
                       max_model_len=256, num_blocks=128, warmup=False,
                       fault_spec="slow_replica:idx=0,ms=50")
    srv = LLMServer(cfg, engine=make_engine(runner))
    assert srv.async_engine.step_delay_s == pytest.approx(0.05)


def test_sse_stream_failure_has_terminal_event(server, monkeypatch):
    """The round-9 satellite: a failed generation must end the SSE stream
    with a structured {"error": ..., "finished": true} terminal event, so
    truncation is distinguishable from completion."""
    monkeypatch.setattr(server, "_wait_per_slot", None)

    async def go(client):
        resp = await client.post(
            "/chat", json={"prompt": "hi", "stream": True, "max_tokens": 64,
                           "deadline_ms": 0.1})
        assert resp.status == 200  # stream already committed: error rides SSE
        events = _sse_events(await resp.read())
        final = events[-1]
        assert final["finished"] is True
        assert "deadline exceeded" in final["error"]
        assert final["reason"] == "deadline"

    _http(server, go)


def test_started_streams_never_retry(runner):
    """A stream that already emitted tokens gets its error terminal
    passed through instead of a retry (no silent token replay)."""
    model_cfg, r = runner

    def factory(i):
        return LLMEngine(EngineConfig(
            model=MODEL, dtype=DTYPE, max_num_seqs=4, max_model_len=256,
            block_size=16, num_blocks=128), model_cfg=model_cfg, runner=r)

    pool = EnginePool.build(factory, 2, policy="round_robin")
    pool.start()
    try:
        async def go():
            # Poison the owning engine AFTER the prefill emitted the first
            # token: decode dispatches then fail, mid-stream.
            ev_reasons, toks = [], []
            first = True
            async for ev in pool.generate(
                    churn_prompts(1)[0],
                    SamplingParams(temperature=0.0, max_tokens=32,
                                   ignore_eos=True), request_id="mid"):
                toks.extend(ev.new_token_ids)
                if first and toks:
                    first = False
                    from agentic_traffic_testing_tpu.runtime.faultinject import (
                        FaultInjector,
                    )

                    for e in pool.engines:
                        e._faults = FaultInjector.from_spec(
                            "dispatch_error:p=1", 0)
                if ev.finished:
                    ev_reasons.append(ev.request.finish_reason)
            return ev_reasons, toks

        reasons, toks = asyncio.run(go())
        assert toks, "stream started"
        assert reasons == [FinishReason.ERROR]
        assert pool.request_retries == 0
    finally:
        for e in pool.engines:
            e._faults = None
        pool.shutdown()
