"""Parity tests: C++ runtime core (native/) vs. pure-Python fallback.

The native library implements the block pool, sequence tables, batched
block-table fill, and the decode capacity/preemption pass with bit-exact
semantics (including free-list ordering), so the two implementations are
interchangeable under the scheduler and engine. These tests drive both with
identical workloads and assert identical observable state.
"""

import numpy as np
import pytest

from agentic_traffic_testing_tpu import native
from agentic_traffic_testing_tpu.runtime.block_allocator import (
    BlockAllocator,
    make_block_allocator,
)
from agentic_traffic_testing_tpu.runtime.request import Request, SamplingParams
from agentic_traffic_testing_tpu.runtime.scheduler import (
    DecodeBatch,
    PrefillBatch,
    Scheduler,
    SchedulerConfig,
)

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain unavailable"
)


def pair(num_blocks=32, block_size=4):
    return (
        BlockAllocator(num_blocks, block_size),
        native.NativeBlockAllocator(num_blocks, block_size),
    )


def test_factory_selects_native():
    alloc = make_block_allocator(8, 4)
    assert isinstance(alloc, native.NativeBlockAllocator)
    assert isinstance(make_block_allocator(8, 4, native=False), BlockAllocator)


def test_allocate_free_order_parity():
    py, nt = pair()
    rng = np.random.default_rng(0)
    held_py, held_nt = [], []
    for _ in range(200):
        if rng.random() < 0.6 or not held_py:
            n = int(rng.integers(1, 5))
            a, b = py.allocate(n), nt.allocate(n)
            assert a == b
            if a is not None:
                held_py.append(a)
                held_nt.append(b)
        else:
            i = int(rng.integers(0, len(held_py)))
            py.free(held_py.pop(i))
            nt.free(held_nt.pop(i))
        assert py.num_free_blocks == nt.num_free_blocks
        assert py.num_used_blocks == nt.num_used_blocks
    assert py.usable_tokens == nt.usable_tokens


def test_sequence_parity():
    py, nt = pair()
    sp, sn = py.new_sequence(), nt.new_sequence()
    for tokens in (3, 9, 9, 20, 57):
        assert sp.ensure_capacity(tokens) == sn.ensure_capacity(tokens)
        assert sp.blocks == sn.blocks
        assert sp.num_blocks == sn.num_blocks
        assert sp.capacity_tokens == sn.capacity_tokens
        assert sp.table_row(20) == sn.table_row(20)
    sp.release(), sn.release()
    assert py.num_free_blocks == nt.num_free_blocks
    # release is idempotent on both
    sp.release(), sn.release()
    assert py.num_free_blocks == nt.num_free_blocks


def test_exhaustion_all_or_nothing():
    py, nt = pair(num_blocks=6, block_size=4)   # 5 usable blocks
    sp, sn = py.new_sequence(), nt.new_sequence()
    assert sp.ensure_capacity(12) and sn.ensure_capacity(12)   # 3 blocks
    sp2, sn2 = py.new_sequence(), nt.new_sequence()
    # needs 3, only 2 free: must fail atomically on both
    assert not sp2.ensure_capacity(12)
    assert not sn2.ensure_capacity(12)
    assert py.num_free_blocks == nt.num_free_blocks == 2
    assert sp2.blocks == sn2.blocks == []


def test_double_free_detection():
    _, nt = pair()
    blocks = nt.allocate(3)
    nt.free(blocks)
    with pytest.raises((ValueError, RuntimeError)):
        nt.free([99])  # out of range
    with pytest.raises(RuntimeError):
        for _ in range(40):
            nt.free(blocks)  # repeated free must eventually trip the guard


def test_fill_tables_batch():
    nt = native.NativeBlockAllocator(32, 4)
    seqs = []
    for tokens in (5, 1, 17):
        s = nt.new_sequence()
        assert s.ensure_capacity(tokens)
        seqs.append(s)
    out = np.full((3, 6), -7, np.int32)
    nt.fill_tables(seqs, 6, out)
    for i, s in enumerate(seqs):
        assert out[i].tolist() == s.table_row(6)


def test_decode_capacity_pass_self_preemption():
    """A single oversized sequence with nothing to evict preempts itself."""
    nt = native.NativeBlockAllocator(4, 4)   # 3 usable blocks
    s = nt.new_sequence()
    assert s.ensure_capacity(12)
    keep = nt.decode_capacity_pass([s], [64])
    assert keep == [False]
    assert nt.num_free_blocks == 3
    assert s.num_blocks == 0


# -- scheduler-level parity --------------------------------------------------


def make_sched(alloc):
    cfg = SchedulerConfig(
        max_num_seqs=4, max_num_batched_tokens=256, max_model_len=64,
        block_size=alloc.block_size, decode_lookahead=2, min_prefill_bucket=8,
    )
    return Scheduler(cfg, alloc)


def req(rid, n_prompt, arrival):
    r = Request(
        request_id=rid,
        prompt_ids=list(range(1, n_prompt + 1)),
        sampling=SamplingParams(max_tokens=64),
    )
    r.arrival_time = arrival
    return r


def plan_sig(plan):
    if isinstance(plan, PrefillBatch):
        return ("prefill", [r.request_id for r in plan.requests],
                plan.padded_len, plan.padded_batch)
    if isinstance(plan, DecodeBatch):
        return ("decode", [r.request_id for r in plan.requests], plan.padded_batch)
    return ("idle",)


def drive(scheduler_alloc_native: bool, seed: int):
    """Run a randomized admission/decode workload; return the event trace."""
    alloc = make_block_allocator(20, 4, native=scheduler_alloc_native)
    sched = make_sched(alloc)
    rng = np.random.default_rng(seed)
    trace = []
    arrivals = iter(range(1000))
    for step in range(120):
        if rng.random() < 0.3:
            n = int(rng.integers(1, 40))
            sched.add_request(req(f"r{step}", n, next(arrivals)))
        plan = sched.plan()
        trace.append(plan_sig(plan))
        if isinstance(plan, DecodeBatch):
            for r in plan.requests:
                r.output_ids.append(0)   # sequence grows one token
            # randomly finish a request to churn block state
            if rng.random() < 0.15:
                victim = plan.requests[int(rng.integers(0, len(plan.requests)))]
                sched.finish(victim)
                trace.append(("finish", victim.request_id))
        trace.append(("stats", tuple(sorted(sched.kv_stats().items()))))
    return trace


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_scheduler_trace_parity(seed):
    """Identical plan/preemption/accounting traces from both allocators."""
    assert drive(False, seed) == drive(True, seed)


def test_tie_break_parity_equal_arrivals():
    """Equal arrival_times must evict the same victim on both paths."""
    traces = {}
    for use_native in (False, True):
        alloc = make_block_allocator(12, 4, native=use_native)  # 11 usable
        sched = make_sched(alloc)
        reqs = [req(f"r{i}", 12, arrival=5) for i in range(3)]  # all tied
        for r in reqs:
            sched.add_request(r)
        sigs = []
        for _ in range(12):
            plan = sched.plan()
            sigs.append(plan_sig(plan))
            if isinstance(plan, DecodeBatch):
                for r in plan.requests:
                    r.output_ids.append(0)
        traces[use_native] = sigs
    assert traces[False] == traces[True]
