"""Round-11 elastic-serving suite: live migration of in-flight streams,
drain-and-migrate quarantine, and telemetry-driven pool scaling.

Covers the ISSUE-11 acceptance gates on CPU. The fast engine-level pins
(identity + KV byte-identity, bf16/int8) and every policy/degrade path
run in the default tier; the expensive pool-level soak variants (churn
identity per KV dtype, concurrent async e2e) carry the `slow` marker —
the tier-4 budget precedent (PR-4 warmup sweep, PR-1 hybrid parity) —
and scripts/dev/chaos_ab.py's migration-soak arm repeats the pool-level
identity gate as a tier-1 smoke.

Gates:
  * a stream interrupted mid-decode completes on a survivor with its full
    token sequence byte-for-byte identical to an uninterrupted run
    (greedy and seeded), for bf16 and int8 KV pools;
  * checkpoint → adopt restores the KV pages byte-identically;
  * migrate-during-chunked-prefill completes cleanly;
  * `migrate_error` degrades to the round-9 kill path with a structured
    terminal;
  * scale_to up/down e2e with rendezvous keys reclaimed;
  * all knobs at defaults leave the round-9 paths untouched;
  * the retry-once fix: the client sees the LAST attempt's terminal and
    retries are counted by reason.
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from agentic_traffic_testing_tpu.models.config import resolve_config
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import (
    FinishReason,
    SamplingParams,
)
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner
from agentic_traffic_testing_tpu.runtime.scheduler import QueueFullError
from agentic_traffic_testing_tpu.serving.replica_pool import (
    MAX_STREAM_MIGRATIONS,
    EnginePool,
)

MODEL = "tiny"
DTYPE = "float32"


@pytest.fixture(scope="module")
def runner():
    import jax
    import jax.numpy as jnp

    cfg = resolve_config(MODEL)
    params = init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, ModelRunner(cfg, params, decode_steps=1)


def make_engine(runner, **kw):
    model_cfg, r = runner
    defaults = dict(model=MODEL, dtype=DTYPE, max_num_seqs=4,
                    max_model_len=256, block_size=16, num_blocks=128,
                    migration=1)
    defaults.update(kw)
    return LLMEngine(EngineConfig(**defaults), model_cfg=model_cfg, runner=r)


def prompts_for(n, length=24, seed=13):
    wl = np.random.default_rng(seed)
    return [wl.integers(10, 200, length).tolist() for _ in range(n)]


def drive(eng_or_pool, cap=4000):
    steps = 0
    events = []
    while eng_or_pool.has_work() and steps < cap:
        events.extend(eng_or_pool.step())
        steps += 1
    assert steps < cap, "failed to drain (hung requests)"
    return events


def run_to_step(eng, req, k):
    """Step until the request has sampled >= k tokens (host-observed)."""
    steps = 0
    while req.sampling_step < k and steps < 2000:
        eng.step()
        steps += 1
    assert req.sampling_step >= k
    return req


def track_finals(events, finals):
    """Per-request-id FINAL request object (a migrated stream's later
    events carry a NEW Request under the same id, with more tokens)."""
    for ev in events:
        cur = finals.get(ev.request.request_id)
        if cur is None or ev.request.sampling_step >= cur.sampling_step:
            finals[ev.request.request_id] = ev.request
    return finals


# -------------------------------------------------- checkpoint -> adopt


@pytest.mark.parametrize("sampling", [
    SamplingParams(temperature=0.0, max_tokens=12, ignore_eos=True),
    SamplingParams(temperature=0.8, top_k=20, seed=11, max_tokens=12,
                   ignore_eos=True),
], ids=["greedy", "seeded"])
def test_migration_token_identity_mid_decode(runner, sampling):
    """The acceptance criterion: interrupt a stream mid-decode, resume on
    another engine, full token sequence byte-for-byte identical to the
    uninterrupted run."""
    import dataclasses

    prompt = prompts_for(1, 40)[0]
    base = make_engine(runner).generate(
        prompt, dataclasses.replace(sampling)).generated_ids
    src, dst = make_engine(runner), make_engine(runner)
    req = src.add_request(prompt, dataclasses.replace(sampling))
    run_to_step(src, req, 5)
    plan = src.checkpoint_request(req, trigger="drain")
    assert plan is not None and plan.decodable
    assert req.finish_reason is FinishReason.MIGRATED
    adopted = dst.adopt_request(plan)
    assert adopted.num_computed_tokens == adopted.num_prompt_tokens
    drive(dst)
    assert adopted.generated_ids == base
    assert adopted.finish_reason in (FinishReason.STOP, FinishReason.LENGTH)


def test_migration_mid_chunked_prefill_completes_cleanly(runner):
    """Checkpoint between prefill chunks: only the computed full blocks
    travel, the target resumes the remaining chunks on the same ladder
    rungs, and the output is identical."""
    kw = dict(prefill_chunk_tokens=32, num_blocks=256)
    sp = lambda: SamplingParams(temperature=0.7, top_k=30, seed=3,
                                max_tokens=8, ignore_eos=True)
    prompt = prompts_for(1, 54, seed=5)[0]
    base = make_engine(runner, **kw).generate(prompt, sp()).generated_ids
    src, dst = make_engine(runner, **kw), make_engine(runner, **kw)
    req = src.add_request(prompt, sp())
    src.step()  # first chunk only
    assert req.is_prefilling
    plan = src.checkpoint_request(req)
    assert not plan.decodable
    assert plan.kv_tokens == req.num_computed_tokens
    adopted = dst.adopt_request(plan)
    assert adopted.is_prefilling  # resumes on the chunk path
    drive(dst)
    assert adopted.generated_ids == base


@pytest.mark.parametrize("pool_kw", [
    dict(dtype="bfloat16"),
    dict(kv_cache_dtype="int8"),
], ids=["bf16", "int8"])
def test_checkpoint_adopt_kv_byte_identity(runner, pool_kw):
    """The transplanted pages (and, for int8, their scale pairs) land in
    the target pool byte-identical to the checkpoint capture — and the
    resumed stream matches the uninterrupted run."""
    import jax

    sp = lambda: SamplingParams(temperature=0.0, max_tokens=10,
                                ignore_eos=True)
    prompt = prompts_for(1, 40, seed=7)[0]
    base = make_engine(runner, **pool_kw).generate(prompt,
                                                   sp()).generated_ids
    src, dst = make_engine(runner, **pool_kw), make_engine(runner, **pool_kw)
    req = src.add_request(prompt, sp())
    run_to_step(src, req, 5)
    plan = src.checkpoint_request(req)
    assert plan.blocks
    adopted = dst.adopt_request(plan)
    assert adopted.state.value == "running"  # transplant, not recompute
    blks = list(adopted.blocks.blocks)
    k = np.asarray(jax.device_get(dst.cache.k))
    v = np.asarray(jax.device_get(dst.cache.v))
    quant = dst.cache.quantized
    ks = np.asarray(jax.device_get(dst.cache.k_scale)) if quant else None
    vs = np.asarray(jax.device_get(dst.cache.v_scale)) if quant else None
    bs = dst.cfg.block_size
    for i, mb in enumerate(plan.blocks):
        valid = min(bs, plan.kv_tokens - i * bs)
        assert np.array_equal(k[:, :, blks[i], :valid],
                              np.asarray(mb.k)[:, :, :valid])
        assert np.array_equal(v[:, :, blks[i], :valid],
                              np.asarray(mb.v)[:, :, :valid])
        if quant:
            assert np.array_equal(ks[:, blks[i]], np.asarray(mb.k_scale))
            assert np.array_equal(vs[:, blks[i]], np.asarray(mb.v_scale))
    drive(dst)
    assert adopted.generated_ids == base


def test_adopt_falls_back_to_recompute_without_room(runner):
    """A target with no seat (or no KV room) re-queues the folded history
    at the head instead of transplanting — the stream still completes."""
    sp = lambda: SamplingParams(temperature=0.0, max_tokens=12,
                                ignore_eos=True)
    prompt = prompts_for(1, 40, seed=9)[0]
    src = make_engine(runner)
    req = src.add_request(prompt, sp())
    run_to_step(src, req, 5)
    plan = src.checkpoint_request(req)
    dst = make_engine(runner, max_num_seqs=1)
    # Occupy the only seat so the transplant path refuses.
    blocker = dst.add_request(prompts_for(1, 16, seed=10)[0], sp())
    dst.step()
    adopted = dst.adopt_request(plan)
    assert adopted.state.value == "waiting"  # recompute path
    assert adopted.num_computed_tokens == 0
    drive(dst)
    assert blocker.is_finished() and adopted.is_finished()
    assert adopted.finish_reason in (FinishReason.STOP, FinishReason.LENGTH)
    # The folded history is preserved verbatim (the preemption contract);
    # the recompute continuation is deterministic for this engine.
    assert adopted.generated_ids[:plan.sampling_step] == \
        plan.token_ids[plan.num_orig_prompt_tokens:]


# ----------------------------------------------- pool: drain-and-migrate


def churn_sampling(i, max_tokens=10):
    if i % 2 == 0:
        return SamplingParams(temperature=0.0, max_tokens=max_tokens - (i % 3),
                              ignore_eos=True)
    return SamplingParams(temperature=0.8, top_k=20, seed=5 + i,
                          max_tokens=max_tokens // 2 + (i % 4),
                          ignore_eos=True)


def pool_of(runner, specs, **kw):
    return EnginePool([make_engine(runner, fault_spec=s, fault_seed=17,
                                   num_blocks=256, **kw) for s in specs],
                      policy="round_robin")


@pytest.mark.slow
@pytest.mark.parametrize("pool_kw", [
    dict(dtype="bfloat16"),
    dict(kv_cache_dtype="int8"),
], ids=["bf16", "int8"])
def test_pool_migration_token_identity_under_churn(runner, pool_kw):
    """Drain-and-migrate under composition churn: more requests than
    seats (admission mid-decode), mixed greedy/seeded sampling, EOS
    mid-batch — every stream interrupted by an injected quarantine
    (LLM_FAULT_SPEC) completes on the survivor byte-identical to the
    clean run, for bf16 and int8 KV pools (the acceptance criterion;
    the f32 path is pinned by the engine-level tests above and the
    chaos_ab migration soak)."""
    n = 5
    prompts = prompts_for(n)

    def sampling(i):
        if i == 4:
            # EOS mid-batch: stop on a token the clean run emits
            # mid-stream (probed below).
            return SamplingParams(temperature=0.0, max_tokens=8,
                                  stop_token_ids=(stop_tok,))
        return churn_sampling(i, max_tokens=6)

    # Probe request 4's greedy stream for a mid-stream stop token with no
    # earlier occurrence (the PR-6 rule); request 4 is the first whose
    # greedy stream is not immediately periodic on this seed. Probed on
    # the SAME pool dtype: bf16/int8 pools can emit different streams.
    probe = make_engine(runner, num_blocks=256, **pool_kw).generate(
        prompts[4], SamplingParams(temperature=0.0, max_tokens=8,
                                   ignore_eos=True)).generated_ids
    stop_tok = next(t for i, t in enumerate(probe[1:], start=1)
                    if t not in probe[:i])

    def run(spec0):
        pool = pool_of(runner, [spec0, ""], **pool_kw)
        reqs = [pool.add_request(p, sampling(i), request_id=f"c{i}")
                for i, p in enumerate(prompts)]
        finals = {r.request_id: r for r in reqs}
        track_finals(drive(pool), finals)
        return pool, finals

    _, clean = run("")
    pool, chaos = run("dispatch_error:p=0.15")
    adopted = sum(v for (t, s), v in pool.migrations.items()
                  if s == "adopted")
    assert adopted >= 1, "the fault spec must actually trigger migration"
    assert all(r.is_finished() for r in chaos.values())
    for rid, r in chaos.items():
        assert r.finish_reason in (FinishReason.STOP, FinishReason.LENGTH), \
            (rid, r.finish_reason, r.error)
        assert r.generated_ids == clean[rid].generated_ids, rid
    # The EOS request stopped on its stop token in both arms.
    assert chaos["c4"].finish_reason is FinishReason.STOP
    assert chaos["c4"].generated_ids[-1] == stop_tok


@pytest.mark.slow
def test_pool_migration_async_e2e(runner):
    """Async serving path: concurrent streams on a 2-replica pool with
    replica 0 fault-injected — MIGRATED terminals never reach a client,
    every stream completes, and each matches its clean solo reference."""
    n = 4
    prompts = prompts_for(n, seed=21)
    refs = []
    ref_eng = make_engine(runner, num_blocks=256)
    for i, p in enumerate(prompts):
        refs.append(ref_eng.generate(p, churn_sampling(i)).generated_ids)

    pool = pool_of(runner, ["dispatch_error:p=0.3", ""])
    pool.start()
    try:
        async def one(i):
            toks = []
            async for ev in pool.generate(prompts[i], churn_sampling(i),
                                          request_id=f"a{i}"):
                toks.extend(ev.new_token_ids)
                if ev.finished:
                    assert ev.request.finish_reason is not \
                        FinishReason.MIGRATED
                    assert ev.request.finish_reason in (
                        FinishReason.STOP, FinishReason.LENGTH), \
                        ev.request.error
            return toks

        async def go():
            return await asyncio.gather(*(one(i) for i in range(n)))

        outs = asyncio.run(go())
    finally:
        pool.shutdown()
    assert outs == refs
    assert sum(v for (t, s), v in pool.migrations.items()
               if s == "adopted") >= 1


def test_migrate_error_degrades_to_kill_path(runner):
    """Injected migrate_error: the checkpoint fails BEFORE any teardown
    and the stream gets the round-9 structured ERROR terminal instead of
    hanging — CPU-testable proof that the fallback is the old path."""
    n = 6
    pool = pool_of(runner,
                   ["dispatch_error:p=0.25;migrate_error:p=1", ""])
    reqs = [pool.add_request(p, churn_sampling(i), request_id=f"k{i}")
            for i, p in enumerate(prompts_for(n))]
    finals = track_finals(drive(pool), {r.request_id: r for r in reqs})
    assert all(r.is_finished() for r in finals.values())
    assert not pool.migrations.get(("quarantine", "adopted"))
    killed = [r for r in finals.values()
              if r.finish_reason is FinishReason.ERROR]
    assert killed, "the chaos spec must hit at least one started stream"
    assert any("migration failed" in (r.error or "") for r in killed)


def test_migration_hop_bound_terminates(runner):
    """A stream past MAX_STREAM_MIGRATIONS checkpoints stops migrating:
    adoption refuses and the terminal degrades in place to the round-9
    structured ERROR — no infinite checkpoint/adopt ping-pong under a
    pool-wide fault. The hop count survives re-checkpoints (an adopted
    stream's next plan carries hops+1)."""
    sp = SamplingParams(temperature=0.0, max_tokens=30, ignore_eos=True)
    src = make_engine(runner)
    req = src.add_request(prompts_for(1)[0], sp)
    run_to_step(src, req, 4)
    plan = src.checkpoint_request(req, trigger="quarantine")
    assert plan.hops == 1
    # Hop accounting survives a checkpoint -> adopt -> checkpoint chain.
    mid = make_engine(runner)
    adopted = mid.adopt_request(plan)
    run_to_step(mid, adopted, plan.sampling_step + 2)
    plan2 = mid.checkpoint_request(adopted, trigger="quarantine")
    assert plan2.hops == 2
    # Within the bound: the pool adopts.
    pool = pool_of(runner, ["", ""])
    adopted.migration = plan2
    assert pool._adopt_sync(adopted, source=0)
    assert pool.migrations == {("quarantine", "adopted"): 1}
    # Past the bound: refused, terminal degrades to a structured ERROR.
    victim = pool.engines[1]._requests[plan2.request_id]
    plan3 = pool.engines[1].checkpoint_request(victim, "quarantine")
    assert plan3.hops == 3  # adopt carried plan2's count forward
    plan3.hops = MAX_STREAM_MIGRATIONS + 1
    assert not pool._adopt_sync(victim, source=1)
    assert victim.finish_reason is FinishReason.ERROR
    assert "migration failed" in victim.error
    assert pool.migrations[("quarantine", "failed")] == 1


# ------------------------------------------------------------ elastic pool


def test_scale_to_up_down_e2e(runner):
    """scale_to up mid-traffic admits new replicas into rendezvous
    routing at fresh ORIGINAL indices; scale_to down drains-and-migrates
    every live stream and reclaims the survivors' keys — completions stay
    byte-identical to a fixed-size run."""
    model_cfg, r = runner

    def factory(i):
        return LLMEngine(EngineConfig(
            model=MODEL, dtype=DTYPE, max_num_seqs=4, max_model_len=256,
            block_size=16, num_blocks=256, migration=1),
            model_cfg=model_cfg, runner=r)

    n = 8
    prompts = prompts_for(n, seed=31)

    def run(scale_script):
        pool = EnginePool.build(factory, 2, policy="round_robin")
        reqs = [pool.add_request(p, churn_sampling(i), request_id=f"s{i}")
                for i, p in enumerate(prompts)]
        finals = {rq.request_id: rq for rq in reqs}
        steps = 0
        while pool.has_work() and steps < 4000:
            if steps in scale_script:
                track_finals(pool.scale_to(scale_script[steps]), finals)
            track_finals(pool.step(), finals)
            steps += 1
        assert steps < 4000
        return pool, finals

    _, clean = run({})
    pool, churn = run({2: 3, 5: 1, 8: 2})
    assert len(pool) == 2 and pool.scale_events == 3
    assert pool.migrations.get(("scale_down", "adopted"), 0) >= 1
    for rid, rq in churn.items():
        assert rq.is_finished()
        assert rq.generated_ids == clean[rid].generated_ids, rid
    # Rendezvous keys reclaimed: scoring is by ORIGINAL index, so the
    # re-created index-1 replica owns exactly the keys index 1 owned
    # before the down/up cycle.
    from agentic_traffic_testing_tpu.serving.router import (
        prefix_route_key,
        rendezvous_pick,
    )

    key = prefix_route_key(prompts[0], 16)
    assert rendezvous_pick(key, [0, 1]) == rendezvous_pick(key, 2)
    assert pool.eligible_replicas() == [0, 1]
    assert len(pool.router.engines) == 2


def test_scale_to_async_down_with_live_streams(runner):
    """Async serving path: scale_to_async(1) mid-traffic — the retiring
    replica's engine thread checkpoints its live streams, the pool's
    generate coroutines adopt them on the survivor, and every stream
    completes identical to its solo reference."""
    model_cfg, r = runner

    def factory(i):
        return LLMEngine(EngineConfig(
            model=MODEL, dtype=DTYPE, max_num_seqs=4, max_model_len=256,
            block_size=16, num_blocks=256, migration=1),
            model_cfg=model_cfg, runner=r)

    n = 4
    prompts = prompts_for(n, seed=61)
    sp = lambda i: SamplingParams(temperature=0.0, max_tokens=12,
                                  ignore_eos=True)
    ref_eng = make_engine(runner, num_blocks=256)
    refs = [ref_eng.generate(p, sp(i)).generated_ids
            for i, p in enumerate(prompts)]

    pool = EnginePool.build(factory, 2, policy="round_robin")
    pool.start()
    try:
        async def one(i):
            toks = []
            async for ev in pool.generate(prompts[i], sp(i),
                                          request_id=f"d{i}"):
                toks.extend(ev.new_token_ids)
                if ev.finished:
                    assert ev.request.finish_reason in (
                        FinishReason.STOP, FinishReason.LENGTH), \
                        ev.request.error
            return toks

        async def go():
            tasks = [asyncio.ensure_future(one(i)) for i in range(n)]
            # Let streams start on both replicas before retiring one.
            await asyncio.sleep(0.2)
            await pool.scale_to_async(1)
            return await asyncio.gather(*tasks)

        outs = asyncio.run(go())
    finally:
        pool.shutdown()
    assert len(pool) == 1 and pool.scale_events == 1
    assert outs == refs


def test_scale_up_requires_factory(runner):
    pool = pool_of(runner, ["", ""])
    with pytest.raises(RuntimeError, match="factory"):
        pool.scale_to(3)
    with pytest.raises(ValueError):
        pool.scale_to(0)


def test_rebalance_trigger_and_newest_stream_selection(runner):
    """The SLO-rebalance decision fires only when a replica's projected
    wait blows the class AND an idle survivor exists; the drained stream
    is the NEWEST started decode stream."""
    pool = pool_of(runner, ["", ""])
    drains = []
    pool._async[0].request_drain = lambda c, t: drains.append((0, c, t))
    pool._async[1].request_drain = lambda c, t: drains.append((1, c, t))
    snaps = {0: dict(num_waiting=6, num_running=4),
             1: dict(num_waiting=0, num_running=0)}
    for i, e in enumerate(pool.engines):
        e.load_snapshot = (lambda i=i: dict(
            snaps[i], inflight_dispatches=0, free_blocks=99,
            max_num_seqs=4, block_size=16))
    # Gates: no EWMA / no SLO class / migration off -> no drain.
    assert pool.maybe_rebalance(None, 100.0) == 0
    assert pool.maybe_rebalance(0.5, 0.0) == 0
    assert pool.maybe_rebalance(0.5, 10_000.0) == 0  # wait under the class
    assert pool.maybe_rebalance(0.5, 100.0) == 1
    assert drains == [(0, 1, "rebalance")]
    drains.clear()
    # Busy "idle" candidate (queued work) -> no shuffle.
    snaps[1]["num_waiting"] = 3
    assert pool.maybe_rebalance(0.5, 100.0) == 0
    # Full-seat "idle" candidate -> no shuffle either: the transplant
    # would refuse and the stream would recompute from scratch.
    snaps[1]["num_waiting"] = 0
    snaps[1]["num_running"] = 4
    assert pool.maybe_rebalance(0.5, 100.0) == 0
    assert not drains

    # Mechanism: drain_for_migration(count=1, started_only) checkpoints
    # the NEWEST started stream and leaves the oldest running.
    eng = make_engine(runner, num_blocks=256)
    old = eng.add_request(prompts_for(1, 24, seed=41)[0],
                          churn_sampling(0, max_tokens=30))
    run_to_step(eng, old, 2)
    new = eng.add_request(prompts_for(1, 24, seed=42)[0],
                          churn_sampling(0, max_tokens=30))
    run_to_step(eng, new, 2)
    events = eng.drain_for_migration("rebalance", count=1,
                                     started_only=True)
    migrated = [ev.request for ev in events
                if ev.request.finish_reason is FinishReason.MIGRATED]
    assert [r.request_id for r in migrated] == [new.request_id]
    assert not old.is_finished()


def test_autoscale_decision():
    from agentic_traffic_testing_tpu.serving.autoscale import (
        AutoscalePolicy,
        AutoscaleSignals,
        decide,
    )

    pol = AutoscalePolicy(min_replicas=1, max_replicas=4)
    sig = lambda **kw: AutoscaleSignals(**dict(dict(
        current=2, waiting=0, running=1, met_delta=0, violated_delta=0,
        idle_ticks=0), **kw))
    # Violation fraction drives growth (with enough verdicts).
    assert decide(sig(met_delta=1, violated_delta=5), pol) == 3
    assert decide(sig(met_delta=1, violated_delta=1), pol) == 2  # noise
    # Queue pressure drives growth without any SLO plane.
    assert decide(sig(waiting=8), pol) == 3
    # Ceiling/floor.
    assert decide(sig(current=4, violated_delta=9, met_delta=0), pol) == 4
    assert decide(sig(current=1, running=0, idle_ticks=5), pol) == 1
    # Idle long enough shrinks by one.
    assert decide(sig(current=3, running=0, idle_ticks=3), pol) == 2
    # Any work (or a recent violation) blocks the shrink.
    assert decide(sig(current=3, running=1, idle_ticks=3), pol) == 3


def test_autoscale_controller_tick(runner):
    """Controller e2e against a real pool: queue pressure scales up, a
    calm pool scales back down — through scale_to_async, so scale-down
    drains ride the migration plane."""
    from agentic_traffic_testing_tpu.serving.autoscale import (
        AutoscaleController,
        AutoscalePolicy,
    )

    model_cfg, r = runner

    def factory(i):
        return LLMEngine(EngineConfig(
            model=MODEL, dtype=DTYPE, max_num_seqs=2, max_model_len=256,
            block_size=16, num_blocks=256, migration=1),
            model_cfg=model_cfg, runner=r)

    pool = EnginePool.build(factory, 2)
    ctl = AutoscaleController(
        pool, AutoscalePolicy(min_replicas=1, max_replicas=3,
                              idle_ticks_down=2))

    async def go():
        # Queue pressure: park requests in replica queues (not started —
        # the pool is never stepped).
        for i, p in enumerate(prompts_for(10, seed=51)):
            pool.add_request(p, churn_sampling(i))
        grew = await ctl.tick()
        assert grew == 3 and len(pool) == 3
        # Drain the queues synchronously, then idle ticks shrink the pool
        # (one calm window is not enough — hysteresis).
        drive(pool)
        assert await ctl.tick() is None
        assert await ctl.tick() == 2 and len(pool) == 2

    asyncio.run(go())
    assert ctl.scale_actions == 2


# ---------------------------------------------------- defaults + retry fix


def test_defaults_touch_no_migration_machinery(runner, monkeypatch):
    """migration=0 (the default): no checkpoint/adopt machinery is ever
    consulted — a dispatch failure takes the exact round-9 kill path."""
    def boom(*a, **k):
        raise AssertionError("migration machinery touched at defaults")

    monkeypatch.setattr(LLMEngine, "checkpoint_request", boom)
    monkeypatch.setattr(LLMEngine, "adopt_request", boom)
    monkeypatch.setattr(LLMEngine, "_checkpoint_or_fail", boom)
    monkeypatch.setattr(LLMEngine, "_try_transplant", boom)
    eng = make_engine(runner, migration=0,
                      fault_spec="dispatch_error:p=0.3", fault_seed=17)
    reqs = [eng.add_request(p, churn_sampling(i, max_tokens=6))
            for i, p in enumerate(prompts_for(5))]
    drive(eng)
    assert all(r.is_finished() for r in reqs)
    assert any(r.finish_reason is FinishReason.ERROR for r in reqs)
    assert eng.num_dispatch_failures >= 1


def test_migration_config_validation(runner):
    from agentic_traffic_testing_tpu.serving.config import ServerConfig

    with pytest.raises(ValueError, match="migration"):
        make_engine(runner, migration=2)
    # Round 14: speculation's history is host-side and the rejection
    # rollback leaves no draft bytes behind, so migration x speculation
    # BUILDS (identity pinned in tests/test_speculative.py).
    EngineConfig(migration=1, speculation="ngram")
    c = ServerConfig(model=MODEL, migration=1, num_replicas=1)
    with pytest.raises(ValueError, match="NUM_REPLICAS"):
        c._validate_elastic()
    c = ServerConfig(model=MODEL, pool_autoscale=1, migration=0,
                     num_replicas=2)
    with pytest.raises(ValueError, match="MIGRATION"):
        c._validate_elastic()
    ok = ServerConfig(model=MODEL, migration=1, pool_autoscale=1,
                      num_replicas=2, pool_max_replicas=4)
    ok._validate_elastic()


def test_started_terminal_with_drained_tokens_never_retries(runner):
    """A stream whose only tokens ride its ERROR terminal (drained by
    _fail_dispatch) is STARTED: the retry-once path must not fire (a
    retry would replay the delivered token), and the terminal — tokens
    included — passes through to the client."""
    from agentic_traffic_testing_tpu.runtime.request import (
        Request,
        RequestState,
    )
    from agentic_traffic_testing_tpu.serving.async_engine import TokenEvent

    pool = pool_of(runner, ["", ""])
    dead = Request(request_id="x", prompt_ids=[1, 2],
                   sampling=SamplingParams())
    dead.state = RequestState.ABORTED
    dead.finish_reason = FinishReason.ERROR
    dead.error = "boom"

    async def fake_gen(prompt_ids, sampling, request_id=None):
        yield TokenEvent([5], True, dead)

    pool._async[0].generate = fake_gen
    pool._async[1].generate = fake_gen  # a retry here would be the bug

    async def go():
        evs = []
        async for ev in pool.generate([1, 2], SamplingParams(), "x"):
            evs.append(ev)
        return evs

    evs = asyncio.run(go())
    assert len(evs) == 1 and evs[0].finished
    assert evs[0].new_token_ids == [5]
    assert evs[0].request.finish_reason is FinishReason.ERROR
    assert pool.request_retries == 0


def test_retry_surfaces_last_attempt_terminal(runner):
    """ISSUE-11 satellite: attempt 1 fails un-started (ERROR), the retry
    is shed by the survivor's engine-side queue bound — the client's
    terminal is the SHED (the attempt that actually ran last), and the
    retry is counted under its triggering reason."""
    pool = pool_of(runner, ["dispatch_error:p=1", ""])

    def refuse(*a, **k):
        raise QueueFullError("wait queue at capacity (test)")

    pool.engines[1].add_request = refuse
    pool.start()
    try:
        async def go():
            async for ev in pool.generate(prompts_for(1)[0],
                                          churn_sampling(0), "rr"):
                if ev.finished:
                    return ev
        ev = asyncio.run(go())
    finally:
        pool.shutdown()
    assert ev.request.finish_reason is FinishReason.SHED
    assert pool.request_retries == 1
    assert pool.retry_reasons == {"error": 1}
