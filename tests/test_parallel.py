"""Multi-chip tests on the virtual 8-device CPU mesh (SURVEY.md §4 strategy).

Covers the three mesh axes: tp (sharded serving runner vs single device),
sp (ring attention vs dense causal attention), and the combined dp/sp/tp
training step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

from agentic_traffic_testing_tpu.models.config import ModelConfig, resolve_config
from agentic_traffic_testing_tpu.models.llama import forward_full, init_params
from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
from agentic_traffic_testing_tpu.ops.ring_attention import make_sp_attention
from agentic_traffic_testing_tpu.parallel.mesh import auto_mesh_shape, make_mesh
from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import SamplingParams
from agentic_traffic_testing_tpu.training.train import (
    causal_lm_loss,
    init_train_state,
    make_train_step,
)


@pytest.fixture(scope="module")
def tiny_cfg():
    return resolve_config("tiny")


@pytest.fixture(scope="module")
def tiny_params(tiny_cfg):
    return init_params(tiny_cfg, jax.random.key(0), dtype=jnp.float32)


def test_eight_cpu_devices_present():
    assert len(jax.devices()) == 8


def test_auto_mesh_shape_covers_device_counts():
    for n in (1, 2, 4, 8):
        dp, sp, tp = auto_mesh_shape(n)
        assert dp * sp * tp == n


@pytest.mark.parametrize("dp,sp,tp", [(1, 4, 1), (2, 2, 2), (1, 8, 1)])
def test_ring_attention_matches_dense(dp, sp, tp):
    mesh = make_mesh(dp=dp, sp=sp, tp=tp)
    attn = make_sp_attention(mesh)
    b, t, h, kh, hd = 2 * dp, 8 * sp, 4, 2, 8
    q = jax.random.normal(jax.random.key(1), (b, t, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.key(2), (b, t, kh, hd), jnp.float32)
    v = jax.random.normal(jax.random.key(3), (b, t, kh, hd), jnp.float32)
    out = attn(q, k, v)
    qpos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    ref = causal_attention(q, k, v, q_positions=qpos,
                           kv_valid_len=jnp.full((b,), t, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_attention_subblock_streaming_matches_dense():
    """kv_block < Tl engages the round-4 two-level streaming (lax.scan over
    sub-blocks inside each ring step): numerics must match the dense oracle
    exactly like the one-level path — including the causal boundary rows at
    every sub-block edge."""
    mesh = make_mesh(sp=2)
    attn = make_sp_attention(mesh, kv_block=4)   # Tl=16 -> 4 sub-blocks
    b, t, h, kh, hd = 2, 32, 4, 2, 8
    q = jax.random.normal(jax.random.key(4), (b, t, h, hd), jnp.float32)
    k = jax.random.normal(jax.random.key(5), (b, t, kh, hd), jnp.float32)
    v = jax.random.normal(jax.random.key(6), (b, t, kh, hd), jnp.float32)
    out = attn(q, k, v)
    qpos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (b, t))
    ref = causal_attention(q, k, v, q_positions=qpos,
                           kv_valid_len=jnp.full((b,), t, jnp.int32))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_tp_engine_matches_single_device(tiny_cfg, tiny_params):
    """Greedy decode must be bit-identical between TP=2 and one device."""
    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=64, max_model_len=128)
    prompt = list(range(7, 27))
    samp = SamplingParams(temperature=0.0, max_tokens=16)

    ref = LLMEngine(ecfg, model_cfg=tiny_cfg, params=tiny_params).generate(prompt, samp)
    runner = TPRunner(tiny_cfg, tiny_params, make_mesh(tp=2))
    tp = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(prompt, samp)
    assert ref.output_ids == tp.output_ids


@pytest.mark.parametrize("sp", [2, 4])
def test_sp_serving_prefill_matches_single_device(tiny_cfg, tiny_params, sp):
    """Serving sequence parallelism (round-4, SURVEY §5.7's last box): a
    long-prompt prefill through SPPrefillRunner — ring attention over the
    sp axis, decode on the replicated pool — must be token-exact vs the
    single-device engine. Prompt length crosses several KV blocks so the
    sp-sharded deferred page write is really exercised."""
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPPrefillRunner

    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=64,
                        max_model_len=128)
    prompt = [(5 * i + 2) % tiny_cfg.vocab_size for i in range(57)]
    samp = SamplingParams(temperature=0.0, max_tokens=12)

    ref = LLMEngine(ecfg, model_cfg=tiny_cfg,
                    params=tiny_params).generate(prompt, samp)
    runner = SPPrefillRunner(tiny_cfg, tiny_params, make_mesh(sp=sp))
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids


def test_sp_batched_prefill_matches_single_device(tiny_cfg, tiny_params):
    """Concurrent same-bucket arrivals ride the BATCHED prefill pass
    (B > 1) — the ring adapter keeps batch unsharded, so this pins the
    [B, T/sp] layout end to end, not just the solo case."""
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPPrefillRunner
    from agentic_traffic_testing_tpu.runtime.request import SamplingParams as SP

    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                        max_model_len=128, max_num_seqs=3,
                        prefill_batch_max_len=128)
    prompts = [[(3 * i + j) % tiny_cfg.vocab_size for i in range(29 + j)]
               for j in range(3)]
    samp = SP(temperature=0.0, max_tokens=8, ignore_eos=True)

    def run(runner):
        eng = (LLMEngine(ecfg, model_cfg=tiny_cfg, params=tiny_params)
               if runner is None else
               LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner))
        reqs = [eng.add_request(p, samp) for p in prompts]
        for _ in range(10_000):
            eng.step()
            if all(r.is_finished() for r in reqs):
                break
        return [list(r.generated_ids) for r in reqs]

    want = run(None)
    got = run(SPPrefillRunner(tiny_cfg, tiny_params, make_mesh(sp=2)))
    assert got == want


def test_sp_moe_serving_prefill_matches_single_device():
    """MoE x sp serving (round 4): the GShard dispatch/combine einsums ride
    GSPMD over the T-sharded prefill activations (the training MoE x sp
    step already proves the partitioning); ring attention handles the
    attention site. Token-exact vs the single-device MoE engine."""
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPPrefillRunner

    mcfg = resolve_config("tiny-moe")
    params = init_params(mcfg, jax.random.key(9), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny-moe", dtype="float32", num_blocks=64,
                        max_model_len=128)
    prompt = [(19 * i + 4) % mcfg.vocab_size for i in range(41)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    ref = LLMEngine(ecfg, model_cfg=mcfg, params=params).generate(prompt, samp)
    runner = SPPrefillRunner(mcfg, params, make_mesh(sp=2))
    got = LLMEngine(ecfg, model_cfg=mcfg, runner=runner).generate(prompt, samp)
    assert got.output_ids == ref.output_ids


def test_sptp_moe_int8_serving_matches_single_device():
    """MoE x int8 x (sp x tp): expert weights shard over tp (QTensor specs),
    the GShard einsums partition over sp-sharded prefill activations, ring
    attention handles the attention site — token-exact vs single-device."""
    from agentic_traffic_testing_tpu.models.quant import quantize_params
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPTPRunner

    mcfg = resolve_config("tiny-moe")
    params = init_params(mcfg, jax.random.key(4), dtype=jnp.float32)
    qparams = quantize_params(params)
    ecfg = EngineConfig(model="tiny-moe", dtype="float32", quantization="int8",
                        num_blocks=64, max_model_len=128)
    prompt = [(23 * i + 6) % mcfg.vocab_size for i in range(37)]
    samp = SamplingParams(temperature=0.0, max_tokens=10, ignore_eos=True)

    ref = LLMEngine(ecfg, model_cfg=mcfg, params=qparams).generate(
        prompt, samp)
    runner = SPTPRunner(mcfg, qparams, make_mesh(sp=2, tp=2))
    got = LLMEngine(ecfg, model_cfg=mcfg, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids


@pytest.mark.parametrize("topology", ["tp", "sp", "sptp", "pp"])
@pytest.mark.parametrize("feature", ["fp8kv", "spec"])
def test_feature_x_topology_matches_single_device(tiny_cfg, tiny_params,
                                                  topology, feature):
    """The README composition matrix, executable: fp8 KV pages and n-gram
    speculation each compose with every serving topology token-exactly —
    the features live in the KV pool dtype and the decode scan,
    orthogonal to how prefill/params shard. The pp column (round 5):
    fp8 KV composes (the staged pool is just pages of another dtype);
    speculation REFUSES by design (capacity ADR), and that refusal is the
    matrix cell being pinned."""
    from agentic_traffic_testing_tpu.parallel.pp_runner import PPRunner
    from agentic_traffic_testing_tpu.parallel.sp_runner import (
        SPPrefillRunner,
        SPTPRunner,
    )

    kw = (dict(kv_cache_dtype="fp8") if feature == "fp8kv"
          else dict(speculation="ngram", spec_tokens=3))
    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=64,
                        max_model_len=128, **kw)
    prompt = ([5, 9, 11, 5, 9, 11, 5, 9, 11, 5, 9] * 3 if feature == "spec"
              else [(29 * i + 8) % tiny_cfg.vocab_size for i in range(33)])
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    spec_kw = dict(spec_tokens=3) if feature == "spec" else {}

    if topology == "pp" and feature == "spec":
        with pytest.raises(NotImplementedError, match="speculation"):
            PPRunner(tiny_cfg, tiny_params, make_mesh(pp=2), **spec_kw)
        return
    ref = LLMEngine(ecfg, model_cfg=tiny_cfg,
                    params=tiny_params).generate(prompt, samp)
    if topology == "tp":
        runner = TPRunner(tiny_cfg, tiny_params, make_mesh(tp=2), **spec_kw)
    elif topology == "sp":
        runner = SPPrefillRunner(tiny_cfg, tiny_params, make_mesh(sp=2),
                                 **spec_kw)
    elif topology == "pp":
        runner = PPRunner(tiny_cfg, tiny_params, make_mesh(pp=2))
    else:
        runner = SPTPRunner(tiny_cfg, tiny_params, make_mesh(sp=2, tp=2),
                            **spec_kw)
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids


def test_chunked_and_prefix_caching_under_tp(tiny_cfg, tiny_params):
    """Chunked prefill and prefix caching are engine-level features that
    must survive a TP runner unchanged: chunked output token-exact vs the
    unchunked single-device engine, and a prefix-cache HIT (second
    identical prompt) as exact as the miss."""
    base = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                        max_model_len=256)
    prompt = [(31 * i + 9) % tiny_cfg.vocab_size for i in range(70)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    ref = LLMEngine(base, model_cfg=tiny_cfg,
                    params=tiny_params).generate(prompt, samp)

    ec = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                      max_model_len=256, prefill_chunk_tokens=32)
    got = LLMEngine(ec, model_cfg=tiny_cfg,
                    runner=TPRunner(tiny_cfg, tiny_params,
                                    make_mesh(tp=2))).generate(prompt, samp)
    assert got.output_ids == ref.output_ids

    ep = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                      max_model_len=256, prefix_caching=True)
    eng = LLMEngine(ep, model_cfg=tiny_cfg,
                    runner=TPRunner(tiny_cfg, tiny_params, make_mesh(tp=2)))
    assert eng.generate(prompt, samp).output_ids == ref.output_ids
    assert eng.generate(prompt, samp).output_ids == ref.output_ids  # hit


@pytest.mark.parametrize("pp", [2, 4])
def test_pp_serving_decode_matches_single_device(pp):
    """Round-5 pipeline-parallel SERVING (parallel/pp_runner.py): layer
    stages over pp chips — L/pp weights and L/pp KV pages each — via the
    phase-loop schedule. No contraction is split across chips, so greedy
    output is BIT-identical to the single-chip engine (unlike TP, no
    reduction-order noise to tolerate). Multi-request batch exercises the
    trash-routed writes for inactive phases and padded lanes. pp=4 uses a
    4-layer config (one layer per stage)."""
    import dataclasses

    from agentic_traffic_testing_tpu.parallel.pp_runner import PPRunner

    cfg = dataclasses.replace(resolve_config("tiny"), num_layers=pp)
    params = init_params(cfg, jax.random.key(2), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                        max_model_len=128)
    prompts = [[(13 * i + 7) % cfg.vocab_size for i in range(45)],
               [(7 * i + 3) % cfg.vocab_size for i in range(21)]]
    samp = SamplingParams(temperature=0.0, max_tokens=10, ignore_eos=True)

    ref_eng = LLMEngine(ecfg, model_cfg=cfg, params=params)
    refs = [ref_eng.generate(p, samp) for p in prompts]

    runner = PPRunner(cfg, params, make_mesh(pp=pp))
    eng = LLMEngine(ecfg, model_cfg=cfg, runner=runner)
    for p, r in zip(prompts, refs):
        assert eng.generate(p, samp).output_ids == r.output_ids


def test_pp_serving_moe_and_guards(tiny_params, tiny_cfg):
    """MoE rides the pp stages unchanged (the expert einsums are per-token
    math inside a stage); guards: layer divisibility, quantization and
    speculation refusals, pp < 2."""
    from agentic_traffic_testing_tpu.models.quant import quantize_params
    from agentic_traffic_testing_tpu.parallel.pp_runner import PPRunner

    mcfg = resolve_config("tiny-moe")
    mparams = init_params(mcfg, jax.random.key(6), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny-moe", dtype="float32", num_blocks=64,
                        max_model_len=128)
    prompt = [(19 * i + 5) % mcfg.vocab_size for i in range(23)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    ref = LLMEngine(ecfg, model_cfg=mcfg, params=mparams).generate(
        prompt, samp)
    got = LLMEngine(ecfg, model_cfg=mcfg,
                    runner=PPRunner(mcfg, mparams, make_mesh(pp=2))
                    ).generate(prompt, samp)
    assert got.output_ids == ref.output_ids

    with pytest.raises(ValueError, match="pp axis"):
        PPRunner(tiny_cfg, tiny_params, make_mesh(pp=1))
    with pytest.raises(ValueError, match="divisible"):
        import dataclasses
        PPRunner(dataclasses.replace(tiny_cfg, num_layers=3), tiny_params,
                 make_mesh(pp=2))
    with pytest.raises(NotImplementedError, match="quantization"):
        PPRunner(tiny_cfg, quantize_params(tiny_params, scheme="int8"),
                 make_mesh(pp=2))
    with pytest.raises(NotImplementedError, match="speculation"):
        PPRunner(tiny_cfg, tiny_params, make_mesh(pp=2), spec_tokens=3)


def test_chunk_ring_hybrid_matches_oracle():
    """Op-level pin for the round-5 chunk-ring hybrid: suffix queries
    sharded over sp with a replicated prior segment reproduce plain causal
    attention over [prior ++ suffix] (prior validity < chunk_start, suffix
    positions offset by it) to f32 accumulation noise."""
    from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
    from agentic_traffic_testing_tpu.ops.ring_attention import (
        make_sp_chunk_attention,
    )

    b, c, w, h, kh, hd = 1, 32, 48, 4, 2, 16
    start = 40                       # 40 valid prior slots of 48 gathered
    ks = jax.random.split(jax.random.key(11), 5)
    q = jax.random.normal(ks[0], (b, c, h, hd), jnp.float32)
    k = jax.random.normal(ks[1], (b, c, kh, hd), jnp.float32)
    v = jax.random.normal(ks[2], (b, c, kh, hd), jnp.float32)
    kp = jax.random.normal(ks[3], (b, w, kh, hd), jnp.float32)
    vp = jax.random.normal(ks[4], (b, w, kh, hd), jnp.float32)

    got = make_sp_chunk_attention(make_mesh(sp=2))(
        q, k, v, kp, vp, jnp.int32(start))

    q_pos = start + jnp.arange(c, dtype=jnp.int32)[None]
    kv_pos = jnp.concatenate(
        [jnp.arange(w, dtype=jnp.int32)[None], q_pos], axis=1)
    kv_mask = jnp.concatenate(
        [jnp.arange(w, dtype=jnp.int32)[None] < start,
         jnp.ones((1, c), bool)], axis=1)
    want = causal_attention(
        q, jnp.concatenate([kp, k], axis=1), jnp.concatenate([vp, v], axis=1),
        q_positions=q_pos, kv_positions=kv_pos, kv_valid_mask=kv_mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_prefix_caching_and_chunked_under_sp(tiny_cfg, tiny_params):
    """Round 5 (the last refused sp cell): prefix caching composes with
    sequence-parallel serving via the chunk-ring hybrid — a cache HIT
    prefills only the suffix, sharded over sp, with the cached pages
    seeding each chip's streaming softmax (models/llama.prefill_chunk_impl
    attn_mode='ring_sp') — and deliberate chunked prefill rides the same
    mode. Token-exact vs the unchunked single-device engine, miss and hit."""
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPPrefillRunner

    base = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                        max_model_len=256)
    prompt = [(31 * i + 9) % tiny_cfg.vocab_size for i in range(70)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    ref = LLMEngine(base, model_cfg=tiny_cfg,
                    params=tiny_params).generate(prompt, samp)

    ep = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                      max_model_len=256, prefix_caching=True)
    eng = LLMEngine(ep, model_cfg=tiny_cfg,
                    runner=SPPrefillRunner(tiny_cfg, tiny_params,
                                           make_mesh(sp=2)))
    assert eng.generate(prompt, samp).output_ids == ref.output_ids  # miss
    assert eng.generate(prompt, samp).output_ids == ref.output_ids  # hit

    ec = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                      max_model_len=256, prefill_chunk_tokens=32)
    got = LLMEngine(ec, model_cfg=tiny_cfg,
                    runner=SPPrefillRunner(tiny_cfg, tiny_params,
                                           make_mesh(sp=2))
                    ).generate(prompt, samp)
    assert got.output_ids == ref.output_ids


def test_prefix_caching_under_sptp(tiny_cfg, tiny_params):
    """The chunk-ring hybrid with heads tp-sharded (SPTPRunner): the
    gathered prior pages arrive KH-sharded over tp (the pool is tp-sharded
    there) and the ring shards the suffix over sp — cache hit token-exact
    vs the single-device engine. The deliberate multi-chunk prefill ladder
    (the other refusal this mesh lifted) is pinned token-exact too."""
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPTPRunner

    base = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                        max_model_len=256)
    prompt = [(37 * i + 5) % tiny_cfg.vocab_size for i in range(70)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    ref = LLMEngine(base, model_cfg=tiny_cfg,
                    params=tiny_params).generate(prompt, samp)

    ep = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                      max_model_len=256, prefix_caching=True)
    eng = LLMEngine(ep, model_cfg=tiny_cfg,
                    runner=SPTPRunner(tiny_cfg, tiny_params,
                                      make_mesh(sp=2, tp=2)))
    assert eng.generate(prompt, samp).output_ids == ref.output_ids  # miss
    assert eng.generate(prompt, samp).output_ids == ref.output_ids  # hit

    # Multi-chunk prefill (70 tokens / 32-token chunks = 3 chunks, partial
    # final) through the same ring_sp mode on the sp x tp mesh.
    ec = EngineConfig(model="tiny", dtype="float32", num_blocks=96,
                      max_model_len=256, prefill_chunk_tokens=32)
    got = LLMEngine(ec, model_cfg=tiny_cfg,
                    runner=SPTPRunner(tiny_cfg, tiny_params,
                                      make_mesh(sp=2, tp=2))
                    ).generate(prompt, samp)
    assert got.output_ids == ref.output_ids


def test_sp_shard_dma_decode_matches_gather(tiny_cfg, tiny_params,
                                            monkeypatch):
    """SPPrefillRunner's TPU decode path (round 4): the DMA kernel under
    shard_map over the SIZE-1 tp axis, replicated over sp — interpret mode
    here must reproduce the gather path's greedy decode exactly."""
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPPrefillRunner

    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=64,
                        max_model_len=128)
    prompt = list(range(9, 41))
    samp = SamplingParams(temperature=0.0, max_tokens=4)

    monkeypatch.delenv("ATT_TP_ATTENTION", raising=False)
    ref_runner = SPPrefillRunner(tiny_cfg, tiny_params, make_mesh(sp=2))
    assert ref_runner.attn_mode == "gather"  # CPU default
    ref = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=ref_runner).generate(
        prompt, samp)

    monkeypatch.setenv("ATT_TP_ATTENTION", "shard_dma")
    runner = SPPrefillRunner(tiny_cfg, tiny_params, make_mesh(sp=2))
    assert runner.attn_mode == "shard_dma"
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids


def test_sp_only_int4_serving_matches_single_device(tiny_cfg, tiny_params):
    """int4 x sp-only (round 4): each chip keeps the FULL packed weights
    (QTensor4TP over the size-1 tp axis — standard packing, no repack)
    while prefill tokens shard over sp. Same logical weights as the
    single-chip int4 engine, so greedy output is token-exact."""
    from agentic_traffic_testing_tpu.models.quant import quantize_params
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPPrefillRunner

    qparams = quantize_params(tiny_params, scheme="int4")
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int4",
                        num_blocks=64, max_model_len=128)
    prompt = [(37 * i + 11) % tiny_cfg.vocab_size for i in range(67)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    ref = LLMEngine(ecfg, model_cfg=tiny_cfg,
                    params=qparams).generate(prompt, samp)
    runner = SPPrefillRunner(tiny_cfg, qparams, make_mesh(sp=2))
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids


def test_sp_only_int4_tp_packed_and_moe_serve(tiny_cfg, tiny_params):
    """Round 5: a TP-packed (groups>1) int4 checkpoint SERVES on an
    sp-only mesh without repacking — the replicated wrap propagates the
    packing aux (QTensor4TP.groups) and the global matmul decodes grouped
    layouts per contiguous group (models/quant._dense4) — token-exact vs
    the standard-packed single-chip engine on the same logical weights
    (grouped and ungrouped packing dequantize identically). int4 MoE
    serves on sp too (the matrix's LAST refusal, lifted round 5): expert
    stacks wrap over the size-1 (ep, tp) axes and the expert scan runs
    replicated per sp chip while ring attention keeps the sp win."""
    from agentic_traffic_testing_tpu.models.quant import quantize_params
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPPrefillRunner

    from agentic_traffic_testing_tpu.models.quant import quantize_array

    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int4",
                        num_blocks=64, max_model_len=128)
    prompt = [(11 * i + 2) % tiny_cfg.vocab_size for i in range(35)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)
    # Same logical weights as the tp-packed tree: int4 layer weights plus
    # the int8 lm_head that quantize_params(int4_groups>1) hybridizes to.
    q_ref = quantize_params(tiny_params, scheme="int4")
    q_ref["unembed"] = quantize_array(tiny_params["unembed"])
    ref = LLMEngine(ecfg, model_cfg=tiny_cfg, params=q_ref).generate(
        prompt, samp)

    tp_packed = quantize_params(tiny_params, scheme="int4", int4_groups=2)
    runner = SPPrefillRunner(tiny_cfg, tp_packed, make_mesh(sp=2))
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids

    mcfg = resolve_config("tiny-moe")
    mq = quantize_params(init_params(mcfg, jax.random.key(8),
                                     dtype=jnp.float32), scheme="int4")
    ecfg_m = EngineConfig(model="tiny-moe", dtype="float32",
                          quantization="int4", num_blocks=64,
                          max_model_len=128)
    mprompt = [(19 * i + 4) % mcfg.vocab_size for i in range(41)]
    ref_m = LLMEngine(ecfg_m, model_cfg=mcfg, params=mq).generate(
        mprompt, samp)
    got_m = LLMEngine(ecfg_m, model_cfg=mcfg,
                      runner=SPPrefillRunner(mcfg, mq, make_mesh(sp=2))
                      ).generate(mprompt, samp)
    assert got_m.output_ids == ref_m.output_ids


def test_sp_runner_rejects_trivial_axis(tiny_cfg, tiny_params):
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPPrefillRunner

    with pytest.raises(ValueError, match="sp axis"):
        SPPrefillRunner(tiny_cfg, tiny_params, make_mesh(sp=1))


def test_sptp_runner_guards(tiny_cfg, tiny_params):
    """SPTPRunner refusals that REMAIN after the round-5 chunk-ring hybrid
    lifted the chunked/prefix-caching ones (those cells now have positive
    token-exact tests below): single-axis meshes and ungrouped int4 params
    still fail fast with actionable errors."""
    from agentic_traffic_testing_tpu.models.quant import quantize_params
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPTPRunner

    with pytest.raises(ValueError, match="sp >= 2 AND tp >= 2"):
        SPTPRunner(tiny_cfg, tiny_params, make_mesh(sp=2, tp=1))
    with pytest.raises(ValueError, match="int4 x TP requires grouped"):
        # Ungrouped int4 packing needs the same attestation as plain TP.
        SPTPRunner(tiny_cfg, quantize_params(tiny_params, scheme="int4"),
                   make_mesh(sp=2, tp=2))
    # Chunked prefill + prefix caching on the sp x tp mesh must CONSTRUCT
    # now (the former refusals) — behavior is pinned token-exact by
    # test_prefix_caching_under_sptp.
    runner = SPTPRunner(tiny_cfg, tiny_params, make_mesh(sp=2, tp=2))
    LLMEngine(EngineConfig(model="tiny", dtype="float32", num_blocks=64,
                           max_model_len=256, prefill_chunk_tokens=64,
                           prefix_caching=True),
              model_cfg=tiny_cfg, runner=runner)


def test_sptp_serving_prefill_matches_single_device(tiny_cfg, tiny_params):
    """sp x tp composition (round 4): long-prompt prefill rides ring
    attention over sp WITH heads tp-sharded, params/KV tp-sharded as in
    plain TP, decode unchanged — token-exact vs the single-device engine
    on a (sp=2, tp=2) CPU mesh."""
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPTPRunner

    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=64,
                        max_model_len=128)
    prompt = [(11 * i + 5) % tiny_cfg.vocab_size for i in range(61)]
    samp = SamplingParams(temperature=0.0, max_tokens=10, ignore_eos=True)

    ref = LLMEngine(ecfg, model_cfg=tiny_cfg,
                    params=tiny_params).generate(prompt, samp)
    runner = SPTPRunner(tiny_cfg, tiny_params, make_mesh(sp=2, tp=2))
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids


def test_sptp_int8_serving_prefill_matches_single_device(tiny_cfg, tiny_params):
    """sp x tp x int8: quantized leaves expand their (q, scale) specs over
    the composed mesh exactly as under plain TP."""
    from agentic_traffic_testing_tpu.models.quant import quantize_params
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPTPRunner

    qparams = quantize_params(tiny_params)
    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int8",
                        num_blocks=64, max_model_len=128)
    prompt = [(7 * i + 2) % tiny_cfg.vocab_size for i in range(45)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    ref = LLMEngine(ecfg, model_cfg=tiny_cfg,
                    params=qparams).generate(prompt, samp)
    runner = SPTPRunner(tiny_cfg, qparams, make_mesh(sp=2, tp=2))
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids


@pytest.mark.parametrize("kg", [0, 32])
def test_sptp_int4_serving_matches_single_device(tiny_cfg, tiny_params, kg):
    """sp x tp x int4 (round 4): the QTensor4TP shard_map carries the sp
    axis and shards the PREFILL activation's token dim by shape, so the
    packed-nibble matmul composes with sequence parallelism — token-exact
    vs the single-chip int4 engine on the same logical weights (grouped
    and ungrouped packing dequantize identically; the lm_head hybridizes
    to int8 under TP, mirrored in the reference params). kg=32 adds
    K-group scales: the grouped-scale axis shards with K on row-parallel
    leaves and rides sp activation sharding unchanged — the full
    quantization feature set under the composed mesh."""
    from agentic_traffic_testing_tpu.models.quant import (
        quantize_array,
        quantize_params,
    )
    from agentic_traffic_testing_tpu.parallel.sp_runner import SPTPRunner

    ecfg = EngineConfig(model="tiny", dtype="float32", quantization="int4",
                        int4_k_group=kg, num_blocks=64, max_model_len=128)
    prompt = [(13 * i + 3) % tiny_cfg.vocab_size for i in range(53)]
    samp = SamplingParams(temperature=0.0, max_tokens=8, ignore_eos=True)

    q_ref = quantize_params(tiny_params, scheme="int4", int4_k_group=kg)
    q_ref["unembed"] = quantize_array(tiny_params["unembed"])
    ref = LLMEngine(ecfg, model_cfg=tiny_cfg,
                    params=q_ref).generate(prompt, samp)
    q_tp = quantize_params(tiny_params, scheme="int4", int4_groups=2,
                           int4_k_group=kg)
    runner = SPTPRunner(tiny_cfg, q_tp, make_mesh(sp=2, tp=2), int4_groups=2)
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(
        prompt, samp)
    assert got.output_ids == ref.output_ids


def test_tp_shard_dma_matches_gather(tiny_cfg, tiny_params, monkeypatch):
    """The shard_map-wrapped DMA kernel (TPU default for TP; interpret mode
    here on the CPU mesh) must reproduce the GSPMD gather path's greedy
    decode exactly."""
    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=64,
                        max_model_len=128)
    prompt = list(range(7, 27))
    samp = SamplingParams(temperature=0.0, max_tokens=6)

    monkeypatch.delenv("ATT_TP_ATTENTION", raising=False)
    ref_runner = TPRunner(tiny_cfg, tiny_params, make_mesh(tp=2))
    assert ref_runner.attn_mode == "gather"  # CPU default
    ref = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=ref_runner).generate(prompt, samp)

    monkeypatch.setenv("ATT_TP_ATTENTION", "shard_dma")
    runner = TPRunner(tiny_cfg, tiny_params, make_mesh(tp=2))
    assert runner.attn_mode == "shard_dma"
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(prompt, samp)
    assert got.output_ids == ref.output_ids


def test_tp_shard_dma_speculative(tiny_cfg, tiny_params, monkeypatch):
    """Multi-query verify under shard_map: TP=2 + ngram speculation matches
    the single-device speculative engine."""
    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=64,
                        max_model_len=128, speculation="ngram", spec_tokens=2)
    prompt = [5, 6, 7, 8] * 5
    samp = SamplingParams(temperature=0.0, max_tokens=6, ignore_eos=True)

    ref = LLMEngine(ecfg, model_cfg=tiny_cfg, params=tiny_params).generate(prompt, samp)

    monkeypatch.setenv("ATT_TP_ATTENTION", "shard_dma")
    runner = TPRunner(tiny_cfg, tiny_params, make_mesh(tp=2), spec_tokens=2)
    got = LLMEngine(ecfg, model_cfg=tiny_cfg, runner=runner).generate(prompt, samp)
    assert got.output_ids == ref.output_ids


def test_tp8_70b_shape_engine_decode(monkeypatch):
    """The TP=8 north-star sharding (Llama-3-70B: 64 heads / 8 KV heads over
    8 chips — serving/configs/llama-3-70b-tp8.yaml) exercised shape-faithfully
    on the 8-device CPU mesh with a scaled-down config: 8 KV heads shard to
    ONE kv head per chip, the hardest GQA split. Runs both TP attention
    paths; greedy tokens must match the single-device engine exactly."""
    monkeypatch.delenv("ATT_TP_ATTENTION", raising=False)
    cfg = ModelConfig(
        name="70b-shape", vocab_size=512, hidden_size=128, intermediate_size=256,
        num_layers=2, num_heads=16, num_kv_heads=8, head_dim=8,
    )
    params = init_params(cfg, jax.random.key(1), dtype=jnp.float32)
    ecfg = EngineConfig(model="tiny", dtype="float32", num_blocks=64,
                        max_model_len=128)
    prompt = list(range(3, 23))
    samp = SamplingParams(temperature=0.0, max_tokens=6)

    ref = LLMEngine(ecfg, model_cfg=cfg, params=params).generate(prompt, samp)
    for mode in ("gather", "shard_dma"):
        monkeypatch.setenv("ATT_TP_ATTENTION", mode)
        runner = TPRunner(cfg, params, make_mesh(tp=8))
        got = LLMEngine(ecfg, model_cfg=cfg, runner=runner).generate(prompt, samp)
        assert got.output_ids == ref.output_ids, mode


def test_tp_forward_logits_match(tiny_cfg, tiny_params):
    """Full forward under TP sharding reproduces single-device logits."""
    from agentic_traffic_testing_tpu.parallel.sharding import shard_params

    tokens = jnp.asarray(
        np.random.default_rng(0).integers(0, tiny_cfg.vocab_size, (2, 16)), jnp.int32
    )
    ref = forward_full(tiny_params, tiny_cfg, tokens)
    mesh = make_mesh(tp=2)
    sharded = shard_params(tiny_params, tiny_cfg, mesh)
    out = forward_full(sharded, tiny_cfg, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_train_step_loss_decreases(tiny_cfg):
    mesh = make_mesh(dp=2, sp=2, tp=2)
    opt = optax.adamw(1e-3)
    params, opt_state = init_train_state(tiny_cfg, mesh, opt)
    ts = make_train_step(tiny_cfg, mesh, opt)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, tiny_cfg.vocab_size, (4, 32)), jnp.int32)
    mask = jnp.ones((4, 32), jnp.float32)
    losses = []
    for _ in range(5):
        params, opt_state, loss = ts(params, opt_state, tokens, mask)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


def test_train_step_sharded_matches_unsharded_first_loss(tiny_cfg):
    """First-step loss on the (2,2,2) mesh equals the single-device loss."""
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, tiny_cfg.vocab_size, (4, 32)), jnp.int32)
    mask = jnp.ones((4, 32), jnp.float32)
    opt = optax.sgd(0.0)

    def first_loss(mesh):
        params, opt_state = init_train_state(tiny_cfg, mesh, opt, seed=3)
        ts = make_train_step(tiny_cfg, mesh, opt, remat=False)
        _, _, loss = ts(params, opt_state, tokens, mask)
        return float(loss)

    l_multi = first_loss(make_mesh(dp=2, sp=2, tp=2))
    l_single = first_loss(make_mesh(1, 1, 1, devices=jax.devices()[:1]))
    assert abs(l_multi - l_single) < 1e-4


def test_causal_lm_loss_masking():
    logits = jnp.zeros((1, 4, 8), jnp.float32)
    tokens = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
    full = causal_lm_loss(logits, tokens, jnp.ones((1, 4), jnp.float32))
    # Uniform logits -> loss == log(V) regardless of mask extent.
    np.testing.assert_allclose(float(full), np.log(8.0), rtol=1e-5)


def test_pp_block_budget_sees_layer_sharding():
    """profile_num_blocks must credit PP's layer sharding (round-5 advisor
    finding): each chip holds L/pp layers of every block, so the budget
    scales ~pp x — otherwise the capacity escape hatch deploys at 1/pp of
    the KV capacity the HBM allows."""
    from agentic_traffic_testing_tpu.runtime.kv_cache import (
        profile_num_blocks,
    )

    cfg = resolve_config("tiny")
    free = 1 << 25   # power of two + utilization 1.0: divisions are exact
    base = profile_num_blocks(cfg, 16, free, 1.0, 2)
    pp2 = profile_num_blocks(cfg, 16, free, 1.0, 2, pp_size=2)
    assert base > 0 and pp2 == 2 * base
