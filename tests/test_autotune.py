"""Flash block autotuner (ops/pallas/autotune.py).

Three contracts pinned here:

  1. NUMERICS: every candidate (q_block, kv_block) config the sweep can
     pick produces oracle-exact attention (block sizes only change tiling)
     — interpret-mode parity across the causal and chunked sites.
  2. TABLE: the JSON cache round-trips (write -> reload -> same choice),
     an explicit ATT_FLASH_TUNE=<path> table deterministically pins the
     blocks with NO sweeping, and a corrupt or missing table file degrades
     to the heuristic instead of crashing the trace.
  3. SWEEP (marked slow — tier-1 runs `-m 'not slow'`): warmup mode times
     the candidates once per shape, persists the winner, and never
     re-sweeps a shape it already knows.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
from agentic_traffic_testing_tpu.ops.pallas import autotune
from agentic_traffic_testing_tpu.ops.pallas.chunk_flash import (
    causal_flash_attention,
    chunk_flash_attention,
)


@pytest.fixture(autouse=True)
def _fresh_tuner(monkeypatch):
    """Each test sees a clean tuner registry and the default (off) mode."""
    monkeypatch.delenv("ATT_FLASH_TUNE", raising=False)
    autotune.reset()
    yield
    autotune.reset()


def _mk(shape, seed=0):
    return jax.random.normal(jax.random.key(seed), shape, jnp.float32)


# ------------------------------------------------------ candidate numerics


CAUSAL = dict(t=512, hd=64, qpk=2)


@pytest.mark.parametrize(
    "qb,kb", autotune.candidate_configs(CAUSAL["t"], CAUSAL["t"],
                                        CAUSAL["hd"], CAUSAL["qpk"], 4))
def test_every_causal_candidate_matches_oracle(qb, kb):
    t, hd, qpk = CAUSAL["t"], CAUSAL["hd"], CAUSAL["qpk"]
    kh = 2
    q = _mk((1, t, kh * qpk, hd), 0)
    k = _mk((1, t, kh, hd), 1)
    v = _mk((1, t, kh, hd), 2)
    pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32)[None], (1, t))
    want = causal_attention(q, k, v, q_positions=pos,
                            kv_valid_len=jnp.full((1,), t, jnp.int32))
    got = causal_flash_attention(q, k, v, q_block=qb, kv_block=kb,
                                 interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize(
    "qb,kb", autotune.candidate_configs(128, 256, 64, 2, 4))
def test_every_chunk_candidate_matches_oracle(qb, kb):
    """Chunked site, BATCHED (the round-6 pipelined-prefill grid): prior
    region + gather-tail gap + in-chunk causality, for every candidate."""
    c, prior, hd, kh, qpk = 128, 128, 64, 1, 2
    chunk_start = 96  # gap [96, 128) in the prior region must be masked
    b = 2
    q = _mk((b, c, kh * qpk, hd), 3)
    k = _mk((b, prior + c, kh, hd), 4)
    v = _mk((b, prior + c, kh, hd), 5)
    pos = jnp.broadcast_to(
        chunk_start + jnp.arange(c, dtype=jnp.int32)[None], (b, c))
    kv_pos = jnp.concatenate(
        [jnp.broadcast_to(jnp.arange(prior, dtype=jnp.int32)[None],
                          (b, prior)), pos], axis=1)
    kv_mask = jnp.concatenate(
        [jnp.broadcast_to(jnp.arange(prior)[None] < chunk_start, (b, prior)),
         jnp.ones((b, c), bool)], axis=1)
    want = causal_attention(q, k, v, q_positions=pos, kv_positions=kv_pos,
                            kv_valid_mask=kv_mask)
    got = chunk_flash_attention(q, k, v, jnp.int32(chunk_start),
                                prior_len=prior, q_block=qb, kv_block=kb,
                                interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_candidates_include_heuristic():
    for t, tkv, qpk in ((256, 256, 1), (2048, 2048, 4), (128, 640, 2)):
        cands = autotune.candidate_configs(t, tkv, 64, qpk)
        assert autotune.heuristic_blocks(t, tkv, qpk) in cands
        for qb, kb in cands:
            assert t % qb == 0


# ------------------------------------------------------------ table logic


def test_deterministic_table_pins_blocks(tmp_path, monkeypatch):
    """Tier-1 fast unit: an ATT_FLASH_TUNE=<path> table deterministically
    selects its recorded config — no sweep, no device timing."""
    path = tmp_path / "tune.json"
    key = autotune.shape_key(256, 256, 64, 2, 0)
    path.write_text(json.dumps({autotune._device_key(): {key: [128, 256]}}))
    monkeypatch.setenv("ATT_FLASH_TUNE", str(path))
    autotune.reset()
    got = autotune.resolve_blocks(t=256, tkv=256, hd=64, qpk=2)
    assert got == (128, 256)
    assert got != autotune.heuristic_blocks(256, 256, 2)
    assert autotune.get_tuner().sweeps == 0
    # Unknown shape in the same table: heuristic, still no sweep.
    assert (autotune.resolve_blocks(t=512, tkv=512, hd=64, qpk=2)
            == autotune.heuristic_blocks(512, 512, 2))
    assert autotune.get_tuner().sweeps == 0


def test_cache_roundtrip_same_choice(tmp_path, monkeypatch):
    """write -> reload -> same choice, through the persist/load pair the
    warmup sweep uses."""
    path = str(tmp_path / "roundtrip.json")
    monkeypatch.setenv("ATT_FLASH_TUNE", path)
    autotune.reset()
    tuner = autotune.get_tuner()
    tuner._load()
    key = autotune.shape_key(640, 640, 128, 4, 0)
    tuner._table[key] = (128, 512)
    tuner._persist()
    autotune.reset()  # fresh tuner = fresh process
    assert autotune.resolve_blocks(t=640, tkv=640, hd=128, qpk=4) == (128, 512)


def test_corrupt_and_missing_tables_fall_back(tmp_path, monkeypatch):
    heur = autotune.heuristic_blocks(256, 256, 2)
    # Missing file.
    monkeypatch.setenv("ATT_FLASH_TUNE", str(tmp_path / "nope.json"))
    autotune.reset()
    assert autotune.resolve_blocks(t=256, tkv=256, hd=64, qpk=2) == heur
    # Corrupt JSON.
    bad = tmp_path / "bad.json"
    bad.write_text("{not json at all")
    monkeypatch.setenv("ATT_FLASH_TUNE", str(bad))
    autotune.reset()
    assert autotune.resolve_blocks(t=256, tkv=256, hd=64, qpk=2) == heur
    # Well-formed JSON, mistyped entries (strings, wrong arity, wrong type).
    ugly = tmp_path / "ugly.json"
    key = autotune.shape_key(256, 256, 64, 2, 0)
    ugly.write_text(json.dumps({autotune._device_key(): {
        key: "128x256", "other": [1, 2, 3], "another": None}}))
    monkeypatch.setenv("ATT_FLASH_TUNE", str(ugly))
    autotune.reset()
    assert autotune.resolve_blocks(t=256, tkv=256, hd=64, qpk=2) == heur
    # An entry whose q_block cannot tile t (table from another ladder).
    off = tmp_path / "offladder.json"
    off.write_text(json.dumps({autotune._device_key(): {key: [96, 256]}}))
    monkeypatch.setenv("ATT_FLASH_TUNE", str(off))
    autotune.reset()
    assert autotune.resolve_blocks(t=256, tkv=256, hd=64, qpk=2) == heur
    # A well-typed entry whose kv_block can never fit VMEM: must degrade,
    # not hand Mosaic an un-compilable tile at serving warmup.
    huge = tmp_path / "huge.json"
    huge.write_text(json.dumps({autotune._device_key(): {key: [128, 1048576]}}))
    monkeypatch.setenv("ATT_FLASH_TUNE", str(huge))
    autotune.reset()
    assert autotune.resolve_blocks(t=256, tkv=256, hd=64, qpk=2) == heur


def test_off_mode_is_heuristic_and_sweepless():
    assert (autotune.resolve_blocks(t=2048, tkv=2048, hd=64, qpk=4)
            == autotune.heuristic_blocks(2048, 2048, 4))
    assert autotune.get_tuner().sweeps == 0


# ------------------------------------------------------------- the sweep


@pytest.mark.slow
def test_warmup_sweep_times_persists_and_memoizes(tmp_path, monkeypatch):
    """warmup mode: one sweep per shape, winner persisted to the default
    cache, later tuners (new processes) reload it without sweeping.
    Interpret-mode timing on CPU — slow tier (the real sweep runs on
    device at server warmup)."""
    cache = str(tmp_path / "warm.json")
    monkeypatch.setattr(autotune, "default_cache_path", lambda: cache)
    monkeypatch.setenv("ATT_FLASH_TUNE", "warmup")
    autotune.reset()
    shape = dict(t=128, tkv=128, hd=64, qpk=1)
    got = autotune.resolve_blocks(**shape, interpret=True)
    tuner = autotune.get_tuner()
    assert tuner.sweeps == 1
    assert got in autotune.candidate_configs(128, 128, 64, 1)
    assert os.path.exists(cache)
    data = json.loads(open(cache).read())
    assert data[autotune._device_key()][
        autotune.shape_key(128, 128, 64, 1, 0)] == list(got)
    # Same shape again: memoized, no second sweep.
    assert autotune.resolve_blocks(**shape, interpret=True) == got
    assert tuner.sweeps == 1
    # Fresh process: reloads the persisted table instead of sweeping.
    autotune.reset()
    assert autotune.resolve_blocks(**shape, interpret=True) == got
    assert autotune.get_tuner().sweeps == 0
