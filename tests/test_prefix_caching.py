"""Prefix caching: content-addressed reuse of computed prompt blocks.

Invariants under test: cache hits never change outputs (token-identical to a
cold engine for greedy and seeded sampling), hits skip prompt compute
(num_computed_tokens starts at the shared-block boundary), shared blocks are
refcounted and survive concurrent users, eviction under pool pressure keeps
correctness, and the whole thing composes with chunked prefill. The
reference reaches this capability via vLLM's --enable-prefix-caching; here
it is runtime/block_allocator.PrefixCachingAllocator + the chunk machinery.
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.block_allocator import (
    PrefixCachingAllocator,
)
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.kv_offload import HostKVStore
from agentic_traffic_testing_tpu.runtime.request import SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

CFG = PRESETS["tiny"]
BS = 8


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def make_engine(params, prefix_caching=True, host_store=None, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 256)
    kw.setdefault("block_size", BS)
    kw.setdefault("num_blocks", 96)
    kw.setdefault("max_num_seqs", 4)
    ecfg = EngineConfig(prefix_caching=prefix_caching, **kw)
    runner = ModelRunner(CFG, params, decode_steps=1)
    return LLMEngine(ecfg, model_cfg=CFG, runner=runner,
                     host_store=host_store)


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


# -- allocator unit tests ----------------------------------------------------


def test_allocator_match_and_refcount():
    a = PrefixCachingAllocator(num_blocks=16, block_size=4)
    prompt = list(range(13))  # 3 full blocks + 1 token
    seq, cached = a.match_prefix(prompt)
    assert cached == 0 and seq.blocks == []
    assert seq.ensure_capacity(16)
    a.register_computed(seq, prompt)

    seq2, cached2 = a.match_prefix(prompt)
    assert cached2 == 12 and seq2.blocks == seq.blocks[:3]
    # Shared blocks survive the first owner's release...
    seq.release()
    seq3, cached3 = a.match_prefix(prompt)
    assert cached3 == 12
    # ...and refcounts drain cleanly.
    seq2.release()
    seq3.release()
    assert a.num_used_blocks == 0


def test_allocator_full_prompt_leaves_one_block_uncached():
    """A prompt that is an exact block multiple must still compute >= 1 token."""
    a = PrefixCachingAllocator(num_blocks=16, block_size=4)
    prompt = list(range(12))  # exactly 3 blocks
    seq, _ = a.match_prefix(prompt)
    seq.ensure_capacity(13)
    a.register_computed(seq, prompt)
    _, cached = a.match_prefix(prompt)
    assert cached == 8  # the final block is recomputed for its logits


def test_allocator_shared_block_survives_owner_release():
    """Owner releases while a sharer still decodes: the shared blocks must
    not become reclaimable (regression: implicit owner refcount let a
    sharer's presence push the count to 0 on the owner's release)."""
    a = PrefixCachingAllocator(num_blocks=8, block_size=4)  # 7 usable
    prompt = list(range(9))
    owner, _ = a.match_prefix(prompt)
    assert owner.ensure_capacity(9)
    a.register_computed(owner, prompt)
    sharer, cached = a.match_prefix(prompt)
    assert cached == 8
    shared = set(sharer.blocks)
    owner.release()
    # Exhaust the pool: nothing handed out may alias the sharer's blocks.
    got = a.allocate(a.num_free_blocks)
    assert got is not None and not (set(got) & shared), (got, shared)
    a.free(got)
    sharer.release()
    assert a.num_used_blocks == 0


def test_cache_hit_at_table_edge_is_clamped(params):
    """A cached suffix chunk near max_model_len must not let padded writes
    clamp onto (and destroy) the last real KV block."""
    rng = np.random.default_rng(7)
    prompt = rng.integers(0, CFG.vocab_size, 250).tolist()
    cold = make_engine(params, prefix_caching=False, max_model_len=256,
                       prefill_chunk_tokens=32)
    want = cold.generate(prompt, greedy(4)).generated_ids
    eng = make_engine(params, max_model_len=256, prefill_chunk_tokens=32)
    assert eng.generate(prompt, greedy(4)).generated_ids == want
    # Second run: suffix chunk starts at the cached boundary (248), right at
    # the table edge — the overflow corrupted this case before the clamp.
    assert eng.generate(prompt, greedy(4)).generated_ids == want


def test_allocator_eviction_reclaims_lru():
    a = PrefixCachingAllocator(num_blocks=6, block_size=4)  # 5 usable
    p1, p2 = list(range(9)), list(range(100, 109))
    s1, _ = a.match_prefix(p1)
    s1.ensure_capacity(9)
    a.register_computed(s1, p1)
    s1.release()  # 3 blocks -> 2 indexed+evictable, 1 free
    assert a.num_free_blocks == 5
    s2, _ = a.match_prefix(p2)
    assert s2.ensure_capacity(20)  # needs all 5: evicts the cached blocks
    _, cached = a.match_prefix(p1)
    assert cached == 0, "evicted content must not match"


# -- engine-level tests ------------------------------------------------------


def test_cache_hit_outputs_identical_and_skips_compute(params):
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, CFG.vocab_size, 50).tolist()
    cold_eng = make_engine(params, prefix_caching=False)
    want = cold_eng.generate(prompt, greedy(10)).generated_ids

    eng = make_engine(params)
    first = eng.generate(prompt, greedy(10))
    assert first.generated_ids == want
    second = eng.generate(prompt, greedy(10))
    assert second.generated_ids == want
    # 50 tokens = 6 full blocks (48) cached; suffix of 2 computed.
    assert second.num_computed_tokens == 50
    stats = eng.kv_stats()
    assert stats["prefix_cache_hit_tokens"] == 48, stats


def test_shared_prefix_different_suffixes(params):
    rng = np.random.default_rng(1)
    prefix = rng.integers(0, CFG.vocab_size, 40).tolist()
    tails = [rng.integers(0, CFG.vocab_size, n).tolist() for n in (5, 9)]
    prompts = [prefix + t for t in tails]
    wants = []
    for p in prompts:
        e = make_engine(params, prefix_caching=False)
        wants.append(e.generate(p, greedy(8)).generated_ids)

    eng = make_engine(params)
    got = [eng.generate(p, greedy(8)).generated_ids for p in prompts]
    assert got == wants
    assert eng.kv_stats()["prefix_cache_hit_tokens"] >= 40 - (40 % BS)


def test_seeded_sampling_with_cache_hit(params):
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, CFG.vocab_size, 33).tolist()
    sp = lambda: SamplingParams(max_tokens=9, temperature=0.7, top_k=12, seed=5)
    eng = make_engine(params)
    a = eng.generate(prompt, sp()).generated_ids
    b = eng.generate(prompt, sp()).generated_ids
    assert a == b


def test_cache_hit_composes_with_chunking(params):
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, CFG.vocab_size, 100).tolist()
    cold = make_engine(params, prefix_caching=False)
    want = cold.generate(prompt, greedy(6)).generated_ids
    eng = make_engine(params, prefill_chunk_tokens=32)
    assert eng.generate(prompt, greedy(6)).generated_ids == want
    assert eng.generate(prompt, greedy(6)).generated_ids == want


def test_host_store_lru_and_collision():
    """HostKVStore unit behavior: byte-budget LRU + token-tuple collision
    check (a hash collision must miss, never serve another prompt's KV)."""
    import numpy as np

    k = np.zeros((2, 2, 4, 8), np.float32)  # 1 KiB
    v = np.zeros_like(k)
    store = HostKVStore(5 * k.nbytes)  # room for two (k, v) pairs + change
    assert store.put(1, (1,), k, v) and store.put(2, (2,), k, v)
    assert store.contains(1, (1,)) and not store.contains(1, (9,))
    assert store.get(2, (9,)) is None  # collision -> miss
    store.get(1, (1,))  # refresh: key 2 becomes LRU
    assert store.put(3, (3,), k, v)
    assert not store.contains(2, (2,)), "LRU entry must have been evicted"
    assert store.contains(1, (1,)) and store.contains(3, (3,))
    stats = store.stats()
    assert stats["host_cache_entries"] == 2
    assert stats["host_cache_evicted_blocks"] == 1
    assert stats["host_cache_used_bytes"] <= store.capacity_bytes


def test_host_offload_requires_prefix_caching(params):
    with pytest.raises(ValueError, match="prefix_caching"):
        EngineConfig(model="tiny", host_cache_gb=1.0)
    with pytest.raises(ValueError, match="prefix_caching"):
        make_engine(params, prefix_caching=False,
                    host_store=HostKVStore(1 << 20))


def test_evict_restore_outputs_identical(params):
    """The tentpole invariant: a prefix evicted under capacity pressure and
    restored from the host tier produces completions byte-identical to a
    cold recompute — greedy AND seeded sampling."""
    rng = np.random.default_rng(5)
    prompt = rng.integers(0, CFG.vocab_size, 40).tolist()
    pressure = [rng.integers(0, CFG.vocab_size, 120).tolist()
                for _ in range(3)]
    seeded = lambda: SamplingParams(max_tokens=9, temperature=0.7, top_k=12,
                                    seed=5)

    cold = make_engine(params, prefix_caching=False, num_blocks=24)
    want_greedy = cold.generate(prompt, greedy(8)).generated_ids
    want_seeded = cold.generate(prompt, seeded()).generated_ids

    store = HostKVStore(64 << 20)
    eng = make_engine(params, num_blocks=24, host_store=store)
    assert eng.generate(prompt, greedy(8)).generated_ids == want_greedy
    for p in pressure:  # 120-token prompts over a 23-block pool: reclaim
        eng.generate(p, greedy(8))
    assert len(store) > 0, "eviction must have spilled blocks to host"
    assert eng.allocator.probe_prefix(prompt) == 0, (
        "device tier must have dropped the prefix")
    restored = eng.generate(prompt, greedy(8))
    assert restored.generated_ids == want_greedy
    stats = eng.kv_stats()
    assert stats["host_cache_hit_tokens"] >= 32, stats
    assert stats["host_cache_restore_bytes"] > 0, stats
    # Restored blocks are re-indexed device-side: the next arrival is a
    # pure device hit, no further restore traffic.
    bytes_before = stats["host_cache_restore_bytes"]
    assert eng.generate(prompt, greedy(8)).generated_ids == want_greedy
    assert eng.kv_stats()["host_cache_restore_bytes"] == bytes_before
    # Seeded sampling across another evict/restore cycle.
    for p in pressure:
        eng.generate(p, greedy(8))
    assert eng.generate(prompt, seeded()).generated_ids == want_seeded


def test_evict_restore_int8_pages_byte_identity(params):
    """Round-10 satellite: the host tier saves/restores scaled int8 pages
    + their fp32 scales RAW (no bf16 round trip) — entries carry int8
    pages and scale pairs, restored completions are byte-identical to the
    cold recompute, and the restored pool bytes match the pre-eviction
    pages exactly."""
    rng = np.random.default_rng(15)
    prompt = rng.integers(0, CFG.vocab_size, 40).tolist()
    pressure = [rng.integers(0, CFG.vocab_size, 120).tolist()
                for _ in range(3)]

    cold = make_engine(params, prefix_caching=False, num_blocks=24,
                       kv_cache_dtype="int8")
    want = cold.generate(prompt, greedy(8)).generated_ids

    store = HostKVStore(64 << 20)
    eng = make_engine(params, num_blocks=24, host_store=store,
                      kv_cache_dtype="int8")
    assert eng.generate(prompt, greedy(8)).generated_ids == want
    for p in pressure:
        eng.generate(p, greedy(8))
    assert len(store) > 0, "eviction must have spilled blocks to host"
    entry = next(iter(store._entries.values()))
    assert entry.k.dtype == np.int8 and entry.v.dtype == np.int8
    assert entry.k_scale is not None and entry.k_scale.dtype == np.float32
    assert entry.k_scale.shape == (CFG.num_layers, CFG.num_kv_heads)
    assert eng.allocator.probe_prefix(prompt) == 0
    restored = eng.generate(prompt, greedy(8))
    assert restored.generated_ids == want
    stats = eng.kv_stats()
    assert stats["host_cache_hit_tokens"] >= 32, stats
    assert stats["host_cache_restore_bytes"] > 0, stats


def test_host_store_shared_across_replicas(params):
    """One host store behind a 2-replica pool: a prefix computed (then
    evicted) on replica 0 is host-restored on replica 1 — the cross-replica
    sharing the shared-nothing device tiers cannot do."""
    from agentic_traffic_testing_tpu.serving.replica_pool import EnginePool

    rng = np.random.default_rng(6)
    prompt = rng.integers(0, CFG.vocab_size, 40).tolist()
    pressure = [rng.integers(0, CFG.vocab_size, 120).tolist()
                for _ in range(3)]

    cold = make_engine(params, prefix_caching=False, num_blocks=24)
    want = cold.generate(prompt, greedy(8)).generated_ids

    store = HostKVStore(64 << 20)
    e0 = make_engine(params, num_blocks=24, host_store=store)
    e1 = make_engine(params, num_blocks=24, host_store=store)
    pool = EnginePool([e0, e1], policy="round_robin")

    assert e0.generate(prompt, greedy(8)).generated_ids == want
    for p in pressure:  # evict on replica 0 -> spill to the shared store
        e0.generate(p, greedy(8))
    assert len(store) > 0
    assert e1.allocator.probe_prefix(prompt) == 0  # replica 1 never saw it
    r1 = e1.generate(prompt, greedy(8))
    assert r1.generated_ids == want
    s1 = e1.kv_stats()
    assert s1["host_cache_hit_tokens"] >= 32, s1
    # Pool aggregation: per-replica counters sum, store-level gauges are
    # reported once (the ONE shared store, not N of them).
    agg = pool.kv_stats()
    assert agg["host_cache_hit_tokens"] == (
        e0.kv_stats()["host_cache_hit_tokens"] + s1["host_cache_hit_tokens"])
    assert agg["host_cache_capacity_bytes"] == store.capacity_bytes
    assert agg["host_cache_used_bytes"] == store.stats()["host_cache_used_bytes"]


def test_eviction_under_pressure_keeps_outputs(params):
    """A pool too small to retain caches must still produce exact outputs."""
    rng = np.random.default_rng(4)
    prompts = [rng.integers(0, CFG.vocab_size, 40).tolist() for _ in range(4)]
    wants = []
    for p in prompts:
        e = make_engine(params, prefix_caching=False, num_blocks=24)
        wants.append(e.generate(p, greedy(6)).generated_ids)
    eng = make_engine(params, num_blocks=24)
    for _ in range(2):  # second round re-runs against whatever cache survived
        got = [eng.generate(p, greedy(6)).generated_ids for p in prompts]
        assert got == wants
    stats = eng.kv_stats()
    assert stats["num_running"] == 0 and stats["num_waiting"] == 0
