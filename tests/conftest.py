"""Test harness config: force JAX onto a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; sharding/collective tests run on
`xla_force_host_platform_device_count=8` CPU devices, per the multi-chip test
strategy in SURVEY.md §4. Must run before the first `import jax` in any test.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the outer env pins the axon TPU tunnel
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("HF_HUB_OFFLINE", "1")
os.environ.setdefault("TRANSFORMERS_OFFLINE", "1")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Undo the axon sitecustomize's platform pin before any backend init (and
# strip the plugin env from test subprocesses) — shared guard, see
# agentic_traffic_testing_tpu/platform_guard.py.
from agentic_traffic_testing_tpu.platform_guard import (  # noqa: E402
    force_cpu_if_requested,
)

force_cpu_if_requested()


def pytest_terminal_summary(terminalreporter, exitstatus, config):
    """Surface the test-tier split: a direct run of a full-marked module
    with the default `-m "not full"` addopts deselects everything silently
    (pytest.ini) — tell the developer how to opt in."""
    n = len(terminalreporter.stats.get("deselected", []))
    if n and config.option.markexpr == "not full":
        terminalreporter.write_line(
            f"[tiers] {n} heavyweight tests deselected by the default "
            f"'-m \"not full\"' tier — run with -m \"full or not full\" "
            f"for the full suite (pytest.ini)")
