"""Tools layer: MCP stdio servers through the real client, tool DB, proxy.

The MCP tests drive all three stdio servers through MCPClientManager over
real subprocess pipes — the analog of the reference's smoke script
(reference: scripts/experiment/test_mcp_servers.py:23-63) promoted to pytest.
"""

import asyncio
import json
import os

import pytest
from aiohttp import ClientSession, web

from agentic_traffic_testing_tpu.agents.common.mcp_client import MCPClientManager
from agentic_traffic_testing_tpu.tools.mcp_rpc import MCPToolServer
from agentic_traffic_testing_tpu.tools.mcp_tool_db.server import (
    ToolDBServer,
    deterministic_record,
)
from agentic_traffic_testing_tpu.tools.mcp_universe.openai_proxy import (
    OpenAIProxy,
    flatten_messages,
)


# ------------------------------------------------------------------ mcp_rpc


def test_mcp_server_dispatch_inline():
    srv = MCPToolServer("t")

    @srv.tool("add")
    def add(a: int, b: int) -> dict:
        return {"sum": a + b}

    @srv.resource("t://r", "res")
    def res() -> str:
        return "hello"

    init = srv.handle({"jsonrpc": "2.0", "id": 1, "method": "initialize",
                       "params": {}})
    assert init["result"]["serverInfo"]["name"] == "t"
    assert srv.handle({"jsonrpc": "2.0", "method":
                       "notifications/initialized"}) is None
    tools = srv.handle({"jsonrpc": "2.0", "id": 2, "method": "tools/list"})
    spec = tools["result"]["tools"][0]
    assert spec["name"] == "add"
    assert spec["inputSchema"]["required"] == ["a", "b"]
    call = srv.handle({"jsonrpc": "2.0", "id": 3, "method": "tools/call",
                       "params": {"name": "add", "arguments": {"a": 2, "b": 3}}})
    assert json.loads(call["result"]["content"][0]["text"]) == {"sum": 5}
    bad = srv.handle({"jsonrpc": "2.0", "id": 4, "method": "tools/call",
                      "params": {"name": "add", "arguments": {"a": 2}}})
    assert bad["result"]["isError"] is True
    read = srv.handle({"jsonrpc": "2.0", "id": 5, "method": "resources/read",
                       "params": {"uri": "t://r"}})
    assert read["result"]["contents"][0]["text"] == "hello"
    missing = srv.handle({"jsonrpc": "2.0", "id": 6, "method": "nope"})
    assert missing["error"]["code"] == -32601


def test_mcp_servers_over_stdio(tmp_path, monkeypatch):
    """All three tool servers, through real subprocess pipes."""
    monkeypatch.setenv("TELEMETRY_LOG_DIR", str(tmp_path))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")

    async def run():
        mgr = MCPClientManager()
        await mgr.connect_all()
        try:
            tools = await mgr.list_tools()
            assert set(tools) == {"coding", "finance", "maps"}
            assert {t["name"] for t in tools["coding"]} == {
                "execute_python_code", "analyze_code_complexity"}

            out = await mgr.call_tool("coding", "execute_python_code",
                                      {"code": "print(6*7)"})
            assert json.loads(out)["stdout"].strip() == "42"

            out = await mgr.call_tool("finance", "get_stock_price",
                                      {"symbol": "acme"})
            quote = json.loads(out)
            assert quote["symbol"] == "ACME" and quote["synthetic"]
            assert abs(quote["price"] - 184.20) / 184.20 <= 0.021

            out = await mgr.call_tool(
                "maps", "calculate_distance",
                {"origin": "madrid", "destination": "paris"})
            dist = json.loads(out)["distance_km"]
            assert 1000 < dist < 1100  # great-circle MAD-PAR ~1054 km

            cat = await mgr.read_resource("maps", "maps://catalog")
            assert "madrid" in cat
        finally:
            await mgr.close_all()

    asyncio.run(run())


# ------------------------------------------------------------------ tool db


def test_tool_db_deterministic(tmp_path, monkeypatch):
    monkeypatch.setenv("TELEMETRY_LOG_DIR", str(tmp_path))
    assert deterministic_record("q1") == deterministic_record("q1")
    assert deterministic_record("q1") != deterministic_record("q2")

    async def run():
        runner = web.AppRunner(ToolDBServer().build_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        try:
            async with ClientSession() as http:
                async with http.post(f"http://127.0.0.1:{port}/query",
                                     json={"query": "select x"},
                                     headers={"X-Task-ID": "t9"}) as resp:
                    assert resp.status == 200
                    data = await resp.json()
            assert data["result"]["row_count"] == 3
            log = os.path.join(str(tmp_path), "local_mcp_tool_db.log")
            events = [json.loads(l)["event_type"] for l in open(log)]
            assert events[-2:] == ["tool_request", "tool_response"]
        finally:
            await runner.cleanup()

    asyncio.run(run())


# ------------------------------------------------------------------- proxy


def test_flatten_messages():
    prompt = flatten_messages([
        {"role": "system", "content": "be brief"},
        {"role": "user", "content": [{"type": "text", "text": "hi"}]},
    ])
    assert prompt == "[SYSTEM]\nbe brief\n\n[USER]\nhi"


def test_openai_proxy_end_to_end(tmp_path):
    async def run():
        async def fake_chat(request: web.Request) -> web.Response:
            body = await request.json()
            assert body.get("skip_chat_template") is True
            return web.json_response({
                "output": "proxied!",
                "meta": {"prompt_tokens": 7, "completion_tokens": 2,
                         "total_tokens": 9},
            })

        llm_app = web.Application()
        llm_app.router.add_post("/chat", fake_chat)
        llm_runner = web.AppRunner(llm_app)
        await llm_runner.setup()
        llm_site = web.TCPSite(llm_runner, "127.0.0.1", 0)
        await llm_site.start()
        llm_port = llm_runner.addresses[0][1]

        proxy = OpenAIProxy(backend_url=f"http://127.0.0.1:{llm_port}/chat")
        runner = web.AppRunner(proxy.build_app())
        await runner.setup()
        site = web.TCPSite(runner, "127.0.0.1", 0)
        await site.start()
        port = runner.addresses[0][1]
        try:
            async with ClientSession() as http:
                async with http.post(
                        f"http://127.0.0.1:{port}/v1/chat/completions",
                        json={"model": "m", "max_tokens": 16,
                              "messages": [{"role": "user", "content": "hi"}]},
                ) as resp:
                    assert resp.status == 200
                    data = await resp.json()
            assert data["object"] == "chat.completion"
            assert data["choices"][0]["message"]["content"] == "proxied!"
            assert data["usage"]["total_tokens"] == 9
        finally:
            await runner.cleanup()
            await llm_runner.cleanup()

    asyncio.run(run())
