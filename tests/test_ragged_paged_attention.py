"""Ragged paged-attention kernel vs the jnp oracle, plus the every-mode
trace smoke.

Two jobs:
  * Parity for the NEW ragged kernel (ops/pallas/ragged_paged_attention):
    mixed decode/prefill-chunk rows in one grid, interpret mode on CPU,
    against the grouped gather+causal_attention oracle.
  * A trace-smoke test that BUILDS every ATT_TPU_ATTENTION kernel mode in
    interpret mode and checks parity vs the jnp oracle. The dma3
    missing-scratch bug (kernel unpacked 7 scratch refs, scratch_shapes
    declared 6) crashed at TRACE time — a whole mode could ship broken
    without any tier-1 test noticing until hardware. This class of bug
    must fail here, in the default tier, not on a v5e.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.ops.attention_backend import (
    paged_decode_attention,
)
from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
    paged_attention_decode_dma,
    paged_attention_decode_dma2,
    paged_attention_decode_dma3,
)
from agentic_traffic_testing_tpu.ops.pallas.ragged_paged_attention import (
    ragged_paged_attention,
    ragged_paged_attention_ref,
)
from agentic_traffic_testing_tpu.runtime.kv_cache import TRASH_BLOCK, gather_kv


def _ragged_case(rng, q_lens, positions, *, h=4, kh=2, hd=64, bs=4,
                 num_blocks=64, width=16, dtype=jnp.float32):
    t = sum(q_lens)
    q = jnp.asarray(rng.standard_normal((t, h, hd)), dtype)
    kp = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)), dtype)
    vp = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)), dtype)
    bt = np.full((len(q_lens), width), TRASH_BLOCK, np.int32)
    nxt = 1
    for r, (ln, p0) in enumerate(zip(q_lens, positions)):
        n = -(-(p0 + ln) // bs)
        bt[r, :n] = np.arange(nxt, nxt + n)
        nxt += n
    assert nxt <= num_blocks
    return q, kp, vp, jnp.asarray(bt), jnp.asarray(positions, jnp.int32)


# -- ragged kernel parity ---------------------------------------------------


@pytest.mark.parametrize(
    "q_lens,positions",
    [
        # decode-only (uniform 1-token rows)
        ((1, 1, 1), (5, 0, 12)),
        # the hybrid shape: decode rows + one chunk row
        ((1, 1, 1, 13), (6, 0, 14, 8)),
        # chunk starting at position 0 (fresh prompt's first chunk)
        ((1, 16), (3, 0)),
        # two chunks of different lengths, no decode rows
        ((9, 5), (4, 0)),
    ],
)
def test_ragged_kernel_matches_oracle(q_lens, positions):
    rng = np.random.default_rng(42)
    q, kp, vp, bt, pos = _ragged_case(rng, q_lens, positions)
    got = ragged_paged_attention(q, kp, vp, bt, pos, q_lens, interpret=True)
    want = ragged_paged_attention_ref(q, kp, vp, bt, pos, q_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ragged_oracle_matches_causal_attention():
    """The oracle itself against a hand-built causal_attention per row —
    so kernel parity isn't circular through a buggy oracle."""
    rng = np.random.default_rng(3)
    q_lens, positions = (1, 6), (7, 2)
    q, kp, vp, bt, pos = _ragged_case(rng, q_lens, positions)
    want = ragged_paged_attention_ref(q, kp, vp, bt, pos, q_lens)
    start = 0
    for r, ln in enumerate(q_lens):
        k_all = gather_kv(kp, bt[r:r + 1])
        v_all = gather_kv(vp, bt[r:r + 1])
        qpos = pos[r] + jnp.arange(ln, dtype=jnp.int32)[None]
        row = causal_attention(
            q[start:start + ln][None], k_all, v_all,
            q_positions=qpos, kv_valid_len=pos[r:r + 1] + ln)
        np.testing.assert_allclose(
            np.asarray(want[start:start + ln]), np.asarray(row[0]),
            atol=2e-5, rtol=2e-5)
        start += ln


def test_ragged_kernel_stacked_padded_pool():
    """The serving layout: stacked [L, ...] pool, lane-padded pages, layer
    scalar — exactly what the hybrid step passes from the decode scan."""
    rng = np.random.default_rng(11)
    q_lens, positions = ((1, 1, 9)), (5, 0, 4)
    q, kp, vp, bt, pos = _ragged_case(rng, q_lens, positions, num_blocks=32)
    L, hdp, hd = 3, 128, q.shape[-1]
    kh, nb, bs = kp.shape[0], kp.shape[1], kp.shape[2]
    kp5 = jnp.zeros((L, kh, nb, bs, hdp), kp.dtype)
    vp5 = jnp.zeros((L, kh, nb, bs, hdp), vp.dtype)
    kp5 = kp5.at[1, ..., :hd].set(kp).at[1, ..., hd:].set(99.0)
    vp5 = vp5.at[1, ..., :hd].set(vp).at[1, ..., hd:].set(99.0)
    got = ragged_paged_attention(q, kp5, vp5, bt, pos, q_lens,
                                 layer=jnp.int32(1), interpret=True)
    want = ragged_paged_attention_ref(q, kp, vp, bt, pos, q_lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=2e-5)


def test_ragged_kernel_bf16():
    rng = np.random.default_rng(7)
    q_lens, positions = (1, 1, 8), (11, 3, 0)
    q, kp, vp, bt, pos = _ragged_case(rng, q_lens, positions, h=8, kh=2,
                                      bs=8, dtype=jnp.bfloat16)
    got = ragged_paged_attention(q, kp, vp, bt, pos, q_lens, interpret=True)
    want = ragged_paged_attention_ref(q, kp, vp, bt, pos, q_lens)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=2e-2, rtol=2e-2)


def test_ragged_kernel_output_is_finite_with_dead_row():
    """A trash-table 1-token row (the scheduler's dead-lane shape) must
    produce finite garbage — padded q-block rows included."""
    rng = np.random.default_rng(5)
    q_lens, positions = (1, 5), (0, 2)
    q, kp, vp, bt, pos = _ragged_case(rng, q_lens, positions)
    bt = bt.at[0].set(TRASH_BLOCK)
    got = ragged_paged_attention(q, kp, vp, bt, pos, q_lens, interpret=True)
    assert np.isfinite(np.asarray(got)).all()


# -- every-mode trace smoke -------------------------------------------------

_DIRECT_KERNELS = {
    "dma": paged_attention_decode_dma,
    "dma2": paged_attention_decode_dma2,
    "dma3": paged_attention_decode_dma3,
}


@pytest.mark.parametrize(
    "mode", ["gather", "interpret", "dma", "dma2", "dma3", "ragged"])
@pytest.mark.parametrize("s", [1, 3])
def test_every_mode_traces_and_matches_oracle(mode, s):
    """Build EVERY decode-attention mode on the decode (S=1) and verify
    (S>1) shapes and assert parity vs the gather oracle. Pallas kernels
    run in interpret mode; trace-time breakage (scratch_shapes vs kernel
    unpack mismatches, BlockSpec arity bugs, version drift in
    CompilerParams) fails HERE instead of on hardware."""
    rng = np.random.default_rng(9)
    b, h, kh, hd, bs = 2, 4, 2, 64, 4
    ctx = [6, 11]
    q = jnp.asarray(rng.standard_normal((b, s, h, hd)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((kh, 16, bs, hd)), jnp.float32)
    vp = jnp.asarray(rng.standard_normal((kh, 16, bs, hd)), jnp.float32)
    bt = np.full((b, 8), TRASH_BLOCK, np.int32)
    nxt = 1
    for i, ln in enumerate(ctx):
        n = -(-(ln + s - 1) // bs)
        bt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
    bt = jnp.asarray(bt)
    cl = jnp.asarray(ctx, jnp.int32)
    positions = cl - 1

    if mode in _DIRECT_KERNELS:
        got = _DIRECT_KERNELS[mode](
            q[:, 0] if s == 1 else q, kp, vp, bt, cl, interpret=True)
        if s == 1:
            got = got[:, None]
    else:
        got = paged_decode_attention(q, kp, vp, bt, positions, mode=mode)
    want = paged_decode_attention(q, kp, vp, bt, positions, mode="gather")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-4)
