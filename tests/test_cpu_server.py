"""Contract tests for the CPU fallback server (hf_cpu_server analog).

Verifies the no-accelerator drop-in speaks the same `/chat` JSON contract as
the main TPU backend (SURVEY.md §2.1): request field aliases, meta block,
health endpoints, and error shapes — using the offline tiny model.
"""

import json
import threading
import urllib.request
from http.server import ThreadingHTTPServer

import pytest

from agentic_traffic_testing_tpu.serving.cpu_server import CPUFallbackHandler


@pytest.fixture(scope="module")
def base_url():
    server = ThreadingHTTPServer(("127.0.0.1", 0), CPUFallbackHandler)
    t = threading.Thread(target=server.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{server.server_address[1]}"
    server.shutdown()


def post(url, payload, headers=None):
    req = urllib.request.Request(
        url, json.dumps(payload).encode(),
        {"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_health_endpoints(base_url):
    for path in ("/health", "/ready", "/live"):
        with urllib.request.urlopen(base_url + path, timeout=10) as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["status"] == "ok"


def test_chat_contract(base_url):
    status, body = post(base_url + "/chat", {"prompt": "Hello", "max_tokens": 4})
    assert status == 200
    assert isinstance(body["output"], str)
    meta = body["meta"]
    for key in ("request_id", "latency_ms", "queue_wait_s", "prompt_tokens",
                "completion_tokens", "total_tokens", "otel"):
        assert key in meta
    assert meta["total_tokens"] == meta["prompt_tokens"] + meta["completion_tokens"]
    assert meta["completion_tokens"] <= 4 + 1


def test_input_alias_and_request_id(base_url):
    status, body = post(
        base_url + "/generate", {"input": "hi", "max_tokens": 2},
        headers={"X-Request-ID": "req-xyz"},
    )
    assert status == 200
    assert body["meta"]["request_id"] == "req-xyz"


def test_error_shapes(base_url):
    status, body = post(base_url + "/chat", {"max_tokens": 2})
    assert status == 400 and "error" in body
    status, _ = post(base_url + "/nope", {"prompt": "x"})
    assert status == 404
    req = urllib.request.Request(
        base_url + "/chat", b"{not json", {"Content-Type": "application/json"}
    )
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 400


def test_deterministic_greedy(base_url):
    _, a = post(base_url + "/chat", {"prompt": "abc", "max_tokens": 6})
    _, b = post(base_url + "/chat", {"prompt": "abc", "max_tokens": 6})
    assert a["output"] == b["output"]


def test_num_replicas_round_robin(monkeypatch):
    """LLM_NUM_REPLICAS on the CPU fallback: N independent tiny pipelines
    rotated per call (TPU EnginePool parity, trivially)."""
    import agentic_traffic_testing_tpu.serving.cpu_server as cs

    monkeypatch.setattr(cs, "_pipes", [])
    monkeypatch.setenv("LLM_NUM_REPLICAS", "2")
    monkeypatch.setenv("LLM_MODEL", "tiny")
    p1, p2, p3 = cs.get_pipeline(), cs.get_pipeline(), cs.get_pipeline()
    assert len(cs._pipes) == 2
    assert p1 is not p2
    assert p3 is p1  # rotation wraps


def test_num_replicas_rejects_hf_model_at_startup(monkeypatch):
    """Replicas x real HF checkpoint refuse LOUDLY when the pipelines are
    built (run() builds them eagerly at startup) — never a mid-request
    500 from an N-fold model load."""
    import agentic_traffic_testing_tpu.serving.cpu_server as cs

    monkeypatch.setattr(cs, "_pipes", [])
    monkeypatch.setenv("LLM_NUM_REPLICAS", "2")
    monkeypatch.setenv("LLM_MODEL", "some-org/some-model")
    with pytest.raises(RuntimeError, match="LLM_NUM_REPLICAS"):
        cs.get_pipeline()
    monkeypatch.setenv("LLM_NUM_REPLICAS", "0")
    with pytest.raises(RuntimeError, match=">= 1"):
        cs._num_replicas()


def test_pipeline_build_never_holds_lock(monkeypatch):
    """Round-10 lock-discipline fix (statics thread-blocking-under-lock):
    the pipeline build — an HF checkpoint download on real models —
    happens OUTSIDE _pipe_lock, so concurrent handler threads are never
    serialized behind one cold-start build."""
    import agentic_traffic_testing_tpu.serving.cpu_server as cs

    monkeypatch.setattr(cs, "_pipes", [])
    monkeypatch.setenv("LLM_NUM_REPLICAS", "1")
    monkeypatch.setenv("LLM_MODEL", "tiny")
    built = []

    def fake_build():
        assert not cs._pipe_lock.locked(), "pipeline built under _pipe_lock"
        built.append(object())
        return built[-1]

    monkeypatch.setattr(cs, "_build_tiny", fake_build)
    p = cs.get_pipeline()
    assert p is built[0] and len(cs._pipes) == 1


def test_pipeline_build_race_builds_exactly_once(monkeypatch):
    """Threads racing the first request serialize on _build_lock: exactly
    ONE build runs (losers wait, re-check the registry, and reuse it) —
    no N-fold model loads on a cold start, and no double install."""
    import threading as th
    import time as time_mod

    import agentic_traffic_testing_tpu.serving.cpu_server as cs

    monkeypatch.setattr(cs, "_pipes", [])
    monkeypatch.setenv("LLM_NUM_REPLICAS", "1")
    monkeypatch.setenv("LLM_MODEL", "tiny")
    calls = []

    def fake_build():
        calls.append(th.current_thread().name)
        time_mod.sleep(0.2)   # wide window for the racers to pile up
        return object()

    monkeypatch.setattr(cs, "_build_tiny", fake_build)
    out = []
    ts = [th.Thread(target=lambda: out.append(cs.get_pipeline()))
          for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(10)
    assert len(calls) == 1          # one build, not one per racer
    assert len(cs._pipes) == 1
    assert all(p is cs._pipes[0] for p in out)
