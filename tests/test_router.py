"""Replica router policies + EnginePool end-to-end (data-parallel serving).

Two layers, matching the feature's structure:

  * Pure host logic (no engines, no jax dispatch): policy scoring,
    consistent-hash stability under membership change, saturation
    fallback — driven through stub engines exposing exactly the lock-free
    snapshot surface LLMEngine exports (load_snapshot /
    probe_prefix_tokens / chain_keys_for).
  * 2-replica EnginePool over real tiny engines on the conftest CPU mesh:
    prefix_affinity must beat round_robin on aggregate
    prefix_cache_hit_tokens for the fan-out workload, a mid-stream abort
    on one replica must leave sibling streams on BOTH replicas flushing
    and finishing exactly, and a 1-replica pool must be token-identical
    to the bare engine.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import FinishReason, SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner
from agentic_traffic_testing_tpu.serving.replica_pool import EnginePool
from agentic_traffic_testing_tpu.serving.router import (
    LeastLoadedRouter,
    PrefixAffinityRouter,
    RoundRobinRouter,
    make_router,
    prefix_route_key,
    rendezvous_pick,
)

CFG = PRESETS["tiny"]
NUM_REPLICAS = 2

# Pool tests never request more replicas than the (virtual) device mesh
# offers: on an exotic host with fewer devices, skip with a clear message
# instead of crashing in device/mesh construction.
require_devices = pytest.mark.skipif(
    len(jax.devices()) < NUM_REPLICAS,
    reason=f"pool tests need >= {NUM_REPLICAS} (virtual) devices, "
           f"have {len(jax.devices())} — check conftest's "
           f"xla_force_host_platform_device_count")


# ------------------------------------------------------- policy unit tests


class StubEngine:
    """The router-facing engine surface, as plain host data."""

    def __init__(self, waiting=0, running=0, max_num_seqs=4, hit_tokens=0,
                 block_size=8):
        self.waiting = waiting
        self.running = running
        self.max_num_seqs = max_num_seqs
        self.hit_tokens = hit_tokens
        self.block_size = block_size

    def load_snapshot(self):
        return {
            "num_waiting": self.waiting,
            "num_running": self.running,
            "inflight_dispatches": 0,
            "free_blocks": 64,
            "max_num_seqs": self.max_num_seqs,
            "block_size": self.block_size,
        }

    def chain_keys_for(self, prompt_ids):
        return None

    def probe_prefix_tokens(self, prompt_ids, keys=None):
        return self.hit_tokens


PROMPT = list(range(100, 132))


def test_round_robin_rotates():
    r = RoundRobinRouter([StubEngine(), StubEngine(), StubEngine()])
    assert [r.select(PROMPT) for _ in range(6)] == [0, 1, 2, 0, 1, 2]


def test_least_loaded_picks_min_queue_depth():
    r = LeastLoadedRouter([StubEngine(waiting=2, running=2),
                           StubEngine(waiting=0, running=1)])
    assert r.select(PROMPT) == 1
    # Equal loads break to the lowest index (deterministic).
    r = LeastLoadedRouter([StubEngine(running=1), StubEngine(running=1)])
    assert r.select(PROMPT) == 0


def test_prefix_affinity_deepest_hit_wins():
    r = PrefixAffinityRouter([StubEngine(hit_tokens=16),
                              StubEngine(hit_tokens=48),
                              StubEngine(hit_tokens=0)])
    assert r.select(PROMPT) == 1


def test_prefix_affinity_equal_hits_break_on_load():
    r = PrefixAffinityRouter([StubEngine(hit_tokens=32, running=3),
                              StubEngine(hit_tokens=32, running=0)])
    assert r.select(PROMPT) == 1


def test_prefix_affinity_cold_prefix_hash_is_stable():
    """Cold prefixes route by rendezvous hash: deterministic across router
    instances (fan-out siblings co-locate BEFORE the prefix is cached)."""
    a = PrefixAffinityRouter([StubEngine(), StubEngine()])
    b = PrefixAffinityRouter([StubEngine(), StubEngine()])
    picks = {a.select(PROMPT), b.select(PROMPT), a.select(PROMPT)}
    assert len(picks) == 1
    # Different first-block content can (and across many prompts does)
    # land elsewhere — the hash spreads distinct scenarios.
    spread = {a.select([i] * 32) for i in range(32)}
    assert spread == {0, 1}


def test_rendezvous_minimal_remap_on_member_loss():
    """Removing the last replica only remaps ITS keys: every key owned by a
    surviving replica keeps its assignment (the property plain hash%n
    lacks — a resize would cold-start every replica's prefix cache)."""
    keys = [prefix_route_key([i, i + 1, i + 2, 7 * i], 8) for i in range(200)]
    before = [rendezvous_pick(k, 3) for k in keys]
    after = [rendezvous_pick(k, 2) for k in keys]
    for b, a in zip(before, after):
        if b < 2:
            assert a == b, "survivor-owned key remapped on member loss"
    assert any(b == 2 for b in before), "degenerate key set: nothing on 2"


def test_prefix_affinity_saturated_target_overflows():
    """A full extra wave queued on the affinity target: the request
    overflows to the least-loaded unsaturated replica — bounded queue wait
    beats a cache hit stuck behind max_num_seqs others."""
    hot = StubEngine(hit_tokens=64, waiting=4, max_num_seqs=4)
    cold = StubEngine(hit_tokens=0, running=1)
    colder = StubEngine(hit_tokens=0, running=0)
    r = PrefixAffinityRouter([hot, cold, colder])
    assert r.select(PROMPT) == 2
    # Everyone saturated: affinity is still the best of the bad options.
    sat = [StubEngine(hit_tokens=h, waiting=4) for h in (0, 48, 8)]
    assert PrefixAffinityRouter(sat).select(PROMPT) == 1


def test_make_router_rejects_unknown_policy():
    with pytest.raises(ValueError, match="least_loaded"):
        make_router("fastest", [StubEngine()])
    with pytest.raises(ValueError, match="at least one replica"):
        make_router("round_robin", [])


# ------------------------------------------------- pool end-to-end (tiny)


@pytest.fixture(scope="module")
def runner():
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    return ModelRunner(CFG, params)


def make_pool(runner, n, policy, prefix_caching=True, **kw):
    kw.setdefault("max_model_len", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_num_seqs", 4)
    engines = [
        LLMEngine(EngineConfig(model="tiny", dtype="float32",
                               prefix_caching=prefix_caching, **kw),
                  model_cfg=CFG, runner=runner)
        for _ in range(n)
    ]
    return EnginePool(engines, policy=policy)


def greedy(max_tokens=4, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0,
                          ignore_eos=True, **kw)


def drain(pool, reqs):
    for _ in range(10_000):
        pool.step()
        if all(r.is_finished() for r in reqs):
            return
        if not pool.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


def fan_out(pool, rng_seed=0):
    """The agentic workload: a group leader, then siblings quoting the same
    long prefix with distinct task suffixes. Leader drains first so the
    siblings' probes see its registered prefix (deterministic hits)."""
    rng = np.random.default_rng(rng_seed)
    prefix = rng.integers(0, CFG.vocab_size, 33).tolist()
    lead = pool.add_request(prefix + rng.integers(0, CFG.vocab_size, 4).tolist(),
                            greedy())
    drain(pool, [lead])
    sibs = [pool.add_request(
        prefix + rng.integers(0, CFG.vocab_size, 4).tolist(), greedy())
        for _ in range(4)]
    drain(pool, sibs)
    return [lead] + sibs


@require_devices
def test_prefix_affinity_beats_round_robin_on_fanout(runner):
    """The tentpole claim, engine-level: on the SAME fan-out workload a
    2-replica prefix_affinity pool serves strictly more prompt tokens from
    the prefix caches than round_robin (siblings land where the scenario
    prefix's KV already lives instead of recomputing on the other
    replica), and every request still finishes."""
    aff = make_pool(runner, NUM_REPLICAS, "prefix_affinity")
    rr = make_pool(runner, NUM_REPLICAS, "round_robin")
    aff_reqs = fan_out(aff)
    rr_reqs = fan_out(rr)
    aff_hits = aff.kv_stats()["prefix_cache_hit_tokens"]
    rr_hits = rr.kv_stats()["prefix_cache_hit_tokens"]
    assert aff_hits > rr_hits, (aff_hits, rr_hits)
    # Same workload, same model: outputs must agree pairwise regardless of
    # placement (cache hits are exact-reuse, not approximation).
    assert ([r.generated_ids for r in aff_reqs]
            == [r.generated_ids for r in rr_reqs])


@require_devices
def test_prefix_affinity_colocates_siblings(runner):
    """Routing decisions directly: the leader's replica takes every
    sibling (probe hits beat the hash fallback once the prefix is
    registered)."""
    pool = make_pool(runner, NUM_REPLICAS, "prefix_affinity")
    fan_out(pool)
    # 5 requests total: all on one replica, none on the other.
    assert sorted(pool.routed_requests) == [0, 5], pool.routed_requests


@require_devices
def test_round_robin_pool_spreads_and_matches_solo(runner):
    """round_robin spreads exactly evenly, and pool outputs are
    token-identical to solo single-engine runs (shared-nothing replicas
    cannot perturb each other's numerics)."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, CFG.vocab_size, n).tolist()
               for n in (5, 11, 17, 9)]
    solos = []
    for p in prompts:
        eng = LLMEngine(EngineConfig(model="tiny", dtype="float32",
                                     max_model_len=128, block_size=8,
                                     num_blocks=64, max_num_seqs=4),
                        model_cfg=CFG, runner=runner)
        solos.append(eng.generate(p, greedy(8)).generated_ids)
    pool = make_pool(runner, NUM_REPLICAS, "round_robin",
                     prefix_caching=False)
    reqs = [pool.add_request(p, greedy(8)) for p in prompts]
    assert pool.routed_requests == [2, 2]
    drain(pool, reqs)
    assert [r.generated_ids for r in reqs] == solos


@require_devices
def test_single_replica_pool_is_the_engine(runner):
    """A 1-replica pool must behave exactly like the bare engine (the
    LLM_NUM_REPLICAS=1 bit-identity the server default relies on)."""
    rng = np.random.default_rng(2)
    p = rng.integers(0, CFG.vocab_size, 12).tolist()
    eng = LLMEngine(EngineConfig(model="tiny", dtype="float32",
                                 max_model_len=128, block_size=8,
                                 num_blocks=64, max_num_seqs=4),
                    model_cfg=CFG, runner=runner)
    solo = eng.generate(p, greedy(8)).generated_ids
    pool = make_pool(runner, 1, "prefix_affinity", prefix_caching=False)
    req = pool.add_request(p, greedy(8))
    drain(pool, [req])
    assert req.generated_ids == solo
    assert pool.routed_requests == [1]


@require_devices
def test_pool_abort_flushes_sibling_streams_on_both_replicas(runner):
    """Pool-level abort correctness: abort one request mid-stream (its
    tokens still riding the in-flight pipeline) and every OTHER stream —
    batchmates on the same replica AND requests on the other replica —
    still flushes and finishes with its exact solo output. The abort's
    sibling drain events must route exactly like step()'s
    (runtime/engine.py abort_request contract), now through the pool."""
    rng = np.random.default_rng(16)
    prompts = [rng.integers(0, CFG.vocab_size, 9).tolist() for _ in range(4)]
    solos = []
    for p in prompts:
        eng = LLMEngine(EngineConfig(model="tiny", dtype="float32",
                                     max_model_len=128, block_size=8,
                                     num_blocks=64, max_num_seqs=4),
                        model_cfg=CFG, runner=runner)
        solos.append(eng.generate(p, greedy(6)).generated_ids)

    pool = make_pool(runner, NUM_REPLICAS, "round_robin",
                     prefix_caching=False)
    # round_robin: requests 0,2 -> replica 0; requests 1,3 -> replica 1.
    reqs = [pool.add_request(p, greedy(6)) for p in prompts]
    victim, survivors = reqs[0], reqs[1:]
    streamed = {r.request_id: [] for r in reqs}
    # Step until the victim's replica has every remaining token in flight,
    # so the abort drain is guaranteed to produce sibling events.
    owner = pool.engines[0]
    for _ in range(10_000):
        for ev in pool.step():
            streamed[ev.request.request_id].extend(ev.new_token_ids)
        if owner._inflight and owner._decode_budget_satisfied():
            break
        assert pool.has_work()
    events = pool.abort_request(victim)
    assert victim.finish_reason == FinishReason.ABORT
    for ev in events:
        assert ev.request is not victim or not ev.new_token_ids
        streamed[ev.request.request_id].extend(ev.new_token_ids)
    for _ in range(10_000):
        if all(r.is_finished() for r in survivors):
            break
        for ev in pool.step():
            streamed[ev.request.request_id].extend(ev.new_token_ids)
    for r, solo in zip(reqs, solos):
        if r is victim:
            continue
        assert r.is_finished(), "sibling stream stranded after pool abort"
        assert r.generated_ids == solo
        assert streamed[r.request_id] == r.generated_ids, (
            "stream events disagree with the request state after abort")


@require_devices
def test_pool_kv_stats_aggregate_sums(runner):
    pool = make_pool(runner, NUM_REPLICAS, "round_robin")
    stats = pool.kv_stats()
    per = [e.kv_stats() for e in pool.engines]
    assert stats["num_blocks"] == sum(p["num_blocks"] for p in per)
    assert stats["total_tokens"] == sum(p["total_tokens"] for p in per)
    assert stats["block_size"] == per[0]["block_size"]
    assert pool.usable_tokens == sum(e.cache.usable_tokens
                                     for e in pool.engines)


def test_engine_load_snapshot_shape(runner):
    """The lock-free snapshot carries exactly what the router reads."""
    eng = LLMEngine(EngineConfig(model="tiny", dtype="float32",
                                 max_model_len=128, block_size=8,
                                 num_blocks=64, max_num_seqs=4),
                    model_cfg=CFG, runner=runner)
    s = eng.load_snapshot()
    assert s["num_waiting"] == 0 and s["num_running"] == 0
    assert s["max_num_seqs"] == 4 and s["block_size"] == 8
    rng = np.random.default_rng(3)
    eng.add_request(rng.integers(0, CFG.vocab_size, 8).tolist(), greedy(2))
    assert eng.load_snapshot()["num_waiting"] == 1
    # No prefix caching: the probe is a constant 0, never an error.
    assert eng.probe_prefix_tokens([1] * 32) == 0
    assert eng.chain_keys_for([1] * 32) is None
