"""End-to-end real-weights parity: HF checkpoint -> server /chat vs HF.

Round-3 verdict item #10: the golden tests cover the model functions on
converted state dicts, but the full serving path (safetensors load ->
quantize/shard -> engine -> HTTP) ran random weights only. Here a tiny HF
Llama checkpoint is written to disk with save_pretrained, the server loads
it through the production weights path (ServerConfig.weights_path ->
models/weights.py load_params), and greedy /chat completions must match
transformers' generate() token-for-token. Reference analog: the hf_cpu_server
behavior contract (reference llm/hf_cpu_server.py) — same model, same
greedy tokens, different engine.

A second, env-gated test does the same against a REAL checkpoint when
ATT_E2E_WEIGHTS_PATH is set (no weights are downloadable in CI).
"""

import asyncio
import os

import numpy as np
import pytest

from aiohttp.test_utils import TestClient, TestServer

from agentic_traffic_testing_tpu.serving.config import ServerConfig
from agentic_traffic_testing_tpu.serving.server import LLMServer


@pytest.fixture(scope="module")
def tiny_hf_checkpoint(tmp_path_factory):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    torch.manual_seed(7)
    hf_cfg = LlamaConfig(
        # vocab covers the server's byte-fallback tokenizer (256 bytes + 6
        # specials) so /chat prompts tokenize into this model's id space.
        vocab_size=262,
        hidden_size=64,
        intermediate_size=160,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
        rope_theta=10000.0,
        tie_word_embeddings=False,
    )
    model = LlamaForCausalLM(hf_cfg).eval()
    path = tmp_path_factory.mktemp("tiny-llama-ckpt")
    model.save_pretrained(path, safe_serialization=True)
    return str(path), model


def _chat(server, payload):
    async def wrapper():
        app = server.make_app(manage_engine=False)
        async with TestClient(TestServer(app)) as client:
            resp = await client.post("/chat", json=payload)
            assert resp.status == 200, await resp.text()
            return await resp.json()

    return asyncio.run(wrapper())


def test_chat_matches_hf_generate_on_loaded_checkpoint(tiny_hf_checkpoint):
    import torch

    path, hf_model = tiny_hf_checkpoint
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=2, max_model_len=128,
        num_blocks=64, max_tokens=12, temperature=0.0,
        # Default margin (128) would swallow the whole prompt at this
        # max_model_len — the guardrail has its own test (test_serving.py).
        safety_margin_tokens=8,
        weights_path=path,
    )
    srv = LLMServer(cfg)
    assert srv.model_loaded is True
    assert b"llm_model_loaded 1.0" in srv.metrics.render()
    srv.async_engine.start()
    try:
        prompt = "hello tiny model"
        body = _chat(srv, {"prompt": prompt, "skip_chat_template": True,
                           "max_tokens": 12, "temperature": 0.0})
        # Reconstruct the exact ids the server prefilled (BOS + byte ids —
        # the server's own tokenizer is the ground truth for both sides).
        ids = srv.tokenizer.encode(prompt)
        bos = getattr(srv.tokenizer, "bos_id", None) or srv.tokenizer.bos_token_id
        if ids[0] != bos:
            ids = [bos] + ids
        with torch.no_grad():
            out = hf_model.generate(
                torch.tensor([ids]), max_new_tokens=12, do_sample=False,
                pad_token_id=0)
        hf_completion = out[0, len(ids):].tolist()
        expect = srv.tokenizer.decode(hf_completion)
        # HF stops at its config eos (id 2) which the byte tokenizer does
        # not treat as a stop, so the server may continue past it — parity
        # holds token-for-token over HF's whole natural trajectory
        # (including its final eos token).
        assert len(hf_completion) >= 4
        assert body["output"].startswith(expect), (body["output"], expect,
                                                   hf_completion)
    finally:
        srv.async_engine.shutdown()


@pytest.mark.skipif(not os.environ.get("ATT_E2E_WEIGHTS_PATH"),
                    reason="set ATT_E2E_WEIGHTS_PATH to a local HF "
                           "checkpoint dir to run real-weights parity")
def test_chat_matches_hf_generate_real_checkpoint():
    import torch
    from transformers import AutoModelForCausalLM

    path = os.environ["ATT_E2E_WEIGHTS_PATH"]
    cfg = ServerConfig(
        model=path, dtype="bfloat16", max_num_seqs=2, max_model_len=512,
        max_tokens=16, temperature=0.0, weights_path=path,
        tokenizer_path=path,
    )
    srv = LLMServer(cfg)
    assert srv.model_loaded is True
    srv.async_engine.start()
    try:
        prompt = "The capital of France is"
        body = _chat(srv, {"prompt": prompt, "skip_chat_template": True,
                           "max_tokens": 16, "temperature": 0.0})
        ids = srv.tokenizer.encode(prompt)
        bos = getattr(srv.tokenizer, "bos_id", None) or srv.tokenizer.bos_token_id
        if ids[0] != bos:
            ids = [bos] + ids
        model = AutoModelForCausalLM.from_pretrained(
            path, torch_dtype=torch.float32).eval()
        with torch.no_grad():
            out = model.generate(torch.tensor([ids]), max_new_tokens=16,
                                 do_sample=False)
        expect = srv.tokenizer.decode(out[0, len(ids):].tolist())
        assert body["output"] == expect
    finally:
        srv.async_engine.shutdown()
