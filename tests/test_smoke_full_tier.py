"""Default-tier smoke tests for the heavyweight ("full"-marked) surfaces.

The full tier (`-m "full or not full"`) carries the deep suites for the
engine, parallelism, quantization, MoE, speculation, and chunked prefill —
compile-bound, ~35 min on one CPU core, so the default tier deselects them
(pytest.ini). That left a plain `pytest tests/` green while the riskiest
code paths went unexercised (round-3 advisor finding). This module is the
bridge: ONE small, fast test per heavyweight area, always on, sized to add
roughly a minute to the default tier. Each test pins the area's core
correctness contract; the full-tier module it shadows carries the real
depth (named in each docstring).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import forward_full_impl, init_params
from agentic_traffic_testing_tpu.models.quant import (
    _unpack4,
    dense,
    quantize_array4,
    quantize_params,
)
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import SamplingParams

CFG = PRESETS["tiny"]


def _generate(ecfg_kw: dict, prompt: list[int], max_tokens: int = 8,
              params=None) -> list[int]:
    ecfg = EngineConfig(model="tiny", dtype="float32", max_model_len=128,
                        block_size=8, num_blocks=64, max_num_seqs=2, **ecfg_kw)
    eng = LLMEngine(ecfg, model_cfg=CFG, params=params)
    req = eng.add_request(prompt, SamplingParams(temperature=0.0,
                                                 max_tokens=max_tokens,
                                                 ignore_eos=True))
    for _ in range(10_000):
        eng.step()
        if req.is_finished():
            break
    assert req.is_finished()
    return list(req.generated_ids)


def test_smoke_int4_kgroup_dense_matches_unpack_oracle():
    """int4 K-group scales (shadows test_quant's k-group suite): the
    grouped quantizer reconstructs within int4 step error and dense()'s
    fallback path matches the explicit unpack-then-matmul oracle."""
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.standard_normal((128, 32)), jnp.float32)
    qt = quantize_array4(w, k_group=32)
    assert qt.scale.shape == (4, 2, 16)
    deq = _unpack4(qt.packed, qt.scale, jnp.float32)
    assert float(jnp.max(jnp.abs(deq - w))) <= float(jnp.max(qt.scale)) * 0.51
    x = jnp.asarray(rng.standard_normal((3, 128)), jnp.float32)
    np.testing.assert_allclose(np.asarray(dense(x, qt)), np.asarray(x @ deq),
                               rtol=2e-5, atol=2e-5)


def test_smoke_grouped_packing_decodes_on_global_path():
    """The TP byte layout (groups>1) decodes CORRECTLY on the single-chip
    path (round 5: _dense4 decomposes into contiguous per-group slices —
    before that it refused; silently column-permuted decode was the
    round-3 hazard and would show up here as a large mismatch)."""
    rng = np.random.default_rng(7)
    w = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((2, 32)), jnp.float32)
    qg = quantize_array4(w, groups=2)
    assert qg.groups == 2
    want = dense(x, quantize_array4(w))   # standard packing: the oracle
    np.testing.assert_allclose(np.asarray(dense(x, qg)), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


def test_smoke_int4_tp_dense_matches_oracle():
    """int4 x TP shard_map matmul on a 2-device CPU mesh (shadows
    test_quant's tp_int4 suite): grouped packing + QTensor4TP column path
    reproduces the ungrouped dequantize-then-matmul oracle."""
    from jax.sharding import Mesh

    from agentic_traffic_testing_tpu.models.quant import QTensor4TP

    rng = np.random.default_rng(1)
    w = jnp.asarray(rng.standard_normal((32, 64)), jnp.float32)
    q1 = quantize_array4(w)                    # standard packing: the oracle
    want = jnp.ones((2, 32), jnp.float32) @ _unpack4(q1.packed, q1.scale,
                                                     jnp.float32)
    qg = quantize_array4(w, groups=2)          # TP byte layout
    mesh = Mesh(np.array(jax.devices("cpu")[:2]), ("tp",))
    wtp = QTensor4TP(qg.packed, qg.scale, "col", mesh, "tp")
    got = dense(jnp.ones((2, 32), jnp.float32), wtp)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_smoke_chunked_prefill_token_exact():
    """Chunked prefill (shadows test_chunked_prefill): a prompt longer than
    prefill_chunk_tokens must produce exactly the one-shot engine's
    tokens."""
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, CFG.vocab_size, 80).tolist()
    want = _generate({}, prompt, params=params)
    got = _generate({"prefill_chunk_tokens": 32}, prompt, params=params)
    assert got == want


def test_smoke_speculative_decode_token_exact():
    """n-gram speculation (shadows test_speculative): a pure perf knob —
    greedy output must match the non-speculative engine exactly, on a
    repetitive prompt where the proposer actually fires."""
    params = init_params(CFG, jax.random.key(1), dtype=jnp.float32)
    prompt = [5, 9, 11, 5, 9, 11, 5, 9, 11, 5, 9]
    want = _generate({}, prompt, params=params)
    got = _generate({"speculation": "ngram", "spec_tokens": 3},
                    prompt, params=params)
    assert got == want


def test_smoke_moe_int4_logits_match_dequantized_oracle():
    """MoE x int4 (shadows test_moe's int4 suite): the packed-weight
    forward must match the same weights dequantized up front — identical
    routing by construction, so any mismatch is the int4 expert-matmul
    path itself. (A vs-full-precision corr bound is the wrong contract
    at tiny-MoE scale: quantization legitimately flips router top-k.)"""
    mcfg = PRESETS["tiny-moe"]
    params = init_params(mcfg, jax.random.key(2), dtype=jnp.float32)
    qparams = quantize_params(params, scheme="int4")

    def deq(leaf):
        from agentic_traffic_testing_tpu.models.quant import QTensor4

        if isinstance(leaf, QTensor4):
            return _unpack4(leaf.packed, leaf.scale, jnp.float32)
        return leaf

    oracle = jax.tree_util.tree_map(
        deq, qparams,
        is_leaf=lambda x: type(x).__name__ == "QTensor4")
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, mcfg.vocab_size, (1, 12)), jnp.int32)
    want = np.asarray(forward_full_impl(oracle, mcfg, tokens))
    got = np.asarray(forward_full_impl(qparams, mcfg, tokens))
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_smoke_tp2_engine_decode_matches_single_device():
    """TP on a 2-device CPU mesh end-to-end (shadows test_parallel /
    test_quant TP suites): TPRunner greedy decode is token-exact vs the
    single-device engine."""
    from agentic_traffic_testing_tpu.parallel.mesh import single_axis_mesh
    from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner

    params = init_params(CFG, jax.random.key(3), dtype=jnp.float32)
    rng = np.random.default_rng(4)
    prompt = rng.integers(0, CFG.vocab_size, 13).tolist()
    want = _generate({}, prompt, max_tokens=6, params=params)

    runner = TPRunner(CFG, params, single_axis_mesh("tp", 2))
    ecfg = EngineConfig(model="tiny", dtype="float32", max_model_len=128,
                        block_size=8, num_blocks=64, max_num_seqs=2)
    eng = LLMEngine(ecfg, model_cfg=CFG, runner=runner)
    req = eng.add_request(prompt, SamplingParams(temperature=0.0, max_tokens=6,
                                                 ignore_eos=True))
    for _ in range(10_000):
        eng.step()
        if req.is_finished():
            break
    assert list(req.generated_ids) == want
