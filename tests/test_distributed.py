"""Multi-host bootstrap module (parallel/distributed.py).

Single-process tests: the env contract (no-op without config, kwargs built
from ATT_* vars) and the process-identity block. Real multi-process
initialization is exercised by the driver's multichip dry run and on pods.
"""

import numpy as np

import agentic_traffic_testing_tpu.parallel.distributed as dist


def test_noop_without_env(monkeypatch):
    monkeypatch.delenv("ATT_COORDINATOR_ADDRESS", raising=False)
    monkeypatch.delenv("ATT_MULTIHOST", raising=False)
    assert dist.maybe_initialize() is False
    assert dist.is_initialized() is False


def test_process_info_single_host():
    info = dist.process_info()
    assert info["process_index"] == 0
    assert info["process_count"] == 1
    assert info["local_devices"] >= 1
    assert info["global_devices"] == info["local_devices"]
    assert info["distributed"] is False


def test_global_mesh_devices_ordering():
    import jax

    devs = dist.global_mesh_devices()
    assert list(devs) == list(jax.devices())
    assert list(dist.global_mesh_devices(1)) == [jax.devices()[0]]


def test_mesh_over_global_devices():
    """A fleet mesh built from global_mesh_devices composes with make_mesh."""
    from agentic_traffic_testing_tpu.parallel.mesh import make_mesh

    devs = dist.global_mesh_devices()
    n = len(devs)
    tp = 2 if n % 2 == 0 else 1
    mesh = make_mesh(dp=n // tp, sp=1, tp=tp, devices=devs)
    assert int(np.prod(list(mesh.shape.values()))) == n
