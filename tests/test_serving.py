"""Golden contract tests for the LLM HTTP backend.

Pin the request/response JSON shape, header handling, and Prometheus family
names against the reference contract documented in SURVEY.md §2.1
(reference: llm/serve_llm.py:731-955). These are the tests the reference
never had — its verification was operational only (SURVEY.md §4).
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from agentic_traffic_testing_tpu.serving.config import ServerConfig
from agentic_traffic_testing_tpu.serving.server import LLMServer

# Every llm_* family the reference exports (SURVEY.md §2.1 metrics table).
EXPECTED_METRIC_FAMILIES = [
    "llm_requests_total",
    "llm_request_latency_seconds",
    "llm_queue_wait_seconds",
    "llm_inflight_requests",
    "llm_prompt_tokens_total",
    "llm_completion_tokens_total",
    "llm_batch_size",
    "llm_config_max_num_seqs",
    "llm_config_max_num_batched_tokens",
    "llm_config_gpu_memory_utilization",
    "llm_config_max_tokens",
    "llm_kv_cache_num_gpu_blocks",
    "llm_kv_cache_block_size_tokens",
    "llm_kv_cache_total_tokens",
    "llm_kv_cache_est_max_concurrency_at_max_model_len",
    "llm_computed_max_concurrency",
    "llm_interarrival_seconds",
    "llm_model_loaded",
]


@pytest.fixture(scope="module")
def server():
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=4, max_model_len=256,
        num_blocks=128, max_tokens=16, temperature=0.0,
    )
    srv = LLMServer(cfg)
    srv.async_engine.start()
    yield srv
    srv.async_engine.shutdown()


def _run(server, coro_fn):
    async def wrapper():
        app = server.make_app(manage_engine=False)
        async with TestClient(TestServer(app)) as client:
            return await coro_fn(client)

    return asyncio.run(wrapper())


def test_health_ready_live(server):
    async def go(client):
        for path in ("/health", "/ready", "/live"):
            resp = await client.get(path)
            assert resp.status == 200
            assert (await resp.json()) == {"status": "ok"}

    _run(server, go)


def test_chat_response_contract(server):
    async def go(client):
        resp = await client.post("/chat", json={"prompt": "Hello", "max_tokens": 4})
        assert resp.status == 200
        body = await resp.json()
        assert isinstance(body["output"], str)
        meta = body["meta"]
        for key in ("request_id", "latency_ms", "queue_wait_s", "prompt_tokens",
                    "completion_tokens", "total_tokens", "otel"):
            assert key in meta, f"missing meta.{key}"
        assert meta["completion_tokens"] >= 1
        assert meta["total_tokens"] == meta["prompt_tokens"] + meta["completion_tokens"]
        assert meta["queue_wait_s"] >= 0
        return body

    _run(server, go)


def test_input_alias_and_request_id_header(server):
    async def go(client):
        resp = await client.post("/chat", json={"input": "Hi", "max_tokens": 2},
                                 headers={"X-Request-ID": "my-req-42"})
        body = await resp.json()
        assert body["meta"]["request_id"] == "my-req-42"

    _run(server, go)


def test_completion_and_generate_aliases(server):
    async def go(client):
        for path in ("/completion", "/generate"):
            resp = await client.post(path, json={"prompt": "x", "max_tokens": 2})
            assert resp.status == 200, path

    _run(server, go)


def test_missing_prompt_400(server):
    async def go(client):
        resp = await client.post("/chat", json={"max_tokens": 4})
        assert resp.status == 400
        resp = await client.post("/chat", data=b"{not json",
                                 headers={"Content-Type": "application/json"})
        assert resp.status == 400

    _run(server, go)


def test_metrics_families_present(server):
    async def go(client):
        # Generate one request first so counters exist.
        await client.post("/chat", json={"prompt": "hello", "max_tokens": 2})
        resp = await client.get("/metrics")
        assert resp.status == 200
        text = (await resp.read()).decode()
        for fam in EXPECTED_METRIC_FAMILIES:
            assert fam in text, f"missing metric family {fam}"

    _run(server, go)


def test_prompt_truncation_guardrail(server):
    """Over-long prompts are token-truncated (head kept), not rejected
    (reference: llm/serve_llm.py:812-844)."""
    async def go(client):
        long_prompt = "word " * 2000   # byte tokenizer -> ~10k tokens >> 256
        resp = await client.post("/chat", json={"prompt": long_prompt,
                                                "max_tokens": 8})
        assert resp.status == 200
        body = await resp.json()
        assert body["meta"]["prompt_tokens"] <= 256

    _run(server, go)


def test_skip_chat_template(server):
    async def go(client):
        resp = await client.post(
            "/chat", json={"prompt": "raw", "skip_chat_template": True,
                           "max_tokens": 2})
        assert resp.status == 200

    _run(server, go)


def test_parallel_fanout_requests(server):
    """5 concurrent requests (the agent-b fan-out shape) all succeed."""
    async def go(client):
        async def one(i):
            resp = await client.post(
                "/chat", json={"prompt": f"task {i}", "max_tokens": 4})
            assert resp.status == 200
            return (await resp.json())["meta"]["request_id"]

        ids = await asyncio.gather(*[one(i) for i in range(5)])
        assert len(set(ids)) == 5

    _run(server, go)


def test_kv_gauges_reflect_engine(server):
    async def go(client):
        resp = await client.get("/metrics")
        text = (await resp.read()).decode()
        num_blocks = server.engine.cache.num_blocks - 1
        bs = server.engine.cache.block_size
        assert f"llm_kv_cache_num_gpu_blocks {float(num_blocks)}" in text
        assert f"llm_kv_cache_total_tokens {float(num_blocks * bs)}" in text

    _run(server, go)


def test_profile_endpoints(server, tmp_path):
    """jax.profiler trace start/stop round-trip (SURVEY.md §5.1: the
    TPU-idiomatic profiling the reference stack lacks)."""
    async def go(client):
        log_dir = str(tmp_path / "trace")
        resp = await client.post("/profile/start", json={"log_dir": log_dir})
        assert resp.status == 200
        assert (await resp.json())["log_dir"] == log_dir
        # Double-start must 409, not crash the profiler.
        resp = await client.post("/profile/start", json={"log_dir": log_dir})
        assert resp.status == 409
        resp = await client.post("/profile/stop")
        assert resp.status == 200
        # Stop without an active trace must 409.
        resp = await client.post("/profile/stop")
        assert resp.status == 409
        return log_dir

    log_dir = _run(server, go)
    import os

    assert os.path.isdir(log_dir), "profiler wrote nothing"



def test_bad_weights_path_fails_fast(tmp_path):
    """A weight-load failure must abort startup, not silently serve random
    weights behind 200s (round-1 verdict weak #3)."""
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=2, max_model_len=128,
        num_blocks=64, weights_path=str(tmp_path / "no-such-checkpoint"),
    )
    with pytest.raises(RuntimeError, match="LLM_ALLOW_RANDOM_WEIGHTS"):
        LLMServer(cfg)


def test_bad_weights_path_opt_in_random(tmp_path):
    """LLM_ALLOW_RANDOM_WEIGHTS=1 restores the fallback and reports
    llm_model_loaded 0."""
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=2, max_model_len=128,
        num_blocks=64, weights_path=str(tmp_path / "no-such-checkpoint"),
        allow_random_weights=True,
    )
    srv = LLMServer(cfg)
    assert srv.model_loaded is False
    assert b"llm_model_loaded 0.0" in srv.metrics.render()
