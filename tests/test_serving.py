"""Golden contract tests for the LLM HTTP backend.

Pin the request/response JSON shape, header handling, and Prometheus family
names against the reference contract documented in SURVEY.md §2.1
(reference: llm/serve_llm.py:731-955). These are the tests the reference
never had — its verification was operational only (SURVEY.md §4).
"""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from agentic_traffic_testing_tpu.serving.config import ServerConfig
from agentic_traffic_testing_tpu.serving.server import LLMServer

# Every llm_* family the reference exports (SURVEY.md §2.1 metrics table).
EXPECTED_METRIC_FAMILIES = [
    "llm_requests_total",
    "llm_request_latency_seconds",
    "llm_queue_wait_seconds",
    "llm_inflight_requests",
    "llm_prompt_tokens_total",
    "llm_completion_tokens_total",
    "llm_batch_size",
    "llm_config_max_num_seqs",
    "llm_config_max_num_batched_tokens",
    "llm_config_gpu_memory_utilization",
    "llm_config_max_tokens",
    "llm_kv_cache_num_gpu_blocks",
    "llm_kv_cache_block_size_tokens",
    "llm_kv_cache_total_tokens",
    "llm_kv_cache_est_max_concurrency_at_max_model_len",
    "llm_computed_max_concurrency",
    "llm_interarrival_seconds",
    "llm_model_loaded",
]


def test_server_config_env_contract(monkeypatch):
    """The LLM_* env surface is the reference's operator contract
    (reference: llm/serve_llm.py:52-82): every knob must parse from env,
    and unset optionals stay None rather than becoming 0/""."""
    env = {
        "LLM_MODEL": "llama-3.2-3b",
        "LLM_DTYPE": "bfloat16",
        "LLM_MAX_NUM_SEQS": "10",
        "LLM_MAX_NUM_BATCHED_TOKENS": "4096",
        "LLM_GPU_MEMORY_UTILIZATION": "0.8",
        "LLM_MAX_MODEL_LEN": "2048",
        "LLM_MAX_TOKENS": "256",
        "LLM_PROMPT_SAFETY_MARGIN_TOKENS": "64",
        "LLM_TEMPERATURE": "0.4",
        "LLM_HOST": "127.0.0.9",
        "LLM_PORT": "8123",
        "LLM_TP_SIZE": "2",
        "LLM_NUM_REPLICAS": "3",
        "LLM_ROUTER_POLICY": "prefix_affinity",
        "LLM_QUANTIZATION": "int8",
        "LLM_DECODE_STEPS": "32",
        "LLM_PREFILL_CHUNK_TOKENS": "1024",
        "LLM_PREFILL_BATCH_MAX_LEN": "512",
        "LLM_PREFIX_CACHING": "1",
        "LLM_NUM_BLOCKS": "2048",
        "LLM_BLOCK_SIZE": "32",
        "LLM_WEIGHTS_PATH": "/ckpts/llama",
        "LLM_ALLOW_RANDOM_WEIGHTS": "1",
        "LLM_MOE_CAPACITY_FACTOR": "4.0",
        "LLM_SPECULATION": "ngram",
        "LLM_SPEC_TOKENS": "4",
        "LLM_SPEC_NGRAM": "2",
        "LLM_WARMUP": "0",
        "LLM_METRICS_ENABLED": "0",
        "LOG_LLM_REQUESTS": "1",
        "LLM_LOG_MAX_CHARS": "99",
    }
    for k, v in env.items():
        monkeypatch.setenv(k, v)
    c = ServerConfig.from_env()
    assert (c.model, c.dtype) == ("llama-3.2-3b", "bfloat16")
    assert (c.max_num_seqs, c.max_num_batched_tokens) == (10, 4096)
    assert (c.memory_utilization, c.safety_margin_tokens) == (0.8, 64)
    assert (c.max_model_len, c.max_tokens) == (2048, 256)
    assert c.temperature == 0.4
    assert (c.host, c.port) == ("127.0.0.9", 8123)
    assert (c.tp_size, c.quantization, c.decode_steps) == (2, "int8", 32)
    assert (c.num_replicas, c.router_policy) == (3, "prefix_affinity")
    assert (c.prefill_chunk_tokens, c.prefill_batch_max_len) == (1024, 512)
    assert (c.prefix_caching, c.num_blocks, c.block_size) == (True, 2048, 32)
    assert (c.weights_path, c.allow_random_weights) == ("/ckpts/llama", True)
    assert c.moe_capacity_factor == 4.0
    assert (c.speculation, c.spec_tokens, c.spec_ngram) == ("ngram", 4, 2)
    assert (c.warmup, c.metrics_enabled) == (False, False)
    assert (c.log_requests, c.log_max_chars) == (True, 99)

    for k in env:
        monkeypatch.delenv(k)
    # Hermetic second half: clear optionals a CI environment might export.
    for k in ("LLM_NUM_BLOCKS", "LLM_WEIGHTS_PATH", "LLM_MOE_CAPACITY_FACTOR"):
        monkeypatch.delenv(k, raising=False)
    d = ServerConfig.from_env()
    # Unset optionals are None (auto), not zero/empty-string coercions.
    assert d.prefill_batch_max_len is None
    assert d.decode_steps is None
    assert d.quantization is None
    assert d.speculation is None
    assert d.num_blocks is None
    assert d.moe_capacity_factor is None


@pytest.fixture(scope="module")
def server():
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=4, max_model_len=256,
        num_blocks=128, max_tokens=16, temperature=0.0,
    )
    srv = LLMServer(cfg)
    srv.async_engine.start()
    yield srv
    srv.async_engine.shutdown()


def _run(server, coro_fn):
    async def wrapper():
        app = server.make_app(manage_engine=False)
        async with TestClient(TestServer(app)) as client:
            return await coro_fn(client)

    return asyncio.run(wrapper())


def test_health_ready_live(server):
    async def go(client):
        for path in ("/health", "/ready", "/live"):
            resp = await client.get(path)
            assert resp.status == 200
            assert (await resp.json()) == {"status": "ok"}

    _run(server, go)


def test_chat_response_contract(server):
    async def go(client):
        resp = await client.post("/chat", json={"prompt": "Hello", "max_tokens": 4})
        assert resp.status == 200
        body = await resp.json()
        assert isinstance(body["output"], str)
        meta = body["meta"]
        for key in ("request_id", "latency_ms", "queue_wait_s", "prompt_tokens",
                    "completion_tokens", "total_tokens", "otel"):
            assert key in meta, f"missing meta.{key}"
        assert meta["completion_tokens"] >= 1
        assert meta["total_tokens"] == meta["prompt_tokens"] + meta["completion_tokens"]
        assert meta["queue_wait_s"] >= 0
        return body

    _run(server, go)


def test_input_alias_and_request_id_header(server):
    async def go(client):
        resp = await client.post("/chat", json={"input": "Hi", "max_tokens": 2},
                                 headers={"X-Request-ID": "my-req-42"})
        body = await resp.json()
        assert body["meta"]["request_id"] == "my-req-42"

    _run(server, go)


def test_completion_and_generate_aliases(server):
    async def go(client):
        for path in ("/completion", "/generate"):
            resp = await client.post(path, json={"prompt": "x", "max_tokens": 2})
            assert resp.status == 200, path

    _run(server, go)


def test_missing_prompt_400(server):
    async def go(client):
        resp = await client.post("/chat", json={"max_tokens": 4})
        assert resp.status == 400
        resp = await client.post("/chat", data=b"{not json",
                                 headers={"Content-Type": "application/json"})
        assert resp.status == 400

    _run(server, go)


def test_metrics_families_present(server):
    async def go(client):
        # Generate one request first so counters exist.
        await client.post("/chat", json={"prompt": "hello", "max_tokens": 2})
        resp = await client.get("/metrics")
        assert resp.status == 200
        text = (await resp.read()).decode()
        for fam in EXPECTED_METRIC_FAMILIES:
            assert fam in text, f"missing metric family {fam}"

    _run(server, go)


def test_prompt_truncation_guardrail(server):
    """Over-long prompts are token-truncated (head kept), not rejected
    (reference: llm/serve_llm.py:812-844)."""
    async def go(client):
        long_prompt = "word " * 2000   # byte tokenizer -> ~10k tokens >> 256
        resp = await client.post("/chat", json={"prompt": long_prompt,
                                                "max_tokens": 8})
        assert resp.status == 200
        body = await resp.json()
        assert body["meta"]["prompt_tokens"] <= 256

    _run(server, go)


def test_skip_chat_template(server):
    async def go(client):
        resp = await client.post(
            "/chat", json={"prompt": "raw", "skip_chat_template": True,
                           "max_tokens": 2})
        assert resp.status == 200

    _run(server, go)


def test_parallel_fanout_requests(server):
    """5 concurrent requests (the agent-b fan-out shape) all succeed."""
    async def go(client):
        async def one(i):
            resp = await client.post(
                "/chat", json={"prompt": f"task {i}", "max_tokens": 4})
            assert resp.status == 200
            return (await resp.json())["meta"]["request_id"]

        ids = await asyncio.gather(*[one(i) for i in range(5)])
        assert len(set(ids)) == 5

    _run(server, go)


def test_kv_gauges_reflect_engine(server):
    async def go(client):
        resp = await client.get("/metrics")
        text = (await resp.read()).decode()
        num_blocks = server.engine.cache.num_blocks - 1
        bs = server.engine.cache.block_size
        assert f"llm_kv_cache_num_gpu_blocks {float(num_blocks)}" in text
        assert f"llm_kv_cache_total_tokens {float(num_blocks * bs)}" in text

    _run(server, go)


def test_profile_endpoints(server, tmp_path):
    """jax.profiler trace start/stop round-trip (SURVEY.md §5.1: the
    TPU-idiomatic profiling the reference stack lacks)."""
    async def go(client):
        log_dir = str(tmp_path / "trace")
        resp = await client.post("/profile/start", json={"log_dir": log_dir})
        assert resp.status == 200
        assert (await resp.json())["log_dir"] == log_dir
        # Double-start must 409, not crash the profiler.
        resp = await client.post("/profile/start", json={"log_dir": log_dir})
        assert resp.status == 409
        resp = await client.post("/profile/stop")
        assert resp.status == 200
        # Stop without an active trace must 409.
        resp = await client.post("/profile/stop")
        assert resp.status == 409
        return log_dir

    log_dir = _run(server, go)
    import os

    assert os.path.isdir(log_dir), "profiler wrote nothing"



def test_sp_serving_refusals():
    """Sequence-parallel serving fail-fast hook (round 5: now EMPTY — the
    validator must accept every shipped feature combination, including the
    round-4 int4 wraps and the round-5 prefix-caching chunk-ring hybrid).
    The hook stays so future sp-incompatible features fail fast there."""
    from agentic_traffic_testing_tpu.serving.server import (
        validate_sp_serving_config,
    )

    c = ServerConfig()
    c.sp_size, c.quantization = 2, "int4"
    validate_sp_serving_config(c)  # int4 serves on either sp mesh (round 4)
    c.prefix_caching = True
    validate_sp_serving_config(c)  # prefix caching x sp serves (round 5)


def test_pp_serving_branch_builds_and_guards(monkeypatch):
    """LLM_PP_SIZE server wiring (round 5): the pp branch builds a working
    PPRunner engine (chunk knob dropped like the sp branch), and its
    guards fire loudly — pp x sp/tp mutual exclusion wins the dispatch
    even though the sp branch comes later, prefix caching and speculation
    refuse instead of silently vanishing."""
    from agentic_traffic_testing_tpu.parallel.pp_runner import PPRunner
    from agentic_traffic_testing_tpu.serving.server import LLMServer

    cfg = ServerConfig(model="tiny", dtype="float32", max_num_seqs=2,
                       max_model_len=128, num_blocks=64, warmup=False,
                       metrics_enabled=False)
    cfg.pp_size = 2
    server = LLMServer(cfg)
    assert isinstance(server.engine.runner, PPRunner)
    assert server.engine.cfg.prefill_chunk_tokens == 0

    bad = ServerConfig(model="tiny", dtype="float32", max_num_seqs=2,
                       max_model_len=128, num_blocks=64, warmup=False,
                       metrics_enabled=False)
    bad.pp_size, bad.sp_size = 2, 2
    with pytest.raises(NotImplementedError, match="pp does not compose"):
        LLMServer(bad)

    px = ServerConfig(model="tiny", dtype="float32", max_num_seqs=2,
                      max_model_len=128, num_blocks=64, warmup=False,
                      metrics_enabled=False, prefix_caching=True)
    px.pp_size = 2
    with pytest.raises(NotImplementedError, match="prefix caching"):
        LLMServer(px)

    sp = ServerConfig(model="tiny", dtype="float32", max_num_seqs=2,
                      max_model_len=128, num_blocks=64, warmup=False,
                      metrics_enabled=False, speculation="ngram",
                      spec_tokens=3)
    sp.pp_size = 2
    with pytest.raises(NotImplementedError, match="speculation"):
        LLMServer(sp)


def test_replica_pool_server_end_to_end():
    """LLM_NUM_REPLICAS=2 serving: the /chat contract is unchanged, every
    pre-pool llm_* family keeps its exact name reporting the POOL AGGREGATE
    (kv blocks sum across replicas), and the per-replica labeled series
    appear. Requests spread across both replicas (round_robin)."""
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=4, max_model_len=256,
        num_blocks=128, max_tokens=16, temperature=0.0,
        num_replicas=2, router_policy="round_robin",
    )
    srv = LLMServer(cfg)
    assert srv.pool is not None and len(srv.pool) == 2
    srv.pool.start()
    try:
        async def go(client):
            for i in range(4):
                resp = await client.post(
                    "/chat", json={"prompt": f"task {i}", "max_tokens": 2})
                assert resp.status == 200
                meta = (await resp.json())["meta"]
                assert meta["completion_tokens"] >= 1
            resp = await client.get("/metrics")
            return (await resp.read()).decode()

        text = _run(srv, go)
        for fam in EXPECTED_METRIC_FAMILIES:
            assert fam in text, f"missing metric family {fam}"
        # Aggregate under the pre-pool names: blocks/tokens SUM.
        total_blocks = sum(e.cache.num_blocks - 1 for e in srv.pool.engines)
        bs = srv.pool.block_size
        assert f"llm_kv_cache_num_gpu_blocks {float(total_blocks)}" in text
        assert f"llm_kv_cache_total_tokens {float(total_blocks * bs)}" in text
        assert "llm_config_num_replicas 2.0" in text
        # Per-replica labeled series, one sample per replica.
        for fam in ("llm_replica_routed_requests_total",
                    "llm_replica_num_running", "llm_replica_kv_used_blocks"):
            assert f'{fam}{{replica="0"}}' in text, fam
            assert f'{fam}{{replica="1"}}' in text, fam
        assert srv.pool.routed_requests == [2, 2]
    finally:
        srv.pool.shutdown()


def test_replica_pool_singleton_keeps_single_engine_path():
    """num_replicas=1 (the default) must not build a pool: the exact
    pre-pool single-engine path, and /metrics carries NO replica-labeled
    series (BASELINE dashboard byte-parity)."""
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=2, max_model_len=128,
        num_blocks=64, warmup=False,
    )
    srv = LLMServer(cfg)
    assert srv.pool is None
    from agentic_traffic_testing_tpu.serving.async_engine import AsyncLLMEngine
    assert isinstance(srv.async_engine, AsyncLLMEngine)
    text = srv.metrics.render().decode()
    assert "llm_replica_" not in text
    assert "llm_config_num_replicas 1.0" in text


def test_num_replicas_env_validation(monkeypatch):
    """LLM_NUM_REPLICAS=0 must refuse at config parse — it would silently
    serve single-engine while exporting llm_config_num_replicas 0 (pool
    capacity formulas read as zero)."""
    monkeypatch.setenv("LLM_NUM_REPLICAS", "0")
    with pytest.raises(ValueError, match="LLM_NUM_REPLICAS"):
        ServerConfig.from_env()
    monkeypatch.setenv("LLM_NUM_REPLICAS", "-2")
    with pytest.raises(ValueError, match="LLM_NUM_REPLICAS"):
        ServerConfig.from_env()


def test_replica_pool_refuses_mesh_composition():
    """Replicas x tp/sp/pp must refuse at startup — a replica is a single-
    chip engine; nesting meshes would over-subscribe devices silently."""
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=2, max_model_len=128,
        num_blocks=64, warmup=False, num_replicas=2,
    )
    cfg.tp_size = 2
    with pytest.raises(NotImplementedError, match="do not compose"):
        LLMServer(cfg)


def test_bad_weights_path_fails_fast(tmp_path):
    """A weight-load failure must abort startup, not silently serve random
    weights behind 200s (round-1 verdict weak #3)."""
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=2, max_model_len=128,
        num_blocks=64, weights_path=str(tmp_path / "no-such-checkpoint"),
    )
    with pytest.raises(RuntimeError, match="LLM_ALLOW_RANDOM_WEIGHTS"):
        LLMServer(cfg)


def test_bad_weights_path_opt_in_random(tmp_path):
    """LLM_ALLOW_RANDOM_WEIGHTS=1 restores the fallback and reports
    llm_model_loaded 0."""
    cfg = ServerConfig(
        model="tiny", dtype="float32", max_num_seqs=2, max_model_len=128,
        num_blocks=64, weights_path=str(tmp_path / "no-such-checkpoint"),
        allow_random_weights=True,
    )
    srv = LLMServer(cfg)
    assert srv.model_loaded is False
    assert b"llm_model_loaded 0.0" in srv.metrics.render()
