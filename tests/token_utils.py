"""Shared token-stream helpers for the engine test suites.

`pick_midstream_stop` is the stop-token scan that used to live inline in
test_speculative.py::test_spec_stop_token_exact (rewritten in PR 6 after
the fixed-index version picked a token that already occurred earlier and
asserted the wrong prefix). The engine stops on a stop token's FIRST
occurrence, so any test that injects a stop token into a known stream
must pick one whose first occurrence is exactly where it expects the
stream to end — every speculative accept-path test reuses THIS helper
instead of forking the scan.
"""

from __future__ import annotations

from typing import Optional, Sequence


def pick_midstream_stop(
    generated_ids: Sequence[int],
    prompt_ids: Sequence[int] = (),
    min_index: int = 2,
) -> Optional[tuple[int, int]]:
    """(stop_index, token) for a stop-token test over a known stream, or
    None when the stream has no usable candidate.

    Picks the first token at index >= `min_index` (and before the final
    token) with NO earlier occurrence in the stream — the engine's
    first-occurrence stop semantics then guarantee the truncated stream
    is exactly generated_ids[: stop_index + 1]. Candidates that also
    occur in `prompt_ids` are preferred: the n-gram drafter copies
    history continuations, so a prompt token CAN land inside an accepted
    draft run (the mid-run-stop scenario speculative tests exist for),
    while a token new to the whole history can only ever be the round's
    own target-sampled correction."""
    candidates = [(i, t) for i, t in enumerate(generated_ids)
                  if min_index <= i < len(generated_ids) - 1
                  and t not in generated_ids[:i]]
    if not candidates:
        return None
    prompt_set = set(prompt_ids)
    return next(((i, t) for i, t in candidates if t in prompt_set),
                candidates[0])
