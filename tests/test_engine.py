"""Continuous-batching engine tests.

The hardest correctness surface of the rebuild (SURVEY.md §7 step 4):
batching-invariance (a request's output must not depend on its batchmates),
preemption + recompute, stop conditions under pipelined readback, KV block
accounting. Greedy sampling + tiny fp32 model => deterministic oracles.
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import FinishReason, SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

CFG = PRESETS["tiny"]


@pytest.fixture(scope="module")
def runner():
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    return ModelRunner(CFG, params)


def make_engine(runner, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_num_seqs", 4)
    ecfg = EngineConfig(**kw)
    return LLMEngine(ecfg, model_cfg=CFG, runner=runner)


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


def run_all(engine, reqs):
    for _ in range(10_000):
        engine.step()
        if all(r.is_finished() for r in reqs):
            return
        if not engine.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


def test_single_request_greedy(runner):
    eng = make_engine(runner)
    rng = np.random.default_rng(0)
    req = eng.generate(rng.integers(0, CFG.vocab_size, 12).tolist(), greedy(10))
    assert req.finish_reason == FinishReason.LENGTH
    assert len(req.generated_ids) == 10
    assert req.queue_wait_s is not None and req.queue_wait_s >= 0


def test_batching_invariance(runner):
    """Outputs identical whether a request runs alone or with 3 batchmates."""
    rng = np.random.default_rng(1)
    prompts = [rng.integers(0, CFG.vocab_size, n).tolist() for n in (5, 11, 17, 9)]

    solo_outputs = []
    for p in prompts:
        eng = make_engine(runner)
        solo_outputs.append(eng.generate(p, greedy(12)).generated_ids)

    eng = make_engine(runner)
    reqs = [eng.add_request(p, greedy(12)) for p in prompts]
    run_all(eng, reqs)
    for r, solo in zip(reqs, solo_outputs):
        assert r.generated_ids == solo, "batched output diverged from solo run"


def test_streaming_events_reconstruct_output(runner):
    eng = make_engine(runner)
    rng = np.random.default_rng(2)
    req = eng.add_request(rng.integers(0, CFG.vocab_size, 7).tolist(), greedy(9))
    seen = []
    for _ in range(1000):
        for ev in eng.step():
            if ev.request is req:
                seen.extend(ev.new_token_ids)
        if req.is_finished() and not eng.has_work():
            break
    # Drain any trailing events
    for ev in eng.step():
        if ev.request is req:
            seen.extend(ev.new_token_ids)
    assert seen == req.generated_ids


def test_stop_token_truncates(runner):
    """Find the greedy continuation, then re-run with its 3rd token as a stop id."""
    rng = np.random.default_rng(3)
    prompt = rng.integers(0, CFG.vocab_size, 6).tolist()
    eng = make_engine(runner)
    free = eng.generate(prompt, greedy(8)).generated_ids
    stop_tok = free[2]

    eng = make_engine(runner)
    req = eng.generate(prompt, greedy(8, stop_token_ids=(stop_tok,)))
    assert req.finish_reason == FinishReason.STOP
    assert req.generated_ids == free[:3], "must stop exactly at (and include) the stop token"


def test_preemption_recompute_exact(runner):
    """A KV pool too small for both requests forces preemption; outputs must
    still match the solo oracles exactly."""
    rng = np.random.default_rng(4)
    p1 = rng.integers(0, CFG.vocab_size, 30).tolist()
    p2 = rng.integers(0, CFG.vocab_size, 30).tolist()

    solos = []
    for p in (p1, p2):
        eng = make_engine(runner)
        solos.append(eng.generate(p, greedy(16)).generated_ids)

    # 11 usable blocks * 8 = 88 tokens < two seqs' peak 2*(30+16) = 92:
    # both admit (5 blocks each) but growth must preempt one. (The engine
    # no longer dispatches past a lane's budget, so the old 13-block pool —
    # sized against wasted-lookahead growth — now fits without preempting.)
    eng = make_engine(runner, num_blocks=12)
    reqs = [eng.add_request(p1, greedy(16)), eng.add_request(p2, greedy(16))]
    run_all(eng, reqs)
    assert [r.generated_ids for r in reqs] == solos
    assert eng.scheduler.num_preemptions > 0, "KV pool was sized to force preemption"


def test_max_model_len_stops_generation(runner):
    eng = make_engine(runner, max_model_len=32)
    rng = np.random.default_rng(5)
    req = eng.generate(rng.integers(0, CFG.vocab_size, 20).tolist(), greedy(1000))
    assert req.finish_reason == FinishReason.LENGTH
    assert req.total_len <= 32


def test_kv_blocks_all_freed_after_completion(runner):
    eng = make_engine(runner)
    rng = np.random.default_rng(6)
    reqs = [eng.add_request(rng.integers(0, CFG.vocab_size, 9).tolist(), greedy(6))
            for _ in range(3)]
    run_all(eng, reqs)
    stats = eng.kv_stats()
    assert stats["used_blocks"] == 0, stats
    assert stats["num_running"] == 0 and stats["num_waiting"] == 0


def test_temperature_reproducible_across_batches(runner):
    """Seeded sampling must give identical output solo vs batched."""
    rng = np.random.default_rng(7)
    p = rng.integers(0, CFG.vocab_size, 10).tolist()
    sp = lambda: SamplingParams(max_tokens=10, temperature=0.8, top_k=20, seed=1234)

    eng = make_engine(runner)
    solo = eng.generate(p, sp()).generated_ids

    eng = make_engine(runner)
    other = [eng.add_request(rng.integers(0, CFG.vocab_size, 8).tolist(), greedy(10))
             for _ in range(2)]
    req = eng.add_request(p, sp())
    run_all(eng, other + [req])
    assert req.generated_ids == solo


def test_more_requests_than_max_num_seqs(runner):
    eng = make_engine(runner, max_num_seqs=2)
    rng = np.random.default_rng(8)
    reqs = [eng.add_request(rng.integers(0, CFG.vocab_size, 5).tolist(), greedy(5))
            for _ in range(6)]
    run_all(eng, reqs)
    for r in reqs:
        assert len(r.generated_ids) == 5


def test_native_allocator_engine_parity(runner):
    """End-to-end generation identical under the C++ and Python allocators."""
    from agentic_traffic_testing_tpu import native as native_mod

    if not native_mod.available():
        pytest.skip("native toolchain unavailable")
    rng = np.random.default_rng(7)
    prompts = [rng.integers(0, CFG.vocab_size, n).tolist() for n in (6, 13, 21)]

    outs = {}
    for use_native in (False, True):
        # Small pool forces block growth + preemption machinery through
        # whichever allocator backs the run.
        eng = make_engine(runner, num_blocks=24, native_allocator=use_native)
        reqs = [eng.add_request(p, greedy(16)) for p in prompts]
        run_all(eng, reqs)
        outs[use_native] = [r.generated_ids for r in reqs]
        kind = type(eng.allocator).__name__
        assert ("Native" in kind) == use_native, kind
    assert outs[False] == outs[True]


def test_warmup_decode_buckets_harmless(runner):
    """Warmup precompiles every batch bucket; dummy writes land in the trash
    block, so subsequent generation is token-exact vs an unwarmed engine."""
    rng = np.random.default_rng(9)
    prompt = rng.integers(0, CFG.vocab_size, 12).tolist()
    ref = make_engine(runner).generate(prompt, greedy(8)).generated_ids

    eng = make_engine(runner)
    n = eng.warmup_decode_buckets()
    assert n >= 1
    assert eng.generate(prompt, greedy(8)).generated_ids == ref


def test_warmup_chunk_buckets_harmless(runner):
    """Chunk-ladder warmup (prefix-caching deployments) leaves generation
    token-exact."""
    rng = np.random.default_rng(10)
    prompt = rng.integers(0, CFG.vocab_size, 12).tolist()
    ref = make_engine(runner).generate(prompt, greedy(8)).generated_ids

    eng = make_engine(runner, prefill_chunk_tokens=32)
    n = eng.warmup_chunk_buckets()
    assert n >= 1
    assert eng.generate(prompt, greedy(8)).generated_ids == ref


def test_long_prefill_batching(runner):
    """With prefill_batch_max_len raised, same-bucket long prompts prefill in
    ONE batched dispatch (not solo), and outputs stay token-exact."""
    rng = np.random.default_rng(12)
    prompts = [rng.integers(0, CFG.vocab_size, n).tolist() for n in (60, 57, 49)]
    solos = []
    for p in prompts:
        eng = make_engine(runner)
        solos.append(eng.generate(p, greedy(6)).generated_ids)

    eng = make_engine(runner, prefill_batch_max_len=64)
    reqs = [eng.add_request(p, greedy(6)) for p in prompts]
    eng.step()  # first step must admit ALL THREE in one prefill batch
    assert eng.scheduler.num_scheduled_prefills == 1
    assert sum(1 for r in reqs if r.state.name == "RUNNING") == 3
    run_all(eng, reqs)
    assert [r.generated_ids for r in reqs] == solos

    # With a cap below the 64-token bucket the head admits solo instead.
    eng = make_engine(runner, prefill_batch_max_len=32)
    reqs = [eng.add_request(p, greedy(6)) for p in prompts]
    eng.step()
    assert eng.scheduler.num_scheduled_prefills == 1
    assert sum(1 for r in reqs if r.state.name == "RUNNING") == 1  # solo head
    run_all(eng, reqs)
    assert [r.generated_ids for r in reqs] == solos


def test_warmup_prefill_buckets_harmless(runner):
    """Warming batched-prefill shapes neither corrupts live KV nor changes
    outputs, and covers the (batch, length) combos under the cap."""
    rng = np.random.default_rng(13)
    prompt = rng.integers(0, CFG.vocab_size, 40).tolist()
    eng = make_engine(runner, prefill_batch_max_len=64)
    ref = eng.generate(prompt, greedy(6)).generated_ids
    n = eng.warmup_prefill_buckets()
    # tiny engine: length buckets {32, 64} x batch buckets {1, 2, 4}, plus
    # the solo (1, 128) shape past the batching cap (solo prompts above the
    # cap still take the batched-prefill path with batch 1).
    assert n == 7
    assert eng.generate(prompt, greedy(6)).generated_ids == ref


def test_abort_after_early_release(runner):
    """Abort a request whose lane was released by the wave-overlap path but
    whose in-flight tokens have not harvested yet: no crash, no tokens
    applied after the abort, and the next wave still completes exactly."""
    rng = np.random.default_rng(15)
    prompts = [rng.integers(0, CFG.vocab_size, 9).tolist() for _ in range(4)]
    solos = []
    for p in prompts:
        eng = make_engine(runner)
        solos.append(eng.generate(p, greedy(8, ignore_eos=True)).generated_ids)

    eng = make_engine(runner, max_num_seqs=2)
    reqs = [eng.add_request(p, greedy(8, ignore_eos=True)) for p in prompts]
    aborted = None
    for _ in range(10_000):
        eng.step()
        if aborted is None:
            # Early release moves a still-RUNNING first-wave request out of
            # the scheduler while its tokens ride the in-flight pipeline.
            gone = [r for r in reqs[:2]
                    if not r.is_finished() and r not in eng.scheduler.running
                    and r.state.name == "RUNNING"]
            if gone:
                aborted = gone[0]
                n_before = len(aborted.generated_ids)
                eng.abort_request(aborted)
                assert aborted.finish_reason == FinishReason.ABORT
        if all(r.is_finished() for r in reqs):
            break
    assert aborted is not None, "wave overlap never released a live lane"
    assert len(aborted.generated_ids) == n_before, (
        "tokens landed on an aborted request after abort_request returned")
    for r, solo in zip(reqs, solos):
        if r is not aborted:
            assert r.generated_ids == solo


def test_abort_returns_finished_sibling_events(runner):
    """abort_request's drain can finish batchmates; their events must come
    back from abort_request itself — with the engine empty afterwards, no
    later step() would ever flush them (the async façade would strand the
    surviving client's stream)."""
    rng = np.random.default_rng(16)
    eng = make_engine(runner)
    a = eng.add_request(rng.integers(0, CFG.vocab_size, 9).tolist(),
                        greedy(6, ignore_eos=True))
    b = eng.add_request(rng.integers(0, CFG.vocab_size, 9).tolist(),
                        greedy(6, ignore_eos=True))
    got_b_tokens = []
    # Step until every remaining token rides the in-flight pipeline, then
    # abort `a` while both are mid-flight.
    for _ in range(10_000):
        for ev in eng.step():
            if ev.request is b:
                got_b_tokens.extend(ev.new_token_ids)
        if eng._inflight and eng._decode_budget_satisfied():
            break
        assert eng.has_work()
    events = eng.abort_request(a)
    for ev in events:
        if ev.request is b:
            got_b_tokens.extend(ev.new_token_ids)
    while not b.is_finished() and eng.has_work():
        # drain may not have covered b's full budget
        for ev in eng.step():
            if ev.request is b:
                got_b_tokens.extend(ev.new_token_ids)
    assert b.is_finished()
    assert got_b_tokens == b.generated_ids, (
        "sibling tokens lost: stream events disagree with the request state")


def test_warmup_prefill_covers_live_shapes(runner, monkeypatch):
    """Every (batch, length) prefill shape the scheduler emits under bursty
    traffic must already be warmed — the warmup's reason to exist is that a
    cold shape is a multi-second XLA compile mid-burst. Guards the padded-
    batch-ladder bound (the scheduler budgets the UNPADDED count, then pads
    UP to a batch bucket)."""
    # max_num_seqs=4 -> batch ladder [1, 2, 4]; budget 192 caps a 64-token
    # bucket at 3 UNPADDED members (64*4 > 192), which then pad UP to the
    # 4-bucket — so shape (4, 64) is live even though 4*64 exceeds the
    # budget, and a warmup that bounded b*t by the budget would miss it.
    eng = make_engine(runner, max_num_seqs=4, prefill_batch_max_len=64,
                      max_num_batched_tokens=192)
    shapes: set[tuple[int, int]] = set()
    orig = eng.runner.prefill

    def recording(tokens, *a, **kw):
        shapes.add(tuple(tokens.shape))
        return orig(tokens, *a, **kw)

    monkeypatch.setattr(eng.runner, "prefill", recording)
    eng.warmup_prefill_buckets()
    warmed = set(shapes)
    shapes.clear()

    rng = np.random.default_rng(14)
    # (100,) lands above the 64-token batching cap: still the batched-prefill
    # path, solo — warmup must have compiled that (1, 128) shape too.
    for lens in [(60, 57, 49), (20, 22), (9,), (33, 40, 61), (100,)]:
        reqs = [eng.add_request(rng.integers(0, CFG.vocab_size, n).tolist(),
                                greedy(4)) for n in lens]
        run_all(eng, reqs)
    assert shapes, "burst traffic never hit the batched-prefill path"
    assert shapes <= warmed, f"cold prefill shapes after warmup: {shapes - warmed}"


def test_wave_overlap_releases_lanes_early(runner, monkeypatch):
    """Successive waves of budget-bound requests: satisfied lanes release
    their slots early so the next wave's prefill dispatches behind the
    in-flight work — no blocking drain between waves (only the final one),
    and outputs stay token-exact vs solo runs."""
    rng = np.random.default_rng(11)
    prompts = [rng.integers(0, CFG.vocab_size, 9).tolist() for _ in range(6)]
    solos = []
    for p in prompts:
        eng = make_engine(runner)
        solos.append(eng.generate(p, greedy(8, ignore_eos=True)).generated_ids)

    eng = make_engine(runner, max_num_seqs=2)
    drains_with_entries = []
    orig = eng._drain_all

    def counting():
        if eng._inflight:
            drains_with_entries.append(len(eng._inflight))
        return orig()

    monkeypatch.setattr(eng, "_drain_all", counting)
    reqs = [eng.add_request(p, greedy(8, ignore_eos=True)) for p in prompts]
    run_all(eng, reqs)
    assert [r.generated_ids for r in reqs] == solos
    # Waves hand over through early release + in-flight prefill, not through
    # mid-run blocking drains; at most the run's tail drains with entries.
    assert len(drains_with_entries) <= 1, drains_with_entries
