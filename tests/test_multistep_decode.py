"""Multi-step decode (fused K model steps per dispatch) must be token-exact.

The engine's TPU hot path runs `decode_steps` model steps inside one jitted
dispatch (lax.scan in runtime/runner.py), with the sampled token feeding the
next step on device. These tests pin the invariant that K is purely a
performance knob: outputs are identical to the single-step engine for greedy
and seeded sampling, stop conditions land on the exact token, and KV
accounting still drains to zero.
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import FinishReason, SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

CFG = PRESETS["tiny"]


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def make_engine(params, decode_steps, **kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("dtype", "float32")
    kw.setdefault("max_model_len", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_num_seqs", 4)
    ecfg = EngineConfig(decode_steps=decode_steps, **kw)
    runner = ModelRunner(CFG, params, decode_steps=decode_steps)
    return LLMEngine(ecfg, model_cfg=CFG, runner=runner)


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


def run_all(engine, reqs):
    for _ in range(10_000):
        engine.step()
        if all(r.is_finished() for r in reqs):
            return
        if not engine.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


def oracle(params, prompt, sampling):
    eng = make_engine(params, decode_steps=1)
    return eng.generate(prompt, sampling).generated_ids


@pytest.mark.parametrize("k", [2, 4, 8])
def test_greedy_exact_vs_single_step(params, k):
    rng = np.random.default_rng(0)
    prompt = rng.integers(0, CFG.vocab_size, 11).tolist()
    want = oracle(params, prompt, greedy(13))  # 13 % k != 0 for every k
    eng = make_engine(params, decode_steps=k)
    req = eng.generate(prompt, greedy(13))
    assert req.generated_ids == want
    assert req.finish_reason == FinishReason.LENGTH


def test_seeded_sampling_exact_vs_single_step(params):
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, CFG.vocab_size, 9).tolist()
    sp = lambda: SamplingParams(max_tokens=12, temperature=0.9, top_k=30, seed=77)
    want = oracle(params, prompt, sp())
    eng = make_engine(params, decode_steps=4)
    req = eng.generate(prompt, sp())
    assert req.generated_ids == want


def test_stop_token_mid_block(params):
    """EOS landing inside a K-block must truncate exactly there."""
    rng = np.random.default_rng(2)
    prompt = rng.integers(0, CFG.vocab_size, 6).tolist()
    free = oracle(params, prompt, greedy(12))
    stop_tok = free[5]
    cut = free.index(stop_tok)  # first occurrence is where generation stops
    eng = make_engine(params, decode_steps=4)
    req = eng.generate(prompt, greedy(12, stop_token_ids=(stop_tok,)))
    assert req.finish_reason == FinishReason.STOP
    assert req.generated_ids == free[: cut + 1]


def test_batched_multistep_matches_solo(params):
    rng = np.random.default_rng(3)
    prompts = [rng.integers(0, CFG.vocab_size, n).tolist() for n in (5, 14, 20)]
    solos = [oracle(params, p, greedy(10)) for p in prompts]
    eng = make_engine(params, decode_steps=4)
    reqs = [eng.add_request(p, greedy(10)) for p in prompts]
    run_all(eng, reqs)
    assert [r.generated_ids for r in reqs] == solos


def test_kv_drains_and_lookahead_respected(params):
    """Lookahead covers (pipeline_depth+1)*K writes; pool drains to zero."""
    eng = make_engine(params, decode_steps=4)
    la = eng.scheduler.cfg.decode_lookahead
    assert la >= (eng.cfg.pipeline_depth + 1) * 4, la
    rng = np.random.default_rng(4)
    reqs = [eng.add_request(rng.integers(0, CFG.vocab_size, 9).tolist(), greedy(7))
            for _ in range(3)]
    run_all(eng, reqs)
    stats = eng.kv_stats()
    assert stats["used_blocks"] == 0, stats


def test_max_model_len_boundary_multistep(params):
    """A request hitting max_model_len mid-K-block stops at the boundary."""
    eng = make_engine(params, decode_steps=4, max_model_len=32)
    rng = np.random.default_rng(5)
    req = eng.generate(rng.integers(0, CFG.vocab_size, 21).tolist(), greedy(1000))
    assert req.finish_reason == FinishReason.LENGTH
    assert req.total_len <= 32


def test_preemption_with_multistep(params):
    rng = np.random.default_rng(6)
    p1 = rng.integers(0, CFG.vocab_size, 30).tolist()
    p2 = rng.integers(0, CFG.vocab_size, 30).tolist()
    solos = [oracle(params, p, greedy(32)) for p in (p1, p2)]
    # Tight pool: growth under the larger multi-step lookahead must preempt,
    # and recompute must reproduce the exact sequences. (13 usable blocks,
    # peak demand 2*(30+32)=124 tokens > 104; sized for the budget-aware
    # dispatcher, which no longer grows lookahead past a lane's max_tokens.)
    eng = make_engine(params, decode_steps=4, num_blocks=14)
    reqs = [eng.add_request(p1, greedy(32)), eng.add_request(p2, greedy(32))]
    run_all(eng, reqs)
    assert [r.generated_ids for r in reqs] == solos
    assert eng.scheduler.num_preemptions > 0


def test_bs32_auto_decode_steps_parity(params):
    """ROADMAP item 2 (round 6): with LLM_DECODE_STEPS unset, the TPU auto
    scales the fused dispatch length with the lane count (32 at bs>=32,
    16 below — the per-step host work grows with B, so a larger K
    amortizes it). The parity half: the fused K the bs32 auto resolves to
    must stay token-exact vs single-step decode, same as every other K."""
    k32 = EngineConfig(max_num_seqs=32).resolved_decode_steps("tpu")
    assert k32 == 32
    assert EngineConfig(max_num_seqs=8).resolved_decode_steps("tpu") == 16
    rng = np.random.default_rng(8)
    prompt = rng.integers(0, CFG.vocab_size, 9).tolist()
    want = oracle(params, prompt, greedy(k32 + 1))  # K+1: crosses a K block
    eng = make_engine(params, decode_steps=k32, max_model_len=64)
    req = eng.generate(prompt, greedy(k32 + 1))
    assert req.generated_ids == want
    assert req.finish_reason == FinishReason.LENGTH


def test_no_wasted_trailing_dispatches(params, monkeypatch):
    """Once every lane's budget is in flight, the engine drains instead of
    dispatching: exactly ceil(max_tokens / K) decode dispatches for a
    fixed-length batch (round-2: 2 of 6 dispatches in the bench shape were
    computing only dropped tokens)."""
    k = 4
    eng = make_engine(params, decode_steps=k)
    calls = {"decode": 0}
    orig = eng.runner.decode

    def counting(*a, **kw):
        calls["decode"] += 1
        return orig(*a, **kw)

    monkeypatch.setattr(eng.runner, "decode", counting)
    max_tokens = 16
    reqs = [eng.add_request(list(range(2, 12)),
                            SamplingParams(temperature=0.0,
                                           max_tokens=max_tokens,
                                           ignore_eos=True))
            for _ in range(3)]
    while eng.has_work() and not all(r.is_finished() for r in reqs):
        eng.step()
    assert all(len(r.output_ids) == max_tokens for r in reqs)
    # prefill samples token 1; decode covers the remaining 15 -> ceil(15/4)=4
    assert calls["decode"] == -(-(max_tokens - 1) // k)
