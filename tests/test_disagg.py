"""Round-16 disaggregated prefill/decode serving suite.

Covers the ISSUE-16 acceptance gates on CPU:

  * handoff identity — a stream prefilled on a prefill-role replica and
    handed to a decode replica via the disagg trigger completes with its
    full token sequence byte-for-byte identical to a never-handed-off
    mixed-pool run (greedy and seeded), for bf16 and int8 KV pools;
  * EOS mid-batch churn — a request that finishes ON the prefill replica
    never migrates, while its batchmates each hand off exactly once
    (counter reconciliation against pool.migrations[("disagg","adopted")]);
  * degrade paths — a checkpoint failure mid-handoff takes the round-9
    kill path (structured ERROR, no adoption), and a decode replica with
    no seat falls back to recompute (the stream still completes
    identically);
  * 1-prefill + N-decode async e2e — concurrent streams through the
    served pool, every output matching its solo reference;
  * the byte-identity pin — LLM_POOL_ROLES unset leaves the /metrics
    payload free of every round-16 family and the routing path free of
    role filtering;
  * unit coverage for SLO-class admission, PhaseAwareRouter,
    decide_role_targets, and the loud empty-eligible router overflow
    (satellite 6).
"""

from __future__ import annotations

import asyncio

import numpy as np
import pytest

from agentic_traffic_testing_tpu.models.config import resolve_config
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import (
    FinishReason,
    SamplingParams,
)
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner
from agentic_traffic_testing_tpu.serving.replica_pool import (
    DISAGG_TRIGGER,
    EnginePool,
)

MODEL = "tiny"
DTYPE = "float32"


@pytest.fixture(scope="module")
def runner():
    import jax
    import jax.numpy as jnp

    cfg = resolve_config(MODEL)
    params = init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, ModelRunner(cfg, params, decode_steps=1)


def make_engine(runner, **kw):
    model_cfg, r = runner
    defaults = dict(model=MODEL, dtype=DTYPE, max_num_seqs=4,
                    max_model_len=256, block_size=16, num_blocks=256,
                    migration=1)
    defaults.update(kw)
    return LLMEngine(EngineConfig(**defaults), model_cfg=model_cfg, runner=r)


def disagg_pool(runner, decode_replicas=1, **kw):
    """1 prefill-role replica + N decode-role replicas."""
    engines = [make_engine(runner, disagg_role="prefill", **kw)]
    engines += [make_engine(runner, disagg_role="decode", **kw)
                for _ in range(decode_replicas)]
    return EnginePool(engines, policy="round_robin")


def mixed_pool(runner, n=2, **kw):
    return EnginePool([make_engine(runner, **kw) for _ in range(n)],
                      policy="round_robin")


def prompts_for(n, length=24, seed=13):
    wl = np.random.default_rng(seed)
    return [wl.integers(10, 200, length).tolist() for _ in range(n)]


def drive(pool, cap=4000):
    steps = 0
    events = []
    while pool.has_work() and steps < cap:
        events.extend(pool.step())
        steps += 1
    assert steps < cap, "failed to drain (hung requests)"
    return events


def track_finals(events, finals):
    for ev in events:
        cur = finals.get(ev.request.request_id)
        if cur is None or ev.request.sampling_step >= cur.sampling_step:
            finals[ev.request.request_id] = ev.request
    return finals


def adopted_count(pool, trigger=DISAGG_TRIGGER):
    return pool.migrations.get((trigger, "adopted"), 0)


# --------------------------------------------------------- handoff identity


@pytest.mark.parametrize("pool_kw", [
    dict(dtype="bfloat16"),
    dict(kv_cache_dtype="int8"),
], ids=["bf16", "int8"])
@pytest.mark.parametrize("sampling", [
    SamplingParams(temperature=0.0, max_tokens=10, ignore_eos=True),
    SamplingParams(temperature=0.8, top_k=20, seed=11, max_tokens=10,
                   ignore_eos=True),
], ids=["greedy", "seeded"])
def test_disagg_handoff_token_identity(runner, sampling, pool_kw):
    """The acceptance criterion: a 1-prefill/1-decode pool must produce
    the exact token streams of a same-size mixed pool that never hands
    anything off, for bf16 and int8 KV — the handoff rides the migration
    plane's byte-identical checkpoint/adopt."""
    import dataclasses

    prompts = prompts_for(2, 40)

    def run(pool):
        reqs = [pool.add_request(p, dataclasses.replace(sampling),
                                 request_id=f"h{i}")
                for i, p in enumerate(prompts)]
        finals = {r.request_id: r for r in reqs}
        track_finals(drive(pool), finals)
        return pool, finals

    _, base = run(mixed_pool(runner, **pool_kw))
    pool, moved = run(disagg_pool(runner, **pool_kw))
    assert adopted_count(pool) == len(prompts), pool.migrations
    assert not pool.migrations.get((DISAGG_TRIGGER, "failed"))
    for rid, r in moved.items():
        assert r.is_finished()
        assert r.finish_reason in (FinishReason.STOP, FinishReason.LENGTH), \
            (rid, r.finish_reason, r.error)
        assert r.generated_ids == base[rid].generated_ids, rid


def test_disagg_eos_mid_batch_finisher_never_migrates(runner):
    """EOS churn on the prefill replica: a request that terminates at its
    first sampled token finishes IN PLACE (the handoff hook skips finished
    requests), while every longer batchmate hands off exactly once — the
    adopted counter reconciles to the survivor count exactly."""
    prompts = prompts_for(4, seed=23)

    def sampling(i):
        if i == 0:
            return SamplingParams(temperature=0.0, max_tokens=1)
        return SamplingParams(temperature=0.0, max_tokens=8,
                              ignore_eos=True)

    base_pool = mixed_pool(runner)
    base = {f"e{i}": base_pool.add_request(p, sampling(i),
                                           request_id=f"e{i}")
            for i, p in enumerate(prompts)}
    drive(base_pool)

    pool = disagg_pool(runner)
    reqs = [pool.add_request(p, sampling(i), request_id=f"e{i}")
            for i, p in enumerate(prompts)]
    finals = track_finals(drive(pool), {r.request_id: r for r in reqs})
    # The 1-token request finished on the prefill replica, untouched.
    assert finals["e0"].finish_reason is FinishReason.LENGTH
    assert adopted_count(pool) == len(prompts) - 1, pool.migrations
    for rid, r in finals.items():
        assert r.is_finished()
        assert r.generated_ids == base[rid].generated_ids, rid


# ------------------------------------------------------------ degrade paths


def test_disagg_checkpoint_failure_takes_kill_path(runner):
    """migrate_error injected on the prefill replica: the handoff
    checkpoint fails BEFORE any teardown and the stream degrades to the
    round-9 structured ERROR terminal — never a silent hang, never a
    half-moved stream."""
    engines = [make_engine(runner, disagg_role="prefill",
                           fault_spec="migrate_error:p=1", fault_seed=17),
               make_engine(runner, disagg_role="decode")]
    pool = EnginePool(engines, policy="round_robin")
    reqs = [pool.add_request(p, SamplingParams(temperature=0.0, max_tokens=8,
                                               ignore_eos=True))
            for p in prompts_for(2, seed=29)]
    finals = track_finals(drive(pool), {r.request_id: r for r in reqs})
    assert not adopted_count(pool)
    killed = [r for r in finals.values()
              if r.finish_reason is FinishReason.ERROR]
    assert killed, "the injected checkpoint failure must surface"
    assert any("migration failed" in (r.error or "") for r in killed)


def test_disagg_adopt_without_seat_falls_back_to_recompute(runner):
    """A decode replica whose only seat is occupied refuses the
    transplant: the handed-off stream re-queues as a recompute and still
    completes with the mixed-pool tokens (the adoption fallback, not a
    loss)."""
    sp = lambda: SamplingParams(temperature=0.0, max_tokens=8,
                                ignore_eos=True)
    prompt = prompts_for(1, 40, seed=31)[0]
    base = make_engine(runner).generate(prompt, sp()).generated_ids

    engines = [make_engine(runner, disagg_role="prefill"),
               make_engine(runner, disagg_role="decode", max_num_seqs=1)]
    pool = EnginePool(engines, policy="round_robin")
    # Occupy the decode replica's only seat before the handoff arrives.
    blocker = pool.engines[1].add_request(prompts_for(1, 16, seed=32)[0],
                                          sp())
    pool.engines[1].step()
    req = pool.add_request(prompt, sp(), request_id="r0")
    finals = track_finals(drive(pool), {"r0": req,
                                        blocker.request_id: blocker})
    assert adopted_count(pool) == 1  # handed over, then recomputed there
    assert finals[blocker.request_id].is_finished()
    moved = finals["r0"]
    assert moved.is_finished()
    assert moved.generated_ids == base


# ----------------------------------------------------- 1-prefill + N-decode


def test_disagg_one_prefill_two_decode_async_e2e(runner):
    """Async serving path over a 1-prefill + 2-decode pool: concurrent
    streams each route to the prefill replica, hand off after their first
    token, and finish on a decode replica identical to their solo
    reference — MIGRATED terminals never reach a client."""
    n = 4
    prompts = prompts_for(n, seed=37)
    sp = lambda: SamplingParams(temperature=0.0, max_tokens=10,
                                ignore_eos=True)
    ref_eng = make_engine(runner)
    refs = [ref_eng.generate(p, sp()).generated_ids for p in prompts]

    pool = disagg_pool(runner, decode_replicas=2)
    assert pool.roles == ["prefill", "decode", "decode"]
    assert pool.role_counts() == {"prefill": 1, "decode": 2, "mixed": 0}
    pool.start()
    try:
        async def one(i):
            toks = []
            async for ev in pool.generate(prompts[i], sp(),
                                          request_id=f"a{i}"):
                toks.extend(ev.new_token_ids)
                if ev.finished:
                    assert ev.request.finish_reason is not \
                        FinishReason.MIGRATED
                    assert ev.request.finish_reason in (
                        FinishReason.STOP, FinishReason.LENGTH), \
                        ev.request.error
            return toks

        async def go():
            return await asyncio.gather(*(one(i) for i in range(n)))

        outs = asyncio.run(go())
    finally:
        pool.shutdown()
    assert outs == refs
    assert adopted_count(pool) == n, pool.migrations
    # Fresh work only ever routed to the prefill replica (index 0); the
    # decode replicas took adoptions, not routes... except adoption
    # placement also counts as a routing decision (_alternate).
    assert pool.routed_requests[0] == n


# ------------------------------------------------- byte-identity pin (unset)


def test_metrics_payload_unchanged_when_roles_unset():
    """The LLM_POOL_ROLES-unset contract: at ANY replica count the scrape
    payload carries none of the round-16 families (role gauges, overflow
    counter, disagg trigger pre-touch, no_eligible_replica shed reason),
    and constructing LLMMetrics with and without the new parameter is
    byte-identical."""
    from prometheus_client import generate_latest

    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics

    def scrape(m):
        # _created samples are wall-clock construction timestamps — they
        # differ between ANY two registries, PR or no PR, so the byte
        # contract is over everything else.
        return b"\n".join(l for l in generate_latest(m.registry).split(b"\n")
                          if b"_created" not in l)

    for n in (1, 2, 3):
        default = LLMMetrics("llm", include_tokens=True, num_replicas=n,
                             host_cache=True, vllm_compat=True)
        explicit = LLMMetrics("llm", include_tokens=True, num_replicas=n,
                              host_cache=True, vllm_compat=True,
                              pool_roles=None)
        payload = scrape(default)
        assert payload == scrape(explicit)
        for token in (b"pool_role_replicas", b"role_overflow_total",
                      b'trigger="disagg"', b'reason="no_eligible_replica"'):
            assert token not in payload, token
    # And with roles SET the families (plus their pre-touched series)
    # appear.
    roled = LLMMetrics("llm", num_replicas=2,
                       pool_roles=("prefill", "decode", "mixed"))
    payload = generate_latest(roled.registry)
    assert b'llm_pool_role_replicas{role="prefill"}' in payload
    assert b'llm_role_overflow_total{role="decode"}' in payload
    assert b'trigger="disagg"' in payload
    assert b'reason="no_eligible_replica"' in payload


def test_roleless_pool_routing_untouched(runner):
    """All-mixed (the unset shape): roles_active is False, route() never
    consults the role filter, and the overflow ledger stays empty."""
    pool = mixed_pool(runner)
    assert pool.roles == ["mixed", "mixed"]
    assert not pool.roles_active
    reqs = [pool.add_request(p, SamplingParams(temperature=0.0,
                                               max_tokens=2,
                                               ignore_eos=True))
            for p in prompts_for(2, seed=41)]
    drive(pool)
    assert all(r.is_finished() for r in reqs)
    assert pool.role_overflows == {}
    assert pool.migrations == {}


# ----------------------------------------------------------- config plumbing


def test_pool_roles_config_validation():
    from agentic_traffic_testing_tpu.serving.config import ServerConfig

    c = ServerConfig(model=MODEL, num_replicas=2, migration=1,
                     pool_roles="prefill,decode")
    c._validate_elastic()
    assert c.parsed_pool_roles() == ("prefill", "decode")
    assert ServerConfig(model=MODEL).parsed_pool_roles() is None

    with pytest.raises(ValueError, match="entries"):
        ServerConfig(model=MODEL, num_replicas=2, migration=1,
                     pool_roles="prefill,turbo")._validate_elastic()
    with pytest.raises(ValueError, match="NUM_REPLICAS"):
        ServerConfig(model=MODEL, num_replicas=3, migration=1,
                     pool_roles="prefill,decode")._validate_elastic()
    with pytest.raises(ValueError, match="MIGRATION"):
        ServerConfig(model=MODEL, num_replicas=2, migration=0,
                     pool_roles="prefill,decode")._validate_elastic()
    with pytest.raises(ValueError, match="decode"):
        ServerConfig(model=MODEL, num_replicas=2, migration=1,
                     pool_roles="prefill,prefill")._validate_elastic()


def test_engine_disagg_role_validation():
    with pytest.raises(ValueError, match="disagg_role"):
        EngineConfig(disagg_role="turbo")
    with pytest.raises(ValueError, match="migration=1"):
        EngineConfig(disagg_role="prefill", migration=0)
    cfg = EngineConfig(disagg_role="decode", migration=1)
    assert cfg.scheduler_config().slo_class_admission
    assert not EngineConfig().scheduler_config().slo_class_admission


# ------------------------------------------------------- scheduler admission


def test_slo_class_admission_ordering():
    from agentic_traffic_testing_tpu.runtime.block_allocator import (
        BlockAllocator,
    )
    from agentic_traffic_testing_tpu.runtime.request import Request
    from agentic_traffic_testing_tpu.runtime.scheduler import (
        Scheduler,
        SchedulerConfig,
    )

    def req(rid, slo):
        return Request(request_id=rid, prompt_ids=[1, 2, 3],
                       sampling=SamplingParams(slo_ttft_ms=slo))

    def order(slo_admission, arrivals):
        cfg = SchedulerConfig(max_num_seqs=4, max_model_len=64,
                              block_size=16,
                              slo_class_admission=slo_admission)
        sched = Scheduler(cfg, BlockAllocator(num_blocks=32, block_size=16))
        for rid, slo in arrivals:
            sched.add_request(req(rid, slo))
        return [r.request_id for r in sched.waiting]

    arrivals = [("a", None), ("b", 500.0), ("c", 100.0), ("d", 500.0),
                ("e", None), ("f", 100.0)]
    # Default admission: plain FCFS, byte-identical to append.
    assert order(False, arrivals) == ["a", "b", "c", "d", "e", "f"]
    # SLO-class admission: tightest class first, FIFO within a class,
    # unclassed (None) last.
    assert order(True, arrivals) == ["c", "f", "b", "d", "a", "e"]


# ------------------------------------------------------------ router policy


class StubEngine:
    def __init__(self, waiting=0, running=0, max_num_seqs=4):
        self.waiting = waiting
        self.running = running
        self.max_num_seqs = max_num_seqs

    def load_snapshot(self):
        return {"num_waiting": self.waiting, "num_running": self.running,
                "inflight_dispatches": 0, "free_blocks": 64,
                "max_num_seqs": self.max_num_seqs, "block_size": 8}


PROMPT = list(range(100, 132))
TIGHT = SamplingParams(slo_ttft_ms=100.0)
LOOSE = SamplingParams()


def test_phase_aware_router_slo_vs_best_effort():
    from agentic_traffic_testing_tpu.serving.router import make_router

    # Replica 0 is shallow but SLOW (high wait EWMA); replica 1 deeper
    # but fast. Tight-SLO work picks the lowest PROJECTED wait.
    r = make_router("phase_aware", [StubEngine(waiting=2),
                                    StubEngine(waiting=3)])
    r.note_wait(0, 2.0)
    r.note_wait(1, 0.1)
    assert r.select(PROMPT, sampling=TIGHT) == 1
    # With no observations the projection degrades to least-loaded.
    cold = make_router("phase_aware", [StubEngine(waiting=2),
                                       StubEngine(waiting=1)])
    assert cold.select(PROMPT, sampling=TIGHT) == 1
    # Best-effort work rotates over the UNSATURATED candidates only.
    r2 = make_router("phase_aware", [StubEngine(waiting=4, max_num_seqs=4),
                                     StubEngine(), StubEngine()])
    picks = {r2.select(PROMPT, sampling=LOOSE) for _ in range(4)}
    assert picks == {1, 2}


def test_phase_aware_note_wait_is_an_ewma():
    from agentic_traffic_testing_tpu.serving.router import PhaseAwareRouter

    r = PhaseAwareRouter([StubEngine()])
    r.note_wait(0, 1.0)
    assert r._wait_ewma[0] == 1.0
    r.note_wait(0, 0.0)
    assert r._wait_ewma[0] == pytest.approx(0.8)


def test_router_empty_eligible_overflows_loudly(caplog):
    """Satellite 6: an empty eligible set no longer raises — selection
    overflows to the full replica set with a warning, and the pool's
    shed policy stays the real overload valve."""
    import logging

    from agentic_traffic_testing_tpu.serving.router import make_router

    r = make_router("least_loaded", [StubEngine(), StubEngine(waiting=5)])
    with caplog.at_level(logging.WARNING, logger="att_tpu.router"):
        assert r.select(PROMPT, eligible=[]) == 0
    assert any("empty eligible" in m for m in caplog.messages)


def test_pool_role_overflow_counted(runner):
    """A role-restricted pool whose prefill replica is unavailable
    overflows loudly and counts it (llm_role_overflow_total{role})."""
    pool = disagg_pool(runner)
    # Only the decode replica offered: the prefill/mixed filter keeps
    # nothing and falls back to the full candidate set.
    assert pool._role_filter([1], ("prefill", "mixed")) == [1]
    assert pool.role_overflows == {"prefill": 1}


# ------------------------------------------------------- per-role autoscale


def test_decide_role_targets():
    from agentic_traffic_testing_tpu.serving.autoscale import (
        AutoscalePolicy,
        AutoscaleSignals,
        decide_role_targets,
    )

    pol = AutoscalePolicy(min_replicas=1, max_replicas=4)
    sig = lambda **kw: AutoscaleSignals(**dict(dict(
        current=1, waiting=0, running=1, met_delta=0, violated_delta=0,
        idle_ticks=0), **kw))
    # A prefill backlog grows the prefill tier; an idle decode tier
    # shrinks no further than one replica.
    targets = decide_role_targets(
        {"prefill": sig(waiting=8),
         "decode": sig(running=0, idle_ticks=5)}, pol)
    assert targets == {"prefill": 2, "decode": 1}
    # A role never shrinks below one replica even when pol.min_replicas
    # would allow the POOL to (per-role floor beats the pool floor).
    targets = decide_role_targets(
        {"decode": sig(current=2, running=0, idle_ticks=5)}, pol)
    assert targets == {"decode": 1}
