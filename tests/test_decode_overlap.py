"""Overlapped decode loop (LLM_DECODE_OVERLAP): speculation about the NEXT
step's composition must be a pure performance knob.

The round-7 fast path dispatches fused-step N+1 against the predicted
composition while step N executes (engine._dispatch_decode fast path →
scheduler.extend_decode + the incremental device-side table scatter +
runner.decode_overlapped's donated two-slot DecodeState carry). Invariants
pinned here, in the DEFAULT tier on CPU (acceptance criterion):

  * knob OFF (default): the serial loop runs exactly as before — the
    overlapped jit is never touched, plan() runs per dispatch, zero
    overlap counters, oracle-equal output.
  * knob ON: token-identical to the serial engine under EOS mid-batch,
    admission mid-decode, and abort — the three churn shapes whose
    reconciliation (discard + re-plan) the prediction must survive —
    for greedy and seeded sampling.
  * the dma3 widened (B, KH, C) lane-parallel grid matches dma2 and the
    jnp oracle in interpret mode for every head-count shape in the mode
    table.
  * config guards: tp/sp/pp runners refuse the knob at build, not at
    first step (speculation composes since round 14); the sampling-array
    memo evicts LRU instead
    of clearing wholesale.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.models.config import PRESETS
from agentic_traffic_testing_tpu.models.llama import init_params
from agentic_traffic_testing_tpu.runtime.engine import EngineConfig, LLMEngine
from agentic_traffic_testing_tpu.runtime.request import SamplingParams
from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

CFG = PRESETS["tiny"]


@pytest.fixture(scope="module")
def runner():
    # ONE runner for the whole module: serial and overlapped engines run
    # different jit objects on it, so every program compiles exactly once
    # (keeps this suite in the default tier's budget).
    params = init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    return ModelRunner(CFG, params, decode_steps=1)


def make_engine(runner, overlap, **kw):
    kw.setdefault("max_model_len", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("num_blocks", 64)
    kw.setdefault("max_num_seqs", 4)
    return LLMEngine(EngineConfig(model="tiny", dtype="float32",
                                  decode_overlap=overlap, **kw),
                     model_cfg=CFG, runner=runner)


def greedy(max_tokens=8, **kw):
    return SamplingParams(max_tokens=max_tokens, temperature=0.0, **kw)


def drive(engine, reqs):
    for _ in range(10_000):
        engine.step()
        if all(r.is_finished() for r in reqs):
            return
        if not engine.has_work():
            break
    assert all(r.is_finished() for r in reqs), [r.state for r in reqs]


PROMPT_LENS = (12, 20, 9)


def prompts():
    rng = np.random.default_rng(3)
    return [rng.integers(0, CFG.vocab_size, n).tolist() for n in PROMPT_LENS]


# ------------------------------------------------- knob off: serial pin


def test_knob_off_is_serial_loop(runner, monkeypatch):
    """Default off: the overlapped jit is never invoked, no fast-path
    dispatch happens, and output matches — the bit-identical-to-main
    contract's observable half."""
    eng = make_engine(runner, overlap=0)
    monkeypatch.setattr(
        runner, "decode_overlapped",
        lambda *a, **kw: pytest.fail("overlapped jit ran with the knob off"))
    reqs = [eng.add_request(p, greedy(6)) for p in prompts()]
    drive(eng, reqs)
    assert eng.num_overlap_dispatches == 0
    assert eng.num_overlap_mispredicts == 0
    want = make_engine(runner, overlap=0)
    wreqs = [want.add_request(p, greedy(6)) for p in prompts()]
    drive(want, wreqs)
    assert [r.generated_ids for r in reqs] == [
        r.generated_ids for r in wreqs]


# ------------------------------------- knob on: token identity under churn


def _run(runner, overlap, sampling_for, n_seats=4, mid_abort=False,
         late_arrival=None):
    eng = make_engine(runner, overlap, max_num_seqs=n_seats)
    ps = prompts()
    reqs = [eng.add_request(p, sampling_for(i)) for i, p in enumerate(ps)]
    for _ in range(5):
        eng.step()
    if mid_abort:
        eng.abort_request(reqs[1])
    if late_arrival is not None:
        reqs.append(eng.add_request(ps[0][:7], late_arrival))
    drive(eng, [r for r in reqs if r not in
                ([reqs[1]] if mid_abort else [])])
    return [r.generated_ids for r in reqs], eng


def test_overlap_token_identical_mixed_stops(runner):
    """Mixed max_tokens: lanes stop at different dispatches, so the fast
    path repeatedly predicts through LENGTH churn."""
    samp = lambda i: greedy((10, 4, 7)[i])
    want, _ = _run(runner, 0, samp)
    got, eng = _run(runner, 1, samp)
    assert got == want
    assert eng.num_overlap_dispatches > 0


def test_overlap_token_identical_seeded(runner):
    samp = lambda i: SamplingParams(max_tokens=8, temperature=0.9, top_k=20,
                                    seed=7 + i)
    want, _ = _run(runner, 0, samp)
    got, eng = _run(runner, 1, samp)
    assert got == want
    assert eng.num_overlap_dispatches > 0


def test_overlap_token_identical_eos_mid_batch(runner):
    """An EOS landing mid-batch while speculative dispatches are in flight
    is THE mispredict shape: the post-stop tail must be discarded and the
    corrected batch re-planned, token streams unchanged."""
    base, _ = _run(runner, 0, lambda i: greedy(10))
    stop_tok = base[0][2]  # reachable greedy token → a real mid-stream stop
    samp = lambda i: greedy(10, stop_token_ids=[stop_tok])
    want, _ = _run(runner, 0, samp)
    got, eng = _run(runner, 1, samp)
    assert got == want
    assert eng.num_overlap_dispatches > 0
    assert eng.num_overlap_mispredicts >= 1
    assert eng._overlap_unharvested == 0  # accounting drained clean


def test_overlap_token_identical_admission_mid_decode(runner):
    """A late arrival admitted into a decoding wave (2 seats, request 3
    waits) — the prediction window must close and reopen around the
    admission without corrupting either wave's streams."""
    samp = lambda i: greedy(12)
    late = greedy(6)
    want, _ = _run(runner, 0, samp, n_seats=2, late_arrival=late)
    got, eng = _run(runner, 1, samp, n_seats=2, late_arrival=late)
    assert got == want
    assert eng.num_overlap_dispatches > 0


def test_overlap_token_identical_abort(runner):
    samp = lambda i: greedy(12)
    want, _ = _run(runner, 0, samp, mid_abort=True)
    got, eng = _run(runner, 1, samp, mid_abort=True)
    # The aborted lane's stream is whatever had been harvested pre-abort
    # on each arm; survivors must match exactly.
    assert [want[0], want[2]] == [got[0], got[2]]
    assert eng._overlap_unharvested == 0


def test_overlap_uses_incremental_table_scatter(runner, monkeypatch):
    """The fast path must maintain tables via the device-side scatter, not
    the host rebuild (long decode crosses block boundaries: block_size=8,
    12 tokens of growth ⇒ counts change mid-wave)."""
    import agentic_traffic_testing_tpu.runtime.engine as engine_mod

    eng = make_engine(runner, overlap=1)
    calls = {"full": 0}
    orig = engine_mod.LLMEngine._refresh_decode_tables

    def counting(self):
        calls["full"] += 1
        return orig(self)

    monkeypatch.setattr(engine_mod.LLMEngine, "_refresh_decode_tables",
                        counting)
    reqs = [eng.add_request(p, greedy(14, ignore_eos=True))
            for p in prompts()]
    drive(eng, reqs)
    assert eng.num_overlap_dispatches > 0
    # The serial engine refreshes via the full rebuild on every boundary
    # crossing; the overlap engine's fast-path dispatches must not.
    serial = make_engine(runner, overlap=0)
    scalls = {"full": 0}

    def scounting(self):
        scalls["full"] += 1
        return orig(self)

    monkeypatch.setattr(engine_mod.LLMEngine, "_refresh_decode_tables",
                        scounting)
    sreqs = [serial.add_request(p, greedy(14, ignore_eos=True))
             for p in prompts()]
    drive(serial, sreqs)
    assert [r.generated_ids for r in reqs] == [
        r.generated_ids for r in sreqs]
    assert calls["full"] < scalls["full"]


# --------------------------------------------------------- config guards


def test_composes_with_speculation():
    # Round 14: the speculative verify carry is a plain DecodeState with
    # its own donated-state jit, so overlap x speculation BUILDS (token
    # identity under churn is pinned in tests/test_speculative.py).
    EngineConfig(decode_overlap=1, speculation="ngram")


def test_refused_on_unsupporting_runner(runner):
    class NoOverlapRunner(ModelRunner):
        supports_decode_overlap = False

    no = NoOverlapRunner(CFG, runner.params, decode_steps=1)
    with pytest.raises(ValueError, match="overlapped decode"):
        make_engine(no, overlap=1)
    make_engine(no, overlap=0)  # knob off still builds


def test_mesh_runners_declare_no_overlap():
    """tp/sp/pp runners refuse at build through the support flag — the
    class attributes are the contract (construction needs a device mesh,
    but the flag consultation does not)."""
    from agentic_traffic_testing_tpu.parallel.pp_runner import PPRunner
    from agentic_traffic_testing_tpu.parallel.sp_runner import (
        SPPrefillRunner,
        SPTPRunner,
    )
    from agentic_traffic_testing_tpu.parallel.tp_runner import TPRunner

    for cls in (TPRunner, SPPrefillRunner, SPTPRunner, PPRunner):
        assert cls.supports_decode_overlap is False, cls.__name__


def test_rejects_bad_knob_values():
    with pytest.raises(ValueError, match="decode_overlap"):
        EngineConfig(decode_overlap=2)


# ---------------------------------------------------- samp-cache LRU


def test_samp_cache_evicts_lru(runner):
    """The memo bound must evict least-recently-used, not clear wholesale:
    a composition re-touched every step (the steady decode batch) survives
    300 cold insertions, so a churning mix never re-pays its rebuild."""
    eng = make_engine(runner, overlap=0)
    hot = eng._sampling_arrays([], 2)
    for i in range(300):
        eng._sampling_arrays([], 1000 + i)  # cold: distinct padded width
        # ...while steady traffic keeps touching the hot composition.
        assert eng._sampling_arrays([], 2) is hot
    assert eng._sampling_arrays([], 2) is hot
    assert len(eng._samp_cache) <= 256
    # And the oldest cold entries really were evicted, not the hot one.
    assert (1000, ()) not in eng._samp_cache


# --------------------------------- dma3 widened-grid parity (mode table)


from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
    paged_attention_decode_dma2,
    paged_attention_decode_dma3,
)
from agentic_traffic_testing_tpu.runtime.kv_cache import (
    TRASH_BLOCK,
    gather_kv,
)


def _paged_case(rng, *, b, h, kh, hd, bs, ctx_lens):
    max_blocks = max(-(-ln // bs) for ln in ctx_lens) + 2
    num_blocks = 1 + sum(-(-ln // bs) for ln in ctx_lens) + 1
    q = jnp.asarray(rng.standard_normal((b, h, hd)), jnp.float32)
    kp = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)),
                     jnp.float32)
    vp = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)),
                     jnp.float32)
    bt = np.full((b, max_blocks), TRASH_BLOCK, np.int32)
    nxt = 1
    for i, ln in enumerate(ctx_lens):
        n = -(-ln // bs)
        bt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
    return q, kp, vp, jnp.asarray(bt), jnp.asarray(ctx_lens, jnp.int32)


@pytest.mark.parametrize(
    "b,h,kh,hd,bs,ctx_lens",
    [
        # Every head-count shape the backend mode table serves: MQA (kh=1),
        # GQA 2:1 / 4:1, MHA — ragged contexts, block-boundary lengths,
        # a near-dead lane, and a multi-chunk walk per lane.
        (1, 8, 1, 32, 4, [13]),             # MQA
        (2, 4, 2, 16, 4, [5, 9]),           # GQA 2:1
        (3, 8, 2, 16, 4, [1, 8, 17]),       # GQA 4:1, boundary lengths
        (2, 8, 8, 16, 8, [3, 40]),          # MHA, long second lane
        (4, 16, 4, 16, 4, [7, 1, 30, 12]),  # mixed, one lane nearly dead
    ],
)
def test_dma3_widened_grid_parity(b, h, kh, hd, bs, ctx_lens):
    rng = np.random.default_rng(11)
    q, kp, vp, bt, cl = _paged_case(rng, b=b, h=h, kh=kh, hd=hd, bs=bs,
                                    ctx_lens=ctx_lens)
    want = causal_attention(
        q[:, None], gather_kv(kp, bt), gather_kv(vp, bt),
        q_positions=(cl - 1)[:, None], kv_valid_len=cl)[:, 0]
    # pages_per_chunk=2 forces multi-chunk walks (the double-buffer slots
    # actually alternate) at these tiny contexts.
    got3 = paged_attention_decode_dma3(q, kp, vp, bt, cl, interpret=True,
                                       pages_per_chunk=2)
    got2 = paged_attention_decode_dma2(q, kp, vp, bt, cl, interpret=True,
                                       pages_per_chunk=2)
    np.testing.assert_allclose(np.asarray(got3), np.asarray(want),
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(got3), np.asarray(got2),
                               atol=2e-5, rtol=2e-5)


def test_dma3_widened_grid_verify_layout():
    """The speculative-verify 4D q layout (S queries per sequence) rides
    the same widened grid."""
    rng = np.random.default_rng(12)
    b, h, kh, hd, bs = 2, 8, 2, 16, 4
    q, kp, vp, bt, cl = _paged_case(rng, b=b, h=h, kh=kh, hd=hd, bs=bs,
                                    ctx_lens=[6, 11])
    q4 = jnp.asarray(rng.standard_normal((b, 3, h, hd)), jnp.float32)
    got3 = paged_attention_decode_dma3(q4, kp, vp, bt, cl, interpret=True,
                                       pages_per_chunk=2)
    got2 = paged_attention_decode_dma2(q4, kp, vp, bt, cl, interpret=True,
                                       pages_per_chunk=2)
    np.testing.assert_allclose(np.asarray(got3), np.asarray(got2),
                               atol=2e-5, rtol=2e-5)
