"""Pallas paged-attention decode kernels vs. the jnp gather oracle.

Runs BOTH kernels — v1 (one BlockSpec pipeline step per page) and the DMA
variant (the TPU-default production path: grid (B, KH), double-buffered
manual page DMA) — in interpreter mode on CPU (SURVEY.md §4: kernel unit
tests diff Pallas against the reference jnp attention). The oracle is
`gather_kv` + `causal_attention` — the exact math the serving decode step
uses when ATT_TPU_ATTENTION=gather.
"""

import numpy as np
import pytest

# Heavyweight tier: CPU-mesh jit compiles dominate (pytest.ini tiering).
pytestmark = pytest.mark.full

import jax
import jax.numpy as jnp

from agentic_traffic_testing_tpu.ops.jnp_ops import causal_attention
from agentic_traffic_testing_tpu.ops.pallas.paged_attention import (
    paged_attention_decode,
    paged_attention_decode_dma,
    paged_attention_decode_dma2,
    paged_attention_decode_dma3,
)
from agentic_traffic_testing_tpu.runtime.kv_cache import TRASH_BLOCK, gather_kv

KERNELS = {
    "v1": paged_attention_decode,
    "dma": paged_attention_decode_dma,
    "dma2": paged_attention_decode_dma2,
    "dma3": paged_attention_decode_dma3,
}


def kernel_params(fn):
    """Parametrize a test over both kernel entry points."""
    return pytest.mark.parametrize("kernel", KERNELS.values(), ids=KERNELS)(fn)


def _random_case(rng, *, b, h, kh, hd, bs, max_blocks, num_blocks, ctx_lens,
                 dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((b, h, hd)), dtype)
    k_pages = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)), dtype)
    v_pages = jnp.asarray(rng.standard_normal((kh, num_blocks, bs, hd)), dtype)
    bt = np.full((b, max_blocks), TRASH_BLOCK, np.int32)
    nxt = 1
    for i, ln in enumerate(ctx_lens):
        n = -(-ln // bs)
        bt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
    assert nxt <= num_blocks
    return q, k_pages, v_pages, jnp.asarray(bt), jnp.asarray(ctx_lens, jnp.int32)


def _oracle(q, k_pages, v_pages, bt, ctx_lens):
    k_all = gather_kv(k_pages, bt)
    v_all = gather_kv(v_pages, bt)
    out = causal_attention(
        q[:, None], k_all, v_all,
        q_positions=(ctx_lens - 1)[:, None], kv_valid_len=ctx_lens,
    )
    return out[:, 0]


@kernel_params
@pytest.mark.parametrize(
    "b,h,kh,hd,bs,ctx_lens",
    [
        (2, 4, 2, 64, 4, [5, 9]),          # GQA 2:1, ragged contexts
        (3, 4, 4, 64, 8, [1, 8, 17]),      # MHA, boundary lengths
        (1, 8, 1, 128, 4, [13]),           # MQA, hd=128
        (4, 4, 2, 64, 4, [4, 1, 30, 12]),  # mixed, one lane nearly dead
    ],
)
def test_kernel_matches_oracle(kernel, b, h, kh, hd, bs, ctx_lens):
    rng = np.random.default_rng(42)
    max_blocks = max(-(-ln // bs) for ln in ctx_lens) + 2
    num_blocks = 1 + sum(-(-ln // bs) for ln in ctx_lens) + 2
    q, kp, vp, bt, cl = _random_case(
        rng, b=b, h=h, kh=kh, hd=hd, bs=bs, max_blocks=max_blocks,
        num_blocks=num_blocks, ctx_lens=ctx_lens,
    )
    got = kernel(q, kp, vp, bt, cl, interpret=True)
    want = _oracle(q, kp, vp, bt, cl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


@kernel_params
def test_kernel_stacked_padded_pool(kernel):
    """The serving layout: stacked [L, ...] pool with lane-padded pages
    (kv_cache.phys_head_dim) and a layer scalar — the exact operands the
    decode scan passes on TPU."""
    rng = np.random.default_rng(11)
    L, kh, hd, hdp, bs = 3, 2, 64, 128, 4
    b, h = 2, 4
    ctx_lens = [5, 9]
    max_blocks = 4
    num_blocks = 8
    q, kp, vp, bt, cl = _random_case(
        rng, b=b, h=h, kh=kh, hd=hd, bs=bs, max_blocks=max_blocks,
        num_blocks=num_blocks, ctx_lens=ctx_lens,
    )
    kp5 = jnp.zeros((L, kh, num_blocks, bs, hdp), kp.dtype)
    vp5 = jnp.zeros((L, kh, num_blocks, bs, hdp), vp.dtype)
    li = 1
    kp5 = kp5.at[li, ..., :hd].set(kp)
    vp5 = vp5.at[li, ..., :hd].set(vp)
    # Garbage in the pad lanes must not leak into the output.
    kp5 = kp5.at[li, ..., hd:].set(99.0)
    got = kernel(q, kp5, vp5, bt, cl, layer=jnp.int32(li), interpret=True)
    want = _oracle(q, kp, vp, bt, cl)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=2e-5, rtol=2e-5)


@kernel_params
def test_kernel_bf16_matches_oracle(kernel):
    rng = np.random.default_rng(7)
    q, kp, vp, bt, cl = _random_case(
        rng, b=2, h=8, kh=2, hd=64, bs=8, max_blocks=4, num_blocks=8,
        ctx_lens=[11, 23], dtype=jnp.bfloat16,
    )
    got = kernel(q, kp, vp, bt, cl, interpret=True)
    want = _oracle(q, kp, vp, bt, cl)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        atol=2e-2, rtol=2e-2,
    )


@kernel_params
def test_inactive_lane_is_finite(kernel):
    """Dead lanes (ctx 1, trash table) must return finite garbage, not NaN."""
    rng = np.random.default_rng(3)
    q, kp, vp, bt, cl = _random_case(
        rng, b=2, h=4, kh=2, hd=64, bs=4, max_blocks=3, num_blocks=6,
        ctx_lens=[6, 1],
    )
    bt = bt.at[1].set(TRASH_BLOCK)
    got = kernel(q, kp, vp, bt, cl, interpret=True)
    assert np.isfinite(np.asarray(got)).all()


def test_decode_step_uses_kernel_when_forced(monkeypatch):
    """End-to-end: forcing ATT_TPU_ATTENTION=interpret through the model's
    decode step must reproduce the gather path's logits."""
    monkeypatch.setenv("ATT_TPU_ATTENTION", "interpret")
    import jax

    from agentic_traffic_testing_tpu.models.config import PRESETS
    from agentic_traffic_testing_tpu.models.llama import decode_step_impl, init_params, prefill
    from agentic_traffic_testing_tpu.runtime.kv_cache import make_kv_cache

    cfg = PRESETS["tiny"]
    params = init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 4)), jnp.int32)
    bt = jnp.asarray([[1, 2, TRASH_BLOCK], [3, 4, TRASH_BLOCK]], jnp.int32)
    cache = make_kv_cache(cfg, num_blocks=8, block_size=4, dtype=jnp.float32)
    lens = jnp.asarray([4, 4], jnp.int32)
    logits, cache = prefill(params, cfg, tokens, cache, bt, lens)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    got, _ = decode_step_impl(params, cfg, nxt, cache, bt, lens)
    monkeypatch.setenv("ATT_TPU_ATTENTION", "gather")
    want, _ = decode_step_impl(params, cfg, nxt, cache, bt, lens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-3)


@kernel_params
def test_kernel_multi_query_verify_layout(kernel):
    """S>1 (speculative verify): query token s sits at ctx-1+s and may
    attend through its own freshly written slot."""
    rng = np.random.default_rng(9)
    b, s, h, kh, hd, bs = 2, 3, 4, 2, 64, 4
    ctx = [6, 11]  # context of query token 0; slots for s=1,2 already written
    q = jnp.asarray(rng.standard_normal((b, s, h, hd)), jnp.float32)
    k_pages = jnp.asarray(rng.standard_normal((kh, 16, bs, hd)), jnp.float32)
    v_pages = jnp.asarray(rng.standard_normal((kh, 16, bs, hd)), jnp.float32)
    bt = np.full((b, 8), TRASH_BLOCK, np.int32)
    nxt = 1
    for i, ln in enumerate(ctx):
        n = -(-(ln + s - 1) // bs)
        bt[i, :n] = np.arange(nxt, nxt + n)
        nxt += n
    bt = jnp.asarray(bt)
    cl = jnp.asarray(ctx, jnp.int32)

    got = kernel(q, k_pages, v_pages, bt, cl, interpret=True)
    k_all = gather_kv(k_pages, bt)
    v_all = gather_kv(v_pages, bt)
    qpos = (cl - 1)[:, None] + jnp.arange(s, dtype=jnp.int32)[None]
    want = causal_attention(q, k_all, v_all, q_positions=qpos,
                            kv_valid_len=cl + s - 1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-5, rtol=1e-4)
