"""The round-15 agentic traffic plane (agentic_traffic_testing_tpu/loadgen).

Covers the ISSUE-15 acceptance surface on CPU:
  * trace schema round-trip: synthesize → serialize → deserialize →
    replay-plan identity;
  * the open-loop contract: a stalled completion must NOT delay
    subsequent arrivals (the coordinated-omission regression);
  * SLO-report math against hand-computed fixtures;
  * deterministic replay under a fixed seed;
  * CPU e2e against an in-process engine: the report's attainment and
    shed counts reconcile exactly with the engine's Prometheus
    counters / terminal events;
  * the vllm:* compat alias surface (default 0 = byte-identical scrape
    payload, pinned) + the loadgen's own always-registered exposition.
"""

from __future__ import annotations

import asyncio
import json
import urllib.request

import pytest

from agentic_traffic_testing_tpu.loadgen.arrival import arrival_offsets
from agentic_traffic_testing_tpu.loadgen.measure import (
    LoadgenMetrics,
    MetricsExposition,
    build_report,
    capacity_knee,
)
from agentic_traffic_testing_tpu.loadgen.replay import (
    ReplayConfig,
    RequestRecord,
    replay_against_engine,
    run_open_loop,
)
from agentic_traffic_testing_tpu.loadgen.trace import (
    Trace,
    TraceNode,
    TraceRecorder,
    build_replay_plan,
    materialize_prompts,
    materialize_texts,
    synthesize_agentverse_trace,
    topological_order_ok,
)

MODEL = "tiny"


@pytest.fixture(scope="module")
def runner():
    """One shared ModelRunner (the test_faults idiom): every engine in
    this module reuses its compiled programs."""
    import jax
    import jax.numpy as jnp

    from agentic_traffic_testing_tpu.models.config import resolve_config
    from agentic_traffic_testing_tpu.models.llama import init_params
    from agentic_traffic_testing_tpu.runtime.runner import ModelRunner

    cfg = resolve_config(MODEL)
    params = init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, ModelRunner(cfg, params, decode_steps=1)


def _engine(runner, *, seats=4, max_len=512, **kw):
    from agentic_traffic_testing_tpu.runtime.engine import (
        EngineConfig,
        LLMEngine,
    )

    model_cfg, r = runner
    return LLMEngine(EngineConfig(
        model=MODEL, dtype="float32", max_num_seqs=seats,
        max_model_len=max_len, block_size=16, num_blocks=512, **kw),
        model_cfg=model_cfg, runner=r)


# ------------------------------------------------------------- schema


def test_trace_roundtrip_replay_plan_identity():
    """synthesize → serialize → deserialize: identical nodes AND an
    identical replay plan for every arrival process."""
    tr = synthesize_agentverse_trace(tasks=2, seed=7)
    rt = Trace.from_json(tr.to_json())
    assert rt.nodes == tr.nodes
    assert rt.prefixes == tr.prefixes and rt.slo_classes == tr.slo_classes
    for arrival, rate in (("trace", 0.0), ("poisson", 8.0),
                          ("deterministic", 8.0)):
        p1 = build_replay_plan(tr, arrival=arrival, rate=rate, seed=3)
        p2 = build_replay_plan(rt, arrival=arrival, rate=rate, seed=3)
        assert [(s.fire_at_s, s.node.request_id) for s in p1] == \
               [(s.fire_at_s, s.node.request_id) for s in p2]


def test_trace_save_load_roundtrip(tmp_path):
    tr = synthesize_agentverse_trace(tasks=1, seed=1)
    path = str(tmp_path / "t.json")
    tr.save(path)
    assert Trace.load(path).nodes == tr.nodes


def test_trace_schema_version_rejected():
    tr = synthesize_agentverse_trace(tasks=1, seed=0)
    doc = json.loads(tr.to_json())
    doc["schema_version"] = 99
    with pytest.raises(ValueError, match="schema_version"):
        Trace.from_json(json.dumps(doc))


def test_trace_validation():
    node = TraceNode(request_id="a", session_id="s", role="solver",
                     stage="execute", arrival_offset_s=0.0)
    with pytest.raises(ValueError, match="SLO class"):
        Trace(name="x", seed=0, prefixes={}, slo_classes={}, nodes=[node])
    with pytest.raises(ValueError, match="duplicate"):
        Trace(name="x", seed=0, prefixes={},
              slo_classes={"interactive": {"ttft_ms": 1}},
              nodes=[node, TraceNode(
                  request_id="a", session_id="s", role="solver",
                  stage="execute", arrival_offset_s=0.1)])


def test_synthesizer_dag_shape():
    """The AgentVerse template drives the shape: recruit fans into
    num_experts decide nodes, execute rounds ladder, evaluator closes;
    tool calls hang off experts; any monotonic plan is topological."""
    tr = synthesize_agentverse_trace(tasks=2, seed=5)
    sessions = {n.session_id for n in tr.nodes}
    assert len(sessions) == 2
    for sid in sessions:
        ns = [n for n in tr.nodes if n.session_id == sid]
        stages = {n.stage for n in ns}
        assert {"recruit", "decide", "execute", "evaluate"} <= stages
        recruit = [n for n in ns if n.stage == "recruit"]
        decide = [n for n in ns if n.stage == "decide"]
        assert len(recruit) == 1 and len(decide) == 3  # template num_experts
        assert all(n.parents == (recruit[0].request_id,) for n in decide)
        (ev,) = [n for n in ns if n.stage == "evaluate"]
        assert ev.slo_class == "batch"
    for arrival, rate in (("poisson", 4.0), ("deterministic", 16.0),
                          ("trace", 0.0)):
        plan = build_replay_plan(tr, arrival=arrival, rate=rate, seed=2)
        assert topological_order_ok(tr, plan)


def test_materialize_shared_prefixes():
    """Fan-out siblings share their session's exact token prefix, the
    session prefix extends the global system prefix, and materialization
    is deterministic under seed."""
    tr = synthesize_agentverse_trace(tasks=2, seed=3)
    p1 = materialize_prompts(tr, 512, seed=9)
    p2 = materialize_prompts(tr, 512, seed=9)
    assert p1 == p2
    assert p1 != materialize_prompts(tr, 512, seed=10)
    s0 = [n for n in tr.nodes
          if n.session_id == tr.nodes[0].session_id and n.role != "mcp_tool"]
    k = tr.prefixes[s0[0].prefix_id]
    sysk = tr.prefixes["system"]
    for n in s0[1:]:
        assert p1[n.request_id][:k] == p1[s0[0].request_id][:k]
    other = [n for n in tr.nodes
             if n.session_id != tr.nodes[0].session_id
             and n.role != "mcp_tool"][0]
    assert p1[other.request_id][:sysk] == p1[s0[0].request_id][:sysk]
    # the text materialization carries the SAME nested sharing: session
    # prefixes extend the literal system-prefix string
    texts = materialize_texts(tr, seed=9)
    assert set(texts) == set(p1)
    assert all(isinstance(t, str) and t for t in texts.values())
    a_words = texts[s0[0].request_id].split()
    for n in s0[1:]:
        assert texts[n.request_id].split()[:k] == a_words[:k]
    assert texts[other.request_id].split()[:sysk] == a_words[:sysk]


# ------------------------------------------------------------ arrivals


def test_arrival_processes():
    det = arrival_offsets(4, "deterministic", 8.0)
    assert det == [0.0, 0.125, 0.25, 0.375]
    poi = arrival_offsets(100, "poisson", 10.0, seed=4)
    assert poi == arrival_offsets(100, "poisson", 10.0, seed=4)
    assert poi != arrival_offsets(100, "poisson", 10.0, seed=5)
    assert all(b > a for a, b in zip(poi, poi[1:]))
    # mean interarrival ~ 1/λ
    assert 0.05 < poi[-1] / 100 < 0.2
    tr = arrival_offsets(3, "trace", 0.0, trace_offsets=[1.0, 2.0, 4.0],
                         time_scale=0.5)
    assert tr == [0.0, 0.5, 1.5]
    with pytest.raises(ValueError, match="unknown arrival"):
        arrival_offsets(1, "weibull", 1.0)
    with pytest.raises(ValueError, match="positive rate"):
        arrival_offsets(1, "poisson", 0.0)
    with pytest.raises(ValueError, match="trace_offsets"):
        arrival_offsets(1, "trace", 1.0)


def test_replay_config_from_env(monkeypatch):
    monkeypatch.setenv("LOADGEN_ARRIVAL", "deterministic")
    monkeypatch.setenv("LOADGEN_RATE", "12.5")
    monkeypatch.setenv("LOADGEN_SEED", "7")
    monkeypatch.setenv("LOADGEN_TIME_SCALE", "2.0")
    monkeypatch.setenv("LOADGEN_TRACE", "/tmp/x.json")
    monkeypatch.setenv("LOADGEN_METRICS_PORT", "9102")
    c = ReplayConfig.from_env()
    assert (c.arrival, c.rate, c.seed, c.time_scale, c.trace_path,
            c.metrics_port) == ("deterministic", 12.5, 7, 2.0,
                                "/tmp/x.json", 9102)
    monkeypatch.setenv("LOADGEN_RATE", "-1")
    with pytest.raises(ValueError, match="LOADGEN_RATE"):
        ReplayConfig.from_env()


# ----------------------------------------------- the open-loop contract


class _StallTarget:
    """First request hangs until released; the rest return instantly —
    the coordinated-omission trap."""

    def __init__(self):
        self.release = asyncio.Event()
        self.fired = []

    async def fire(self, node, trace, rec, seq):
        self.fired.append(node.request_id)
        if seq == 0:
            await self.release.wait()
        rec.status = "ok"


def test_open_loop_schedule_not_delayed_by_stall():
    """A stalled completion must NOT delay subsequent arrivals: every
    later request still fires within tolerance of its schedule while
    request 0 is wedged for the whole run."""
    tr = synthesize_agentverse_trace(tasks=1, seed=0)
    plan = build_replay_plan(tr, arrival="deterministic", rate=100.0)
    target = _StallTarget()

    async def go():
        task = asyncio.ensure_future(run_open_loop(plan, tr, target))
        while len(target.fired) < len(plan):
            await asyncio.sleep(0.002)
        target.release.set()  # only NOW may request 0 complete
        return await task

    records = asyncio.run(go())
    assert len(records) == len(plan)
    assert all(r.status == "ok" for r in records)
    # every arrival after the stalled one left on schedule
    assert max(r.lag_s for r in records[1:]) < 0.25
    # and the stalled request itself fired first, on schedule
    assert records[0].lag_s < 0.25


def test_open_loop_drain_timeout_marks_hung():
    """The all_terminated gate is real: a request whose target NEVER
    terminates is cancelled at the drain timeout and recorded as
    non-terminal ("hung"), failing all_terminated — while conforming
    requests keep their terminals."""
    tr = synthesize_agentverse_trace(tasks=1, seed=0)
    plan = build_replay_plan(tr, arrival="deterministic", rate=200.0)

    class _Wedged:
        async def fire(self, node, trace, rec, seq):
            if seq == 0:
                await asyncio.Event().wait()  # never terminates
            rec.status = "ok"

    records = asyncio.run(run_open_loop(
        plan, tr, _Wedged(), drain_timeout_s=0.3))
    assert records[0].status == "hung"
    assert records[0].error and "drain timeout" in records[0].error
    assert all(r.status == "ok" for r in records[1:])
    rep = build_report(records, trace=tr, duration_s=1.0,
                       arrival="deterministic", rate=200.0)
    assert rep["all_terminated"] is False
    assert rep["hung"] == 1
    # non-terminal records attain no SLO verdict
    assert records[0].ttft_met is None


def test_open_loop_records_schedule_lag_metrics():
    tr = synthesize_agentverse_trace(tasks=1, seed=0)
    plan = build_replay_plan(tr, arrival="deterministic", rate=200.0)
    m = LoadgenMetrics.for_trace(tr)

    class _Instant:
        async def fire(self, node, trace, rec, seq):
            rec.status = "ok"
            rec.ttft_s, rec.e2e_s, rec.n_tokens = 0.01, 0.02, 2
            rec.slo_ttft_ms, _ = trace.slo_for(node)

    asyncio.run(run_open_loop(plan, tr, _Instant(), metrics=m))
    out = m.render().decode()
    get = m.registry.get_sample_value
    assert get("loadgen_offered_requests_total") == len(plan)
    assert "loadgen_schedule_lag_seconds_bucket" in out
    met = get("loadgen_slo_attainment_total",
              {"slo_class": "interactive", "slo": "ttft", "status": "met"})
    assert met and met > 0


# ------------------------------------------------------- report math


def _mk_trace_for_report():
    return Trace(name="fixture", seed=0, prefixes={},
                 slo_classes={"interactive": {"ttft_ms": 100.0,
                                              "itl_ms": 50.0},
                              "batch": {"ttft_ms": 1000.0, "itl_ms": 0}},
                 nodes=[])


def _rec(i, status, ttft=None, itl=None, cls="interactive", role="solver",
         lag=0.001, e2e=0.5, ttft_slo=100.0, itl_slo=50.0):
    return RequestRecord(
        request_id=f"r{i}", session_id="s", role=role, stage="execute",
        slo_class=cls, scheduled_s=0.1 * i, fire_s=0.1 * i + lag, lag_s=lag,
        status=status, ttft_s=ttft, mean_itl_s=itl, e2e_s=e2e, n_tokens=4,
        slo_ttft_ms=ttft_slo, slo_itl_ms=itl_slo)


def test_report_math_hand_computed():
    """SLO attainment, goodput and percentiles against a hand-built
    record set (the telemetry-plane verdict rules: shed/error attain
    nothing; a deadline'd request with a first token does)."""
    records = [
        _rec(0, "ok", ttft=0.05, itl=0.01),            # ttft met, itl met
        _rec(1, "ok", ttft=0.20, itl=0.01),            # ttft VIOLATED
        _rec(2, "shed"),                               # no verdict
        _rec(3, "deadline", ttft=0.05),                # ttft met (deadline)
        _rec(4, "error", ttft=0.01),                   # no verdict
        _rec(5, "ok", ttft=0.50, cls="batch", role="evaluator",
             ttft_slo=1000.0, itl_slo=None),           # batch met, no itl
    ]
    rep = build_report(records, trace=_mk_trace_for_report(),
                       duration_s=2.0, arrival="poisson", rate=4.0)
    assert (rep["requests"], rep["completed"], rep["shed"], rep["deadline"],
            rep["errors"]) == (6, 3, 1, 1, 1)
    assert rep["all_terminated"] is True
    inter = rep["slo"]["interactive"]
    assert (inter["ttft_met"], inter["ttft_total"]) == (2, 3)
    assert inter["ttft_attainment"] == pytest.approx(2 / 3, abs=1e-4)
    assert (inter["itl_met"], inter["itl_total"]) == (2, 2)
    batch = rep["slo"]["batch"]
    assert (batch["ttft_met"], batch["ttft_total"]) == (1, 1)
    assert batch["itl_total"] == 0 and batch["itl_attainment"] is None
    # overall: met verdicts 3 of 4
    assert rep["ttft_attainment"] == pytest.approx(3 / 4, abs=1e-4)
    # goodput: ok AND no violated axis -> records 0 and 5 (1 violated ttft)
    assert rep["goodput_rate"] == pytest.approx(2 / 2.0, abs=1e-4)
    assert rep["achieved_rate"] == pytest.approx(3 / 2.0, abs=1e-4)
    assert rep["roles"]["solver"]["requests"] == 5
    assert rep["roles"]["solver"]["ttft_p50_s"] == 0.05
    assert rep["roles"]["evaluator"]["ttft_p50_s"] == 0.5


def test_capacity_knee():
    sweep = [(4.0, {"ttft_attainment": 1.0}),
             (8.0, {"ttft_attainment": 0.995}),
             (16.0, {"ttft_attainment": 0.7}),
             (32.0, {"ttft_attainment": None})]
    assert capacity_knee(sweep, target=0.99) == 8.0
    assert capacity_knee(sweep, target=0.6) == 16.0
    assert capacity_knee([(4.0, {"ttft_attainment": 0.1})]) is None
    assert capacity_knee([]) is None
    # non-monotone sweeps: a higher rate is NOT sustainable when a lower
    # swept rate missed the target (noisy/bimodal attainment)
    bimodal = [(8.0, {"ttft_attainment": 0.97}),
               (16.0, {"ttft_attainment": 0.995})]
    assert capacity_knee(bimodal, target=0.99) is None
    # and the walk sorts by rate, whatever order the sweep ran in
    assert capacity_knee(list(reversed(sweep)), target=0.99) == 8.0


# --------------------------------------------- deterministic replay


def test_deterministic_replay_same_seed(runner):
    """Same seed = same schedule, same prompts, same completions; a
    different seed produces a different poisson schedule."""
    tr = synthesize_agentverse_trace(tasks=1, seed=2, max_tokens=4)
    p1 = build_replay_plan(tr, arrival="poisson", rate=50.0, seed=6)
    p2 = build_replay_plan(tr, arrival="poisson", rate=50.0, seed=6)
    p3 = build_replay_plan(tr, arrival="poisson", rate=50.0, seed=7)
    assert [s.fire_at_s for s in p1] == [s.fire_at_s for s in p2]
    assert [s.fire_at_s for s in p1] != [s.fire_at_s for s in p3]

    outs = []
    for _ in range(2):
        records, report = replay_against_engine(
            _engine(runner), tr, arrival="poisson", rate=50.0, seed=6,
            vocab_size=runner[0].vocab_size)
        assert report["all_terminated"]
        outs.append({r.request_id: (r.status, r.n_tokens) for r in records})
    assert outs[0] == outs[1]


# --------------------------------------------------- CPU e2e reconcile


def test_e2e_report_reconciles_with_engine_counters(runner):
    """The acceptance pin: the report's SLO-attainment counts equal the
    engine's llm_slo_attainment_total (drained from the step clock into
    a real LLMMetrics registry) and its shed count equals the engine's
    shed counter — exactly."""
    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics

    tr = synthesize_agentverse_trace(tasks=2, seed=4, max_tokens=5)
    eng = _engine(runner, seats=2, step_trace=1, max_queue=3)
    records, report = replay_against_engine(
        eng, tr, arrival="poisson", rate=60.0, seed=8,
        vocab_size=runner[0].vocab_size)
    assert report["all_terminated"]
    # Overload at 60 req/s on 2 seats with a 3-deep queue must shed.
    assert report["shed"] > 0
    assert report["shed"] == eng.num_shed
    assert report["completed"] + report["shed"] + report["errors"] \
        + report["deadline"] == len(tr.nodes)

    m = LLMMetrics()
    m.observe_step_clock([eng.telemetry])
    get = m.registry.get_sample_value
    prom = {s: get("llm_slo_attainment_total",
                   {"slo": "ttft", "status": s}) or 0
            for s in ("met", "violated")}
    rep_met = sum(c["ttft_met"] for c in report["slo"].values())
    rep_total = sum(c["ttft_total"] for c in report["slo"].values())
    assert int(prom["met"]) == rep_met
    assert int(prom["met"] + prom["violated"]) == rep_total
    assert rep_total > 0  # the pin is vacuous if nothing attained


# ------------------------------------------------- loadgen exposition


def test_loadgen_metrics_always_registered_and_served():
    """The second exposition surface: every family present (zeroed) on a
    scrape BEFORE the first request, served over HTTP on its own
    (ephemeral) port."""
    tr = synthesize_agentverse_trace(tasks=1, seed=0)
    m = LoadgenMetrics.for_trace(tr)
    exposition = MetricsExposition(m, port=0, host="127.0.0.1")
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{exposition.port}/metrics",
                timeout=10) as resp:
            payload = resp.read().decode()
            assert resp.headers["Content-Type"].startswith("text/plain")
    finally:
        exposition.close()
    for fam in ("loadgen_offered_requests_total", "loadgen_requests_total",
                "loadgen_ttft_seconds", "loadgen_itl_seconds",
                "loadgen_e2e_seconds", "loadgen_schedule_lag_seconds",
                "loadgen_slo_attainment_total", "loadgen_offered_rate",
                "loadgen_achieved_rate", "loadgen_goodput_rate"):
        assert fam in payload, fam
    # pre-touched label combos render zeroed series per role/class
    assert 'loadgen_slo_attainment_total{slo="ttft",slo_class="batch",' \
           'status="met"} 0.0' in payload \
           or 'slo_class="batch"' in payload


# ------------------------------------------------------- vllm compat


def _strip_volatile(payload: bytes) -> list:
    return [ln for ln in payload.decode().splitlines()
            if "_created" not in ln]


def test_vllm_compat_default_off_byte_identical():
    """Default 0: no vllm:* token anywhere, and the payload is
    line-identical to a flagless LLMMetrics (modulo the per-instance
    _created timestamps)."""
    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics

    off = LLMMetrics()
    flagless = LLMMetrics(vllm_compat=False)
    assert b"vllm:" not in off.render()
    assert _strip_volatile(off.render()) == _strip_volatile(flagless.render())


def test_vllm_compat_aliases_ride_llm_values():
    """Compat on: the BASELINE-named families appear, carry the llm_*
    values, and the llm_* payload itself is untouched."""
    from agentic_traffic_testing_tpu.serving.metrics import LLMMetrics

    on = LLMMetrics(vllm_compat=True)
    on.record_request("success", 2.0, 0.3, 100, 40)
    on.set_compat_stats(num_running=3, num_waiting=2, cache_usage=0.25)
    off = LLMMetrics()
    off.record_request("success", 2.0, 0.3, 100, 40)

    payload = on.render()
    get = on.registry.get_sample_value
    assert get("vllm:prompt_tokens_total") == 100
    assert get("vllm:generation_tokens_total") == 40
    assert get("vllm:request_success_total") == 1
    assert get("vllm:num_requests_running") == 3
    assert get("vllm:num_requests_waiting") == 2
    assert get("vllm:gpu_cache_usage_perc") == 0.25
    assert get("vllm:time_to_first_token_seconds_sum") == \
        get("llm_queue_wait_seconds_sum")
    assert get("vllm:e2e_request_latency_seconds_count") == 1
    assert b"vllm:time_per_output_token_seconds" in payload
    # llm_* families byte-untouched by the aliases
    on_llm = [ln for ln in _strip_volatile(payload)
              if not ln.startswith("# HELP vllm:")
              and not ln.startswith("# TYPE vllm:")
              and not ln.startswith("vllm:")]
    assert on_llm == _strip_volatile(off.render())


def test_vllm_compat_server_scrape(runner):
    """End to end through LLMServer.handle_metrics: compat on exposes
    the vllm:* families with live scheduler gauges; compat off (same
    engine) serves a vllm-free payload."""
    from aiohttp.test_utils import TestClient, TestServer

    from agentic_traffic_testing_tpu.serving.config import ServerConfig
    from agentic_traffic_testing_tpu.serving.server import LLMServer

    async def scrape(compat):
        cfg = ServerConfig(model=MODEL, dtype="float32", max_num_seqs=2,
                           max_model_len=256, num_blocks=128, max_tokens=8,
                           vllm_compat_metrics=compat)
        srv = LLMServer(cfg, engine=_engine(runner, seats=2, max_len=256))
        srv.async_engine.start()
        try:
            app = srv.make_app(manage_engine=False)
            async with TestClient(TestServer(app)) as client:
                resp = await client.get("/metrics")
                assert resp.status == 200
                return await resp.text()
        finally:
            srv.async_engine.shutdown()

    on = asyncio.run(scrape(1))
    off = asyncio.run(scrape(0))
    assert "vllm:" not in off
    for fam in ("vllm:time_to_first_token_seconds",
                "vllm:num_requests_running", "vllm:num_requests_waiting",
                "vllm:generation_tokens_total", "vllm:prompt_tokens_total",
                "vllm:gpu_cache_usage_perc", "vllm:request_success_total"):
        assert fam in on, fam
    assert "llm_requests_total" in on and "llm_requests_total" in off


def test_vllm_compat_env_validation(monkeypatch):
    from agentic_traffic_testing_tpu.serving.config import ServerConfig

    monkeypatch.setenv("LLM_VLLM_COMPAT_METRICS", "1")
    assert ServerConfig.from_env().vllm_compat_metrics == 1
    monkeypatch.setenv("LLM_VLLM_COMPAT_METRICS", "2")
    with pytest.raises(ValueError, match="LLM_VLLM_COMPAT_METRICS"):
        ServerConfig.from_env()


# ----------------------------------------------------- HTTP target


def test_http_target_replays_against_live_server(runner):
    """The HTTP replay path end to end: the trace replays over SSE
    against a live (in-process) server, client-observed TTFT recorded,
    SLO body overrides delivered (visible as llm_slo_attainment series
    once the step clock is on)."""
    from aiohttp.test_utils import TestClient, TestServer

    from agentic_traffic_testing_tpu.loadgen.replay import HTTPTarget
    from agentic_traffic_testing_tpu.serving.config import ServerConfig
    from agentic_traffic_testing_tpu.serving.server import LLMServer

    tr = synthesize_agentverse_trace(tasks=1, seed=6, max_tokens=4)
    plan = build_replay_plan(tr, arrival="deterministic", rate=40.0)
    texts = materialize_texts(tr, seed=6)

    cfg = ServerConfig(model=MODEL, dtype="float32", max_num_seqs=4,
                       max_model_len=512, num_blocks=256, max_tokens=8,
                       step_trace=1)
    srv = LLMServer(cfg, engine=_engine(runner, step_trace=1))
    srv.async_engine.start()

    async def go():
        app = srv.make_app(manage_engine=False)
        async with TestClient(TestServer(app)) as client:
            target = HTTPTarget(str(client.make_url("/chat")), texts,
                                session=client.session)
            records = await run_open_loop(plan, tr, target)
            resp = await client.get("/metrics")
            return records, await resp.text()

    try:
        records, scrape = asyncio.run(go())
    finally:
        srv.async_engine.shutdown()
    assert len(records) == len(tr.nodes)
    assert all(r.status == "ok" for r in records), [
        (r.request_id, r.status, r.error) for r in records]
    assert all(r.ttft_s is not None and r.ttft_s > 0 for r in records)
    assert all(r.n_tokens > 0 for r in records)
    # the SLO body overrides reached the engine's telemetry plane
    assert 'llm_slo_attainment_total{slo="ttft"' in scrape


# --------------------------------------------------------- recorder


def test_trace_recorder_roundtrip(tmp_path):
    """Recorder → trace → replay plan: the captured schema replays like
    a synthesized one, with per-session parent chaining."""
    rec = TraceRecorder(name="live")
    rec.record_call(request_id="a", session_id="t1", role="agent_a",
                    stage="root", prompt_chars=400, max_tokens=32, t=100.0)
    rec.record_call(request_id="b", session_id="t1", role="agent_b",
                    stage="subtask", prompt_chars=80, max_tokens=16,
                    t=100.5)
    rec.record_call(request_id="c", session_id="t2", role="agent_a",
                    stage="root", prompt_tokens=64, t=101.0)
    tr = rec.to_trace()
    assert len(tr.nodes) == 3
    by_id = {n.request_id: n for n in tr.nodes}
    assert by_id["a"].arrival_offset_s == 0.0
    assert by_id["b"].arrival_offset_s == 0.5
    assert by_id["b"].parents == ("a",)     # same session chains
    assert by_id["c"].parents == ()         # new session starts fresh
    assert by_id["a"].prompt_tokens == 100  # ~4 chars/token estimate
    assert by_id["c"].prompt_tokens == 64   # explicit token count wins
    assert by_id["b"].stage == "execute"    # unknown stage coerced
    path = str(tmp_path / "rec.json")
    tr.save(path)
    plan = build_replay_plan(Trace.load(path), arrival="trace")
    assert [s.node.request_id for s in plan] == ["a", "b", "c"]
    assert [s.fire_at_s for s in plan] == [0.0, 0.5, 1.0]


def test_trace_recorder_dedups_reused_request_ids():
    """Caller-supplied ids can repeat (client retries reuse
    X-Request-ID); the recorder dedups at record time so the atexit
    flush can never throw away the whole capture on a duplicate."""
    rec = TraceRecorder()
    for t in (1.0, 2.0, 3.0):
        rec.record_call(request_id="dup", session_id="t", role="agent_a",
                        prompt_chars=8, t=t)
    tr = rec.to_trace()  # must not raise
    assert [n.request_id for n in tr.nodes] == ["dup", "dup#2", "dup#3"]
    assert tr.nodes[2].parents == ("dup#2",)  # chaining uses deduped ids


def test_llm_client_recorder_hook(tmp_path, monkeypatch):
    """The opt-in llm_client wiring: off = no recorder object; on = one
    process-global recorder keyed by the env path."""
    from agentic_traffic_testing_tpu.agents.common import llm_client

    monkeypatch.delenv("LOADGEN_RECORD_TRACE", raising=False)
    monkeypatch.setattr(llm_client, "_trace_recorder", None)
    assert llm_client.trace_recorder() is None
    path = str(tmp_path / "live.json")
    monkeypatch.setenv("LOADGEN_RECORD_TRACE", path)
    rec = llm_client.trace_recorder()
    assert rec is not None
    assert llm_client.trace_recorder() is rec  # one global instance
    rec.record_call(request_id="x", session_id="t", role="agent_a",
                    prompt_chars=40)
    assert len(rec) == 1
